#!/bin/bash
# Round-5 TPU hardware backlog: run everything the round's CPU-side work
# queued up, in priority order, appending artifacts as it goes.  Safe to
# re-run; each block is independent.  Run from the repo root with the
# TPU visible.
#
#   bash tools_tpu_r5_queue.sh [quick]
#
# "quick" skips the long blocks (2^30, e2e 60s, compile-cache proof).
set -u
OUT=${SRTB_PERF_OUT:-PERF_TPU.jsonl}
stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
note() { echo "{\"ts\": \"$(stamp)\", \"variant\": \"note\", \"note\": \"$1\"}" >> "$OUT"; }
run() {
  local tag="$1"; shift
  echo "== $tag =="
  local line
  line=$("$@" 2>/dev/null | grep '^{' | tail -1)
  if [ -n "$line" ]; then
    echo "{\"ts\": \"$(stamp)\", \"variant\": \"$tag\", \"result\": $line}" >> "$OUT"
    echo "$line"
  else
    echo "{\"ts\": \"$(stamp)\", \"variant\": \"$tag\", \"error\": true}" >> "$OUT"
  fi
}

QUICK=${1:-}

note "r5 queue start: anchored chirp A/B, pallas A/Bs, 2^30 rebench, e2e live, compile cache"

# ---- 1. headline + the round-2 pending A/Bs (VERDICT weak #4) ----
run baseline    env SRTB_BENCH_TRACE_DIR=/tmp/r5_trace_baseline python bench.py
run pallas      env SRTB_BENCH_USE_PALLAS=1 python bench.py
run pallas_sk   env SRTB_BENCH_USE_PALLAS=1 SRTB_BENCH_USE_PALLAS_SK=1 python bench.py
run pallas_fs   env SRTB_BENCH_FFT_STRATEGY=pallas python bench.py
# the fused two-pass four-step (ops/pallas_fft2): segment C2C in 2 HBM
# round trips, no XLA FFT op — the round-4 roofline-gap candidate.
# Acceptance first, in isolation: does Mosaic take the two kernels at
# all (strided col blocks, in-VMEM transposes, in-kernel twiddle)?
echo "== pallas2 kernel acceptance probe (size sweep) =="
# per-size isolation, flagship sizes included (round-3 advisor: the
# padded-footprint sizing must be validated at m=2^28/2^29 before the
# blocks become defaults); each size in its own subprocess so a Mosaic
# rejection or VMEM failure at one size can't mask the others
sweep_failed=0
for log2m in 24 27 28 29; do
  timeout 900 python -m srtb_tpu.tools.pallas2_probe --log2m "$log2m" \
      > /tmp/p2probe.json 2>/dev/null
  rc=$?
  line=$(grep '^{' /tmp/p2probe.json 2>/dev/null | tail -1)
  echo "{\"ts\": \"$(stamp)\", \"variant\": \"pallas2_mosaic_probe_$log2m\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$OUT"
  echo "${line:-probe $log2m: no output (rc=$rc)}"
  [ "$rc" -ne 0 ] && sweep_failed=1
done
# if any size failed at the default 80 MiB plan budget, A/B the largest
# size at a reduced budget (smaller blocks, same kernels) before the
# pipeline benches commit to a spelling
if [ "$sweep_failed" = 1 ]; then
  run pallas2_lowvmem_29 env SRTB_PALLAS2_VMEM_MB=48 timeout 900 \
      python -m srtb_tpu.tools.pallas2_probe --log2m 29
  run pallas2_lowvmem_small_29 env SRTB_PALLAS2_VMEM_MB=48 \
      SRTB_PALLAS2_BB=128 SRTB_PALLAS2_RB=8 timeout 900 \
      python -m srtb_tpu.tools.pallas2_probe --log2m 29
fi
# factorization A/B at 2^27 (default n1=4096x32768 vs 8192x16384):
# different block geometry, same math — the fallback axis if the
# default plan misses VMEM or underperforms
run pallas2_n1_8192_27 env SRTB_PALLAS2_N1=8192 timeout 900 \
    python -m srtb_tpu.tools.pallas2_probe --log2m 27
# First pipeline exposure: bound it so a Mosaic/VMEM failure can't eat
# the queue; if VMEM overflows, retry with smaller blocks.
run pallas2     env SRTB_BENCH_FFT_STRATEGY=pallas2 SRTB_BENCH_DEADLINE=900 \
    SRTB_BENCH_TRACE_DIR=/tmp/r5_trace_pallas2 python bench.py
echo "== trace summary (pallas2) =="
python -m srtb_tpu.tools.trace_summary /tmp/r5_trace_pallas2 --top 10 \
    2>/dev/null \
  | while read -r line; do
      case "$line" in {*)
        echo "{\"ts\": \"$(stamp)\", \"variant\": \"trace_summary_pallas2\", \"result\": $line}" >> "$OUT"
        echo "$line";;
      esac
    done
run pallas2_small_blk env SRTB_BENCH_FFT_STRATEGY=pallas2 SRTB_PALLAS2_BB=64 \
    SRTB_PALLAS2_RB=8 SRTB_BENCH_DEADLINE=900 python bench.py
# (the SRTB_PALLAS2_P1/SRTB_PALLAS2_ROWS/SRTB_PALLAS_ROWS A/B legs are
# retired: real Mosaic rejects the alternate spellings' minor-lb
# reshapes, so only the column-native + vmem_fft_rows lowering ships —
# see PERF.md "pallas2" and ops/pallas_fft.vmem_fft_rows)
# big-block A/B on the same proven kernels: 56 MiB plan vs the 1 MB-plane
# default (v5e has 128 MiB VMEM; fewer grid steps, longer DMA bursts)
run pallas_bigblk env SRTB_BENCH_USE_PALLAS=1 SRTB_BENCH_USE_PALLAS_SK=1 \
    SRTB_PALLAS_VMEM_MB=56 SRTB_BENCH_DEADLINE=900 python bench.py
# everything-fused flagship: two-pass FFT + fused RFI/chirp + fused
# waterfall/SK stats
run pallas2_full env SRTB_BENCH_FFT_STRATEGY=pallas2 SRTB_BENCH_USE_PALLAS=1 \
    SRTB_BENCH_USE_PALLAS_SK=1 SRTB_BENCH_DEADLINE=900 python bench.py

# per-stage attribution of the baseline trace captured above
echo "== trace summary (baseline) =="
python -m srtb_tpu.tools.trace_summary /tmp/r5_trace_baseline --top 10 \
    2>/dev/null \
  | while read -r line; do
      case "$line" in {*)
        echo "{\"ts\": \"$(stamp)\", \"variant\": \"trace_summary\", \"result\": $line}" >> "$OUT"
        echo "$line";;
      esac
    done

# ---- 1b. blocked-plane Pallas unpack: Mosaic acceptance probe ----
# (flip ops/pallas_kernels.PLANES_UNPACK_MOSAIC_OK to True if this
# compiles and matches — the spelling avoids the sample-order kernel's
# lane interleave, but only a real-chip compile proves Mosaic takes it)
echo "== planes unpack Mosaic probe =="
( timeout 300 python - <<'PYEOF'
import numpy as np, jax.numpy as jnp
from srtb_tpu.ops import pallas_kernels as pk, unpack as U
rng = np.random.default_rng(0)
data = jnp.asarray(rng.integers(0, 256, 1 << 16, dtype=np.uint8))
got = np.asarray(pk.unpack_subbyte_planes_window(data, 2, interpret=False))
want = np.asarray(U.unpack_subbyte_planes(data, 2))
np.testing.assert_array_equal(got, want)
print('{"probe": "planes_unpack_mosaic", "ok": true}')
PYEOF
) > /tmp/planes_probe.json 2>/dev/null
rc=$?
line=$(grep '^{' /tmp/planes_probe.json 2>/dev/null | tail -1)
echo "{\"ts\": \"$(stamp)\", \"variant\": \"planes_unpack_mosaic_probe\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$OUT"

# ---- 1c. MXU DFT precision A/B: 3-pass vs 6-pass bf16 on chip ----
# accuracy is only provable on real bf16 MXU passes (CPU computes f32
# exactly); if 'high' holds ~1e-6 while running ~2x, flip the default
echo "== mxu precision probe =="
( timeout 600 python - <<'PYEOF'
import json, os, time
from srtb_tpu.utils.platform import apply_platform_env
apply_platform_env()
import numpy as np, jax, jax.numpy as jnp
from srtb_tpu.ops.mxu_fft import mxu_fft
n = 1 << 22
rng = np.random.default_rng(0)
x = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(np.complex64)
want = np.fft.fft(x.astype(np.complex128))
for prec in ("highest", "high"):
    os.environ["SRTB_MXU_PRECISION"] = prec
    f = jax.jit(lambda v: mxu_fft(v))
    y = f(jnp.asarray(x))
    re, im = np.asarray(jnp.real(y)), np.asarray(jnp.imag(y))
    t0 = time.perf_counter()
    for _ in range(5):
        y = f(jnp.asarray(x))
    np.asarray(jnp.real(y)[:8])
    dt = (time.perf_counter() - t0) / 5
    err = np.abs((re + 1j * im) - want).max() / np.abs(want).max()
    print(json.dumps({"probe": "mxu_precision", "prec": prec,
                      "rel_err": float(err), "ms": round(dt * 1e3, 2)}))
PYEOF
) | while read -r line; do
      # one variant per precision: load-latest-row-per-variant consumers
      # (queue_decisions) must see BOTH rows
      case "$line" in
        *'"prec": "highest"'*) v=mxu_precision_probe_highest;;
        *'"prec": "high"'*) v=mxu_precision_probe_high;;
        *) v=mxu_precision_probe;;
      esac
      case "$line" in {*) echo "{\"ts\": \"$(stamp)\", \"variant\": \"$v\", \"result\": $line}" >> "$OUT"; echo "$line";; esac
    done

# ---- 1e. overlap A/B at the bench default (async dispatch window vs
#           a blocking host sync per segment — measures how much host
#           time + tunnel RTT the in-flight engine hides) ----
run overlap_on_27  env SRTB_BENCH_LOG2N=27 SRTB_BENCH_REPS=5 \
    python bench.py --overlap on
run overlap_off_27 env SRTB_BENCH_LOG2N=27 SRTB_BENCH_REPS=5 \
    python bench.py --overlap off

# ---- 2. per-kernel rows incl. the anchored-vs-exact chirp A/B ----
echo "== kernel bench (anchored chirp A/B) =="
python -m srtb_tpu.tools.kernel_bench --log2n 28 --reps 5 2>/dev/null \
  | while read -r line; do
      echo "{\"ts\": \"$(stamp)\", \"variant\": \"kernel\", \"result\": $line}" >> "$OUT"
      echo "$line"
    done

if [ "$QUICK" = "quick" ]; then exit 0; fi

# ---- 1d. segment-R2C isolation sweep: pallas2 vs the field ----
echo "== fft isolation sweep =="
timeout 2400 python -m srtb_tpu.tools.fft_bench 27 29 \
    monolithic,pallas,pallas2 2>/dev/null \
  | while read -r line; do
      case "$line" in {*)
        echo "{\"ts\": \"$(stamp)\", \"variant\": \"fft_bench\", \"result\": $line}" >> "$OUT"
        echo "$line";;
      esac
    done


# ---- 3. 2^30 production segment rebench (VERDICT #3) ----
run n2_30       env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 SRTB_BENCH_REPS=3 python bench.py
# classic staged plan with Pallas leg FFTs (VMEM rows instead of XLA's
# giant batched FFTs) — candidate for the >=2x 2^30 target
# first run of Pallas legs at this shape: bound it tighter than
# bench.py's default 3000 s watchdog so a hang can't eat the queue
run n2_30_pallas_legs env SRTB_STAGED_ROWS_IMPL=pallas SRTB_BENCH_LOG2N=30 \
    SRTB_BENCH_LOG2CHAN=15 SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=1200 \
    python bench.py
# the blocked staged stage_a SIGSEGV probe: bounded, in a subshell so a
# compiler crash cannot wedge this queue (note the rc either way)
echo "== staged-blocked 2^30 probe =="
( timeout 900 env SRTB_STAGED_BLOCKED=1 SRTB_BENCH_LOG2N=30 \
    SRTB_BENCH_LOG2CHAN=15 SRTB_BENCH_REPS=1 SRTB_BENCH_DEADLINE=800 \
    python bench.py > /tmp/staged_blocked_probe.json 2>/dev/null )
rc=$?
line=$(grep '^{' /tmp/staged_blocked_probe.json 2>/dev/null | tail -1)
echo "{\"ts\": \"$(stamp)\", \"variant\": \"staged_blocked_probe\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$OUT"
# workaround candidate: Pallas leg FFTs (no XLA batched-FFT op in the
# crashing program at all)
echo "== staged-blocked 2^30 probe, pallas legs =="
( timeout 900 env SRTB_STAGED_BLOCKED=1 SRTB_STAGED_ROWS_IMPL=pallas \
    SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 SRTB_BENCH_REPS=1 \
    SRTB_BENCH_DEADLINE=800 \
    python bench.py > /tmp/staged_blocked_pallas.json 2>/dev/null )
rc=$?
line=$(grep '^{' /tmp/staged_blocked_pallas.json 2>/dev/null | tail -1)
echo "{\"ts\": \"$(stamp)\", \"variant\": \"staged_blocked_pallas_probe\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$OUT"
# fused two-pass legs across the staged boundary (pass1 | pass2): the
# fewest-HBM-passes 2^30 plan, classic unpack first, then the
# lane-dense blocked unpack (both XLA-FFT-free)
run n2_30_pallas2 env SRTB_STAGED_ROWS_IMPL=pallas2 SRTB_BENCH_LOG2N=30 \
    SRTB_BENCH_LOG2CHAN=15 SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=1200 \
    python bench.py
# flagship everything-on 2^30: pallas2 staged legs + fused RFI/chirp +
# fused waterfall/SK stats in stage (c)
run n2_30_pallas2_full env SRTB_STAGED_ROWS_IMPL=pallas2 \
    SRTB_BENCH_USE_PALLAS=1 SRTB_BENCH_USE_PALLAS_SK=1 \
    SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 SRTB_BENCH_REPS=3 \
    SRTB_BENCH_DEADLINE=1200 python bench.py
# one-program 2^30: no XLA FFT scratch with pallas2, so the fused plan
# may fit in 16 GB where it used to OOM — would erase both 4 GB staged
# boundary crossings (VERDICT #3's second half).  Bounded probe.
echo "== one-program 2^30 probe, pallas2 fused =="
( timeout 1200 env SRTB_BENCH_STAGED=0 SRTB_BENCH_FFT_STRATEGY=pallas2 \
    SRTB_BENCH_USE_PALLAS=1 SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=1100 \
    python bench.py > /tmp/fused_2_30_pallas2.json 2>/dev/null )
rc=$?
line=$(grep '^{' /tmp/fused_2_30_pallas2.json 2>/dev/null | tail -1)
echo "{\"ts\": \"$(stamp)\", \"variant\": \"fused_2_30_pallas2_probe\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$OUT"

echo "== staged-blocked 2^30 probe, pallas2 legs =="
( timeout 1200 env SRTB_STAGED_BLOCKED=1 SRTB_STAGED_ROWS_IMPL=pallas2 \
    SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 SRTB_BENCH_REPS=3 \
    SRTB_BENCH_DEADLINE=1100 \
    python bench.py > /tmp/staged_blocked_pallas2.json 2>/dev/null )
rc=$?
line=$(grep '^{' /tmp/staged_blocked_pallas2.json 2>/dev/null | tail -1)
echo "{\"ts\": \"$(stamp)\", \"variant\": \"staged_blocked_pallas2_probe\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$OUT"

# overlap A/B at the 2^30 production segment (staged plan): the serial
# leg pays the host sync against a 2.7 s device segment — small relative
# win expected here, but the off row anchors the model
run overlap_on_30  env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=1200 python bench.py --overlap on
run overlap_off_30 env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=1200 python bench.py --overlap off

# ---- 4. live UDP -> TPU end-to-end, 60 s at 2x wire rate (VERDICT #6),
#         two receivers = the reference's per-polarization deployment ----
python -m srtb_tpu.tools.e2e_live --seconds 60 --rate_x 2.0 --log2n 27 \
  --receivers 2 --deadline_s 120 --gui --gui_min_interval_s 1 \
  --out E2E_LIVE.jsonl \
  || note "e2e_live failed"

# ---- 5. compile-cache cold/warm proof across process restarts (VERDICT #7) ----
# same config twice in separate processes; the second run's compile_s is
# the warm number (target <= 10 s)
run cache_cold  env SRTB_BENCH_LOG2N=27 SRTB_BENCH_REPS=3 python bench.py
run cache_warm  env SRTB_BENCH_LOG2N=27 SRTB_BENCH_REPS=3 python bench.py

# ---- 5b. AOT executable cache cold/warm (round-5: utils/aot_cache) ----
# the fallback when the compile cache is bypassed by a remote-compile
# service: the second run loads persisted *executables* — its compile_s
# is the AOT warm-restart number (target <= 10 s regardless of cache
# behavior above).  Then the number that actually matters: the 2^30
# staged plan, whose cold compile was ~11 min in round 2.
rm -rf /tmp/r5_aot_27 /tmp/r5_aot_30
run aot_cold    env SRTB_BENCH_LOG2N=27 SRTB_BENCH_REPS=3 \
    SRTB_BENCH_AOT_DIR=/tmp/r5_aot_27 python bench.py
run aot_warm    env SRTB_BENCH_LOG2N=27 SRTB_BENCH_REPS=3 \
    SRTB_BENCH_AOT_DIR=/tmp/r5_aot_27 python bench.py
run aot_cold_30 env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_REPS=1 SRTB_BENCH_AOT_DIR=/tmp/r5_aot_30 python bench.py
run aot_warm_30 env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_REPS=1 SRTB_BENCH_AOT_DIR=/tmp/r5_aot_30 python bench.py

note "r5 queue done"

# turn the rows into the decision tree's conclusions (report only;
# applying a flip stays a reviewed edit) — the recovery commit then
# carries its own analysis even if nobody is attached.  A crash here
# must leave a trace like every other block (stderr goes to the queue
# log, failure lands as a note row).
line=$(python -m srtb_tpu.tools.queue_decisions --perf "$OUT" \
       --out DECISIONS_r5.md | grep '^{' | tail -1)
if [ -n "$line" ]; then
  echo "{\"ts\": \"$(stamp)\", \"variant\": \"decisions\", \"result\": $line}" >> "$OUT"
else
  note "queue_decisions failed (no JSON line; see queue log stderr)"
fi

# ---- decision tree for the results ----
# (srtb_tpu.tools.queue_decisions evaluates this tree automatically at
#  the end of every queue run into DECISIONS_r5.md; applying a flip
#  stays a reviewed edit, in-session or next round)
# pallas2_mosaic_probe_24..29 all ok AND pallas2 >= 1.2x baseline
#     -> make resolve_strategy "auto" pick pallas2 for n in [2^25, 2^30)
#        and rerun the default bench so BENCH_r0N reflects it.
# pallas2 VMEM/compile failure
#     -> pallas2_lowvmem_* / pallas2_small_blk / pallas2_n1_8192_27 are
#        the retries (budget, blocks, factorization); if all fail,
#        monolithic stays default and the probe rc/error rows document
#        why.
# best(n2_30_pallas2, n2_30_pallas2_full, staged_blocked_pallas2,
#      fused_2_30_pallas2) <= 1.4 s/segment
#     -> VERDICT #3 target met; make that plan the n >= 2^30 default.
# planes_unpack_mosaic_probe ok -> flip pallas_kernels.PLANES_UNPACK_MOSAIC_OK.
# mxu_precision_probe_high rel_err <= ~2e-6 -> flip SRTB_MXU_PRECISION default.
# pallas_bigblk >= pallas_sk -> adopt SRTB_PALLAS_VMEM_MB=56 as the
#     accelerator default row-block plan (ops/pallas_fft._row_block).
# cache_warm compile_s <= 10 s -> VERDICT #7 done; else the axon remote
#     compile service bypasses the local disk cache — document and file.
# aot_warm / aot_warm_30 compile_s <= 10 s -> the AOT executable cache
#     closes the warm-restart gap even with the compile cache bypassed;
#     document the measured warm numbers in PERF.md and recommend
#     aot_plan_path in the production config.
# overlap_on_27 / overlap_off_27 -> the measured per-segment host-sync
#     cost (~60 ms RTT model, PERF.md); if on/off >= 1.1x the async
#     engine's default inflight_segments=2 stands confirmed, and
#     overlap_off_30 anchors the same model at the staged 2^30 plan.
