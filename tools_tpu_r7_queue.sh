#!/bin/bash
# Round-7 TPU hardware backlog: incremental-H2D ring A/Bs (device-
# resident overlap-save carry) on top of the still-undrained r6 backlog
# (fused-plan legs).  Each ring pair uploads bytes per segment the way
# the streaming engine does — "off" re-uploads the full segment, "on"
# only the stride's new bytes — so the delta isolates the transfer-side
# win; h2d_gb / h2d_hidden_ms land in every line.  Safe to re-run; each
# block is independent.  Run from the repo root with the TPU visible
# (tools_tpu_watcher.sh fires it automatically).
#
#   bash tools_tpu_r7_queue.sh [quick]
#
# "quick" drains only the new ring rows (skips the r6 backlog and the
# long 2^30 blocks).
set -u
OUT=${SRTB_PERF_OUT:-PERF_TPU.jsonl}
stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
note() { echo "{\"ts\": \"$(stamp)\", \"variant\": \"note\", \"note\": \"$1\"}" >> "$OUT"; }
run() {
  local tag="$1"; shift
  echo "== $tag =="
  local line
  line=$("$@" 2>/dev/null | grep '^{' | tail -1)
  if [ -n "$line" ]; then
    echo "{\"ts\": \"$(stamp)\", \"variant\": \"$tag\", \"result\": $line}" >> "$OUT"
    echo "$line"
  else
    echo "{\"ts\": \"$(stamp)\", \"variant\": \"$tag\", \"error\": true}" >> "$OUT"
  fi
}

QUICK=${1:-}

# ---- 0. the r6 backlog first (fused-plan legs, never drained) ----
if [ "$QUICK" != "quick" ] && [ -f tools_tpu_r6_queue.sh ]; then
  note "r7 queue: draining r6 backlog first"
  bash tools_tpu_r6_queue.sh quick
fi

note "r7 queue start: incremental-H2D ring A/Bs (stride uploads vs full re-uploads)"

# ---- 1. ring A/B at 2^27 (production |DM| 478.80 reserves ~16% of
#          the segment; the ring should cut steady-state H2D by that
#          fraction, bit-identically).  four_step hosts the fused tail
#          so compute-side traffic matches the r6 flagship plans.
run ring_off_27 env SRTB_BENCH_LOG2N=27 SRTB_BENCH_FFT_STRATEGY=four_step \
    SRTB_BENCH_DEADLINE=900 python bench.py --ring off
run ring_on_27  env SRTB_BENCH_LOG2N=27 SRTB_BENCH_FFT_STRATEGY=four_step \
    SRTB_BENCH_DEADLINE=900 python bench.py --ring on

# ---- 2. high-reserved-fraction legs at 2^27: |DM| 1600 reserves
#          ~55% of the segment — the regime where re-uploading the
#          tail dominates ingest traffic and the ring saves the most.
run ring_hidm_off_27 env SRTB_BENCH_LOG2N=27 SRTB_BENCH_FFT_STRATEGY=four_step \
    SRTB_BENCH_DM=-1600 SRTB_BENCH_DEADLINE=900 python bench.py --ring off
run ring_hidm_on_27  env SRTB_BENCH_LOG2N=27 SRTB_BENCH_FFT_STRATEGY=four_step \
    SRTB_BENCH_DM=-1600 SRTB_BENCH_DEADLINE=900 python bench.py --ring on

if [ "$QUICK" = "quick" ]; then exit 0; fi

# ---- 3. 2^30 staged production segment: the staged ring
#          (stage_a_ring emits the carry alongside the canonical
#          boundary).  3 reps — each leg moves ~0.27 GB (warm) vs
#          ~0.34 GB (cold) of H2D per segment at the ~2% 2^30
#          reserved fraction, so the headline check here is
#          bit-identical plans + h2d accounting, with the hi-DM pair
#          below carrying the bandwidth story.
run staged_ring_off_30 env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=2700 python bench.py --ring off
run staged_ring_on_30  env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=2700 python bench.py --ring on
# high-DM 2^30 staged pair (|DM| 12000 reserves ~40% of 2^30): the
# production regime the ISSUE motivates — reserved-dominated ingest
run staged_ring_hidm_off_30 env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_DM=-12000 SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=2700 \
    python bench.py --ring off
run staged_ring_hidm_on_30  env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_DM=-12000 SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=2700 \
    python bench.py --ring on

note "r7 queue done"
