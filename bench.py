"""Benchmark: sustained coherent-dedispersion pipeline throughput on one
chip, in the J1644-4559 configuration (2-bit samples, 128 MSa/s, |DM| =
478.80, inverted 64 MHz band — ref: srtb_config_1644-4559.cfg).

Prints ONE JSON line:
  {"metric": ..., "value": Msamples/s, "unit": ..., "vs_baseline": x, ...}
where vs_baseline is the real-time factor against the 128 MSa/s baseband
rate (BASELINE.md target: >= 1x real-time on a single v5e chip).

Hardened against the round-1 failure mode (TPU backend init hang/crash):
the backend is probed in a *subprocess* with a timeout before the main
process commits to it, with retries; if no accelerator comes up the bench
still emits a JSON line — a CPU-fallback measurement tagged
"platform": "cpu" plus the accelerator error — instead of dying with a
stack trace.  Every failure path emits a diagnostic JSON line and exits 0.

Extra emitted fields (roofline model, see PERF.md):
  model_gflops      — FFT-dominated FLOP count of one segment / 1e9
  achieved_gflops_s — model_gflops / measured time
  model_hbm_gb      — modeled HBM bytes moved per segment / 1e9
  achieved_gbps     — model_hbm_gb / measured time
  roofline_frac     — achieved_gbps / chip HBM peak (v5e: 819 GB/s)
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time

import numpy as np

# v5e public peak numbers (How to Scale Your Model, table "TPU v5e"):
# 819 GB/s HBM bandwidth, 197 bf16 TFLOP/s.  The pipeline is f32 VPU/FFT
# bound, so HBM bandwidth is the governing roof.
V5E_HBM_PEAK_GBPS = 819.0


def emit(obj) -> None:
    print(json.dumps(obj))
    sys.stdout.flush()


def probe_backend(timeout_s: float):
    """Initialize JAX in a subprocess so a hung backend init cannot take
    the bench down with it.  Returns (platform_name | None, error | None).
    """
    # SRTB_BENCH_PROBE_PLATFORM pins the probed platform (tests use an
    # unknown name to exercise the fallback path deterministically)
    code = ("import os, jax\n"
            "p = os.environ.get('SRTB_BENCH_PROBE_PLATFORM')\n"
            "if p: jax.config.update('jax_platforms', p)\n"
            "d = jax.devices()\n"
            "print('PLATFORM:' + d[0].platform)")
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s,
                           env={**os.environ})
    except subprocess.TimeoutExpired:
        return None, f"backend init timed out after {timeout_s:.0f}s"
    except OSError as e:  # pragma: no cover - subprocess launch failure
        return None, f"probe subprocess failed: {e}"
    for line in p.stdout.splitlines():
        if line.startswith("PLATFORM:"):
            return line.split(":", 1)[1], None
    tail = (p.stderr or p.stdout or "").strip().splitlines()
    return None, " | ".join(tail[-3:]) if tail else "no platform reported"


def pick_platform():
    """Probe the accelerator with retries; fall back to CPU.

    Returns (platform_for_env, accelerator_error | None).

    A preset ``JAX_PLATFORMS`` is *probed, not trusted*: the round-2
    artifact came out 0.0 precisely because the driver environment pinned
    the platform and the old code skipped the probe/fallback machinery,
    letting the main process run head-first into a dead tunnel.  The
    probe subprocess inherits the preset via its environment, so pinning
    still selects the platform — it just has to actually come up.  Only
    ``cpu`` is exempt (it is its own fallback and always initializes).

    Transient tunnel loss gets a bounded retry-over-minutes loop
    (SRTB_BENCH_RETRY_BUDGET seconds total, default 330) before the CPU
    fallback, so a blip during the driver's capture doesn't cost the
    round its accelerator number.  The defaults bound the WHOLE
    failure path (probe + retries + CPU-fallback measurement) to
    ~6 minutes: a healthy tunnel inits in 20-40 s, so 150 s per probe
    is generous, and a driver whose own budget is unknown must see the
    diagnostic line before it gives up — the round-1/round-2 artifacts
    both died to exactly this (rc=1, then value 0.0).
    """
    preset = os.environ.get("JAX_PLATFORMS")
    if preset == "cpu":
        return "cpu", None
    t0 = float(os.environ.get("SRTB_BENCH_INIT_TIMEOUT", "150"))
    budget = float(os.environ.get("SRTB_BENCH_RETRY_BUDGET", "330"))
    deadline = time.monotonic() + budget
    retry_timeout = min(120.0, t0)
    err = None
    first = True
    while True:
        platform, err = probe_backend(t0 if first else retry_timeout)
        if platform is not None:
            # keep the preset spelling: the plugin's registered name (e.g.
            # "axon") can differ from the device's .platform (e.g. "tpu"),
            # and JAX_PLATFORMS must use the registered name
            return (preset or platform), None
        first = False
        # a retry only launches if sleep + its full probe timeout still
        # fit in the budget — the budget is a bound, not a target
        sleep_s = min(30.0, max(0.0, deadline - time.monotonic()))
        if time.monotonic() + sleep_s + retry_timeout > deadline:
            break
        time.sleep(sleep_s)
    if preset:
        err = f"preset JAX_PLATFORMS={preset!r} failed probe: {err}"
    return "cpu", err


def roofline_model(n: int, channel_count: int, nbits: int,
                   hbm_passes: int = 7):
    """Static FLOP / HBM-byte model of one segment (documented in PERF.md).

    FFT work (5 m log2 m per length-m complex FFT, m = n/2 packed C2C):
    segment R2C + per-channel backward C2C; elementwise stages modeled at
    ~30 flops/bin.  HBM bytes: the input read plus ``hbm_passes``
    spectrum-sized sweeps — the *plan-dependent* traffic floor, taken
    from ``SegmentProcessor.hbm_passes`` (7 for the legacy chain: R2C
    read+write, RFI+chirp read+write, watfft read+write, SK+detect
    read; <= 4 for the fused plans that fold RFI/chirp into the R2C's
    final pass and SK/detect into the watfft write).  Computing the
    model from the per-plan count keeps ``roofline_frac`` honest: a
    fused plan is measured against its own smaller floor instead of
    being silently flattered by the legacy 7-pass model.
    """
    m = n // 2
    wlen = max(m // channel_count, 1)
    flops = 5.0 * m * math.log2(max(m, 2)) \
        + 5.0 * m * math.log2(max(wlen, 2)) \
        + 30.0 * m
    input_bytes = n * abs(nbits) / 8.0
    spectrum_bytes = 8.0 * m  # complex64
    bytes_moved = input_bytes + spectrum_bytes * hbm_passes
    return flops, bytes_moved


def baseline_pass(on_accel: bool, realtime_factor: float) -> bool:
    """The BASELINE.md gate (>= 1x real-time on one accelerator chip) as
    an explicit artifact field, so a perf regression cannot land looking
    green.  A CPU fallback is a fail by definition — the target names
    the chip."""
    return bool(on_accel and realtime_factor >= 1.0)


def parse_args(argv=None):
    """--overlap on|off: A/B legs for the async-dispatch overlap win.
    "on" (default, the historical timer semantics) dispatches all reps
    back to back and syncs once — host time and tunnel RTT hide under
    device compute, the way the runtime's in-flight engine streams.
    "off" is the serial reference leg: a blocking host sync after every
    segment, so the per-segment RTT lands in every segment."""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--overlap", choices=("on", "off"), default="on")
    # fused spectrum tail A/B legs (Config.fused_tail): "on" forces the
    # epilogue-fused plans (requires a non-monolithic strategy, e.g.
    # SRTB_BENCH_FFT_STRATEGY=four_step), "off" the legacy 7-pass chain,
    # "auto" the plan's own resolution.  SRTB_BENCH_FUSED_TAIL is the
    # env spelling the queue scripts use.
    p.add_argument("--fused-tail", choices=("auto", "on", "off"),
                   default=os.environ.get("SRTB_BENCH_FUSED_TAIL", "auto"))
    # front-fused staged megakernel A/B legs (Config.front_fuse, the
    # staged_ffuse family): "on" forces the raw-bytes pass-1 + fused
    # pass-2-epilogue kernels (requires SRTB_BENCH_STAGED=1 +
    # SRTB_STAGED_ROWS_IMPL=pallas2), "off" the classic staged front,
    # "auto" the plan's own resolution.  SRTB_BENCH_FRONT_FUSE is the
    # env spelling the queue scripts use.
    p.add_argument("--front-fuse", choices=("auto", "on", "off"),
                   default=os.environ.get("SRTB_BENCH_FRONT_FUSE",
                                          "auto"))
    # incremental H2D ring A/B legs (Config.ingest_ring).  Both ring
    # legs upload bytes PER REP (the streaming pipeline's real transfer
    # pattern, with overlap-save reserving a tail): "on" re-uploads only
    # the stride through the warm assemble plan, "off" re-uploads the
    # full segment.  The default "none" keeps the historical
    # device-resident-input loop (no per-rep H2D, no reserve) so
    # headline rows stay comparable across rounds.  SRTB_BENCH_RING is
    # the env spelling the queue scripts use.
    p.add_argument("--ring", choices=("on", "off", "none"),
                   default=os.environ.get("SRTB_BENCH_RING", "none"))
    # cross-tenant continuous batching A/B legs (Config.fleet_batch_max,
    # pipeline/fleet._BatchFormer): instead of the solo processor loop,
    # run N same-shape streams through the fleet engine — "on" with the
    # batch former armed (fleet_batch_max=N), "off" with it disabled
    # (every segment its own dispatch).  The delta is the dispatch
    # amortization win.  SRTB_BENCH_FLEET_BATCH is the env spelling the
    # queue scripts use; SRTB_BENCH_FLEET_STREAMS / _FLEET_SEGMENTS
    # size the leg.
    p.add_argument("--fleet-batch", choices=("none", "on", "off"),
                   default=os.environ.get("SRTB_BENCH_FLEET_BATCH",
                                          "none"))
    # perf-ledger output (utils/perf_ledger.py): append this run's
    # measurement — value, per-rep seconds, plan signature hash, host
    # fingerprint, git sha — to the queryable trajectory.
    # SRTB_PERF_LEDGER is the env spelling the queue scripts use.
    p.add_argument("--ledger",
                   default=os.environ.get("SRTB_PERF_LEDGER", ""))
    return p.parse_args(argv)


def run_bench(platform_error, overlap: str = "on",
              fused_tail: str = "auto", ring: str = "none",
              ledger: str = "", front_fuse: str = "auto"):
    import jax

    from srtb_tpu.utils.platform import apply_platform_env
    apply_platform_env()  # main() put the chosen platform in JAX_PLATFORMS

    # FFTW-wisdom analog: reuse compiled programs across bench runs (the
    # staged 2^30 plan compiles for ~10 min cold, O(seconds) cached)
    from srtb_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache()

    from srtb_tpu.config import Config

    platform = jax.default_backend()
    on_accel = platform not in ("cpu",)

    # J1644-4559 parameters (ref: srtb_config_1644-4559.cfg) at a segment
    # size that exercises the large-FFT path while fitting one chip.
    # SRTB_BENCH_* env knobs allow A/B runs of specific code paths
    # without changing the headline default.  The CPU fallback shrinks the
    # segment so a diagnostic line still lands within the driver's budget.
    default_log2n = "27" if on_accel else \
        os.environ.get("SRTB_BENCH_CPU_LOG2N", "21")
    n = 1 << int(os.environ.get("SRTB_BENCH_LOG2N", default_log2n))
    channels = 1 << int(os.environ.get("SRTB_BENCH_LOG2CHAN", "11"))
    cfg = Config(
        baseband_input_count=n,
        baseband_input_bits=2,
        baseband_format_type="simple",
        baseband_freq_low=1405.0 + 32.0,
        baseband_bandwidth=-64.0,
        baseband_sample_rate=128e6,
        # SRTB_BENCH_DM: the reserved fraction scales with |DM|, so the
        # ring legs use it both to fit small CI shapes (the production
        # DM reserves more than a 2^16 segment) and to push the
        # high-reserved-fraction legs where the ring saves the most
        dm=float(os.environ.get("SRTB_BENCH_DM", "-478.80")),
        spectrum_channel_count=channels,
        mitigate_rfi_average_method_threshold=1.5,
        mitigate_rfi_spectral_kurtosis_threshold=1.05,
        signal_detect_signal_noise_threshold=8.0,
        signal_detect_max_boxcar_length=256,
        mitigate_rfi_freq_list="1418-1422",
        # the ring legs measure overlap-save transfer traffic, so they
        # reserve the dedispersion tail (|DM| 478.80 reserves ~16% of a
        # 2^27 segment); the historical headline path keeps reserve off
        baseband_reserve_sample=(ring != "none"),
        ingest_ring=("on" if ring == "on" else "off"),
        fft_strategy=os.environ.get("SRTB_BENCH_FFT_STRATEGY", "auto"),
        use_pallas=bool(int(os.environ.get("SRTB_BENCH_USE_PALLAS", "0"))),
        use_pallas_sk=bool(int(os.environ.get("SRTB_BENCH_USE_PALLAS_SK",
                                              "0"))),
        fused_tail=fused_tail,
        front_fuse=front_fuse,
        # AOT executable cache A/B (utils/aot_cache): run the same
        # config twice with this set — the second run's compile_s is
        # the AOT warm-restart number
        aot_plan_path=os.environ.get("SRTB_BENCH_AOT_DIR", ""),
        # registered search mode (pipeline/registry.py):
        # SRTB_BENCH_SEARCH_MODE=periodicity benches the harmonic-sum
        # + folding plan family (the r8 queue's periodicity legs)
        search_mode=os.environ.get("SRTB_BENCH_SEARCH_MODE",
                                   "single_pulse"),
    )
    # "" = auto (staged at n >= 2^30); "0"/"1" force the plan — the
    # one-program 2^30 experiment (pallas2 has no XLA FFT scratch, so
    # the fused plan may fit where it used to OOM) needs the override
    staged_env = os.environ.get("SRTB_BENCH_STAGED", "")
    # segment bytes + H2D transfer are config-only: do them before any
    # timer so neither compile_s definition counts RNG or transfer time
    rng = np.random.default_rng(0)
    raw = rng.integers(0, 256, size=cfg.segment_bytes(1), dtype=np.uint8)
    raw_dev = jax.device_put(raw)

    # With SRTB_BENCH_AOT_DIR the compile (or the AOT load that replaces
    # it) happens inside SegmentProcessor.__init__, so compile_s must
    # start BEFORE construction for the aot_cold/aot_warm A/B to mean
    # anything.  Without it, keep the historical timer position (first
    # step only) so compile_s rows stay comparable with rounds 2-4 and
    # host-side constant building (chirp banks) isn't miscounted as
    # compile.
    t0 = time.perf_counter()
    # uniform compile accounting (perf observatory): ONE timer started
    # before construction for BOTH protocols — compile_ms covers
    # construction + warmup sync whether the compile happened inside
    # __init__ (AOT load-or-compile) or inside the first dispatch
    # (lazy jit), unlike the legacy compile_s whose start point
    # differs by path (kept below for row comparability with rounds
    # 2+).  The plan/AOT cache counters are metric deltas across the
    # same window.
    from srtb_tpu.utils.metrics import metrics as _metrics
    cache0 = {k: _metrics.get(k) for k in
              ("aot_cache_hits", "aot_cache_misses", "plan_compiles",
               "compile_seconds")}
    t_build = time.perf_counter()
    from srtb_tpu.pipeline import registry
    proc = registry.build_processor(
        cfg, staged=None if staged_env == "" else bool(int(staged_env)))
    # key the timer semantics on AOT actually ENGAGING, not merely being
    # requested: a silently-inactive cache (CPU without the opt-in) must
    # not produce AOT-protocol compile_s rows
    if not getattr(proc, "aot_active", False):
        t0 = time.perf_counter()

    # warmup / compile.  Sync via a host fetch of the (tiny) counts:
    # on some TPU tunnels block_until_ready returns silently on an
    # errored async execution — the error only surfaces at value fetch,
    # and a bench that never fetches would time failures as ~0 s.
    # Ring legs warm BOTH carry-emitting programs (cold + warm assemble)
    # so compile_s covers what the measured loop dispatches.
    if ring == "on":
        (wf, res), carry0 = proc.run_device_cold(raw_dev)
        np.asarray(res.signal_counts)
        del wf, res
        (wf, res), carry0 = proc.run_device_ring(
            carry0, jax.device_put(raw[proc.reserved_bytes:]))
        np.asarray(res.signal_counts)
        del carry0
    else:
        wf, res = proc.run_device(raw_dev)
        np.asarray(res.signal_counts)
    compile_s = time.perf_counter() - t0
    compile_ms = (time.perf_counter() - t_build) * 1e3
    cache_delta = {k: _metrics.get(k) - cache0[k] for k in cache0}
    del wf, res  # a retained 4 GB waterfall would OOM the next 2^30 run

    # optional profiler capture of the steady state (xprof format)
    trace_dir = os.environ.get("SRTB_BENCH_TRACE_DIR", "")
    if trace_dir:
        from srtb_tpu.utils.tracing import device_trace
        with device_trace(trace_dir):
            wf, res = proc.run_device(raw_dev)
            np.asarray(res.signal_counts)
            del wf, res

    # Steady state: dispatch `reps` segments back to back and sync once.
    # This measures streaming throughput the way the runtime actually
    # streams (no host sync between segments); a per-segment host fetch
    # would add the tunnel's ~60 ms dispatch+sync RTT to every segment
    # and understate throughput by up to 3x at 2^27.  Dropping each
    # waterfall handle right after dispatch lets its 4 GB free as soon
    # as its segment completes (2^30 would OOM otherwise).
    reps = int(os.environ.get("SRTB_BENCH_REPS", "5"))
    # the stride's "new" bytes for warm ring reps (length stride_bytes)
    raw_tail = raw[proc.reserved_bytes:] if ring == "on" else None
    h2d_host_s = 0.0
    h2d_bytes_total = 0
    t0 = time.perf_counter()
    last = None
    carry = None
    rep_seconds = []  # per-rep wall: REAL per-segment samples with
    # overlap off (each rep ends in a blocking sync); dispatch-issue
    # times with overlap on (the device sync lands after the loop) —
    # the regression gate should feed on overlap=off legs
    for _ in range(reps):
        t_rep = time.perf_counter()
        if ring == "none":
            wf, res = proc.run_device(raw_dev)
        elif ring == "on" and carry is not None:
            # warm: only the stride's new bytes cross the link; the
            # staging host time is what the async engine hides under
            # device compute (h2d_hidden_ms)
            th = time.perf_counter()
            new_dev = jax.device_put(raw_tail)
            h2d_host_s += time.perf_counter() - th
            h2d_bytes_total += raw_tail.nbytes
            (wf, res), carry = proc.run_device_ring(carry, new_dev)
        else:
            # ring off (full re-upload per segment, the streaming
            # pipeline's pre-ring transfer pattern) or the cold first
            # ring dispatch
            th = time.perf_counter()
            dev = jax.device_put(raw)
            h2d_host_s += time.perf_counter() - th
            h2d_bytes_total += raw.nbytes
            if ring == "on":
                (wf, res), carry = proc.run_device_cold(dev)
            else:
                wf, res = proc.run_device(dev)
        last = res.signal_counts
        del wf, res
        if overlap == "off":
            # serial reference leg (the runtime's inflight_segments=1
            # A/B twin): a blocking host sync per segment, so the
            # per-segment dispatch + tunnel RTT (~60 ms, PERF.md) is
            # paid every time
            np.asarray(last)
        rep_seconds.append(round(time.perf_counter() - t_rep, 5))
    np.asarray(last)
    del carry
    dt = (time.perf_counter() - t0) / reps

    samples_per_sec = n / dt
    msamples = samples_per_sec / 1e6
    realtime_factor = samples_per_sec / cfg.baseband_sample_rate
    flops, bytes_moved = roofline_model(n, channels,
                                        cfg.baseband_input_bits,
                                        hbm_passes=proc.hbm_passes)
    out = {
        "metric": "coherent_dedispersion_pipeline_throughput",
        "value": round(msamples, 2),
        "unit": "Msamples/s/chip",
        "vs_baseline": round(realtime_factor, 3),
        "platform": platform,
        "log2n": int(math.log2(n)),
        "segment_time_s": round(dt, 4),
        "compile_s": round(compile_s, 1),
        # uniform-semantics compile time (construction -> warmup sync,
        # both AOT and lazy-jit protocols) + the cache/compile counter
        # deltas over the same window — every line now says whether
        # its compile was a cache hit, a miss, or a lazy first
        # dispatch, identically across protocols
        "compile_ms": round(compile_ms, 1),
        "aot_cache_hits": int(cache_delta["aot_cache_hits"]),
        "aot_cache_misses": int(cache_delta["aot_cache_misses"]),
        "plan_compiles": int(cache_delta["plan_compiles"]),
        "rep_seconds": rep_seconds,
        "model_gflops": round(flops / 1e9, 1),
        "achieved_gflops_s": round(flops / dt / 1e9, 1),
        "model_hbm_gb": round(bytes_moved / 1e9, 3),
        "achieved_gbps": round(bytes_moved / dt / 1e9, 1),
        "overlap": overlap,
        # per-plan traffic model inputs (spectrum-pass fusion): the plan
        # that actually ran and its modeled spectrum-sweep count, so
        # every artifact line is self-describing about which floor its
        # roofline_frac was computed against
        "plan": proc.plan_name,
        "hbm_passes": proc.hbm_passes,
        "fused_tail": "on" if proc.fused_tail else "off",
        "front_fuse": "on" if getattr(proc, "front_fuse", False)
        else "off",
        "ring": ring,
        "search_mode": proc.MODE,
    }
    if ring != "none":
        # H2D accounting (PERF.md "H2D accounting"): average uploaded
        # bytes per segment (stride model: one cold full segment, then
        # stride_bytes per warm rep) and the host wall time spent
        # staging them — hidden under device compute with overlap on,
        # serialized into every segment with overlap off
        out["h2d_gb"] = round(h2d_bytes_total / reps / 1e9, 4)
        out["h2d_hidden_ms"] = round(h2d_host_s / reps * 1e3, 2)
        out["reserved_frac"] = round(
            proc.reserved_bytes / proc._segment_bytes, 3)
    if int(os.environ.get("SRTB_BENCH_AUDIT", "0")):
        # Roofline cross-check against the compile-time HLO plan
        # auditor (srtb_tpu/analysis/hlo_audit.py): the measured plan's
        # OWN compiled artifacts are re-lowered and their structural
        # spectrum-sized sweeps counted, so the two HBM accountings —
        # model_hbm_gb (the hbm_passes floor model above) and the
        # audited artifact traffic — cite each other in one line.
        # Opt-in (it compiles the plan a second time): ci.sh's bench
        # smoke sets it; big-n TPU headline runs leave it off.
        from srtb_tpu.analysis import hlo_audit as HA
        card = HA.audit_processor(proc)
        spectrum_bytes = 8.0 * proc.n_spectrum
        audited_bytes = raw.nbytes \
            + card["total_spectrum_passes"] * spectrum_bytes
        out["audit_spectrum_passes"] = card["total_spectrum_passes"]
        out["audit_hbm_gb"] = round(audited_bytes / 1e9, 3)
        out["audit_checks_ok"] = not HA.failed_checks({"bench": card})
        # the model is a FLOOR of the artifact's structural traffic: a
        # model claiming >10% more bytes than the audited sweeps means
        # the hbm_passes declaration went stale (e.g. a fusion landed
        # without lowering the declared floor) and achieved_gbps /
        # roofline_frac are being flattered
        if bytes_moved > 1.1 * audited_bytes:
            out["audit_warning"] = (
                f"model_hbm_gb {out['model_hbm_gb']} exceeds audited "
                f"artifact traffic {out['audit_hbm_gb']} by >10% — "
                "hbm_passes floor is stale for this plan")
            print(f"bench: WARNING: {out['audit_warning']}",
                  file=sys.stderr)
    if cfg.aot_plan_path:
        # whether the AOT executable cache actually engaged — the
        # queue's aot_cold/aot_warm verdicts require this to be true
        out["aot_active"] = bool(getattr(proc, "aot_active", False))
    if on_accel:
        # only meaningful against the accelerator's HBM peak — a CPU
        # fallback measurement has no v5e roofline to be a fraction of
        out["roofline_frac"] = round(bytes_moved / dt / 1e9
                                     / V5E_HBM_PEAK_GBPS, 3)
    out["pass"] = baseline_pass(on_accel, realtime_factor)
    if platform_error:
        out["accelerator_error"] = platform_error
    if ledger:
        try:
            from srtb_tpu.utils import perf_ledger as PL
            extra = {k: out[k] for k in
                     ("overlap", "ring", "hbm_passes", "fused_tail",
                      "front_fuse", "compile_s", "compile_ms",
                      "roofline_frac", "achieved_gbps", "vs_baseline",
                      "search_mode")
                     if k in out}
            PL.PerfLedger(ledger).append(PL.make_record(
                "bench", out["value"], out["unit"],
                plan=proc.plan_name,
                plan_signature=proc.plan_signature(),
                shape={"log2n": out["log2n"], "channels": channels,
                       "nbits": cfg.baseband_input_bits},
                platform=platform, samples_s=rep_seconds,
                extra=extra))
        except Exception as e:  # the artifact line must still land
            print(f"bench: WARNING: perf-ledger append failed: {e}",
                  file=sys.stderr)
    emit(out)


def run_fleet_bench(platform_error, leg: str, ledger: str = ""):
    """The --fleet-batch A/B leg: N same-shape streams through the
    fleet engine, batch former armed ("on", fleet_batch_max=N) or
    disabled ("off").  Emits ONE JSON line with the aggregate
    throughput plus the batching counters (batched_dispatches,
    batched_segments, mean batch_size, implied device dispatches), so
    the on/off delta reads directly as dispatch amortization."""
    import tempfile

    import jax

    from srtb_tpu.utils.platform import apply_platform_env
    apply_platform_env()
    from srtb_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache()

    from srtb_tpu.config import Config
    from srtb_tpu.pipeline.fleet import StreamFleet, StreamSpec
    from srtb_tpu.utils.metrics import metrics

    platform = jax.default_backend()
    on_accel = platform not in ("cpu",)
    default_log2n = "21" if on_accel else \
        os.environ.get("SRTB_BENCH_CPU_LOG2N", "16")
    n = 1 << int(os.environ.get("SRTB_BENCH_LOG2N", default_log2n))
    channels = 1 << int(os.environ.get("SRTB_BENCH_LOG2CHAN", "11"))
    streams = max(2, int(os.environ.get("SRTB_BENCH_FLEET_STREAMS",
                                        "4")))
    segments = max(1, int(os.environ.get("SRTB_BENCH_FLEET_SEGMENTS",
                                         "6")))
    reps = int(os.environ.get("SRTB_BENCH_REPS", "3"))
    batch_max = streams if leg == "on" else 0

    tmp = tempfile.mkdtemp(prefix="srtb_fleet_bench_")
    rng = np.random.default_rng(0)

    def stream_cfg(i: int) -> Config:
        # the J1644 shape (2-bit, inverted band) shared across all
        # streams — one plan family, the batchable case.  Reserve off:
        # the leg measures dispatch amortization, not overlap-save.
        path = os.path.join(tmp, f"bb{i}.bin")
        if not os.path.exists(path):
            rng.integers(0, 256, size=(n * 2 // 8) * segments,
                         dtype=np.uint8).tofile(path)
        return Config(
            baseband_input_count=n,
            baseband_input_bits=2,
            baseband_format_type="simple",
            baseband_freq_low=1405.0 + 32.0,
            baseband_bandwidth=-64.0,
            baseband_sample_rate=128e6,
            dm=float(os.environ.get("SRTB_BENCH_DM", "-478.80")),
            spectrum_channel_count=channels,
            mitigate_rfi_average_method_threshold=1.5,
            mitigate_rfi_spectral_kurtosis_threshold=1.05,
            signal_detect_signal_noise_threshold=8.0,
            signal_detect_max_boxcar_length=256,
            mitigate_rfi_freq_list="1418-1422",
            input_file_path=path,
            stream_name=f"bb{i}",
            fft_strategy=os.environ.get("SRTB_BENCH_FFT_STRATEGY",
                                        "auto"),
            fleet_batch_max=batch_max,
        )

    def one_rep() -> tuple:
        metrics.reset()
        specs = [StreamSpec(name=f"bb{i}", cfg=stream_cfg(i),
                            keep_waterfall=False)
                 for i in range(streams)]
        t0 = time.perf_counter()
        fleet = StreamFleet(specs)
        results = fleet.run()
        fleet.close()
        dt = time.perf_counter() - t0
        drained = sum(r.drained for r in results.values())
        return dt, drained, \
            int(metrics.get("batched_dispatches")), \
            int(metrics.get("batched_segments"))

    # rep 1 pays the (shared) compile; the reported value is the
    # median of all reps, with per-rep seconds in the artifact so a
    # cold first rep is visible, not hidden
    rep_out = [one_rep() for _ in range(reps)]
    rep_seconds = [round(dt, 5) for dt, _, _, _ in rep_out]
    dt, drained, bdisp, bsegs = sorted(rep_out)[len(rep_out) // 2]
    seg_s = drained / dt if dt else 0.0
    msamples = seg_s * n / 1e6
    device_dispatches = drained - bsegs + bdisp
    out = {
        "metric": "fleet_batched_throughput",
        "value": round(msamples, 2),
        "unit": "Msamples/s/chip",
        "vs_baseline": round(seg_s * n / 128e6, 3),
        "platform": platform,
        "fleet_batch": leg,
        "fleet_batch_max": batch_max,
        "streams": streams,
        "segments_per_stream": segments,
        "log2n": int(math.log2(n)),
        "drained": drained,
        "elapsed_s": round(dt, 3),
        "rep_seconds": rep_seconds,
        "batched_dispatches": bdisp,
        "batched_segments": bsegs,
        "batch_size_mean": round(bsegs / bdisp, 2) if bdisp else 0.0,
        "device_dispatches": device_dispatches,
        "pass": True,
    }
    if platform_error:
        out["accelerator_error"] = platform_error
    if ledger:
        try:
            from srtb_tpu.utils import perf_ledger as PL
            PL.PerfLedger(ledger).append(PL.make_record(
                "fleet_bench", out["value"], out["unit"],
                plan=f"fleet_batch_{leg}",
                shape={"log2n": out["log2n"], "channels": channels,
                       "nbits": 2, "streams": streams},
                platform=platform, samples_s=rep_seconds,
                extra={k: out[k] for k in
                       ("fleet_batch", "fleet_batch_max",
                        "batched_dispatches", "batched_segments",
                        "batch_size_mean", "device_dispatches",
                        "drained")}))
        except Exception as e:  # the artifact line must still land
            print(f"bench: WARNING: perf-ledger append failed: {e}",
                  file=sys.stderr)
    emit(out)


def _arm_watchdog(platform, err):
    """Hard deadline for the whole bench: a wedged TPU tunnel can hang
    *mid-run* (device_put/compile never returning — observed on a v5e
    after a compiler SIGSEGV wedged the remote helper), where the init
    probe can't help.  On expiry, emit the diagnostic JSON line and exit
    0 so the driver still records an artifact."""
    import threading

    deadline = float(os.environ.get("SRTB_BENCH_DEADLINE", "3000"))
    if deadline <= 0:
        return None

    def fire():
        emit({
            "metric": "coherent_dedispersion_pipeline_throughput",
            "value": 0.0,
            "unit": "Msamples/s/chip",
            "vs_baseline": 0.0,
            "pass": False,
            "error": f"bench deadline exceeded ({deadline:.0f}s): "
                     "backend hang mid-run (wedged tunnel?)",
            "platform": platform,
            "accelerator_error": err,
        })
        os._exit(0)

    t = threading.Timer(deadline, fire)
    t.daemon = True
    t.start()
    return t


def main():
    args = parse_args()
    platform, err = pick_platform()
    os.environ["JAX_PLATFORMS"] = platform
    watchdog = _arm_watchdog(platform, err)
    try:
        if args.fleet_batch != "none":
            run_fleet_bench(err, leg=args.fleet_batch,
                            ledger=args.ledger)
        else:
            run_bench(err, overlap=args.overlap,
                      fused_tail=args.fused_tail,
                      ring=args.ring, ledger=args.ledger,
                      front_fuse=args.front_fuse)
        # disarm before teardown: a slow runtime shutdown must not fire
        # a second, contradictory diagnostic line after the real result
        if watchdog is not None:
            watchdog.cancel()
    except Exception as e:  # always land a JSON diagnostic, never rc != 0
        emit({
            "metric": "coherent_dedispersion_pipeline_throughput",
            "value": 0.0,
            "unit": "Msamples/s/chip",
            "vs_baseline": 0.0,
            "pass": False,
            "error": f"{type(e).__name__}: {e}"[:500],
            "platform": platform,
            "accelerator_error": err,
        })


if __name__ == "__main__":
    main()
