"""Benchmark: sustained coherent-dedispersion pipeline throughput on one
chip, in the J1644-4559 configuration (2-bit samples, 128 MSa/s, |DM| =
478.80, inverted 64 MHz band — ref: srtb_config_1644-4559.cfg).

Prints ONE JSON line:
  {"metric": ..., "value": Msamples/s, "unit": ..., "vs_baseline": x}
where vs_baseline is the real-time factor against the 128 MSa/s baseband
rate (BASELINE.md target: >= 1x real-time on a single v5e chip).
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import os

    import jax

    from srtb_tpu.config import Config
    from srtb_tpu.pipeline.segment import SegmentProcessor

    # J1644-4559 parameters (ref: srtb_config_1644-4559.cfg) at a segment
    # size that exercises the large-FFT path while fitting one chip.
    # SRTB_BENCH_* env knobs allow A/B runs of specific code paths
    # without changing the headline default.
    n = 1 << int(os.environ.get("SRTB_BENCH_LOG2N", "27"))
    cfg = Config(
        baseband_input_count=n,
        baseband_input_bits=2,
        baseband_format_type="simple",
        baseband_freq_low=1405.0 + 32.0,
        baseband_bandwidth=-64.0,
        baseband_sample_rate=128e6,
        dm=-478.80,
        spectrum_channel_count=1 << 11,
        mitigate_rfi_average_method_threshold=1.5,
        mitigate_rfi_spectral_kurtosis_threshold=1.05,
        signal_detect_signal_noise_threshold=8.0,
        signal_detect_max_boxcar_length=256,
        mitigate_rfi_freq_list="1418-1422",
        baseband_reserve_sample=False,
        fft_strategy=os.environ.get("SRTB_BENCH_FFT_STRATEGY", "auto"),
        use_pallas=bool(int(os.environ.get("SRTB_BENCH_USE_PALLAS", "0"))),
    )
    proc = SegmentProcessor(cfg)

    rng = np.random.default_rng(0)
    raw = rng.integers(0, 256, size=cfg.segment_bytes(1), dtype=np.uint8)
    raw_dev = jax.device_put(raw)

    # warmup / compile
    wf, res = proc._jit_process(raw_dev, proc.chirp)
    jax.block_until_ready(res.signal_counts)

    # optional profiler capture of the steady state (xprof format)
    trace_dir = os.environ.get("SRTB_BENCH_TRACE_DIR", "")
    if trace_dir:
        from srtb_tpu.utils.tracing import device_trace
        with device_trace(trace_dir):
            wf, res = proc._jit_process(raw_dev, proc.chirp)
            jax.block_until_ready(res.signal_counts)

    # steady state: time several segments back to back
    reps = int(os.environ.get("SRTB_BENCH_REPS", "5"))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        wf, res = proc._jit_process(raw_dev, proc.chirp)
        jax.block_until_ready(res.signal_counts)
        times.append(time.perf_counter() - t0)
    dt = min(times)

    samples_per_sec = n / dt
    msamples = samples_per_sec / 1e6
    realtime_factor = samples_per_sec / cfg.baseband_sample_rate
    print(json.dumps({
        "metric": "coherent_dedispersion_pipeline_throughput",
        "value": round(msamples, 2),
        "unit": "Msamples/s/chip",
        "vs_baseline": round(realtime_factor, 3),
    }))


if __name__ == "__main__":
    main()
