#!/bin/bash
# Round-10 TPU hardware backlog: cross-tenant continuous batching
# (fleet_batch, ISSUE 17) — the fleet's batch former folds ready
# segments from N same-shape streams into ONE vmapped dispatch, so
# the per-dispatch host + tunnel RTT (~60 ms at 2^27, PERF.md) is
# paid once per batch instead of once per tenant.  These legs are the
# on/off A/B: identical N-stream fleets, the only difference is
# fleet_batch_max (N vs 0).  Read the rows' "batched_dispatches" /
# "batch_size_mean" / "device_dispatches" fields — the off leg must
# show device_dispatches == drained, the on leg ~drained/N.
# On top of the still-undrained r9 backlog.  Safe to re-run; each
# block is independent.  Run from the repo root with the TPU visible
# (tools_tpu_watcher.sh fires it automatically).
#
#   bash tools_tpu_r10_queue.sh [quick]
#
# "quick" drains only the new r10 rows (skips the r9 backlog and the
# long 2^30 blocks).
set -u
OUT=${SRTB_PERF_OUT:-PERF_TPU.jsonl}
stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
note() { echo "{\"ts\": \"$(stamp)\", \"variant\": \"note\", \"note\": \"$1\"}" >> "$OUT"; }
run() {
  local tag="$1"; shift
  echo "== $tag =="
  local line
  line=$("$@" 2>/dev/null | grep '^{' | tail -1)
  if [ -n "$line" ]; then
    echo "{\"ts\": \"$(stamp)\", \"variant\": \"$tag\", \"result\": $line}" >> "$OUT"
    echo "$line"
  else
    echo "{\"ts\": \"$(stamp)\", \"variant\": \"$tag\", \"error\": true}" >> "$OUT"
  fi
}

QUICK=${1:-}

# ---- 0. the r9 backlog first (staged_ffuse A/B + Mosaic probe) ----
if [ "$QUICK" != "quick" ] && [ -f tools_tpu_r9_queue.sh ]; then
  note "r10 queue: draining r9 backlog first"
  bash tools_tpu_r9_queue.sh quick
fi

note "r10 queue start: cross-tenant continuous batching (fleet_batch) A/B"

# ---- 1. fleet-batch A/B at 2^27, 4 streams: the headline pair.
#          Alternated off/on/off/on so drift between legs reads as
#          noise, not as the win (the PERF.md round-18 discipline).
for rep in 1 2; do
  run fleet_batch_off_27_$rep env SRTB_BENCH_LOG2N=27 \
      SRTB_BENCH_FLEET_STREAMS=4 SRTB_BENCH_FLEET_SEGMENTS=6 \
      SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=1800 \
      python bench.py --fleet-batch off
  run fleet_batch_on_27_$rep env SRTB_BENCH_LOG2N=27 \
      SRTB_BENCH_FLEET_STREAMS=4 SRTB_BENCH_FLEET_SEGMENTS=6 \
      SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=1800 \
      python bench.py --fleet-batch on
done

# ---- 2. width sweep at 2^27: where does the amortization flatten?
#          (2 streams = the smallest batch; 8 probes whether a wider
#          vmap still fits HBM at this shape — an error row here is
#          an answer, not a failure.)
run fleet_batch_on_27_w2 env SRTB_BENCH_LOG2N=27 \
    SRTB_BENCH_FLEET_STREAMS=2 SRTB_BENCH_FLEET_SEGMENTS=6 \
    SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=1800 \
    python bench.py --fleet-batch on
run fleet_batch_on_27_w8 env SRTB_BENCH_LOG2N=27 \
    SRTB_BENCH_FLEET_STREAMS=8 SRTB_BENCH_FLEET_SEGMENTS=4 \
    SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=2400 \
    python bench.py --fleet-batch on

if [ "$QUICK" = "quick" ]; then exit 0; fi

# ---- 3. smaller-segment regime, 2^23: dispatch overhead is a larger
#          fraction of segment time here, so the batching win should
#          GROW as the segment shrinks — the many-small-files archive
#          case in fleet form.
run fleet_batch_off_23 env SRTB_BENCH_LOG2N=23 \
    SRTB_BENCH_FLEET_STREAMS=4 SRTB_BENCH_FLEET_SEGMENTS=12 \
    SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=1200 \
    python bench.py --fleet-batch off
run fleet_batch_on_23 env SRTB_BENCH_LOG2N=23 \
    SRTB_BENCH_FLEET_STREAMS=4 SRTB_BENCH_FLEET_SEGMENTS=12 \
    SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=1200 \
    python bench.py --fleet-batch on

note "r10 queue done"
