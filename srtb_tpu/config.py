"""Runtime configuration.

TPU-native re-design of the reference's two-tier config system
(ref: config.hpp:80-249 runtime struct; program_options.hpp:34-309 parsing
with precedence CLI > config file > defaults; arithmetic expressions in
values, e.g. ``2 ** 30``; comma-split lists for multi-receiver options).

Differences from the reference, by design:
- a frozen-ish dataclass passed explicitly instead of a mutable global
  (jit-friendly: derived static quantities hang off this object);
- TPU-specific knobs (`devices`, `dm_list` for multi-chip DM trials).
"""

from __future__ import annotations

import dataclasses
import sys
from dataclasses import dataclass, field

from srtb_tpu.utils.expression import parse_number
from srtb_tpu.utils.logging import log

BITS_PER_BYTE = 8


@dataclass
class Config:
    """Runtime configuration (ref: config.hpp:80-249, same option names)."""

    config_file_name: str = "srtb_config.cfg"

    # count of samples per segment transferred to the device; power of 2
    baseband_input_count: int = 1 << 28
    # bit width of one input sample; negative = signed integer
    baseband_input_bits: int = 8
    # baseband format: simple, interleaved_samples_2 (alias naocpsr_roach2),
    # naocpsr_snap1, gznupsr_a1, gznupsr_a1_v2_1 (ref: io/backend_registry.hpp)
    baseband_format_type: str = "simple"
    # lowest frequency of received baseband signal, MHz
    baseband_freq_low: float = 1000.0
    # bandwidth, MHz (may be negative for inverted bands)
    baseband_bandwidth: float = 500.0
    # samples / second
    baseband_sample_rate: float = 1000e6
    # overlap consecutive segments by nsamps_reserved to mask dedispersion edges
    baseband_reserve_sample: bool = True
    # target dispersion measure, pc cm^-3
    dm: float = 0.0
    # DM trial list for multi-chip DM search (TPU extension; empty = single dm)
    dm_list: list = field(default_factory=list)

    udp_receiver_address: list = field(default_factory=lambda: ["10.0.1.2"])
    udp_receiver_port: list = field(default_factory=lambda: [12004])
    udp_receiver_cpu_preferred: list = field(default_factory=lambda: [0])
    # "block": counter-aligned blocks with reorder tolerance
    # (udp_receive_block_worker, ref: udp_receiver.hpp:180-272);
    # "continuous": strictly sequential gap-free stream, payloads straddle
    # segment boundaries (continuous_udp_receiver_worker, ref: 42-168)
    udp_receiver_mode: str = "block"
    # packet provider for block mode (ref dispatch:
    # udp_receiver_pipe.hpp:158-187): "recvmmsg" = batched syscalls
    # (native, default), "packet_ring" = AF_PACKET TPACKET_V3 mmap ring
    # (native, needs CAP_NET_RAW), "recvfrom" = pure-Python fallback
    udp_packet_provider: str = "recvmmsg"
    # interface the packet_ring provider captures on
    udp_packet_ring_interface: str = "lo"
    # SO_RCVBUF request for the receiver sockets (the reference hardcodes
    # its SO_RCVBUF, recvmmsg_packet_provider.hpp:79; a knob because the
    # right size is deployment-specific: big enough to ride out a
    # compile-time stall, small enough that overload surfaces as prompt
    # accounted loss instead of seconds of silent latency)
    udp_receiver_rcvbuf_bytes: int = 1 << 28

    input_file_path: str = ""
    input_file_offset_bytes: int = 0
    baseband_output_file_prefix: str = "srtb_baseband_output_"
    baseband_write_all: bool = False
    # stamp segment timestamps deterministically from the STREAM
    # OFFSET instead of the wall clock (io/file_input.py
    # DeterministicTimestampReader): the same segment gets the same
    # stamp in every run and every resume, so file-mode artifact names
    # (timestamp-derived when no UDP counter exists) reproduce across
    # runs — what makes an archive replay's output set comparable
    # byte-for-byte against a golden run, and what the crash/archive
    # soaks' exactly-once path+SHA-256 equality gates build on.
    # File sources only; ignored for UDP (real packets carry counters).
    deterministic_timestamps: bool = False

    log_level: int = 3

    mitigate_rfi_average_method_threshold: float = 10.0
    mitigate_rfi_spectral_kurtosis_threshold: float = 1.1
    # "11-12, 15-90" style frequency pairs to zap
    mitigate_rfi_freq_list: str = ""

    spectrum_sum_count: int = 1
    # count of complex channels in spectrum waterfall
    spectrum_channel_count: int = 1 << 15

    signal_detect_signal_noise_threshold: float = 6.0
    signal_detect_channel_threshold: float = 0.9
    signal_detect_max_boxcar_length: int = 1024

    # ---- search mode (pipeline/registry.py registered modes) ----
    # "single_pulse": the reference's boxcar cascade.  "periodicity":
    # single-pulse PLUS a harmonic-summed power-spectrum search over
    # the dedispersed time series with phase-folded profiles at the
    # top candidates (ops/periodicity.py; the FPGA pulsar-search
    # paper's module set), inside the same traced program — every
    # execution plan (fused/staged/ring/micro-batch) carries it.
    # Registered modes land in the plan auditor, the demotion ladder
    # (which sheds the mode FIRST on a device fault) and the fleet
    # automatically.
    search_mode: str = "single_pulse"
    # max harmonics summed incoherently (ladder 1, 2, 4, ... <= this)
    periodicity_harmonics: int = 8
    # top-K candidates folded per stream (static shape)
    periodicity_candidates: int = 4
    # phase bins of each folded pulse profile
    periodicity_fold_bins: int = 64
    # exclude power-spectrum bins below this (DC + red-noise leakage)
    periodicity_min_bin: int = 2
    # a segment is "positive" (candidate files written) when any
    # folded candidate's harmonic-summed score reaches this MARGIN
    # above the trials-expected noise maximum: the per-bin score is
    # ~exponential under noise, so its max over (searched bins x
    # harmonic levels) trials sits near ln(trials) — the gate
    # compares against ln(trials) + this margin (Gumbel scale ~1 per
    # unit; 5 = roughly an e^-5 per-segment false-positive rate).
    # Candidates are always computed and journaled regardless — the
    # gate only decides whether the segment writes candidate files.
    periodicity_snr_threshold: float = 5.0

    thread_query_work_wait_time: int = 1000

    gui_enable: bool = False
    gui_pixmap_width: int = 1920
    gui_pixmap_height: int = 1080
    # serve live waterfall frames over HTTP on this port (0 = disabled;
    # TPU-headless replacement for the reference's Qt windows)
    gui_http_port: int = 0

    # ---- TPU-specific options (no reference equivalent) ----
    # number of devices to use; 0 = all local devices
    n_devices: int = 0
    # use two-float (df64) on-device chirp generation instead of host f64
    use_emulated_fp64: bool = False
    # resume state file for file-mode streaming ("" = disabled)
    checkpoint_path: str = ""
    # durable exactly-once outputs (io/manifest.py): append-only,
    # CRC'd run-manifest WAL recording intent->commit for every sink
    # artifact plus the checkpoint consistency point.  On startup the
    # manifest is recovered (torn tail truncated, uncommitted intents
    # rolled back, committed segments rebuilt into a done-set so a
    # resumed run skips already-written artifacts instead of
    # duplicating them).  Verify/repair offline with
    # `python -m srtb_tpu.tools.fsck`.  "" = disabled.
    run_manifest_path: str = ""
    # arm the WAL's two durability points (io/manifest.py): the
    # publish barrier (pending intents fdatasync'd between an
    # artifact's temp write and its atomic rename — no artifact
    # reaches its final name before the WAL durably holds the intent)
    # and the checkpoint consistency-point record.  0 drops both:
    # process-death (SIGKILL) recovery is unaffected — the page cache
    # survives the process — but power loss may then leak an
    # untracked renamed artifact.
    manifest_fsync: bool = True
    # record a CRC32 of every committed artifact's content in the WAL
    # (fsck's deep bit-rot check).  Costs ~1 ms per dumped MB on the
    # sink path; 0 drops to existence+size verification — worth it
    # only for deployments dumping multi-GB baseband per candidate.
    manifest_hash: bool = True
    # persistent XLA compile cache dir; the FFTW-wisdom analog
    # ("" = default ~/.cache location, "off" = disabled)
    fft_fftw_wisdom_path: str = ""
    # AOT executable cache dir ("" = disabled): persists the segment
    # plan's *compiled executables* across process restarts
    # (utils/aot_cache.py) — the warm-restart fallback for deployments
    # where the XLA compile cache is bypassed by a remote-compile
    # service.  Off on CPU backends unless SRTB_AOT_ALLOW_CPU=1.
    aot_plan_path: str = ""
    # segment R2C strategy:
    # auto | monolithic | four_step | mxu | pallas | pallas2
    fft_strategy: str = "auto"
    # longest 1-D row length handed to XLA's FFT directly; longer rows
    # recurse into the four-step decomposition (0 = the library default,
    # ops/fft._XLA_FFT_LEN_CAP = 2^16 measured on v5e).  Lowering it
    # forces the recursion at tiny shapes — how the multichip dryrun
    # exercises the production 2^30 in-shard code path without 2^30
    # samples
    fft_len_cap: int = 0
    # use Pallas fused kernels where available (fused RFI-s1 + df64
    # chirp-multiply, VMEM row-FFT waterfall C2C)
    use_pallas: bool = False
    # fused SK-zap + time-series Pallas kernel: separate knob because it
    # measured *slower* than the jnp pair at bench shapes
    # (PERF_TPU.jsonl kernel rows) — opt-in for shapes where the 2-read
    # pass wins
    use_pallas_sk: bool = False
    # fused spectrum tail ("auto" | "on" | "off"): fold RFI stage 1 +
    # the dedispersion chirp into the forward FFT's final (Hermitian
    # post-process) pass so the spectrum is written to HBM exactly once,
    # already zapped/normalized/masked/chirped; with use_pallas +
    # use_pallas_sk the SK zap + detection time series additionally fold
    # into the waterfall FFT's write (ops/pallas_fft.fft_rows_skzap_ri)
    # and the detect stage never re-reads the waterfall.  "auto" = on
    # for every plan whose final pass can host the epilogue (four_step /
    # mxu / pallas / pallas2 / staged), off for the monolithic XLA R2C
    # custom call; "on" forces it (errors on monolithic); "off"
    # restores the legacy 7-pass chain.  SegmentProcessor.hbm_passes
    # reports the resulting modeled spectrum-pass count (bench.py
    # roofline).
    fused_tail: str = "auto"
    # front-fused staged megakernel ("auto" | "on" | "off"): fold the
    # sub-byte unpack + window + even/odd pack + forward-FFT pass 1
    # into the pallas2 row-FFT kernel (raw bytes in, blocked
    # intermediate out) and the whole spectrum tail — Hermitian
    # post-process, RFI s1, dedispersion chirp — into pass 2's
    # epilogue, so a staged segment's front half completes in 2 HBM
    # sweeps (SegmentProcessor.hbm_passes = 2; the staged_ffuse plan
    # family, ops/pallas_fft2).  Requires the staged plan with
    # SRTB_STAGED_ROWS_IMPL=pallas2, a fusable tail, and an unpack
    # variant the kernel can spell in-register (simple 1/2/4/8-bit or
    # 2-pol byte-interleaved).  "auto" = on when all of that holds AND
    # the kernels are trusted (the FFUSE_MOSAIC_OK probe flag or
    # SRTB_PALLAS_FFUSE=1 — never implicitly, so existing pallas2
    # configs keep their plan); "on" forces (errors when structurally
    # impossible — how the staged_ffuse family, tests and the
    # hardware-probe legs select it); "off" restores the classic
    # staged front.  The
    # demotion ladder's front_fuse rung drops exactly this knob, so a
    # Mosaic rejection heals onto today's audited staged plan.
    front_fuse: str = "auto"
    # escape hatch: force the exact per-element df64 chirp evaluation
    # (~3 df64 divisions/channel) instead of the anchored-Taylor fast
    # path that is the default everywhere (segment plans, Pallas
    # kernels, DM-grid on-device banks) — a paranoia/A-B knob; the
    # anchored path agrees with the exact one to ~1e-9 turns
    # (ops/dedisperse.anchored_chirp_consts error budget)
    chirp_exact: bool = False
    # incremental H2D overlap-save ring ("auto" | "on" | "off"): keep
    # each segment's reserved tail device-resident as a raw-byte carry
    # so every warm dispatch uploads only the stride's NEW bytes — H2D
    # bytes per segment drop by exactly the reserved fraction,
    # bit-identically (pipeline/segment.py ring plans; the carry
    # donation is a proven input->output alias, checked by the plan
    # audit).  "auto" = on whenever overlap-save reserves a byte-
    # aligned non-empty tail; "on" forces it (errors when nothing is
    # reserved); "off" restores full per-segment uploads and the file
    # reader's legacy seek-back re-reads.  Cold full uploads (first
    # segment, watchdog requeue, dispatch retry, shed, checkpoint
    # resume) re-arm the carry from the retained host buffer.
    ingest_ring: str = "auto"
    # bounded window of segments dispatched to the device before the
    # oldest result is drained (pipeline/runtime.py async engine):
    # ingest + unpack + H2D staging of segment k+1..k+W-1 run while the
    # device computes segment k, and fetch polls device readiness
    # instead of blocking.  1 = fully serial (the A/B reference leg);
    # 2-3 hides host time under device compute (the reference's
    # queue-capacity-2 pipe graph, config.hpp:40-43)
    inflight_segments: int = 2
    # micro-batch: stack B consecutive segments into ONE jit call
    # (vmapped fused plan) to amortize per-dispatch host overhead and
    # tunnel RTT (~60 ms per host sync, PERF.md) over B segments.
    # 1 = off; >1 requires the fused plan (not staged)
    micro_batch_segments: int = 1
    # opt-in runtime sanitizer (analysis/sanitizer.py): traps implicit
    # device->host transfers, NaN/Inf at segment-plan boundaries,
    # stage shape/dtype contract breaks, wrong-thread access to engine
    # window state, leaked threads, and makes use-after-donate loud on
    # every backend.  Serializes dispatch — a debugging mode with zero
    # cost when off.  A/B methodology: PERF.md "Sanitizer".
    sanitize: bool = False
    # opt-in runtime concurrency checker (analysis/tsan.py): lockdep
    # acquisition-order graph with live cycle traps, held-too-long
    # stall log, and claim-on-first-use ownership guards on fleet lane
    # state and batch-former group slots.  The fleet holds None when
    # off — zero wrapper indirection on the hot path.  Driven under
    # schedule perturbation by tools/race_soak.py.
    tsan: bool = False
    # fail-fast watchdog on the per-segment device sync (seconds,
    # 0 = disabled): a wedged accelerator runtime otherwise hangs the
    # observation silently — on expiry the process aborts through the
    # termination handler (loud stacktrace), matching the reference's
    # fail-loudly philosophy (ref: util/termination_handler.hpp)
    segment_deadline_s: float = 0.0
    # ---- resilience (srtb_tpu/resilience/) ----
    # retry budget for the pipeline's guarded operations (ingest read,
    # H2D staging, dispatch, fetch, sink write, checkpoint flush);
    # includes the first attempt, <= 1 disables retries entirely
    # (zero-cost-off, like the sanitizer).  Only failures classified
    # transient/data-loss by resilience/errors.py are retried.
    retry_max_attempts: int = 3
    # exponential backoff: base * 2^(attempt-1), capped, with
    # deterministic +/-25% jitter (hash of site+attempt, not random)
    retry_backoff_base_s: float = 0.05
    retry_backoff_max_s: float = 2.0
    # total wall-clock budget of one guarded operation including its
    # backoff sleeps (0 = unbounded): bounds worst-case added latency
    retry_deadline_s: float = 0.0
    # segment watchdog: with segment_deadline_s > 0, an in-flight
    # segment whose fetch never becomes ready within the deadline is
    # cancelled and re-dispatched up to this many times before the
    # run escalates (0 keeps the legacy abort-on-deadline behavior).
    # Scope: the requeue covers the drain-head COMPUTE wedge (results
    # never materialize).  A wedge inside a blocking D2H transfer that
    # already started (the sink's lazy multi-GB waterfall fetch) is
    # uninterruptible from Python and still takes the legacy fail-fast
    # abort after segment_deadline_s — loud exit over a silent hang.
    segment_watchdog_requeues: int = 0
    # bounded restarts for crashed workers (sink drain pipe, GUI
    # server): this many restarts within supervisor_window_s, then
    # escalation to clean shutdown; 0 disables supervision (every
    # crash propagates immediately, the pre-resilience behavior)
    supervisor_max_restarts: int = 3
    supervisor_window_s: float = 60.0
    # graceful-degradation ladder (resilience/degrade.py): under
    # sustained sink backlog or accounted loss, shed waterfall dumps,
    # then baseband dumps, then name whole-segment loss.  Hysteresis:
    # step after degrade_hold_segments consecutive drains above
    # degrade_queue_high occupancy; recover below degrade_queue_low.
    degrade_enable: bool = True
    degrade_queue_high: float = 0.9
    degrade_queue_low: float = 0.25
    degrade_hold_segments: int = 3
    # ---- self-healing compute (resilience/demote.py) ----
    # plan-demotion ladder for device OOM / compile faults: "auto"
    # walks search_mode -> micro_batch -> front_fuse -> ring -> skzap
    # -> fused_tail
    # -> staged -> monolithic (the registry's canonical order,
    # cumulatively, skipping rungs the active config
    # doesn't use); an explicit comma list selects a subset in that
    # order; "off" disables demotion (device faults escalate like any
    # fatal).  Each demotion rebuilds the segment plan from the rung's
    # config (the AOT cache misses cleanly via plan_signature) and
    # re-dispatches the faulted segment cold from its retained host
    # buffer.  Every demotion-ladder target is audited: the plan-audit
    # CI gate proves each rung resolves to a carded plan family.
    plan_ladder: str = "auto"
    # promotion probe: after this many consecutively healthy segments
    # on a demoted plan, step one rung back up (the next dispatch
    # probes the richer plan; a recurring fault just demotes again).
    # 0 = stay demoted for the rest of the run.
    promote_after_segments: int = 0
    # device-halt recovery: tear down in-flight device state, clear
    # the jax caches, rebuild the processor (fresh executables on the
    # new backend handle) and re-dispatch in-flight segments from
    # their retained host buffers — at most this many reinits within
    # device_reinit_window_s, then escalation (a flapping device must
    # not flap forever).  0 disables reinit recovery.
    device_reinit_max: int = 2
    device_reinit_window_s: float = 300.0
    # deterministic fault injection (resilience/faults.py):
    # "site:action@index,..." with sites ingest|h2d|dispatch|fetch|
    # sink_write|checkpoint and actions raise|fatal|corrupt|
    # stall=SECONDS, plus the device-fault actions oom|compile_fail|
    # device_halt (h2d/dispatch/fetch sites only — they raise with
    # the real jax exception strings so the self-healing ladder's
    # string classifier is exercised); "" = off (zero cost)
    fault_plan: str = ""
    # bounded join of worker threads at shutdown (pipeline sink pipe,
    # ThreadedPipeline drain): on expiry the wedged thread is reported
    # (name + stack) via utils/termination, still-queued segments are
    # accounted as segments_dropped, and shutdown proceeds WITHOUT
    # flushing the wedged sink's writer pools.  0 (default) waits
    # forever: a slow-but-healthy final flush of a multi-GB waterfall
    # must not be cut short and silently lose dumps — arm this only
    # for real-time deployments that prefer bounded exit over
    # completeness (recommended 120-300 there).
    shutdown_join_timeout_s: float = 0.0
    # ---- multi-tenant stream fleet (pipeline/fleet.py) ----
    # label of THIS stream in a fleet: stamps telemetry spans (v6
    # ``stream`` field), per-stream Prometheus labels, /healthz
    # per-stream staleness, and scopes fault_plan entries carrying a
    # stream selector ("stream0:dispatch:oom@3").  "" = unnamed
    # single-stream run (everything reads exactly as before).
    stream_name: str = ""
    # admission/shedding priority of this stream (higher = more
    # important): when the fleet is over capacity, lower-priority
    # streams are queued/rejected first, and under fleet-wide sink
    # pressure the lowest-priority REAL-TIME stream is shed first
    # (resilience/degrade.FleetShedPolicy).
    stream_priority: int = 0
    # max concurrently admitted streams in a StreamFleet (0 = no
    # admission limit); streams beyond capacity are queued (up to
    # fleet_queue_limit, priority order) or rejected.  Read from the
    # FLEET config (the first spec's cfg), not per stream.
    fleet_max_streams: int = 0
    # queued-stream slots behind the admission gate (0 = reject
    # immediately when over capacity)
    fleet_queue_limit: int = 0
    # cross-tenant continuous batching: max segments from DIFFERENT
    # lanes sharing a plan_cache_key folded into one vmapped device
    # dispatch (pipeline/fleet._BatchFormer).  0 or 1 = off (every
    # lane dispatches solo, bit-identical to the pre-batching fleet).
    # Read from the FLEET config (the first spec's cfg), not per
    # stream.  Batched lanes trade bit-exactness of float artifacts
    # for dispatch amortization: .bin candidates stay bitwise equal,
    # .tim/.npy match solo within the documented vmap tolerance.
    fleet_batch_max: int = 0
    # how long a partially formed batch may wait for co-tenants
    # before it is flushed anyway (milliseconds) — a lone tenant
    # never waits longer than this for neighbors that may not come
    fleet_batch_linger_ms: float = 2.0
    # elastic device pool (pipeline/pool.py): number of pool members
    # the fleet places lanes across.  0/1 = the single-device fleet
    # (bit-identical to the pre-pool engine).  >= 2 on an accelerator
    # host maps onto real jax.devices() (capped at the hardware
    # count); on CPU it builds a deterministic VIRTUAL pool — N
    # logical devices with distinct plan caches / batch families /
    # HALT domains on one physical device (what CI's migration gates
    # run on).  Read from the FLEET config, not per stream.
    fleet_devices: int = 0
    # SLO-driven rebalance: when the burn-rate tracker (utils/slo.py)
    # marks a stream degraded/burning and a strictly less-loaded
    # healthy pool member exists, live-migrate that stream onto it
    # before the error budget is spent.  Needs fleet_devices >= 2 and
    # an armed SLO objective.  Read from the FLEET config.
    migrate_on_burn: bool = False
    # live-migration drain budget (seconds): how long a TRUSTED
    # migration (rebalance / rolling restart — the source device is
    # healthy) may spend draining the lane's in-flight window before
    # the remainder moves via cold re-dispatch instead.  Halted-device
    # migrations never drain (the in-flight results died with the
    # device); cold re-dispatch is lossless either way.
    drain_deadline_s: float = 5.0
    # segment-span telemetry journal: one JSONL record per processed
    # segment (per-stage wall clock, queue depth, loss counters,
    # detection count, dump decision — utils/telemetry.py); "" disables.
    # Summarize with `python -m srtb_tpu.tools.telemetry_report`.
    telemetry_journal_path: str = ""
    # size-rotate the journal when the active file would exceed this
    # (one previous generation kept)
    telemetry_journal_max_bytes: int = 64 << 20
    # gzip the rotated generation (<path>.1.gz instead of <path>.1):
    # a long soak's journal history stays bounded AND small; the
    # reader/report handle both transparently.  0 keeps plaintext.
    telemetry_journal_compress: bool = True
    # ---- causal tracing + flight recorder (utils/events.py) ----
    # arm the process-global event hub: every SegmentWork carries a
    # trace_id and every subsystem that touches it (stage edges,
    # retries, heal/demote decisions, degrade/admission, watchdog,
    # supervisor, ring transitions, manifest records) emits typed
    # monotonic-clocked events onto a bounded per-thread ring — the
    # always-on flight recorder incident bundles and
    # tools/trace_export.py read.  0 disarms (the zero-cost-off
    # None-hook path; PERF.md round 17 A/B).  Process-global, like
    # the metrics registry.
    events_enable: bool = True
    # flight-recorder ring slots PER THREAD (O(ring) memory, no
    # per-event allocation growth)
    events_ring_size: int = 4096
    # write the flight-recorder contents (merged, oldest-first JSONL)
    # here at Pipeline.close() — the input of
    # `python -m srtb_tpu.tools.trace_export`; "" disables
    events_dump_path: str = ""
    # ---- incident bundles (utils/incidents.py) ----
    # on any escalation (LadderExhausted, ReinitBudgetExceeded,
    # WatchdogEscalation, wedged sink, failed fleet lane,
    # manifest-recovery LOSS) dump a self-contained bundle directory
    # here: flight-recorder tail, the offending segment's causal
    # trace, active plan + signature, config + metrics snapshots, last
    # journal spans.  Atomic (temp+rename), rate-limited and bounded
    # in count.  "" disables.
    incident_dir: str = ""
    incident_max_bundles: int = 8
    incident_min_interval_s: float = 30.0
    # ---- SLO burn-rate objectives (utils/slo.py) ----
    # per-stream error-budget burn evaluation over a fast + slow
    # window pair; states ok / degraded (violations within budget) /
    # burning (both windows above slo_burn_threshold) on /healthz and
    # as slo_burn_rate / slo_state gauges on /metrics.  Each objective
    # arms independently: latency (per-segment host wall clock >
    # slo_latency_ms counts against slo_latency_budget), loss
    # (accounted whole-segment drops against slo_loss_budget),
    # staleness (gap beyond slo_staleness_s against
    # slo_staleness_budget as a window fraction).  0 targets = off.
    slo_latency_ms: float = 0.0
    slo_latency_budget: float = 0.01
    slo_loss_budget: float = 0.0
    slo_staleness_s: float = 0.0
    slo_staleness_budget: float = 0.05
    slo_fast_window_s: float = 300.0
    slo_slow_window_s: float = 3600.0
    slo_burn_threshold: float = 1.0
    # sensitivity objective (pulse-injection canary feed): allowed
    # fraction of FAILED canary checks before the burn rate reads 1.0
    # (> 0 arms; needs canary_every_segments > 0 to get observations)
    slo_sensitivity_budget: float = 0.0
    # ---- science observatory (srtb_tpu/quality/) ----
    # on-device per-segment data-quality statistics as a cheap
    # epilogue side-output of the segment plans: zapped-bin fraction,
    # coarse RFI occupancy map, spectral-kurtosis summary, bandpass
    # mean/variance + EWMA drift detector, dead/hot channel flags —
    # exported as quality_* gauges, journaled on segment spans
    # (telemetry v9) and rendered by tools/quality_report.py.  Enters
    # the traced program (trace-relevant: plans with/without the
    # epilogue are different programs and miss the AOT cache cleanly).
    quality_stats: bool = False
    # coarse bins of the occupancy/bandpass maps (trace-relevant:
    # static output shape)
    quality_coarse_bins: int = 64
    # a channel is DEAD below this multiple of the median channel
    # power, HOT above the hot multiple (trace-relevant constants)
    quality_dead_threshold: float = 0.1
    quality_hot_threshold: float = 10.0
    # read every k-th spectrum bin / waterfall sample for the quality
    # statistics (trace-relevant).  Telemetry does not need every bin:
    # subsampling scales the epilogue's read volume — and the producer
    # recompute XLA sometimes chooses for a second consumer — down by
    # k, which is what keeps the epilogue under the perf gate's noise
    # floor on the CPU path.  1 = exact statistics.
    quality_subsample: int = 8
    # host-side EWMA drift detector on the bandpass mean: alert when
    # an observation sits more than quality_drift_threshold EWMA
    # sigmas from the running mean (alpha = smoothing weight)
    quality_drift_threshold: float = 4.0
    quality_drift_alpha: float = 0.05
    # ---- pulse-injection canary (srtb_tpu/quality/canary.py) ----
    # inject a deterministic synthetic dispersed pulse into the RAW
    # uint8 stream every N segments (0 = off) and check the recovered
    # S/N at the detection stage.  Canary segments are quarantined
    # from science outputs (signals gate + candidate sinks) and
    # flagged in journal + run manifest; non-canary artifacts stay
    # bit-identical to a canary-off run.  8-bit 'simple' format only.
    canary_every_segments: int = 0
    # per-sample pulse amplitude in digitizer counts (the 8-bit
    # digitizer model keeps ~3 sigma full-scale, i.e. noise sigma
    # ~42.5 counts — 25 is a comfortably-detectable burst)
    canary_amp: float = 25.0
    # burst width in raw samples
    canary_width: int = 32
    # dispersion measure of the injected pulse (< 0 = use `dm`, so
    # the search recovers it coherently by default)
    canary_dm: float = -1.0
    # pulse start as a fraction of the segment's non-overlapped span
    canary_position: float = 0.5
    # expected recovered S/N; 0 = auto-calibrate from the first
    # checked canary of the run (the calibration is journaled)
    canary_expected_snr: float = 0.0
    # a canary FAILS when recovered/expected drops below this ratio
    # — drives detection_health_state, /healthz detection section,
    # the SLO sensitivity objective and an incident bundle
    canary_min_ratio: float = 0.5
    # ---- performance observatory ----
    # HBM peak (GB/s) the live roofline_frac gauge divides by (v5e
    # public number by default; set per accelerator generation).  The
    # gauge is a LOWER bound by construction: the traffic model is the
    # active plan's audited hbm_passes floor and the device wall is an
    # upper bound (see pipeline/runtime.py _device_time_account).
    hbm_peak_gbps: float = 819.0
    # record a REAL jax.profiler (XLA) trace of the first N drained
    # segments of a run into profile_capture_dir, next to the Perfetto
    # event export; the capture.json sidecar records the covered
    # trace_ids so the device timeline and the causal-event timeline
    # join exactly.  0 = off (zero cost).
    profile_capture_segments: int = 0
    profile_capture_dir: str = "artifacts/profile"
    # append one "steady" perf record per finished run to this perf
    # ledger (utils/perf_ledger.py JSONL; tools/perf_report.py renders
    # the trajectory, tools/perf_gate.py gates regressions).  "" = off.
    perf_ledger_path: str = ""
    # ---- fleet control tower (srtb_tpu/obs/) ----
    # long-horizon rollup store directory the aggregator writes
    # (obs/rollup.py tails the lanes' journals + event dumps into
    # per-minute rollups, quantile digests and the fleet event
    # timeline; gui/server.py's /fleet and tools/console.py read it).
    # "" = off (zero cost).
    obs_store_dir: str = ""
    # downsampling resolution of the rollup minute-series (seconds
    # per bucket)
    obs_rollup_resolution_s: int = 60
    # compaction drops rollup rows older than this many minutes
    # behind the newest minute IN THE DATA (0 = keep everything)
    obs_retention_minutes: int = 0
    # mid-run regression watch (obs/regression.py): both the live
    # rollup and the ledger history must have at least this many
    # per-segment samples before a verdict is attempted
    obs_regression_min_samples: int = 8
    # extra required effect on top of the computed noise floor
    # (fractional; 0.0 = the floor alone decides)
    obs_regression_min_effect: float = 0.0
    # /healthz flips to 503 when the last processed segment is older
    # than this many seconds (gui/server.py staleness detection)
    health_stale_after_s: float = 30.0
    # candidate-writer thread count; >0 uses the async writer pool (native
    # C++ when built — the reference's boost thread pools,
    # write_signal_pipe.hpp:159-280), 0 writes synchronously
    writer_thread_count: int = 2
    # scrolling-waterfall GUI mode: lines contributed per segment
    # (0 = simple whole-segment frames, like the reference's live
    # SimpleSpectrumImageProvider vs legacy scrolling provider)
    gui_scroll_lines: int = 0
    # multi-host process group (jax.distributed); the DCN layer the
    # reference lacks. coordinator is "host:port" of process 0
    distributed_coordinator: str = ""
    distributed_num_processes: int = 1
    distributed_process_id: int = 0

    # ------------------------------------------------------------------
    # derived quantities
    # ------------------------------------------------------------------

    @property
    def bytes_per_sample(self) -> float:
        return abs(self.baseband_input_bits) / BITS_PER_BYTE

    @property
    def baseband_freq_high(self) -> float:
        return self.baseband_freq_low + self.baseband_bandwidth

    def segment_bytes(self, data_stream_count: int = 1) -> int:
        """Bytes of one input segment (all interleaved streams)."""
        return int(self.baseband_input_count * self.bytes_per_sample
                   * data_stream_count)

    # ------------------------------------------------------------------
    # loading
    # ------------------------------------------------------------------

    _INT_FIELDS = frozenset({
        "baseband_input_count", "baseband_input_bits",
        "input_file_offset_bytes", "spectrum_sum_count",
        "spectrum_channel_count", "signal_detect_max_boxcar_length",
        "thread_query_work_wait_time", "gui_pixmap_width",
        "gui_pixmap_height", "gui_http_port", "n_devices", "log_level",
        "writer_thread_count", "distributed_num_processes",
        "distributed_process_id", "gui_scroll_lines",
        "telemetry_journal_max_bytes", "inflight_segments",
        "micro_batch_segments", "retry_max_attempts",
        "segment_watchdog_requeues", "supervisor_max_restarts",
        "degrade_hold_segments", "promote_after_segments",
        "device_reinit_max", "stream_priority", "fleet_max_streams",
        "fleet_queue_limit", "fleet_devices", "periodicity_harmonics",
        "periodicity_candidates", "periodicity_fold_bins",
        "periodicity_min_bin", "events_ring_size",
        "incident_max_bundles", "profile_capture_segments",
        "quality_coarse_bins", "quality_subsample",
        "canary_every_segments", "canary_width",
        "obs_rollup_resolution_s", "obs_retention_minutes",
        "obs_regression_min_samples",
    })
    _FLOAT_FIELDS = frozenset({
        "baseband_freq_low", "baseband_bandwidth", "baseband_sample_rate",
        "dm", "mitigate_rfi_average_method_threshold",
        "mitigate_rfi_spectral_kurtosis_threshold",
        "signal_detect_signal_noise_threshold",
        "signal_detect_channel_threshold", "segment_deadline_s",
        "health_stale_after_s", "retry_backoff_base_s",
        "retry_backoff_max_s", "retry_deadline_s",
        "supervisor_window_s", "degrade_queue_high",
        "degrade_queue_low", "shutdown_join_timeout_s",
        "device_reinit_window_s", "periodicity_snr_threshold",
        "incident_min_interval_s", "slo_latency_ms",
        "slo_latency_budget", "slo_loss_budget", "slo_staleness_s",
        "slo_staleness_budget", "slo_fast_window_s",
        "slo_slow_window_s", "slo_burn_threshold", "drain_deadline_s",
        "hbm_peak_gbps",
        "slo_sensitivity_budget", "quality_dead_threshold",
        "quality_hot_threshold", "quality_drift_threshold",
        "quality_drift_alpha", "canary_amp", "canary_dm",
        "canary_position", "canary_expected_snr", "canary_min_ratio",
        "obs_regression_min_effect",
    })
    _BOOL_FIELDS = frozenset({
        "baseband_reserve_sample", "baseband_write_all", "gui_enable",
        "use_emulated_fp64", "use_pallas", "use_pallas_sk", "sanitize",
        "tsan",
        "degrade_enable", "chirp_exact", "manifest_fsync",
        "manifest_hash", "deterministic_timestamps", "events_enable",
        "telemetry_journal_compress", "quality_stats",
        "migrate_on_burn",
    })
    _LIST_FIELDS = frozenset({
        "udp_receiver_address", "udp_receiver_port",
        "udp_receiver_cpu_preferred", "dm_list",
    })

    def set_option(self, key: str, value: str) -> bool:
        """Set one option from its string form, with expression evaluation
        (ref: program_options.hpp:197-263).  Returns False for unknown keys."""
        key = key.strip()
        if not hasattr(self, key):
            return False
        if key in self._INT_FIELDS:
            setattr(self, key, int(parse_number(value)))
        elif key in self._FLOAT_FIELDS:
            setattr(self, key, float(parse_number(value)))
        elif key in self._BOOL_FIELDS:
            setattr(self, key, bool(int(parse_number(value))))
        elif key in self._LIST_FIELDS:
            items = [s.strip() for s in value.split(",") if s.strip()]
            if key == "udp_receiver_address":
                setattr(self, key, items)
            elif key == "dm_list":
                setattr(self, key, [float(parse_number(s)) for s in items])
            else:
                setattr(self, key, [int(parse_number(s)) for s in items])
        else:
            setattr(self, key, value.strip())
        return True

    def load_file(self, path: str) -> None:
        """Load ``key = value`` lines; ``#`` comments; unknown keys warn with
        file/line pointer (ref: program_options.hpp:290-295)."""
        with open(path) as f:
            for lineno, line in enumerate(f, start=1):
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                if "=" not in line:
                    log.warning(f"{path}:{lineno}: cannot parse {line!r}")
                    continue
                key, value = line.split("=", 1)
                if not self.set_option(key, value):
                    log.warning(
                        f"{path}:{lineno}: unknown option {key.strip()!r}")

    @classmethod
    def from_args(cls, argv: list[str] | None = None) -> "Config":
        """Build a config with precedence CLI > config file > defaults
        (ref: program_options.hpp:148-179).

        CLI syntax: ``--key=value`` or ``--key value``.
        """
        if argv is None:
            argv = sys.argv[1:]
        cli: dict[str, str] = {}
        i = 0
        while i < len(argv):
            arg = argv[i]
            if not arg.startswith("--"):
                raise SystemExit(f"unexpected argument: {arg}")
            body = arg[2:]
            if "=" in body:
                key, value = body.split("=", 1)
            else:
                key = body
                if i + 1 >= len(argv):
                    raise SystemExit(f"missing value for --{key}")
                i += 1
                value = argv[i]
            cli[key.replace("-", "_")] = value
            i += 1

        cfg = cls()
        config_file = cli.get("config_file_name", cfg.config_file_name)
        import os
        if os.path.exists(config_file):
            cfg.config_file_name = config_file
            cfg.load_file(config_file)
        for key, value in cli.items():
            if not cfg.set_option(key, value):
                log.warning(f"unknown command-line option --{key}")
        log.level = cfg.log_level
        return cfg

    def replace(self, **kwargs) -> "Config":
        return dataclasses.replace(self, **kwargs)
