"""Opt-in runtime concurrency checker (``Config.tsan``) — the dynamic
twin of the srtb-tsan lint rules (rules/lock_order.py & friends).

The static rules see *spellings*; this module sees *behavior*, with
the sanitizer's zero-cost-off contract: the fleet holds ``None`` when
``Config.tsan`` is off, every hook site is an ``if ts is not None``,
and the locks themselves are plain ``threading`` objects — no wrapper
indirection on the hot path unless the knob is on.

When on:

- **lockdep order graph**: every instrumented acquisition records an
  edge ``held -> wanted`` in a global order graph; an acquisition that
  would close a cycle raises :class:`TsanError` BEFORE acquiring — the
  *potential* deadlock is trapped on whichever thread hits the
  inverted order first, without needing the fatal interleave itself.
  Re-acquiring a non-reentrant lock already held by this thread is the
  degenerate cycle and trapped the same way.
- **held-too-long stalls**: a lock held longer than ``stall_s`` is
  recorded (counter + warning, not an exception: a stall is a latency
  bug, not a correctness bug) with the hold site and duration.
- **ownership guards**: the sanitizer's claim-on-first-use
  ``assert_owner`` pattern, extended to fleet lane state and the batch
  former's group slots.
- **schedule perturbation**: an installed :class:`SchedulePerturber`
  injects deterministic yields/sleeps at every instrumented
  acquisition point, widening race windows reproducibly
  (tools/race_soak.py drives this; same seed => same schedule).
"""

from __future__ import annotations

import threading
import time
import zlib

from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics


class TsanError(AssertionError):
    """A concurrency tripwire fired: lock-order cycle, non-reentrant
    re-acquire, condvar misuse, or thread-ownership violation."""


# ------------------------------------------------------------------
# seeded schedule perturbation
# ------------------------------------------------------------------

class SchedulePerturber:
    """Deterministic yield/sleep injection at lock acquisition points.

    The decision for occurrence ``k`` of site ``site`` is a pure hash
    of ``(seed, site, k)`` — no RNG state, no wall clock — so the same
    seed yields the same perturbation schedule for any interleaving of
    threads hitting the sites, and a recorded (site, k) journal can be
    replayed exactly (tests/test_tsan.py pins this).
    """

    def __init__(self, seed: int, rate: float = 0.25,
                 sleep_s: float = 0.002):
        self.seed = int(seed)
        self.rate = float(rate)
        self.sleep_s = float(sleep_s)
        self._mu = threading.Lock()
        self._counts: dict[str, int] = {}
        self.journal: list[tuple[str, int]] = []

    def decide(self, site: str, k: int) -> bool:
        """Pure: perturb occurrence ``k`` of ``site``?"""
        h = zlib.crc32(f"{self.seed}:{site}:{k}".encode())
        return (h % 10_000) < self.rate * 10_000

    def perturb(self, site: str) -> None:
        """Called at an instrumented acquisition point: maybe sleep."""
        with self._mu:
            k = self._counts.get(site, 0)
            self._counts[site] = k + 1
            hit = self.decide(site, k)
            if hit:
                self.journal.append((site, k))
        if hit:
            metrics.add("tsan_perturbs")
            # a real sleep (not just a GIL yield): wide enough to let
            # any thread runnable at this instant overtake us
            time.sleep(self.sleep_s)


_perturber: SchedulePerturber | None = None
_perturber_mu = threading.Lock()


def install_perturber(p: SchedulePerturber) -> None:
    """Arm ``p`` process-wide so fleets the caller did not construct
    (e.g. inside fleet_soak) still get perturbed acquisitions."""
    global _perturber
    with _perturber_mu:
        _perturber = p


def uninstall_perturber() -> None:
    global _perturber
    with _perturber_mu:
        _perturber = None


def current_perturber() -> SchedulePerturber | None:
    return _perturber


# ------------------------------------------------------------------
# instrumented primitives
# ------------------------------------------------------------------

class InstrumentedLock:
    """``threading.Lock`` with lockdep bookkeeping around acquire and
    release.  The inner lock is real — instrumentation adds checks, it
    never changes blocking semantics (except to raise instead of
    deadlocking on a detected cycle)."""

    def __init__(self, tsan: "Tsan", name: str):
        self._tsan = tsan
        self.name = name
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._tsan._before_acquire(self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._tsan._after_acquire(self.name)
        return got

    def release(self) -> None:
        self._tsan._before_release(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class InstrumentedCondition:
    """``threading.Condition`` wrapper with the same bookkeeping.

    Deliberately NOT ``threading.Condition(lock=InstrumentedLock)``:
    ``Condition._is_owned`` probes with ``acquire(0)`` on the lock it
    already holds, which the lockdep self-edge trap would (correctly,
    for a user lock) flag.  Instead a plain Condition is wrapped and
    the tsan bookkeeping brackets acquire/release/wait — ``wait``
    releases the lock, so the held-stack entry is popped for the
    sleep and re-pushed on wakeup.
    """

    def __init__(self, tsan: "Tsan", name: str):
        self._tsan = tsan
        self.name = name
        self._inner = threading.Condition()

    def __enter__(self):
        self._tsan._before_acquire(self.name)
        self._inner.__enter__()
        self._tsan._after_acquire(self.name)
        return self

    def __exit__(self, *exc) -> None:
        self._tsan._before_release(self.name)
        self._inner.__exit__(*exc)

    def _assert_held(self, op: str) -> None:
        if not self._tsan._holds(self.name):
            raise TsanError(
                f"[tsan] {op} on condition '{self.name}' without "
                "holding its lock — the waiter can check its "
                "predicate, miss this notify, and sleep forever "
                "(srtb-lint: condvar-misuse)")

    def wait(self, timeout: float | None = None):
        self._assert_held("wait")
        self._tsan._before_release(self.name)
        try:
            return self._inner.wait(timeout)
        finally:
            self._tsan._after_acquire(self.name)

    def wait_for(self, predicate, timeout: float | None = None):
        self._assert_held("wait_for")
        self._tsan._before_release(self.name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._tsan._after_acquire(self.name)

    def notify(self, n: int = 1) -> None:
        self._assert_held("notify")
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._assert_held("notify_all")
        self._inner.notify_all()


# ------------------------------------------------------------------
# the checker
# ------------------------------------------------------------------

class Tsan:
    """One fleet run's concurrency-checker state: the global
    acquisition-order graph, per-thread held stacks, stall log, and
    claim-on-first-use owners (the sanitizer pattern, extended to
    fleet lane state and batch-former group slots)."""

    def __init__(self, stall_s: float = 0.5):
        self.stall_s = float(stall_s)
        self._mu = threading.Lock()
        # a -> {b: "thread that first took b under a"}
        self._order: dict[str, dict[str, str]] = {}
        self._tls = threading.local()
        self._owners: dict[str, tuple[int, str]] = {}
        self.stalls: list[tuple[str, float, str]] = []

    def lock(self, name: str) -> InstrumentedLock:
        return InstrumentedLock(self, name)

    def condition(self, name: str) -> InstrumentedCondition:
        return InstrumentedCondition(self, name)

    # -- held bookkeeping

    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def _holds(self, name: str) -> bool:
        return any(n == name for n, _t in self._held())

    def _path(self, src: str, dst: str) -> bool:
        """Is there a path src -> ... -> dst in the order graph?
        (called with self._mu held)"""
        seen = set()
        stack = [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(self._order.get(n, ()))
        return False

    def _before_acquire(self, name: str) -> None:
        p = current_perturber()
        if p is not None:
            p.perturb(name)
        held = self._held()
        if self._holds(name):
            raise TsanError(
                f"[tsan] re-acquire of non-reentrant lock '{name}' "
                f"on thread '{threading.current_thread().name}' "
                "(already held) — self-deadlock (srtb-lint: "
                "lock-order-inversion)")
        if not held:
            return
        tname = threading.current_thread().name
        with self._mu:
            for h, _t in held:
                # adding h -> name: a cycle exists iff name already
                # reaches h.  Trap BEFORE acquiring — the potential
                # deadlock is the finding, no fatal interleave needed.
                if self._path(name, h):
                    first = self._order.get(name, {})
                    via = next((f"'{name}' -> '{k}' (first taken on "
                                f"thread '{first[k]}')"
                                for k in first if self._path(k, h)
                                or k == h), f"'{name}' -> ... -> '{h}'")
                    raise TsanError(
                        f"[tsan] lock-order inversion: thread "
                        f"'{tname}' holds '{h}' and wants '{name}', "
                        f"but the order {via} is already on record — "
                        "two threads interleaving these paths "
                        "deadlock; pick one global order (srtb-lint: "
                        "lock-order-inversion)")
                self._order.setdefault(h, {}).setdefault(name, tname)

    def _after_acquire(self, name: str) -> None:
        self._held().append((name, time.monotonic()))

    def _before_release(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                _n, t0 = held.pop(i)
                dt = time.monotonic() - t0
                if dt > self.stall_s:
                    metrics.add("tsan_stalls")
                    tname = threading.current_thread().name
                    with self._mu:
                        self.stalls.append((name, dt, tname))
                    log.warning(
                        f"[tsan] lock '{name}' held {dt * 1e3:.0f} ms "
                        f"by thread '{tname}' (stall_s="
                        f"{self.stall_s}) — a blocking call under "
                        "the lock? (srtb-lint: blocking-under-lock)")
                return

    # -- thread ownership (sanitizer pattern)

    def assert_owner(self, name: str) -> None:
        """Claim-on-first-use: the first thread to touch state
        ``name`` owns it for the run; any other thread is a
        cross-thread mutation bug."""
        t = threading.current_thread()
        with self._mu:
            owner = self._owners.setdefault(name, (t.ident, t.name))
        if owner[0] != t.ident:
            raise TsanError(
                f"[tsan] thread-ownership violation on '{name}': "
                f"owned by thread '{owner[1]}' but touched from "
                f"'{t.name}' — lane step state is scheduler-owned and "
                "former group slots are single-writer by design "
                "(srtb-lint: unguarded-shared-state)")

    def release_owners(self, prefix: str | None = None) -> None:
        """Drop claims (all, or those under ``prefix``) — e.g. when a
        lane is torn down and its successor may run on a new thread."""
        with self._mu:
            if prefix is None:
                self._owners.clear()
            else:
                for k in [k for k in self._owners
                          if k.startswith(prefix)]:
                    del self._owners[k]

    # -- reporting

    def report(self) -> dict:
        with self._mu:
            edges = sum(len(v) for v in self._order.values())
            return {
                "order_edges": edges,
                "order_nodes": len(
                    set(self._order)
                    | {b for v in self._order.values() for b in v}),
                "stalls": list(self.stalls),
                "owners": dict(self._owners),
            }
