"""srtb-lint: project-specific static analysis + runtime sanitizer.

The async in-flight engine (pipeline/runtime.py) lives or dies on
properties pytest cannot see: no hidden host syncs inside the dispatch
window, no reads of donated buffers, no per-call jit re-tracing, no f64
drift into the df64 device chain, no cross-thread mutation of engine
state without a lock.  This package checks those mechanically:

- :mod:`srtb_tpu.analysis.lint` — an AST linter over the package source
  (no imports of the scanned code), one rule module per hazard class
  under :mod:`srtb_tpu.analysis.rules`.  Run it with
  ``python -m srtb_tpu.tools.lint srtb_tpu/``.
- :mod:`srtb_tpu.analysis.sanitizer` — an opt-in runtime sanitizer
  (``Config.sanitize``) that traps implicit device-to-host transfers,
  NaN/Inf at segment-plan boundaries, stage contract violations,
  wrong-thread access to engine state, and leaked threads.  Zero cost
  when disabled.

Pragmas: ``# srtb-lint: disable=RULE[,RULE...]`` on the offending line
(or the comment line directly above) suppresses a finding;
``# srtb-lint: disable-file=RULE`` anywhere suppresses a rule for the
whole file.  Pre-existing accepted findings live in ``baseline.json``
next to this package; the CLI fails only on findings not in the
baseline.
"""

from srtb_tpu.analysis.core import Finding  # noqa: F401
