"""srtb-lint driver: scan paths, run every rule, apply pragmas and the
baseline, render findings.

Usage (CI runs exactly this)::

    python -m srtb_tpu.tools.lint srtb_tpu/

Exit code 0 when every finding is pragma-suppressed or baselined, 1
when new findings exist (print them), 2 on usage errors.  The baseline
lives at ``srtb_tpu/analysis/baseline.json``; refresh it after fixing
or accepting findings with ``--write-baseline`` (notes on existing
entries are carried forward).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from srtb_tpu.analysis.core import Baseline, ModuleSource, Project
from srtb_tpu.analysis.rules import ALL_RULES

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")


def _iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in sorted(dirs)
                           if d not in ("__pycache__", ".git")]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        else:
            raise FileNotFoundError(p)


def _rel_dotted(path: str, scan_root: str) -> tuple[str, str]:
    """Stable package-relative path + dotted module name.  Files inside
    a package (``__init__.py`` chain) key relative to the directory
    containing the top package ("srtb_tpu/ops/fft.py"); loose files
    (test fixtures) key relative to the scanned root."""
    p = os.path.abspath(path)
    d = os.path.dirname(p)
    root = d
    while os.path.exists(os.path.join(root, "__init__.py")):
        root = os.path.dirname(root)
    if root != d:
        rel = os.path.relpath(p, root)
    else:
        rel = os.path.relpath(p, scan_root)
    dotted = rel[:-3].replace(os.sep, ".")
    if dotted.endswith(".__init__"):
        dotted = dotted[: -len(".__init__")]
    return rel, dotted


def load_modules(paths) -> list[ModuleSource]:
    scan_root = None
    for p in paths:
        r = p if os.path.isdir(p) else os.path.dirname(p) or "."
        scan_root = r if scan_root is None else os.path.commonpath(
            [scan_root, os.path.abspath(r)])
        scan_root = os.path.abspath(scan_root)
    mods = []
    for f in _iter_py_files(paths):
        rel, dotted = _rel_dotted(f, scan_root or ".")
        with open(f, encoding="utf-8") as fh:
            text = fh.read()
        try:
            mods.append(ModuleSource(f, rel, text, dotted))
        except SyntaxError as e:
            raise SyntaxError(f"{f}: {e}") from e
    return mods


def run(paths) -> list:
    """All pragma-filtered findings for ``paths``, sorted."""
    mods = load_modules(paths)
    project = Project(mods)
    findings = []
    for mod in mods:
        for rule in ALL_RULES:
            for f in rule.check(project, mod):
                if not mod.disabled(f.line, f.rule):
                    findings.append(f)
    findings.sort(key=lambda f: (f.rel, f.line, f.col, f.rule))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="srtb-lint",
        description="static analysis for JAX hot-path hazards "
                    "(see srtb_tpu/analysis/)")
    ap.add_argument("paths", nargs="*", default=["srtb_tpu"])
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON (default: the checked-in one)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, baseline ignored")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept current findings into --baseline "
                         "(existing notes are kept)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--verbose", "-v", action="store_true",
                    help="also show baselined findings and stale "
                         "baseline entries")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.RULE}: {rule.DOC}")
        return 0

    try:
        findings = run(args.paths)
    except (FileNotFoundError, SyntaxError) as e:
        print(f"srtb-lint: {e}", file=sys.stderr)
        return 2

    baseline = (Baseline() if args.no_baseline
                else Baseline.load(args.baseline))
    if args.write_baseline:
        old = Baseline.load(args.baseline)
        Baseline.from_findings(findings, old=old).save(args.baseline)
        print(f"srtb-lint: wrote {len(findings)} finding(s) to "
              f"{args.baseline}")
        return 0
    new, accepted, stale = baseline.filter(findings)

    if args.format == "json":
        print(json.dumps({
            "new": [vars(f) for f in new],
            "accepted": [vars(f) for f in accepted],
            "stale_baseline_keys": stale,
        }, indent=2, default=str))
    else:
        for f in new:
            print(f.render())
        if args.verbose:
            for f in accepted:
                print(f"{f.render()}  [baselined]")
            for k in stale:
                print(f"stale baseline entry (no longer fires): {k}")
        summary = (f"srtb-lint: {len(new)} new, {len(accepted)} "
                   f"baselined, {len(stale)} stale baseline entr"
                   f"{'y' if len(stale) == 1 else 'ies'} "
                   f"({len(findings)} total)")
        print(summary, file=sys.stderr if new else sys.stdout)
    return 1 if new else 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
