"""Compile-time HLO plan auditor: prove ``hbm_passes``, donation, and
transfer-freedom per execution plan, without a device.

The pipeline is HBM-bandwidth bound, and PR 5 made the spectrum-pass
count a first-class *claim* (``SegmentProcessor.hbm_passes``) that
``bench.py`` feeds straight into the roofline model.  srtb-lint
(analysis/core.py) checks the Python source; this module checks one
level down, at the **lowered-HLO / compiled-artifact** level, so a
regression in bytes moved, aliasing, or dtype is caught on CPU CI
before a TPU run ever happens (cf. the bandwidth-accounting discipline
of arXiv:2506.15437 and the stream/overlap audit methodology of
arXiv:2101.00941).

For every plan family reachable from ``plan_signature()`` the auditor
AOT-lowers the plan's jitted programs (``SegmentProcessor.lowerables``
— abstract avals only, nothing runs) and statically audits the
compiled artifact:

- ``compiled.memory_analysis()`` / ``cost_analysis()`` for bytes
  accessed, argument/output/temp footprints and aliased bytes;
- the ``input_output_alias`` table, to prove ``donate_argnums`` was
  **honored** by XLA and not silently dropped — jax only aliases a
  donated input to an output with an *identical aval*, so a donated
  buffer with no shape-matching output is a structural no-op (the
  silent failure mode the canonical staged boundary in
  pipeline/segment.py exists to eliminate);
- an HLO-text walk flagging f64/c128 ops, host callbacks
  (``custom-call`` to callback targets), collectives, infeed/outfeed,
  and entry-level ``copy``/``transpose`` ops;
- a structural count of **spectrum-sized HBM round trips**: every
  entry-computation instruction's operand and result buffers, in units
  of one spectrum (``8 * n_spectrum`` bytes).  Buffers inside a fusion
  stay in registers/VMEM, so entry-level granularity approximates what
  actually crosses HBM; the count is compared against the plan's
  declared ``hbm_passes`` floor (audited >= declared must hold — the
  declaration is a floor, never an overclaim) and pinned exactly in the
  baseline so *any* newly materialized spectrum-sized pass fails CI.

Each plan emits a JSON "plan card"; cards diff against the checked-in
``srtb_tpu/analysis/plan_cards.json`` with the same re-baseline
workflow as srtb-lint (``--write-baseline`` keeps notes).  Driver:
``python -m srtb_tpu.tools.plan_audit`` (new ci.sh stage).

Counts are deterministic for a fixed jax/XLA version and audit shape;
the baseline records both.  The audit runs the CPU backend's pipeline
— TPU fusion differs in *degree* (it fuses more, never less at entry
level), so the CPU count is itself an upper-ish floor check, and the
regression gate is the exact pinned value, not a cross-backend truth.
"""

from __future__ import annotations

import contextlib
import json
import os
import re

from srtb_tpu.pipeline import registry

# ------------------------------------------------------------------
# plan families: enumerated from the ONE plan-family registry
# (pipeline/registry.py) — this module keeps NO family list of its
# own, so the auditable zoo, the demotion ladder and the fleet's plan
# cache can never drift apart.  ``PlanSpec`` is the registry's
# dataclass (the pre-registry name, kept for importers), and the
# module attributes PLAN_FAMILIES / PLAN_KEYS are LIVE views so a
# ``registry.temp_family`` registration (tests, the selftest) is
# visible here too.

PlanSpec = registry.PlanFamily


def __getattr__(name: str):
    if name == "PLAN_FAMILIES":
        return registry.plan_families()
    if name == "PLAN_KEYS":
        return registry.plan_keys()
    raise AttributeError(name)

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "plan_cards.json")

# audit shape defaults: ci.sh stage-7's fused-parity shape — every
# family (incl. skzap's VMEM row window) is live and lowers in ~1 s
DEFAULT_LOG2N = 16
DEFAULT_CHANNELS = 8


def _audit_config(log2n: int, channels: int, overrides: dict):
    from srtb_tpu.config import Config
    base = dict(
        baseband_input_count=1 << log2n, baseband_input_bits=2,
        baseband_format_type="simple", baseband_freq_low=1405.0,
        baseband_bandwidth=64.0, baseband_sample_rate=128e6, dm=30.0,
        spectrum_channel_count=channels,
        mitigate_rfi_average_method_threshold=25.0,
        mitigate_rfi_spectral_kurtosis_threshold=1.05,
        signal_detect_signal_noise_threshold=5.0,
        signal_detect_max_boxcar_length=8,
        mitigate_rfi_freq_list="1410-1412",
        baseband_reserve_sample=False)
    base.update(overrides)
    return Config(**base)


@contextlib.contextmanager
def _env(overrides: dict):
    saved = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def build_plan(spec: PlanSpec, log2n: int = DEFAULT_LOG2N,
               channels: int = DEFAULT_CHANNELS):
    """Construct the segment processor for one plan family at the
    audit shape (device constants are built, but no plan program
    runs).  Built through the registry, so a family whose config
    selects a registered search mode (``search_mode``) audits that
    mode's actual processor class."""
    cfg = _audit_config(log2n, channels, spec.cfg)
    with _env(spec.env):
        return registry.build_processor(cfg, staged=spec.staged,
                                        donate_input=spec.donate)


# ------------------------------------------------------------------
# HLO-text structural analysis

_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]+|pred)\[([0-9,]*)\]")
_INSTR_RE = re.compile(r"(?:ROOT )?%?[\w.\-]+ = (.*)")
_OP_RE = re.compile(r"\)?\}?\s*([a-z][a-z0-9\-]*)\(")
_ENTRY_RE = re.compile(r"^ENTRY [^\n]*\{$(.*?)^\}", re.M | re.S)
# the alias table nests one brace level per entry ("{0}: (0, {},
# may-alias), {1}: ..."), so the body match must admit inner braces — a
# lazy .*? would stop at the first entry's "{}" and silently drop every
# later aliased parameter
_ALIAS_RE = re.compile(
    r"input_output_alias=\{((?:[^{}]|\{[^{}]*\})*)\}")
_ALIAS_ENTRY_RE = re.compile(r"\{[0-9, ]*\}:\s*\((\d+)")
_CC_RE = re.compile(r'custom_call_target="([^"]+)"')

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
                "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
                "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}

# ops that move no HBM bytes of their own (aliases, metadata, scalars)
_NO_TRAFFIC_OPS = frozenset((
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "iota", "opt-barrier", "after-all", "partition-id", "replica-id"))

_COLLECTIVE_OPS = frozenset((
    "all-gather", "all-reduce", "all-to-all", "collective-permute",
    "collective-broadcast", "reduce-scatter", "all-gather-start",
    "all-reduce-start"))

_HOST_TRANSFER_OPS = frozenset((
    "infeed", "outfeed", "send", "recv", "send-done", "recv-done"))

# custom-call targets that re-enter Python / the host mid-program
_CALLBACK_MARKERS = ("callback", "py_func", "host")


def _shape_units(text: str, unit: int) -> int:
    """Total buffer traffic of one instruction line, in spectrum units
    (integer floor per buffer: sub-spectrum buffers count 0)."""
    units = 0
    for dt, dims in _SHAPE_RE.findall(text):
        nelem = 1
        for d in dims.split(","):
            if d:
                nelem *= int(d)
        units += (nelem * _DTYPE_BYTES.get(dt, 4)) // unit
    return units


def analyze_hlo(txt: str, spectrum_bytes: int) -> dict:
    """Structural audit of one compiled module's HLO text."""
    m = _ENTRY_RE.search(txt)
    body = m.group(1) if m else txt
    passes = copies = transposes = 0
    collectives: list[str] = []
    host_transfers: list[str] = []
    for line in body.splitlines():
        im = _INSTR_RE.match(line.strip())
        if not im:
            continue
        rest = im.group(1)
        om = _OP_RE.search(rest)
        op = om.group(1) if om else ""
        if op in _NO_TRAFFIC_OPS:
            continue
        if op == "copy":
            copies += 1
        elif op == "transpose":
            transposes += 1
        if op in _COLLECTIVE_OPS:
            collectives.append(op)
        if op in _HOST_TRANSFER_OPS:
            host_transfers.append(op)
        passes += _shape_units(rest, spectrum_bytes)
    custom_calls = sorted(set(_CC_RE.findall(txt)))
    callbacks = [c for c in custom_calls
                 if any(s in c.lower() for s in _CALLBACK_MARKERS)]
    # whole-module dtype scan: f64/c128 anywhere (incl. fusion bodies)
    # means a 64-bit op survived lowering — the drift srtb-lint's
    # dtype-drift rule guards at source level, proven here at HLO level
    f64_ops = len(re.findall(r"\bf64\[", txt))
    c128_ops = len(re.findall(r"\bc128\[", txt))
    am = _ALIAS_RE.search(txt)
    aliased_params = (sorted({int(p) for p in
                              _ALIAS_ENTRY_RE.findall(am.group(1))})
                      if am else [])
    return {
        "spectrum_passes": passes,
        "entry_copies": copies,
        "entry_transposes": transposes,
        "collectives": sorted(set(collectives)),
        "host_transfer_ops": sorted(set(host_transfers)),
        "custom_calls": custom_calls,
        "host_callbacks": callbacks,
        "f64_ops": f64_ops,
        "c128_ops": c128_ops,
        "aliased_params": aliased_params,
    }


# ------------------------------------------------------------------
# program + plan audits


def _flat_param_index(args, pos: int) -> int | None:
    """Flattened HLO parameter number of positional python arg ``pos``
    (None args contribute no leaves)."""
    import jax
    idx = 0
    for i, a in enumerate(args):
        leaves = len(jax.tree_util.tree_leaves(a))
        if i == pos:
            return idx if leaves else None
        idx += leaves
    return None


def audit_program(jit_fn, args, donated: tuple, spectrum_bytes: int,
                  keep_text: bool = False) -> dict:
    """AOT-lower + compile one jitted program and audit the artifact.
    Nothing executes; ``args`` are ShapeDtypeStructs (or None)."""
    import jax

    lowered = jit_fn.lower(*args)
    compiled = lowered.compile()
    txt = compiled.as_text()
    audit = analyze_hlo(txt, spectrum_bytes)

    mem = compiled.memory_analysis()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}

    out_avals = [(tuple(a.shape), str(a.dtype)) for a in
                 jax.tree_util.tree_leaves(jax.eval_shape(jit_fn, *args))]
    declared, aliased, dropped, no_candidate = [], [], [], []
    for pos in donated:
        p = _flat_param_index(args, pos)
        if p is None:
            continue
        declared.append(p)
        leaf = jax.tree_util.tree_leaves(args[pos])[0]
        in_aval = (tuple(leaf.shape), str(leaf.dtype))
        if p in audit["aliased_params"]:
            aliased.append(p)
        elif in_aval in out_avals:
            # an identically-shaped output existed and XLA still did
            # not alias it — a genuinely dropped donation (regression)
            dropped.append(p)
        else:
            # structurally unusable: no output shares the donated aval,
            # so jax warns "donated buffers were not usable" and the
            # donation is a no-op by construction.  Recorded, not
            # failed: the raw uint8 input can never alias f32 outputs.
            no_candidate.append(p)

    card = {
        "spectrum_passes": audit["spectrum_passes"],
        "entry_copies": audit["entry_copies"],
        "entry_transposes": audit["entry_transposes"],
        "collectives": audit["collectives"],
        "host_transfer_ops": audit["host_transfer_ops"],
        "custom_calls": audit["custom_calls"],
        "host_callbacks": audit["host_callbacks"],
        "f64_ops": audit["f64_ops"],
        "c128_ops": audit["c128_ops"],
        "donation": {"declared": declared, "aliased": aliased,
                     "dropped": dropped, "no_candidate": no_candidate},
        "alias_bytes": int(getattr(mem, "alias_size_in_bytes", 0)),
        # informational (vary with jax/XLA build; excluded from diff)
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "argument_bytes": int(getattr(mem, "argument_size_in_bytes", 0)),
        "output_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
        "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
    }
    if keep_text:
        card["hlo_text"] = txt
    return card


def audit_processor(proc, keep_text: bool = False) -> dict:
    """Plan card for one constructed SegmentProcessor: per-program
    audits + plan-level invariant checks."""
    spectrum_bytes = 8 * proc.n_spectrum
    programs = {}
    for name, fn, args, donated in proc.lowerables():
        programs[name] = audit_program(fn, args, donated, spectrum_bytes,
                                       keep_text=keep_text)
    total_passes = sum(p["spectrum_passes"] for p in programs.values())
    ring = bool(getattr(proc, "ring", False))
    # the warm assemble programs whose carry (flat param 0) MUST alias:
    # a dropped/no_candidate carry donation means every warm dispatch
    # allocates a fresh reserved-tail buffer — the exact silent
    # regression the ring-v1 gate exists to catch
    warm_names = ("ring", "stage_a_ring", "batch_ring")
    warm_progs = {n: p for n, p in programs.items() if n in warm_names}
    checks = {
        # declared hbm_passes is a FLOOR of real spectrum traffic: the
        # compiled artifact must sweep at least that much
        "hbm_floor_ok": total_passes >= proc.hbm_passes,
        # no donation may be dropped while a matching output existed
        "donation_ok": all(not p["donation"]["dropped"]
                           for p in programs.values()),
        # single-chip plans must be free of host round trips and
        # cross-chip transfers
        "transfer_free": all(
            not p["host_callbacks"] and not p["collectives"]
            and not p["host_transfer_ops"] for p in programs.values()),
        "dtype_clean": all(p["f64_ops"] == 0 and p["c128_ops"] == 0
                           for p in programs.values()),
        # ring-v1: the carry donation is a proven alias on EVERY warm
        # assemble program (and those programs exist when the ring is
        # resolved on); vacuously true for direct-ingest plans
        "ring_alias_ok": (not ring or (
            bool(warm_progs) and all(
                0 in p["donation"]["aliased"] and p["alias_bytes"] > 0
                for p in warm_progs.values()))),
    }
    return {
        "plan_name": proc.plan_name,
        "declared_hbm_passes": proc.hbm_passes,
        "fused_tail": bool(proc.fused_tail),
        "staged": bool(proc.staged),
        "ingest": "ring-v1" if ring else "direct",
        "reserved_bytes": int(getattr(proc, "reserved_bytes", 0)),
        "n_spectrum": proc.n_spectrum,
        "programs": programs,
        "total_spectrum_passes": total_passes,
        "checks": checks,
    }


def audit_families(keys=None, log2n: int = DEFAULT_LOG2N,
                   channels: int = DEFAULT_CHANNELS) -> dict:
    """Cards for the requested plan families (default: every family
    in the registry)."""
    specs = {s.key: s for s in registry.plan_families()}
    keys = list(keys) if keys else list(registry.plan_keys())
    cards = {}
    for k in keys:
        if k not in specs:
            raise KeyError(
                f"unknown plan family {k!r} "
                f"(known: {', '.join(registry.plan_keys())})")
        spec = specs[k]
        with _env(spec.env):
            proc = build_plan(spec, log2n=log2n, channels=channels)
            card = audit_processor(proc)
        card["audit_shape"] = {"log2n": log2n, "channels": channels}
        card["mode"] = spec.mode
        if spec.hbm_passes is not None:
            card["checks"]["declared_matches_family"] = (
                proc.hbm_passes == spec.hbm_passes)
            card["expected_hbm_passes"] = spec.hbm_passes
        cards[k] = card
    return cards


# ------------------------------------------------------------------
# baseline + diff (same accept/re-baseline workflow as srtb-lint)

# per-program fields whose exact values are pinned; everything else in
# the card is informational context
_DIFF_PROGRAM_KEYS = (
    "spectrum_passes", "entry_copies", "entry_transposes", "collectives",
    "host_transfer_ops", "custom_calls", "host_callbacks", "f64_ops",
    "c128_ops", "donation", "alias_bytes")
_DIFF_PLAN_KEYS = ("plan_name", "declared_hbm_passes", "fused_tail",
                   "staged", "ingest", "reserved_bytes", "mode",
                   "total_spectrum_passes", "checks")


def stable_view(card: dict) -> dict:
    """The baseline-pinned subset of one plan card."""
    view = {k: card[k] for k in _DIFF_PLAN_KEYS if k in card}
    view["programs"] = {
        name: {k: prog[k] for k in _DIFF_PROGRAM_KEYS if k in prog}
        for name, prog in card.get("programs", {}).items()}
    return view


class CardBaseline:
    """Checked-in plan cards + per-plan acceptance notes."""

    def __init__(self, data: dict | None = None):
        data = data or {}
        self.cards: dict = data.get("cards", {})
        self.notes: dict = data.get("notes", {})

    @classmethod
    def load(cls, path: str) -> "CardBaseline":
        if not path or not os.path.exists(path):
            return cls()
        with open(path) as f:
            return cls(json.load(f))

    def save(self, path: str) -> None:
        import jax
        out = {"version": 1, "jax": jax.__version__,
               "cards": self.cards, "notes": self.notes}
        with open(path, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
            f.write("\n")

    @classmethod
    def from_cards(cls, cards: dict,
                   old: "CardBaseline | None" = None) -> "CardBaseline":
        b = cls()
        b.cards = {k: stable_view(c) for k, c in cards.items()}
        if old is not None:  # carry notes forward across rewrites
            b.notes = {k: n for k, n in old.notes.items() if k in b.cards}
        return b


def _walk_diff(path: str, base, cur, out: list) -> None:
    if isinstance(base, dict) and isinstance(cur, dict):
        for k in sorted(set(base) | set(cur)):
            _walk_diff(f"{path}.{k}" if path else k,
                       base.get(k), cur.get(k), out)
    elif base != cur:
        out.append(f"{path}: baseline {base!r} -> audited {cur!r}")


def diff_cards(cards: dict, baseline: CardBaseline):
    """(regressions, new_plans, stale_plans): exact-match diff of the
    stable card subset against the baseline."""
    regressions: list[str] = []
    new_plans: list[str] = []
    for key, card in cards.items():
        cur = stable_view(card)
        if key not in baseline.cards:
            new_plans.append(key)
            continue
        plan_diffs: list[str] = []
        _walk_diff("", baseline.cards[key], cur, plan_diffs)
        regressions.extend(f"{key}: {d}" for d in plan_diffs)
    stale = sorted(k for k in baseline.cards if k not in cards)
    return regressions, new_plans, stale


def failed_checks(cards: dict) -> list:
    """Invariant violations (independent of any baseline)."""
    out = []
    for key, card in cards.items():
        for name, ok in sorted(card.get("checks", {}).items()):
            if not ok:
                out.append(f"{key}: check {name} failed")
    return out


# ------------------------------------------------------------------
# demotion-ladder target audit: the self-healing ladder must never
# demote into an unaudited plan family

# the fully-featured ladder base: every canonical demotion rung is
# live from here (search mode, micro-batch, ring, skzap, fused tail,
# staged, monolithic), so walking it exercises the ladder's whole
# range — including the periodicity mode's shed-the-mode-first rung
LADDER_AUDIT_CFG = {
    "fft_strategy": "four_step", "fused_tail": "on",
    "use_pallas": True, "use_pallas_sk": True,
    "micro_batch_segments": 2, "search_mode": "periodicity",
    "baseband_reserve_sample": True, "dm": 0.1,
}

# second ladder walk: the front-fused staged megakernel's demotion
# chain.  staged_ffuse is structurally disjoint from the fused-plan
# base above (staged forbids micro-batch, front fusion requires the
# pallas2 staged rows), so its rungs — front_fuse -> today's staged
# plan, then the shared back half — are only exercised by walking
# from an ffuse-featured base of their own.
FFUSE_LADDER_AUDIT_CFG = {
    "fft_strategy": "four_step", "fused_tail": "on",
    "front_fuse": "on", "baseband_reserve_sample": True, "dm": 0.1,
}
FFUSE_LADDER_AUDIT_ENV = {"SRTB_STAGED_ROWS_IMPL": "pallas2"}


def _plan_fingerprint(plan_name: str, ingest: str, staged: bool,
                      micro_batch: bool) -> tuple:
    return (str(plan_name), str(ingest), bool(staged),
            bool(micro_batch))


def _card_fingerprints(baseline: "CardBaseline") -> dict:
    """fingerprint -> [family keys] over the checked-in cards.  The
    fingerprint is (plan_name, ingest, staged, has-micro-batch):
    plan_name already encodes strategy + fused_tail + skzap + ring,
    and a micro-batching plan carries a "batch" program."""
    out: dict[tuple, list] = {}
    for key, card in baseline.cards.items():
        fp = _plan_fingerprint(
            card.get("plan_name", ""), card.get("ingest", "direct"),
            card.get("staged", False),
            "batch" in card.get("programs", {}))
        out.setdefault(fp, []).append(key)
    return out


def audit_ladder(baseline: "CardBaseline",
                 log2n: int = DEFAULT_LOG2N,
                 channels: int = DEFAULT_CHANNELS) -> list:
    """Check that EVERY demotion-ladder rung reachable from the
    fully-featured audit config resolves to a plan family already
    carded in the baseline AND registered as ladder-ELIGIBLE — the
    self-healing ladder (resilience/demote.py) must never land the
    run on an unaudited plan, nor on a family the registry declared
    off-limits as a demotion target (``PlanFamily.ladder=False``,
    e.g. the periodicity mode the ladder sheds, never enters).
    Returns failure strings (empty = every target is carded).

    Builds each rung's processor at the audit shape (constants only —
    nothing lowers or runs) and matches its resolved fingerprint
    against the baseline cards."""
    from srtb_tpu.resilience.demote import ladder_rungs

    cfg = _audit_config(log2n, channels, dict(LADDER_AUDIT_CFG))
    rungs = ladder_rungs(cfg)
    failures = []
    if not rungs:
        return ["ladder: no demotion rungs resolved from the "
                "fully-featured audit config (ladder dead?)"]
    fps = _card_fingerprints(baseline)
    _check_rungs(rungs, fps, failures)
    # the front-fused staged chain (its base is a different plan
    # topology — see FFUSE_LADDER_AUDIT_CFG)
    ffcfg = _audit_config(log2n, channels, dict(FFUSE_LADDER_AUDIT_CFG))
    with _env(dict(FFUSE_LADDER_AUDIT_ENV)):
        ffrungs = ladder_rungs(ffcfg, base_staged=True)
        if not any(r.step == "front_fuse" for r in ffrungs):
            failures.append(
                "ladder: the front_fuse rung never resolved from the "
                "ffuse-featured audit config (rung dead?)")
        _check_rungs(ffrungs, fps, failures)
    return failures


def _check_rungs(rungs, fps, failures) -> None:
    """Shared per-rung carded/registered/eligible checks of
    :func:`audit_ladder` (one body for both ladder walks)."""
    for rung in rungs:
        proc = registry.build_processor(rung.cfg, staged=rung.staged,
                                        donate_input=True)
        mb = int(getattr(rung.cfg, "micro_batch_segments", 1) or 1)
        fp = _plan_fingerprint(proc.plan_name,
                               "ring-v1" if proc.ring else "direct",
                               proc.staged, mb > 1)
        keys = fps.get(fp, [])
        if not keys:
            failures.append(
                f"ladder: rung {rung.step!r} resolves to an UNAUDITED "
                f"plan (plan={fp[0]} ingest={fp[1]} staged={fp[2]} "
                f"micro_batch={fp[3]}) — card the family in "
                "plan_cards.json before the ladder may demote into it")
            continue
        fams = {k: registry.family(k) for k in keys}
        unregistered = sorted(k for k, f in fams.items() if f is None)
        if unregistered and not any(fams.values()):
            failures.append(
                f"ladder: rung {rung.step!r} lands on "
                f"{'/'.join(unregistered)}, carded but NOT in the "
                "registry — stale plan_cards.json entry (re-run "
                "--write-baseline)")
            continue
        if not any(f is not None and f.ladder for f in fams.values()):
            failures.append(
                f"ladder: rung {rung.step!r} lands on "
                f"{'/'.join(keys)}, registered ladder-INELIGIBLE "
                "(PlanFamily.ladder=False) — the ladder may shed such "
                "a family but never demote into it")


# ------------------------------------------------------------------
# selftest: prove the auditor catches the regressions it exists for


def extra_pass_jit(proc):
    """The fused plan with a deliberately un-fusable extra
    spectrum-sized round trip appended: a cumulative sum along the time
    axis is a sequential scan XLA cannot fold into the producing
    kernel's elementwise epilogue, so the waterfall is re-read and a
    same-sized result re-written (a plain ``+ eps`` behind an
    optimization_barrier is NOT enough — XLA re-fuses it after the
    barrier is dropped).  Audit-only — never executed."""
    import jax
    import jax.numpy as jnp

    def f(raw, chirp_ri, chirp_w_ri=None):
        wf, res = proc._process(raw, chirp_ri, chirp_w_ri)
        return jnp.cumsum(wf, axis=-1), res
    return jax.jit(f)


def selftest(log2n: int = DEFAULT_LOG2N,
             channels: int = DEFAULT_CHANNELS) -> list:
    """Inject the two regression classes the CI gate must catch and
    verify each one moves the audited card.  Returns a list of failure
    strings (empty = the auditor is sharp)."""
    import jax

    failures = []
    spec = registry.family("four_step_ftail")
    proc = build_plan(spec, log2n=log2n, channels=channels)
    spectrum_bytes = 8 * proc.n_spectrum
    (name, fn, args, donated), = [p for p in proc.lowerables()
                                  if p[0] == "fused"]
    clean = audit_program(fn, args, donated, spectrum_bytes)
    dirty = audit_program(extra_pass_jit(proc), args, donated,
                          spectrum_bytes)
    gained = dirty["spectrum_passes"] - clean["spectrum_passes"]
    if gained < 2:
        failures.append(
            "extra-pass injection not caught: audited passes moved by "
            f"{gained} (expected >= 2: one read + one write)")

    sspec = registry.family("staged")
    sproc = build_plan(sspec, log2n=log2n, channels=channels)
    sbytes = 8 * sproc.n_spectrum
    progs = {p[0]: p for p in sproc.lowerables()}
    _, bfn, bargs, bdon = progs["stage_b"]
    honored = audit_program(bfn, bargs, bdon, sbytes)
    if not honored["donation"]["aliased"] or not honored["alias_bytes"]:
        failures.append(
            "staged stage_b donation NOT proven aliased in the clean "
            f"artifact: {honored['donation']} "
            f"alias_bytes={honored['alias_bytes']}")
    # deliberately disable donation via a non-donating wrapper: the
    # audited donation table must visibly lose the alias
    undonated = audit_program(jax.jit(sproc._stage_b), bargs, (), sbytes)
    if undonated["donation"]["declared"] or undonated["alias_bytes"]:
        failures.append(
            "donation-disabled injection not caught: non-donating "
            f"wrapper still audits as aliased: {undonated['donation']} "
            f"alias_bytes={undonated['alias_bytes']}")

    # ring-v1: the carry alias must be proven on the warm assemble
    # program, and a plan that loses it (non-donating wrapper again)
    # must fail the ring_alias_ok check
    rspec = registry.family("four_step_ftail_ring")
    rproc = build_plan(rspec, log2n=log2n, channels=channels)
    if not rproc.ring:
        failures.append("ring family resolved with the ring OFF "
                        "(audit shape reserves no tail?)")
        return failures
    rcard = audit_processor(rproc)
    if not rcard["checks"]["ring_alias_ok"]:
        failures.append(
            "clean ring plan fails ring_alias_ok: "
            f"{rcard['programs'].get('ring', {}).get('donation')}")
    rbytes = 8 * rproc.n_spectrum
    (_, _, rargs, _), = [p for p in rproc.lowerables()
                         if p[0] == "ring"]
    lost = audit_program(jax.jit(rproc._process_ring), rargs, (), rbytes)
    if lost["donation"]["declared"] or 0 in lost["donation"]["aliased"]:
        failures.append(
            "carry-donation-disabled injection not caught: the "
            f"non-donating assemble still audits aliased: "
            f"{lost['donation']}")

    # front-fuse: an UN-fused unpack front — the sample-order unpack +
    # even/odd pack materialized as its own spectrum-sized pass before
    # pass 1 consumes it — must move the ffuse stage_a's pinned count
    # by at least a read + a write.  As with the extra-pass injection
    # above, the materialization is anchored by a cumulative sum (its
    # exact inverse follows, so the values are the same z): a plain
    # unpack->pack chain re-fuses into pass 1's operands at the tiny
    # audit shape and the z traffic goes entry-invisible.
    import jax.numpy as jnp
    from srtb_tpu.ops import pallas_fft2 as pf2

    fspec = registry.family("staged_ffuse")
    fproc = build_plan(fspec, log2n=log2n, channels=channels)
    fbytes = 8 * fproc.n_spectrum
    (_, afn, aargs, adon), = [p for p in fproc.lowerables()
                              if p[0] == "stage_a"]
    fclean = audit_program(afn, aargs, adon, fbytes)
    fn1, fn2 = fproc._ffuse_fac

    def unfused_front(raw):
        z = fproc._staged_pack(raw)   # sample-order unpack + pack
        zri = jnp.stack([jnp.real(z), jnp.imag(z)])  # [2, S, m]
        zri = jnp.cumsum(zri, axis=-1)               # materialize ...
        zri = zri - jnp.concatenate(                 # ... then undo
            [jnp.zeros_like(zri[..., :1]), zri[..., :-1]], axis=-1)
        outs = [pf2.pass1_2d(zri[0, s].reshape(fn1, fn2),
                             zri[1, s].reshape(fn1, fn2),
                             interpret=True)
                for s in range(z.shape[0])]
        a_ri = jnp.stack([jnp.stack([o[0] for o in outs]),
                          jnp.stack([o[1] for o in outs])])
        aux = jnp.zeros((z.shape[0], 3, 128), jnp.float32)
        return fproc._boundary_canon(a_ri), aux

    funfused = audit_program(jax.jit(unfused_front), aargs, (), fbytes)
    fgained = funfused["spectrum_passes"] - fclean["spectrum_passes"]
    if fgained < 2:
        failures.append(
            "un-fused-unpack injection not caught: audited passes "
            f"moved by {fgained} (expected >= 2: the materialized "
            "sample-order z write + read the front fusion eliminates)")

    # demotion-ladder gate: every rung must match the checked-in
    # baseline, and the gate must visibly fail against a baseline
    # with no cards (= every rung unaudited)
    checked_in = CardBaseline.load(DEFAULT_BASELINE)
    if checked_in.cards:
        ladder_problems = audit_ladder(checked_in, log2n=log2n,
                                       channels=channels)
        if ladder_problems:
            failures.append(
                "demotion-ladder targets do not all resolve to "
                "checked-in plan cards: " + "; ".join(ladder_problems))
    missing = audit_ladder(CardBaseline(), log2n=log2n,
                           channels=channels)
    if not missing:
        failures.append(
            "ladder-gate injection not caught: an EMPTY baseline "
            "still passes audit_ladder (the gate would never fire)")

    # registry gate: a plan family REGISTERED without a checked-in
    # plan card must fail the CI diff as unbaselined — registering a
    # new capability (a search mode, a plan variant) in
    # pipeline/registry.py is not done until its card is accepted
    with registry.temp_family(registry.PlanFamily(
            key="__selftest_uncarded",
            desc="selftest: registered but never carded",
            cfg={"fft_strategy": "four_step", "fused_tail": "on"},
            donate=True, hbm_passes=5)):
        cards = audit_families(["__selftest_uncarded"], log2n=log2n,
                               channels=channels)
        _, new_plans, _ = diff_cards(cards, checked_in)
        if "__selftest_uncarded" not in new_plans:
            failures.append(
                "uncarded-family injection not caught: a family "
                "registered without a plan card did not surface as "
                "unbaselined (the registry gate would never fire)")
    return failures
