"""Shared machinery for the srtb-tsan concurrency rules.

The four concurrency rules (lock-order-inversion, blocking-under-lock,
condvar-misuse, check-then-act) all reason about the same primitives:
*which expressions name locks*, *which code runs while a lock is
held* (lexically inside a ``with <lock>:`` span, or reachable through
the project call graph from a call made inside one), and *which
functions run on spawned threads* (the same thread-entry resolution
``unguarded-shared-state`` uses).  This module centralizes that so the
rules agree on lock identity — a cycle between the names rule A
derives and the names rule B derives would be meaningless.

Lock identity is a static approximation: ``self._x_lock`` canonicalizes
to ``"<rel>::<Class>._x_lock"`` (instance identity is erased — good
enough for the engine, where every lock attribute belongs to exactly
one object per scope), bare names to ``"<rel>::<scope>:<name>"``.
Only names containing a lock-ish token count, so ``with open(...)``
and ``with tempfile...`` spans never pollute the graph.
"""

from __future__ import annotations

import ast

from srtb_tpu.analysis.core import FunctionInfo, ModuleSource, Project

# tokens that mark a name as a lock/condvar (superset of
# shared_state._LOCKISH: the fleet's scheduler condvar is `_wake`)
LOCKISH = ("lock", "_cv", "cv", "cond", "mutex", "_mu", "wake", "sem")

# condition-variable method names (threading.Condition)
CV_WAIT = ("wait", "wait_for")
CV_NOTIFY = ("notify", "notify_all")


def is_lockish(text: str) -> bool:
    low = text.lower()
    return any(tok in low for tok in LOCKISH)


def lock_key(mod: ModuleSource, info: FunctionInfo,
             expr: ast.expr) -> str | None:
    """Canonical cross-function identity of a lock expression, or None
    when the expression does not name a lock-ish object."""
    try:
        text = ast.unparse(expr)
    except Exception:  # noqa: BLE001 - exotic expr, not a lock name
        return None
    if not is_lockish(text):
        return None
    chain: list[str] = []
    t = expr
    while isinstance(t, ast.Attribute):
        chain.append(t.attr)
        t = t.value
    if not isinstance(t, ast.Name):
        return None
    if t.id == "self" and chain:
        cls = info.class_name or "<no-class>"
        return f"{mod.rel}::{cls}." + ".".join(reversed(chain))
    parts = ".".join([t.id] + list(reversed(chain)))
    scope = info.qualname if info is not None else "<module>"
    return f"{mod.rel}::{scope}:{parts}"


def pretty(key: str) -> str:
    """Human form of a lock key (drop the file prefix)."""
    return key.split("::", 1)[-1]


def span_contains(outer: ast.AST, node: ast.AST) -> bool:
    """Lexical containment by line span (the same approximation
    shared_state._guarded uses)."""
    line = getattr(node, "lineno", None)
    if line is None:
        return False
    end = getattr(outer, "end_lineno", outer.lineno)
    return outer.lineno <= line <= end


def with_locks(mod: ModuleSource, info: FunctionInfo):
    """Yield ``(key, with_node, item_expr)`` for every lock-ish
    ``with`` item in this function's own body."""
    for node in info.body_nodes():
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            key = lock_key(mod, info, item.context_expr)
            if key is not None:
                yield key, node, item.context_expr


def guarded_span(mod: ModuleSource, info: FunctionInfo,
                 node: ast.AST) -> bool:
    """Is ``node``'s ENTIRE span inside one lock-ish with block?  The
    check-then-act rule needs whole-statement containment: a test
    outside the lock with the mutation inside is exactly the bug."""
    end = getattr(node, "end_lineno", node.lineno)
    for _key, w, _e in with_locks(mod, info):
        wend = getattr(w, "end_lineno", w.lineno)
        if w.lineno <= node.lineno and end <= wend and w is not node:
            return True
    return False


def thread_entries(project: Project, mod: ModuleSource) -> set:
    """Functions handed to ``threading.Thread``/``Timer`` or the
    framework's ``start_pipe`` in this module (shared with
    unguarded-shared-state — one definition of "runs on a thread")."""
    from srtb_tpu.analysis.rules.shared_state import _entry_functions
    return _entry_functions(project, mod)


# ------------------------------------------------------------------
# project-wide concurrency analysis (memoized on the Project object,
# like host_sync's hot-path cache: rules run per module, the graph is
# global)
# ------------------------------------------------------------------


class ConcurrencyAnalysis:
    """Per-project lock-acquisition facts: every function's own
    ``with <lock>`` acquisitions, the transitive closure of
    acquisitions reachable through its calls, and the global
    acquisition-order edge set."""

    def __init__(self, project: Project):
        self.project = project
        # FunctionInfo -> list[(lock_key, with_node)]
        self.own_acquires: dict = {}
        for m in project.modules:
            for info in m.functions.values():
                acq = [(k, w) for k, w, _e in with_locks(m, info)]
                if acq:
                    self.own_acquires[info] = acq
        self._closure_cache: dict = {}
        # (A, B) -> (mod, anchor_node, context_qualname, note)
        self.edges: dict = {}
        self._build_edges()

    # -- transitive acquisitions

    def acquires_closure(self, fn: FunctionInfo) -> set:
        """Lock keys acquired by ``fn`` or anything reachable from it."""
        hit = self._closure_cache.get(fn)
        if hit is None:
            hit = set()
            for g in self.project.reachable({fn}):
                for key, _w in self.own_acquires.get(g, ()):
                    hit.add(key)
            self._closure_cache[fn] = hit
        return hit

    # -- acquisition-order edges

    def _build_edges(self) -> None:
        for mod in self.project.modules:
            for info in mod.functions.values():
                self._edges_in(mod, info)

    def _edges_in(self, mod: ModuleSource, info: FunctionInfo) -> None:
        spans = list(with_locks(mod, info))
        if not spans:
            return
        nodes = list(info.body_nodes())
        for held, w, _e in spans:
            # multi-item `with A, B:` orders left-to-right
            keys = [lock_key(mod, info, it.context_expr)
                    for it in w.items]
            keys = [k for k in keys if k is not None]
            if len(keys) > 1:
                i = keys.index(held)
                for nxt in keys[i + 1:]:
                    self._edge(held, nxt, mod, w, info,
                               "acquired in the same with statement")
            for node in nodes:
                if not span_contains(w, node) or node is w:
                    continue
                if isinstance(node, ast.With):
                    for it in node.items:
                        nxt = lock_key(mod, info, it.context_expr)
                        if nxt is not None and nxt != held:
                            self._edge(held, nxt, mod, node, info,
                                       "nested with")
                        elif nxt == held and node is not w:
                            # re-acquiring a non-reentrant lock you
                            # already hold: a self-deadlock
                            self._edge(held, nxt, mod, node, info,
                                       "re-acquired while held")
                elif isinstance(node, ast.Call):
                    callee = self.project.resolve_call(
                        mod, info, node.func)
                    if callee is None:
                        continue
                    for nxt in self.acquires_closure(callee):
                        if nxt != held:
                            self._edge(held, nxt, mod, node, info,
                                       f"via {callee.qualname}()")

    def _edge(self, a: str, b: str, mod, node, info, note) -> None:
        self.edges.setdefault((a, b), (mod, node, info.qualname, note))

    # -- cycles (strongly connected components of the edge set)

    def cycles(self) -> list[list[str]]:
        adj: dict[str, set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set[str] = set()
        stack: list[str] = []
        out: list[list[str]] = []
        counter = [0]

        def strongconnect(v0):
            work = [(v0, iter(sorted(adj[v0])))]
            index[v0] = low[v0] = counter[0]
            counter[0] += 1
            stack.append(v0)
            on_stack.add(v0)
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(adj[w]))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    pv = work[-1][0]
                    low[pv] = min(low[pv], low[v])
                if low[v] == index[v]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == v:
                            break
                    if len(scc) > 1 or (v, v) in self.edges:
                        out.append(sorted(scc))

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        return out


def analysis(project: Project) -> ConcurrencyAnalysis:
    a = getattr(project, "_tsan_concurrency", None)
    if a is None:
        a = project._tsan_concurrency = ConcurrencyAnalysis(project)
    return a
