"""sync-hot-path: host synchronization reachable from the dispatch
window or inside a jit-traced body.

The async engine's overlap win exists only while the dispatch side
(ingest -> H2D staging -> program enqueue) never blocks on the device:
one ``np.asarray`` / ``.item()`` / ``.block_until_ready()`` on that
path serializes the stream exactly like the hidden syncs that erased
AstroAccelerate's CUDA-stream overlap (arXiv:2101.00941).  Inside a
jit body the same calls either break tracing or silently force a
host round trip per call.

Hot zones:
- the dispatch-window functions of pipeline/runtime.py and the device
  entry points of pipeline/segment.py (``HOT_ROOTS``), plus everything
  reachable from them through the project call graph;
- every function reachable from a ``jax.jit`` root anywhere in the
  scanned tree.

The sanctioned sync points (the fetch/drain side, sinks) are *not*
rooted here, so an explicit ``jax.device_get`` in a drain function is
clean while the same call inside ``fill_window`` is a finding.
"""

from __future__ import annotations

import ast

from srtb_tpu.analysis.core import Finding, ModuleSource, Project

RULE = "sync-hot-path"
DOC = ("host sync (np.asarray/.item()/block_until_ready/device_get) "
       "reachable from the dispatch window or a jit body")

# dispatch-window roots: (rel-path suffix, function names)
HOT_ROOTS = (
    ("pipeline/runtime.py", {
        "_dispatch_segment", "_dispatch_micro_batch", "_result_ready",
        "_timed_ingest", "fill_window", "ingest_one"}),
    ("pipeline/segment.py", {"stage_input", "run_device",
                             "run_device_ring", "run_device_cold",
                             "stack_batch"}),
)

_SYNC_FUNCS = {
    "numpy.asarray": "np.asarray forces a device->host transfer",
    "numpy.array": "np.array forces a device->host copy",
    "jax.device_get": "device_get blocks on device completion",
    "jax.block_until_ready": "block_until_ready stalls dispatch",
}
_SYNC_METHODS = {
    "item": ".item() is a blocking device->host scalar fetch",
    "block_until_ready": ".block_until_ready() stalls dispatch",
    "tolist": ".tolist() is a blocking device->host fetch",
}


def _hot_sets(project: Project):
    """(dispatch-window closure, jit-body closure), memoized on the
    project (rules run once per module)."""
    cached = getattr(project, "_sync_hot_cache", None)
    if cached is not None:
        return cached
    roots = set()
    for mod in project.modules:
        for suffix, names in HOT_ROOTS:
            if mod.rel.endswith(suffix):
                roots.update(info for info in mod.functions.values()
                             if info.name in names)
    dispatch = project.reachable(roots)
    cached = (dispatch, project.jit_bodies)
    project._sync_hot_cache = cached
    return cached


def _scan(info, mod: ModuleSource, zone: str):
    for node in info.body_nodes():
        if not isinstance(node, ast.Call):
            continue
        dotted = mod.dotted_name(node.func)
        msg = _SYNC_FUNCS.get(dotted or "")
        if msg is None and isinstance(node.func, ast.Attribute) \
                and not node.args and node.func.attr in _SYNC_METHODS:
            msg = _SYNC_METHODS[node.func.attr]
        if msg is None and zone == "jit body" \
                and isinstance(node.func, ast.Name) \
                and node.func.id in ("float", "int", "bool") \
                and len(node.args) == 1 \
                and isinstance(node.args[0], ast.Name) \
                and node.args[0].id in _params(info.node):
            msg = (f"{node.func.id}() on a traced argument forces "
                   "concretization (host sync or trace error)")
        if msg is not None:
            yield Finding(
                RULE, mod.path, mod.rel, node.lineno, node.col_offset,
                f"{msg} — keep host syncs off the {zone} "
                "(move to the drain/sink side or use async staging)",
                info.qualname, mod.line_text(node.lineno))


def _params(fnode) -> set[str]:
    a = fnode.args
    return {p.arg for p in
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)}


def check(project: Project, mod: ModuleSource):
    dispatch, jit_bodies = _hot_sets(project)
    seen = set()
    for info in dispatch:
        if info.module is mod:
            for f in _scan(info, mod, "dispatch window"):
                seen.add((f.line, f.col))
                yield f
    for info in jit_bodies:
        if info.module is mod:
            for f in _scan(info, mod, "jit body"):
                if (f.line, f.col) not in seen:
                    yield f
