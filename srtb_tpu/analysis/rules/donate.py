"""use-after-donate: reading a buffer after it was passed to a donated
jit argument.

The engine donates every segment's input buffer (SegmentProcessor
``donate_input``) so XLA can recycle its HBM as program scratch.  On an
accelerator that makes the buffer *invalid the moment the call is
dispatched* — a later read returns garbage or raises, and CPU CI never
notices because CPU donation is a no-op.  This rule tracks, per
function, variables passed at a donated position and flags any
subsequent read (branch-aware: a read in a sibling ``else`` branch is
not "after"; a read earlier in the same loop body is — the donation
invalidates the buffer for the *next* iteration).

Donating callees are found two ways: wrappers assigned from
``jax.jit(..., donate_argnums=...)`` in the scanned tree (a non-literal
``donate_argnums`` counts as donating position 0), plus the known
donating API of this codebase (``DONATING_API``) whose donation is
conditional on construction flags and therefore invisible at the call
site.
"""

from __future__ import annotations

import ast

from srtb_tpu.analysis.core import Finding, ModuleSource, Project

RULE = "use-after-donate"
DOC = "read of a buffer after it was passed to a donated jit argument"

# method name -> donated positional args (0-based, self excluded).
# SegmentProcessor.run_device / process_batch donate their input when
# constructed with donate_input=True — the call site can't see that.
DONATING_API = {"run_device": {0}, "process_batch": {0}}


def _stmt_paths(fnode):
    """Map id(stmt) -> path of (block-id, index, block-is-loop-body)
    tuples, giving a branch-aware 'executes after' partial order."""
    paths: dict[int, tuple] = {}

    def walk(stmts, prefix, is_loop):
        for i, s in enumerate(stmts):
            p = prefix + ((id(stmts), i, is_loop),)
            paths[id(s)] = p
            for _name, blk in ast.iter_fields(s):
                if isinstance(blk, list) and blk \
                        and isinstance(blk[0], ast.stmt):
                    walk(blk, p, isinstance(s, (ast.For, ast.While)))
    walk(fnode.body, (), False)
    return paths


def _order(dp, lp):
    """'after' | 'loop' (same loop body, lexically before — next
    iteration reads a donated buffer) | None."""
    for k in range(min(len(dp), len(lp))):
        db, di, dloop = dp[k]
        lb, li, _ = lp[k]
        if db != lb:
            return None  # diverged into sibling branches
        if di != li:
            if li > di:
                return "after"
            return "loop" if dloop else None
    return None  # nested within the same statement


def _donating_positions(project: Project, mod: ModuleSource, caller,
                        call: ast.Call):
    func = call.func
    # self._jit_x / module-level wrapper assigned from jax.jit(...)
    name = cls = None
    if isinstance(func, ast.Attribute) and isinstance(
            func.value, ast.Name) and func.value.id == "self":
        name, cls = func.attr, caller.class_name
    elif isinstance(func, ast.Name):
        name, cls = func.id, None
    if name is not None:
        hit = project.jit_wrappers.get((mod.dotted, cls, name))
        if hit is not None:
            donated = hit[1]
            if donated == "dynamic":
                return {0}
            if donated:
                return set(donated)
    if isinstance(func, ast.Attribute) and func.attr in DONATING_API:
        return DONATING_API[func.attr]
    return None


def _enclosing_stmt(paths, node, fnode):
    """Innermost statement (known to paths) containing node."""
    best = None
    for stmt in ast.walk(fnode):
        if id(stmt) in paths and hasattr(stmt, "lineno"):
            end = getattr(stmt, "end_lineno", stmt.lineno)
            if stmt.lineno <= node.lineno <= end:
                if best is None or stmt.lineno >= best.lineno:
                    best = stmt
    return best


def check(project: Project, mod: ModuleSource):
    for info in mod.functions.values():
        fnode = info.node
        paths = None
        donations = []   # (stmt, call, varname)
        for node in info.body_nodes():
            if not isinstance(node, ast.Call):
                continue
            pos = _donating_positions(project, mod, info, node)
            if not pos:
                continue
            if paths is None:
                paths = _stmt_paths(fnode)
            stmt = _enclosing_stmt(paths, node, fnode)
            if stmt is None or isinstance(stmt, ast.Return):
                continue  # a donation in `return f(x)` has no 'after'
            for p in sorted(pos):
                if p < len(node.args) and isinstance(
                        node.args[p], ast.Name):
                    donations.append((stmt, node, node.args[p].id))
        if not donations:
            continue
        loads, stores = [], []
        for node in info.body_nodes():
            if isinstance(node, ast.Name):
                (loads if isinstance(node.ctx, ast.Load)
                 else stores).append(node)
        for dstmt, dcall, var in donations:
            dp = paths[id(dstmt)]
            killed_lines = [s.lineno for s in stores if s.id == var]
            for ld in loads:
                if ld.id != var:
                    continue
                lstmt = _enclosing_stmt(paths, ld, fnode)
                if lstmt is None or lstmt is dstmt:
                    continue
                rel = _order(dp, paths[id(lstmt)])
                if rel is None:
                    continue
                if rel == "after" and any(
                        dcall.lineno <= k <= ld.lineno
                        for k in killed_lines):
                    continue  # reassigned between donation and read
                if rel == "loop" and killed_lines:
                    continue  # refreshed somewhere in the loop
                how = ("read after donation" if rel == "after" else
                       "read on the next loop iteration after donation")
                yield Finding(
                    RULE, mod.path, mod.rel, ld.lineno, ld.col_offset,
                    f"'{var}' {how} to "
                    f"'{ast.unparse(dcall.func)}' (line "
                    f"{dcall.lineno}) — the buffer is invalid on "
                    "accelerators once the donated call is dispatched",
                    info.qualname, mod.line_text(ld.lineno))
                break  # one finding per donation is enough
