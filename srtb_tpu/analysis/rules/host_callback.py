"""host-callback-in-jit: a Python host callback reachable inside a jit
body or the dispatch window.

``jax.pure_callback`` / ``jax.experimental.io_callback`` /
``jax.debug.callback`` / ``jax.debug.print`` lower to a ``custom-call``
that re-enters Python **mid-program**: on TPU the device stalls on the
host round trip every execution (the exact overlap-killer class of
arXiv:2101.00941's hidden syncs), and inside the dispatch window it
serializes the in-flight stream just like an explicit host sync.  A
debug print left in a hot path is invisible at Python level once jitted
— this rule catches it at the source, and the compile-time plan auditor
(analysis/hlo_audit.py ``transfer_free`` check) proves the lowered
artifact stayed callback-free at the HLO level.

Accepted diagnostic uses (none exist today) belong in the baseline with
a note, or behind a ``# srtb-lint: disable=host-callback-in-jit``
pragma.
"""

from __future__ import annotations

import ast

from srtb_tpu.analysis.core import Finding, ModuleSource, Project
from srtb_tpu.analysis.rules.host_sync import _hot_sets

RULE = "host-callback-in-jit"
DOC = ("pure_callback/io_callback/debug.callback/debug.print reachable "
       "from a jit body or the dispatch window")

_CALLBACKS = {
    "jax.pure_callback":
        "pure_callback re-enters Python mid-program",
    "jax.experimental.io_callback":
        "io_callback re-enters Python mid-program (and orders against "
        "every other effect)",
    "jax.experimental.host_callback.call":
        "host_callback.call is the deprecated host round-trip API",
    "jax.debug.callback":
        "debug.callback re-enters Python mid-program",
    "jax.debug.print":
        "debug.print lowers to a host callback custom-call",
}


def _scan(info, mod: ModuleSource, zone: str):
    for node in info.body_nodes():
        if not isinstance(node, ast.Call):
            continue
        dotted = mod.dotted_name(node.func)
        msg = _CALLBACKS.get(dotted or "")
        if msg is not None:
            yield Finding(
                RULE, mod.path, mod.rel, node.lineno, node.col_offset,
                f"{msg} — the device stalls on the host every "
                f"execution; keep callbacks out of the {zone} (move "
                "diagnostics to the drain/sink side, or gate behind "
                "the sanitizer)",
                info.qualname, mod.line_text(node.lineno))


def check(project: Project, mod: ModuleSource):
    dispatch, jit_bodies = _hot_sets(project)
    seen = set()
    for info in dispatch:
        if info.module is mod:
            for f in _scan(info, mod, "dispatch window"):
                seen.add((f.line, f.col))
                yield f
    for info in jit_bodies:
        if info.module is mod:
            for f in _scan(info, mod, "jit body"):
                if (f.line, f.col) not in seen:
                    yield f
