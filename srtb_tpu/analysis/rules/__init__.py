"""srtb-lint rule registry: one module per hazard class.

Each rule module exposes ``RULE`` (the id used in findings, pragmas and
the baseline), ``DOC`` (one line for ``--list-rules``) and
``check(project, module) -> iterator of Finding``.
"""

from srtb_tpu.analysis.rules import (donate, dtype_drift, host_callback,
                                     host_sync, recompile, shared_state,
                                     swallowed_except)

ALL_RULES = (host_sync, host_callback, donate, recompile, dtype_drift,
             shared_state, swallowed_except)

RULE_IDS = tuple(r.RULE for r in ALL_RULES)
