"""srtb-lint rule registry: one module per hazard class.

Each rule module exposes ``RULE`` (the id used in findings, pragmas and
the baseline), ``DOC`` (one line for ``--list-rules``) and
``check(project, module) -> iterator of Finding``.

The four srtb-tsan concurrency rules (lock_order, blocking_lock,
condvar, atomicity) share lock identity and thread-entry resolution
via ``_concurrency``; their runtime twin is ``analysis/tsan.py``.
"""

from srtb_tpu.analysis.rules import (atomicity, blocking_lock, condvar,
                                     donate, dtype_drift, host_callback,
                                     host_sync, lock_order, recompile,
                                     shared_state, swallowed_except)

ALL_RULES = (host_sync, host_callback, donate, recompile, dtype_drift,
             shared_state, swallowed_except, lock_order, blocking_lock,
             condvar, atomicity)

RULE_IDS = tuple(r.RULE for r in ALL_RULES)
