"""condvar-misuse: a condition wait outside a predicate loop, or a
notify without the lock held.

``Condition.wait`` may return spuriously and may lose a wakeup that
landed before the wait started; the only correct shape is the
predicate loop (``while not pred: cv.wait(...)``) — an ``if`` guard
re-checks nothing and turns a spurious wakeup into a missed state
transition (the fleet scheduler's idle wakeup was exactly this shape
before this rule).  ``notify``/``notify_all`` without holding the
condition's lock races the waiter's predicate check: the waiter can
test the predicate, lose the CPU, miss the notify, and sleep forever.
"""

from __future__ import annotations

import ast

from srtb_tpu.analysis.core import Finding, ModuleSource, Project
from srtb_tpu.analysis.rules import _concurrency as cc

RULE = "condvar-misuse"
DOC = ("condition wait outside a while-predicate loop, or notify "
       "without the lock held")


def _in_predicate_loop(info, w: ast.With, node: ast.AST) -> bool:
    """Is ``node`` inside a while loop that is itself inside the
    with-span ``w``?  (``while True`` with a break counts: the
    re-check is the loop body's job and deadline-bounded variants
    spell it that way.)"""
    for n in info.body_nodes():
        if isinstance(n, ast.While) and cc.span_contains(w, n) \
                and cc.span_contains(n, node) and n is not node:
            return True
    return False


def check(project: Project, mod: ModuleSource):
    for info in mod.functions.values():
        nodes = list(info.body_nodes())
        spans = list(cc.with_locks(mod, info))
        for node in nodes:
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            recv_key = cc.lock_key(mod, info, node.func.value)
            if recv_key is None:
                continue
            if attr == "wait":
                # a wait on the condition you hold must sit in a
                # predicate loop; wait_for embeds the loop itself
                for held, w, _e in spans:
                    if held == recv_key and cc.span_contains(w, node):
                        if not _in_predicate_loop(info, w, node):
                            yield Finding(
                                RULE, mod.path, mod.rel, node.lineno,
                                node.col_offset,
                                f"wait on '{cc.pretty(recv_key)}' "
                                "outside a predicate loop — a "
                                "spurious wakeup skips the re-check; "
                                "use `while not <predicate>: "
                                "cv.wait(...)` (or cv.wait_for)",
                                info.qualname,
                                mod.line_text(node.lineno))
                        break
            elif attr in cc.CV_NOTIFY:
                if not any(held == recv_key
                           and cc.span_contains(w, node)
                           for held, w, _e in spans):
                    yield Finding(
                        RULE, mod.path, mod.rel, node.lineno,
                        node.col_offset,
                        f"{attr}() on '{cc.pretty(recv_key)}' "
                        "without holding its lock — the waiter can "
                        "check its predicate, miss this notify, and "
                        "sleep forever; wrap in `with "
                        f"{cc.pretty(recv_key).split('.')[-1]}:`",
                        info.qualname, mod.line_text(node.lineno))
