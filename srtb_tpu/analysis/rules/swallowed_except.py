"""swallowed-except: a bare/overbroad except in pipeline/io code that
drops the exception without logging, re-raising, or recording it.

The streaming runtime's whole resilience story rests on failures being
*classified and accounted* (resilience/errors.py): a handler that
catches ``Exception``/``BaseException`` (or everything) and silently
discards it removes a failure from the taxonomy entirely — it can
never be retried, escalated, or even seen on /metrics.  Narrow
catches (``OSError``, ``queue.Empty``, ...) are out of scope: a named
exception type is itself a documented decision.

A handler counts as *handling* the exception when its body re-raises
(any ``raise``), calls a logging-ish function (``log.*``,
``logging.*``, ``logger.*``, ``warnings.warn``), or reads the bound
exception name (storing it, formatting it, returning it).  Scope is
restricted to pipeline/ and io/ modules — the hot path where a
swallowed failure becomes silent data loss; elsewhere (GUI taps,
best-effort telemetry) broad swallows can be a deliberate
availability choice.
"""

from __future__ import annotations

import ast

from srtb_tpu.analysis.core import Finding

RULE = "swallowed-except"
DOC = ("bare/overbroad except in pipeline/io code that drops the "
       "exception without logging or re-raising")

_SCOPES = ("pipeline/", "io/")
_BROAD = {"Exception", "BaseException"}
_LOGGISH = ("log", "logging", "logger", "warnings")


def _in_scope(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    return any(f"/{s}" in f"/{rel}" for s in _SCOPES)


def _is_broad(type_node) -> bool:
    """Bare except, Exception/BaseException (possibly dotted), or a
    tuple containing one."""
    if type_node is None:
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(e) for e in type_node.elts)
    name = None
    if isinstance(type_node, ast.Name):
        name = type_node.id
    elif isinstance(type_node, ast.Attribute):
        name = type_node.attr
    return name in _BROAD


def _handles(handler: ast.ExceptHandler) -> bool:
    bound = handler.name
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Name) and node.id == bound \
                and isinstance(node.ctx, ast.Load):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            root = f
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in _LOGGISH:
                return True
    return False


def check(project, mod):
    if not _in_scope(mod.rel):
        return
    # map line -> enclosing function qualname for finding context
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node.type):
            continue
        if _handles(node):
            continue
        enclosing = None
        for info in mod.functions.values():
            f = info.node
            end = getattr(f, "end_lineno", f.lineno)
            if f.lineno <= node.lineno <= end and (
                    enclosing is None
                    or f.lineno > enclosing.node.lineno):
                enclosing = info  # innermost = latest-starting
        context = enclosing.qualname if enclosing else "<module>"
        caught = ("everything" if node.type is None
                  else ast.unparse(node.type))
        yield Finding(
            RULE, mod.path, mod.rel, node.lineno, node.col_offset,
            f"catches {caught} and drops the exception (no raise, no "
            "logging, bound name unused) — classify it "
            "(resilience/errors.py), log it, or narrow the except",
            context, mod.line_text(node.lineno))
