"""unguarded-shared-state: the same attribute mutated from a spawned
thread and from other code without a lock.

The engine is deliberately multi-threaded — the sink Pipe, UDP
receivers, the backpressure pump, writer pools — and every shared
mutable touched from two threads needs a lock (or a documented
exclusivity argument recorded in the baseline).  This rule finds, per
class (and per closure scope for nested functions), attributes and
closure containers that are mutated both inside thread-entry code
(functions handed to ``threading.Thread``/``Timer`` or the framework's
``start_pipe``, plus everything they call) and outside it, where at
least one mutation site is not inside a ``with <...lock/cv...>:``
block.

Mutation means assignment/augmented assignment to ``self.X...`` or a
closure container, and calls of known mutating methods
(``append``/``popleft``/``update``/...).  ``__init__`` is excluded
(it runs before any thread exists).
"""

from __future__ import annotations

import ast

from srtb_tpu.analysis.core import Finding, ModuleSource, Project

RULE = "unguarded-shared-state"
DOC = ("attribute mutated both on a spawned thread and outside it "
       "without a lock")

_SPAWN_THREAD = {"threading.Thread", "threading.Timer"}
_MUTATORS = {"append", "appendleft", "extend", "add", "remove",
             "discard", "pop", "popleft", "popitem", "clear", "insert",
             "update", "setdefault", "put", "put_nowait"}
_LOCKISH = ("lock", "_cv", "cv", "cond", "mutex", "_mu")
_EXEMPT = {"__init__", "__post_init__", "__del__"}


def _entry_functions(project: Project, mod: ModuleSource):
    """Functions handed to Thread/Timer/start_pipe in this module."""
    entries = set()
    for info in mod.functions.values():
        for node in info.body_nodes():
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.dotted_name(node.func) or ""
            target = None
            if dotted in _SPAWN_THREAD:
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
                if target is None and dotted.endswith("Timer") \
                        and len(node.args) >= 2:
                    target = node.args[1]
            elif dotted == "start_pipe" or dotted.endswith(
                    ".start_pipe"):
                if node.args:
                    target = node.args[0]
            if target is None:
                continue
            resolved = project.resolve_call(mod, info, target)
            if resolved is not None:
                entries.add(resolved)
    return entries


def _top_scope(mod: ModuleSource, info) -> str:
    while info.parent:
        info = mod.functions[info.parent]
    return info.qualname


def _param_names(fnode) -> set[str]:
    a = fnode.args
    return {p.arg for p in
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)}


def _assigned_names(fnode) -> set[str]:
    names = set()
    for node in ast.walk(fnode):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fnode:
            continue
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Store):
            names.add(node.id)
    return names


def _guarded(fnode, node) -> bool:
    """Is ``node`` lexically inside a with-block over a lock-ish
    object?"""
    for w in ast.walk(fnode):
        if not isinstance(w, ast.With):
            continue
        end = getattr(w, "end_lineno", w.lineno)
        if not (w.lineno <= node.lineno <= end):
            continue
        for item in w.items:
            text = ast.unparse(item.context_expr).lower()
            if any(tok in text for tok in _LOCKISH):
                return True
    return False


def _mutations(mod: ModuleSource, info):
    """Yield (key, node, guarded).  Keys: "Class.self.attr" for
    attribute state, "scope:name" for closure containers."""
    fnode = info.node
    params_ = _param_names(fnode)
    locals_ = _assigned_names(fnode)

    def attr_key(target):
        # self.a.b.c -> first attribute after self
        chain = []
        t = target
        while isinstance(t, ast.Attribute):
            chain.append(t.attr)
            t = t.value
        if isinstance(t, ast.Name) and t.id == "self" and chain:
            cls = info.class_name or "<no-class>"
            return f"{cls}.self.{chain[-1]}"
        return None

    def closure_key(name_node):
        # containers shared between a scope and its nested thread
        # functions: keyed by the top enclosing scope, so the same
        # name in unrelated functions never collides.  Params are the
        # callee's own view (tracked at the caller); imported
        # singletons (metrics, log) own their locking.
        if not isinstance(name_node, ast.Name):
            return None
        n = name_node.id
        if n in params_ or n in mod.import_alias or n == "self":
            return None
        if info.parent is None and n not in locals_:
            return None  # module global mutation: out of scope here
        return f"{_top_scope(mod, info)}:{n}"

    for node in info.body_nodes():
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            key = None
            if isinstance(t, ast.Attribute):
                key = attr_key(t)
            elif isinstance(t, ast.Subscript):
                key = (attr_key(t.value)
                       or closure_key(t.value))
            if key is not None:
                yield key, node, _guarded(fnode, node)
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            recv = node.func.value
            key = None
            if isinstance(recv, ast.Attribute):
                key = attr_key(recv)
            elif isinstance(recv, ast.Name):
                key = closure_key(recv)
            if key is not None:
                yield key, node, _guarded(fnode, node)


def check(project: Project, mod: ModuleSource):
    entries = _entry_functions(project, mod)
    if not entries:
        return
    entry_closure = {f for f in project.reachable(entries)
                     if f.module is mod}
    entry_muts: dict[str, list] = {}
    other_muts: dict[str, list] = {}
    for info in mod.functions.values():
        if info.name in _EXEMPT:
            continue
        side = (entry_muts if info in entry_closure else other_muts)
        for key, node, guarded in _mutations(mod, info):
            side.setdefault(key, []).append((info, node, guarded))
    for key in sorted(set(entry_muts) & set(other_muts)):
        sites = entry_muts[key] + other_muts[key]
        unguarded = [s for s in sites if not s[2]]
        if not unguarded:
            continue
        info, node, _ = min(
            unguarded, key=lambda s: (s[1].lineno, s[1].col_offset))
        e_names = sorted({s[0].qualname for s in entry_muts[key]})
        o_names = sorted({s[0].qualname for s in other_muts[key]})
        state = key.split(":", 1)[-1].replace(".self.", ".")
        yield Finding(
            RULE, mod.path, mod.rel, node.lineno, node.col_offset,
            f"'{state}' is mutated on a spawned thread "
            f"({', '.join(e_names)}) and outside it "
            f"({', '.join(o_names)}) with at least one unlocked "
            "site — guard with a lock or record the exclusivity "
            "argument in the baseline",
            info.qualname, mod.line_text(node.lineno))
