"""blocking-under-lock: an unbounded blocking call made while a lock
is held.

A lock held across a blocking call convoys every other thread that
needs it for the full duration of the block — on the fleet scheduler
or a sink-pipe hot path that turns one slow tenant's I/O into a
fleet-wide stall, and combined with any second lock it upgrades a
latency bug into a deadlock.  Flagged while a ``with <lock>`` span is
open (lexically, or reachable through the call graph from a call made
inside one):

- ``os.fdatasync`` / ``os.fsync`` (storage-durability barrier:
  milliseconds to seconds on a busy disk);
- socket ``.recv``/``.recvfrom``/``.recv_into`` (peer-paced);
- queue ``.get()`` with no timeout (blocks until a producer shows up
  — the framework's ``WorkQueue.pop`` uses a 50 ms timeout loop for
  exactly this reason);
- ``.join()`` on a pipe/thread/process (waits on another thread,
  which may need the held lock: the classic self-deadlock);
- ``.wait(...)`` on a DIFFERENT condition/lock than the one held
  (waiting on cv B under lock A deadlocks the notifier if it needs A;
  waiting on the cv you hold is the sanctioned idiom and exempt).
"""

from __future__ import annotations

import ast

from srtb_tpu.analysis.core import Finding, FunctionInfo, ModuleSource, Project
from srtb_tpu.analysis.rules import _concurrency as cc

RULE = "blocking-under-lock"
DOC = ("fdatasync / socket recv / untimed queue.get / join / foreign "
       "condition-wait while a lock is held")

_RECV = ("recv", "recvfrom", "recv_into")
_JOINISH = ("pipe", "thread", "proc", "worker")


def _blocking(mod: ModuleSource, info: FunctionInfo, node: ast.Call,
              held: str | None):
    """Describe why ``node`` is an unbounded blocking call, or None.
    ``held`` is the lock key currently held (None = classifying a
    callee's body for the transitive scan, where any foreign wait
    counts)."""
    dotted = mod.dotted_name(node.func)
    if dotted in ("os.fdatasync", "os.fsync"):
        return f"{dotted}() durability barrier"
    if not isinstance(node.func, ast.Attribute):
        return None
    attr = node.func.attr
    recv = node.func.value
    try:
        recv_text = ast.unparse(recv)
    except Exception:  # noqa: BLE001 - exotic receiver
        return None
    if attr in _RECV:
        return f"socket {attr}() on '{recv_text}'"
    if attr == "get" and not node.args \
            and not any(kw.arg == "timeout" for kw in node.keywords):
        # x.get() with no args and no timeout: the blocking queue
        # read (dict.get always passes a key)
        return f"untimed blocking get() on '{recv_text}'"
    if attr == "join" and dotted != "os.path.join" \
            and not isinstance(recv, ast.Constant) \
            and any(tok in recv_text.lower() for tok in _JOINISH):
        return f"join() on '{recv_text}'"
    if attr in cc.CV_WAIT:
        key = cc.lock_key(mod, info, recv)
        if key is not None and key != held:
            return (f"wait on '{recv_text}' (a different lock than "
                    "the one held)")
    return None


def _own_blocking(mod: ModuleSource, info: FunctionInfo):
    """(desc, node) for blocking calls in this function's own body
    that are NOT under a with-span of their own (those are reported
    at the holding site)."""
    out = []
    for node in info.body_nodes():
        if isinstance(node, ast.Call):
            desc = _blocking(mod, info, node, held=None)
            if desc is not None:
                out.append((desc, node))
    return out


def _closure_blocking(project: Project, fn: FunctionInfo):
    """Blocking calls reachable from ``fn`` (memoized)."""
    cache = getattr(project, "_blocking_closure", None)
    if cache is None:
        cache = project._blocking_closure = {}
    hit = cache.get(fn)
    if hit is None:
        hit = []
        for g in project.reachable({fn}):
            for desc, node in _own_blocking(g.module, g):
                hit.append((desc, g, node))
        cache[fn] = hit
    return hit


def check(project: Project, mod: ModuleSource):
    for info in mod.functions.values():
        spans = list(cc.with_locks(mod, info))
        if not spans:
            continue
        nodes = list(info.body_nodes())
        seen: set[tuple] = set()
        for held, w, _e in spans:
            for node in nodes:
                if not isinstance(node, ast.Call) \
                        or not cc.span_contains(w, node):
                    continue
                desc = _blocking(mod, info, node, held=held)
                if desc is not None:
                    if (node.lineno, node.col_offset, desc) in seen:
                        continue
                    seen.add((node.lineno, node.col_offset, desc))
                    yield Finding(
                        RULE, mod.path, mod.rel, node.lineno,
                        node.col_offset,
                        f"{desc} while holding "
                        f"'{cc.pretty(held)}' — every thread needing "
                        "the lock convoys behind the block; move the "
                        "call outside the critical section or bound "
                        "it with a timeout", info.qualname,
                        mod.line_text(node.lineno))
                    continue
                callee = project.resolve_call(mod, info, node.func)
                if callee is None:
                    continue
                for desc, g, _bn in _closure_blocking(project, callee):
                    key = (node.lineno, node.col_offset, desc)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield Finding(
                        RULE, mod.path, mod.rel, node.lineno,
                        node.col_offset,
                        f"call reaches {desc} (in {g.qualname}, "
                        f"{g.module.rel}) while holding "
                        f"'{cc.pretty(held)}' — the blocking I/O "
                        "executes inside the critical section",
                        info.qualname, mod.line_text(node.lineno))
