"""recompile-hazard: jit construction patterns that retrace or
recompile per call.

``jax.jit`` caches compiled executables keyed on the *identity* of the
wrapped callable.  Three spellings defeat that cache:

- ``jax.jit(f)`` inside a loop: a fresh wrapper per iteration;
- ``jax.jit(f)(x)`` immediately invoked inside a function that runs
  per segment: a fresh wrapper per call;
- ``jax.jit(self.method)`` / ``jax.jit(lambda ...)`` outside
  ``__init__``: bound methods and lambdas are new objects on every
  evaluation, so even a cached-looking spelling recompiles every call.

At the 2^30 production segment shape one recompile costs minutes of
XLA time (PERF.md), so "it still returns the right numbers" hides an
outage-grade regression.  Construction in ``__init__`` or at module
scope is exempt (one-time cost by construction), as is a jit result
cached onto a ``self`` attribute (the lazy-build pattern).
"""

from __future__ import annotations

import ast

from srtb_tpu.analysis.core import (Finding, ModuleSource, Project,
                                    _assign_parent, _jit_callee)

RULE = "recompile-hazard"
DOC = ("jax.jit construction in a loop / immediately invoked / of a "
      "bound method or lambda outside __init__")

_EXEMPT_FUNCS = {"__init__", "__post_init__"}


def _in_loop(mod: ModuleSource, call: ast.Call, fnode) -> bool:
    scope = fnode if fnode is not None else mod.tree
    for node in ast.walk(scope):
        if isinstance(node, (ast.For, ast.While)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= call.lineno <= end:
                return True
    return False


def check(project: Project, mod: ModuleSource):
    immediate_jits = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Call) and _jit_callee(node.func, mod):
            immediate_jits.add(id(node.func))
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and _jit_callee(node, mod)):
            continue
        info = mod.enclosing_function(node)
        qual = info.qualname if info else "<module>"
        fname = info.name if info else "<module>"
        fnode = info.node if info else None
        exempt = info is None or fname in _EXEMPT_FUNCS
        if _in_loop(mod, node, fnode):
            yield Finding(
                RULE, mod.path, mod.rel, node.lineno, node.col_offset,
                "jax.jit constructed inside a loop — a fresh wrapper "
                "(and compile-cache key) per iteration; hoist the jit "
                "out of the loop", qual, mod.line_text(node.lineno))
            continue
        if exempt:
            continue
        if id(node) in immediate_jits:
            yield Finding(
                RULE, mod.path, mod.rel, node.lineno, node.col_offset,
                "jax.jit(...)(...) immediately invoked — a fresh "
                "wrapper per call retraces and recompiles every time; "
                "build the jit once in __init__ and reuse it",
                qual, mod.line_text(node.lineno))
            continue
        wrapped = node.args[0] if node.args else None
        bound = (isinstance(wrapped, ast.Attribute)
                 and isinstance(wrapped.value, ast.Name)
                 and wrapped.value.id == "self")
        lam = isinstance(wrapped, ast.Lambda)
        if not (bound or lam):
            continue
        assign = _assign_parent(mod.tree, node)
        cached_on_self = False
        if assign is not None:
            targets = (assign.targets if isinstance(assign, ast.Assign)
                       else [assign.target])
            cached_on_self = any(
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self" for t in targets)
        if cached_on_self:
            continue
        what = "a lambda" if lam else f"bound method 'self.{wrapped.attr}'"
        yield Finding(
            RULE, mod.path, mod.rel, node.lineno, node.col_offset,
            f"jax.jit of {what} outside __init__ — the wrapped object "
            "is new on every evaluation, so the jit cache misses and "
            "recompiles per call; cache the wrapper on self",
            qual, mod.line_text(node.lineno))
