"""lock-order-inversion: a cycle in the static lock-acquisition-order
graph.

Lockdep's core invariant, checked at lint time: if any code path
acquires lock B while holding lock A, then no path may acquire A while
holding B — two threads interleaving the two paths deadlock, each
holding what the other wants.  The graph is built from ``with <lock>``
nestings (lexical, plus acquisitions reachable through the project
call graph from calls made while a lock is held), so the inversion is
caught even when the two halves live in different functions or
modules.  A self-edge (re-acquiring a non-reentrant lock already
held) is the degenerate cycle and equally fatal.

The runtime twin is ``analysis/tsan.py``: ``Config.tsan`` records the
same graph from live acquisitions and traps cycles (and stalls) the
static approximation cannot see (locks passed through aliases,
dynamic dispatch).
"""

from __future__ import annotations

from srtb_tpu.analysis.core import Finding, ModuleSource, Project
from srtb_tpu.analysis.rules import _concurrency as cc

RULE = "lock-order-inversion"
DOC = ("cycle in the with-block lock acquisition order graph "
       "(deadlock when threads interleave)")


def _findings(project: Project) -> dict[str, list[Finding]]:
    """One finding per acquisition-order cycle, anchored at the
    cycle's first edge site (computed once per project, emitted by the
    module that owns the anchor)."""
    cached = getattr(project, "_lock_order_findings", None)
    if cached is not None:
        return cached
    ana = cc.analysis(project)
    by_mod: dict[str, list[Finding]] = {}
    for scc in ana.cycles():
        inside = sorted(
            (a, b) for (a, b) in ana.edges
            if a in scc and b in scc)
        # anchor: the first edge by file/line, deterministic
        def site(e):
            mod, node, _ctx, _note = ana.edges[e]
            return (mod.rel, node.lineno, node.col_offset)
        inside.sort(key=site)
        a, b = inside[0]
        mod, node, ctx, note = ana.edges[(a, b)]
        chain = " -> ".join(cc.pretty(k) for k in scc + [scc[0]])
        others = "; ".join(
            f"'{cc.pretty(x)}' before '{cc.pretty(y)}' at "
            f"{ana.edges[(x, y)][0].rel}:{ana.edges[(x, y)][1].lineno}"
            f" ({ana.edges[(x, y)][3]})"
            for (x, y) in inside[1:3])
        msg = (f"lock acquisition order cycle [{chain}]: "
               f"'{cc.pretty(a)}' is held while taking "
               f"'{cc.pretty(b)}' ({note}), but the reverse order "
               f"also exists ({others or 'self-edge'}) — pick one "
               "global order or record the exclusivity argument in "
               "the baseline")
        by_mod.setdefault(mod.rel, []).append(Finding(
            RULE, mod.path, mod.rel, node.lineno, node.col_offset,
            msg, ctx, mod.line_text(node.lineno)))
    project._lock_order_findings = by_mod
    return by_mod


def check(project: Project, mod: ModuleSource):
    yield from _findings(project).get(mod.rel, ())
