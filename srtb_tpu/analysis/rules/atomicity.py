"""check-then-act: a test on thread-shared state and the mutation it
gates are not atomic.

``unguarded-shared-state`` sees each attribute access in isolation: if
every *mutation* of ``self.active`` sits under the lock it stays
silent.  But ``if self.active: ... self.active = False`` is still a
race when the ``if`` reads outside the lock — another thread can flip
the flag between the check and the act, and both sides win.  This rule
tracks attributes shared between thread-entry closures (the same
entry-point resolution unguarded-shared-state uses) and the rest of
the class, and flags any ``if``/``while`` whose test reads a shared
attribute and whose body mutates it, unless the WHOLE statement sits
inside one lock-ish ``with`` block — check and act under the same
critical section is the fix, locking only the act is the bug.
"""

from __future__ import annotations

import ast

from srtb_tpu.analysis.core import Finding, FunctionInfo, ModuleSource, Project
from srtb_tpu.analysis.rules import _concurrency as cc
from srtb_tpu.analysis.rules.shared_state import (
    _EXEMPT, _MUTATORS, _mutations)

RULE = "check-then-act"
DOC = ("non-atomic test-then-mutate on state shared with a spawned "
       "thread")


def _attr_key(info: FunctionInfo, expr: ast.expr) -> str | None:
    """"Class.self.attr" for a self-attribute chain (same key shape as
    unguarded-shared-state, so the two rules agree on identity)."""
    chain = []
    t = expr
    while isinstance(t, ast.Attribute):
        chain.append(t.attr)
        t = t.value
    if isinstance(t, ast.Name) and t.id == "self" and chain:
        cls = info.class_name or "<no-class>"
        return f"{cls}.self.{chain[-1]}"
    return None


def _reads(info: FunctionInfo, node: ast.AST):
    """Self-attr keys read anywhere under ``node``."""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load):
            key = _attr_key(info, n)
            if key is not None:
                yield key


def _writes(info: FunctionInfo, node: ast.AST):
    """Self-attr keys mutated anywhere under ``node``."""
    for n in ast.walk(node):
        targets = []
        if isinstance(n, ast.Assign):
            targets = n.targets
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            targets = [n.target]
        for t in targets:
            if isinstance(t, ast.Subscript):
                t = t.value
            key = _attr_key(info, t) if isinstance(
                t, ast.Attribute) else None
            if key is not None:
                yield key
        if isinstance(n, ast.Call) and isinstance(
                n.func, ast.Attribute) and n.func.attr in _MUTATORS:
            key = _attr_key(info, n.func.value)
            if key is not None:
                yield key


def _shared_keys(project: Project, mod: ModuleSource) -> set[str]:
    """Self-attr keys mutated on one side of the thread boundary and
    touched (read or mutated) on the other."""
    entries = cc.thread_entries(project, mod)
    if not entries:
        return set()
    entry_closure = {f for f in project.reachable(entries)
                     if f.module is mod}
    muts: dict[bool, set[str]] = {True: set(), False: set()}
    touch: dict[bool, set[str]] = {True: set(), False: set()}
    for info in mod.functions.values():
        if info.name in _EXEMPT:
            continue
        side = info in entry_closure
        for key, _node, _g in _mutations(mod, info):
            if ".self." in key:
                muts[side].add(key)
                touch[side].add(key)
        for key in _reads(info, info.node):
            touch[side].add(key)
    return (muts[True] & touch[False]) | (muts[False] & touch[True])


def check(project: Project, mod: ModuleSource):
    shared = _shared_keys(project, mod)
    if not shared:
        return
    for info in mod.functions.values():
        if info.name in _EXEMPT:
            continue
        for node in info.body_nodes():
            if not isinstance(node, (ast.If, ast.While)):
                continue
            tested = set(_reads(info, node.test)) & shared
            if not tested:
                continue
            acted = set()
            for stmt in node.body + node.orelse:
                acted |= set(_writes(info, stmt))
            hits = sorted(tested & acted)
            if not hits or cc.guarded_span(mod, info, node):
                continue
            attrs = ", ".join(
                f"'{k.replace('.self.', '.')}'" for k in hits)
            yield Finding(
                RULE, mod.path, mod.rel, node.lineno, node.col_offset,
                f"check-then-act on {attrs} (shared with a spawned "
                "thread) is not atomic — another thread can change it "
                "between the test and the mutation; hold the lock "
                "across BOTH (move the if/while inside the with "
                "block) or record the exclusivity argument in the "
                "baseline",
                info.qualname, mod.line_text(node.lineno))
