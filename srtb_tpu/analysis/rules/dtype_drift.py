"""dtype-drift: 64-bit float/complex entering device code.

TPUs have no f64 ALU: the chirp's precision comes from the hand-built
df64 (two-float) path in ops/df64.py, and JAX silently truncates f64 to
f32 unless x64 mode is enabled (which this codebase never does —
enabling it globally would *change the numerics of every op*).  A
``jnp.float64`` or an f64 dtype inside a jit-traced function therefore
either truncates silently or diverges between CPU CI and TPU — the
exact drift class that corrupted the chirp precision the df64 chain
exists to protect (SURVEY.md §3.2).

Flagged:
- ``jnp.float64`` / ``jnp.complex128`` anywhere in ops/parallel code;
- numpy f64/c128 dtype references *inside jit-traced functions* (host
  f64 precompute outside traces — window tables, twiddles — is the
  sanctioned pattern and stays clean);
- string dtypes ``"float64"`` / ``"complex128"`` inside jit bodies;
- ``jax.config.update("jax_enable_x64", ...)`` in library code.

Intentional trace-time host-constant folding (e.g. the hi/lo splits in
ops/dedisperse.py computing ``np.float64(dm) - np.float32(dm)`` on
*Python scalars*) belongs in the baseline with a note, keeping the rule
hot for genuine drift.
"""

from __future__ import annotations

import ast

from srtb_tpu.analysis.core import Finding, ModuleSource, Project

RULE = "dtype-drift"
DOC = "f64/c128 dtype reaching device code (breaks TPU df64 paths)"

_JNP_64 = {"jax.numpy.float64", "jax.numpy.complex128",
           "jax.numpy.float128"}
_NP_64 = {"numpy.float64", "numpy.complex128", "numpy.float128",
          "numpy.longdouble"}
_STR_64 = {"float64", "complex128", "float128"}

# device-code directories (rel-path fragments)
_DEVICE_DIRS = ("ops/", "parallel/", "pipeline/")


def _is_device_module(mod: ModuleSource) -> bool:
    return any(d in mod.rel for d in _DEVICE_DIRS)


def _f(mod, node, msg, qual):
    return Finding(RULE, mod.path, mod.rel, node.lineno,
                   node.col_offset, msg, qual,
                   mod.line_text(node.lineno))


def check(project: Project, mod: ModuleSource):
    jit_here = {info for info in project.jit_bodies
                if info.module is mod}

    def in_jit(node):
        info = mod.enclosing_function(node)
        return info if info in jit_here else None

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            dotted = mod.dotted_name(node.func)
            if dotted == "jax.config.update" and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value == "jax_enable_x64":
                info = mod.enclosing_function(node)
                yield _f(mod, node,
                         "jax_enable_x64 toggled in library code — "
                         "changes the numerics of every op globally; "
                         "use the df64 two-float path instead",
                         info.qualname if info else "<module>")
        if isinstance(node, ast.Attribute):
            dotted = mod.dotted_name(node)
            if dotted in _JNP_64 and _is_device_module(mod):
                info = mod.enclosing_function(node)
                yield _f(mod, node,
                         f"{dotted.replace('jax.numpy', 'jnp')} in "
                         "device code — TPUs truncate to f32 without "
                         "x64 mode; use the ops/df64 two-float path",
                         info.qualname if info else "<module>")
            elif dotted in _NP_64:
                info = in_jit(node)
                if info is not None:
                    yield _f(mod, node,
                             f"np.{node.attr} inside jit-traced "
                             f"'{info.name}' — f64 host constants "
                             "fold into an f32 trace (silent "
                             "truncation on TPU)", info.qualname)
        if isinstance(node, ast.Constant) and node.value in _STR_64:
            info = in_jit(node)
            if info is not None:
                yield _f(mod, node,
                         f'dtype string "{node.value}" inside '
                         f"jit-traced '{info.name}' — silently "
                         "truncates to f32 on TPU", info.qualname)
