"""Opt-in runtime sanitizer for the pipeline (``Config.sanitize``).

The linter (analysis/lint.py) catches hazard *spellings*; this module
catches hazard *behavior* on a live run, trading throughput for
trapping, with zero cost when disabled (the pipeline holds ``None``
and never calls in here):

- **implicit device->host transfers**: ``np.asarray``/``np.array`` on
  a ``jax.Array`` raises :class:`SanitizerError`; explicit
  ``jax.device_get`` stays allowed.  Two mechanisms, because
  ``jax.transfer_guard`` is a no-op on the CPU backend (host==device,
  nothing crosses a boundary): the guard config is set globally for
  real accelerators, and the numpy entry points are wrapped for the
  call-pattern check that CPU CI can enforce.  Process-wide, so sink
  Pipe / writer threads are covered too.
- **use-after-donate**: after a donated dispatch completes, the input
  buffer is explicitly ``delete()``-d, so a later read raises
  ("Array has been deleted") even on CPU where donation itself is a
  no-op and the bug would otherwise ship silently to the TPU.
- **NaN/Inf tripwires** at segment-plan boundaries
  (:func:`check_finite`), and **shape/dtype contract asserts** between
  stages (:func:`check_contract`).
- **thread-ownership guards**: engine state names are claimed by the
  first accessing thread and asserted on every subsequent access
  (:meth:`Sanitizer.assert_owner`).
- **leaked-thread check**: a run must end with every thread it spawned
  joined (utils/termination.leaked_threads).

Sanitized dispatches serialize (the donation expiry blocks on the
result), so ``Config.sanitize`` is a debugging mode, not a production
mode — PERF.md documents the A/B showing zero overhead when off.
"""

from __future__ import annotations

# srtb-lint: disable-file=sync-hot-path (every sync in this module IS
# the sanitizer doing its job: sanitize mode serializes by design)

import contextlib
import threading

import numpy as np

from srtb_tpu.utils.logging import log


class SanitizerError(AssertionError):
    """A sanitizer tripwire fired.  Message includes the stage/state
    name and what to do about it."""


# ------------------------------------------------------------------
# implicit-transfer tripwire (module-level: installed refcounted so
# nested sanitized pipelines compose; thread-local allowance so
# jax.device_get stays the sanctioned spelling)
# ------------------------------------------------------------------

_tls = threading.local()
_install_lock = threading.Lock()
_install_count = 0
_saved = {}


def _allowed() -> bool:
    return getattr(_tls, "allow_transfers", 0) > 0


@contextlib.contextmanager
def allow_transfers():
    """Mark the current thread as performing a sanctioned explicit
    transfer (used by the wrapped ``jax.device_get``)."""
    prev = getattr(_tls, "allow_transfers", 0)
    _tls.allow_transfers = prev + 1
    try:
        yield
    finally:
        _tls.allow_transfers = prev


def _wrap_np(orig, name):
    def wrapped(a, *args, **kwargs):
        import jax
        if isinstance(a, jax.Array) and not _allowed():
            raise SanitizerError(
                f"[sanitize] implicit device->host transfer: "
                f"np.{name}() on a jax.Array of shape {a.shape} "
                f"dtype {a.dtype} — use jax.device_get(...) at a "
                "sanctioned sync point (drain/sink side), never on "
                "the dispatch hot path (srtb-lint: sync-hot-path)")
        return orig(a, *args, **kwargs)
    wrapped.__name__ = name
    wrapped._srtb_sanitize_orig = orig
    return wrapped


def _install_tripwire() -> None:
    global _install_count
    with _install_lock:
        _install_count += 1
        if _install_count > 1:
            return
        import jax
        _saved["asarray"] = np.asarray
        _saved["array"] = np.array
        np.asarray = _wrap_np(np.asarray, "asarray")
        np.array = _wrap_np(np.array, "array")
        _saved["device_get"] = jax.device_get

        def device_get(x):
            with allow_transfers():
                return _saved["device_get"](x)
        jax.device_get = device_get
        # real accelerators also get JAX's own guard (no-op on CPU);
        # host->device stays permissive: implicit H2D is a perf wart
        # the linter covers, not a stream-serializing sync
        try:
            _saved["guard"] = jax.config.jax_transfer_guard_device_to_host
            jax.config.update("jax_transfer_guard_device_to_host",
                              "disallow")
        except Exception:  # config knob absent on this jax
            _saved["guard"] = None
            log.warning("[sanitize] jax transfer-guard config "
                        "unavailable; numpy tripwire only")


def _uninstall_tripwire() -> None:
    global _install_count
    with _install_lock:
        _install_count -= 1
        if _install_count > 0:
            return
        import jax
        np.asarray = _saved.pop("asarray")
        np.array = _saved.pop("array")
        jax.device_get = _saved.pop("device_get")
        guard = _saved.pop("guard", None)
        with contextlib.suppress(Exception):
            jax.config.update("jax_transfer_guard_device_to_host",
                              guard if guard is not None else "allow")


# ------------------------------------------------------------------
# value / contract checks
# ------------------------------------------------------------------

def _float_leaves(tree):
    import jax
    for leaf in jax.tree_util.tree_leaves(tree):
        dt = getattr(leaf, "dtype", None)
        if dt is not None and np.issubdtype(dt, np.inexact):
            yield leaf


def check_finite(tag: str, tree) -> None:
    """NaN/Inf tripwire over every float/complex leaf of ``tree``
    (device leaves are reduced on device; only a scalar crosses)."""
    import jax
    import jax.numpy as jnp
    for leaf in _float_leaves(tree):
        if isinstance(leaf, jax.Array):
            ok = bool(jax.device_get(jnp.isfinite(leaf).all()))
        else:
            ok = bool(np.isfinite(np.asarray(leaf)).all())
        if not ok:
            raise SanitizerError(
                f"[sanitize] non-finite values at '{tag}' (shape "
                f"{getattr(leaf, 'shape', '?')}, dtype "
                f"{getattr(leaf, 'dtype', '?')}) — a stage upstream "
                f"of '{tag}' produced NaN/Inf; re-run with per-stage "
                "checks (staged plan) to bisect, and check RFI "
                "normalization / window coefficients first")


def check_contract(tag: str, arr, *, ndim: int | None = None,
                   lead: int | None = None, dtype=None) -> None:
    """Shape/dtype contract between stages: the stacked (re, im)
    boundary representation is load-bearing (complex never crosses
    jit boundaries on some TPU runtimes — segment.py)."""
    if arr is None:
        return
    shape = getattr(arr, "shape", None)
    adt = getattr(arr, "dtype", None)
    if ndim is not None and len(shape) != ndim:
        raise SanitizerError(
            f"[sanitize] stage contract broken at '{tag}': expected "
            f"ndim {ndim}, got shape {shape} — a plan change altered "
            "the boundary representation without updating consumers")
    if lead is not None and (not shape or shape[0] != lead):
        raise SanitizerError(
            f"[sanitize] stage contract broken at '{tag}': expected "
            f"leading axis {lead} (stacked re/im), got shape {shape}")
    if dtype is not None and adt != np.dtype(dtype):
        raise SanitizerError(
            f"[sanitize] stage contract broken at '{tag}': expected "
            f"dtype {np.dtype(dtype)}, got {adt} — dtype drift "
            "(srtb-lint: dtype-drift) breaks the TPU df64 path")


def expire_donated(raw, results) -> None:
    """Make use-after-donate loud on every backend: once the donated
    call's ``results`` are materialized the input buffer is dead by
    contract, so delete it — a later read raises 'Array has been
    deleted' at the offending line instead of returning garbage on
    the TPU only."""
    import jax
    jax.block_until_ready(results)
    with contextlib.suppress(Exception):
        raw.delete()


# ------------------------------------------------------------------
# the per-pipeline object
# ------------------------------------------------------------------

class Sanitizer:
    """One pipeline run's sanitizer state (thread owners + run scope).

    The pipeline holds ``None`` when ``Config.sanitize`` is off; every
    hook site is an ``if san is not None`` — nothing else, which is
    what makes the disabled path zero-cost.
    """

    def __init__(self):
        self._owners: dict[str, tuple[int, str]] = {}
        self._lock = threading.Lock()

    # -- thread ownership

    def assert_owner(self, name: str) -> None:
        """Claim-on-first-use thread ownership: the first thread to
        touch state ``name`` owns it for the run; any other thread
        touching it afterwards is a cross-thread mutation bug."""
        t = threading.current_thread()
        with self._lock:
            owner = self._owners.setdefault(name, (t.ident, t.name))
        if owner[0] != t.ident:
            raise SanitizerError(
                f"[sanitize] thread-ownership violation on '{name}': "
                f"owned by thread '{owner[1]}' but touched from "
                f"'{t.name}' — engine window state is single-owner "
                "by design; route cross-thread work through the sink "
                "Pipe or add a lock (srtb-lint: "
                "unguarded-shared-state)")

    def release_owners(self) -> None:
        with self._lock:
            self._owners.clear()

    # -- run scope

    @contextlib.contextmanager
    def run_scope(self):
        """Arm the transfer tripwire and the leaked-thread check for
        the duration of one pipeline run."""
        from srtb_tpu.utils import termination
        snapshot = termination.thread_snapshot()
        _install_tripwire()
        try:
            yield self
        finally:
            _uninstall_tripwire()
            self.release_owners()
            leaked = termination.leaked_threads(snapshot)
            if leaked:
                names = termination.describe_threads(leaked)
                raise SanitizerError(
                    f"[sanitize] leaked thread(s) after run: {names} "
                    "— every thread spawned during a run must be "
                    "joined on shutdown (see the join audit in "
                    "utils/termination.py)")

    # -- per-segment checks (module functions re-exported for hooks)

    check_finite = staticmethod(check_finite)
    check_contract = staticmethod(check_contract)
    expire_donated = staticmethod(expire_donated)
