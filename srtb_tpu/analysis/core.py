"""Shared machinery for the srtb-lint rules.

Pure-AST: the scanned code is parsed, never imported, so the linter can
run on broken or accelerator-only modules from any environment.  The
interesting piece is a lightweight whole-project call graph — enough
name resolution (module aliases, ``self.method``, nested functions,
``jax.jit`` wrapper assignments) to answer the two questions every rule
here needs: *which functions execute inside a jit trace* and *which
functions run on a spawned thread*.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field

_PRAGMA_RE = re.compile(
    r"#\s*srtb-lint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\- ]+)")


# ----------------------------------------------------------- findings


@dataclass
class Finding:
    """One rule hit, pointing at file:line with enough context to build
    a line-number-independent baseline key."""

    rule: str
    path: str          # path as given on the command line (display)
    rel: str           # package-relative path (stable baseline key part)
    line: int
    col: int
    message: str
    context: str       # enclosing function qualname or "<module>"
    line_text: str

    @property
    def key(self) -> str:
        """Baseline identity: survives unrelated edits that only move
        line numbers (file + rule + enclosing function + source text)."""
        return "::".join((self.rel, self.rule, self.context,
                          self.line_text.strip()))

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message} [in {self.context}]")


# ----------------------------------------------------------- functions


@dataclass
class FunctionInfo:
    """One function/method/nested def, with its resolution context."""

    name: str
    qualname: str
    node: ast.AST                    # FunctionDef / AsyncFunctionDef
    module: "ModuleSource"
    class_name: str | None = None    # nearest enclosing class
    parent: str | None = None        # enclosing function qualname
    calls: set = field(default_factory=set)   # resolved FunctionInfo set

    def __hash__(self):
        return hash((self.module.rel, self.qualname))

    def __eq__(self, other):
        return (isinstance(other, FunctionInfo)
                and self.module is other.module
                and self.qualname == other.qualname)

    def body_nodes(self):
        """All AST nodes of this function's own body, excluding the
        bodies of nested function/class definitions (those are separate
        FunctionInfo / scope units)."""
        todo = list(ast.iter_child_nodes(self.node))
        while todo:
            node = todo.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            yield node
            todo.extend(ast.iter_child_nodes(node))


# ------------------------------------------------------------- module


class ModuleSource:
    """One parsed source file: AST, function index, import aliases and
    suppression pragmas."""

    def __init__(self, path: str, rel: str, text: str, dotted: str):
        self.path = path
        self.rel = rel
        self.dotted = dotted
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, list[FunctionInfo]] = {}
        # local name -> dotted module, or "dotted.module:symbol"
        self.import_alias: dict[str, str] = {}
        self._collect_functions()
        self._collect_imports()
        self._disable_line: dict[int, set[str]] = {}
        self._disable_file: set[str] = set()
        self._collect_pragmas()

    # -- construction

    def _collect_functions(self) -> None:
        mod = self

        class V(ast.NodeVisitor):
            def __init__(self):
                self.stack: list[tuple[str, str]] = []  # (kind, name)

            def _qual(self, name):
                return ".".join([n for _, n in self.stack] + [name])

            def visit_ClassDef(self, node):
                self.stack.append(("class", node.name))
                self.generic_visit(node)
                self.stack.pop()

            def _func(self, node):
                qual = self._qual(node.name)
                cls = next((n for k, n in reversed(self.stack)
                            if k == "class"), None)
                parent = None
                for k, n in reversed(self.stack):
                    if k == "func":
                        parent = ".".join(
                            [x for _, x in self.stack[
                                :self.stack.index((k, n)) + 1]])
                        break
                info = FunctionInfo(node.name, qual, node, mod,
                                    class_name=cls, parent=parent)
                mod.functions[qual] = info
                if cls is not None:
                    mod.classes.setdefault(cls, []).append(info)
                self.stack.append(("func", node.name))
                self.generic_visit(node)
                self.stack.pop()

            visit_FunctionDef = _func
            visit_AsyncFunctionDef = _func

        V().visit(self.tree)

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    self.import_alias[local] = (a.name if a.asname
                                                else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    local = a.asname or a.name
                    self.import_alias[local] = f"{node.module}:{a.name}"

    def _collect_pragmas(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",")
                     if r.strip()}
            if m.group("file"):
                self._disable_file |= rules
            else:
                self._disable_line.setdefault(i, set()).update(rules)

    # -- queries

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def disabled(self, lineno: int, rule: str) -> bool:
        """Suppressed by a pragma on this line, on directly preceding
        comment-only lines, or file-wide."""
        def hit(ln):
            rules = self._disable_line.get(ln, ())
            return rule in rules or "all" in rules

        if rule in self._disable_file or "all" in self._disable_file:
            return True
        if hit(lineno):
            return True
        ln = lineno - 1
        while ln >= 1 and self.line_text(ln).lstrip().startswith("#"):
            if hit(ln):
                return True
            ln -= 1
        return False

    def enclosing_function(self, node: ast.AST) -> FunctionInfo | None:
        """Innermost FunctionInfo whose span contains ``node``."""
        best = None
        for info in self.functions.values():
            f = info.node
            end = getattr(f, "end_lineno", f.lineno)
            if f.lineno <= node.lineno <= end:
                if best is None or f.lineno > best.node.lineno:
                    best = info
        return best

    def resolves_to(self, expr: ast.expr, *candidates: str) -> bool:
        """True when ``expr`` names one of the dotted ``candidates``
        through this module's import aliases.  E.g. with ``import
        jax``, ``jax.jit`` resolves to "jax.jit"; with ``from jax
        import jit as J``, ``J`` resolves to "jax.jit"."""
        dotted = self.dotted_name(expr)
        return dotted is not None and dotted in candidates

    def dotted_name(self, expr: ast.expr) -> str | None:
        """Alias-resolved dotted name of a Name/Attribute chain."""
        parts = []
        while isinstance(expr, ast.Attribute):
            parts.append(expr.attr)
            expr = expr.value
        if not isinstance(expr, ast.Name):
            return None
        base = self.import_alias.get(expr.id, expr.id)
        base = base.replace(":", ".")
        return ".".join([base] + list(reversed(parts)))


# ------------------------------------------------------------ project


def _jit_callee(call: ast.Call, mod: ModuleSource) -> bool:
    return mod.resolves_to(call.func, "jax.jit", "jax.api.jit",
                           "jax._src.api.jit", "jax.pjit")


def _donated_positions(call: ast.Call):
    """donate_argnums of a jax.jit call: a set of ints, or "dynamic"
    when the value is not a literal (conditionally donating wrappers —
    still rule-relevant, treated as position 0)."""
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            v = kw.value
            if isinstance(v, (ast.Tuple, ast.List)):
                out = set()
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(
                            e.value, int):
                        out.add(e.value)
                    else:
                        return "dynamic"
                return out
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            return "dynamic"
    return set()


class Project:
    """All scanned modules + the cross-module call graph + jit roots."""

    def __init__(self, modules: list[ModuleSource]):
        self.modules = modules
        self.by_dotted: dict[str, ModuleSource] = {}
        for m in modules:
            self.by_dotted[m.dotted] = m
            # a package's modules are importable both as
            # "srtb_tpu.ops.fft" and (scan-root relative) "ops.fft"
            short = m.dotted.split(".", 1)[-1]
            self.by_dotted.setdefault(short, m)
        # (module, class|None, name) -> (target FunctionInfo, donated)
        self.jit_wrappers: dict[tuple, tuple[FunctionInfo, object]] = {}
        self.jit_roots: set[FunctionInfo] = set()
        self._build_call_graph()
        self._find_jit_roots()
        self.jit_bodies = self.reachable(self.jit_roots)

    # -- resolution

    def _resolve_module_func(self, mod: ModuleSource, dotted: str,
                             name: str) -> FunctionInfo | None:
        target = self.by_dotted.get(dotted)
        if target is None:
            return None
        return target.functions.get(name)

    def resolve_call(self, mod: ModuleSource, caller: FunctionInfo,
                     func: ast.expr) -> FunctionInfo | None:
        """Best-effort callee resolution for the edge kinds this project
        actually contains: bare names (nested/sibling/module scope),
        ``self.method``, and ``alias.func`` across modules."""
        if isinstance(func, ast.Name):
            name = func.id
            # own nested defs, then enclosing-function siblings
            scope = caller
            while scope is not None:
                hit = mod.functions.get(f"{scope.qualname}.{name}")
                if hit is not None:
                    return hit
                scope = (mod.functions.get(scope.parent)
                         if scope.parent else None)
            # same-class method referenced bare (rare), module function
            if caller.class_name:
                hit = mod.functions.get(f"{caller.class_name}.{name}")
                if hit is not None:
                    return hit
            hit = mod.functions.get(name)
            if hit is not None:
                return hit
            # imported symbol
            alias = mod.import_alias.get(name)
            if alias and ":" in alias:
                dotted, sym = alias.split(":", 1)
                return self._resolve_module_func(mod, dotted, sym)
            return None
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                name = func.attr
                if caller.class_name:
                    hit = mod.functions.get(
                        f"{caller.class_name}.{name}")
                    if hit is not None:
                        return hit
                # inherited methods: any class in this module that
                # defines the method (approximation good enough for the
                # Pipeline/ThreadedPipeline pair)
                for infos in mod.classes.values():
                    for info in infos:
                        if info.name == name:
                            return info
                return None
            dotted = mod.dotted_name(func.value)
            if dotted is not None:
                return self._resolve_module_func(mod, dotted, func.attr)
        return None

    # -- graph construction

    def _build_call_graph(self) -> None:
        for mod in self.modules:
            for info in mod.functions.values():
                for node in info.body_nodes():
                    if isinstance(node, ast.Call):
                        callee = self.resolve_call(mod, info, node.func)
                        if callee is not None:
                            info.calls.add(callee)

    def _find_jit_roots(self) -> None:
        for mod in self.modules:
            # decorator spellings: @jax.jit, @jit, and
            # @partial(jax.jit, ...) all make the function a jit body
            for info in mod.functions.values():
                for dec in getattr(info.node, "decorator_list", ()):
                    if mod.resolves_to(dec, "jax.jit") or (
                            isinstance(dec, ast.Call)
                            and (_jit_callee(dec, mod) or any(
                                mod.resolves_to(a, "jax.jit")
                                for a in dec.args))):
                        self.jit_roots.add(info)
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and _jit_callee(node, mod) and node.args):
                    continue
                donated = _donated_positions(node)
                enclosing = mod.enclosing_function(node)
                targets = self._jit_targets(mod, enclosing, node.args[0])
                for t in targets:
                    self.jit_roots.add(t)
                self._record_wrapper(mod, node, targets, donated)

    def _jit_targets(self, mod, enclosing, wrapped) -> list[FunctionInfo]:
        """Function(s) a jax.jit argument refers to.  For a lambda the
        functions *called inside it* become jit bodies."""
        if isinstance(wrapped, ast.Lambda):
            out = []
            for sub in ast.walk(wrapped.body):
                if isinstance(sub, ast.Call):
                    t = self.resolve_call(
                        mod, enclosing or _module_scope(mod), sub.func)
                    if t is not None:
                        out.append(t)
            return out
        if isinstance(wrapped, ast.Call):
            # jax.jit(jax.vmap(f)) and friends: unwrap one level
            if wrapped.args:
                return self._jit_targets(mod, enclosing, wrapped.args[0])
            return []
        t = self.resolve_call(mod, enclosing or _module_scope(mod),
                              wrapped)
        return [t] if t is not None else []

    def _record_wrapper(self, mod, call, targets, donated) -> None:
        """If the jax.jit(...) result is assigned (``self._jit_x = ...``
        or ``wrapper = ...``), remember the wrapper name so call sites
        through it can be linked to the wrapped function + donation."""
        if not targets:
            return
        assign = _assign_parent(mod.tree, call)
        if assign is None:
            return
        for tgt in assign.targets if isinstance(
                assign, ast.Assign) else [assign.target]:
            cls = None
            name = None
            if isinstance(tgt, ast.Attribute) and isinstance(
                    tgt.value, ast.Name) and tgt.value.id == "self":
                enclosing = mod.enclosing_function(call)
                cls = enclosing.class_name if enclosing else None
                name = tgt.attr
            elif isinstance(tgt, ast.Name):
                name = tgt.id
            if name is not None:
                self.jit_wrappers[(mod.dotted, cls, name)] = (
                    targets[0], donated)

    # -- reachability

    def reachable(self, seeds) -> set[FunctionInfo]:
        seen = set(seeds)
        todo = list(seeds)
        while todo:
            f = todo.pop()
            for callee in f.calls:
                if callee not in seen:
                    seen.add(callee)
                    todo.append(callee)
        return seen


def _module_scope(mod: ModuleSource) -> FunctionInfo:
    """Synthetic scope for module-level expressions."""
    return FunctionInfo("<module>", "<module>", mod.tree, mod)


def _assign_parent(tree: ast.AST, call: ast.Call) -> ast.AST | None:
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                and node.value is call:
            return node
    return None


# ----------------------------------------------------------- baseline


class Baseline:
    """Checked-in accepted findings.  Keys are line-number independent
    (see Finding.key); each entry carries an occurrence count (the same
    source line may legitimately hit a rule twice in one function) and
    a human note explaining why the finding is accepted."""

    def __init__(self, entries: dict[str, dict] | None = None):
        self.entries: dict[str, dict] = entries or {}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not path or not os.path.exists(path):
            return cls()
        with open(path) as f:
            data = json.load(f)
        return cls(data.get("entries", {}))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"version": 1, "entries": self.entries}, f,
                      indent=2, sort_keys=True)
            f.write("\n")

    def filter(self, findings: list[Finding]):
        """Split findings into (new, accepted) honoring per-key counts,
        and report stale baseline keys that no longer fire."""
        budget = {k: v.get("count", 1) for k, v in self.entries.items()}
        new, accepted = [], []
        for f in findings:
            if budget.get(f.key, 0) > 0:
                budget[f.key] -= 1
                accepted.append(f)
            else:
                new.append(f)
        stale = sorted(k for k, n in budget.items()
                       if n >= self.entries.get(k, {}).get("count", 1)
                       and n > 0)
        return new, accepted, stale

    @classmethod
    def from_findings(cls, findings: list[Finding],
                      old: "Baseline | None" = None) -> "Baseline":
        entries: dict[str, dict] = {}
        for f in findings:
            e = entries.setdefault(f.key, {"count": 0})
            e["count"] += 1
        if old is not None:  # carry notes forward across rewrites
            for k, e in entries.items():
                note = old.entries.get(k, {}).get("note")
                if note:
                    e["note"] = note
        return cls(entries)
