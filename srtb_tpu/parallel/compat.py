"""jax version compatibility for the parallel layer.

``shard_map`` graduated from ``jax.experimental`` to the top level, and
its replication-check kwarg was renamed ``check_rep`` -> ``check_vma``
along the way.  The modules here are written against the new spelling;
this shim keeps them importable (and the 8-virtual-device CPU test mesh
runnable) on the older runtime the container ships.
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax: experimental module only
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(*args, **kwargs):
    if "check_vma" not in _PARAMS:
        # the old replication checker predates several primitives these
        # programs use (its rep-rule table returns None for them and
        # _check_rep crashes), so the fallback disables the check
        # outright — it is a static validation pass, not semantics
        kwargs.pop("check_vma", None)
        kwargs.setdefault("check_rep", False)
    return _shard_map(*args, **kwargs)
