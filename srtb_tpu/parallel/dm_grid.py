"""Multi-chip DM-trial search.

The reference dedisperses at a single configured DM (config.hpp:129-132
"TODO: DM search list for unknown source").  On TPU a DM search is the
natural scale-out axis: every trial applies a different chirp to the *same*
spectrum — pure data parallelism.  The spectrum is broadcast over ICI once
per segment; the chirp bank lives sharded over the ``dm`` mesh axis
(precomputed once, reused for every segment); each chip runs
chirp-multiply -> waterfall FFT -> spectral kurtosis -> detection on its
local trials and only tiny per-trial summaries leave the chips.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from srtb_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from srtb_tpu.ops import dedisperse as dd
from srtb_tpu.ops import detect as det
from srtb_tpu.ops import fft as F
from srtb_tpu.ops import rfi


class DMTrialResult(NamedTuple):
    dm_list: np.ndarray          # [n_dm] host
    zero_count: jnp.ndarray      # [n_dm]
    signal_counts: jnp.ndarray   # [n_dm, n_boxcars]
    snr_peaks: jnp.ndarray       # [n_dm, n_boxcars]
    time_series: jnp.ndarray     # [n_dm, T] mean-subtracted boxcar-1 series


def build_chirp_bank(dm_list, n_spectrum: int, f_min: float, df: float,
                     f_c: float, mesh: Mesh | None = None,
                     on_device: bool = False,
                     exact: bool = False) -> jnp.ndarray:
    """[n_dm, 2, n_spectrum] (re, im) float32 chirp bank, optionally sharded
    over the mesh's ``dm`` axis.  ``on_device=True`` computes each chirp
    with df64 two-float arithmetic directly on the owning chip (no
    host->device transfer of the bank, SURVEY.md §7 step 6).

    The on-device path defaults to the anchored-Taylor evaluation: k is
    linear in dm, so dm-independent anchor coefficients (validated once
    at the grid's max |dm|) are scaled by each trial's dm on device —
    one df64 multiply per anchor instead of ~3 df64 divisions per
    channel per trial.  ``exact=True`` (the Config.chirp_exact escape
    hatch) restores the per-element division chains."""
    dm_list = np.asarray(dm_list, dtype=np.float64)
    if on_device and mesh is not None:
        from srtb_tpu.ops import df64 as ds
        dm_hi, dm_lo = ds.from_float64(dm_list)  # keep full f64 precision
        dm_absmax = float(np.max(np.abs(dm_list))) if dm_list.size else 0.0
        consts = None if exact else dd.anchored_chirp_consts(
            n_spectrum, f_min, df, f_c, dm_absmax or 1.0, unit_dm=True)

        def gen(hi_block, lo_block):
            return jax.vmap(lambda h, l: dd.chirp_factor_df64_ri(
                n_spectrum, f_min, df, f_c, h, dm_lo=l,
                anchor_consts=consts))(hi_block, lo_block)
        fn = jax.jit(shard_map(gen, mesh=mesh, in_specs=(P("dm"), P("dm")),
                               out_specs=P("dm")))
        return fn(jnp.asarray(dm_hi), jnp.asarray(dm_lo))
    bank = np.stack([dd.chirp_factor_host_ri(n_spectrum, f_min, df, f_c, dm)
                     for dm in dm_list])
    if mesh is not None:
        sharding = NamedSharding(mesh, P("dm", None, None))
        return jax.device_put(bank, sharding)
    return jnp.asarray(bank)


def _trial_body(spec_ri, chirp_block, *, channel_count, time_reserved_count,
                snr_threshold, max_boxcar_length, sk_threshold,
                dewindow=None, len_cap=None):
    """Per-device: run all local DM trials on the replicated spectrum."""
    spec = jax.lax.complex(spec_ri[0], spec_ri[1])

    def one(chirp_ri):
        chirp = jax.lax.complex(chirp_ri[0], chirp_ri[1])
        s = dd.dedisperse(spec, chirp)
        wf = F.waterfall_c2c(s, channel_count, dewindow, len_cap=len_cap)
        wf = rfi.mitigate_rfi_spectral_kurtosis(wf, sk_threshold)
        r = det.detect(wf, time_reserved_count, snr_threshold,
                       max_boxcar_length)
        return r.zero_count, r.signal_counts, r.snr_peaks, r.time_series

    return jax.vmap(one)(chirp_block)


def dm_trial_search(spectrum_ri: jnp.ndarray, chirp_bank: jnp.ndarray,
                    dm_list, mesh: Mesh, *, channel_count: int,
                    time_reserved_count: int, snr_threshold: float,
                    max_boxcar_length: int, sk_threshold: float,
                    dewindow=None, len_cap: int | None = None
                    ) -> DMTrialResult:
    """Run the DM grid on one segment's (RFI-cleaned) spectrum.

    ``spectrum_ri`` [2, n_spectrum] (re, im) is replicated (XLA broadcasts
    it over ICI); ``chirp_bank`` [n_dm, 2, n_spectrum] is sharded over the
    ``dm`` axis.  ``dewindow``: pre-sanitized watfft-window divisors
    (window.dewindow_coefficients) when the spectrum was produced with a
    non-rectangle window — keeps this path consistent with the single-chip
    and DistSegmentProcessor paths.
    """
    body = partial(_trial_body, channel_count=channel_count,
                   time_reserved_count=time_reserved_count,
                   snr_threshold=snr_threshold,
                   max_boxcar_length=max_boxcar_length,
                   sk_threshold=sk_threshold,
                   dewindow=None if dewindow is None
                   else jnp.asarray(dewindow),
                   len_cap=len_cap)
    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(), P("dm", None, None)),
                   out_specs=P("dm"))
    zero_count, counts, peaks, ts = jax.jit(fn)(spectrum_ri, chirp_bank)
    return DMTrialResult(
        dm_list=np.asarray(dm_list),
        zero_count=zero_count,
        signal_counts=counts,
        snr_peaks=peaks,
        time_series=ts,
    )


def best_trial(result: DMTrialResult) -> tuple[int, float]:
    """(index, peak SNR) of the strongest trial across all boxcars."""
    peaks = np.asarray(result.snr_peaks)
    idx = int(np.argmax(peaks.max(axis=-1)))
    return idx, float(peaks[idx].max())
