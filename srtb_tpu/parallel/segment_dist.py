"""The full segment step sharded over a ("dm", "seq") mesh.

This is the multi-chip version of pipeline.segment.SegmentProcessor: one
``shard_map`` program covering unpack -> distributed R2C FFT -> RFI s1 ->
DM-trial chirp -> waterfall FFT -> RFI s2 -> detection, with

- ``seq``: the segment's samples/channels sharded over chips (sequence /
  context parallelism; all_to_all transposes inside the distributed FFT,
  psum reductions for the global statistics), and
- ``dm``:  independent DM trials replicating the sequence work (data
  parallelism; the cleaned spectrum is computed once per seq-shard and
  reused by every local trial).

Collective inventory per segment: 3 all_to_all (FFT transposes, seq) +
2 ppermute (Hermitian mirror, seq) + 3 psum over seq (mean power, zero
count, time series) + 3 psum over dm (the replicated trial summaries) —
all riding ICI.  Pinned by jaxpr inspection in
tests/test_parallel.py::test_dist_step_collective_inventory so a
silently-added collective fails CI.
"""

from __future__ import annotations

import os
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from srtb_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from srtb_tpu.config import Config
from srtb_tpu.io import formats
from srtb_tpu.ops import dedisperse as dd
from srtb_tpu.ops import detect as det
from srtb_tpu.ops import fft as F
from srtb_tpu.ops import rfi
from srtb_tpu.ops import unpack as U
from srtb_tpu.ops import window as W
from srtb_tpu.parallel import dist_fft as DF
from srtb_tpu.parallel import dm_grid


class DistSegmentResult(NamedTuple):
    zero_count: jnp.ndarray      # [n_dm, S]           (replicated)
    signal_counts: jnp.ndarray   # [n_dm, S, n_boxcars] (replicated)
    snr_peaks: jnp.ndarray       # [n_dm, S, n_boxcars] (replicated)
    time_series: jnp.ndarray     # [n_dm, S, T]         (dm-sharded)


def _put_sharded(host_array: np.ndarray, sharding: NamedSharding):
    """Host array -> sharded jax.Array; works in multi-controller runs
    (every process supplies its local shards by slicing the same host
    data), unlike a plain ``jax.device_put``."""
    return jax.make_array_from_callback(
        host_array.shape, sharding, lambda idx: host_array[idx])


class DistSegmentProcessor:
    """Builds the jitted multi-chip step for one baseband segment and a DM
    trial list."""

    def __init__(self, cfg: Config, mesh: Mesh, dm_list=None,
                 chirp_on_device: bool | None = None,
                 window_name: str = W.DEFAULT_WINDOW):
        self.cfg = cfg
        self.mesh = mesh
        self.fmt = formats.resolve(cfg.baseband_format_type)
        self.n_seq = mesh.shape["seq"]
        self.n_dm_devices = mesh.shape["dm"]
        if dm_list is None:
            dm_list = cfg.dm_list or [cfg.dm]
        if len(dm_list) % self.n_dm_devices:
            raise ValueError("len(dm_list) must divide by dm-axis size")
        self.dm_list = np.asarray(dm_list, dtype=np.float64)

        n = cfg.baseband_input_count
        self.n = n
        self.n_spectrum = n // 2
        self.channel_count = min(cfg.spectrum_channel_count, self.n_spectrum)
        self.watfft_len = self.n_spectrum // self.channel_count
        if self.channel_count % self.n_seq:
            raise ValueError("spectrum_channel_count must divide by seq axis")
        if self.n_spectrum % self.channel_count:
            # the single-chip path truncates the spectrum tail to a
            # whole number of waterfall rows; sharded, that truncation
            # would straddle a shard boundary (channel rows are
            # contiguous wlen-blocks of the seq-sharded spectrum), so
            # non-dividing channel counts must be rejected loudly here
            # rather than fail as a reshape deep inside shard_map
            raise ValueError(
                f"spectrum_channel_count {self.channel_count} must divide "
                f"the {self.n_spectrum}-channel spectrum for the "
                "distributed plan (power-of-two counts always do); the "
                "single-chip pipeline handles non-dividing counts by "
                "truncation")

        f_min, f_c, df = dd.spectrum_frequencies(cfg, self.n_spectrum)
        self.f_min, self.f_c, self.df = f_min, f_c, df
        # chirp either streams from an HBM bank [n_dm, 2, n_spec] sharded
        # (dm, -, seq), or is generated per trial inside the step with
        # df64 (no bank resident in HBM — the better choice when
        # n_trials * n_spec gets large; default follows use_emulated_fp64)
        if chirp_on_device is None:
            chirp_on_device = cfg.use_emulated_fp64
        self.chirp_on_device = chirp_on_device
        if chirp_on_device:
            from srtb_tpu.ops import df64 as ds
            dm_hi, dm_lo = ds.from_float64(self.dm_list)
            self.chirp_bank = _put_sharded(
                np.stack([dm_hi, dm_lo], axis=1),    # [n_dm, 2]
                NamedSharding(mesh, P("dm", None)))
            # dm-linear anchored-Taylor coefficients (validated at the
            # grid's max |dm|): turns the per-trial in-step chirp from
            # ~3 df64 divisions/channel into one anchored update —
            # None (exact path) when the bound can't be proven or the
            # Config.chirp_exact escape hatch is set
            dm_absmax = max((abs(float(d)) for d in self.dm_list),
                            default=0.0) or 1.0
            self.chirp_anchor_consts = None \
                if getattr(cfg, "chirp_exact", False) \
                else dd.anchored_chirp_consts(
                    self.n_spectrum, f_min, df, f_c, dm_absmax,
                    unit_dm=True)
        else:
            self.chirp_bank = _put_sharded(
                np.asarray(dm_grid.build_chirp_bank(
                    self.dm_list, self.n_spectrum, f_min, df, f_c)),
                NamedSharding(mesh, P("dm", None, "seq")))

        mask = rfi.rfi_ranges_to_mask(
            rfi.eval_rfi_ranges(cfg.mitigate_rfi_freq_list), self.n_spectrum,
            cfg.baseband_freq_low, cfg.baseband_bandwidth)
        if mask is None:
            mask = np.zeros(self.n_spectrum, dtype=bool)
        self.rfi_mask = _put_sharded(mask, NamedSharding(mesh, P("seq")))

        # unpack window, sharded over seq (each device windows its own
        # contiguous sample block); watfft-length de-window divided out of
        # the dynamic spectrum after the per-row backward C2C, same as the
        # single-chip path (ref: fft_pipe.hpp:346-359)
        win = W.window_coefficients(window_name, n)
        self.window = None if win is None \
            else _put_sharded(win, NamedSharding(mesh, P("seq")))
        watfft_dewindow = W.dewindow_coefficients(window_name,
                                                  self.watfft_len)

        self.norm_coeff = rfi.normalization_coefficient(
            self.n_spectrum, self.channel_count)
        self.nsamps_reserved = dd.nsamps_reserved(cfg)
        self.time_reserved_count = self.nsamps_reserved // self.channel_count

        # who runs the local FFT legs under the a2a transposes: the env
        # knob mirrors SRTB_STAGED_ROWS_IMPL; Pallas kernels need
        # interpret mode off-TPU (CPU-mesh CI)
        from srtb_tpu.parallel.dist_fft import resolve_rows_impl
        rows_impl = resolve_rows_impl(
            os.environ.get("SRTB_DIST_ROWS_IMPL", "xla"))
        body = partial(
            self._body,
            rows_impl=rows_impl,
            len_cap=cfg.fft_len_cap or None,
            variant=self.fmt.unpack_variant,
            nbits=cfg.baseband_input_bits,
            n=self.n, n_seq=self.n_seq, n_dm_dev=self.n_dm_devices,
            chirp_on_device=chirp_on_device,
            has_window=self.window is not None,
            watfft_dewindow=watfft_dewindow,
            f_min=f_min, f_c=f_c, df=df,
            chirp_anchor_consts=(self.chirp_anchor_consts
                                 if chirp_on_device else None),
            n_spectrum=self.n_spectrum,
            channel_count=self.channel_count,
            norm_coeff=self.norm_coeff,
            avg_threshold=cfg.mitigate_rfi_average_method_threshold,
            sk_threshold=cfg.mitigate_rfi_spectral_kurtosis_threshold,
            time_reserved_count=self.time_reserved_count,
            snr_threshold=cfg.signal_detect_signal_noise_threshold,
            max_boxcar_length=cfg.signal_detect_max_boxcar_length,
        )
        # trial summaries leave the step replicated (all_gather over dm in
        # the body) so every controller process can read them; the bulky
        # time series stays dm-sharded
        chirp_spec = P("dm", None) if chirp_on_device \
            else P("dm", None, "seq")
        in_specs = [P("seq"), chirp_spec, P("seq")]
        if self.window is not None:
            in_specs.append(P("seq"))
        self._step = jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=(P(), P(), P(), P("dm")),
            # whole-body vma opt-out for Pallas legs: accepted scope
            # (see parallel/dist_fft.py — interpret-mode kernels trace
            # under shard_map and trip the checker on unvarying kernel
            # consts); the same collectives run checker-ON in the
            # default-xla tests
            check_vma=rows_impl == "xla"))

    # ------------------------------------------------------------------

    @staticmethod
    def _body(raw_block, chirp_block, mask_block, *rest, variant, nbits, n,
              rows_impl, len_cap, n_seq, n_dm_dev, chirp_on_device,
              f_min, f_c, df,
              chirp_anchor_consts, n_spectrum, channel_count, norm_coeff,
              avg_threshold, sk_threshold, time_reserved_count,
              snr_threshold, max_boxcar_length,
              has_window=False, watfft_dewindow=None):
        from srtb_tpu.pipeline.segment import unpack_streams

        # ---- unpack (local; each device windows its own contiguous
        # sample block with its seq-shard of the global window) ----
        window_block = rest[0] if has_window else None
        xs = unpack_streams(raw_block, variant, nbits,
                            window_block)             # [S, n/n_seq]
        n_streams = xs.shape[0]

        # ---- distributed R2C FFT per stream, drop Nyquist ----
        m = n // 2
        log2m = m.bit_length() - 1
        n1 = 1 << (log2m // 2)
        n2 = m // n1
        specs = []
        for s in range(n_streams):  # S is tiny (1-4); loop, don't vmap
            # lane-dense even/odd pack — a [m, 2] reshape pads its minor
            # dim 2 -> 128 lanes on real TPU (64x HBM, ops/fft.py)
            z = F.pack_even_odd(xs[s])
            zf = DF._dist_fft_block(z, axis_name="seq", n1=n1, n2=n2,
                                    n_dev=n_seq, inverse=False,
                                    rows_impl=rows_impl, len_cap=len_cap)
            spec = DF._dist_rfft_post_block(zf, axis_name="seq", m=m,
                                            n_dev=n_seq)   # [m/n_seq]
            # RFI stage 1: global mean power via psum, zap + normalize
            power = jnp.real(spec) ** 2 + jnp.imag(spec) ** 2
            mean_power = jax.lax.psum(jnp.sum(power), "seq") / n_spectrum
            zap = power > avg_threshold * mean_power
            spec = jnp.where(zap, 0.0 + 0.0j, spec * norm_coeff)
            spec = jnp.where(mask_block, 0.0 + 0.0j, spec)
            specs.append(spec)
        spec_all = jnp.stack(specs)                    # [S, m/n_seq]

        # ---- per-DM-trial: chirp, waterfall, SK, detect ----
        wlen = n_spectrum // channel_count
        ch_local = channel_count // n_seq
        t = wlen - time_reserved_count \
            if wlen > time_reserved_count else wlen

        def one_trial(chirp_in):
            if chirp_on_device:
                # generate this trial's chirp block in-place with df64
                # (chirp_in is the (dm_hi, dm_lo) pair; no HBM bank)
                n_local = n_spectrum // n_seq
                seq_idx = jax.lax.axis_index("seq")
                chirp_ri = dd.chirp_factor_df64_ri(
                    n_local, f_min, df, f_c, chirp_in[0],
                    i0=seq_idx * n_local, dm_lo=chirp_in[1],
                    anchor_consts=chirp_anchor_consts)
            else:
                chirp_ri = chirp_in
            s = spec_all * jax.lax.complex(chirp_ri[0], chirp_ri[1])
            # local channels are complete contiguous sub-bands
            wf = s.reshape(n_streams, ch_local, wlen)
            wf = jnp.fft.ifft(wf, axis=-1, norm="forward")
            if watfft_dewindow is not None:
                wf = wf / watfft_dewindow
            wf = rfi.mitigate_rfi_spectral_kurtosis(wf, sk_threshold)
            # global zapped-channel count per stream
            zero_count = jax.lax.psum(
                jnp.sum((jnp.abs(wf[:, :, 0]) == 0).astype(jnp.int32),
                        axis=-1), "seq")               # [S]
            # global time series: sum power over all channels — local
            # pairwise tree (det.tree_sum_freq: deterministic O(log K)
            # rounding) + psum's own log2(n_seq)-level tree across shards
            ts = jax.lax.psum(
                det.tree_sum_freq(
                    jnp.real(wf[:, :, :t]) ** 2
                    + jnp.imag(wf[:, :, :t]) ** 2),
                "seq")                                  # [S, t]
            # tree-sum the time mean too (same discipline as the local
            # channel sum above; shared spelling with the single-chip
            # detect tail)
            ts = ts - det.tree_mean(ts)
            # boxcar cascade on the (replicated) time series
            lengths = det.boxcar_lengths(max_boxcar_length, t)
            acc = jnp.cumsum(ts, axis=-1)
            counts, peaks = [], []
            for b in lengths:
                series = ts if b == 1 \
                    else acc[..., b:] - acc[..., :-b]
                c, p = det.count_signal(series, snr_threshold)
                counts.append(c)
                peaks.append(p)
            return (zero_count, jnp.stack(counts, axis=-1),
                    jnp.stack(peaks, axis=-1), ts)

        zc, counts, peaks, ts = jax.vmap(one_trial)(chirp_block)

        # replicate the small per-trial summaries across the dm axis
        # (multi-host: every controller must be able to materialize them).
        # scatter-into-zeros + psum is replication the VMA checker can
        # prove invariant, unlike all_gather
        dm_idx = jax.lax.axis_index("dm")
        trials_local = chirp_block.shape[0]

        def replicate_trials(x):
            full = jnp.zeros((trials_local * n_dm_dev,) + x.shape[1:],
                             x.dtype)
            full = jax.lax.dynamic_update_slice_in_dim(
                full, x, dm_idx * trials_local, axis=0)
            return jax.lax.psum(full, "dm")

        return (replicate_trials(zc), replicate_trials(counts),
                replicate_trials(peaks), ts)

    # ------------------------------------------------------------------

    def process(self, raw) -> DistSegmentResult:
        raw = _put_sharded(np.asarray(raw, dtype=np.uint8),
                           NamedSharding(self.mesh, P("seq")))
        args = [raw, self.chirp_bank, self.rfi_mask]
        if self.window is not None:
            args.append(self.window)
        out = self._step(*args)
        return DistSegmentResult(*out)
