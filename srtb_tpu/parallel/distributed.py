"""Multi-host / multi-slice runtime (the DCN layer).

The reference is strictly single-process, single-device — its only
"network backend" is the ingest UDP stack (SURVEY.md §5.8).  The TPU
build adds the distributed communication backend the reference lacks:
``jax.distributed`` process groups (one process per host), XLA
collectives riding ICI within a slice and DCN across slices.

Topology policy: the ``dm`` (DM-trial) axis is embarrassingly parallel —
one spectrum broadcast, then zero inter-trial traffic — so it is the axis
laid across **DCN** slices, while the communication-heavy ``seq`` axis
(all_to_all / ppermute inside the distributed four-step FFT,
parallel/dist_fft.py) stays **inside** a slice on ICI.
``hybrid_dm_seq_mesh`` encodes exactly that placement.

Verified by a real two-process CPU ring in tests/test_distributed.py
(the CI analog of a DCN pod: cross-process Gloo collectives).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from srtb_tpu.utils.logging import log


def initialize(coordinator_address: str, num_processes: int,
               process_id: int, local_device_ids=None) -> None:
    """Join (or create) the multi-host process group.

    Call once per host before any jax computation, exactly like
    ``jax.distributed.initialize`` — this thin wrapper exists so the CLI
    (``--distributed_coordinator host:port --distributed_num_processes N
    --distributed_process_id i``) and library users share one entry point
    with logging.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    log.info(f"[distributed] process {process_id}/{num_processes} joined "
             f"via {coordinator_address}: {len(jax.devices())} global / "
             f"{len(jax.local_devices())} local devices")


def maybe_initialize_from_config(cfg) -> bool:
    """Initialize the process group if the config asks for it.  Returns
    True when running multi-process."""
    if cfg.distributed_num_processes <= 1:
        return False
    if not cfg.distributed_coordinator:
        raise ValueError("distributed_num_processes > 1 needs "
                         "distributed_coordinator host:port")
    initialize(cfg.distributed_coordinator, cfg.distributed_num_processes,
               cfg.distributed_process_id)
    return True


def _slice_index(device) -> int:
    # TPU devices carry slice_index on multi-slice (DCN) deployments; a
    # TPU without it is a single slice — ICI spans all its hosts, so the
    # whole pod is one cheap-communication domain.  On CPU/GPU process
    # groups the cross-process boundary is the DCN-cost domain, so there
    # "slice" = owning process.
    s = getattr(device, "slice_index", None)
    if s is not None:
        return s
    if device.platform == "tpu":
        return 0
    return device.process_index


def hybrid_dm_seq_mesh(n_seq: int | None = None, devices=None) -> Mesh:
    """("dm", "seq") mesh with dm laid across slices/hosts (DCN) and seq
    contiguous within a slice (ICI).

    ``n_seq`` defaults to the per-slice device count (pure DM parallelism
    across slices); it must divide the devices of every slice.
    """
    if devices is None:
        devices = jax.devices()
    slices: dict[int, list] = {}
    for d in devices:
        slices.setdefault(_slice_index(d), []).append(d)
    counts = {len(v) for v in slices.values()}
    if len(counts) != 1:
        raise ValueError(f"uneven slices: { {k: len(v) for k, v in slices.items()} }")
    per_slice = counts.pop()
    if n_seq is None:
        n_seq = per_slice
    if per_slice % n_seq:
        raise ValueError(f"n_seq={n_seq} does not divide the "
                         f"{per_slice} devices per slice")
    # rows = dm shards: (slice, intra-slice block); cols = seq shard.
    # Within a row all seq neighbours share a slice -> seq collectives
    # never cross DCN.
    rows = []
    for k in sorted(slices):
        devs = slices[k]
        for b in range(per_slice // n_seq):
            rows.append(devs[b * n_seq:(b + 1) * n_seq])
    mesh = Mesh(np.asarray(rows), ("dm", "seq"))
    log.debug(f"[distributed] hybrid mesh dm={len(rows)} seq={n_seq} "
              f"over {len(slices)} slice(s)")
    return mesh


def process_local_dm_indices(mesh: Mesh, n_trials: int) -> list[int]:
    """Which DM-trial indices have a shard on this process — lets each
    host report/write only its own trials' results.

    Layout matches the trial sharding (NamedSharding ``P("dm", ...)`` of
    the chirp bank / time series): contiguous blocks of
    ``n_trials // n_dm`` trials per dm row.
    """
    n_dm = mesh.devices.shape[0]
    if n_trials % n_dm:
        raise ValueError(f"n_trials={n_trials} must divide by dm={n_dm}")
    per_row = n_trials // n_dm
    local = set()
    me = jax.process_index()
    for i, row in enumerate(mesh.devices):
        if any(d.process_index == me for d in row):
            local.update(range(i * per_row, (i + 1) * per_row))
    return sorted(local)
