"""Distributed layer (no reference equivalent — the reference is
single-device, single-process; SURVEY.md §2.9/§5.8).

- ``mesh``         — device mesh construction over ICI/DCN
- ``dist_fft``     — sequence-sharded large FFT (four-step + all_to_all)
- ``dm_grid``      — DM-trial data parallelism: chirp bank sharded over
                     chips, spectrum broadcast once over ICI
- ``segment_dist`` — the full segment step sharded over a ("dm", "seq") mesh
"""

from srtb_tpu.parallel import mesh, dist_fft  # noqa: F401
