"""Device-mesh construction.

The TPU scaling axes for this workload:
- ``dm``  — embarrassingly-parallel DM trials (data parallelism over chips;
  the spectrum is broadcast over ICI once per segment, each chip applies
  its own chirp);
- ``seq`` — sequence (frequency/sample) sharding of one huge segment whose
  FFT exceeds a single chip (sequence/context parallelism analog).

Multi-host meshes come from ``jax.devices()`` spanning hosts; the same code
runs under ``jax.distributed.initialize`` with DCN-connected slices.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: F401


def make_mesh(n_dm: int = 1, n_seq: int = 1,
              devices=None) -> Mesh:
    """Build a ("dm", "seq") mesh.  n_dm * n_seq must divide the available
    device count; by default all devices go to the dm axis."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n_dm * n_seq == 1:
        n_dm = n
    if n % (n_dm * n_seq):
        raise ValueError(
            f"{n} devices not divisible into dm={n_dm} x seq={n_seq}")
    use = np.asarray(devices[: n_dm * n_seq]).reshape(n_dm, n_seq)
    return Mesh(use, ("dm", "seq"))


def seq_mesh(n_seq: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if n_seq is None:
        n_seq = len(devices)
    return Mesh(np.asarray(devices[:n_seq]), ("seq",))


def dm_mesh(n_dm: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if n_dm is None:
        n_dm = len(devices)
    return Mesh(np.asarray(devices[:n_dm]), ("dm",))
