"""Sequence-sharded large 1-D FFT over a device mesh.

The reference's hardest resource constraint is the single 2^30-point R2C
FFT (SURVEY.md §7 hard part #1); one chip's HBM bounds the segment size.
This module removes that bound: the four-step factorization
(ops.fft.four_step_fft) distributed over the ``seq`` mesh axis with
``shard_map`` + ``all_to_all`` transposes — the TPU-native analog of
sequence/context parallelism.  Layout (n = n1 * n2, D devices):

  x flat, sharded in j1-blocks        [n1/D, n2]   per device
  all_to_all transpose             -> [n2/D, n1]
  local FFT (length n1, columns of A) + twiddle exp(-2*pi*i*k1*j2/n)
  all_to_all transpose back        -> [n1/D, n2]   rows now B[k1, j2]
  local FFT (length n2)            -> C[k1, k2]
  all_to_all transpose             -> natural order X[k2*n1+k1]

The R2C variant packs 2m reals as m complex, runs the distributed C2C,
and applies the Hermitian post-process (ref: fft/fft_1d_r2c_post_process.
hpp:33-82) with the conjugate-mirrored spectrum materialized via a global
flip (local flip + ppermute device reversal + edge-roll).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from srtb_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from srtb_tpu.ops.fft import _fft_minor, _phase_exp, pack_even_odd


def _local_transpose_a2a(x_block, axis_name, n_dev):
    """Global [R, C] -> [C, R] transpose of a row-sharded matrix:
    split local rows' columns into n_dev chunks, all_to_all, reassemble."""
    r_loc, c = x_block.shape
    c_loc = c // n_dev
    # [r_loc, n_dev, c_loc] -> a2a over chunk axis -> [n_dev, r_loc, c_loc]
    t = x_block.reshape(r_loc, n_dev, c_loc)
    t = jax.lax.all_to_all(t, axis_name, split_axis=1, concat_axis=0,
                           tiled=False)
    # t: [n_dev, r_loc, c_loc] where first axis = source device (row block)
    # global columns of this device: [c_loc rows] x [R = n_dev*r_loc]
    t = jnp.transpose(t, (2, 0, 1)).reshape(c_loc, n_dev * r_loc)
    return t


def _dist_fft_block(x_block, *, axis_name, n1, n2, n_dev, inverse,
                    rows_impl="xla", len_cap=None):
    """shard_map body: x_block [n_local] = this device's j1-block rows,
    viewed as [n1/D, n2].  ``rows_impl`` selects who runs the local leg
    FFTs (ops.fft._fft_minor dispatch): "xla", or "pallas"/
    "pallas_interpret" for the VMEM row kernel — the same per-chip
    kernels the single-chip plans use, now under the a2a transposes.
    ``len_cap`` threads ops.fft._fft_minor's XLA length cap through the
    in-shard legs (tiny-shape dryruns force the four-step recursion a
    production 2^30 shard takes by lowering it)."""
    a = x_block.reshape(n1 // n_dev, n2)

    # transpose so columns (j1 axis) become local rows
    at = _local_transpose_a2a(a, axis_name, n_dev)          # [n2/D, n1]
    bt = _fft_minor(at, inverse, rows_impl, len_cap)
    # twiddle: row j2 (global), column k1: exp(sign*2*pi*i*k1*j2/n).
    # The residue k1*j2 < n1*n2 = n fits int32 exactly for n <= 2^30, and
    # _phase_exp splits it hi/lo so the f32 phase stays exact at large n
    # (same precision discipline as ops/fft.py:_twiddle; a plain f32
    # ratio product here diverges for shards >= 2^24).
    idx = jax.lax.axis_index(axis_name)
    j2 = (idx * (n2 // n_dev)
          + jax.lax.iota(jnp.int32, n2 // n_dev)).astype(jnp.int32)
    k1 = jax.lax.iota(jnp.int32, n1)
    r = j2[:, None] * k1[None, :]
    tw = _phase_exp(r, n1 * n2, 1.0 if inverse else -1.0)
    bt = bt * tw

    # transpose back: rows k1 local again
    b = _local_transpose_a2a(bt, axis_name, n_dev)          # [n1/D, n2]
    c = _fft_minor(b, inverse, rows_impl, len_cap)
    # natural order: X[k2*n1 + k1] = C[k1, k2] -> global transpose
    ct = _local_transpose_a2a(c, axis_name, n_dev)          # [n2/D, n1]
    return ct.reshape(-1)


def resolve_rows_impl(impl: str) -> str:
    """Validate + resolve a distributed leg implementation: typos must
    fail loudly (the segment.py:_resolve_rows_impl rule), and "pallas"
    downgrades to interpret mode off-TPU (utils.platform.on_accelerator
    is the single home of the backend set)."""
    if impl not in ("xla", "pallas", "pallas_interpret"):
        raise ValueError(
            f"unknown SRTB_DIST_ROWS_IMPL / rows_impl {impl!r}")
    from srtb_tpu.utils.platform import on_accelerator
    if impl == "pallas" and not on_accelerator():
        return "pallas_interpret"
    return impl


def dist_fft(x, mesh: Mesh, axis_name: str = "seq",
             inverse: bool = False, rows_impl: str = "xla",
             len_cap: int | None = None):
    """Distributed unnormalized C2C FFT of a 1-D power-of-two array sharded
    (or shardable) over ``axis_name``.  Returns the spectrum in natural
    order with the same sharding."""
    n = x.shape[-1]
    n_dev = mesh.shape[axis_name]
    rows_impl = resolve_rows_impl(rows_impl)
    if n > 1 << 30:
        # the twiddle residue j2*k1 is int32; products stay < n, so 2^30
        # is a safe static ceiling (2^31 would need int64 residues)
        raise ValueError(f"n={n} exceeds the int32 twiddle-residue ceiling "
                         "of 2^30; split the segment or use int64 residues")
    log2n = n.bit_length() - 1
    n1 = 1 << (log2n // 2)
    n2 = n // n1
    if n1 % n_dev or n2 % n_dev:
        raise ValueError(f"n1={n1}, n2={n2} must divide by {n_dev} devices")
    # With Pallas legs the vma checker is off for the WHOLE body — an
    # accepted scope, not an oversight: jax 0.9 can annotate a
    # pallas_call's outputs (ShapeDtypeStruct(vma=...)), but in
    # interpret mode (all CPU CI) the kernel body is traced under
    # shard_map, where unvarying kernel consts meet varying refs and
    # the checker itself rejects the mul ("requires varying manual
    # axes to match").  Every collective here is identical across
    # rows_impls and covered with the checker ON by the default-xla
    # tests (tests/test_dist_fft.py).
    fn = shard_map(
        partial(_dist_fft_block, axis_name=axis_name, n1=n1, n2=n2,
                n_dev=n_dev, inverse=inverse, rows_impl=rows_impl,
                len_cap=len_cap),
        mesh=mesh, in_specs=P(axis_name), out_specs=P(axis_name),
        check_vma=rows_impl == "xla")
    return fn(x.astype(jnp.complex64))


# ----------------------------------------------------------------
# distributed R2C with Hermitian post-process
# ----------------------------------------------------------------

def _global_conj_mirror(f_block, axis_name, n_dev):
    """Given F sharded in blocks, return G with G[k] = conj(F[(m-k) % m]),
    same sharding.  Global flip = local flip + device-order reversal; the
    ``% m`` index shift is a global roll right by one element."""
    rev = jnp.flip(f_block, axis=-1)
    perm = [(d, n_dev - 1 - d) for d in range(n_dev)]
    rev = jax.lax.ppermute(rev, axis_name, perm)   # global flip(F)
    # roll right by 1: each device receives the last element of the
    # previous device's block (cyclic)
    shift_perm = [(d, (d + 1) % n_dev) for d in range(n_dev)]
    prev_last = jax.lax.ppermute(rev[..., -1:], axis_name, shift_perm)
    rolled = jnp.concatenate([prev_last, rev[..., :-1]], axis=-1)
    return jnp.conj(rolled)


def _dist_rfft_post_block(zf_block, *, axis_name, m, n_dev):
    """Hermitian reconstruction on the m-point C2C spectrum of packed
    reals; emits m bins (Nyquist dropped, matching segment_rfft)."""
    f_k = zf_block
    f_mk = _global_conj_mirror(zf_block, axis_name, n_dev)
    even = 0.5 * (f_k + f_mk)
    odd = -0.5j * (f_k - f_mk)
    idx = jax.lax.axis_index(axis_name)
    k = (idx * (m // n_dev)
         + jax.lax.iota(jnp.int32, m // n_dev)).astype(jnp.int32)
    # w[k] = exp(-i*pi*k/m) = exp(-2*pi*i*k/(2m)) via the exact hi/lo
    # phase split (a raw f32 k/m loses bits of phase for m >= 2^24).
    w = _phase_exp(k, 2 * m, -1.0)
    return even + w * odd


def dist_rfft_drop_nyquist(x, mesh: Mesh, axis_name: str = "seq",
                           rows_impl: str = "xla",
                           len_cap: int | None = None):
    """Distributed R2C of 2m reals -> m complex bins (drop-Nyquist
    convention of the segment FFT, ref: fft_pipe.hpp:75-77)."""
    n = x.shape[-1]
    m = n // 2
    n_dev = mesh.shape[axis_name]

    def pack(blk):
        # lane-dense even/odd pack — a [m, 2] reshape pads its minor dim
        # 2 -> 128 lanes on real TPU (64x HBM), see ops/fft.pack_even_odd.
        # Known future work: for sub-byte input the single-chip path now
        # skips sample order entirely (ops/fft.rfft_subbyte blocked
        # planes); the distributed analog would hold each shard as field
        # planes and absorb the cross-plane butterfly after dist_fft,
        # but that changes the output sharding layout (k = k2*M + k1
        # interleaves device blocks) and with it every downstream
        # index computation in segment_dist — deferred until real
        # multi-chip hardware is available to measure on.
        return pack_even_odd(blk)

    z = shard_map(pack, mesh=mesh, in_specs=P(axis_name),
                  out_specs=P(axis_name))(x.astype(jnp.float32))
    zf = dist_fft(z, mesh, axis_name, rows_impl=rows_impl,
                  len_cap=len_cap)
    post = shard_map(
        partial(_dist_rfft_post_block, axis_name=axis_name, m=m,
                n_dev=n_dev),
        mesh=mesh, in_specs=P(axis_name), out_specs=P(axis_name))
    return post(zf)
