// Native asynchronous file-writer pool.
//
// TPU-native equivalent of the reference's candidate-writer thread pools
// (ref: pipeline/write_signal_pipe.hpp:159-280 — one boost::asio::thread_pool
// for baseband .bin writes with fdatasync, one for .npy/.tim spectrum
// writes).  Here a single pool with a configurable thread count accepts
// (path, bytes, fsync) jobs; submission copies the payload so the caller's
// buffer (a numpy array on the Python side) can be reused immediately,
// matching the reference's shared_ptr-owned work semantics.
//
// Exposed as a C ABI for Python ctypes (no pybind11 in this image).
//
// Build: make -C srtb_tpu/native  (produces libsrtb_writer.so)

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <new>
#include <string>
#include <thread>
#include <vector>

namespace {

struct WriteJob {
  std::string path;
  std::vector<uint8_t> data;
  bool fsync = false;
  bool append = false;
};

struct WriterPool {
  std::vector<std::thread> threads;
  std::deque<WriteJob> jobs;
  std::mutex mu;
  std::condition_variable cv_push;   // signalled when a job arrives / stop
  std::condition_variable cv_drain;  // signalled when a job completes
  bool stopping = false;
  size_t in_flight = 0;        // queued + running
  size_t queued_bytes = 0;     // payload bytes queued + being written
  size_t max_queued_bytes = 0; // submit blocks above this (0 = unbounded)
  size_t active_submitters = 0;  // threads inside srtb_writer_submit

  // statistics (ref keeps per-write logs; we expose counters)
  std::atomic<uint64_t> jobs_done{0};
  std::atomic<uint64_t> bytes_written{0};
  std::atomic<uint64_t> errors{0};

  void worker() {
    for (;;) {
      WriteJob job;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_push.wait(lk, [&] { return stopping || !jobs.empty(); });
        if (jobs.empty()) return;  // stopping and drained
        job = std::move(jobs.front());
        jobs.pop_front();
      }
      if (!write_one(job)) errors.fetch_add(1);
      jobs_done.fetch_add(1);
      {
        std::lock_guard<std::mutex> lk(mu);
        in_flight--;
        queued_bytes -= job.data.size();
      }
      cv_drain.notify_all();
    }
  }

  bool write_one(const WriteJob& job) {
    // crash consistency (non-append jobs): write <path>.srtb_tmp and
    // atomically rename into place on success, so a reader — or a
    // restarted run's orphan sweep (io/writers.recover_orphan_temps)
    // — never sees a torn candidate file.  Appends are in-place by
    // nature.  Mirrors the Python fallback (io/native_writer.py).
    const std::string path =
        job.append ? job.path : job.path + ".srtb_tmp";
    int flags = O_WRONLY | O_CREAT | (job.append ? O_APPEND : O_TRUNC);
    int fd = open(path.c_str(), flags, 0644);
    if (fd < 0) return false;
    const uint8_t* p = job.data.data();
    size_t left = job.data.size();
    bool ok = true;
    while (left > 0) {
      ssize_t n = write(fd, p, left);
      if (n < 0) {
        if (errno == EINTR) continue;
        ok = false;
        break;
      }
      p += n;
      left -= (size_t)n;
    }
    // the reference fdatasync()s candidate baseband so a captured transient
    // survives a crash of the host (ref: write_signal_pipe.hpp:187-197)
    if (ok && job.fsync && fdatasync(fd) != 0) ok = false;
    if (close(fd) != 0) ok = false;
    if (!job.append) {
      if (ok) {
        ok = std::rename(path.c_str(), job.path.c_str()) == 0;
      }
      // failed write OR failed rename: drop the temp, matching the
      // Python atomic_write contract — a live-run failure must not
      // masquerade as an interrupted-run orphan at the next startup
      if (!ok) unlink(path.c_str());
    }
    if (ok) bytes_written.fetch_add(job.data.size());
    return ok;
  }
};

}  // namespace

extern "C" {

// `max_queued_bytes` bounds the RAM held by queued payload copies; when
// the bound would be exceeded, submit blocks until writers catch up — the
// backpressure the reference gets for free from its bounded work queues
// (work.hpp:35-41).  0 = unbounded.
WriterPool* srtb_writer_create(int32_t n_threads,
                               uint64_t max_queued_bytes) {
  if (n_threads < 1) n_threads = 1;
  WriterPool* pool = new (std::nothrow) WriterPool;
  if (!pool) return nullptr;
  pool->max_queued_bytes = (size_t)max_queued_bytes;
  pool->threads.reserve((size_t)n_threads);
  for (int32_t i = 0; i < n_threads; i++)
    pool->threads.emplace_back([pool] { pool->worker(); });
  return pool;
}

// Enqueue one write; copies `data` so the caller may reuse its buffer.
// Returns 0 on success, -1 if the pool is stopping or allocation failed.
int32_t srtb_writer_submit(WriterPool* pool, const char* path,
                           const uint8_t* data, uint64_t nbytes,
                           int32_t fsync_flag, int32_t append_flag) {
  if (!pool || !path) return -1;
  WriteJob job;
  job.path = path;
  job.fsync = fsync_flag != 0;
  job.append = append_flag != 0;
  try {
    job.data.assign(data, data + nbytes);
  } catch (...) {
    return -1;
  }
  {
    std::unique_lock<std::mutex> lk(pool->mu);
    if (pool->stopping) return -1;
    pool->active_submitters++;
    int32_t rc = 0;
    if (pool->max_queued_bytes > 0) {
      // block until the job fits (oversized jobs wait for an empty queue)
      pool->cv_drain.wait(lk, [&] {
        return pool->stopping ||
               pool->queued_bytes + job.data.size() <=
                   pool->max_queued_bytes ||
               pool->queued_bytes == 0;
      });
      if (pool->stopping) rc = -1;
    }
    if (rc == 0) {
      pool->queued_bytes += job.data.size();
      pool->jobs.push_back(std::move(job));
      pool->in_flight++;
      pool->cv_push.notify_one();
    }
    pool->active_submitters--;
    // notify while still holding mu: a destroyer waiting for
    // active_submitters == 0 can then only delete the pool after our
    // unique_lock releases — no pool access happens after the unlock,
    // so submit-vs-destroy cannot use freed memory
    pool->cv_drain.notify_all();
    return rc;
  }
}

// Block until every submitted job has been written (or failed).
void srtb_writer_drain(WriterPool* pool) {
  std::unique_lock<std::mutex> lk(pool->mu);
  pool->cv_drain.wait(lk, [&] { return pool->in_flight == 0; });
}

uint64_t srtb_writer_jobs_done(WriterPool* pool) {
  return pool->jobs_done.load();
}
uint64_t srtb_writer_bytes_written(WriterPool* pool) {
  return pool->bytes_written.load();
}
uint64_t srtb_writer_errors(WriterPool* pool) { return pool->errors.load(); }

// Drain, stop the workers and free the pool.
//
// A submitter blocked in the backpressure wait when destroy begins is
// woken via cv_drain, returns -1 on the stopping flag, and destroy waits
// for it to leave submit() (active_submitters == 0) before freeing the
// pool — so submit-vs-destroy is safe for already-entered calls.  Calls
// *entered after* destroy returns are still use-after-free (the pointer
// is dead); the Python wrapper's close() serializes that.
void srtb_writer_destroy(WriterPool* pool) {
  if (!pool) return;
  {
    std::unique_lock<std::mutex> lk(pool->mu);
    pool->stopping = true;
    pool->cv_push.notify_all();
    pool->cv_drain.notify_all();  // wake backpressure waiters in submit
    pool->cv_drain.wait(lk, [&] { return pool->active_submitters == 0; });
  }
  pool->cv_push.notify_all();  // workers may have missed the first notify
  for (auto& t : pool->threads) t.join();
  delete pool;
}

}  // extern "C"
