// AF_PACKET TPACKET_V3 RX-ring packet provider.
//
// The reference ships a packet_mmap v3 provider but marks it "not
// correctly implemented" and keeps it out of the dispatch table
// (ref: io/udp/packet_mmap_v3_provider.hpp:61-65, 3rdparty/
// packet_mmap_v3.c).  This is a working equivalent: the kernel DMA-fills
// a mmap'd ring of blocks and hands each block to userspace with one
// wakeup, so packet reception costs no per-packet (and almost no
// per-batch) syscalls — the next step up from recvmmsg
// (udp_receiver.cpp) for line-rate capture.
//
// Same block-assembly contract as the recvmmsg receiver: payload of the
// packet with counter c lands at offset (c - begin) * payload_size of
// the caller's buffer, reordering within a block is tolerated, lost
// packets stay zero-filled and are accounted.  Kernel-side filtering is
// L2: the socket sees every IPv4 packet on the interface, and frames
// are filtered here for UDP + destination port + exact datagram size.
// Requires CAP_NET_RAW (the reference's provider has the same
// requirement; deployments that cannot grant it use the recvmmsg path).
//
// Exposed as a C ABI for Python ctypes (no pybind11 in this image).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <vector>

#include <linux/filter.h>
#include <linux/if_packet.h>
#include <net/ethernet.h>
#include <net/if.h>
#include <netinet/in.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <unistd.h>

#include <new>

namespace {

// counter parsers — must match udp_receiver.cpp's CounterKind values
enum CounterKind : int32_t {
  kCounterLe64 = 0,
  kCounterVdif67 = 1,
};

inline uint64_t parse_counter(const uint8_t* pkt, int32_t kind) {
  uint64_t c = 0;
  if (kind == kCounterVdif67) {
    uint32_t w6, w7;
    std::memcpy(&w6, pkt + 6 * 4, 4);
    std::memcpy(&w7, pkt + 7 * 4, 4);
    c = (uint64_t)w6 | ((uint64_t)w7 << 32);
  } else {
    std::memcpy(&c, pkt, 8);
  }
  return c;
}

struct PktRing {
  int fd = -1;
  uint8_t* map = nullptr;
  size_t map_len = 0;
  uint32_t block_size = 0;
  uint32_t block_count = 0;

  uint16_t port_be = 0;        // filter: UDP destination port (network order)
  size_t packet_size = 0;      // expected datagram size (header + payload)
  size_t header_size = 0;
  int32_t counter_kind = kCounterLe64;

  // iteration state (persists across receive_block calls so an
  // overflowing packet's ring block is resumed, not dropped)
  uint32_t cur_block = 0;
  uint32_t pkt_index = 0;      // next frame index within cur_block
  uint32_t num_pkts = 0;       // frames in cur_block (0 = block not open)
  uint8_t* frame = nullptr;    // next frame pointer
  std::vector<uint8_t> slot_filled;  // per-block fill map (reused)

  uint64_t next_counter = 0;
  bool have_counter = false;

  // datagram that overflowed the previous block (it belongs to a later
  // one): consumed first by the next receive_block call.  Copied out of
  // the ring so its ring block can be released to the kernel.
  uint8_t* pending = nullptr;   // packet_size bytes when pending_set
  bool pending_set = false;

  uint64_t total_packets = 0;
  uint64_t lost_packets = 0;

  size_t payload_size() const { return packet_size - header_size; }

  tpacket_block_desc* block(uint32_t i) const {
    return (tpacket_block_desc*)(map + (size_t)i * block_size);
  }
};

// The block_status word is the kernel<->userspace handoff: it needs
// acquire on the TP_STATUS_USER read (frame loads must not be satisfied
// from pre-fill memory) and release on the TP_STATUS_KERNEL store (all
// payload loads must complete before the kernel may DMA-refill the
// block) — plain accesses reorder on weakly-ordered CPUs and silently
// corrupt payload under load.
inline uint32_t status_acquire(tpacket_block_desc* bd) {
  return __atomic_load_n(&bd->hdr.bh1.block_status, __ATOMIC_ACQUIRE);
}

inline void release_to_kernel(tpacket_block_desc* bd) {
  __atomic_store_n(&bd->hdr.bh1.block_status, TP_STATUS_KERNEL,
                   __ATOMIC_RELEASE);
}

// Advance to the next available frame, opening/releasing ring blocks and
// poll()ing as needed.  Returns the UDP payload pointer of a frame that
// passes the port/size filter, or nullptr on poll error.
const uint8_t* next_packet(PktRing* r) {
  for (;;) {
    if (r->num_pkts == 0) {  // open the current block (or wait for it)
      tpacket_block_desc* bd = r->block(r->cur_block);
      while (!(status_acquire(bd) & TP_STATUS_USER)) {
        pollfd pfd{r->fd, POLLIN | POLLERR, 0};
        if (poll(&pfd, 1, -1) < 0 && errno != EINTR) return nullptr;
      }
      r->num_pkts = bd->hdr.bh1.num_pkts;
      r->pkt_index = 0;
      r->frame = (uint8_t*)bd + bd->hdr.bh1.offset_to_first_pkt;
      if (r->num_pkts == 0) {  // timed-out empty block: hand back, next
        release_to_kernel(bd);
        r->cur_block = (r->cur_block + 1) % r->block_count;
        continue;
      }
    }
    while (r->pkt_index < r->num_pkts) {
      tpacket3_hdr* tp = (tpacket3_hdr*)r->frame;
      const uint8_t* cur = r->frame;
      r->pkt_index++;
      r->frame = tp->tp_next_offset
                     ? r->frame + tp->tp_next_offset
                     : r->frame;  // last frame: index check ends the loop
      // loopback delivers each datagram twice (outgoing + incoming);
      // keep one copy
      auto* sll = (const sockaddr_ll*)(cur + sizeof(tpacket3_hdr));
      if (sll->sll_pkttype == PACKET_OUTGOING) continue;
      const uint8_t* ip = cur + tp->tp_net;
      if ((ip[0] >> 4) != 4) continue;                   // IPv4 only
      const size_t ihl = (size_t)(ip[0] & 0x0F) * 4;
      if (ip[9] != IPPROTO_UDP) continue;
      const uint16_t frag = (uint16_t)((ip[6] << 8) | ip[7]) & 0x3FFF;
      if (frag != 0) continue;                           // no fragments
      const uint8_t* udp = ip + ihl;
      uint16_t dport;
      std::memcpy(&dport, udp + 2, 2);
      if (dport != r->port_be) continue;
      uint16_t ulen_be;
      std::memcpy(&ulen_be, udp + 4, 2);
      const size_t dgram = (size_t)ntohs(ulen_be) - 8;
      if (dgram != r->packet_size) continue;             // runt/foreign
      return udp + 8;
    }
    // block fully consumed: release to the kernel, move on.  NOTE: a
    // packet returned from this block may still be read by the caller
    // (memcpy into the assembly buffer) strictly before the next call
    // re-enters here, and the overflow path copies its packet out
    // before release — both happen-before this store.
    release_to_kernel(r->block(r->cur_block));
    r->cur_block = (r->cur_block + 1) % r->block_count;
    r->num_pkts = 0;
  }
}

}  // namespace

extern "C" {

// Create the ring on `ifname` (e.g. "lo", "eth0"), filtering for UDP
// datagrams of exactly `packet_size` bytes to `port`.  block_size must
// be a multiple of the page size; block_count blocks are mapped.
// Returns nullptr on failure (typically missing CAP_NET_RAW).
PktRing* srtb_pkt_ring_create(const char* ifname, uint16_t port,
                              uint64_t packet_size, uint64_t header_size,
                              int32_t counter_kind, uint32_t block_size,
                              uint32_t block_count) {
  PktRing* r = new (std::nothrow) PktRing;
  if (!r) return nullptr;
  r->packet_size = packet_size;
  r->header_size = header_size;
  r->counter_kind = counter_kind;
  r->port_be = htons(port);
  r->block_size = block_size;
  r->block_count = block_count;
  r->pending = new (std::nothrow) uint8_t[packet_size];
  if (!r->pending) { delete r; return nullptr; }

  r->fd = socket(AF_PACKET, SOCK_RAW, htons(ETH_P_IP));
  if (r->fd < 0) { delete[] r->pending; delete r; return nullptr; }

  {
    // Kernel-level classic BPF: "ipv4 && udp && !frag && dst port P &&
    // udp length == packet_size + 8".  Without it every packet on the
    // interface is copied into the 64 MB ring and filtered in
    // userspace — foreign bursts would evict wanted baseband blocks.
    // Offsets assume an Ethernet-style link header (true for loopback
    // and standard NICs).
    const uint16_t dport = port;
    const uint16_t ulen = (uint16_t)(packet_size + 8);
    sock_filter code[] = {
        {BPF_LD | BPF_H | BPF_ABS, 0, 0, 12},            //  0: ethertype
        {BPF_JMP | BPF_JEQ | BPF_K, 0, 10, 0x0800},      //  1: ipv4?
        {BPF_LD | BPF_B | BPF_ABS, 0, 0, 23},            //  2: ip proto
        {BPF_JMP | BPF_JEQ | BPF_K, 0, 8, IPPROTO_UDP},  //  3: udp?
        {BPF_LD | BPF_H | BPF_ABS, 0, 0, 20},            //  4: frag field
        {BPF_JMP | BPF_JSET | BPF_K, 6, 0, 0x1FFF},      //  5: fragment?
        {BPF_LDX | BPF_B | BPF_MSH, 0, 0, 14},           //  6: x = ihl
        {BPF_LD | BPF_H | BPF_IND, 0, 0, 16},            //  7: dst port
        {BPF_JMP | BPF_JEQ | BPF_K, 0, 3, dport},        //  8
        {BPF_LD | BPF_H | BPF_IND, 0, 0, 18},            //  9: udp length
        {BPF_JMP | BPF_JEQ | BPF_K, 0, 1, ulen},         // 10
        {BPF_RET | BPF_K, 0, 0, 0xFFFFFFFF},             // 11: accept
        {BPF_RET | BPF_K, 0, 0, 0},                      // 12: drop
    };
    sock_fprog prog{sizeof(code) / sizeof(code[0]), code};
    if (setsockopt(r->fd, SOL_SOCKET, SO_ATTACH_FILTER, &prog,
                   sizeof(prog)) < 0)
      goto fail;
  }

  {
    int v = TPACKET_V3;
    if (setsockopt(r->fd, SOL_PACKET, PACKET_VERSION, &v, sizeof(v)) < 0)
      goto fail;
  }

  {
    tpacket_req3 req{};
    req.tp_block_size = block_size;
    req.tp_block_nr = block_count;
    // frame size is a v3 sizing hint; large enough for jumbo payloads
    req.tp_frame_size = 16384;
    req.tp_frame_nr = (uint32_t)(((uint64_t)block_size * block_count) /
                                 req.tp_frame_size);
    req.tp_retire_blk_tov = 60;  // ms: deliver partial blocks promptly
    if (setsockopt(r->fd, SOL_PACKET, PACKET_RX_RING, &req, sizeof(req)) < 0)
      goto fail;
  }

  r->map_len = (size_t)block_size * block_count;
  r->map = (uint8_t*)mmap(nullptr, r->map_len, PROT_READ | PROT_WRITE,
                          MAP_SHARED | MAP_LOCKED, r->fd, 0);
  if (r->map == MAP_FAILED) {
    // MAP_LOCKED can exceed RLIMIT_MEMLOCK; retry unlocked
    r->map = (uint8_t*)mmap(nullptr, r->map_len, PROT_READ | PROT_WRITE,
                            MAP_SHARED, r->fd, 0);
    if (r->map == MAP_FAILED) goto fail;
  }

  {
    sockaddr_ll sll{};
    sll.sll_family = AF_PACKET;
    sll.sll_protocol = htons(ETH_P_IP);
    sll.sll_ifindex = (int)if_nametoindex(ifname && ifname[0] ? ifname
                                                              : "lo");
    if (sll.sll_ifindex == 0 ||
        bind(r->fd, (sockaddr*)&sll, sizeof(sll)) < 0)
      goto fail;
  }
  return r;

fail:
  if (r->map && r->map != MAP_FAILED) munmap(r->map, r->map_len);
  if (r->fd >= 0) close(r->fd);
  delete[] r->pending;
  delete r;
  return nullptr;
}

// Same contract as srtb_udp_rx_receive_block (udp_receiver.cpp).
int32_t srtb_pkt_ring_receive_block(PktRing* r, uint8_t* out,
                                    uint64_t out_bytes,
                                    uint64_t* first_counter_out,
                                    uint64_t* lost_out,
                                    uint64_t* total_out) {
  const size_t payload = r->payload_size();
  if (out_bytes % payload != 0) return -22;  // EINVAL
  const uint64_t packets_per_block = out_bytes / payload;
  std::memset(out, 0, out_bytes);

  uint64_t begin_counter = 0;
  bool begin_set = false;
  if (r->have_counter) {
    begin_counter = r->next_counter;
    begin_set = true;
  }
  uint64_t filled = 0;
  uint64_t seen = 0;
  // per-slot fill map: a duplicated counter must not inflate the fill
  // count, or the block closes early with a silently-zeroed slot and
  // lost = 0 (mirrors the Python provider's fix).  Member buffer: no
  // per-block allocation in the line-rate drain loop
  r->slot_filled.assign(packets_per_block, 0);
  std::vector<uint8_t>& slot_filled = r->slot_filled;

  for (;;) {
    const uint8_t* pkt;
    if (r->pending_set) {
      pkt = r->pending;
      r->pending_set = false;
    } else {
      pkt = next_packet(r);
      if (!pkt) return -1;
    }
    const uint64_t c = parse_counter(pkt, r->counter_kind);
    if (!begin_set) {
      begin_counter = c;
      begin_set = true;
    }
    if (c < begin_counter) continue;  // stale packet from a prior block
    const uint64_t slot = c - begin_counter;
    if (slot >= packets_per_block) {
      // block complete; the overflowing packet belongs to a later block
      // — stash a copy for the next call (the ring frame itself may be
      // handed back to the kernel before then)
      if (pkt != r->pending) {
        std::memcpy(r->pending, pkt, r->packet_size);
      }
      r->pending_set = true;
      r->next_counter = begin_counter + packets_per_block;
      r->have_counter = true;
      r->total_packets += seen;
      r->lost_packets += packets_per_block - filled;
      if (first_counter_out) *first_counter_out = begin_counter;
      if (lost_out) *lost_out = packets_per_block - filled;
      if (total_out) *total_out = packets_per_block;
      return 0;
    }
    std::memcpy(out + slot * payload, pkt + r->header_size, payload);
    if (!slot_filled[slot]) {
      slot_filled[slot] = 1;
      filled++;
    }
    seen++;
    if (filled == packets_per_block) {
      r->next_counter = begin_counter + packets_per_block;
      r->have_counter = true;
      r->total_packets += seen;
      if (first_counter_out) *first_counter_out = begin_counter;
      if (lost_out) *lost_out = 0;
      if (total_out) *total_out = packets_per_block;
      return 0;
    }
  }
}

uint64_t srtb_pkt_ring_total_packets(PktRing* r) { return r->total_packets; }
uint64_t srtb_pkt_ring_lost_packets(PktRing* r) { return r->lost_packets; }

void srtb_pkt_ring_destroy(PktRing* r) {
  if (!r) return;
  if (r->map && r->map != MAP_FAILED) munmap(r->map, r->map_len);
  if (r->fd >= 0) close(r->fd);
  delete[] r->pending;
  delete r;
}

}  // extern "C"
