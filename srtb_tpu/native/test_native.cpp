// Sanitizer-instrumented harness for the native layer.
//
// The reference compiles its CTest suite with (commented-in) ASan flags
// and relies on in-kernel asserts + allocator diagnostics for memory
// bugs (SURVEY.md §5.2).  The TPU build's native code is this trio —
// recvmmsg receiver, AF_PACKET ring, async writer pool — so this
// harness exercises all three end-to-end under
// -fsanitize=address,undefined (built and run by `make -C
// srtb_tpu/native check`; ci.sh invokes it).  Any leak, use-after-free,
// data race on shutdown, or UB in header parsing fails the exit code.
//
// Self-contained: sends its own UDP datagrams over loopback, so it
// needs no fixture beyond CAP_NET_RAW for the ring section (skipped
// with a notice when unavailable).

#include <arpa/inet.h>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <string>
#include <thread>
#include <vector>

// C ABI under test (udp_receiver.cpp, packet_ring.cpp, file_writer.cpp)
extern "C" {
struct UdpRx;
UdpRx* srtb_udp_rx_create(const char*, uint16_t, uint64_t, uint64_t,
                          int32_t, int64_t);
int32_t srtb_udp_rx_receive_block(UdpRx*, uint8_t*, uint64_t, uint64_t*,
                                  uint64_t*, uint64_t*);
uint64_t srtb_udp_rx_lost_packets(UdpRx*);
void srtb_udp_rx_destroy(UdpRx*);

struct PktRing;
PktRing* srtb_pkt_ring_create(const char*, uint16_t, uint64_t, uint64_t,
                              int32_t, uint32_t, uint32_t);
int32_t srtb_pkt_ring_receive_block(PktRing*, uint8_t*, uint64_t,
                                    uint64_t*, uint64_t*, uint64_t*);
void srtb_pkt_ring_destroy(PktRing*);

struct WriterPool;
WriterPool* srtb_writer_create(int32_t, uint64_t);
int32_t srtb_writer_submit(WriterPool*, const char*, const uint8_t*,
                           uint64_t, int32_t, int32_t);
void srtb_writer_drain(WriterPool*);
uint64_t srtb_writer_bytes_written(WriterPool*);
uint64_t srtb_writer_errors(WriterPool*);
void srtb_writer_destroy(WriterPool*);
}

// CHECK() vanishes under NDEBUG, which would turn this harness into a
// silently green gate — CHECK always executes and always aborts on
// failure, whatever the build flags.
#define CHECK(cond)                                                       \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,       \
                   __LINE__, #cond);                                      \
      std::abort();                                                       \
    }                                                                     \
  } while (0)

namespace {

// pid-derived ports so concurrent runs on one host don't share sockets
const uint16_t kPort = (uint16_t)(40000 + (getpid() % 2000) * 2);
constexpr size_t kHeader = 8;
constexpr size_t kPayload = 1024;
constexpr size_t kPacket = kHeader + kPayload;

void send_counters(uint16_t port, const std::vector<uint64_t>& counters) {
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  CHECK(fd >= 0);
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(port);
  sa.sin_addr.s_addr = inet_addr("127.0.0.1");
  std::vector<uint8_t> pkt(kPacket);
  for (uint64_t c : counters) {
    std::memcpy(pkt.data(), &c, 8);
    std::memset(pkt.data() + kHeader, (int)(c & 0xFF), kPayload);
    (void)sendto(fd, pkt.data(), pkt.size(), 0, (sockaddr*)&sa,
                 sizeof(sa));
    usleep(2000);
  }
  close(fd);
}

int test_recvmmsg() {
  UdpRx* rx = srtb_udp_rx_create("127.0.0.1", kPort, kPacket, kHeader,
                                 /*le64*/ 0, 1 << 22);
  CHECK(rx && "bind failed");
  // loss (counter 2) + reorder (3 before 1) + overflow (4 -> next block)
  std::thread sender(send_counters, kPort,
                     std::vector<uint64_t>{0, 3, 1, 4});
  std::vector<uint8_t> out(4 * kPayload);
  uint64_t first = 0, lost = 0, total = 0;
  int rc = srtb_udp_rx_receive_block(rx, out.data(), out.size(), &first,
                                     &lost, &total);
  sender.join();
  CHECK(rc == 0 && first == 0 && total == 4 && lost == 1);
  CHECK(out[0] == 0 && out[kPayload] == 1);
  CHECK(out[2 * kPayload] == 0);  // zero-filled gap
  CHECK(out[3 * kPayload] == 3);
  CHECK(srtb_udp_rx_lost_packets(rx) == 1);
  srtb_udp_rx_destroy(rx);
  std::printf("recvmmsg: OK\n");
  return 0;
}

int test_ring() {
  PktRing* r = srtb_pkt_ring_create("lo", kPort + 1, kPacket, kHeader,
                                    /*le64*/ 0, 1 << 18, 16);
  if (!r) {
    std::printf("ring: SKIPPED (no CAP_NET_RAW)\n");
    return 0;
  }
  // hold the UDP port so the kernel does not ICMP-reject the sender
  int holder = socket(AF_INET, SOCK_DGRAM, 0);
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(kPort + 1);
  sa.sin_addr.s_addr = INADDR_ANY;
  (void)bind(holder, (sockaddr*)&sa, sizeof(sa));

  std::thread sender(send_counters, kPort + 1,
                     std::vector<uint64_t>{0, 1, 2, 3, 4, 5});
  std::vector<uint8_t> out(4 * kPayload);
  uint64_t first = 0, lost = 0, total = 0;
  int rc = srtb_pkt_ring_receive_block(r, out.data(), out.size(), &first,
                                       &lost, &total);
  CHECK(rc == 0 && first == 0 && lost == 0 && total == 4);
  CHECK(out[kPayload] == 1 && out[3 * kPayload] == 3);
  // second block starts at the pending overflow packet (counter 4)
  rc = srtb_pkt_ring_receive_block(r, out.data(), 2 * kPayload, &first,
                                   &lost, &total);
  sender.join();
  CHECK(rc == 0 && first == 4 && lost == 0 && total == 2);
  CHECK(out[0] == 4 && out[kPayload] == 5);
  srtb_pkt_ring_destroy(r);
  close(holder);
  std::printf("ring: OK\n");
  return 0;
}

int test_writer() {
  char path[96];
  std::snprintf(path, sizeof(path), "/tmp/srtb_native_test_writer.%d.bin",
                (int)getpid());
  std::remove(path);
  WriterPool* w = srtb_writer_create(2, 1 << 20);
  CHECK(w);
  std::vector<uint8_t> data(4096, 0x5A);
  for (int i = 0; i < 16; i++)
    CHECK(srtb_writer_submit(w, path, data.data(), data.size(),
                              /*fsync*/ i == 15, /*append*/ 1) == 0);
  srtb_writer_drain(w);
  CHECK(srtb_writer_errors(w) == 0);
  CHECK(srtb_writer_bytes_written(w) == 16 * data.size());
  srtb_writer_destroy(w);
  FILE* f = std::fopen(path, "rb");
  CHECK(f);
  std::fseek(f, 0, SEEK_END);
  CHECK(std::ftell(f) == long(16 * data.size()));
  std::fclose(f);
  std::remove(path);
  std::printf("writer: OK\n");
  return 0;
}

}  // namespace

int main() {
  // watchdog: a missed datagram must fail the gate, not hang CI
  alarm(60);
  int rc = test_writer();
  rc |= test_recvmmsg();
  rc |= test_ring();
  std::printf("native sanitizer harness: %s\n", rc ? "FAIL" : "PASS");
  return rc;
}
