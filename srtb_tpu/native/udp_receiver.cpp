// Native UDP baseband receiver.
//
// TPU-native equivalent of the reference's ingest stack
// (ref: io/udp/recvmmsg_packet_provider.hpp, io/udp/udp_receiver.hpp
// udp_receive_block_worker): batched recvmmsg() syscalls (128
// packets/call), counter parsing per packet format, placement of payloads
// by counter offset into a caller-provided block buffer (tolerating
// reordering within a block), zero-fill of lost packets with loss-rate
// accounting, optional CPU pinning of the receive thread.
//
// Exposed as a C ABI for Python ctypes (no pybind11 in this image).
//
// Build: make -C srtb_tpu/native  (produces libsrtb_udp.so)

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <netinet/in.h>
#include <sched.h>
#include <sys/socket.h>
#include <unistd.h>

#include <new>
#include <vector>

namespace {

constexpr size_t kBatch = 128;  // packets per recvmmsg (ref: recvmmsg_packet_provider.hpp)

// counter parsers (ref: io/backend_registry.hpp:63-73, 129-152)
enum CounterKind : int32_t {
  kCounterLe64 = 0,   // first 8 bytes little-endian (fastmb_roach2 / snap1)
  kCounterVdif67 = 1, // VDIF words 6 & 7 (gznupsr_a1)
};

inline uint64_t parse_counter(const uint8_t* pkt, int32_t kind) {
  uint64_t c = 0;
  if (kind == kCounterVdif67) {
    uint32_t w6, w7;
    std::memcpy(&w6, pkt + 6 * 4, 4);
    std::memcpy(&w7, pkt + 7 * 4, 4);
    c = (uint64_t)w6 | ((uint64_t)w7 << 32);
  } else {
    std::memcpy(&c, pkt, 8);
  }
  return c;
}

struct UdpRx {
  int fd = -1;
  size_t packet_size = 0;   // total datagram size incl. header
  size_t header_size = 0;
  int32_t counter_kind = kCounterLe64;
  uint64_t next_counter = 0;
  bool have_counter = false;

  // batch state: received but not yet consumed packets
  std::vector<uint8_t> buf;           // kBatch * packet_size
  std::vector<uint8_t> slot_filled;   // per-block fill map (reused)
  std::vector<mmsghdr> msgs;
  std::vector<iovec> iovs;
  size_t batch_pos = 0;
  size_t batch_len = 0;

  // statistics
  uint64_t total_packets = 0;
  uint64_t lost_packets = 0;

  size_t payload_size() const { return packet_size - header_size; }
};

bool refill(UdpRx* rx) {
  for (size_t i = 0; i < kBatch; i++) {
    rx->iovs[i].iov_base = rx->buf.data() + i * rx->packet_size;
    rx->iovs[i].iov_len = rx->packet_size;
    std::memset(&rx->msgs[i].msg_hdr, 0, sizeof(msghdr));
    rx->msgs[i].msg_hdr.msg_iov = &rx->iovs[i];
    rx->msgs[i].msg_hdr.msg_iovlen = 1;
  }
  int n = recvmmsg(rx->fd, rx->msgs.data(), kBatch, MSG_WAITFORONE, nullptr);
  if (n <= 0) return false;
  rx->batch_pos = 0;
  rx->batch_len = (size_t)n;
  return true;
}

}  // namespace

extern "C" {

// Create a bound UDP socket with a large receive buffer.
// Returns nullptr on failure.
UdpRx* srtb_udp_rx_create(const char* addr, uint16_t port,
                          uint64_t packet_size, uint64_t header_size,
                          int32_t counter_kind, int64_t rcvbuf_bytes) {
  UdpRx* rx = new (std::nothrow) UdpRx;
  if (!rx) return nullptr;
  rx->packet_size = packet_size;
  rx->header_size = header_size;
  rx->counter_kind = counter_kind;
  rx->buf.resize(kBatch * packet_size);
  rx->msgs.resize(kBatch);
  rx->iovs.resize(kBatch);

  rx->fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (rx->fd < 0) { delete rx; return nullptr; }
  int reuse = 1;
  setsockopt(rx->fd, SOL_SOCKET, SO_REUSEADDR, &reuse, sizeof(reuse));
  if (rcvbuf_bytes > 0) {
    // like the reference's max SO_RCVBUF tuning (README.md deployment notes)
    int v = (int)rcvbuf_bytes;
    setsockopt(rx->fd, SOL_SOCKET, SO_RCVBUF, &v, sizeof(v));
  }
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(port);
  sa.sin_addr.s_addr = addr && addr[0] ? inet_addr(addr) : INADDR_ANY;
  if (bind(rx->fd, (sockaddr*)&sa, sizeof(sa)) < 0) {
    close(rx->fd);
    delete rx;
    return nullptr;
  }
  return rx;
}

// Pin the calling thread to a CPU (ref: util/thread_affinity.hpp:34-122).
int32_t srtb_set_thread_affinity(int32_t cpu) {
  cpu_set_t set;
  CPU_ZERO(&set);
  CPU_SET(cpu, &set);
  return sched_setaffinity(0, sizeof(set), &set);
}

// Receive exactly one block of `out_bytes` payload bytes, assembled by
// packet counter.  Payload of packet with counter c goes to offset
// (c - begin_counter) * payload_size; gaps are left zeroed (caller provides
// a zeroed buffer or we memset here); packets beyond the block terminate
// assembly and are kept for the next call (ref: io/udp/udp_receiver.hpp
// 180-272 block worker).
// Returns 0 on success; fills first_counter / lost / total statistics.
int32_t srtb_udp_rx_receive_block(UdpRx* rx, uint8_t* out,
                                  uint64_t out_bytes,
                                  uint64_t* first_counter_out,
                                  uint64_t* lost_out, uint64_t* total_out) {
  const size_t payload = rx->payload_size();
  if (out_bytes % payload != 0) return -22;  // EINVAL
  const uint64_t packets_per_block = out_bytes / payload;
  std::memset(out, 0, out_bytes);

  uint64_t begin_counter = 0;
  bool begin_set = false;
  if (rx->have_counter) {
    begin_counter = rx->next_counter;
    begin_set = true;
  }
  uint64_t filled = 0;
  uint64_t seen = 0;
  // per-slot fill map: a duplicated counter must not inflate the fill
  // count, or the block closes early with a silently-zeroed slot and
  // lost = 0 (mirrors the Python provider's fix).  Member buffer: no
  // per-block allocation in the line-rate drain loop
  rx->slot_filled.assign(packets_per_block, 0);
  std::vector<uint8_t>& slot_filled = rx->slot_filled;

  while (true) {
    if (rx->batch_pos >= rx->batch_len) {
      if (!refill(rx)) return -1;
    }
    for (; rx->batch_pos < rx->batch_len; rx->batch_pos++) {
      const size_t i = rx->batch_pos;
      if (rx->msgs[i].msg_len < rx->packet_size) continue;  // runt
      const uint8_t* pkt = rx->buf.data() + i * rx->packet_size;
      const uint64_t c = parse_counter(pkt, rx->counter_kind);
      if (!begin_set) {
        begin_counter = c;
        begin_set = true;
      }
      if (c < begin_counter) continue;  // stale packet from previous block
      const uint64_t slot = c - begin_counter;
      if (slot >= packets_per_block) {
        // block complete; keep this packet position for next call
        rx->next_counter = begin_counter + packets_per_block;
        rx->have_counter = true;
        rx->total_packets += seen;
        rx->lost_packets += packets_per_block - filled;
        if (first_counter_out) *first_counter_out = begin_counter;
        if (lost_out) *lost_out = packets_per_block - filled;
        if (total_out) *total_out = packets_per_block;
        return 0;
      }
      std::memcpy(out + slot * payload, pkt + rx->header_size, payload);
      if (!slot_filled[slot]) {
        slot_filled[slot] = 1;
        filled++;
      }
      seen++;
      if (filled == packets_per_block) {
        rx->batch_pos++;
        rx->next_counter = begin_counter + packets_per_block;
        rx->have_counter = true;
        rx->total_packets += seen;
        if (first_counter_out) *first_counter_out = begin_counter;
        if (lost_out) *lost_out = 0;
        if (total_out) *total_out = packets_per_block;
        return 0;
      }
    }
  }
}

uint64_t srtb_udp_rx_total_packets(UdpRx* rx) { return rx->total_packets; }
uint64_t srtb_udp_rx_lost_packets(UdpRx* rx) { return rx->lost_packets; }

void srtb_udp_rx_destroy(UdpRx* rx) {
  if (!rx) return;
  if (rx->fd >= 0) close(rx->fd);
  delete rx;
}

}  // extern "C"
