"""Fault tolerance for the streaming runtime (PR 4).

A transient-search backend is only useful if it survives a night of
observing: the reference keeps its SYCL pipeline alive across packet
loss and slow consumers, and streamed GPU pipelines treat continuity
under stalls as a first-class design constraint (PAPERS.md:
arXiv:2101.00941 CUDA-streams AstroAccelerate; arXiv:1806.01556
always-on FPGA modules).  This package gives the srtb_tpu runtime the
same property, in six composable pieces:

- :mod:`errors` — the typed taxonomy every other piece dispatches on:
  *transient* (retryable), *fatal* (escalate to clean shutdown),
  *data-loss* (retryable, but the occurrence is accounted), and
  *device* (a compute-side OOM / compile failure / device halt —
  never retried verbatim, handed to the self-healing ladder);
- :mod:`retry` — configurable retry with exponential backoff,
  deterministic jitter and deadlines, applied by the pipeline to
  ingest reads, H2D staging, dispatch, fetch, sink writes, and
  checkpoint flushes;
- :mod:`supervisor` — bounded restarts for crashed workers (the sink
  drain Pipe, the GUI server thread) with escalation to clean
  shutdown when the budget is exhausted;
- :mod:`degrade` — the graceful-degradation ladder: under sustained
  sink backlog or accounted loss, shed waterfall dumps first, then
  baseband dumps, then whole segments (the existing
  ``DropOldestSegmentBuffer``), every step counted;
- :mod:`demote` — self-healing compute: the plan-demotion ladder
  (micro_batch -> front_fuse -> ring -> skzap -> fused_tail -> staged
  -> monolithic)
  that survives device OOM and compile faults on a cheaper plan, and
  bounded device-reinit recovery for halt faults — the compute-side
  twin of the supervisor;
- :mod:`faults` — deterministic fault injection (``Config.fault_plan``)
  arming named sites to raise/stall/corrupt — or fail like the
  accelerator runtime (oom / compile_fail / device_halt, with the real
  jax exception strings) — on scheduled segment indices, zero-cost
  when off (the same None-hook pattern as the runtime sanitizer), so
  every recovery path above is testable on CPU CI
  (``tools/chaos_soak.py`` composes them into randomized soaks; an
  optional stream selector ``beam3:dispatch:oom@4`` scopes an entry
  to one fleet lane);
- :mod:`admission` — the multi-tenant fleet's admission gate:
  capacity-bounded concurrent streams with a priority-ordered wait
  queue, every admit/queue/reject decision a stream-labeled counter
  (``pipeline/fleet.py`` consumes it; ``degrade.FleetShedPolicy`` is
  its overload-time twin, shedding the lowest-priority real-time
  stream first under fleet-wide sink pressure).

Everything is surfaced: retries, requeues, restarts, shed dumps, the
degradation level, plan demotions/promotions, device reinits and the
active-plan ladder level are Prometheus counters/gauges and journal
fields (telemetry schema v4).
"""
