"""Fault tolerance for the streaming runtime (PR 4).

A transient-search backend is only useful if it survives a night of
observing: the reference keeps its SYCL pipeline alive across packet
loss and slow consumers, and streamed GPU pipelines treat continuity
under stalls as a first-class design constraint (PAPERS.md:
arXiv:2101.00941 CUDA-streams AstroAccelerate; arXiv:1806.01556
always-on FPGA modules).  This package gives the srtb_tpu runtime the
same property, in five composable pieces:

- :mod:`errors` — the typed taxonomy every other piece dispatches on:
  *transient* (retryable), *fatal* (escalate to clean shutdown), and
  *data-loss* (retryable, but the occurrence is accounted);
- :mod:`retry` — configurable retry with exponential backoff,
  deterministic jitter and deadlines, applied by the pipeline to
  ingest reads, H2D staging, dispatch, fetch, sink writes, and
  checkpoint flushes;
- :mod:`supervisor` — bounded restarts for crashed workers (the sink
  drain Pipe, the GUI server thread) with escalation to clean
  shutdown when the budget is exhausted;
- :mod:`degrade` — the graceful-degradation ladder: under sustained
  sink backlog or accounted loss, shed waterfall dumps first, then
  baseband dumps, then whole segments (the existing
  ``DropOldestSegmentBuffer``), every step counted;
- :mod:`faults` — deterministic fault injection (``Config.fault_plan``)
  arming named sites to raise/stall/corrupt on scheduled segment
  indices, zero-cost when off (the same None-hook pattern as the
  runtime sanitizer), so every recovery path above is testable on CPU
  CI.

Everything is surfaced: retries, requeues, restarts, shed dumps and
the degradation level are Prometheus counters/gauges and journal
fields (telemetry schema v3).
"""
