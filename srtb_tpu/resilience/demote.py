"""Self-healing compute: the plan-demotion ladder and device reinit.

PR 4's supervisor hardened the *host* side (sinks, watchdog,
degradation); a compute-side failure — an XLA ``RESOURCE_EXHAUSTED``,
a Mosaic compile error, a halted device — still killed the stream even
though the repo has everything needed to recover: 20 audited plan
families (plan_cards.json), retained host buffers that re-dispatch any
segment cold and bit-identically, and checkpoint resume.  This module
closes that gap with two mechanisms, both driven by the typed
device-fault classification in :mod:`srtb_tpu.resilience.errors`:

**Plan demotion** (oom / compile faults).  The ladder is an ordered
list of progressively cheaper execution plans derived from the active
config by switching off features in a fixed order (owned by the plan
registry, ``pipeline/registry.py``)::

    search_mode -> micro_batch -> front_fuse -> ring -> skzap ->
    fused_tail
                -> staged -> monolithic

Each rung is CUMULATIVE (rung k applies every earlier step too) and
rungs that would not change the active config are skipped, so the
ladder a given run walks contains only real alternatives.  On a
device fault at a dispatch/fetch site the engine demotes one rung,
rebuilds the :class:`SegmentProcessor` from the rung's config (the
rung changes trace-relevant knobs, so ``plan_signature()`` differs and
any AOT cache misses cleanly and re-lowers), and re-dispatches the
faulted segment COLD from its already-retained host buffer — the same
recovery path the watchdog requeue proved bit-identical.  The rung
order mirrors cost/fragility: the micro-batch multiplies the program's
footprint by B; the ring adds the carry programs; skzap and the fused
tail are the Pallas-heavy fusions (the likeliest Mosaic compile
surface); the staged plan trades one big program for three small ones
(each program's temporaries freed before the next — the proven answer
to chain OOM at 2^30); monolithic is the minimal-feature floor that
must run anywhere XLA runs.  Every demotion-ladder target must
resolve to a plan family already carded in ``plan_cards.json``
(``analysis/hlo_audit.audit_ladder``, gated in ci.sh): the run never
demotes into an unaudited plan.

**Device reinit** (halt faults).  A halted backend invalidates every
in-flight device buffer and compiled-executable handle.  Recovery:
drop all in-flight device state, ``jax.clear_caches()``, rebuild the
processor at the CURRENT rung (a fresh processor holds no loaded AOT
executables or jit caches bound to the dead backend handle, and the
engine separately invalidates the warm ingest-ring carry), then
re-dispatch every in-flight segment cold from its retained host
buffer — in dispatch order, so journal order and checkpoint resume
offsets are unchanged.  Reinits are budgeted by the same
bounded-restart supervisor the sink pipe uses (``device_reinit_max``
within ``device_reinit_window_s``): a flapping device escalates to a
clean shutdown instead of flapping forever.

**Promotion probe.**  With ``promote_after_segments = N > 0``, N
consecutively healthy drained segments promote one rung back up; the
next dispatch probes the richer plan, and if the fault recurs the
engine simply demotes again (each further promotion needs another N
healthy segments, so a persistent fault settles at the highest rung
that works).  0 (default) sticks with the demoted plan for the rest
of the run.

Every transition is accounted: ``plan_demotions`` /
``plan_promotions`` / ``device_reinits`` counters, the
``plan_ladder_level`` gauge, and the v4 journal's ``active_plan``
field (utils/telemetry.py) — a run that quietly survives on the
monolithic floor must be visible on /metrics.
"""

from __future__ import annotations

from dataclasses import dataclass

from srtb_tpu.pipeline import registry
from srtb_tpu.resilience.errors import classify_device
from srtb_tpu.resilience.supervisor import Supervisor
from srtb_tpu.utils import events
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

# canonical rung order, cheapest-to-drop first — read from the ONE
# plan-family registry (pipeline/registry.py), which also owns each
# step's apply rule; this module keeps only the per-run state machine
LADDER_ORDER = registry.ladder_order()


@dataclass(frozen=True)
class Rung:
    """One demotion target: the step that produced it, the demoted
    config, and the explicit ``staged`` constructor override (None =
    let the processor resolve from the segment size)."""

    step: str
    cfg: object
    staged: bool | None

    @property
    def name(self) -> str:
        return self.step


def _apply_step(cfg, step: str, staged: bool | None):
    """(new_cfg, new_staged) after one ladder step, or None when the
    step would not change the active RESOLVED plan (skipped rung —
    demoting onto an identical plan would burn a ladder level while
    recovering nothing).  The apply rules themselves live in the plan
    registry, next to the families they demote between — and they
    delegate to the SegmentProcessor's own pure-config resolvers, so
    no mirrored rule can drift."""
    return registry.ladder_step(step).apply(cfg, staged)


def parse_ladder(text: str) -> tuple[str, ...]:
    """``Config.plan_ladder`` -> ordered step tuple.  "auto" is the
    full canonical order; an explicit comma list selects a subset (in
    the given order); unknown step names raise at startup — a ladder
    with a typo must fail loudly, not silently never demote."""
    text = (text or "auto").strip().lower()
    if text in ("auto", ""):
        return LADDER_ORDER
    if text == "off":
        return ()
    steps = tuple(s.strip() for s in text.split(",") if s.strip())
    for s in steps:
        if s not in LADDER_ORDER:
            raise ValueError(
                f"plan_ladder step {s!r} unknown "
                f"(steps: {', '.join(LADDER_ORDER)}, or auto/off)")
    return steps


def ladder_rungs(cfg, base_staged: bool | None = None,
                 steps: tuple[str, ...] = LADDER_ORDER) -> list[Rung]:
    """The demotion rungs reachable from ``cfg``: cumulative configs in
    ladder order, no-op steps skipped.  ``base_staged`` is the CURRENT
    processor's resolved staged flag (so a run already on the staged
    plan skips that rung)."""
    rungs: list[Rung] = []
    cur, staged = cfg, base_staged
    for step in steps:
        out = _apply_step(cur, step, staged)
        if out is None:
            continue
        cur, staged = out
        rungs.append(Rung(step, cur, staged))
    return rungs


class ComputeHealer:
    """Per-run self-healing state machine: ladder position, promotion
    counter, and the reinit budget.  Owned by the Pipeline; the engine
    calls :meth:`classify` on any dispatch/fetch failure and then one
    of :meth:`demote` / :meth:`reinit`, swapping in the processor each
    returns.  ``factory(cfg, staged)`` builds the replacement
    processor (the pipeline's hook, overridable in tests).

    Zero-cost when healthy: the engine consults this object only from
    exception handlers and one counter bump per drained segment."""

    def __init__(self, cfg, factory, steps: tuple[str, ...] = None,
                 base_staged: bool | None = None,
                 promote_after: int = 0, reinit_max: int = 0,
                 reinit_window_s: float = 300.0):
        if steps is None:
            steps = parse_ladder(getattr(cfg, "plan_ladder", "auto"))
        self._cfg = cfg
        self._factory = factory
        self._steps = steps
        self._rungs = ladder_rungs(cfg, base_staged, steps)
        self._base_staged = base_staged
        self._level = 0  # 0 = the configured (full) plan
        self._healthy = 0
        self.promote_after = int(promote_after)
        self._reinit = None
        if int(reinit_max) > 0:
            # counter=None: reinits are accounted under their OWN
            # device_reinits counter (in reinit()); riding the default
            # worker_restarts would journal phantom worker restarts
            self._reinit = Supervisor(
                "device_reinit", max_restarts=int(reinit_max),
                window_s=float(reinit_window_s), counter=None)
        # per-stream twins (multi-tenant fleet): the flat series stay
        # process-wide; the labeled ones attribute demotions/ladder
        # position to the tenant whose device fault caused them
        stream = str(getattr(cfg, "stream_name", "") or "")
        self._labels = {"stream": stream} if stream else None
        metrics.set("plan_ladder_level", 0)
        if self._labels is not None:
            metrics.set("plan_ladder_level", 0, labels=self._labels)

    def _mark(self, counter: str | None) -> None:
        if counter is not None:
            metrics.add(counter)
        metrics.set("plan_ladder_level", self._level)
        if self._labels is not None:
            if counter is not None:
                metrics.add(counter, labels=self._labels)
            metrics.set("plan_ladder_level", self._level,
                        labels=self._labels)

    @classmethod
    def from_config(cls, cfg, factory) -> "ComputeHealer | None":
        """None (zero-cost off) when both mechanisms are disabled:
        ``plan_ladder = off`` AND ``device_reinit_max = 0``."""
        steps = parse_ladder(getattr(cfg, "plan_ladder", "auto"))
        reinit_max = int(getattr(cfg, "device_reinit_max", 0) or 0)
        if not steps and reinit_max <= 0:
            return None
        return cls(
            cfg, factory, steps=steps,
            promote_after=int(getattr(cfg, "promote_after_segments",
                                      0) or 0),
            reinit_max=reinit_max,
            reinit_window_s=float(getattr(cfg, "device_reinit_window_s",
                                          300.0)))

    # ------------------------------------------------------- state

    @property
    def level(self) -> int:
        return self._level

    @property
    def rungs(self) -> list[Rung]:
        return list(self._rungs)

    @property
    def active_cfg(self):
        """The config of the active rung (the base config at level 0)."""
        if self._level == 0:
            return self._cfg
        return self._rungs[self._level - 1].cfg

    @property
    def active_step(self) -> str:
        return "full" if self._level == 0 \
            else self._rungs[self._level - 1].step

    @property
    def micro_batch(self) -> int:
        """Micro-batch size of the ACTIVE plan — the engine's dispatch
        unit must follow demotions (the micro_batch rung drops it to
        1, and the demoted processor has no batch programs)."""
        return max(1, int(getattr(self.active_cfg,
                                  "micro_batch_segments", 1) or 1))

    def bind_base(self, base_staged: bool | None) -> None:
        """Late-bind the resolved staged flag of the pipeline's actual
        processor (the healer is built before the processor resolves
        on a custom-processor pipeline) and rebuild the rungs."""
        if base_staged != self._base_staged:
            self._base_staged = base_staged
            self._rungs = ladder_rungs(self._cfg, base_staged,
                                       self._steps)

    # -------------------------------------------------- transitions

    def classify(self, exc: BaseException) -> str | None:
        """Device-fault kind of ``exc`` (None = not a device fault).
        Deliberately NOT filtered by remaining budget: the engine must
        learn the kind even when nothing is left, so it can raise the
        typed FATAL escalation (LadderExhausted /
        ReinitBudgetExceeded) instead of letting a DEVICE-classified
        exception escape — an outer supervisor would restart on
        DEVICE, and a permanently OOMing run must escalate, not
        flap."""
        return classify_device(exc)

    def _build(self, rung_level: int):
        if rung_level == 0:
            return self._factory(self._cfg, self._base_staged)
        rung = self._rungs[rung_level - 1]
        return self._factory(rung.cfg, rung.staged)

    def demote(self, exc: BaseException, kind: str):
        """One rung down: returns the replacement processor, or None
        when the ladder is exhausted (the engine then escalates).
        Every demotion resets the promotion counter."""
        if self._level >= len(self._rungs):
            return None
        self._level += 1
        self._healthy = 0
        rung = self._rungs[self._level - 1]
        self._mark("plan_demotions")
        events.emit("heal.demote",
                    stream=(self._labels or {}).get("stream"),
                    info=f"{rung.step}@{self._level} ({kind})")
        log.warning(
            f"[selfheal] device fault ({kind}) — demoting to ladder "
            f"rung {self._level}/{len(self._rungs)} ({rung.step}): "
            f"{exc!r}")
        return self._build(self._level)

    def reinit(self, exc: BaseException):
        """Backend reinit at the current rung: returns the fresh
        processor, or None when the reinit budget is spent within the
        window (the engine then escalates — a flapping device must
        not flap forever).  The caller owns the surrounding teardown
        (jax.clear_caches, ring invalidation, pending re-dispatch)."""
        if self._reinit is None or \
                not self._reinit.should_restart(exc):
            return None
        metrics.add("device_reinits")
        if self._labels is not None:
            metrics.add("device_reinits", labels=self._labels)
        events.emit("heal.reinit",
                    stream=(self._labels or {}).get("stream"),
                    info=f"{self.active_step}@{self._level}")
        log.warning(
            f"[selfheal] device halt — reinitializing backend at "
            f"ladder rung {self._level} ({self.active_step}): {exc!r}")
        return self._build(self._level)

    def rebuild(self, shared=None):
        """Fresh processor at the CURRENT rung, with no budget check
        and no counters: the fleet's SHARED device reinit
        (pipeline/fleet.py) makes one budgeted decision for the whole
        device and then rebuilds every lane — charging each lane's own
        reinit budget for a fault it didn't cause would let one
        flapping neighbor bankrupt the fleet.

        ``shared`` (a zero-arg factory) serves the fleet's LIVE
        migration: a lane at rung 0 re-admits through its target
        device's shared plan cache (rejoining that member's batch
        family and paying a compile only if the family is new there);
        a DEMOTED lane stays on its unshared rung — exactly the
        batch-former's membership rule."""
        if shared is not None and self._level == 0:
            return shared()
        return self._build(self._level)

    # --------------------------------------------- promotion probe

    def note_healthy(self) -> None:
        """One successfully fetched segment on a demoted plan."""
        if self._level > 0 and self.promote_after > 0:
            self._healthy += 1

    def promote_due(self) -> bool:
        return (self._level > 0 and self.promote_after > 0
                and self._healthy >= self.promote_after)

    def promote(self):
        """One rung back up (the promotion probe): returns the richer
        processor; the NEXT dispatch probes it and a recurring fault
        simply demotes again."""
        if self._level <= 0:
            return None
        self._level -= 1
        self._healthy = 0
        self._mark("plan_promotions")
        events.emit("heal.promote",
                    stream=(self._labels or {}).get("stream"),
                    info=f"{self.active_step}@{self._level}")
        log.info(
            f"[selfheal] {self.promote_after} healthy segments — "
            f"promotion probe back to rung {self._level} "
            f"({self.active_step})")
        return self._build(self._level)
