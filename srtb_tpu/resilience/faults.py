"""Deterministic fault injection (``Config.fault_plan``).

Recovery code that is never executed is broken code waiting for a bad
night at the telescope.  This module arms the pipeline's named fault
sites to fail *on schedule*, so every retry / watchdog / supervisor /
degradation path runs deterministically on CPU CI and can be soaked
with ``tools/udp_soak.py --fault-plan``.

Plan syntax (comma-separated entries)::

    [stream:]site:action@index

- ``stream``  OPTIONAL stream selector (multi-tenant fleet): the entry
              fires only in the pipeline whose ``Config.stream_name``
              matches (e.g. ``stream0:dispatch:oom@3`` hits only the
              fleet's "stream0" lane).  Entries without a selector
              keep the existing semantics — they arm in every pipeline
              the plan reaches — so existing soaks and tests are
              untouched.  Any prefix that is not a known site name is
              read as a stream selector;
- ``site``    one of ``ingest``, ``h2d``, ``dispatch``, ``fetch``,
              ``sink_write``, ``checkpoint`` — the hook points wired
              through pipeline/runtime.py;
- ``action``  ``raise`` (transient :class:`InjectedFault`),
              ``fatal`` (:class:`InjectedFatal`, escalates),
              ``corrupt`` (:class:`InjectedCorruption`, a data-loss
              fault: retried AND accounted),
              ``stall=SECONDS`` (sleeps — long enough trips the
              segment watchdog), or a device-fault action ``oom`` |
              ``compile_fail`` | ``device_halt`` (raises an exception
              whose TYPE NAME and MESSAGE mimic the real jaxlib
              ``XlaRuntimeError`` strings, so the self-healing
              ladder's string classifier — not a typed shortcut — is
              what recovers the run, the same code path a real TPU
              fault takes);
- ``index``   the segment index the fault fires on — dispatch-order
              within the run, 0-based, the SAME space at every site
              (a resumed run's journal numbering continues from the
              checkpoint, but fault indices always count from this
              run's first ingested segment).

Example: ``ingest:raise@1,fetch:stall=0.5@2,sink_write:corrupt@3``.

Each armed fault fires exactly once, so "transient fault retries to
success" is the deterministic outcome.  When ``Config.fault_plan`` is
empty the injector is ``None`` and the pipeline never calls in here —
the same zero-cost-off None-hook pattern as the runtime sanitizer.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from srtb_tpu.resilience.errors import (DataLossError, FatalError,
                                        TransientError)
from srtb_tpu.utils import events
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

SITES = ("ingest", "h2d", "dispatch", "fetch", "sink_write",
         "checkpoint")
DEVICE_ACTIONS = ("oom", "compile_fail", "device_halt")
ACTIONS = ("raise", "fatal", "corrupt", "stall") + DEVICE_ACTIONS
# device faults only make sense where device work happens: staging,
# program dispatch, result fetch
DEVICE_SITES = ("h2d", "dispatch", "fetch")


class InjectedFault(TransientError):
    """A scheduled transient fault."""


class InjectedFatal(FatalError):
    """A scheduled fatal fault."""


class InjectedCorruption(DataLossError):
    """A scheduled data-loss fault."""


class _InjectedXlaError(Exception):
    """Stand-in for jaxlib's ``XlaRuntimeError`` (which cannot be
    constructed portably across jaxlib releases).  The classifier in
    resilience/errors.py matches the TYPE NAME plus the status string,
    so renaming this class makes the injected fault travel the exact
    recognition path a real accelerator fault takes — no typed
    shortcut, the string classifier is what the test proves."""


_InjectedXlaError.__name__ = "XlaRuntimeError"
_InjectedXlaError.__qualname__ = "XlaRuntimeError"

# messages copied from the shapes jax actually raises (v5e / CPU
# allocator / Mosaic), with an [injected] tag so a log reader is never
# fooled into debugging phantom hardware
_DEVICE_MESSAGES = {
    "oom": ("RESOURCE_EXHAUSTED: Out of memory while trying to "
            "allocate 68719476736 bytes. [injected fault at {spec}]"),
    "compile_fail": ("INTERNAL: Mosaic failed to compile TPU kernel: "
                     "injected compile fault at {spec}"),
    "device_halt": ("INTERNAL: Accelerator device halted prematurely, "
                    "perhaps due to an on-device check-failure. "
                    "[injected fault at {spec}]"),
}


@dataclass
class FaultSpec:
    site: str
    action: str
    index: int
    arg: float = 0.0     # stall duration
    stream: str | None = None   # None = every pipeline (legacy)
    fired: bool = field(default=False, compare=False)

    def __str__(self) -> str:
        a = (f"{self.action}={self.arg:g}" if self.action == "stall"
             else self.action)
        pre = f"{self.stream}:" if self.stream else ""
        return f"{pre}{self.site}:{a}@{self.index}"


def parse_plan(text: str) -> list[FaultSpec]:
    """Parse the plan syntax above; raises ``ValueError`` with the
    offending entry on any malformed piece (a fault plan with a typo
    must fail the run at startup, not silently never fire)."""
    specs = []
    for entry in (e.strip() for e in text.split(",")):
        if not entry:
            continue
        try:
            site, rest = entry.split(":", 1)
            stream = None
            if site.strip() not in SITES and ":" in rest:
                # leading stream selector: "stream0:dispatch:oom@3"
                stream, site, rest = site, *rest.split(":", 1)
                stream = stream.strip()
            action, idx = rest.rsplit("@", 1)
            arg = 0.0
            if "=" in action:
                action, arg_s = action.split("=", 1)
                arg = float(arg_s)
            site, action = site.strip(), action.strip()
            index = int(idx)
        except ValueError as e:
            raise ValueError(
                f"fault_plan entry {entry!r}: expected "
                "'[stream:]site:action@index' with action raise|fatal|"
                f"corrupt|stall=SECONDS ({e})") from e
        if site not in SITES:
            raise ValueError(f"fault_plan entry {entry!r}: unknown site "
                             f"{site!r} (sites: {', '.join(SITES)})")
        if action not in ACTIONS:
            raise ValueError(
                f"fault_plan entry {entry!r}: unknown action {action!r} "
                f"(actions: {', '.join(ACTIONS)})")
        if action == "stall" and arg <= 0:
            raise ValueError(f"fault_plan entry {entry!r}: stall needs "
                             "a positive duration (stall=SECONDS)")
        if action in DEVICE_ACTIONS and site not in DEVICE_SITES:
            raise ValueError(
                f"fault_plan entry {entry!r}: device-fault action "
                f"{action!r} only fires at a device site "
                f"({', '.join(DEVICE_SITES)})")
        specs.append(FaultSpec(site, action, index, arg, stream))
    return specs


class FaultInjector:
    """Armed fault sites; ``fire`` is the per-site hook the pipeline
    calls with the current segment index."""

    def __init__(self, specs: list[FaultSpec]):
        self._by_site: dict[str, dict[int, FaultSpec]] = {}
        for s in specs:
            site = self._by_site.setdefault(s.site, {})
            if s.index in site:
                # overwriting would silently never fire the first spec
                # — the fail-at-startup contract of parse_plan applies
                raise ValueError(
                    f"fault_plan: duplicate entry for {s.site}@"
                    f"{s.index} ({site[s.index]} vs {s})")
            site[s.index] = s

    @classmethod
    def from_plan(cls, text: str,
                  stream: str = "") -> "FaultInjector | None":
        """None (zero-cost off) for an empty plan, or when every entry
        is scoped to some OTHER stream.  ``stream`` is this pipeline's
        ``Config.stream_name``: entries without a selector always arm
        (legacy semantics); entries with one arm only in the matching
        pipeline — the fleet hands each lane the whole plan and each
        lane keeps exactly its own faults."""
        if not text or not text.strip():
            return None
        specs = [s for s in parse_plan(text)
                 if s.stream is None or s.stream == stream]
        if not specs:
            return None
        return cls(specs)

    def armed(self, site: str) -> bool:
        return site in self._by_site

    def fire(self, site: str, index: int) -> None:
        """Raise/stall if a fault is scheduled at (site, index) and has
        not fired yet.  Counted per fire (``faults_injected``)."""
        spec = self._by_site.get(site, {}).get(index)
        if spec is None or spec.fired:
            return
        spec.fired = True
        metrics.add("faults_injected")
        events.emit("fault.injected", seg=index, info=str(spec))
        log.warning(f"[faults] firing {spec}")
        if spec.action == "stall":
            time.sleep(spec.arg)
            return
        if spec.action == "fatal":
            raise InjectedFatal(f"injected fatal fault at {spec}")
        if spec.action == "corrupt":
            raise InjectedCorruption(f"injected corruption at {spec}")
        if spec.action in DEVICE_ACTIONS:
            raise _InjectedXlaError(
                _DEVICE_MESSAGES[spec.action].format(spec=spec))
        raise InjectedFault(f"injected transient fault at {spec}")

    def unfired(self) -> list[FaultSpec]:
        """Specs that never fired (a test asserting full plan coverage
        calls this at the end of a run)."""
        return [s for site in self._by_site.values()
                for s in site.values() if not s.fired]
