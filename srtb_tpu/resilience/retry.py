"""Retry with exponential backoff, deterministic jitter and deadlines.

Applied by the pipeline to its six fault sites (ingest, h2d, dispatch,
fetch, sink_write, checkpoint).  Only failures classified TRANSIENT or
DATA_LOSS by :func:`srtb_tpu.resilience.errors.classify` are retried;
FATAL failures and exhausted budgets propagate, which is how a retry
escalates to the supervisor / clean shutdown, and DEVICE failures
propagate un-retried to the self-healing compute ladder
(resilience/demote.py) — the recovery for an OOM or compile fault is a
cheaper plan, not the same program again.

Jitter is *deterministic* (a hash of site and attempt, not
``random``): a replayed run with a fault plan backs off identically,
so recovery tests and soak reproductions are bit-stable in their
scheduling too.  Every retry is accounted (``retries_total`` plus a
per-site counter) — recovery that happens silently cannot be
distinguished from a pipeline that never faults.
"""

from __future__ import annotations

import time
import zlib
from dataclasses import dataclass

from srtb_tpu.resilience.errors import DATA_LOSS, TRANSIENT, classify
from srtb_tpu.utils import events
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics


@dataclass(frozen=True)
class RetryPolicy:
    """``max_attempts`` includes the first try; ``deadline_s`` bounds
    the total wall clock of one guarded operation including backoff
    sleeps (0 disables); jitter is a +/- fraction of each backoff."""

    max_attempts: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    jitter: float = 0.25
    deadline_s: float = 0.0

    @classmethod
    def from_config(cls, cfg) -> "RetryPolicy | None":
        """None when retries are configured off (``retry_max_attempts
        <= 1``) — the pipeline then calls operations directly, the
        zero-cost-disabled pattern shared with the sanitizer."""
        attempts = int(getattr(cfg, "retry_max_attempts", 0) or 0)
        if attempts <= 1:
            return None
        return cls(
            max_attempts=attempts,
            backoff_base_s=float(getattr(cfg, "retry_backoff_base_s",
                                         0.05)),
            backoff_max_s=float(getattr(cfg, "retry_backoff_max_s",
                                        2.0)),
            deadline_s=float(getattr(cfg, "retry_deadline_s", 0.0)))

    def backoff(self, site: str, attempt: int) -> float:
        """Exponential backoff for the given (site, attempt), with
        deterministic jitter so replayed runs schedule identically."""
        base = min(self.backoff_max_s,
                   self.backoff_base_s * (2 ** (attempt - 1)))
        h = zlib.crc32(f"{site}:{attempt}".encode()) / 0xFFFFFFFF
        return base * (1.0 + self.jitter * (2.0 * h - 1.0))


def retry_call(fn, policy: RetryPolicy, site: str, sleep=time.sleep):
    """Run ``fn`` under ``policy``; the site name labels counters and
    log lines.  Raises the last failure when FATAL, when attempts are
    exhausted, or when the next backoff would cross the deadline.

    The no-failure path is one try/except around ``fn`` — no clocks,
    no allocations — so wrapping every hot-path operation costs
    nothing measurable until something actually fails."""
    try:
        return fn()
    except BaseException as e:  # noqa: BLE001 - classified below
        exc = e
    t0 = time.monotonic()  # failure path only
    attempt = 1
    while True:
        cat = classify(exc)
        if cat not in (TRANSIENT, DATA_LOSS):
            # FATAL escalates; DEVICE propagates to the self-healing
            # ladder (pipeline/runtime.py): re-running an OOMing or
            # uncompilable program verbatim fails verbatim — the
            # recovery is a different plan, not a retry
            raise exc
        if cat == DATA_LOSS:
            # the retry may succeed, but the loss itself happened
            metrics.add("data_loss_total")
        if attempt >= policy.max_attempts:
            log.error(f"[resilience] {site}: {exc!r} — retry budget "
                      f"({policy.max_attempts} attempts) exhausted")
            raise exc
        delay = policy.backoff(site, attempt)
        if policy.deadline_s > 0 and \
                time.monotonic() - t0 + delay > policy.deadline_s:
            log.error(f"[resilience] {site}: {exc!r} — retry deadline "
                      f"{policy.deadline_s}s would be exceeded")
            raise exc
        metrics.add("retries_total")
        metrics.add(f"retries_{site}")
        # flight-recorder: the ambient context (set by the engine at
        # each guarded site) attributes the retry to its segment
        events.emit("retry", info=f"{site}:{cat}:{attempt}")
        log.warning(
            f"[resilience] {site}: {cat} {exc!r}; retrying "
            f"({attempt}/{policy.max_attempts - 1}) in "
            f"{delay * 1e3:.0f} ms")
        sleep(delay)
        attempt += 1
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001
            exc = e
