"""Admission control for the multi-tenant stream fleet.

A device serving N concurrent streams (pipeline/fleet.py) has a hard
capacity: every admitted stream holds an in-flight window of device
buffers, and admitting one more tenant past that point degrades every
existing one (the noisy-neighbor failure the bulkheads exist to
prevent).  The admission gate makes that boundary explicit and FAIR:

- up to ``Config.fleet_max_streams`` streams run concurrently
  (0 = no limit — a dev box running two replay jobs needs no gate);
- past capacity, new streams are **queued** (up to
  ``Config.fleet_queue_limit`` slots) in priority order
  (``Config.stream_priority``, higher first; FIFO within a priority)
  and started as running streams finish;
- past the queue, the LOWEST-priority request loses: a new request
  that outranks the worst queued entry evicts it (the evictee is
  rejected), otherwise the new request itself is rejected.

Every decision is a counter with a ``stream`` label — an operator
must be able to answer "who was turned away, and why" from /metrics
alone.  Rejection is an ANSWER, not an error: the fleet reports
rejected streams in its result instead of raising, so a submitting
service can retry, re-prioritize, or route to another device.
"""

from __future__ import annotations

import itertools

from srtb_tpu.utils import events
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

ADMIT = "admit"
QUEUE = "queue"
REJECT = "reject"


class AdmissionController:
    """Capacity gate + priority wait queue over stream names.

    Not thread-safe by itself: the fleet scheduler (single-threaded)
    is the only caller.
    """

    def __init__(self, max_streams: int = 0, queue_limit: int = 0):
        self.max_streams = max(0, int(max_streams))
        self.queue_limit = max(0, int(queue_limit))
        self.running: set[str] = set()
        # sort key: (-priority, arrival seq) — higher priority first,
        # FIFO within a priority band
        self._seq = itertools.count()
        self._queue: list[tuple[int, int, str]] = []
        self.rejected: list[str] = []
        # batch-aware admission (cross-tenant continuous batching):
        # each stream's plan-family key, when the caller knows it.
        # Eviction prefers streams with no co-tenant family — kicking
        # a batch-group member also costs its neighbors the formed
        # batch density, kicking a loner costs one tenant.
        self._plan_keys: dict[str, str] = {}

    @classmethod
    def from_config(cls, cfg) -> "AdmissionController":
        return cls(
            max_streams=int(getattr(cfg, "fleet_max_streams", 0) or 0),
            queue_limit=int(getattr(cfg, "fleet_queue_limit", 0) or 0))

    # ------------------------------------------------------- decisions

    _COUNTERS = {ADMIT: "fleet_admitted", QUEUE: "fleet_queued",
                 REJECT: "fleet_rejected"}

    def _mark(self, decision: str, name: str) -> None:
        counter = self._COUNTERS[decision]
        metrics.add(counter)
        metrics.add(counter, labels={"stream": name})
        metrics.set("fleet_running", len(self.running))
        metrics.set("fleet_queued_depth", len(self._queue))
        events.emit("admission", trace=0, stream=name, info=decision)

    def request(self, name: str, priority: int = 0,
                plan_key: str | None = None) -> str:
        """One stream asking to run; returns ADMIT / QUEUE / REJECT.
        A queued stream surfaces later via :meth:`pop_ready` once
        capacity frees up (the fleet starts its lane then).
        ``plan_key`` (optional) is the stream's plan-family key; the
        eviction tie-break prefers keeping families with co-tenants
        together (batch-aware admission)."""
        if plan_key is not None:
            self._plan_keys[name] = plan_key
        if self.max_streams <= 0 or len(self.running) < self.max_streams:
            self.running.add(name)
            self._mark("admit", name)
            return ADMIT
        entry = (-int(priority), next(self._seq), name)
        if len(self._queue) < self.queue_limit:
            self._queue.append(entry)
            self._queue.sort()
            self._mark("queue", name)
            log.info(f"[admission] fleet at capacity "
                     f"({self.max_streams}): queued stream {name!r} "
                     f"(priority {priority})")
            return QUEUE
        if self._queue and entry[:1] < self._queue[-1][:1]:
            # the new request outranks the worst queued entry: the
            # queue keeps the highest-priority waiters, the evictee
            # is rejected in the newcomer's place
            evicted = self._queue.pop(self._evict_index())[-1]
            self._plan_keys.pop(evicted, None)
            self.rejected.append(evicted)
            self._mark("reject", evicted)
            log.warning(f"[admission] queued stream {evicted!r} "
                        f"evicted by higher-priority {name!r}")
            self._queue.append(entry)
            self._queue.sort()
            self._mark("queue", name)
            return QUEUE
        self._plan_keys.pop(name, None)
        self.rejected.append(name)
        self._mark("reject", name)
        log.warning(f"[admission] fleet over capacity: rejected "
                    f"stream {name!r} (priority {priority})")
        return REJECT

    def _evict_index(self) -> int:
        """Which queue entry an outranking request displaces: within
        the lowest-priority band (the only candidates — priority
        order is never violated), a stream whose plan family has NO
        co-tenant among running or queued streams goes first, newest
        arrival first; with no loner, the newest arrival of the band
        (the pre-batching behavior).  Streams without a known plan
        key count as loners."""
        band = self._queue[-1][0]
        idxs = [i for i, e in enumerate(self._queue) if e[0] == band]
        counts: dict[str, int] = {}
        for n in list(self.running) + [e[-1] for e in self._queue]:
            k = self._plan_keys.get(n)
            if k is not None:
                counts[k] = counts.get(k, 0) + 1
        for i in reversed(idxs):
            k = self._plan_keys.get(self._queue[i][-1])
            if k is None or counts.get(k, 0) <= 1:
                return i
        return idxs[-1]

    def pop_ready(self) -> str | None:
        """Highest-priority queued stream if capacity allows, else
        None; the returned stream is immediately counted as running."""
        if not self._queue or (self.max_streams > 0
                               and len(self.running)
                               >= self.max_streams):
            return None
        name = self._queue.pop(0)[-1]
        self.running.add(name)
        self._mark("admit", name)
        return name

    def note_migration(self, name: str, src: str, dst: str) -> None:
        """A running stream was LIVE-migrated between pool devices:
        its admission slot is unchanged (the stream never stopped
        running), but the re-admission on the target must be
        attributable — who moved, from where, to where — from
        /metrics and the event trace alone, like every other
        admission decision."""
        if name not in self.running and self.max_streams > 0:
            log.warning(f"[admission] migration noted for "
                        f"{name!r}, which holds no admission slot")
        metrics.add("fleet_readmitted")
        metrics.add("fleet_readmitted", labels={"stream": name})
        metrics.add("fleet_readmitted", labels={"device": dst})
        events.emit("admission", trace=0, stream=name,
                    info=f"migrate:{src}->{dst}")

    def release(self, name: str) -> None:
        """A running stream finished (or failed): frees its slot."""
        self.running.discard(name)
        self._plan_keys.pop(name, None)
        metrics.set("fleet_running", len(self.running))

    @property
    def queued(self) -> list[str]:
        return [name for _, _, name in self._queue]
