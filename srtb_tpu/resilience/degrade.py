"""Graceful-degradation ladder: shed work before shedding data.

When the sink side cannot keep up (a slow disk, a flooded writer
pool), the reference's answer is lossy visualization taps and kernel
packet drops — the excess surfaces as loss at the *edges*.  The ladder
makes the middle of the pipeline degrade in a chosen order instead of
an arbitrary one:

- level 0 ``full``            everything runs;
- level 1 ``shed_waterfall``  waterfall dumps are withheld from sinks
  (the multi-GB .npy writes and GUI frames go first — diagnostics,
  not science data);
- level 2 ``shed_baseband``   sinks marked ``sheddable`` (the
  candidate/baseband writers) are skipped entirely;
- level 3 ``shed_segments``   whole segments are being dropped — the
  accounted drop-oldest loss of ``io.backpressure`` — and the ladder
  names the state.

Escalation is driven by the signals the engine already measures: sink
pressure (the engine had to *wait* on the sink — a full queue at push
or the whole in-flight window parked in the sink backlog — observed
as occupancy 1.0; a raw queue fraction otherwise) and whether
accounted segment loss is currently happening.  Sink pressure counts
only for real-time sources: degradation exists to preserve
*liveness*, and a file-mode run that throttles its reader losslessly
is behaving, not drowning (the engine passes occupancy 0 there).
Active loss escalates regardless of sink
occupancy — deliberately: segments_dropped only moves on engine-level
overload (drop-oldest or watchdog sheds, never receiver packet loss),
whole-segment loss is strictly worse than any shed dump, and withheld
waterfall/candidate output also frees the D2H transfer and writer
capacity every bottleneck shares; recovery likewise waits for loss to
stop, because un-degrading while segments are still being dropped
would trade science data for diagnostics.  Hysteresis (``hold``
consecutive observations above ``high`` / below ``low``) keeps one
slow flush from thrashing the ladder.  Every transition and every shed dump is a
Prometheus counter and a journal field (schema v3) — graceful
degradation that is not accounted is just silent loss with better
marketing.

This ladder is the SINK-side twin of the compute-side plan-demotion
ladder (resilience/demote.py): the two are independent state machines
over independent signals (sink backlog/loss here, device faults
there) and compose freely — a run can be shedding waterfalls at
degrade level 1 while computing on a demoted plan, and each journals
its own level (``degrade_level`` vs ``plan_ladder_level``).
"""

from __future__ import annotations

from srtb_tpu.utils import events
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

LEVELS = ("full", "shed_waterfall", "shed_baseband", "shed_segments")


class DegradationLadder:
    """Hysteretic escalation over ``LEVELS`` driven by per-drain
    observations of sink backlog and loss state."""

    def __init__(self, high: float = 0.9, low: float = 0.25,
                 hold: int = 3, stream: str = ""):
        if not 0.0 <= low < high <= 1.0:
            raise ValueError(f"need 0 <= low < high <= 1, got "
                             f"low={low} high={high}")
        self.high = float(high)
        self.low = float(low)
        self.hold = max(1, int(hold))
        self.level = 0
        self._above = 0
        self._below = 0
        # per-stream twin of the degrade_level gauge (multi-tenant
        # fleet): the flat gauge stays process-wide for solo runs,
        # the labeled one names the tenant
        self._labels = {"stream": stream} if stream else None
        self._set_gauge(0)

    def _set_gauge(self, level: int) -> None:
        metrics.set("degrade_level", level)
        if self._labels is not None:
            metrics.set("degrade_level", level, labels=self._labels)

    @classmethod
    def from_config(cls, cfg) -> "DegradationLadder":
        return cls(high=float(getattr(cfg, "degrade_queue_high", 0.9)),
                   low=float(getattr(cfg, "degrade_queue_low", 0.25)),
                   hold=int(getattr(cfg, "degrade_hold_segments", 3)),
                   stream=str(getattr(cfg, "stream_name", "") or ""))

    def observe(self, occupancy: float, loss_active: bool) -> int:
        """One per-drained-segment observation; returns the (possibly
        updated) level.  ``occupancy`` is the sink backlog fraction;
        ``loss_active`` is whether accounted segment loss happened in
        the recent window (level 3's defining signal)."""
        pressure = occupancy >= self.high or loss_active
        relief = occupancy <= self.low and not loss_active
        if pressure:
            self._above += 1
            self._below = 0
        elif relief:
            self._below += 1
            self._above = 0
        else:
            # between the thresholds: hold the current level
            self._above = self._below = 0
        if self._above >= self.hold and self.level < len(LEVELS) - 1:
            self.level += 1
            self._above = 0
            metrics.add("degrade_steps")
            events.emit("degrade",
                        stream=(self._labels or {}).get("stream"),
                        info=f"{LEVELS[self.level - 1]}->"
                             f"{LEVELS[self.level]}")
            log.warning(
                f"[degrade] sustained pressure (occupancy "
                f"{occupancy:.2f}, loss={loss_active}): stepping up to "
                f"level {self.level} ({LEVELS[self.level]})")
        elif self._below >= self.hold and self.level > 0:
            self.level -= 1
            self._below = 0
            metrics.add("degrade_recoveries")
            events.emit("degrade",
                        stream=(self._labels or {}).get("stream"),
                        info=f"{LEVELS[self.level + 1]}->"
                             f"{LEVELS[self.level]}")
            log.info(f"[degrade] pressure cleared: recovering to level "
                     f"{self.level} ({LEVELS[self.level]})")
        self._set_gauge(self.level)
        return self.level


class FleetShedPolicy:
    """Cross-stream fairness under fleet-wide sink pressure
    (pipeline/fleet.py): when the FLEET as a whole is drowning — a
    sustained fraction of lanes reporting sink pressure or active
    accounted loss — shed the lowest-priority REAL-TIME stream first
    (force its ladder to ``shed_segments``), instead of letting every
    tenant degrade a little and the overload land arbitrarily.

    Same hysteresis discipline as the per-stream ladder: ``hold``
    consecutive pressured observations shed one more stream (lowest
    priority first, name as tie-break for determinism); ``hold``
    consecutive relieved observations restore one (highest priority
    first).  File-mode streams throttle losslessly by design and are
    never shed (the per-stream ladder's real_time rule, applied
    fleet-wide).  Every transition is a counter with a ``stream``
    label — fleet shedding that is not attributable per tenant is
    just noisy-neighbor loss with better marketing."""

    def __init__(self, high: float = 0.9, low: float = 0.25,
                 hold: int = 3):
        if not 0.0 <= low < high <= 1.0:
            raise ValueError(f"need 0 <= low < high <= 1, got "
                             f"low={low} high={high}")
        self.high = float(high)
        self.low = float(low)
        self.hold = max(1, int(hold))
        self._above = 0
        self._below = 0
        self.shed: set[str] = set()

    @classmethod
    def from_config(cls, cfg) -> "FleetShedPolicy":
        return cls(high=float(getattr(cfg, "degrade_queue_high", 0.9)),
                   low=float(getattr(cfg, "degrade_queue_low", 0.25)),
                   hold=int(getattr(cfg, "degrade_hold_segments", 3)))

    def observe(self, pressure: float, loss_active: bool,
                lanes: list[tuple]) -> set[str]:
        """One fleet-scheduler observation.  ``pressure`` is the
        fraction of running lanes that waited on their sink since the
        last observation; ``lanes`` is [(name, priority, real_time)]
        — or [(name, priority, real_time, batched)] when the fleet
        runs cross-stream batching, or [(..., batched, device)] when
        it runs on a device pool (the label attributes shed/restore
        decisions per pool member) — for every RUNNING lane.  Returns
        the set of stream names currently force-shed (their lanes
        drop whole segments as accounted per-stream loss until
        restored).

        Batch-aware shed: within a priority band, an UNBATCHED lane
        sheds first — shedding a batch-group member also degrades its
        whole family (the formed batches thin out for every
        co-tenant), while shedding a solo lane costs one tenant.
        Restore order mirrors it (batched members come back first)."""
        lanes5 = [(e[0], e[1], e[2],
                   bool(e[3]) if len(e) > 3 else False,
                   e[4] if len(e) > 4 else None)
                  for e in lanes]
        live = {name for name, _, _, _, _ in lanes5}
        self.shed &= live  # finished lanes leave the shed set
        device_of = {name: dev for name, _, _, _, dev in lanes5}
        sheddable = sorted(
            ((prio, batched, name)
             for name, prio, rt, batched, _dev in lanes5
             if rt and name not in self.shed))
        restorable = sorted(
            ((prio, batched, name)
             for name, prio, _, batched, _dev in lanes5
             if name in self.shed), reverse=True)
        if pressure >= self.high or loss_active:
            self._above += 1
            self._below = 0
        elif pressure <= self.low and not loss_active:
            self._below += 1
            self._above = 0
        else:
            self._above = self._below = 0
        if self._above >= self.hold and sheddable:
            prio, _batched, name = sheddable[0]
            self.shed.add(name)
            self._above = 0
            metrics.add("fleet_sheds")
            metrics.add("fleet_sheds", labels={"stream": name})
            dev = device_of.get(name)
            events.emit("fleet.force_shed", trace=0, stream=name,
                        info=f"priority={prio}"
                        + (f" device={dev}" if dev else ""))
            log.warning(
                f"[fleet] sustained fleet pressure {pressure:.2f} "
                f"(loss={loss_active}): shedding lowest-priority "
                f"real-time stream {name!r} (priority {prio})")
        elif self._below >= self.hold and restorable:
            prio, _batched, name = restorable[0]
            self.shed.discard(name)
            self._below = 0
            metrics.add("fleet_restores")
            metrics.add("fleet_restores", labels={"stream": name})
            events.emit("fleet.restore", trace=0, stream=name,
                        info=f"priority={prio}")
            log.info(f"[fleet] pressure cleared: restoring stream "
                     f"{name!r} (priority {prio})")
        metrics.set("fleet_shed_streams", len(self.shed))
        return set(self.shed)
