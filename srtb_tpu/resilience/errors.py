"""Typed error taxonomy for the streaming runtime.

Every recovery decision in this package dispatches on one question:
*what kind of failure is this?*  Three categories cover the pipeline:

- ``TRANSIENT`` — the operation may succeed if simply re-run (an
  interrupted read, a momentarily unavailable socket, a stalled device
  fetch).  Retried with backoff by :mod:`srtb_tpu.resilience.retry`.
- ``DATA_LOSS`` — the operation can be re-run, but something was lost
  or corrupted on the way (a torn packet block, a corrupted buffer).
  Retried like a transient, and additionally accounted in the
  ``data_loss_total`` counter: loss must never be silent.
- ``FATAL`` — retrying cannot help (programming errors, explicit
  escalations).  Propagates to a clean shutdown.
- ``DEVICE`` — the *accelerator side* failed in a way plain retry
  cannot fix but the self-healing compute ladder can: an XLA
  ``RESOURCE_EXHAUSTED``/OOM (re-running the identical program OOMs
  identically — a cheaper plan may not), a Pallas/Mosaic or XLA
  compile/lowering failure (same program recompiles to the same
  failure — a different plan family lowers differently), or a
  device halt mid-run (nothing dispatched to the dead handle can
  succeed — a reinitialized backend can).  Never retried by
  :mod:`srtb_tpu.resilience.retry`; handled by the plan-demotion /
  device-reinit machinery in :mod:`srtb_tpu.resilience.demote` and
  ``pipeline/runtime.py``, which escalates to FATAL when its budget
  (ladder rungs, ``device_reinit_max``) is spent.

Unknown exceptions default to FATAL: retrying an unclassified failure
hides bugs, and the reference's fail-loudly philosophy
(ref: util/termination_handler.hpp) applies whenever we cannot argue
the retry is safe.

Device-fault *kind* classification (:func:`classify_device`) works
from the real exception strings jax raises — ``XlaRuntimeError``
status prefixes (``RESOURCE_EXHAUSTED:``, ``INTERNAL: Mosaic
failed...``, ``INTERNAL: Accelerator device halted...``) — because the
runtime's failures arrive as opaque ``jaxlib`` types, not as anything
this package can subclass.  Typed :class:`DeviceFault` subclasses
exist for code that *knows* what happened (fault injection, tests).
"""

from __future__ import annotations

import errno

TRANSIENT = "transient"
FATAL = "fatal"
DATA_LOSS = "data_loss"
DEVICE = "device"

# device-fault kinds, ordered from cheapest recovery to heaviest:
# oom/compile demote the plan, halt reinitializes the backend
DEVICE_OOM = "oom"
DEVICE_COMPILE = "compile"
DEVICE_HALT = "halt"
DEVICE_KINDS = (DEVICE_OOM, DEVICE_COMPILE, DEVICE_HALT)


class PipelineError(Exception):
    """Base of the typed taxonomy; ``category`` drives every retry /
    restart / escalation decision."""

    category = FATAL


class TransientError(PipelineError):
    """Retryable: re-running the operation may succeed."""

    category = TRANSIENT


class FatalError(PipelineError):
    """Not retryable: escalate to a clean shutdown."""

    category = FATAL


class DataLossError(PipelineError):
    """Retryable, but data was lost/corrupted — the occurrence is
    accounted (``data_loss_total``) even when the retry succeeds."""

    category = DATA_LOSS


class SegmentTimeout(TransientError):
    """An in-flight segment exceeded the deadline (fetch never became
    ready); the watchdog cancels and re-dispatches it."""


class WatchdogEscalation(FatalError):
    """A segment stayed wedged through every allowed requeue."""


class RestartBudgetExceeded(FatalError):
    """A supervised worker crashed more times than its restart budget
    allows within the window."""


class DeviceFault(PipelineError):
    """A compute-side failure the self-healing ladder may recover:
    ``kind`` is one of :data:`DEVICE_KINDS` and selects the recovery
    (demote for oom/compile, reinit for halt)."""

    category = DEVICE
    kind = DEVICE_HALT


class DeviceOOM(DeviceFault):
    """XLA ``RESOURCE_EXHAUSTED``: the plan's HBM footprint does not
    fit — re-running it verbatim OOMs again; a demoted plan may fit."""

    kind = DEVICE_OOM


class CompileFault(DeviceFault):
    """A compile/lowering failure (Mosaic, XLA): deterministic for the
    same program, so the recovery is a different plan family."""

    kind = DEVICE_COMPILE


class DeviceHalt(DeviceFault):
    """The device halted / the runtime handle died mid-run: every
    in-flight program is suspect; recovery is a backend reinit."""

    kind = DEVICE_HALT


class LadderExhausted(FatalError):
    """A device fault persisted through every demotion rung."""


class ReinitBudgetExceeded(FatalError):
    """The device kept halting past ``device_reinit_max`` reinits in
    the window — a flapping accelerator escalates, never flaps
    forever."""


# errnos that indicate a momentary condition, not a broken system
_TRANSIENT_ERRNOS = frozenset(
    e for e in (
        getattr(errno, name, None)
        for name in ("EINTR", "EAGAIN", "EWOULDBLOCK", "EBUSY",
                     "ENOBUFS", "ETIMEDOUT", "ECONNRESET",
                     "ECONNREFUSED", "ENETUNREACH", "EHOSTUNREACH"))
    if e is not None)


# --- device-fault classification from the strings jax actually raises.
# Matching is gated on the exception TYPE being XLA-runtime-shaped
# (see _is_xla_exception): "RESOURCE_EXHAUSTED" inside a ValueError
# from user code must stay FATAL, not turn into a plan demotion.

# RESOURCE_EXHAUSTED status + the allocator phrasings of the CPU/GPU/
# TPU backends ("Out of memory while trying to allocate ...",
# "Program hbm requirement ... exceeds HBM capacity")
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory",
                "out of memory", "exceeds HBM capacity",
                "Attempting to allocate")
# Mosaic/XLA compile + lowering failures ("INTERNAL: Mosaic failed to
# compile TPU kernel", "Compilation failure", UNIMPLEMENTED lowerings)
_COMPILE_MARKERS = ("Mosaic failed", "Compilation failure",
                    "compilation failed", "failed to compile",
                    "Failed to lower", "lowering failed",
                    "UNIMPLEMENTED", "Unsupported HLO")
# mid-run death of the device / runtime handle ("INTERNAL: Accelerator
# device halted prematurely...", aborted streams, dead executables)
_HALT_MARKERS = ("device halted", "halted prematurely", "ABORTED",
                 "DATA_LOSS", "Device or handle", "device is in an",
                 "failed to enqueue", "Stream is in an error state",
                 "executable has been deleted", "backend was destroyed")

# exception type names that ARE compile failures wherever they appear
# (jax raises these from its own lowering paths, no status prefix)
_COMPILE_TYPE_NAMES = ("MosaicError", "LoweringError",
                       "XlaCompileError", "VerificationError")


def _is_xla_exception(exc: BaseException) -> bool:
    """Whether ``exc`` is the accelerator runtime speaking: jaxlib's
    ``XlaRuntimeError`` (matched by name — the concrete class moved
    between jaxlib releases) or any exception raised from jax/jaxlib
    internals."""
    for klass in type(exc).__mro__:
        if klass.__name__ == "XlaRuntimeError":
            return True
        mod = getattr(klass, "__module__", "") or ""
        if mod.startswith(("jaxlib", "jax.")) or mod == "jax":
            return True
    return False


def classify_device(exc: BaseException) -> str | None:
    """Device-fault kind of ``exc`` (:data:`DEVICE_KINDS`), or None
    when it is not a device fault.  Typed :class:`DeviceFault`
    subclasses carry their kind; real jax/jaxlib exceptions are
    classified from their status strings (OOM checked first: a TPU OOM
    message can mention compilation context, but RESOURCE_EXHAUSTED is
    the authoritative status)."""
    if isinstance(exc, DeviceFault):
        return exc.kind
    if isinstance(exc, PipelineError):
        return None  # other typed errors already chose their category
    name = type(exc).__name__
    if any(t in name for t in _COMPILE_TYPE_NAMES):
        return DEVICE_COMPILE
    if not _is_xla_exception(exc):
        return None
    msg = str(exc)
    if any(m in msg for m in _OOM_MARKERS):
        return DEVICE_OOM
    if any(m in msg for m in _COMPILE_MARKERS):
        return DEVICE_COMPILE
    if any(m in msg for m in _HALT_MARKERS):
        return DEVICE_HALT
    return None


def classify(exc: BaseException) -> str:
    """Map any exception to a taxonomy category.

    Typed :class:`PipelineError` subclasses carry their own category;
    accelerator-runtime failures with a recognized device-fault
    signature are DEVICE (handled by the self-healing ladder, not
    retried); the stdlib's momentary-condition types (timeouts,
    interrupted syscalls, connection churn) are transient; everything
    else — including plain programming errors and unrecognized XLA
    errors — is FATAL, because retrying an unclassified failure hides
    bugs instead of surviving faults."""
    if isinstance(exc, PipelineError):
        return exc.category
    if classify_device(exc) is not None:
        return DEVICE
    if isinstance(exc, (TimeoutError, InterruptedError,
                        BlockingIOError, ConnectionError)):
        return TRANSIENT
    if isinstance(exc, OSError) and exc.errno in _TRANSIENT_ERRNOS:
        return TRANSIENT
    return FATAL
