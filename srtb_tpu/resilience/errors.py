"""Typed error taxonomy for the streaming runtime.

Every recovery decision in this package dispatches on one question:
*what kind of failure is this?*  Three categories cover the pipeline:

- ``TRANSIENT`` — the operation may succeed if simply re-run (an
  interrupted read, a momentarily unavailable socket, a stalled device
  fetch).  Retried with backoff by :mod:`srtb_tpu.resilience.retry`.
- ``DATA_LOSS`` — the operation can be re-run, but something was lost
  or corrupted on the way (a torn packet block, a corrupted buffer).
  Retried like a transient, and additionally accounted in the
  ``data_loss_total`` counter: loss must never be silent.
- ``FATAL`` — retrying cannot help (programming errors, resource
  exhaustion, explicit escalations).  Propagates to a clean shutdown.

Unknown exceptions default to FATAL: retrying an unclassified failure
hides bugs, and the reference's fail-loudly philosophy
(ref: util/termination_handler.hpp) applies whenever we cannot argue
the retry is safe.
"""

from __future__ import annotations

import errno

TRANSIENT = "transient"
FATAL = "fatal"
DATA_LOSS = "data_loss"


class PipelineError(Exception):
    """Base of the typed taxonomy; ``category`` drives every retry /
    restart / escalation decision."""

    category = FATAL


class TransientError(PipelineError):
    """Retryable: re-running the operation may succeed."""

    category = TRANSIENT


class FatalError(PipelineError):
    """Not retryable: escalate to a clean shutdown."""

    category = FATAL


class DataLossError(PipelineError):
    """Retryable, but data was lost/corrupted — the occurrence is
    accounted (``data_loss_total``) even when the retry succeeds."""

    category = DATA_LOSS


class SegmentTimeout(TransientError):
    """An in-flight segment exceeded the deadline (fetch never became
    ready); the watchdog cancels and re-dispatches it."""


class WatchdogEscalation(FatalError):
    """A segment stayed wedged through every allowed requeue."""


class RestartBudgetExceeded(FatalError):
    """A supervised worker crashed more times than its restart budget
    allows within the window."""


# errnos that indicate a momentary condition, not a broken system
_TRANSIENT_ERRNOS = frozenset(
    e for e in (
        getattr(errno, name, None)
        for name in ("EINTR", "EAGAIN", "EWOULDBLOCK", "EBUSY",
                     "ENOBUFS", "ETIMEDOUT", "ECONNRESET",
                     "ECONNREFUSED", "ENETUNREACH", "EHOSTUNREACH"))
    if e is not None)


def classify(exc: BaseException) -> str:
    """Map any exception to a taxonomy category.

    Typed :class:`PipelineError` subclasses carry their own category;
    the stdlib's momentary-condition types (timeouts, interrupted
    syscalls, connection churn) are transient; everything else —
    including plain programming errors — is FATAL, because retrying an
    unclassified failure hides bugs instead of surviving faults."""
    if isinstance(exc, PipelineError):
        return exc.category
    if isinstance(exc, (TimeoutError, InterruptedError,
                        BlockingIOError, ConnectionError)):
        return TRANSIENT
    if isinstance(exc, OSError) and exc.errno in _TRANSIENT_ERRNOS:
        return TRANSIENT
    return FATAL
