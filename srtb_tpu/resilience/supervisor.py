"""Bounded-restart supervision for crashed workers.

Today a crashed ``framework.Pipe`` worker (the sink drain, the GUI
server thread) propagates its exception and kills the run — correct
for bugs, wasteful for a momentary failure eight hours into an
observation.  A :class:`Supervisor` gives each supervised component a
restart budget: crashes classified transient (or data-loss) are
restarted while the budget inside the sliding window lasts; fatal
crashes and exhausted budgets escalate to the clean-shutdown path the
runtime already has.  The same budget machinery bounds the
self-healing compute ladder's device reinits (resilience/demote.py:
the "device_reinit" supervisor — device-classified faults are not
FATAL, so they restart within budget like transients): a flapping
accelerator escalates exactly like a flapping sink pipe.

Every restart is accounted: ``worker_restarts`` plus a per-component
counter, and the journal's v3 ``restarts`` field — a pipeline that is
quietly restarting its sink every minute must be visible on /metrics.
"""

from __future__ import annotations

import collections
import time

from srtb_tpu.resilience.errors import FATAL, classify
from srtb_tpu.utils import events
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics


class Supervisor:
    """Restart-budget bookkeeping for one named component.

    ``should_restart(exc)`` is the whole protocol: the owner of the
    worker calls it when the worker dies; True means "spawn a
    replacement" (the restart is counted), False means "escalate"
    (fatal crash, or budget exhausted within ``window_s``).

    ``restart_fatal=True`` restarts regardless of classification —
    for best-effort components like the GUI server whose death must
    never take the observation down with it.

    ``counter`` names the metrics counter an approved restart bumps
    (plus its ``<counter>_<name>`` variant).  Pass None for budget
    bookkeeping that is accounted elsewhere — the device-reinit
    supervisor counts under ``device_reinits``, and bumping
    ``worker_restarts`` too would journal phantom worker-thread
    restarts for a run whose workers never crashed.
    """

    def __init__(self, name: str, max_restarts: int = 3,
                 window_s: float = 60.0, restart_fatal: bool = False,
                 clock=time.monotonic,
                 counter: str | None = "worker_restarts"):
        self.name = name
        self.max_restarts = int(max_restarts)
        self.window_s = float(window_s)
        self.restart_fatal = restart_fatal
        self.counter = counter
        self._clock = clock
        self._restarts: collections.deque[float] = collections.deque()

    @property
    def restarts(self) -> int:
        return len(self._restarts)

    def remaining(self) -> int:
        """Restart budget left inside the current window — advisory
        (e.g. the fleet's /healthz reports how many fleet-wide
        reinits the no-peer fallback still has); the authoritative
        check stays ``should_restart``."""
        now = self._clock()
        while self._restarts and \
                now - self._restarts[0] > self.window_s:
            self._restarts.popleft()
        return max(0, self.max_restarts - len(self._restarts))

    def should_restart(self, exc: BaseException) -> bool:
        if not self.restart_fatal and classify(exc) == FATAL:
            log.error(f"[supervisor] {self.name}: fatal {exc!r}; "
                      "escalating (not restartable)")
            return False
        now = self._clock()
        while self._restarts and now - self._restarts[0] > self.window_s:
            self._restarts.popleft()
        if len(self._restarts) >= self.max_restarts:
            log.error(
                f"[supervisor] {self.name}: {exc!r} — restart budget "
                f"exhausted ({self.max_restarts} in {self.window_s:g}s);"
                " escalating to clean shutdown")
            return False
        self._restarts.append(now)
        if self.counter:
            metrics.add(self.counter)
            metrics.add(f"{self.counter}_{self.name}")
        events.emit("supervisor.restart",
                    info=f"{self.name}:{len(self._restarts)}")
        log.warning(
            f"[supervisor] {self.name}: crashed with {exc!r}; "
            f"restarting ({len(self._restarts)}/{self.max_restarts} "
            f"in window)")
        return True
