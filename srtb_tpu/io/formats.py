"""Baseband packet-format registry.

Mirrors the reference's compile-time backend descriptors
(ref: io/backend_registry.hpp:36-181) as plain dataclass instances:
per-format header size, payload size, counter parser, data-stream count and
the matching unpack routine.  The VDIF header bit-field layout follows
io/vdif_header.hpp:28-61 exactly.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Callable, NamedTuple


class VdifHeader(NamedTuple):
    """VDIF data-frame header (8 little-endian 32-bit words)
    (ref: io/vdif_header.hpp:28-61; https://vlbi.org/vlbi-standards/vdif/)."""
    seconds_from_ref_epoch: int
    legacy_mode: int
    invalid_data: int
    data_frame_count_in_second: int
    reference_epoch: int
    unassigned: int
    data_frame_length: int
    log2_channels: int
    vdif_version: int
    station_id: int
    thread_id: int
    bits_per_sample_minus_1: int
    data_type: int
    extended_user_data_1: int
    extended_data_version: int
    extended_user_data_2: int
    extended_user_data_3: int
    extended_user_data_4: int


def parse_vdif_header(buf: bytes) -> VdifHeader:
    w = struct.unpack_from("<8I", buf)
    return VdifHeader(
        seconds_from_ref_epoch=w[0] & 0x3FFFFFFF,
        legacy_mode=(w[0] >> 30) & 1,
        invalid_data=(w[0] >> 31) & 1,
        data_frame_count_in_second=w[1] & 0xFFFFFF,
        reference_epoch=(w[1] >> 24) & 0x3F,
        unassigned=(w[1] >> 30) & 0x3,
        data_frame_length=w[2] & 0xFFFFFF,
        log2_channels=(w[2] >> 24) & 0x1F,
        vdif_version=(w[2] >> 29) & 0x7,
        station_id=w[3] & 0xFFFF,
        thread_id=(w[3] >> 16) & 0x3FF,
        bits_per_sample_minus_1=(w[3] >> 26) & 0x1F,
        data_type=(w[3] >> 31) & 1,
        extended_user_data_1=w[4] & 0xFFFFFF,
        extended_data_version=(w[4] >> 24) & 0xFF,
        extended_user_data_2=w[5],
        extended_user_data_3=w[6],
        extended_user_data_4=w[7],
    )


def _parse_counter_le64(packet: bytes) -> tuple[int, int]:
    """First 8 bytes little-endian as (counter, timestamp)
    (ref: backend_registry.hpp:63-73)."""
    counter = struct.unpack_from("<Q", packet)[0]
    return counter, counter


def _parse_counter_vdif(packet: bytes) -> tuple[int, int]:
    """VDIF words 6 & 7 form the u64 counter
    (ref: backend_registry.hpp:129-152)."""
    w6, w7 = struct.unpack_from("<2I", packet, 6 * 4)
    counter = w6 | (w7 << 32)
    return counter, counter


@dataclass(frozen=True)
class PacketFormat:
    name: str
    data_stream_count: int
    packet_header_size: int
    packet_payload_size: int  # total packet size incl. header, as the ref
    parse_packet: Callable[[bytes], tuple[int, int]] | None
    unpack_variant: str  # key into ops.unpack dispatch (see pipeline.segment)

    @property
    def payload_bytes(self) -> int:
        return self.packet_payload_size - self.packet_header_size


# ref: backend_registry.hpp:36-39
SIMPLE = PacketFormat("simple", 1, 0, 0, None, "simple")
# ref: backend_registry.hpp:54-74
FASTMB_ROACH2 = PacketFormat("fastmb_roach2", 1, 8, 4104,
                             _parse_counter_le64, "simple")
# ref: backend_registry.hpp:86-92; "1122" pair interleave
NAOCPSR_SNAP1 = PacketFormat("naocpsr_snap1", 2, 8, 4104,
                             _parse_counter_le64, "naocpsr_snap1")
# ref: backend_registry.hpp:110-153; current version has 2 streams,
# word-interleaved "1212" groups of 4 samples
GZNUPSR_A1 = PacketFormat("gznupsr_a1", 2, 64, 8256,
                          _parse_counter_vdif, "gznupsr_a1_v2_1")
# original 4-stream gznupsr_a1 variant (ref: unpack.hpp:291-328,
# backend_registry.hpp:112 "was 4 in original version")
GZNUPSR_A1_V1 = PacketFormat("gznupsr_a1_v1", 4, 64, 8256,
                             _parse_counter_vdif, "gznupsr_a1")
# byte-interleaved 2-polarization file input, e.g. cpsr2 ("1212")
# (ref: unpack_pipe.hpp:146-260 unpack_interleaved_samples_2_pipe)
INTERLEAVED_SAMPLES_2 = PacketFormat("interleaved_samples_2", 2, 0, 0,
                                     None, "interleaved_samples_2")

_REGISTRY = {f.name: f for f in
             (SIMPLE, FASTMB_ROACH2, NAOCPSR_SNAP1, GZNUPSR_A1,
              GZNUPSR_A1_V1, INTERLEAVED_SAMPLES_2)}
_ALIASES = {"naocpsr_roach2": "fastmb_roach2"}  # ref: backend_registry.hpp:176-181


def resolve(name: str) -> PacketFormat:
    name = _ALIASES.get(name, name)
    if name not in _REGISTRY:
        raise ValueError(f"[backend_registry] unknown backend name {name!r}")
    return _REGISTRY[name]


def get_data_stream_count(name: str) -> int:
    return resolve(name).data_stream_count
