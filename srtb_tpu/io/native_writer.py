"""Asynchronous writer pool.

Python-side interface over the native C++ writer-thread pool
(``srtb_tpu/native/file_writer.cpp``, built to ``libsrtb_writer.so``), with
a pure-Python daemon-thread pool fallback implementing the same
(path, bytes, fsync) job semantics.

The reference writes candidates asynchronously from two
boost::asio::thread_pools so the pipeline never blocks on disk — baseband
``.bin`` blobs are fdatasync'd, spectrum ``.npy``/``.tim`` files are not
(ref: pipeline/write_signal_pipe.hpp:159-280).  An ``AsyncWriterPool`` is
the srtb_tpu equivalent: submission copies the payload so the caller can
reuse its buffer immediately; ``drain()`` blocks until everything queued
has hit the filesystem.
"""

from __future__ import annotations

import ctypes
import os
import queue
import threading
import weakref
from concurrent.futures import Future

import numpy as np

from srtb_tpu.utils import termination
from srtb_tpu.utils.logging import log

_LIB_PATH = os.path.join(os.path.dirname(__file__), "..", "native",
                         "libsrtb_writer.so")


def _load_native():
    try:
        lib = ctypes.CDLL(os.path.abspath(_LIB_PATH))
    except OSError:
        return None
    lib.srtb_writer_create.restype = ctypes.c_void_p
    lib.srtb_writer_create.argtypes = [ctypes.c_int32, ctypes.c_uint64]
    lib.srtb_writer_submit.restype = ctypes.c_int32
    lib.srtb_writer_submit.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32]
    lib.srtb_writer_drain.argtypes = [ctypes.c_void_p]
    for name in ("srtb_writer_jobs_done", "srtb_writer_bytes_written",
                 "srtb_writer_errors"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_uint64
        fn.argtypes = [ctypes.c_void_p]
    lib.srtb_writer_destroy.argtypes = [ctypes.c_void_p]
    return lib


_NATIVE = _load_native()


class _DaemonWriterPool:
    """Minimal Future-based thread pool with DAEMON workers, lazily
    spawned on first submit (like the executor it replaces).

    ``concurrent.futures`` executors use non-daemon threads, which
    ``threading._shutdown`` joins at interpreter exit no matter what —
    dropping them from that module's own exit registry only skips *its*
    join, so a wedged write abandoned by ``close(drain=False)`` would
    still hang process exit.  Daemon workers actually die with the
    process; a ``weakref.finalize`` in ``AsyncWriterPool`` (mirroring
    the native pool's) keeps the flush-at-exit behavior for pools that
    are never explicitly closed."""

    def __init__(self, n_threads: int, name_prefix: str = "srtb-writer"):
        self.n_threads = n_threads
        self.name_prefix = name_prefix
        self._jobs: queue.SimpleQueue = queue.SimpleQueue()
        self._threads: list[threading.Thread] = []

    def _work(self):
        while True:
            job = self._jobs.get()
            if job is None:
                return
            fut, fn, args = job
            if not fut.set_running_or_notify_cancel():
                continue  # cancelled while still queued
            try:
                fut.set_result(fn(*args))
            except BaseException as e:  # noqa: BLE001 - delivered via result()
                fut.set_exception(e)

    def submit(self, fn, *args) -> Future:
        if not self._threads:  # lazy spawn; callers serialize submits
            self._threads = [
                threading.Thread(target=self._work, daemon=True,
                                 name=f"{self.name_prefix}_{i}")
                for i in range(self.n_threads)]
            for t in self._threads:
                termination.tag_thread(t)
                t.start()
        fut = Future()
        self._jobs.put((fut, fn, args))
        return fut

    def shutdown(self, wait: bool = True,
                 cancel_futures: bool = False) -> None:
        if cancel_futures:
            while True:
                try:
                    job = self._jobs.get_nowait()
                except queue.Empty:
                    break
                if job is not None:
                    job[0].cancel()
        for _ in self._threads:
            self._jobs.put(None)
        if wait:
            for t in self._threads:
                t.join()


def native_available() -> bool:
    return _NATIVE is not None


class AsyncWriterPool:
    """Thread-pool writer for (path, bytes, fsync, append) jobs.

    Uses the native C++ pool when ``libsrtb_writer.so`` is built (run
    ``make -C srtb_tpu/native``), otherwise a Python daemon-thread pool
    with identical semantics.
    """

    DEFAULT_MAX_QUEUED_BYTES = 1 << 30  # 1 GiB of queued payload copies

    def __init__(self, n_threads: int = 2, prefer_native: bool = True,
                 max_queued_bytes: int | None = None):
        self.n_threads = max(1, n_threads)
        if max_queued_bytes is None:
            max_queued_bytes = self.DEFAULT_MAX_QUEUED_BYTES
        self.max_queued_bytes = max_queued_bytes
        self._lock = threading.Lock()
        self._space = threading.Condition(self._lock)
        self._queued_bytes = 0
        self._errors_raised = 0
        self._py_errors = 0
        self._py_jobs = 0
        self._py_bytes = 0
        # native pool only: manifest commit callbacks deferred to the
        # drain barrier (see submit); _done_err_base is the error
        # count the pending batch started from
        self._pending_done: list = []
        self._done_err_base = 0
        if prefer_native and _NATIVE is not None:
            self._lib = _NATIVE
            self._h = self._lib.srtb_writer_create(self.n_threads,
                                                   max_queued_bytes)
            self._pool = None
            if not self._h:
                raise MemoryError("srtb_writer_create failed")
            # drain+destroy the native pool even if close() is never
            # called (srtb_writer_destroy joins the C++ threads)
            self._finalizer = weakref.finalize(
                self, self._lib.srtb_writer_destroy, self._h)
        else:
            self._lib = None
            self._h = None
            self._pool = _DaemonWriterPool(self.n_threads)
            self._futures = []
            # flush-at-exit / at-GC for pools never close()d, like the
            # native pool's drain+destroy finalizer (queued jobs finish
            # before the sentinel; daemon workers would otherwise die
            # mid-queue with the process)
            self._finalizer = weakref.finalize(self, self._pool.shutdown)

    @property
    def is_native(self) -> bool:
        return self._h is not None

    # ------------------------------------------------------------------

    def submit(self, path: str, data, *, fsync: bool = False,
               append: bool = False, on_done=None,
               pre_publish=None) -> None:
        """Queue one write. ``data`` is bytes or a numpy array; it is
        copied at submission, so the caller may reuse its buffer.

        ``append`` requires a single-thread pool: with more workers the
        append order would be nondeterministic.

        ``on_done`` (the manifest commit hook, io/manifest.py) fires
        after the write durably landed: the Python pool calls it from
        the worker thread right after the successful atomic rename /
        append; the native C++ pool has no per-job completion hook, so
        callbacks are deferred to the next ``drain()`` barrier.  When
        that drain observed new write errors, the native counter
        cannot say WHICH job failed — so each pending ATOMIC job is
        attributed through the filesystem instead (the C++ pool's
        temp+rename is all-or-nothing: the final file exists at the
        submitted size iff the job succeeded) and commits fire only
        for verified jobs; append commits in an errored batch are
        dropped wholesale (a failed append can leave partial bytes a
        later append papers over, so per-range verification is
        unsound — the committed-prefix truncation heals them on
        resume).  An uncommitted-but-written artifact is rolled back
        and regenerated on resume; a committed-but-failed one would be
        silent loss — every ambiguity errs on the recoverable side.

        ``pre_publish`` (the manifest's publish barrier,
        ``RunManifest.sync``) runs between the worker's temp write and
        its atomic rename on the Python pool; the native C++ pool
        renames in C++, so the barrier runs AT SUBMIT instead — the
        intent is durable before the job exists."""
        if append and self.n_threads > 1:
            raise ValueError(
                "append=True needs n_threads=1 (ordered appends)")
        buf = np.ascontiguousarray(data).view(np.uint8).reshape(-1) \
            if isinstance(data, np.ndarray) else \
            np.frombuffer(bytes(data), dtype=np.uint8)
        if self._h is not None:
            if pre_publish is not None:
                pre_publish()
            ptr = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
            rc = self._lib.srtb_writer_submit(
                self._h, path.encode(), ptr, buf.size,
                1 if fsync else 0, 1 if append else 0)
            if rc != 0:
                raise RuntimeError(f"srtb_writer_submit failed for {path}")
            if on_done is not None:
                with self._lock:
                    self._pending_done.append(
                        (on_done, path, int(buf.size), append))
            return
        payload = buf.tobytes()  # copy-at-submit, like the native pool
        with self._space:
            # backpressure: bound the RAM held by queued copies (oversized
            # payloads wait for an empty queue)
            if self.max_queued_bytes > 0:
                self._space.wait_for(
                    lambda: (self._queued_bytes + len(payload)
                             <= self.max_queued_bytes)
                    or self._queued_bytes == 0)
            self._queued_bytes += len(payload)
            # prune cleanly-completed futures so a long checkpoint-less run
            # doesn't accumulate them until the final drain; keep failed
            # ones so drain() can still surface their exception
            self._futures = [f for f in self._futures
                             if not f.done() or f.exception() is not None]
            fut = self._pool.submit(self._py_write, path, payload, fsync,
                                    append, on_done, pre_publish)
            self._futures.append(fut)

    def _py_write(self, path: str, payload: bytes, fsync: bool,
                  append: bool, on_done=None, pre_publish=None) -> None:
        # accounting must run for ANY exception type, or the backpressure
        # window shrinks permanently and later submits block forever
        ok = False
        try:
            if append:
                with open(path, "ab") as f:
                    f.write(payload)
                    f.flush()
                    if fsync:
                        os.fdatasync(f.fileno())
            else:
                # crash-consistent like the synchronous writer path
                # (shared helper: temp + flush (+ fdatasync) + atomic
                # rename, torn temp dropped on failure) so a worker
                # dying mid-write leaves an orphan temp (swept at
                # startup by io.writers.recover_orphan_temps), not a
                # torn file.  Appends stay in-place by nature.
                from srtb_tpu.io.writers import atomic_write
                atomic_write(path, payload, fsync=fsync,
                             pre_rename=pre_publish)
            # manifest commit, only once the bytes durably landed; a
            # failing commit (the WAL append itself errored) leaves
            # the artifact uncommitted — rolled back + regenerated on
            # resume, never silently trusted
            if on_done is not None:
                on_done()
            ok = True
        except OSError:
            # counted below; surfaced via raise_new_errors().  Anything
            # non-OSError (MemoryError, a bad payload) propagates to
            # the future instead.
            pass
        finally:
            with self._space:
                self._py_jobs += 1
                if ok:
                    self._py_bytes += len(payload)
                else:
                    self._py_errors += 1
                self._queued_bytes -= len(payload)
                self._space.notify_all()

    # ------------------------------------------------------------------

    def drain(self) -> None:
        """Block until every submitted job has been written (or failed)."""
        if self._h is not None:
            self._lib.srtb_writer_drain(self._h)
            with self._lock:
                pending, self._pending_done = self._pending_done, []
                errors = int(self._lib.srtb_writer_errors(self._h))
                base, self._done_err_base = self._done_err_base, errors
            if pending:
                if errors > base:
                    # per-job attribution through the filesystem (see
                    # submit): atomic jobs verify final-file size,
                    # append commits drop wholesale
                    fired = dropped = 0
                    for cb, path, size, append in pending:
                        ok = False
                        if not append:
                            try:
                                ok = os.path.getsize(path) == size
                            except OSError:
                                ok = False
                        if ok:
                            cb()
                            fired += 1
                        else:
                            dropped += 1
                    log.warning(
                        f"[writer_pool] {errors - base} native write "
                        f"error(s) in this drain: {fired} commit(s) "
                        f"verified on disk, {dropped} dropped "
                        "(uncommitted artifacts regenerate on resume)")
                else:
                    for cb, _path, _size, _append in pending:
                        cb()
            return
        with self._lock:
            futures, self._futures = self._futures, []
        for fut in futures:
            fut.result()

    def raise_new_errors(self, context: str) -> None:
        """Raise if writes failed since the last call.  The counter is
        pool-wide: with several sinks sharing one pool, whichever drains
        first reports the failure (with its own context string)."""
        errors = self.stats()["errors"]
        new_errors = errors - self._errors_raised
        self._errors_raised = errors
        if new_errors:
            raise RuntimeError(
                f"{new_errors} async write(s) failed ({context})")

    def stats(self) -> dict:
        if self._h is not None:
            return {
                "jobs_done": self._lib.srtb_writer_jobs_done(self._h),
                "bytes_written": self._lib.srtb_writer_bytes_written(self._h),
                "errors": self._lib.srtb_writer_errors(self._h),
            }
        with self._lock:
            return {"jobs_done": self._py_jobs,
                    "bytes_written": self._py_bytes,
                    "errors": self._py_errors}

    def close(self, drain: bool = True) -> None:
        """``drain=False`` abandons queued/stuck writes instead of
        waiting for them: the bounded-shutdown path uses it when a
        writer is known-wedged (e.g. an NFS-stalled write) — waiting
        would hang exactly the shutdown the caller just bounded.  The
        native pool is deliberately leaked in that case (its destroy
        joins the stuck C++ threads); the Python pool's workers are
        left to die with the process."""
        if self._h is not None:
            if drain:
                if self._pending_done:
                    self.drain()  # fire deferred manifest commits
                self._finalizer()  # idempotent drain + destroy
            else:
                self._finalizer.detach()
                log.warning("[writer_pool] abandoning native pool "
                            "without drain (wedged writes)")
            self._h = None
        elif self._pool is not None:
            if drain:
                self.drain()
                self._finalizer()  # idempotent sentinel + join
            else:
                # cancel still-queued jobs (idle workers exit on the
                # sentinel) and let the DAEMON workers die with the
                # process: a wedged write must not hang the very
                # shutdown this path exists to bound
                self._finalizer.detach()
                self._pool.shutdown(wait=False, cancel_futures=True)
                log.warning("[writer_pool] abandoning queued writes "
                            "without drain (wedged writes)")
            self._pool = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
