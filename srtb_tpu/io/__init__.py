from srtb_tpu.io import formats, file_input, writers  # noqa: F401
