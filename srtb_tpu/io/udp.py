"""UDP baseband ingest.

Python-side interface over the native C++ receiver
(``srtb_tpu/native/udp_receiver.cpp``, built to ``libsrtb_udp.so``), with a
pure-Python socket fallback implementing the same block-assembly semantics
(counter placement, reorder tolerance within a block, zero-fill of lost
packets with loss accounting — ref: io/udp/udp_receiver.hpp:180-272).

``UdpReceiverSource`` is the equivalent of udp_receiver_pipe
(ref: pipeline/udp_receiver_pipe.hpp): one receiver per (address, port)
pair, each yielding full segments stamped with timestamp and first packet
counter.
"""

from __future__ import annotations

import collections
import ctypes
import os
import socket
import struct
import threading
import time

import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.io import formats
from srtb_tpu.pipeline.work import SegmentWork
from srtb_tpu.utils import termination
from srtb_tpu.utils.metrics import metrics
from srtb_tpu.utils.logging import log

COUNTER_LE64 = 0
COUNTER_VDIF67 = 1

_LIB_PATH = os.path.join(os.path.dirname(__file__), "..", "native",
                         "libsrtb_udp.so")


def _load_native():
    try:
        lib = ctypes.CDLL(os.path.abspath(_LIB_PATH))
    except OSError:
        return None
    lib.srtb_udp_rx_create.restype = ctypes.c_void_p
    lib.srtb_udp_rx_create.argtypes = [
        ctypes.c_char_p, ctypes.c_uint16, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_int32, ctypes.c_int64]
    lib.srtb_udp_rx_receive_block.restype = ctypes.c_int32
    lib.srtb_udp_rx_receive_block.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.srtb_udp_rx_total_packets.restype = ctypes.c_uint64
    lib.srtb_udp_rx_total_packets.argtypes = [ctypes.c_void_p]
    lib.srtb_udp_rx_lost_packets.restype = ctypes.c_uint64
    lib.srtb_udp_rx_lost_packets.argtypes = [ctypes.c_void_p]
    lib.srtb_udp_rx_destroy.argtypes = [ctypes.c_void_p]
    lib.srtb_set_thread_affinity.restype = ctypes.c_int32
    lib.srtb_set_thread_affinity.argtypes = [ctypes.c_int32]
    lib.srtb_pkt_ring_create.restype = ctypes.c_void_p
    lib.srtb_pkt_ring_create.argtypes = [
        ctypes.c_char_p, ctypes.c_uint16, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_int32, ctypes.c_uint32, ctypes.c_uint32]
    lib.srtb_pkt_ring_receive_block.restype = ctypes.c_int32
    lib.srtb_pkt_ring_receive_block.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.srtb_pkt_ring_total_packets.restype = ctypes.c_uint64
    lib.srtb_pkt_ring_total_packets.argtypes = [ctypes.c_void_p]
    lib.srtb_pkt_ring_lost_packets.restype = ctypes.c_uint64
    lib.srtb_pkt_ring_lost_packets.argtypes = [ctypes.c_void_p]
    lib.srtb_pkt_ring_destroy.argtypes = [ctypes.c_void_p]
    return lib


_NATIVE = _load_native()

# capability probe result, resolved once per process (None = unprobed)
_RECVMMSG_OK: bool | None = None


def _probe_recvmmsg() -> bool:
    """Whether the recvmmsg(2) syscall actually works here.

    Having ``libsrtb_udp.so`` built says nothing about the *kernel*:
    sandboxed CI (gVisor/seccomp) accepts plain recvfrom but fails
    recvmmsg with EINVAL/ENOSYS, which surfaced as 7 seed test failures
    (``receive_block failed rc=-1``) rather than a clean skip.  Probe a
    throwaway non-blocking loopback socket: EAGAIN means the syscall is
    wired up and there is simply no datagram; anything else means the
    native receiver cannot work in this environment."""
    import errno as _errno

    try:
        libc = ctypes.CDLL(None, use_errno=True)
        recvmmsg = libc.recvmmsg
    except (OSError, AttributeError):
        return False

    class _Iovec(ctypes.Structure):
        _fields_ = [("iov_base", ctypes.c_void_p),
                    ("iov_len", ctypes.c_size_t)]

    class _Msghdr(ctypes.Structure):
        _fields_ = [("msg_name", ctypes.c_void_p),
                    ("msg_namelen", ctypes.c_uint32),
                    ("msg_iov", ctypes.POINTER(_Iovec)),
                    ("msg_iovlen", ctypes.c_size_t),
                    ("msg_control", ctypes.c_void_p),
                    ("msg_controllen", ctypes.c_size_t),
                    ("msg_flags", ctypes.c_int)]

    class _Mmsghdr(ctypes.Structure):
        _fields_ = [("msg_hdr", _Msghdr), ("msg_len", ctypes.c_uint)]

    import select as _select

    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        sock.bind(("127.0.0.1", 0))
        sock.setblocking(False)
        # deliver a real datagram first: some sandboxes answer EAGAIN
        # on an empty queue (looks supported) and only fail EINVAL once
        # recvmmsg actually has a message to deliver
        tx.sendto(b"probe", sock.getsockname())
        if not _select.select([sock], [], [], 2.0)[0]:
            return False  # loopback delivery itself is broken here
        buf = ctypes.create_string_buffer(16)
        iov = _Iovec(ctypes.cast(buf, ctypes.c_void_p), len(buf))
        mm = _Mmsghdr()
        mm.msg_hdr.msg_iov = ctypes.pointer(iov)
        mm.msg_hdr.msg_iovlen = 1
        # mirror the native receiver's exact call shape: this sandbox's
        # kernel accepts plain recvmmsg but rejects MSG_WAITFORONE
        # (0x10000) with EINVAL — probing without the flag would pass
        # here and still fail rc=-1 on the first real receive_block
        msg_waitforone = 0x10000
        rc = recvmmsg(sock.fileno(), ctypes.byref(mm), 1,
                      msg_waitforone, None)
        return rc >= 1
    except OSError:
        return False
    finally:
        tx.close()
        sock.close()


def native_available() -> bool:
    """True when the native recvmmsg block receiver is usable: the lib
    is built AND the kernel/sandbox actually implements recvmmsg.  The
    single capability gate for auto-selection (UdpReceiverSource,
    udp_soak) and for test skips — explicit ``use_native=True`` against
    a False probe raises a clear OSError instead of a cryptic
    ``rc=-1`` mid-receive."""
    global _RECVMMSG_OK
    if _NATIVE is None:
        return False
    if _RECVMMSG_OK is None:
        _RECVMMSG_OK = _probe_recvmmsg()
        if not _RECVMMSG_OK:
            log.warning("[udp] recvmmsg unavailable in this environment "
                        "(sandbox?) — native receiver disabled, Python "
                        "fallback selected")
    return _RECVMMSG_OK


def counter_kind_for(fmt: formats.PacketFormat) -> int:
    return COUNTER_VDIF67 if fmt.name.startswith("gznupsr") else COUNTER_LE64


def parse_packet_counter(fmt: formats.PacketFormat, pkt: bytes) -> int:
    """Packet counter from the header (LE64 at offset 0, or VDIF words
    6|7 for gznupsr formats — ref: io/udp/udp_receiver.hpp backends)."""
    if counter_kind_for(fmt) == COUNTER_VDIF67:
        w6, w7 = struct.unpack_from("<2I", pkt, 24)
        return w6 | (w7 << 32)
    return struct.unpack_from("<Q", pkt)[0]


class NativeBlockReceiver:
    """Block receiver backed by the C++ recvmmsg implementation."""

    def __init__(self, addr: str, port: int, fmt: formats.PacketFormat,
                 rcvbuf_bytes: int = 1 << 28):
        if _NATIVE is None:
            raise RuntimeError("libsrtb_udp.so not built "
                               "(run make -C srtb_tpu/native)")
        if not native_available():
            raise OSError(
                "recvmmsg syscall unavailable in this environment "
                "(sandboxed kernel?) — use the Python receiver "
                "(use_native=False / udp_packet_provider='recvfrom')")
        self._lib = _NATIVE
        self._h = self._lib.srtb_udp_rx_create(
            addr.encode(), port, fmt.packet_payload_size,
            fmt.packet_header_size, counter_kind_for(fmt), rcvbuf_bytes)
        if not self._h:
            raise OSError(f"cannot bind UDP {addr}:{port}")
        self.fmt = fmt

    def receive_block(self, out: np.ndarray) -> tuple[int, int, int]:
        """Fill ``out`` (uint8, multiple of payload size) with one block.
        Returns (first_counter, lost, total)."""
        first = ctypes.c_uint64()
        lost = ctypes.c_uint64()
        total = ctypes.c_uint64()
        rc = self._lib.srtb_udp_rx_receive_block(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.nbytes, ctypes.byref(first), ctypes.byref(lost),
            ctypes.byref(total))
        if rc != 0:
            raise OSError(f"receive_block failed rc={rc}")
        return first.value, lost.value, total.value

    @property
    def total_packets(self) -> int:
        return self._lib.srtb_udp_rx_total_packets(self._h)

    @property
    def lost_packets(self) -> int:
        return self._lib.srtb_udp_rx_lost_packets(self._h)

    def close(self):
        if self._h:
            self._lib.srtb_udp_rx_destroy(self._h)
            self._h = None


class PacketRingReceiver:
    """Block receiver over an AF_PACKET TPACKET_V3 RX ring
    (``native/packet_ring.cpp``): the kernel DMA-fills a mmap'd ring and
    wakes userspace once per block, so capture costs no per-packet
    syscalls.  Working equivalent of the reference's packet_mmap v3
    provider, which is marked broken upstream
    (ref: io/udp/packet_mmap_v3_provider.hpp:61-65).  Requires
    CAP_NET_RAW; captures on an *interface* (default loopback), filtering
    UDP datagrams by destination port and exact size."""

    def __init__(self, addr: str, port: int, fmt: formats.PacketFormat,
                 interface: str = "lo",
                 block_size: int = 1 << 20, block_count: int = 64):
        del addr  # L2 capture binds an interface, not an address
        if _NATIVE is None:
            raise RuntimeError("libsrtb_udp.so not built "
                               "(run make -C srtb_tpu/native)")
        self._lib = _NATIVE
        self._h = self._lib.srtb_pkt_ring_create(
            interface.encode(), port, fmt.packet_payload_size,
            fmt.packet_header_size, counter_kind_for(fmt),
            block_size, block_count)
        if not self._h:
            raise OSError(
                f"cannot create AF_PACKET ring on {interface!r} "
                f"(needs CAP_NET_RAW)")
        self.fmt = fmt
        # Hold the UDP port open (never read): without a bound socket the
        # kernel answers every datagram with ICMP port-unreachable, and a
        # *connected* sender then fails alternate send()s with
        # ECONNREFUSED — observed as an exact 50% "loss" that never hit
        # the wire.  A minimal rcvbuf keeps the dead socket cheap.
        self._port_holder = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._port_holder.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR,
                                     1)
        try:
            self._port_holder.setsockopt(socket.SOL_SOCKET,
                                         socket.SO_RCVBUF, 4096)
        except OSError:
            pass
        try:
            self._port_holder.bind(("", port))
        except OSError:
            self._port_holder.close()
            self._port_holder = None  # port already held elsewhere: fine

    def receive_block(self, out: np.ndarray) -> tuple[int, int, int]:
        first = ctypes.c_uint64()
        lost = ctypes.c_uint64()
        total = ctypes.c_uint64()
        rc = self._lib.srtb_pkt_ring_receive_block(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.nbytes, ctypes.byref(first), ctypes.byref(lost),
            ctypes.byref(total))
        if rc != 0:
            raise OSError(f"ring receive_block failed rc={rc}")
        return first.value, lost.value, total.value

    @property
    def total_packets(self) -> int:
        return self._lib.srtb_pkt_ring_total_packets(self._h)

    @property
    def lost_packets(self) -> int:
        return self._lib.srtb_pkt_ring_lost_packets(self._h)

    def close(self):
        if self._h:
            self._lib.srtb_pkt_ring_destroy(self._h)
            self._h = None
        if getattr(self, "_port_holder", None) is not None:
            self._port_holder.close()
            self._port_holder = None


class PythonBlockReceiver:
    """Same semantics in pure Python (the reference's asio/recvfrom
    providers play this role: a slower but portable fallback)."""

    def __init__(self, addr: str, port: int, fmt: formats.PacketFormat,
                 rcvbuf_bytes: int = 1 << 26):
        self.fmt = fmt
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                  rcvbuf_bytes)
        except OSError:
            pass
        self._sock.bind((addr, port))
        self._pending: tuple[int, bytes] | None = None
        self._next_counter: int | None = None
        self.total_packets = 0
        self.lost_packets = 0

    def _parse_counter(self, pkt: bytes) -> int:
        return parse_packet_counter(self.fmt, pkt)

    def _next_packet(self) -> bytes:
        """Blocking fetch of one full-size packet (overridden by the
        asyncio provider; the base class reads the socket directly)."""
        while True:
            pkt, _ = self._sock.recvfrom(self.fmt.packet_payload_size + 64)
            if len(pkt) >= self.fmt.packet_payload_size:
                return pkt

    def receive_block(self, out: np.ndarray) -> tuple[int, int, int]:
        fmt = self.fmt
        payload = fmt.payload_bytes
        assert out.nbytes % payload == 0
        packets_per_block = out.nbytes // payload
        out[:] = 0
        begin = self._next_counter
        filled = 0
        seen = 0
        # per-slot fill map: a duplicated counter must not inflate the
        # fill count, or the block closes early with a silently-zeroed
        # slot and lost = 0 (found by the round-3 packet-sequence fuzz)
        slot_filled = bytearray(packets_per_block)
        while True:
            if self._pending is not None:
                c, pkt = self._pending
                self._pending = None
            else:
                pkt = self._next_packet()
                c = self._parse_counter(pkt)
            if begin is None:
                begin = c
            if c < begin:
                continue
            slot = c - begin
            if slot >= packets_per_block:
                self._pending = (c, pkt)
                break
            start = slot * payload
            out[start:start + payload] = np.frombuffer(
                pkt, dtype=np.uint8,
                count=payload, offset=fmt.packet_header_size)
            if not slot_filled[slot]:
                slot_filled[slot] = 1
                filled += 1
            seen += 1
            if filled == packets_per_block:
                break
        self._next_counter = begin + packets_per_block
        lost = packets_per_block - filled
        self.total_packets += seen
        self.lost_packets += lost
        return begin, lost, packets_per_block

    def close(self):
        self._sock.close()


class AsyncioBlockReceiver(PythonBlockReceiver):
    """Event-loop packet provider: the analog of the reference's
    boost::asio provider (ref: io/udp/asio_udp_packet_provider.hpp:1-66,
    an io_context-driven receive_from on the same socket the other
    providers use).  Packets are received by an asyncio
    ``DatagramProtocol`` on a dedicated event-loop thread and handed to
    the block assembler (inherited from :class:`PythonBlockReceiver`)
    through a bounded deque; on overflow the oldest packet is dropped and
    surfaces as counter-gap loss, exactly like a kernel buffer drop.
    """

    def __init__(self, addr: str, port: int, fmt: formats.PacketFormat,
                 rcvbuf_bytes: int = 1 << 26, queue_packets: int = 8192):
        super().__init__(addr, port, fmt, rcvbuf_bytes)
        self._q: "collections.deque[bytes]" = collections.deque()
        self._q_max = queue_packets
        self._cv = threading.Condition()
        self._loop = None
        self._transport = None
        self._closed = False
        self._startup_error: BaseException | None = None
        self._ready = threading.Event()
        self._thread = threading.Thread(target=self._run_loop,
                                        name="srtb-asyncio-udp",
                                        daemon=True)
        termination.tag_thread(self._thread)
        self._thread.start()
        # bounded wait + error propagation: a loop-setup failure (e.g. fd
        # exhaustion while creating the selector) must surface here, not
        # hang the constructor
        self._ready.wait(timeout=10)
        if self._startup_error is not None or not self._ready.is_set():
            err = self._startup_error
            self.close()  # release the bound socket, reap the thread
            if err is not None:
                raise RuntimeError(
                    "asyncio UDP provider failed to start") from err
            raise RuntimeError("asyncio UDP provider startup timed out")

    def _run_loop(self):
        import asyncio

        outer = self

        class _Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data, _addr):
                with outer._cv:
                    if len(outer._q) >= outer._q_max:
                        outer._q.popleft()
                    outer._q.append(data)
                    outer._cv.notify()

        loop = None
        try:
            loop = asyncio.new_event_loop()
            self._loop = loop
            self._sock.setblocking(False)
            transport, _ = loop.run_until_complete(
                loop.create_datagram_endpoint(_Proto, sock=self._sock))
            self._transport = transport
        except BaseException as e:  # propagated by __init__
            self._startup_error = e
            # run_forever is never reached, so the finally below never
            # runs: release the selector fd here and clear self._loop so
            # close() doesn't call_soon_threadsafe on a closed loop
            self._loop = None
            if loop is not None:
                loop.close()
            self._ready.set()
            return
        self._ready.set()
        try:
            loop.run_forever()
        finally:
            transport.close()
            loop.run_until_complete(loop.shutdown_asyncgens())
            loop.close()

    def queue_depth(self) -> int:
        """Packets waiting between the event loop and the block
        assembler (the provider's ring-occupancy analog; at ``_q_max``
        the oldest packet is dropped as loss)."""
        with self._cv:
            return len(self._q)

    def _next_packet(self) -> bytes:
        need = self.fmt.packet_payload_size
        while True:
            with self._cv:
                while not self._q:
                    if self._closed:
                        # mirror the recvfrom provider, whose blocked
                        # syscall raises when the fd is closed
                        raise OSError("asyncio UDP provider closed")
                    self._cv.wait()
                pkt = self._q.popleft()
            if len(pkt) >= need:
                return pkt

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()  # unblock a consumer in _next_packet
        loop = self._loop  # snapshot: the worker's error path nulls and
        if loop is not None:  # closes it concurrently with this check
            try:
                loop.call_soon_threadsafe(loop.stop)
            except RuntimeError:  # loop already closed by the worker
                pass
        if self._thread.is_alive():
            # join even when the loop never came up (startup timeout):
            # the thread may still hold self._sock, which the base close
            # below is about to invalidate
            self._thread.join(timeout=5)
        self._loop = None
        # the datagram transport owns (and closed) self._sock; the base
        # close is a harmless double-close guard, and covers startup
        # failures where the transport never took ownership
        try:
            super().close()
        except OSError:  # pragma: no cover
            pass


class PythonContinuousReceiver:
    """The reference's *continuous* receive worker
    (continuous_udp_receiver_worker, ref: io/udp/udp_receiver.hpp:42-168),
    as opposed to the block worker above: packets are consumed strictly
    sequentially, a packet's payload may straddle block boundaries (the
    unread tail carries over to the next call), and lost packets are
    zero-filled inline — ``lost * payload`` zeros injected exactly where
    the missing data would have been, also carrying across calls.  This
    keeps the delivered byte stream gap-free and continuous, at the cost
    of no reorder tolerance.

    Deviation from the reference: a late/duplicate packet (counter <=
    last seen) is dropped instead of underflowing the unsigned lost-count
    arithmetic (udp_receiver.hpp:135 would zero-fill ~2^64 bytes).
    """

    def __init__(self, addr: str, port: int, fmt: formats.PacketFormat,
                 rcvbuf_bytes: int = 1 << 26):
        self.fmt = fmt
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                  rcvbuf_bytes)
        except OSError:
            pass
        self._sock.bind((addr, port))
        self._leftover = b""     # unread payload tail of the last packet
        self._zeros_pending = 0  # zero-fill bytes still owed to the stream
        self._last_counter: int | None = None
        self.total_packets = 0
        self.lost_packets = 0

    def receive_block(self, out: np.ndarray) -> tuple[int, int, int]:
        """Fill ``out`` (uint8, any size) with the next stretch of the
        continuous stream.  Returns (block_counter, lost,
        packets_received_this_call).

        ``block_counter`` is the counter of the packet the block's FIRST
        BYTE belongs to — when the block opens with carried-over payload
        it is the carried packet's counter, and when it opens inside a
        zero-filled gap it is the (lost) counter that gap stands for.
        (The reference returns the first counter *received during the
        call* instead, udp_receiver.hpp:77-86; that labels straddled
        segments off by the carryover length, so downstream
        ``counter * payload`` time reconstruction would drift — a
        deliberate improvement, not an oversight.)
        """
        fmt = self.fmt
        payload = fmt.payload_bytes
        cap = out.nbytes
        pos = 0
        if self._zeros_pending > 0 and self._last_counter is not None:
            # block opens inside the zero-filled gap that precedes
            # _last_counter's payload: gap packets count back from it
            gap_packets = -(-self._zeros_pending // payload)  # ceil
            first_counter = self._last_counter - gap_packets
        elif self._leftover:
            first_counter = self._last_counter
        else:
            first_counter = None  # set by the first packet received
        seen = 0
        lost_this = 0
        while pos < cap:
            if self._zeros_pending > 0:
                k = min(self._zeros_pending, cap - pos)
                out[pos:pos + k] = 0
                self._zeros_pending -= k
                pos += k
            elif self._leftover:
                k = min(len(self._leftover), cap - pos)
                out[pos:pos + k] = np.frombuffer(self._leftover, np.uint8,
                                                 count=k)
                self._leftover = self._leftover[k:]
                pos += k
            else:
                pkt, _ = self._sock.recvfrom(fmt.packet_payload_size + 64)
                if len(pkt) < fmt.packet_payload_size:
                    continue
                c = parse_packet_counter(fmt, pkt)
                if self._last_counter is None:
                    lost = 0
                elif c > self._last_counter:
                    lost = c - self._last_counter - 1
                else:
                    continue  # late/duplicate: stream already moved past
                if first_counter is None:
                    first_counter = c
                seen += 1
                lost_this += lost
                self._zeros_pending += lost * payload
                self._last_counter = c
                self._leftover = pkt[
                    fmt.packet_header_size:fmt.packet_header_size + payload]
        self.total_packets += seen
        self.lost_packets += lost_this
        if first_counter is None:
            first_counter = self._last_counter or 0
        return first_counter, lost_this, seen

    def close(self):
        self._sock.close()


class UdpReceiverSource:
    """Yields SegmentWork blocks from a UDP stream
    (ref: pipeline/udp_receiver_pipe.hpp:106-155)."""

    def __init__(self, cfg: Config, receiver_id: int = 0,
                 use_native: bool | None = None):
        self.cfg = cfg
        self.fmt = formats.resolve(cfg.baseband_format_type)
        if self.fmt.packet_payload_size == 0:
            raise ValueError(
                f"format {self.fmt.name} has no packet structure")
        addr = cfg.udp_receiver_address[
            min(receiver_id, len(cfg.udp_receiver_address) - 1)]
        port = cfg.udp_receiver_port[
            min(receiver_id, len(cfg.udp_receiver_port) - 1)]
        mode = getattr(cfg, "udp_receiver_mode", "block")
        if mode not in ("block", "continuous"):
            raise ValueError(f"unknown udp_receiver_mode {mode!r}")
        provider = getattr(cfg, "udp_packet_provider", "recvmmsg")
        if provider not in ("recvmmsg", "packet_ring", "recvfrom",
                            "asyncio"):
            raise ValueError(f"unknown udp_packet_provider {provider!r}")
        if provider == "asyncio":
            if mode == "continuous":
                raise ValueError(
                    "udp_packet_provider='asyncio' implements the block "
                    "worker only (like the reference's asio provider it "
                    "is an alternative packet transport, not a worker)")
            if use_native:
                raise ValueError(
                    "use_native=True contradicts udp_packet_provider="
                    "'asyncio' (the event-loop Python provider)")
        if mode == "continuous" and provider == "packet_ring":
            # refuse rather than silently downgrade: the operator asked
            # for the zero-loss ring but the continuous worker is the
            # pure-Python sequential receiver
            raise ValueError(
                "udp_packet_provider='packet_ring' requires "
                "udp_receiver_mode='block' (the continuous worker is the "
                "Python sequential receiver)")
        if use_native and provider == "recvfrom":
            raise ValueError(
                "use_native=True contradicts udp_packet_provider="
                "'recvfrom' (the Python fallback)")
        if provider == "packet_ring" and mode == "block" and (
                _NATIVE is None or use_native is False):
            # refuse-don't-downgrade, same policy as above: an explicit
            # ring request must not silently become the lossy recvfrom
            # fallback
            raise ValueError(
                "udp_packet_provider='packet_ring' needs the native lib "
                "(make -C srtb_tpu/native) and use_native != False")
        if use_native is None:
            if provider == "packet_ring":
                # the AF_PACKET ring has its own syscalls (and its own
                # OSError on failure) — recvmmsg availability is
                # irrelevant to it
                use_native = _NATIVE is not None
            else:
                # auto-selection consults the capability probe, not
                # just lib presence: a sandbox without recvmmsg falls
                # back to the Python block receiver instead of
                # erroring mid-stream
                use_native = (native_available() and mode == "block"
                              and provider not in ("recvfrom", "asyncio"))
        rcvbuf = int(getattr(cfg, "udp_receiver_rcvbuf_bytes", 1 << 28))
        if mode == "continuous":
            # the continuous worker is sequential by construction; the
            # native recvmmsg path currently implements only the block
            # worker (its recvmmsg batching conflicts with strict
            # in-order straddling delivery)
            self.receiver = PythonContinuousReceiver(
                addr, port, self.fmt, rcvbuf_bytes=rcvbuf)
        elif use_native and provider == "packet_ring":
            self.receiver = PacketRingReceiver(
                addr, port, self.fmt,
                interface=getattr(cfg, "udp_packet_ring_interface", "lo"))
        elif use_native:
            self.receiver = NativeBlockReceiver(addr, port, self.fmt,
                                                rcvbuf_bytes=rcvbuf)
        elif provider == "asyncio":
            self.receiver = AsyncioBlockReceiver(addr, port, self.fmt,
                                                 rcvbuf_bytes=rcvbuf)
        else:
            self.receiver = PythonBlockReceiver(addr, port, self.fmt,
                                                rcvbuf_bytes=rcvbuf)
        self.data_stream_id = receiver_id
        self.segment_bytes = cfg.segment_bytes(self.fmt.data_stream_count)
        payload = self.fmt.payload_bytes
        if mode == "block" and self.segment_bytes % payload:
            # the continuous worker straddles packet payloads across
            # segments, so it has no multiple-of-payload requirement
            raise ValueError(
                f"segment bytes {self.segment_bytes} not a multiple of "
                f"packet payload {payload}")
        # Overlap-save for the real-time source: with
        # baseband_reserve_sample active, consecutive segments must
        # overlap by the reserved tail (exactly like the file reader's
        # seek-back) so the dedispersion-corrupted edge each segment
        # trims is re-processed by the next one instead of silently
        # lost between UDP blocks.  The tail is retained in host
        # memory and only the stride's NEW bytes are received per
        # segment — the network hands over stride bytes, and when the
        # ingest ring is live the device upload is the same stride.
        from srtb_tpu.ops import dedisperse as dd
        nsamps = dd.nsamps_reserved(cfg)
        bits = abs(cfg.baseband_input_bits)
        reserved = int(nsamps * bits // 8 * self.fmt.data_stream_count)
        self.reserved_bytes = 0
        seq_valid = True
        if reserved > 0:
            # the reserved tail is DM/bandwidth math rounded to
            # waterfall tiles, so payload alignment holds only for
            # cooperating configs.  A misaligned config keeps the
            # legacy non-overlapping block framing (it ran that way
            # before overlap-save existed here) with a loud warning —
            # and its segments are left UNSTAMPED (seq = -1) so the
            # engine's adjacency guard keeps the ingest ring cold
            # rather than warm-assembling non-overlapping blocks
            # against a carry that is not their head.
            problems = []
            if (nsamps * bits) % 8:
                problems.append(f"reserved samples {nsamps} not "
                                f"byte-aligned at {bits}-bit samples")
            if reserved >= self.segment_bytes:
                problems.append(f"reserved bytes {reserved} >= "
                                f"segment {self.segment_bytes}")
            if mode == "block" \
                    and (self.segment_bytes - reserved) % payload:
                problems.append(
                    f"stride {self.segment_bytes - reserved} not a "
                    f"multiple of the packet payload {payload} "
                    "(align spectrum_channel_count / segment size to "
                    "enable overlap)")
            if problems:
                log.warning(
                    "[udp_receiver] overlap-save disabled ("
                    + "; ".join(problems) + "): segments will NOT "
                    "overlap and the ingest ring stays cold for this "
                    "source")
                seq_valid = False
            else:
                self.reserved_bytes = reserved
        self.stride_bytes = self.segment_bytes - self.reserved_bytes
        # shared tail-retention + seq-stamping contract (io/overlap.py)
        from srtb_tpu.io.overlap import OverlapTailCarry
        self._carry = OverlapTailCarry(self.reserved_bytes,
                                       stamp_seq=seq_valid)

    def __iter__(self):
        return self

    def __next__(self) -> SegmentWork:
        buf = np.zeros(self.segment_bytes, dtype=np.uint8)
        # warm: head = retained tail of the previous segment; the
        # receiver fills only the stride's new bytes (a contiguous
        # view — both native and Python receivers write in place)
        reserved = self._carry.head_into(buf)
        first_counter, lost, total = self.receiver.receive_block(
            buf[reserved:] if reserved else buf)
        if reserved:
            # the segment's first byte belongs to a packet
            # reserved_bytes earlier than the first freshly received
            # one (exact in block mode, where reserved is a payload
            # multiple; floor-approximate for a mid-packet continuous
            # tail)
            first_counter -= reserved // self.fmt.payload_bytes
        if self.reserved_bytes > 0:
            self._carry.retain(buf)
        metrics.add("packets_total", total)
        metrics.add("packets_lost", lost)
        # windowed loss accounting: snapshot()/Prometheus derive the
        # loss *rate over the last 10 s* from these — a loss burst is
        # visible while it happens, not diluted into the lifetime ratio
        metrics.window("packets_total").add(total)
        metrics.window("packets_lost").add(lost)
        # per-tenant attribution (multi-tenant fleet): receiver loss
        # labeled by the owning stream (Config.stream_name when the
        # fleet named this lane, else the receiver id) so /metrics can
        # answer "whose packets" — the same rule as segments_dropped
        if lost:
            origin = (str(getattr(self.cfg, "stream_name", "") or "")
                      or str(self.data_stream_id))
            metrics.add("packets_lost", lost,
                        labels={"stream": origin})
        depth = getattr(self.receiver, "queue_depth", None)
        if depth is not None:
            metrics.set(f"udp_rx{self.data_stream_id}_queue_packets",
                        depth())
        if lost:
            log.warning(f"[udp_receiver] lost {lost}/{total} packets "
                        f"({lost / total:.2%})")
        return SegmentWork(
            data=buf,
            timestamp=time.time_ns(),
            udp_packet_counter=first_counter,
            data_stream_id=self.data_stream_id,
            seq=self._carry.next_seq(),
        )

    def close(self):
        self.receiver.close()


class MultiUdpSource:
    """N receivers (one per address/port pair, each on its own thread, like
    the reference's N udp_receiver_pipe instances, ref: main.cpp:261-271)
    multiplexed into one SegmentWork stream distinguished by
    ``data_stream_id``."""

    def __init__(self, cfg: Config, use_native: bool | None = None):
        from srtb_tpu.pipeline import framework as fw
        self.cfg = cfg
        n = len(cfg.udp_receiver_port)
        self.sources = [UdpReceiverSource(cfg, receiver_id=i,
                                          use_native=use_native)
                        for i in range(n)]
        self._stop = fw.StopToken()
        self._queue = fw.WorkQueue(capacity=2 * n)
        self._pipes = []
        for i, src in enumerate(self.sources):
            def make(src, cpu):
                pinned = [False]

                def recv(stop_token, _):
                    if not pinned[0]:
                        # pin the receiver thread near the NIC
                        # (ref: udp_receiver_pipe.hpp:88-98)
                        from srtb_tpu.utils.affinity import \
                            set_thread_affinity
                        set_thread_affinity(cpu)
                        pinned[0] = True
                    return next(src)
                return recv
            cpu = cfg.udp_receiver_cpu_preferred[
                min(i, len(cfg.udp_receiver_cpu_preferred) - 1)]
            self._pipes.append(fw.start_pipe(
                make(src, cpu), None, self._queue, self._stop,
                name=f"udp_receiver_{i}"))

    def __iter__(self):
        return self

    def __next__(self) -> SegmentWork:
        item = self._queue.pop(self._stop)
        if item is None or not isinstance(item, SegmentWork):
            raise StopIteration
        return item

    def close(self):
        from srtb_tpu.pipeline import framework as fw
        fw.on_exit(self._stop, self._pipes)
        for src in self.sources:
            src.close()
