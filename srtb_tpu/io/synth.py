"""Synthetic baseband generation.

The reference validates end-to-end behavior manually on a recorded
pulsar baseband (SURVEY.md §4: J1644-4559 + GUI inspection).  srtb_tpu
ships a generator instead: Gaussian noise plus impulses dispersed by the
*inverse* of the dedispersion chirp (what the ionized interstellar medium
does to a broadband pulse — ref: coherent_dedispersion.hpp physics),
quantized to any supported bit width.  The pipeline must then recover
the pulse at the configured DM; tests and the demo tool both build on
this.
"""

from __future__ import annotations

import numpy as np

from srtb_tpu.ops import dedisperse as dd


def pack_subbyte(values: np.ndarray, nbits: int) -> np.ndarray:
    """Pack small unsigned ints MSB-first into bytes — the inverse of
    ops.unpack for nbits in {1, 2, 4} (ref bit order: unpack.hpp:43-140)."""
    per_byte = 8 // nbits
    v = np.asarray(values, dtype=np.uint8).reshape(-1, per_byte)
    out = np.zeros(v.shape[0], dtype=np.uint16)
    for j in range(per_byte):
        out |= (v[:, j].astype(np.uint16) & ((1 << nbits) - 1)) \
            << (8 - nbits * (j + 1))
    return out.astype(np.uint8)


def quantize(sig: np.ndarray, nbits: int) -> np.ndarray:
    """Quantize a zero-mean float signal to the byte stream of an
    ``nbits``-per-sample unsigned baseband (the digitizer model: scale to
    a few sigma, offset to mid-scale, clip)."""
    levels = 1 << abs(nbits)
    if nbits == 1:
        q = (sig > 0).astype(np.uint8)  # 1-bit digitizer = sign
        return pack_subbyte(q, 1)
    mid = levels / 2
    # keep ~3 sigma inside the range
    scale = (levels / 2 - 0.5) / 3.0
    q = np.clip(np.round(sig / sig.std() * scale + mid), 0, levels - 1)
    q = q.astype(np.uint8 if abs(nbits) <= 8 else np.uint16)
    if nbits in (1, 2, 4):
        return pack_subbyte(q, nbits)
    if nbits == 8:
        return q.astype(np.uint8)
    if nbits == 16:
        return q.astype("<u2").view(np.uint8)
    raise ValueError(f"unsupported nbits {nbits}")


def make_dispersed_baseband(n: int, f_min: float, bandwidth: float,
                            dm: float, pulse_positions, nbits: int = 8,
                            pulse_amp: float = 40.0, pulse_width: int = 32,
                            seed: int = 0) -> np.ndarray:
    """Real-valued baseband of ``n`` samples: unit noise + dispersed
    impulses at ``pulse_positions``, quantized to ``nbits``; returns the
    packed uint8 byte stream ready to feed the pipeline."""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal(n)
    pulse = np.zeros(n)
    if np.isscalar(pulse_positions):
        pulse_positions = [pulse_positions]
    for pos in pulse_positions:
        pos = int(pos)
        pulse[pos:pos + pulse_width] += \
            pulse_amp * rng.standard_normal(min(pulse_width, n - pos))
    n_spec = n // 2
    f_c = f_min + bandwidth
    df = bandwidth / n_spec
    chirp = dd.chirp_factor_host(n_spec, f_min, df, f_c, dm)
    spec = np.fft.rfft(pulse)
    spec[:n_spec] *= np.conj(chirp)  # disperse (medium = inverse chirp)
    sig = x + np.fft.irfft(spec, n)
    return quantize(sig, nbits)
