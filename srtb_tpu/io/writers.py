"""Output writers: candidate capture (.bin/.npy/.tim), write-all mode, and
the sigproc filterbank header.

File formats are byte-compatible with the reference so its offline plot
helpers (src/plot_spectrum.py, plot_tim.py) work unmodified:
- ``<prefix><counter>.bin``      raw baseband bytes of the segment
  (ref: write_signal_pipe.hpp:159-206);
- ``<prefix><counter>.<i>.npy``  complex64 spectrum waterfall, shape
  [freq_bins, time_samples] (ref: write_signal_pipe.hpp:209-246);
- ``<prefix><counter>.<boxcar>.tim``  raw float32 time series
  (ref: write_signal_pipe.hpp:249-280); batched multi-polarization
  results add a stream index: ``<prefix><counter>.s<stream>.<boxcar>.tim``
  (no reference equivalent — its streams are separate work items);
- the "piggybank" logic keeps recent negatives and writes them when they
  overlap (within 0.45 segment) a recent positive in another polarization
  (ref: write_signal_pipe.hpp:77-140).
"""

from __future__ import annotations

import dataclasses
import os
import time
import zlib
from collections import deque
from dataclasses import dataclass

import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.pipeline.work import (NO_UDP_PACKET_COUNTER, SegmentResultWork)
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

# crash consistency: candidate files are written to <path>.srtb_tmp
# and atomically renamed into place, so a reader (or a restarted run)
# never sees a torn half-written candidate; a crash between write and
# rename leaves only an orphan temp, removed by the startup sweep
TMP_SUFFIX = ".srtb_tmp"


def recover_orphan_temps(prefix: str,
                         min_age_s: float = 60.0) -> list[str]:
    """Startup recovery sweep: remove ``<prefix>*.srtb_tmp`` orphans
    left by a run that died between a temp write and its atomic
    rename.  Returns the removed paths; every removal is counted
    (``orphan_temps_removed``) and logged — an interrupted dump is a
    data-loss event, not housekeeping.

    Only temps whose mtime is older than ``min_age_s`` are swept: a
    fresh temp may belong to a LIVE writer sharing the output prefix
    (a concurrent pipeline process, or the previous run's async pool
    still flushing), and unlinking it mid-write would turn that
    healthy atomic write into a failure.  A true orphan missed by the
    age guard (crash + restart within the window) is swept on the
    next startup and is harmless meanwhile."""
    d = os.path.dirname(prefix) or "."
    base = os.path.basename(prefix)
    removed = []
    try:
        names = os.listdir(d)
    except OSError:
        return removed
    now = time.time()
    for name in names:
        if name.startswith(base) and name.endswith(TMP_SUFFIX):
            p = os.path.join(d, name)
            try:
                if now - os.path.getmtime(p) < min_age_s:
                    log.warning(f"[recover] leaving fresh temp {p} "
                                "(possibly a live writer's)")
                    continue
                os.unlink(p)
                removed.append(p)
            except OSError as e:
                log.warning(f"[recover] cannot remove orphan {p}: {e}")
    if removed:
        metrics.add("orphan_temps_removed", len(removed))
        log.warning(f"[recover] removed {len(removed)} orphaned temp "
                    f"file(s) from an interrupted run: "
                    f"{[os.path.basename(p) for p in removed]}")
    return removed


def fsync_dir(path: str) -> None:
    """fsync the PARENT DIRECTORY of ``path``: an ``os.replace`` makes
    the rename atomic but not durable — the directory entry itself can
    vanish on power loss until the directory inode is synced.  Best
    effort: filesystems that refuse directory fds (some network
    mounts) degrade to the rename-only guarantee."""
    d = os.path.dirname(path) or "."
    try:
        fd = os.open(d, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError as e:
        log.debug(f"[writers] cannot open dir {d} for fsync: {e}")
        return
    try:
        os.fsync(fd)
    except OSError as e:
        log.debug(f"[writers] dir fsync of {d} failed: {e}")
    finally:
        os.close(fd)


# crash-window steering hook for the durability harnesses
# (tools/crash_soak.py, tests/test_durability.py): when set, called
# with the destination path after the temp write and BEFORE the atomic
# rename — a SIGKILL landing inside the hook is a deterministic
# mid-rename crash.  None in production (one global read per write).
_PRE_RENAME_HOOK = None


def atomic_write(path: str, payload, *, fsync: bool = False,
                 pre_rename=None) -> None:
    """Crash-consistent write: temp + flush (+ optional fdatasync) +
    atomic rename (+ parent-directory fsync, so the rename survives
    power loss — opt out via the same ``fsync`` knob).  A crash
    mid-write leaves only the orphan temp for the startup sweep; a
    *failed* write from a live run drops its temp so it cannot read as
    an interrupted-run orphan next startup.  The native C++ pool
    implements the same sequence with the same suffix
    (native/file_writer.cpp).

    ``pre_rename`` is the manifest's publish barrier
    (``RunManifest.sync``): invoked between the temp write and the
    rename, so no artifact reaches its final name before the WAL
    durably holds its intent."""
    tmp = path + TMP_SUFFIX
    try:
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            if fsync:
                os.fdatasync(f.fileno())
        if pre_rename is not None:
            pre_rename()
        if _PRE_RENAME_HOOK is not None:
            _PRE_RENAME_HOOK(path)
        os.replace(tmp, path)
        if fsync:
            fsync_dir(path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass  # never created, or the disk is truly gone
        raise


def manifest_stage(manifest, key, path: str, data: np.ndarray):
    """Stage one atomic artifact write against the run manifest: log
    the intent NOW — before any byte reaches the temp file — and
    return the commit callback to fire once the atomic rename has
    published the artifact (synchronously, or from a writer-pool
    thread via ``AsyncWriterPool.submit(on_done=...)``).  The intent
    append is buffered; the durability point is the PUBLISH BARRIER
    (``manifest.sync``), which the writer runs between the temp write
    and the rename — see io/manifest.py.  None when no manifest is
    bound (zero cost)."""
    if manifest is None or key is None:
        return None
    buf = np.ascontiguousarray(data)
    length = int(buf.nbytes)
    # content CRC is the deep fsck check, ~1 ms per dumped MB;
    # Config.manifest_hash=0 drops to existence+size verification
    crc = zlib.crc32(buf) if getattr(manifest, "hash_content", True) \
        else None
    manifest.intent(key, path)

    def commit():
        manifest.commit(key, path, length, crc)

    return commit


def stage_write(path: str, payload, *, fsync: bool = False) -> str:
    """First half of :func:`atomic_write`: write the temp (+ optional
    fdatasync) WITHOUT publishing it.  Returns the temp path; the
    caller renames after its publish barrier — letting one barrier
    cover a whole segment's artifacts (see
    ``WriteSignalSink._publish_staged``)."""
    tmp = path + TMP_SUFFIX
    try:
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            if fsync:
                os.fdatasync(f.fileno())
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass  # never created, or the disk is truly gone
        raise
    return tmp


def _npy_bytes(arr: np.ndarray) -> np.ndarray:
    """Serialize an array in .npy format to a uint8 buffer (cnpy analog —
    the reference writes .npy via cnpy, write_signal_pipe.hpp:243-244)."""
    import io as _io
    bio = _io.BytesIO()
    np.save(bio, arr)
    return np.frombuffer(bio.getvalue(), dtype=np.uint8)


@dataclass
class CandidateFiles:
    """Paths written for one positive segment."""
    bin_path: str
    npy_paths: list
    tim_paths: list
    # periodicity mode only: <base>[.sN].fold.npy folded profiles +
    # <base>[.sN].cand.json candidate metadata
    fold_paths: list = dataclasses.field(default_factory=list)


class WriteSignalSink:
    """Candidate writer with the reference's piggybank capture policy.

    When ``writer_pool`` (an :class:`AsyncWriterPool`) is given, file
    writes are queued to its (native C++) thread pool and this sink never
    blocks on disk — the reference's async thread-pool behavior
    (write_signal_pipe.hpp:159-206 submits to boost thread pools).  Call
    ``drain()`` before reading the files back.
    """

    # degradation ladder level >= 2 skips this sink entirely (shed
    # baseband/candidate dumps before shedding whole segments)
    sheddable = True

    def __init__(self, cfg: Config, fdatasync: bool = True,
                 writer_pool=None):
        self.cfg = cfg
        self.fdatasync = fdatasync
        self.pool = writer_pool
        self._assigned_paths: set[str] = set()
        self.recent_positive_timestamps: deque[int] = deque()
        self.recent_negative_works: deque[SegmentResultWork] = deque()
        self.written: list[CandidateFiles] = []
        # retry re-entry state (see push/_write): the pipeline's
        # sink_write retry calls push() again after a transient
        # mid-write failure, and the replay must be idempotent — no
        # duplicated deque entries, and the partially written segment
        # keeps its already-picked .npy paths instead of spilling the
        # same waterfall under fresh indices.  Keyed on the SEGMENT
        # (identity + metadata): each retry attempt wraps it in a
        # fresh SegmentResultWork (runtime._push_sinks), so the work
        # object itself is not stable across attempts
        self._inflight_key: tuple | None = None
        self._inflight_npy: dict[int, str] = {}
        # durable exactly-once (io/manifest.py): when bound, every
        # artifact logs intent before its temp write and commit after
        # the atomic rename; the runtime sets the (stream, seg, sink)
        # key per push.  None = manifest off, zero cost.
        self.manifest = None
        self._manifest_key = None
        # segment-transaction staging (synchronous path only): with a
        # manifest bound, one segment's artifacts are temp-written
        # first, then published together behind ONE publish barrier —
        # one fdatasync per segment instead of one per artifact.  None
        # when no transaction is open.
        self._tx_staged = None
        # whether the LAST push wrote any artifact: the runtime skips
        # the durable done record for empty pushes (a replayed
        # negative segment recomputes the same decision and writes
        # nothing — nothing to protect, and the common all-negative
        # observation keeps its WAL one record per segment)
        self.last_push_wrote = False
        # check directory writability up front (ref: write_signal_pipe.hpp:62-75)
        check_path = cfg.baseband_output_file_prefix + ".check"
        with open(check_path, "wb"):
            pass
        os.unlink(check_path)

    # ------------------------------------------------------------------

    def bind_manifest(self, manifest) -> None:
        self.manifest = manifest

    def set_manifest_key(self, key) -> None:
        self._manifest_key = key

    # ------------------------------------------------------------------

    def _overlap_window_ns(self) -> float:
        # 0.45 of a segment duration, in ns (ref: write_signal_pipe.hpp:84-86)
        return (0.45 * 1e9 * self.cfg.baseband_input_count
                / self.cfg.baseband_sample_rate)

    def _overlaps_recent_positive(self, timestamp: int) -> bool:
        w = self._overlap_window_ns()
        return any(abs(timestamp - t) < w
                   for t in self.recent_positive_timestamps)

    def push(self, work: SegmentResultWork, has_signal: bool) -> None:
        """Feed one processed segment; writes to disk when warranted."""
        self.last_push_wrote = False
        real_time = self.cfg.input_file_path == ""
        w = self._overlap_window_ns()
        ts = work.segment.timestamp

        # clean outdated positives (ref: write_signal_pipe.hpp:88-94)
        while (real_time and self.recent_positive_timestamps
               and ts - self.recent_positive_timestamps[0] > 5 * w):
            self.recent_positive_timestamps.popleft()

        to_write = None
        if has_signal:
            # idempotent under retry re-entry: the same segment pushed
            # again (transient failure later in this push) must not
            # stamp the overlap window twice
            if not self.recent_positive_timestamps \
                    or self.recent_positive_timestamps[-1] != ts:
                self.recent_positive_timestamps.append(ts)
            to_write = work
        elif real_time and self._overlaps_recent_positive(ts):
            # other-polarization piggyback (ref: write_signal_pipe.hpp:102-115)
            to_write = work
        elif real_time:
            # segment identity, not work identity: a pipeline retry
            # re-enters with a fresh SegmentResultWork around the SAME
            # segment, and the piggyback deque must not hold it twice
            if not self.recent_negative_works \
                    or self.recent_negative_works[-1].segment \
                    is not work.segment:
                self.recent_negative_works.append(work)

        # re-check old negatives against new positives (ref: 122-140).
        # Peek, don't pop: a transient _write failure re-enters this
        # push via the pipeline's sink_write retry, and a popped-but-
        # unwritten piggyback candidate would be silently lost (the
        # retry would pop — and mis-schedule — the NEXT negative)
        popped_negative = False
        if real_time and to_write is None and self.recent_negative_works:
            work_2 = self.recent_negative_works[0]
            if self._overlaps_recent_positive(work_2.segment.timestamp):
                to_write = work_2
                popped_negative = True
            else:
                self.recent_negative_works.popleft()

        if to_write is not None:
            self._write(to_write)
            if popped_negative:
                self.recent_negative_works.popleft()

        # bound the negative queue (the reference relies on deque churn; we
        # cap explicitly to one overlap window's worth of segments)
        while len(self.recent_negative_works) > 16:
            self.recent_negative_works.popleft()

    # ------------------------------------------------------------------

    def _write(self, work: SegmentResultWork) -> None:
        counter = work.segment.udp_packet_counter
        if counter == NO_UDP_PACKET_COUNTER:
            counter = work.segment.timestamp
        base = self.cfg.baseband_output_file_prefix + str(counter)
        # a retry of this same segment (transient failure partway
        # through) must reuse the .npy paths the first attempt picked
        # — the find-first-free scan below would otherwise see its own
        # partial output and assign the same waterfall a fresh index.
        # The key is the segment's identity + metadata (each retry
        # attempt builds a fresh work wrapper; the metadata guards the
        # freak case of a recycled id after an abandoned failure)
        key = (id(work.segment), work.segment.timestamp,
               work.segment.udp_packet_counter)
        if self._inflight_key != key:
            self._inflight_key = key
            self._inflight_npy = {}
        self.last_push_wrote = True
        log.info(f"[write_signal] begin writing, file_counter = {counter}")

        # open the segment transaction: synchronous manifest-armed
        # writes stage temps and publish together after one barrier
        # (the pool path self-batches worker-side instead)
        if self.manifest is not None and self._manifest_key is not None \
                and self.pool is None:
            self._tx_staged = []
        try:
            self._write_artifacts(work, base)
            self._publish_staged()
        except BaseException:
            self._tx_abort()
            raise
        # completed: the next _write (even for a same-counter
        # piggyback) must pick fresh indices, not reuse these
        self._inflight_key = None
        self._inflight_npy = {}
        log.info(f"[write_signal] finished writing, file_counter = {counter}")

    def _write_artifacts(self, work: SegmentResultWork,
                         base: str) -> None:
        bin_path = base + ".bin"
        self._write_bytes(bin_path,
                          np.ascontiguousarray(work.segment.data),
                          fsync=self.fdatasync)

        npy_paths = []
        if work.waterfall is not None:
            # the waterfall may still be device-resident (lazy sink-side
            # transfer): fetch via the explicit D2H spelling so the
            # sanitizer's transfer tripwire stays quiet on this
            # sanctioned sync
            from srtb_tpu.utils.platform import to_host
            wf = to_host(work.waterfall)
            if wf.ndim == 4:  # stacked (re, im) boundary representation
                wf = (wf[0] + 1j * wf[1]).astype(np.complex64)
            if wf.ndim == 2:
                wf = wf[None]
            for i in range(wf.shape[0]):
                path = self._inflight_npy.get(i)
                if path is None:
                    # pick first non-existing index (ref: 230-235);
                    # with an async pool queued-but-unwritten paths
                    # count as taken, as do staged-but-unpublished
                    # ones inside the open segment transaction
                    staged_paths = {p for p, *_ in self._tx_staged} \
                        if self._tx_staged else set()
                    j = i
                    while (os.path.exists(f"{base}.{j}.npy")
                           or f"{base}.{j}.npy" in self._assigned_paths
                           or f"{base}.{j}.npy" in staged_paths):
                        j += 1
                    path = f"{base}.{j}.npy"
                    self._inflight_npy[i] = path
                self._write_bytes(path, _npy_bytes(wf[i].astype(np.complex64)))
                npy_paths.append(path)

        tim_paths = []
        if work.detect is not None:
            counts = np.asarray(work.detect.signal_counts)
            series = np.asarray(work.detect.boxcar_series)
            if counts.ndim == 1:
                counts = counts[None]
                series = series[None]
            lengths = work.detect.boxcar_lengths
            multi = counts.shape[0] > 1
            for s in range(counts.shape[0]):
                for bi, b in enumerate(lengths):
                    if counts[s, bi] > 0:
                        # single-stream keeps the reference's exact name;
                        # batched multi-polarization results need a stream
                        # index or the streams would overwrite each other
                        path = (f"{base}.s{s}.{b}.tim" if multi
                                else f"{base}.{b}.tim")
                        valid = series.shape[-1] - (b if b > 1 else 0)
                        self._write_bytes(
                            path, series[s, bi, :valid].astype("<f4"))
                        tim_paths.append(path)

        # registered-mode hook (the registry contract): a detect
        # result carrying its own extra artifacts (e.g. the
        # periodicity mode's folded profiles + candidate table,
        # pipeline/periodicity.py) hands (path, array) pairs here and
        # they ride the same temp+rename(+manifest) machinery as
        # every other artifact — this writer stays mode-blind.
        fold_paths = []
        extra = (getattr(work.detect, "extra_artifacts", None)
                 if work.detect is not None else None)
        if extra is not None:
            for path, payload in extra(base):
                if path.endswith(".npy"):
                    payload = _npy_bytes(payload)
                self._write_bytes(path, payload)
                fold_paths.append(path)

        self.written.append(CandidateFiles(bin_path, npy_paths,
                                           tim_paths, fold_paths))

    def _publish_staged(self) -> None:
        """Close the segment transaction: ONE publish barrier (all
        pending intents durable), then rename + commit every staged
        artifact.  A crash before the barrier leaves only temps
        (rolled back); between barrier and a rename, temps with
        durable intents (rolled back); after a rename, a committed or
        regenerable artifact — never an untracked final file."""
        staged, self._tx_staged = self._tx_staged, None
        if not staged:
            return
        self.manifest.sync()
        try:
            for path, tmp, fsync, commit in staged:
                if _PRE_RENAME_HOOK is not None:
                    _PRE_RENAME_HOOK(path)
                os.replace(tmp, path)
                if fsync:
                    fsync_dir(path)
                if commit is not None:
                    commit()
        except BaseException:
            for _path, tmp, _fsync, _commit in staged:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass  # already renamed, or the disk is truly gone
            raise

    def _tx_abort(self) -> None:
        staged, self._tx_staged = self._tx_staged, None
        for _path, tmp, _fsync, _commit in staged or ():
            try:
                os.unlink(tmp)
            except OSError:
                pass  # this artifact never reached its temp write

    def _write_bytes(self, path: str, data: np.ndarray, *,
                     fsync: bool = False) -> None:
        commit = manifest_stage(self.manifest, self._manifest_key,
                                path, data)
        barrier = self.manifest.sync if commit is not None else None
        if self._tx_staged is not None:
            tmp = stage_write(path, data.tobytes(), fsync=fsync)
            self._tx_staged.append((path, tmp, fsync, commit))
            return
        if self.pool is not None:
            if path in self._assigned_paths:
                # same target queued again (e.g. a piggybacked segment
                # sharing a packet counter): flush first so the later
                # write deterministically wins instead of racing
                self.pool.drain()
                self._assigned_paths.clear()
            self._assigned_paths.add(path)
            self.pool.submit(path, data, fsync=fsync, on_done=commit,
                             pre_publish=barrier)
            return
        # crash-consistent: a crash mid-write leaves an orphan temp
        # (swept at startup), never a torn candidate file
        atomic_write(path, data.tobytes(), fsync=fsync,
                     pre_rename=barrier)
        if commit is not None:
            commit()

    def drain(self) -> None:
        """Wait for queued async writes to land (no-op when synchronous).

        Raises ``RuntimeError`` if any queued write failed — the
        synchronous path would have raised at the failing ``open``/
        ``write``, and a silently lost candidate defeats the writer's
        purpose.
        """
        if self.pool is not None:
            self.pool.drain()
            self._assigned_paths.clear()
            self.pool.raise_new_errors(
                f"candidate prefix {self.cfg.baseband_output_file_prefix}")


class WriteAllSink:
    """Unconditional append of baseband minus the reserved tail to one file
    per stream (ref: pipeline/write_file_pipe.hpp:41-94, selected when
    ``baseband_write_all``).

    Synchronous by default, as in the reference (the write happens inline
    in the pipe body).  Passing a **single-thread** ``writer_pool`` makes
    appends asynchronous while keeping their order.
    """

    sheddable = True  # degradation ladder: baseband dumps shed at L2
    last_push_wrote = True  # every push appends: always seal done
    # canary quarantine (pipeline/runtime._push_sinks): this sink
    # appends the PRISTINE seg.data — the injected pulse never reaches
    # it — and its output is a contiguous byte stream, so skipping a
    # canary segment would corrupt the append continuity, not protect
    # anything.  Science-product sinks (waterfall writers) stay
    # non-exempt and are skipped for canary segments.
    canary_exempt = True

    def __init__(self, cfg: Config, reserved_bytes: int,
                 data_stream_id: int = 0, writer_pool=None):
        self.reserved_bytes = reserved_bytes
        path = (cfg.baseband_output_file_prefix
                + f"stream{data_stream_id}.bin")
        self.path = path
        self.pool = writer_pool
        if writer_pool is not None and writer_pool.n_threads != 1:
            raise ValueError("WriteAllSink needs a 1-thread pool "
                             "(ordered appends)")
        self._f = None if writer_pool is not None else open(path, "ab")
        # durable exactly-once (io/manifest.py): appends log an intent
        # carrying the pre-append file length, so recovery can
        # truncate a torn append back to the committed prefix.
        # _append_off tracks the SUBMITTED length (appends are
        # ordered); the manifest's committed length only advances at
        # each commit record.
        self.manifest = None
        self._manifest_key = None
        self._append_off = 0

    def bind_manifest(self, manifest) -> None:
        self.manifest = manifest
        try:
            # manifest recovery already truncated any torn tail, so
            # the current size IS the durable committed prefix
            self._append_off = os.path.getsize(self.path)
        except OSError:
            self._append_off = 0

    def set_manifest_key(self, key) -> None:
        self._manifest_key = key

    def push(self, work: SegmentResultWork, has_signal: bool = False) -> None:
        data = work.segment.data
        end = len(data) - self.reserved_bytes
        if end <= 0:
            end = len(data)
        chunk = np.ascontiguousarray(data[:end])
        m, key = self.manifest, self._manifest_key
        commit = None
        if m is not None and key is not None:
            off = self._append_off
            length = int(chunk.nbytes)
            crc = zlib.crc32(chunk) \
                if getattr(m, "hash_content", True) else None
            m.intent(key, self.path, mode="append", offset=off)

            def commit(m=m, key=key, path=self.path, length=length,
                       crc=crc, off=off):
                m.commit(key, path, length, crc, offset=off)

            self._append_off = off + length
        if self.pool is not None:
            self.pool.submit(self.path, chunk, append=True,
                             on_done=commit)
            return
        self._f.write(chunk.tobytes())
        self._f.flush()
        if commit is not None:
            commit()

    def drain(self) -> None:
        if self.pool is not None:
            self.pool.drain()
            self.pool.raise_new_errors(f"append to {self.path}")

    def close(self):
        if self._f is not None:
            self._f.close()


# ----------------------------------------------------------------
# sigproc filterbank header (ref: io/sigproc_filterbank.hpp)
# ----------------------------------------------------------------

def _fb_string(key: str) -> bytes:
    b = key.encode()
    return np.int32(len(b)).tobytes() + b


def _fb_int(key: str, value: int) -> bytes:
    return _fb_string(key) + np.int32(value).tobytes()


def _fb_double(key: str, value: float) -> bytes:
    return _fb_string(key) + np.float64(value).tobytes()


def encode_angle_dms(d: int, m: int, s: float) -> float:
    """Pack degrees/minutes/seconds as ddmmss.s, the sigproc convention
    (ref: io/sigproc_filterbank.hpp:59-70)."""
    sign = -1.0 if d < 0 else 1.0
    return sign * (abs(d) * 10000.0 + m * 100.0 + s)


def write_filterbank_header(f, *, telescope_id: int = 0, machine_id: int = 0,
                            data_type: int = 1, fch1: float = 0.0,
                            foff: float = 0.0, nchans: int = 0,
                            tsamp: float = 0.0, nbits: int = 32,
                            nifs: int = 1, tstart: float = 0.0,
                            src_raj: float = 0.0, src_dej: float = 0.0,
                            source_name: str = "unknown") -> None:
    """Serialize a sigproc filterbank header (keys as in the reference's
    io/sigproc_filterbank.hpp writer)."""
    f.write(_fb_string("HEADER_START"))
    f.write(_fb_string("source_name"))
    f.write(_fb_string(source_name))
    f.write(_fb_int("telescope_id", telescope_id))
    f.write(_fb_int("machine_id", machine_id))
    f.write(_fb_int("data_type", data_type))
    f.write(_fb_double("fch1", fch1))
    f.write(_fb_double("foff", foff))
    f.write(_fb_int("nchans", nchans))
    f.write(_fb_int("nbits", nbits))
    f.write(_fb_double("tstart", tstart))
    f.write(_fb_double("tsamp", tsamp))
    f.write(_fb_int("nifs", nifs))
    f.write(_fb_double("src_raj", src_raj))
    f.write(_fb_double("src_dej", src_dej))
    f.write(_fb_string("HEADER_END"))
