"""Output writers: candidate capture (.bin/.npy/.tim), write-all mode, and
the sigproc filterbank header.

File formats are byte-compatible with the reference so its offline plot
helpers (src/plot_spectrum.py, plot_tim.py) work unmodified:
- ``<prefix><counter>.bin``      raw baseband bytes of the segment
  (ref: write_signal_pipe.hpp:159-206);
- ``<prefix><counter>.<i>.npy``  complex64 spectrum waterfall, shape
  [freq_bins, time_samples] (ref: write_signal_pipe.hpp:209-246);
- ``<prefix><counter>.<boxcar>.tim``  raw float32 time series
  (ref: write_signal_pipe.hpp:249-280); batched multi-polarization
  results add a stream index: ``<prefix><counter>.s<stream>.<boxcar>.tim``
  (no reference equivalent — its streams are separate work items);
- the "piggybank" logic keeps recent negatives and writes them when they
  overlap (within 0.45 segment) a recent positive in another polarization
  (ref: write_signal_pipe.hpp:77-140).
"""

from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass

import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.pipeline.work import (NO_UDP_PACKET_COUNTER, SegmentResultWork)
from srtb_tpu.utils.logging import log


def _npy_bytes(arr: np.ndarray) -> np.ndarray:
    """Serialize an array in .npy format to a uint8 buffer (cnpy analog —
    the reference writes .npy via cnpy, write_signal_pipe.hpp:243-244)."""
    import io as _io
    bio = _io.BytesIO()
    np.save(bio, arr)
    return np.frombuffer(bio.getvalue(), dtype=np.uint8)


@dataclass
class CandidateFiles:
    """Paths written for one positive segment."""
    bin_path: str
    npy_paths: list
    tim_paths: list


class WriteSignalSink:
    """Candidate writer with the reference's piggybank capture policy.

    When ``writer_pool`` (an :class:`AsyncWriterPool`) is given, file
    writes are queued to its (native C++) thread pool and this sink never
    blocks on disk — the reference's async thread-pool behavior
    (write_signal_pipe.hpp:159-206 submits to boost thread pools).  Call
    ``drain()`` before reading the files back.
    """

    def __init__(self, cfg: Config, fdatasync: bool = True,
                 writer_pool=None):
        self.cfg = cfg
        self.fdatasync = fdatasync
        self.pool = writer_pool
        self._assigned_paths: set[str] = set()
        self.recent_positive_timestamps: deque[int] = deque()
        self.recent_negative_works: deque[SegmentResultWork] = deque()
        self.written: list[CandidateFiles] = []
        # check directory writability up front (ref: write_signal_pipe.hpp:62-75)
        check_path = cfg.baseband_output_file_prefix + ".check"
        with open(check_path, "wb"):
            pass
        os.unlink(check_path)

    # ------------------------------------------------------------------

    def _overlap_window_ns(self) -> float:
        # 0.45 of a segment duration, in ns (ref: write_signal_pipe.hpp:84-86)
        return (0.45 * 1e9 * self.cfg.baseband_input_count
                / self.cfg.baseband_sample_rate)

    def _overlaps_recent_positive(self, timestamp: int) -> bool:
        w = self._overlap_window_ns()
        return any(abs(timestamp - t) < w
                   for t in self.recent_positive_timestamps)

    def push(self, work: SegmentResultWork, has_signal: bool) -> None:
        """Feed one processed segment; writes to disk when warranted."""
        real_time = self.cfg.input_file_path == ""
        w = self._overlap_window_ns()
        ts = work.segment.timestamp

        # clean outdated positives (ref: write_signal_pipe.hpp:88-94)
        while (real_time and self.recent_positive_timestamps
               and ts - self.recent_positive_timestamps[0] > 5 * w):
            self.recent_positive_timestamps.popleft()

        to_write = None
        if has_signal:
            self.recent_positive_timestamps.append(ts)
            to_write = work
        elif real_time and self._overlaps_recent_positive(ts):
            # other-polarization piggyback (ref: write_signal_pipe.hpp:102-115)
            to_write = work
        elif real_time:
            self.recent_negative_works.append(work)

        # re-check old negatives against new positives (ref: 122-140)
        if real_time and to_write is None and self.recent_negative_works:
            work_2 = self.recent_negative_works.popleft()
            if self._overlaps_recent_positive(work_2.segment.timestamp):
                to_write = work_2

        if to_write is not None:
            self._write(to_write)

        # bound the negative queue (the reference relies on deque churn; we
        # cap explicitly to one overlap window's worth of segments)
        while len(self.recent_negative_works) > 16:
            self.recent_negative_works.popleft()

    # ------------------------------------------------------------------

    def _write(self, work: SegmentResultWork) -> None:
        counter = work.segment.udp_packet_counter
        if counter == NO_UDP_PACKET_COUNTER:
            counter = work.segment.timestamp
        base = self.cfg.baseband_output_file_prefix + str(counter)
        log.info(f"[write_signal] begin writing, file_counter = {counter}")

        bin_path = base + ".bin"
        self._write_bytes(bin_path,
                          np.ascontiguousarray(work.segment.data),
                          fsync=self.fdatasync)

        npy_paths = []
        if work.waterfall is not None:
            # the waterfall may still be device-resident (lazy sink-side
            # transfer): fetch via the explicit D2H spelling so the
            # sanitizer's transfer tripwire stays quiet on this
            # sanctioned sync
            from srtb_tpu.utils.platform import to_host
            wf = to_host(work.waterfall)
            if wf.ndim == 4:  # stacked (re, im) boundary representation
                wf = (wf[0] + 1j * wf[1]).astype(np.complex64)
            if wf.ndim == 2:
                wf = wf[None]
            for i in range(wf.shape[0]):
                # pick first non-existing index (ref: 230-235); with an
                # async pool queued-but-unwritten paths count as taken
                j = i
                while (os.path.exists(f"{base}.{j}.npy")
                       or f"{base}.{j}.npy" in self._assigned_paths):
                    j += 1
                path = f"{base}.{j}.npy"
                self._write_bytes(path, _npy_bytes(wf[i].astype(np.complex64)))
                npy_paths.append(path)

        tim_paths = []
        if work.detect is not None:
            counts = np.asarray(work.detect.signal_counts)
            series = np.asarray(work.detect.boxcar_series)
            if counts.ndim == 1:
                counts = counts[None]
                series = series[None]
            lengths = work.detect.boxcar_lengths
            multi = counts.shape[0] > 1
            for s in range(counts.shape[0]):
                for bi, b in enumerate(lengths):
                    if counts[s, bi] > 0:
                        # single-stream keeps the reference's exact name;
                        # batched multi-polarization results need a stream
                        # index or the streams would overwrite each other
                        path = (f"{base}.s{s}.{b}.tim" if multi
                                else f"{base}.{b}.tim")
                        valid = series.shape[-1] - (b if b > 1 else 0)
                        self._write_bytes(
                            path, series[s, bi, :valid].astype("<f4"))
                        tim_paths.append(path)

        self.written.append(CandidateFiles(bin_path, npy_paths, tim_paths))
        log.info(f"[write_signal] finished writing, file_counter = {counter}")

    def _write_bytes(self, path: str, data: np.ndarray, *,
                     fsync: bool = False) -> None:
        if self.pool is not None:
            if path in self._assigned_paths:
                # same target queued again (e.g. a piggybacked segment
                # sharing a packet counter): flush first so the later
                # write deterministically wins instead of racing
                self.pool.drain()
                self._assigned_paths.clear()
            self._assigned_paths.add(path)
            self.pool.submit(path, data, fsync=fsync)
            return
        with open(path, "wb") as f:
            f.write(data.tobytes())
            f.flush()
            if fsync:
                os.fdatasync(f.fileno())

    def drain(self) -> None:
        """Wait for queued async writes to land (no-op when synchronous).

        Raises ``RuntimeError`` if any queued write failed — the
        synchronous path would have raised at the failing ``open``/
        ``write``, and a silently lost candidate defeats the writer's
        purpose.
        """
        if self.pool is not None:
            self.pool.drain()
            self._assigned_paths.clear()
            self.pool.raise_new_errors(
                f"candidate prefix {self.cfg.baseband_output_file_prefix}")


class WriteAllSink:
    """Unconditional append of baseband minus the reserved tail to one file
    per stream (ref: pipeline/write_file_pipe.hpp:41-94, selected when
    ``baseband_write_all``).

    Synchronous by default, as in the reference (the write happens inline
    in the pipe body).  Passing a **single-thread** ``writer_pool`` makes
    appends asynchronous while keeping their order.
    """

    def __init__(self, cfg: Config, reserved_bytes: int,
                 data_stream_id: int = 0, writer_pool=None):
        self.reserved_bytes = reserved_bytes
        path = (cfg.baseband_output_file_prefix
                + f"stream{data_stream_id}.bin")
        self.path = path
        self.pool = writer_pool
        if writer_pool is not None and writer_pool.n_threads != 1:
            raise ValueError("WriteAllSink needs a 1-thread pool "
                             "(ordered appends)")
        self._f = None if writer_pool is not None else open(path, "ab")

    def push(self, work: SegmentResultWork, has_signal: bool = False) -> None:
        data = work.segment.data
        end = len(data) - self.reserved_bytes
        if end <= 0:
            end = len(data)
        chunk = np.ascontiguousarray(data[:end])
        if self.pool is not None:
            self.pool.submit(self.path, chunk, append=True)
            return
        self._f.write(chunk.tobytes())
        self._f.flush()

    def drain(self) -> None:
        if self.pool is not None:
            self.pool.drain()
            self.pool.raise_new_errors(f"append to {self.path}")

    def close(self):
        if self._f is not None:
            self._f.close()


# ----------------------------------------------------------------
# sigproc filterbank header (ref: io/sigproc_filterbank.hpp)
# ----------------------------------------------------------------

def _fb_string(key: str) -> bytes:
    b = key.encode()
    return np.int32(len(b)).tobytes() + b


def _fb_int(key: str, value: int) -> bytes:
    return _fb_string(key) + np.int32(value).tobytes()


def _fb_double(key: str, value: float) -> bytes:
    return _fb_string(key) + np.float64(value).tobytes()


def encode_angle_dms(d: int, m: int, s: float) -> float:
    """Pack degrees/minutes/seconds as ddmmss.s, the sigproc convention
    (ref: io/sigproc_filterbank.hpp:59-70)."""
    sign = -1.0 if d < 0 else 1.0
    return sign * (abs(d) * 10000.0 + m * 100.0 + s)


def write_filterbank_header(f, *, telescope_id: int = 0, machine_id: int = 0,
                            data_type: int = 1, fch1: float = 0.0,
                            foff: float = 0.0, nchans: int = 0,
                            tsamp: float = 0.0, nbits: int = 32,
                            nifs: int = 1, tstart: float = 0.0,
                            src_raj: float = 0.0, src_dej: float = 0.0,
                            source_name: str = "unknown") -> None:
    """Serialize a sigproc filterbank header (keys as in the reference's
    io/sigproc_filterbank.hpp writer)."""
    f.write(_fb_string("HEADER_START"))
    f.write(_fb_string("source_name"))
    f.write(_fb_string(source_name))
    f.write(_fb_int("telescope_id", telescope_id))
    f.write(_fb_int("machine_id", machine_id))
    f.write(_fb_int("data_type", data_type))
    f.write(_fb_double("fch1", fch1))
    f.write(_fb_double("foff", foff))
    f.write(_fb_int("nchans", nchans))
    f.write(_fb_int("nbits", nbits))
    f.write(_fb_double("tstart", tstart))
    f.write(_fb_double("tsamp", tsamp))
    f.write(_fb_int("nifs", nifs))
    f.write(_fb_double("src_raj", src_raj))
    f.write(_fb_double("src_dej", src_dej))
    f.write(_fb_string("HEADER_END"))
