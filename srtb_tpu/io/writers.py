"""Output writers: candidate capture (.bin/.npy/.tim), write-all mode, and
the sigproc filterbank header.

File formats are byte-compatible with the reference so its offline plot
helpers (src/plot_spectrum.py, plot_tim.py) work unmodified:
- ``<prefix><counter>.bin``      raw baseband bytes of the segment
  (ref: write_signal_pipe.hpp:159-206);
- ``<prefix><counter>.<i>.npy``  complex64 spectrum waterfall, shape
  [freq_bins, time_samples] (ref: write_signal_pipe.hpp:209-246);
- ``<prefix><counter>.<boxcar>.tim``  raw float32 time series
  (ref: write_signal_pipe.hpp:249-280);
- the "piggybank" logic keeps recent negatives and writes them when they
  overlap (within 0.45 segment) a recent positive in another polarization
  (ref: write_signal_pipe.hpp:77-140).
"""

from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass

import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.pipeline.work import (NO_UDP_PACKET_COUNTER, SegmentResultWork)
from srtb_tpu.utils.logging import log


@dataclass
class CandidateFiles:
    """Paths written for one positive segment."""
    bin_path: str
    npy_paths: list
    tim_paths: list


class WriteSignalSink:
    """Candidate writer with the reference's piggybank capture policy."""

    def __init__(self, cfg: Config, fdatasync: bool = True):
        self.cfg = cfg
        self.fdatasync = fdatasync
        self.recent_positive_timestamps: deque[int] = deque()
        self.recent_negative_works: deque[SegmentResultWork] = deque()
        self.written: list[CandidateFiles] = []
        # check directory writability up front (ref: write_signal_pipe.hpp:62-75)
        check_path = cfg.baseband_output_file_prefix + ".check"
        with open(check_path, "wb"):
            pass
        os.unlink(check_path)

    # ------------------------------------------------------------------

    def _overlap_window_ns(self) -> float:
        # 0.45 of a segment duration, in ns (ref: write_signal_pipe.hpp:84-86)
        return (0.45 * 1e9 * self.cfg.baseband_input_count
                / self.cfg.baseband_sample_rate)

    def _overlaps_recent_positive(self, timestamp: int) -> bool:
        w = self._overlap_window_ns()
        return any(abs(timestamp - t) < w
                   for t in self.recent_positive_timestamps)

    def push(self, work: SegmentResultWork, has_signal: bool) -> None:
        """Feed one processed segment; writes to disk when warranted."""
        real_time = self.cfg.input_file_path == ""
        w = self._overlap_window_ns()
        ts = work.segment.timestamp

        # clean outdated positives (ref: write_signal_pipe.hpp:88-94)
        while (real_time and self.recent_positive_timestamps
               and ts - self.recent_positive_timestamps[0] > 5 * w):
            self.recent_positive_timestamps.popleft()

        to_write = None
        if has_signal:
            self.recent_positive_timestamps.append(ts)
            to_write = work
        elif real_time and self._overlaps_recent_positive(ts):
            # other-polarization piggyback (ref: write_signal_pipe.hpp:102-115)
            to_write = work
        elif real_time:
            self.recent_negative_works.append(work)

        # re-check old negatives against new positives (ref: 122-140)
        if real_time and to_write is None and self.recent_negative_works:
            work_2 = self.recent_negative_works.popleft()
            if self._overlaps_recent_positive(work_2.segment.timestamp):
                to_write = work_2

        if to_write is not None:
            self._write(to_write)

        # bound the negative queue (the reference relies on deque churn; we
        # cap explicitly to one overlap window's worth of segments)
        while len(self.recent_negative_works) > 16:
            self.recent_negative_works.popleft()

    # ------------------------------------------------------------------

    def _write(self, work: SegmentResultWork) -> None:
        counter = work.segment.udp_packet_counter
        if counter == NO_UDP_PACKET_COUNTER:
            counter = work.segment.timestamp
        base = self.cfg.baseband_output_file_prefix + str(counter)
        log.info(f"[write_signal] begin writing, file_counter = {counter}")

        bin_path = base + ".bin"
        with open(bin_path, "wb") as f:
            f.write(np.ascontiguousarray(work.segment.data).tobytes())
            f.flush()
            if self.fdatasync:
                os.fdatasync(f.fileno())

        npy_paths = []
        if work.waterfall is not None:
            wf = np.asarray(work.waterfall)
            if wf.ndim == 4:  # stacked (re, im) boundary representation
                wf = (wf[0] + 1j * wf[1]).astype(np.complex64)
            if wf.ndim == 2:
                wf = wf[None]
            for i in range(wf.shape[0]):
                # pick first non-existing index (ref: 230-235)
                j = i
                while os.path.exists(f"{base}.{j}.npy"):
                    j += 1
                path = f"{base}.{j}.npy"
                np.save(path, wf[i].astype(np.complex64))
                npy_paths.append(path)

        tim_paths = []
        if work.detect is not None:
            counts = np.asarray(work.detect.signal_counts)
            series = np.asarray(work.detect.boxcar_series)
            if counts.ndim == 1:
                counts = counts[None]
                series = series[None]
            lengths = work.detect.boxcar_lengths
            for s in range(counts.shape[0]):
                for bi, b in enumerate(lengths):
                    if counts[s, bi] > 0:
                        path = f"{base}.{b}.tim"
                        valid = series.shape[-1] - (b if b > 1 else 0)
                        series[s, bi, :valid].astype("<f4").tofile(path)
                        tim_paths.append(path)

        self.written.append(CandidateFiles(bin_path, npy_paths, tim_paths))
        log.info(f"[write_signal] finished writing, file_counter = {counter}")


class WriteAllSink:
    """Unconditional append of baseband minus the reserved tail to one file
    per stream (ref: pipeline/write_file_pipe.hpp:41-94, selected when
    ``baseband_write_all``)."""

    def __init__(self, cfg: Config, reserved_bytes: int,
                 data_stream_id: int = 0):
        self.reserved_bytes = reserved_bytes
        path = (cfg.baseband_output_file_prefix
                + f"stream{data_stream_id}.bin")
        self.path = path
        self._f = open(path, "ab")

    def push(self, work: SegmentResultWork, has_signal: bool = False) -> None:
        data = work.segment.data
        end = len(data) - self.reserved_bytes
        if end <= 0:
            end = len(data)
        self._f.write(np.ascontiguousarray(data[:end]).tobytes())
        self._f.flush()

    def close(self):
        self._f.close()


# ----------------------------------------------------------------
# sigproc filterbank header (ref: io/sigproc_filterbank.hpp)
# ----------------------------------------------------------------

def _fb_string(key: str) -> bytes:
    b = key.encode()
    return np.int32(len(b)).tobytes() + b


def _fb_int(key: str, value: int) -> bytes:
    return _fb_string(key) + np.int32(value).tobytes()


def _fb_double(key: str, value: float) -> bytes:
    return _fb_string(key) + np.float64(value).tobytes()


def encode_angle_dms(d: int, m: int, s: float) -> float:
    """Pack degrees/minutes/seconds as ddmmss.s, the sigproc convention
    (ref: io/sigproc_filterbank.hpp:59-70)."""
    sign = -1.0 if d < 0 else 1.0
    return sign * (abs(d) * 10000.0 + m * 100.0 + s)


def write_filterbank_header(f, *, telescope_id: int = 0, machine_id: int = 0,
                            data_type: int = 1, fch1: float = 0.0,
                            foff: float = 0.0, nchans: int = 0,
                            tsamp: float = 0.0, nbits: int = 32,
                            nifs: int = 1, tstart: float = 0.0,
                            src_raj: float = 0.0, src_dej: float = 0.0,
                            source_name: str = "unknown") -> None:
    """Serialize a sigproc filterbank header (keys as in the reference's
    io/sigproc_filterbank.hpp writer)."""
    f.write(_fb_string("HEADER_START"))
    f.write(_fb_string("source_name"))
    f.write(_fb_string(source_name))
    f.write(_fb_int("telescope_id", telescope_id))
    f.write(_fb_int("machine_id", machine_id))
    f.write(_fb_int("data_type", data_type))
    f.write(_fb_double("fch1", fch1))
    f.write(_fb_double("foff", foff))
    f.write(_fb_int("nchans", nchans))
    f.write(_fb_int("nbits", nbits))
    f.write(_fb_double("tstart", tstart))
    f.write(_fb_double("tsamp", tsamp))
    f.write(_fb_int("nifs", nifs))
    f.write(_fb_double("src_raj", src_raj))
    f.write(_fb_double("src_dej", src_dej))
    f.write(_fb_string("HEADER_END"))
