"""Baseband file reader with overlap-save seek-back.

Mirrors read_file_pipe (ref: pipeline/read_file_pipe.hpp:31-127):
- skip ``input_file_offset_bytes`` first;
- each call reads ``baseband_input_count * |bits|/8 * data_stream_count``
  bytes into a zero-filled buffer (short final reads stay zero-padded);
- then seeks back ``nsamps_reserved`` samples' worth of bytes so
  consecutive segments overlap (the overlap-save "long-context" mechanism);
- a logical byte counter, not the stream position, tracks progress because
  the final partial segment reads past EOF.
"""

from __future__ import annotations

import time

import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.io import formats
from srtb_tpu.ops import dedisperse as dd
from srtb_tpu.pipeline.work import SegmentWork
from srtb_tpu.utils.bufferpool import BufferPool
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

# process-wide segment-buffer pool (ref: srtb::host_allocator singleton,
# global_variables.hpp:49-61)
host_buffer_pool = BufferPool("segments")


class BasebandFileReader:
    """Iterates SegmentWork items from a raw baseband file."""

    def __init__(self, cfg: Config, buffer_pool: BufferPool | None = None,
                 start_offset_bytes: int | None = None):
        self.cfg = cfg
        self.fmt = formats.resolve(cfg.baseband_format_type)
        self.segment_bytes = cfg.segment_bytes(self.fmt.data_stream_count)
        nsamps = dd.nsamps_reserved(cfg)
        self.reserved_bytes = int(nsamps * abs(cfg.baseband_input_bits)
                                  // 8 * self.fmt.data_stream_count)
        self.pool = buffer_pool or host_buffer_pool
        self._file = open(cfg.input_file_path, "rb")
        start = (start_offset_bytes if start_offset_bytes is not None
                 else cfg.input_file_offset_bytes)
        self._file.seek(start)
        # logical byte counter (ref: read_file_pipe.hpp:47-55): tracks where
        # the next segment starts, even past EOF zero-padding
        self.logical_offset = start
        self._exhausted = False

    def __iter__(self):
        return self

    def __next__(self) -> SegmentWork:
        if self._exhausted:
            raise StopIteration
        buf = self.pool.acquire(self.segment_bytes)
        try:
            chunk = self._file.read(self.segment_bytes)
        except BaseException:
            # a failed read may be retried by the pipeline's ingest
            # guard, which calls __next__ again and acquires a fresh
            # buffer — this one must go back or every retried
            # transient strands a segment-sized block in the pool
            self.pool.release(buf)
            raise
        if len(chunk) == 0:
            self.pool.release(buf)
            log.info(f"[read_file] {self.cfg.input_file_path} has been read")
            self._exhausted = True
            raise StopIteration
        buf[:len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
        # ingest telemetry: windowed read throughput + pool occupancy
        # gauges (the host-buffer analog of the receiver ring gauges)
        metrics.add("file_bytes_read", len(chunk))
        metrics.window("file_bytes_read").add(len(chunk))
        pool_stats = self.pool.stats()
        metrics.set("segment_pool_cached_blocks",
                    pool_stats["cached_blocks"])
        metrics.set("segment_pool_cached_bytes",
                    pool_stats["cached_bytes"])
        metrics.set("segment_pool_in_use", pool_stats["in_use"])
        self.logical_offset += self.segment_bytes
        if len(chunk) < self.segment_bytes:
            # final partial segment: emit zero-padded, then stop
            # (ref: read_file_pipe.hpp:76-77 memset + short read)
            self._exhausted = True
        elif 0 < self.reserved_bytes < self.segment_bytes:
            # overlap-save: rewind so the next segment reprocesses the
            # dedispersion-corrupted tail (ref: read_file_pipe.hpp:86-99)
            self.logical_offset -= self.reserved_bytes
            self._file.seek(-self.reserved_bytes, 1)
        return SegmentWork(
            data=buf,
            timestamp=time.time_ns(),
        )

    def close(self):
        self._file.close()
