"""Baseband file reader with overlap-save seek-back.

Mirrors read_file_pipe (ref: pipeline/read_file_pipe.hpp:31-127):
- skip ``input_file_offset_bytes`` first;
- each call reads ``baseband_input_count * |bits|/8 * data_stream_count``
  bytes into a zero-filled buffer (short final reads stay zero-padded);
- then seeks back ``nsamps_reserved`` samples' worth of bytes so
  consecutive segments overlap (the overlap-save "long-context" mechanism);
- a logical byte counter, not the stream position, tracks progress because
  the final partial segment reads past EOF.

Skip-read fast path (ingest ring, ``Config.ingest_ring`` != "off"):
once a segment has been emitted, its reserved tail is retained in host
memory, so the next segment reads only the stride's NEW bytes from disk
— no seek-back, no re-read of bytes the reader just delivered — and the
head is a host memcpy of the retained tail.  The emitted byte stream is
bit-identical to the legacy seek-back path, and the ``reserved_bytes``
bookkeeping (``logical_offset`` advancing by ``segment - reserved`` per
segment) is UNCHANGED, so checkpoints written either way resume
identically; a resume (or any start) has no retained tail and takes the
full-read path as the cold fallback.
"""

from __future__ import annotations

import time

import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.io import formats
from srtb_tpu.ops import dedisperse as dd
from srtb_tpu.pipeline.work import SegmentWork
from srtb_tpu.utils.bufferpool import BufferPool
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

# process-wide segment-buffer pool (ref: srtb::host_allocator singleton,
# global_variables.hpp:49-61)
host_buffer_pool = BufferPool("segments")


class BasebandFileReader:
    """Iterates SegmentWork items from a raw baseband file."""

    def __init__(self, cfg: Config, buffer_pool: BufferPool | None = None,
                 start_offset_bytes: int | None = None):
        self.cfg = cfg
        self.fmt = formats.resolve(cfg.baseband_format_type)
        self.segment_bytes = cfg.segment_bytes(self.fmt.data_stream_count)
        nsamps = dd.nsamps_reserved(cfg)
        self.reserved_bytes = int(nsamps * abs(cfg.baseband_input_bits)
                                  // 8 * self.fmt.data_stream_count)
        self.pool = buffer_pool or host_buffer_pool
        self._file = open(cfg.input_file_path, "rb")
        start = (start_offset_bytes if start_offset_bytes is not None
                 else cfg.input_file_offset_bytes)
        self._file.seek(start)
        # logical byte counter (ref: read_file_pipe.hpp:47-55): tracks where
        # the next segment starts, even past EOF zero-padding
        self.logical_offset = start
        self._exhausted = False
        # skip-read fast path: the retained reserved tail of the last
        # emitted segment (None = cold, take the full-read + seek-back
        # path).  Gated on the ingest-ring knob so "off" restores the
        # reference's exact read pattern.
        self._skip_read = (
            str(getattr(cfg, "ingest_ring", "auto")).lower() != "off"
            and 0 < self.reserved_bytes < self.segment_bytes)
        # shared tail-retention + seq-stamping contract (io/overlap.py);
        # seek-back segments overlap too, so seq is always stamped —
        # only the tail retention is gated on the skip-read path
        from srtb_tpu.io.overlap import OverlapTailCarry
        self._carry = OverlapTailCarry(self.reserved_bytes)

    def __iter__(self):
        return self

    def __next__(self) -> SegmentWork:
        if self._exhausted:
            raise StopIteration
        buf = self.pool.acquire(self.segment_bytes)
        warm = self._skip_read and self._carry.warm
        reserved = self.reserved_bytes if warm else 0
        try:
            chunk = self._file.read(self.segment_bytes - reserved)
        except BaseException:
            # a failed read may be retried by the pipeline's ingest
            # guard, which calls __next__ again and acquires a fresh
            # buffer — this one must go back or every retried
            # transient strands a segment-sized block in the pool
            self.pool.release(buf)
            raise
        if len(chunk) == 0 and not warm:
            self.pool.release(buf)
            log.info(f"[read_file] {self.cfg.input_file_path} has been read")
            self._exhausted = True
            raise StopIteration
        if warm:
            # head = retained tail (host memcpy replaces the legacy
            # seek-back disk re-read, bit-identically); with 0 new
            # bytes this still emits the tail + zeros final segment
            # the seek-back path would have produced
            self._carry.head_into(buf)
        buf[reserved:reserved + len(chunk)] = np.frombuffer(
            chunk, dtype=np.uint8)
        # ingest telemetry: windowed read throughput + pool occupancy
        # gauges (the host-buffer analog of the receiver ring gauges)
        metrics.add("file_bytes_read", len(chunk))
        metrics.window("file_bytes_read").add(len(chunk))
        pool_stats = self.pool.stats()
        metrics.set("segment_pool_cached_blocks",
                    pool_stats["cached_blocks"])
        metrics.set("segment_pool_cached_bytes",
                    pool_stats["cached_bytes"])
        metrics.set("segment_pool_in_use", pool_stats["in_use"])
        self.logical_offset += self.segment_bytes
        if len(chunk) < self.segment_bytes - reserved:
            # final partial segment: emit zero-padded, then stop
            # (ref: read_file_pipe.hpp:76-77 memset + short read).
            # Warm short reads land here too: a file ending exactly at
            # a segment boundary still yields the same trailing
            # tail-plus-zeros segment the seek-back path emits.
            self._exhausted = True
        elif 0 < self.reserved_bytes < self.segment_bytes:
            # overlap-save: the next segment reprocesses the
            # dedispersion-corrupted tail (ref: read_file_pipe.hpp:86-99)
            # — by retaining it in host memory (skip-read: the next
            # read starts at the stride boundary, where the file
            # position already is) or by the legacy seek-back re-read.
            # logical_offset bookkeeping is identical either way.
            self.logical_offset -= self.reserved_bytes
            if self._skip_read:
                self._carry.retain(buf)
            else:
                self._file.seek(-self.reserved_bytes, 1)
        return SegmentWork(
            data=buf,
            timestamp=time.time_ns(),
            seq=self._carry.next_seq(),
        )

    def close(self):
        self._file.close()


# fixed epoch the deterministic stamps count from (an arbitrary 2023
# instant): stamps must be stable across processes, so the wall clock
# can play no part
DETERMINISTIC_EPOCH_NS = 1_700_000_000_000_000_000


class DeterministicTimestampReader(BasebandFileReader):
    """File reader stamping ``timestamp`` from the segment's STREAM
    OFFSET instead of the wall clock: the same segment gets the same
    stamp in every run and every resume, so file-mode artifact names
    (timestamp-derived when no UDP counter exists) are reproducible
    across runs.  This is what makes an archive replay's output set
    (paths + SHA-256) comparable byte-for-byte against a golden run —
    and what the crash/archive soaks' exactly-once equality gates are
    built on.  Promoted from the crash-soak tool (PR 10) to a
    first-class reader option (``Config.deterministic_timestamps``)
    so the soaks and the archive replay engine share ONE
    implementation."""

    def __next__(self) -> SegmentWork:
        offset = self.logical_offset
        work = super().__next__()
        work.timestamp = DETERMINISTIC_EPOCH_NS + offset
        return work


def make_file_source(cfg: Config,
                     buffer_pool: BufferPool | None = None,
                     start_offset_bytes: int | None = None
                     ) -> BasebandFileReader:
    """The config-selected file source: the deterministic-timestamp
    reader when ``Config.deterministic_timestamps`` is set, the
    wall-clock reader otherwise.  The single construction point the
    Pipeline, the archive replay engine and the soak harnesses all
    use."""
    cls = (DeterministicTimestampReader
           if getattr(cfg, "deterministic_timestamps", False)
           else BasebandFileReader)
    return cls(cfg, buffer_pool=buffer_pool,
               start_offset_bytes=start_offset_bytes)
