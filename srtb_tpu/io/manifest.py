"""Durable exactly-once outputs: the run-manifest commit log.

The reference is fire-and-forget: a crashed process loses or duplicates
whatever its sinks were writing (SURVEY.md §5.4 — its only durability
analogs are FFTW wisdom and ``input_file_offset_bytes``).  PRs 4/9
hardened *in-process* failures; this module closes the remaining gap:
**process death** (``kill -9``, node preemption, power loss) between a
sink write and the checkpoint update.

The manifest is an append-only, fsync'd JSONL write-ahead log living
next to the run's outputs (``Config.run_manifest_path``).  Every
record carries a CRC32 of its own canonical JSON, so a torn tail (the
record being appended when the process died) is detected and truncated
on recovery instead of being half-parsed.  Artifacts are keyed by
``(data_stream_id, segment index, sink name)`` — the *resume-continuous
drain index*, the same numbering the checkpoint counts — and follow an
intent→commit protocol:

- ``intent``     logged (and fsync'd) BEFORE a sink starts the temp
  write, so no artifact can reach its final name without the WAL
  knowing about it;
- ``commit``     logged after the atomic rename (or ordered append)
  published the artifact, with its length and content CRC32;
- ``done``       logged when a sink finished its whole push for one
  segment — the replay-skip marker;
- ``ckpt``       the checkpoint's consistency point: written by
  ``StreamCheckpoint.update`` BEFORE the checkpoint file itself, so
  the checkpoint can never claim progress the manifest hasn't sealed
  ("checkpoint ahead of manifest" is therefore always corruption, and
  ``tools/fsck.py`` flags it).

Recovery (:func:`recover`, run by ``Pipeline.__init__`` when the
manifest is armed) reconciles WAL vs filesystem:

- truncate the torn WAL tail at the first bad CRC;
- a ``(stream, seg, sink)`` group is **complete** when its ``done``
  marker exists, every intent has a commit, and every committed
  artifact still exists with the committed size — complete groups form
  the durable done-set, and a resumed run SKIPS their sink pushes
  (``replayed_skips``) instead of duplicating them under fresh names;
- any other group at/after the last checkpoint is **rolled back
  whole** (temp files unlinked, renamed-but-uncommitted finals
  unlinked, torn appends truncated to the committed prefix —
  ``rolled_back_intents``): the resumed run re-drains that segment and
  regenerates the group from scratch, exactly once;
- an incomplete or missing group BELOW the checkpoint cannot be
  regenerated (the resume will never re-drain it) — that is real data
  loss and is flagged loudly, never silently repaired.

``recovered_segments`` counts distinct segments whose complete groups
lie at/after the checkpoint — the segments rescued from the
duplicate-on-resume window.

Trust ends at the first bad CRC.  A record forged or bit-rotted in the
MIDDLE of the WAL truncates everything after it: later commits are
forgotten, their segments re-drain on resume (the checkpoint records
after the corruption truncate with them), and artifacts those
forgotten commits had published become UNTRACKED files — detected by
fsck's torn-WAL error and the crash-soak union gate, but not deleted
(recovery only ever removes files the valid WAL prefix names).  That
is the deliberate boundary: crashes are healed automatically,
mid-file corruption is detected loudly and left to the operator.

The WAL grows across resumes of one run (recovery re-reads it whole);
it belongs to ONE logical run in ONE output directory — start fresh
runs with a fresh manifest path.  Compaction is future work.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from dataclasses import dataclass, field

from srtb_tpu.utils import events
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

# same temp suffix as io/writers.atomic_write: an uncommitted intent's
# in-flight temp is <path> + TMP_SUFFIX
TMP_SUFFIX = ".srtb_tmp"


# ----------------------------------------------------------------
# record encoding: one JSON object per line, "c" = CRC32 of the
# canonical JSON (sorted keys, compact separators) of the record
# WITHOUT "c"
# ----------------------------------------------------------------

def record_crc(rec: dict) -> int:
    """CRC32 of a record's canonical JSON form (shared with the
    checkpoint file's integrity field, pipeline/checkpoint.py)."""
    body = json.dumps(rec, sort_keys=True, separators=(",", ":"))
    return zlib.crc32(body.encode())


def encode_record(rec: dict) -> bytes:
    out = dict(rec)
    out["c"] = record_crc(rec)
    return (json.dumps(out, sort_keys=True, separators=(",", ":"))
            + "\n").encode()


def decode_record(line: bytes) -> dict | None:
    """Parse + CRC-verify one WAL line; None = torn/forged."""
    try:
        rec = json.loads(line)
    except ValueError:
        return None
    if not isinstance(rec, dict):
        return None
    crc = rec.pop("c", None)
    if crc is None or record_crc(rec) != crc:
        return None
    return rec


# ----------------------------------------------------------------
# scan: pure read of a WAL into structured state
# ----------------------------------------------------------------

@dataclass
class Artifact:
    """Latest intent/commit state of one path within one group."""
    path: str                   # absolute
    mode: str = "atomic"        # "atomic" | "append"
    committed: bool = False
    length: int | None = None
    crc32: int | None = None
    offset: int | None = None   # append: file length before the append


@dataclass
class Group:
    """One (stream, seg, sink) artifact group."""
    artifacts: dict = field(default_factory=dict)  # path -> Artifact
    done: bool = False


@dataclass
class ManifestScan:
    path: str
    groups: dict = field(default_factory=dict)   # key tuple -> Group
    checkpoints: list = field(default_factory=list)  # ckpt records in order
    records: int = 0
    valid_bytes: int = 0
    total_bytes: int = 0
    bad_line: int | None = None     # 1-based line of the first bad record

    @property
    def torn(self) -> bool:
        return self.valid_bytes < self.total_bytes

    @property
    def last_checkpoint(self) -> dict | None:
        return self.checkpoints[-1] if self.checkpoints else None

    def checkpoint_floor(self) -> int:
        """segments_done of the last ckpt record (0 when none): every
        group below this index is sealed — complete by contract."""
        last = self.last_checkpoint
        return int(last["segments_done"]) if last else 0


def _abs_path(manifest_path: str, p: str) -> str:
    if os.path.isabs(p):
        return p
    return os.path.join(os.path.dirname(os.path.abspath(manifest_path)), p)


def _rel_path_from(base: str, p: str) -> str:
    """Store paths relative to the manifest's directory when possible,
    so a relocated run directory stays verifiable.  ``base`` is the
    pre-computed ``dirname(abspath(manifest))`` — this runs per record
    on the sink path, so the fast prefix check comes first."""
    if p.startswith(base + os.sep) and ".." not in p and "//" not in p:
        return p[len(base) + 1:]
    ap = os.path.abspath(p)
    if os.path.commonpath([base, ap]) == base:
        return os.path.relpath(ap, base)
    return ap


def scan_manifest(path: str) -> ManifestScan:
    """Read a WAL into per-group state, stopping at the first record
    whose CRC fails (everything after an invalid record is untrusted —
    the torn-tail truncation point)."""
    scan = ManifestScan(path=path)
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return scan
    scan.total_bytes = len(data)
    offset = 0
    lineno = 0
    for raw in data.split(b"\n"):
        if not raw:
            offset += 1  # the newline itself (or trailing empty slice)
            continue
        lineno += 1
        rec = decode_record(raw)
        if rec is None:
            scan.bad_line = lineno
            break
        offset += len(raw) + 1
        scan.valid_bytes = min(offset, scan.total_bytes)
        scan.records += 1
        t = rec.get("t")
        if t in ("intent", "commit"):
            key = (int(rec["stream"]), int(rec["seg"]), str(rec["sink"]))
            grp = scan.groups.setdefault(key, Group())
            p = _abs_path(path, rec["path"])
            art = grp.artifacts.get(p)
            if art is None:
                art = grp.artifacts[p] = Artifact(path=p)
            art.mode = rec.get("mode", art.mode)
            if rec.get("off") is not None:
                art.offset = int(rec["off"])
            if t == "commit":
                art.committed = True
                art.length = int(rec["len"])
                art.crc32 = (int(rec["crc32"])
                             if rec.get("crc32") is not None else None)
            else:
                # a fresh intent for an already-committed path is a
                # retry re-entry; the earlier commit stands
                if not art.committed and rec.get("len") is not None:
                    art.length = int(rec["len"])
        elif t == "done":
            key = (int(rec["stream"]), int(rec["seg"]), str(rec["sink"]))
            scan.groups.setdefault(key, Group()).done = True
        elif t == "ckpt":
            scan.checkpoints.append(rec)
        # "run" records (run/resume stamps) carry no recovery state
    return scan


def append_committed_lengths(scan: ManifestScan,
                             complete_keys=None) -> dict:
    """path -> durable committed length for append-mode artifacts.
    With ``complete_keys`` given, only appends belonging to those
    groups count (an incomplete group's committed append is rolled
    back with the rest of its group)."""
    out: dict[str, int] = {}
    for key, grp in scan.groups.items():
        if complete_keys is not None and key not in complete_keys:
            continue
        for art in grp.artifacts.values():
            if art.mode == "append" and art.committed:
                end = int(art.offset or 0) + int(art.length or 0)
                out[art.path] = max(out.get(art.path, 0), end)
    for key, grp in scan.groups.items():
        for art in grp.artifacts.values():
            if art.mode == "append":
                out.setdefault(art.path, 0)
    return out


def group_complete(grp: Group) -> bool:
    """done marker present AND every intent committed (artifact
    existence is checked separately — it needs the filesystem)."""
    return grp.done and all(a.committed for a in grp.artifacts.values())


# ----------------------------------------------------------------
# recovery
# ----------------------------------------------------------------

@dataclass
class RecoveryReport:
    done: set = field(default_factory=set)  # complete (stream,seg,sink)
    last_checkpoint: dict | None = None
    truncated_bytes: int = 0
    rolled_back: list = field(default_factory=list)   # action strings
    rolled_back_intents: int = 0
    missing: list = field(default_factory=list)       # loss, flagged
    recovered_segments: int = 0


def _artifact_on_disk(art: Artifact) -> bool:
    try:
        st = os.stat(art.path)
    except OSError:
        return False
    return art.length is None or st.st_size == art.length


def recover(manifest_path: str, apply: bool = True,
            checkpoint_floor_hint: int = 0) -> RecoveryReport:
    """Reconcile WAL vs filesystem (module docstring has the rules).
    ``apply=False`` reports without touching the filesystem (fsck has
    its own report-oriented pass on the same shared scan/group
    helpers; this flag serves tests and dry runs).

    ``checkpoint_floor_hint`` is the checkpoint FILE's
    ``segments_done`` (the resume authority).  Normally it can never
    exceed the manifest's own floor (update() seals the WAL first) —
    but a truncated/corrupted WAL can FORGET ckpt records, and
    rolling back 'incomplete' groups in that gap would destroy
    published artifacts the resume will never re-drain.  The
    effective floor is the max of both, so the gap is flagged as
    possible loss instead of deleted."""
    report = RecoveryReport()
    scan = scan_manifest(manifest_path)
    report.last_checkpoint = scan.last_checkpoint
    floor = scan.checkpoint_floor()
    if checkpoint_floor_hint > floor:
        if scan.records:
            log.error(
                f"[manifest] checkpoint file claims "
                f"{checkpoint_floor_hint} segment(s) done but the WAL "
                f"only seals {floor}: treating the gap as sealed — "
                "artifacts there are flagged, never rolled back "
                "(corrupt/truncated WAL, or a checkpoint from another "
                "run)")
        floor = checkpoint_floor_hint

    if scan.torn:
        report.truncated_bytes = scan.total_bytes - scan.valid_bytes
        if apply:
            with open(manifest_path, "rb+") as f:
                f.truncate(scan.valid_bytes)
            log.warning(
                f"[manifest] truncated torn WAL tail: "
                f"{report.truncated_bytes} byte(s) after record "
                f"{scan.records} failed CRC/parse")

    # pass 1: classify groups (existence check included — a committed
    # artifact that vanished invalidates its group so the resume can
    # regenerate it where the checkpoint allows)
    complete: set = set()
    for key, grp in scan.groups.items():
        if not group_complete(grp):
            continue
        atomic_ok = all(_artifact_on_disk(a)
                        for a in grp.artifacts.values()
                        if a.mode == "atomic")
        if atomic_ok:
            complete.add(key)
        elif key[1] < floor:
            # below the checkpoint the segment will never re-drain:
            # this is unrecoverable loss, flagged, files untouched
            gone = [a.path for a in grp.artifacts.values()
                    if a.mode == "atomic" and not _artifact_on_disk(a)]
            report.missing.append(
                f"committed artifact(s) missing under checkpoint "
                f"(segment {key[1]}, sink {key[2]}): "
                f"{[os.path.basename(p) for p in gone]}")

    # append files: the durable prefix is what COMPLETE groups committed
    append_targets = append_committed_lengths(scan, complete_keys=complete)

    # pass 2: roll back every group that is not complete and sits
    # at/after the checkpoint (the resume re-drains those segments)
    for key, grp in scan.groups.items():
        if key in complete:
            continue
        if key[1] < floor:
            if key not in complete and not group_complete(grp):
                report.missing.append(
                    f"incomplete artifact group under checkpoint "
                    f"(segment {key[1]}, sink {key[2]}): the manifest "
                    "ordering contract was violated upstream")
            continue
        for art in grp.artifacts.values():
            if art.mode == "append":
                continue  # handled via append_targets truncation below
            # counted per artifact actually on disk: the WAL keeps the
            # stale intent records forever, and recovery must not
            # re-report a rollback it already performed last startup
            rolled_this = False
            for p in (art.path + TMP_SUFFIX, art.path):
                if os.path.exists(p):
                    rolled_this = True
                    report.rolled_back.append(f"unlink {p}")
                    if apply:
                        try:
                            os.unlink(p)
                        except OSError as e:
                            log.warning(
                                f"[manifest] rollback cannot remove "
                                f"{p}: {e}")
            if rolled_this:
                report.rolled_back_intents += 1

    # pass 3: truncate append files to their committed prefix (rolls
    # back both torn appends and committed appends of incomplete
    # groups); a file SHORTER than the committed prefix is loss —
    # drop the groups it invalidates so a resume can regenerate the
    # ones the checkpoint still re-drains.
    #
    # Append paths with an incomplete group BELOW the effective floor
    # (a WAL that forgot commit records under a checkpoint — the hint
    # gap) are exempt from truncation entirely: bytes beyond the
    # surviving committed prefix may well BE that forgotten sealed
    # data, and the resume would never re-append it — flag, never cut.
    gap_paths = {
        art.path
        for key, grp in scan.groups.items()
        if key[1] < floor and key not in complete
        for art in grp.artifacts.values() if art.mode == "append"}
    for p, target in append_targets.items():
        try:
            size = os.path.getsize(p)
        except OSError:
            size = 0
        if size > target and p in gap_paths:
            report.missing.append(
                f"append file {os.path.basename(p)}: {size - target} "
                f"byte(s) beyond the surviving committed prefix belong "
                "to segment(s) sealed under the checkpoint but "
                "forgotten by the WAL — left untouched")
            continue
        if size > target:
            report.rolled_back.append(f"truncate {p} to {target}")
            report.rolled_back_intents += 1
            if apply:
                try:
                    with open(p, "rb+") as f:
                        f.truncate(target)
                except OSError as e:
                    log.warning(f"[manifest] rollback cannot truncate "
                                f"{p}: {e}")
        elif size < target:
            for key in sorted(complete):
                grp = scan.groups[key]
                bad = any(a.mode == "append" and a.path == p
                          and int(a.offset or 0) + int(a.length or 0)
                          > size
                          for a in grp.artifacts.values())
                if bad:
                    complete.discard(key)
                    msg = (f"append file {os.path.basename(p)} shorter "
                           f"than its committed prefix ({size} < "
                           f"{target}): segment {key[1]} sink {key[2]} "
                           "lost")
                    if key[1] < floor:
                        report.missing.append(msg)
                    else:
                        report.rolled_back.append(
                            f"drop {key} from done-set ({msg})")

    report.done = complete
    report.recovered_segments = len(
        {seg for (_s, seg, _k) in complete if seg >= floor})
    if report.rolled_back:
        log.warning(
            f"[manifest] rolled back {report.rolled_back_intents} "
            f"uncommitted intent(s) from an interrupted run: "
            f"{report.rolled_back}")
    if report.missing:
        # fsck-grade LOSS: counted so the caller (Pipeline.__init__)
        # can bundle the evidence, and each flag lands on the flight
        # recorder
        metrics.add("manifest_loss_flags", len(report.missing))
    for msg in report.missing:
        events.emit("manifest.loss", trace=0, info=msg[:200])
        log.error(f"[manifest] DATA LOSS: {msg}")
    return report


# ----------------------------------------------------------------
# writer
# ----------------------------------------------------------------

class RunManifest:
    """Append-side of the WAL.  Thread-safe: sinks append from the
    sink-drain thread, commit callbacks fire from async writer-pool
    threads.

    Durability is BATCHED at the two points that actually need it
    (``fsync=True``): :meth:`sync` — the publish barrier a writer
    calls between its temp write and the atomic rename, making every
    pending intent durable before any artifact can reach its final
    name — and the ``ckpt`` record, which seals everything before it.
    Ordinary commits/done records are appended without their own
    fdatasync: losing them on power loss only means the artifact group
    reads uncommitted and is rolled back + regenerated on resume —
    never a duplicate, never silent loss.  (Append-mode artifacts need
    no barrier at all: bytes beyond the committed prefix are truncated
    by recovery whatever the WAL remembers.)  ``fsync=False`` drops
    even the two required syncs — process-death durability stays
    intact (the page cache survives a SIGKILL), only power loss can
    then leak an untracked renamed artifact.

    A manifest append failure RAISES: unlike the telemetry journal,
    the WAL is a correctness structure — continuing without it would
    silently forfeit exactly-once."""

    def __init__(self, path: str, fsync: bool = True,
                 hash_content: bool = True):
        self.path = path
        self.fsync = fsync
        # whether sinks should record artifact content CRC32s (the
        # deep fsck check; ~1 ms per dumped MB) — consulted by
        # io/writers.manifest_stage, not by the WAL itself
        self.hash_content = hash_content
        self._lock = threading.Lock()
        self._dirty = False
        self._base = os.path.dirname(os.path.abspath(path))
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "ab")
        # a crash can leave a final record whose bytes are complete
        # except the trailing newline (scan accepts it); appending
        # directly would concatenate the next record onto it and tear
        # BOTH — terminate the line first
        if self._f.tell() > 0:
            with open(path, "rb") as rf:
                rf.seek(-1, os.SEEK_END)
                if rf.read(1) != b"\n":
                    self._f.write(b"\n")
                    self._f.flush()
        self._done: set = set()

    # -- open-with-recovery ----------------------------------------

    @classmethod
    def open(cls, path: str, fsync: bool = True,
             hash_content: bool = True,
             checkpoint_floor_hint: int = 0) -> "RunManifest":
        """Recover (truncate torn tail, roll back uncommitted groups,
        rebuild the done-set), then open for appending and stamp a
        run record.  Recovery counters land in the metrics registry:
        ``recovered_segments`` / ``rolled_back_intents``.
        ``checkpoint_floor_hint`` guards rollback against a WAL that
        forgot its ckpt records — see :func:`recover`."""
        existed = os.path.exists(path)
        report = recover(path, apply=True,
                         checkpoint_floor_hint=checkpoint_floor_hint) \
            if existed else RecoveryReport()
        m = cls(path, fsync=fsync, hash_content=hash_content)
        m._done = set(report.done)
        if report.recovered_segments:
            metrics.add("recovered_segments", report.recovered_segments)
            log.warning(
                f"[manifest] recovered {report.recovered_segments} "
                "committed segment(s) beyond the checkpoint; their "
                "sink pushes will be skipped on replay")
        if report.rolled_back_intents:
            metrics.add("rolled_back_intents",
                        report.rolled_back_intents)
        m._append({"t": "run", "ts": time.time(),
                   "resume": bool(existed and report.done
                                  or (existed and report.last_checkpoint
                                      is not None))})
        return m

    # -- record appends --------------------------------------------

    def _append(self, rec: dict, durable: bool = False) -> None:
        line = encode_record(rec)
        with self._lock:
            if self._f is None:
                raise RuntimeError(
                    f"run manifest {self.path} is closed")
            self._f.write(line)
            self._f.flush()
            if durable and self.fsync:
                os.fdatasync(self._f.fileno())
                self._dirty = False
            else:
                self._dirty = True

    def sync(self) -> None:
        """The publish barrier: make every appended record durable.
        Writers call this between an artifact's temp write and its
        atomic rename — no artifact reaches its final name before the
        WAL durably knows the intent.  No-op when nothing is pending
        (consecutive renames batch their records into one fdatasync)
        or when ``fsync=False``."""
        if not self.fsync:
            return
        with self._lock:
            if self._f is None or not self._dirty:
                return
            os.fdatasync(self._f.fileno())
            self._dirty = False

    def _key_fields(self, key) -> dict:
        stream, seg, sink = key
        return {"stream": int(stream), "seg": int(seg),
                "sink": str(sink)}

    def intent(self, key, path: str, mode: str = "atomic",
               offset: int | None = None) -> None:
        rec = {"t": "intent", "path": _rel_path_from(self._base, path),
               "mode": mode, **self._key_fields(key)}
        if offset is not None:
            rec["off"] = int(offset)
        self._append(rec)
        # causal trace: the ambient context (bound by _drain_body on
        # the sink thread) names the segment whose artifact this is —
        # the bundle's "manifest disposition" evidence
        events.emit("manifest.intent", seg=int(key[1]),
                    info=f"{key[2]}:{os.path.basename(path)}")

    def commit(self, key, path: str, length: int,
               crc32: int | None = None,
               offset: int | None = None) -> None:
        rec = {"t": "commit", "path": _rel_path_from(self._base, path),
               "len": int(length), **self._key_fields(key)}
        if crc32 is not None:
            rec["crc32"] = int(crc32)
        if offset is not None:
            rec["off"] = int(offset)
        self._append(rec)
        events.emit("manifest.commit", seg=int(key[1]),
                    info=f"{key[2]}:{os.path.basename(path)}")

    def sink_done(self, key) -> None:
        self._append({"t": "done", **self._key_fields(key)})
        with self._lock:
            self._done.add(tuple(key))
        events.emit("manifest.done", seg=int(key[1]),
                    info=str(key[2]))

    def canary(self, stream: int, seg: int, abs_index: int,
               ok: bool = True) -> None:
        """Flag a pulse-injection canary segment (quality/canary.py):
        offline consumers can prove the quarantine — which drain
        indices carried a synthetic pulse, and whether each passed
        the sensitivity gate.  Carries no recovery state; the scanner
        (and fsck) tolerate it like the "run" stamp."""
        self._append({"t": "canary", "stream": int(stream),
                      "seg": int(seg), "abs": int(abs_index),
                      "ok": bool(ok)})
        events.emit("manifest.canary", seg=int(seg),
                    info=f"abs={int(abs_index)} ok={bool(ok)}")

    def checkpoint(self, segments_done: int,
                   file_offset_bytes: int) -> None:
        # the consistency point is always durable: it seals every
        # record before it, and the checkpoint file rename follows it
        self._append({"t": "ckpt", "segments_done": int(segments_done),
                      "offset": int(file_offset_bytes)}, durable=True)
        events.emit("manifest.ckpt", seg=int(segments_done),
                    info=f"offset={int(file_offset_bytes)}")

    # -- replay-skip query -----------------------------------------

    def is_done(self, key) -> bool:
        return tuple(key) in self._done

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                if self.fsync and self._dirty:
                    os.fdatasync(self._f.fileno())
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
