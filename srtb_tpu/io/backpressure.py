"""Accounted-loss backpressure between a real-time source and the engine.

The reference's never-stall-on-loss property (SURVEY.md; measured in the
e2e overload test): when compute cannot keep up with a real-time source,
the source must keep running and the excess must surface as *accounted*
loss — never as silent latency or a stalled receiver.  For UDP ingest the
kernel already provides this (a full rcvbuf drops packets, and the
counter gaps are accounted by the receivers); this module provides the
same contract at segment granularity for any ``SegmentWork`` iterator —
e.g. a file replayed at wire rate, or a source whose own buffering must
not be trusted to stay bounded when the engine's in-flight window fills.

``DropOldestSegmentBuffer`` pulls the wrapped source on its own thread
into a bounded deque.  When the pipeline (the consumer) falls behind and
the deque is full, the OLDEST buffered segment is dropped and counted
(``segments_dropped`` counter + 10 s window + the span journal's
cumulative field), keeping the freshest data — matching the real-time
bias of the reference's lossy visualization taps (pipe_io.hpp:79-94),
but with loss that is always visible in /metrics and the journal.
"""

from __future__ import annotations

import collections
import threading

from srtb_tpu.utils import termination
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics


class DropOldestSegmentBuffer:
    """Bounded segment buffer with drop-oldest overflow accounting.

    Iterating yields segments in production order (minus accounted
    drops); iteration ends when the wrapped source is exhausted and the
    buffer has drained.  A source exception is re-raised to the
    consumer at the point of the failed ``__next__``.

    Not for checkpointed file replays: the pump thread reads ahead of
    the consumer, so the forwarded ``logical_offset`` is the pump's
    position, and a drop means a resume offset can never be exact —
    lossy buffering and exactly-once checkpointing are contradictory
    by construction.
    """

    def __init__(self, source, capacity: int = 4,
                 name: str = "segment_buffer", stream: str = ""):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.source = source
        self.capacity = int(capacity)
        self.name = name
        # tenant label for drop attribution: the fleet passes the
        # owning Config.stream_name; unnamed buffers fall back to the
        # victim segment's data_stream_id so multi-receiver loss is
        # still auditable per origin stream
        self.stream = stream
        self.dropped = 0
        # per-origin drop counts (data_stream_id or stream label ->
        # count), mirrored into labeled segments_dropped series
        self.dropped_by_stream: dict[str, int] = {}
        self._buf: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._done = False
        self._error: BaseException | None = None
        self._thread = threading.Thread(target=self._pump, name=name,
                                        daemon=True)
        termination.tag_thread(self._thread)
        self._thread.start()

    def _pump(self) -> None:
        try:
            for seg in self.source:
                with self._cv:
                    if self._done:
                        break
                    if len(self._buf) >= self.capacity:
                        victim = self._buf.popleft()
                        self.dropped += 1
                        metrics.add("segments_dropped")
                        metrics.window("segments_dropped").add(1)
                        # attribute the loss to the ORIGINATING stream
                        # (not just the process-wide total): fleet
                        # shedding must be auditable per tenant
                        origin = self.stream or str(
                            getattr(victim, "data_stream_id", 0))
                        self.dropped_by_stream[origin] = \
                            self.dropped_by_stream.get(origin, 0) + 1
                        metrics.add("segments_dropped",
                                    labels={"stream": origin})
                        # a pooled source's buffer must go back to the
                        # pool: the pipeline only releases segments it
                        # actually drains
                        pool = getattr(self.source, "pool", None)
                        if pool is not None:
                            pool.release(victim.data)
                        log.warning(
                            f"[{self.name}] consumer behind: dropped "
                            f"oldest segment ({self.dropped} total)")
                    self._buf.append(seg)
                    metrics.set(f"{self.name}_depth", len(self._buf))
                    self._cv.notify()
        except BaseException as e:  # noqa: BLE001 - hand to the consumer
            with self._cv:
                if not self._done:  # an unblock-by-close is not an error
                    self._error = e
        finally:
            with self._cv:
                self._done = True
                self._cv.notify_all()

    @property
    def pool(self):
        """Forward the wrapped source's buffer pool (if any) so the
        pipeline's drain path keeps releasing segment buffers exactly
        as it would against the unwrapped source."""
        return getattr(self.source, "pool", None)

    @property
    def logical_offset(self):
        return getattr(self.source, "logical_offset", 0)

    def __iter__(self):
        return self

    def __next__(self):
        with self._cv:
            while not self._buf:
                if self._error is not None:
                    err, self._error = self._error, None
                    raise err
                if self._done:
                    raise StopIteration
                self._cv.wait()
            seg = self._buf.popleft()
            metrics.set(f"{self.name}_depth", len(self._buf))
            return seg

    def close(self) -> None:
        with self._cv:
            self._done = True
            self._cv.notify_all()
        # close the wrapped source FIRST: a pump thread blocked inside a
        # receive only unblocks when the underlying fd goes away (the
        # raised OSError is swallowed because _done is already set)
        close = getattr(self.source, "close", None)
        if close is not None:
            close()
        self._thread.join(timeout=5)
