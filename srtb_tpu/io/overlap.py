"""Source-side half of the ingest-ring contract, shared by the file
reader and the UDP source.

Both sources emit segments that overlap by ``reserved_bytes`` (the
overlap-save tail) and stamp ``SegmentWork.seq`` so the engine's
adjacency guard (pipeline/runtime.py ``_ring_adjacent``) can prove a
segment is the stream-adjacent successor of the last dispatched one —
the precondition for warm carry assembly.  This helper owns BOTH
invariants in one place:

- **tail retention**: the reserved tail of the last emitted segment is
  kept in ONE persistent host buffer (``np.copyto``, never a fresh
  allocation per segment — at high DM the tail is a large fraction of
  the segment) and memcpy'd into the next segment's head;
- **seq stamping**: a per-source monotonically increasing emission
  counter, or ``-1`` (never warm-assembled) when the source cannot
  guarantee the overlap — the misaligned-UDP fallback, hand-built
  segments.

A future change to either rule lands here once, for every source.
"""

from __future__ import annotations

import numpy as np


class OverlapTailCarry:
    """Retained reserved-tail + emission-seq bookkeeping for one
    segment source (one instance per receiver/reader)."""

    def __init__(self, reserved_bytes: int, stamp_seq: bool = True):
        self.reserved_bytes = int(reserved_bytes)
        self._stamp_seq = bool(stamp_seq)
        self._tail: np.ndarray | None = None
        self._seq = 0

    @property
    def warm(self) -> bool:
        """Whether a retained tail exists to head the next segment."""
        return self._tail is not None

    def head_into(self, buf: np.ndarray) -> int:
        """Copy the retained tail into ``buf[:reserved_bytes]`` when
        warm; returns the number of head bytes filled (0 when cold —
        the caller must produce the full segment itself)."""
        if self._tail is None:
            return 0
        buf[:self.reserved_bytes] = self._tail
        return self.reserved_bytes

    def retain(self, buf: np.ndarray) -> None:
        """Retain ``buf``'s reserved tail for the next segment's head
        (persistent buffer; no per-segment allocation)."""
        if self._tail is None:
            self._tail = np.empty(self.reserved_bytes, np.uint8)
        np.copyto(self._tail, buf[buf.shape[0] - self.reserved_bytes:])

    def next_seq(self) -> int:
        """The emitted segment's ``SegmentWork.seq``: adjacent stamps
        for overlap-capable sources, -1 (never warm) otherwise."""
        if not self._stamp_seq:
            return -1
        self._seq += 1
        return self._seq - 1
