"""srtb_tpu — a TPU-native radio-telescope transient-search backend.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
fxzjshm/simple-radio-telescope-backend (C++/SYCL): streaming coherent
dedispersion of raw baseband voltage data with RFI mitigation, single-pulse
detection, baseband capture and spectrum-waterfall output — plus a new
distributed layer (DM-trial fan-out and frequency-sharded FFT over a
``jax.sharding.Mesh``) that the reference does not have.

Layer map (mirrors reference layers L0-L7, see SURVEY.md):

- ``srtb_tpu.config``    — runtime configuration (ref: config.hpp, program_options.hpp)
- ``srtb_tpu.utils``     — logging, expression parsing, small helpers (ref: log/, util/)
- ``srtb_tpu.ops``       — device kernels as jittable functions / Pallas kernels
  (ref: unpack.hpp, coherent_dedispersion.hpp, spectrum/, signal_detect.hpp, fft/)
- ``srtb_tpu.pipeline``  — the fused segment processor + streaming runtime
  (ref: pipeline/)
- ``srtb_tpu.io``        — baseband file reader, UDP ingest, packet formats, writers
  (ref: io/)
- ``srtb_tpu.parallel``  — mesh helpers, multi-chip DM-trial grid, sharded FFT
  (no reference equivalent; reference is single-device)
- ``srtb_tpu.gui``       — waterfall pixmap service (ref: gui/, without Qt)
- ``srtb_tpu.tools``     — CLI entry points (ref: src/main.cpp, correlator.cpp, ...)
"""

__version__ = "0.1.0"

from srtb_tpu.config import Config  # noqa: F401
