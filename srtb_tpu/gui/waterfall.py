"""Spectrum-waterfall rendering service.

TPU-native replacement for the Qt GUI chain (ref: pipeline/spectrum_pipe.
hpp simplify_spectrum_pipe_2 -> gui/spectrum_image_provider.hpp -> QML):
the device side is identical — resample to pixmap size, normalize by
2x average, ARGB colormap (ops.spectrum) — but the sink is a PNG/PPM file
or raw pixmap stream per data stream instead of a Qt window, so it runs
headless next to the TPU job.  The lossy-tap semantics of the reference's
``loose_queue_out_functor`` (drop frames when the consumer is slow,
ref: framework/pipe_io.hpp:79-94) are preserved in WaterfallService.
"""

from __future__ import annotations

import os
import struct
import zlib

import jax
import jax.numpy as jnp
import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.ops import spectrum as sp


from srtb_tpu.utils.platform import to_host as _to_host


class WaterfallRenderer:
    """Owns the jitted resample+normalize+colormap function for one
    waterfall geometry."""

    def __init__(self, in_freq: int, in_time: int, out_h: int, out_w: int):
        self.w_freq = jnp.asarray(sp.freq_area_weights(in_freq, out_h))
        self.w_time = jnp.asarray(sp.time_interp_weights(in_time, out_w))
        self._render = jax.jit(self._render_impl)
        # built here, NOT per render_power call: jax.jit of a bound
        # method evaluated per call recompiles every time (srtb-lint
        # recompile-hazard found the old spelling doing exactly that)
        self._render_power = jax.jit(self._render_power_impl)

    def _render_impl(self, wf_ri: jnp.ndarray) -> jnp.ndarray:
        """wf_ri [2, F, T] (re, im) -> ARGB32 [out_h, out_w] uint32."""
        power = wf_ri[0] ** 2 + wf_ri[1] ** 2
        return self._render_power_impl(power)

    def _render_power_impl(self, power: jnp.ndarray) -> jnp.ndarray:
        img = sp.resample_spectrum(power, self.w_freq, self.w_time)
        img = sp.normalize_by_average(img)
        return sp.generate_pixmap(img)

    def render(self, wf_ri) -> np.ndarray:
        return jax.device_get(self._render(jnp.asarray(wf_ri)))

    def render_power(self, power) -> np.ndarray:
        return jax.device_get(self._render_power(
            jnp.asarray(power, dtype=jnp.float32)))


# ----------------------------------------------------------------
# minimal dependency-free PNG writer (RGBA8)
# ----------------------------------------------------------------

def _png_chunk(tag: bytes, data: bytes) -> bytes:
    c = tag + data
    return struct.pack(">I", len(data)) + c + struct.pack(
        ">I", zlib.crc32(c) & 0xFFFFFFFF)


def write_png(path: str, argb: np.ndarray) -> None:
    """Write an ARGB32 uint32 [h, w] array as a PNG file."""
    h, w = argb.shape
    a = ((argb >> 24) & 0xFF).astype(np.uint8)
    r = ((argb >> 16) & 0xFF).astype(np.uint8)
    g = ((argb >> 8) & 0xFF).astype(np.uint8)
    b = (argb & 0xFF).astype(np.uint8)
    rgba = np.stack([r, g, b, a], axis=-1)
    raw = b""
    rows = np.concatenate(
        [np.zeros((h, 1), dtype=np.uint8),  # filter byte 0 per row
         rgba.reshape(h, w * 4)], axis=1)
    raw = rows.tobytes()
    # write-then-rename so concurrent readers (the HTTP viewer) never see a
    # partially written frame
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(b"\x89PNG\r\n\x1a\n")
        f.write(_png_chunk(
            b"IHDR", struct.pack(">IIBBBBB", w, h, 8, 6, 0, 0, 0)))
        f.write(_png_chunk(b"IDAT", zlib.compress(raw, 6)))
        f.write(_png_chunk(b"IEND", b""))
    os.replace(tmp, path)


class RequestSizeScheduler:
    """Adaptive lines-per-update scheduler of the legacy provider: grow
    3n+1 when the consumer starved last round, halve (min 1) when it had
    enough (ref: gui/spectrum_image_provider.hpp:79-102)."""

    def __init__(self):
        self._size = 1

    def set_last_size_too_few(self, too_few: bool) -> None:
        self._size = (3 * self._size + 1) if too_few else max(
            1, self._size // 2)

    def get_next_request_size(self) -> int:
        return self._size


class ScrollingWaterfall:
    """Legacy scrolling-waterfall provider, headless (ref:
    gui/spectrum_image_provider.hpp:118-330 SpectrumImageProvider +
    draw_spectrum_work_holder): each pushed power spectrum becomes one
    pixmap line (frequency along x), lines scroll upward through a
    persistent image; an adaptive :class:`RequestSizeScheduler` decides
    how many pending lines to consume per render so the display keeps up
    with the data rate without dropping to a crawl.
    """

    def __init__(self, in_freq: int, width: int, height: int):
        self.width = width
        self.height = height
        # area-weighted frequency->pixel resample (no bins dropped), the
        # same weights family as the simplify path
        self._w_freq = np.asarray(
            sp.freq_area_weights(in_freq, width)).T   # [in_freq, width]
        self._img = np.zeros((height, width), dtype=np.float32)
        self._pending: list[np.ndarray] = []
        self.scheduler = RequestSizeScheduler()
        self.lines_total = 0

    def push_spectrum(self, power: np.ndarray) -> None:
        """Queue one [in_freq] power spectrum as a future line."""
        self._pending.append(np.asarray(power, dtype=np.float32))

    def consume(self) -> int:
        """Scroll in up to request_size pending lines (one UI update);
        returns the number of lines consumed and adapts the scheduler."""
        want = self.scheduler.get_next_request_size()
        take = min(want, len(self._pending))
        if take:
            lines = np.stack(self._pending[:take]) @ self._w_freq
            del self._pending[:take]
            # scroll down, newest line at the top (ref: update_pixmap
            # scrolls dy=+lines and paints new lines at y=0)
            self._img = np.roll(self._img, take, axis=0)
            keep = lines[-self.height:]
            self._img[:keep.shape[0]] = keep[::-1]
            self.lines_total += take
        # the reference grows 3n+1 whenever the full request was
        # satisfied ("still some work in queue, request more") and halves
        # when the queue ran dry mid-request
        # (ref: spectrum_image_provider.hpp:218-230)
        self.scheduler.set_last_size_too_few(take >= want)
        return take

    def render(self) -> np.ndarray:
        """ARGB32 [height, width] of the current scroll window.
        Normalization uses only rows that have received data, so a
        partially-filled window does not push real lines into the
        overflow color."""
        filled = min(self.lines_total, self.height)
        if filled == 0:
            return _to_host(sp.generate_pixmap(jnp.asarray(self._img)))
        avg = float(self._img[:filled].mean())
        coeff = 1.0 / (2.0 * avg) if avg > np.finfo(np.float32).eps else 1.0
        return _to_host(sp.generate_pixmap(
            jnp.asarray(self._img * np.float32(coeff))))


def _stream_slice(wf: np.ndarray, stream: int) -> np.ndarray:
    """[2, S, F, T] -> this stream's [2, F, T].  data_stream_id indexes
    S only for interleaved formats (several streams in ONE segment
    array); per-receiver sources carry S=1 segments whose id names the
    PANE, not an index (found live: MultiUdpSource receiver 1 crashed
    the tap on wf[:, 1] of an S=1 array).  Single home for all three
    render paths (plain, summed, scrolling)."""
    if wf.ndim == 4:
        return wf[:, stream if wf.shape[1] > 1 else 0]
    return wf



class WaterfallService:
    """Per-stream waterfall file sink with lossy-frame semantics: only the
    most recent segment is rendered; older frames are dropped if rendering
    lags (ref: loose_queue_out_functor, framework/pipe_io.hpp:79-94).

    Two modes, like the reference's two image providers:
    - simple (default): each rendered frame is one whole segment's
      dynamic spectrum (SimpleSpectrumImageProvider);
    - scrolling (``gui_scroll_lines > 0``): each segment contributes that
      many time-averaged spectrum lines to a persistent scrolling image
      (legacy SpectrumImageProvider), written as
      ``waterfall_s<id>_scroll.png`` after every update.
    """

    def __init__(self, cfg: Config, in_freq: int, in_time: int,
                 out_dir: str = ".", fmt: str = "png"):
        self.cfg = cfg
        self.out_dir = out_dir
        self.fmt = fmt
        self.renderer = WaterfallRenderer(
            in_freq, in_time, cfg.gui_pixmap_height, cfg.gui_pixmap_width)
        self.frame_counter = {}
        self._pending = None
        # scroll mode: every stream with queued-but-unrendered lines (a
        # single last-tag slot would starve earlier streams when several
        # are pushed between render_pending calls)
        self._pending_scroll: set[int] = set()
        # sum several segments' power before drawing, reducing host-side
        # frame rate (ref: config.hpp:196-200 spectrum_sum_count)
        self.sum_count = max(1, cfg.spectrum_sum_count)
        self._accum: dict[int, tuple[int, np.ndarray]] = {}
        self.scroll_lines = max(0, cfg.gui_scroll_lines)
        self._scrollers: dict[int, ScrollingWaterfall] = {}
        self._in_freq = in_freq

    def _scroller(self, stream: int) -> ScrollingWaterfall:
        if stream not in self._scrollers:
            self._scrollers[stream] = ScrollingWaterfall(
                self._in_freq, self.cfg.gui_pixmap_width,
                self.cfg.gui_pixmap_height)
        return self._scrollers[stream]

    def _push_scroll(self, wf_ri, stream: int) -> None:
        wf = _stream_slice(_to_host(wf_ri), stream)
        power = wf[0] ** 2 + wf[1] ** 2          # [F, T]
        k = min(self.scroll_lines, power.shape[-1])
        chunks = np.array_split(power, k, axis=-1)
        sw = self._scroller(stream)
        for c in chunks:  # one time-averaged spectrum line per chunk
            sw.push_spectrum(c.mean(axis=-1))
        self._pending_scroll.add(stream)

    def push(self, wf_ri, data_stream_id: int = 0) -> None:
        if self.scroll_lines:
            self._push_scroll(wf_ri, data_stream_id)
            return
        if self.sum_count > 1:
            wf = _stream_slice(_to_host(wf_ri), data_stream_id)
            power = wf[0] ** 2 + wf[1] ** 2
            n, acc = self._accum.get(data_stream_id, (0, 0.0))
            n, acc = n + 1, acc + power
            if n < self.sum_count:
                self._accum[data_stream_id] = (n, acc)
                return
            self._accum[data_stream_id] = (0, 0.0)
            self._pending = (acc, data_stream_id)
            return
        # lossy tap: replace any unrendered frame
        self._pending = (wf_ri, data_stream_id)

    def render_pending(self) -> str | None:
        if self.scroll_lines:
            # render every stream with queued lines; return the last path
            # (None when nothing was consumed anywhere)
            path = None
            for stream in sorted(self._pending_scroll):
                sw = self._scroller(stream)
                if sw.consume() == 0:
                    continue
                p = os.path.join(self.out_dir,
                                 f"waterfall_s{stream}_scroll.{self.fmt}")
                write_png(p, sw.render())
                path = p
            self._pending_scroll.clear()
            return path
        if self._pending is None:
            return None
        wf_ri, stream = self._pending
        self._pending = None
        wf = _stream_slice(_to_host(wf_ri), stream)
        if wf.ndim == 2:  # pre-summed power frame
            pix = self.renderer.render_power(wf)
        else:
            pix = self.renderer.render(wf)
        n = self.frame_counter.get(stream, 0)
        self.frame_counter[stream] = n + 1
        path = os.path.join(self.out_dir,
                            f"waterfall_s{stream}_{n:06d}.{self.fmt}")
        write_png(path, pix)
        return path
