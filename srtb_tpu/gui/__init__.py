from srtb_tpu.gui import waterfall  # noqa: F401
