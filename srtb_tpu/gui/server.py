"""Live waterfall HTTP server.

The reference shows a live Qt waterfall window per data stream
(ref: gui/gui.hpp, spectrum_image_provider.hpp, src/main.qml).  The
headless TPU equivalent: the WaterfallService writes PNG frames, and this
tiny stdlib HTTP server exposes the latest frame per stream with an
auto-refreshing index page — same live view, no GUI toolkit on the host.
"""

from __future__ import annotations

import html
import http.server
import json
import os
import re
import threading

from srtb_tpu.utils.logging import log

_INDEX_TEMPLATE = """<!DOCTYPE html>
<html><head><title>srtb_tpu waterfall</title>
<meta http-equiv="refresh" content="2">
<style>body{{background:#111;color:#eee;font-family:monospace}}
img{{image-rendering:pixelated;border:1px solid #444}}</style></head>
<body><h2>srtb_tpu spectrum waterfall</h2>{body}</body></html>
"""


class _Handler(http.server.BaseHTTPRequestHandler):
    directory = "."

    def log_message(self, *args):  # quiet
        pass

    def _latest_frames(self):
        pat = re.compile(r"waterfall_s(\d+)_(\d+)\.png$")
        latest: dict[int, tuple[int, str]] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            names = []
        for name in names:
            m = pat.match(name)
            if m:
                stream, idx = int(m.group(1)), int(m.group(2))
                if stream not in latest or idx > latest[stream][0]:
                    latest[stream] = (idx, name)
        return {s: name for s, (_, name) in latest.items()}

    def do_GET(self):
        try:
            self._do_get()
        except ConnectionError:
            # browsers abort in-flight <img> loads on every index refresh
            pass

    def _do_get(self):
        if self.path in ("/metrics", "/metrics.json"):
            # live observability beyond the reference's log-only story
            # (SURVEY.md §5.5): JSON snapshot or Prometheus text format
            from srtb_tpu.utils.metrics import metrics

            snap = metrics.snapshot()
            if self.path == "/metrics.json":
                data = (json.dumps(snap, sort_keys=True) + "\n").encode()
                ctype = "application/json"
            else:
                lines = []
                for k in sorted(snap):
                    name = "srtb_" + re.sub(r"[^a-zA-Z0-9_]", "_", k)
                    lines.append(f"{name} {snap[k]:.17g}")
                data = ("\n".join(lines) + "\n").encode()
                ctype = "text/plain; version=0.0.4"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        if self.path in ("/", "/index.html"):
            frames = self._latest_frames()
            if frames:
                body = "".join(
                    f"<div>stream {s}: {html.escape(name)}<br>"
                    f'<img src="/{name}"></div>'
                    for s, name in sorted(frames.items()))
            else:
                body = "<p>no frames yet</p>"
            data = _INDEX_TEMPLATE.format(body=body).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        name = os.path.basename(self.path)
        path = os.path.join(self.directory, name)
        if name.endswith(".png") and os.path.exists(path):
            with open(path, "rb") as f:
                data = f.read()
            self.send_response(200)
            self.send_header("Content-Type", "image/png")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        self.send_response(404)
        self.end_headers()


class WaterfallHTTPServer:
    """Serve the waterfall PNG directory on a background thread."""

    def __init__(self, directory: str, port: int = 0,
                 address: str = "127.0.0.1"):
        handler = type("Handler", (_Handler,), {"directory": directory})
        self._httpd = http.server.ThreadingHTTPServer((address, port),
                                                      handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)

    def start(self) -> "WaterfallHTTPServer":
        self._thread.start()
        log.info(f"[gui] waterfall at http://127.0.0.1:{self.port}/")
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
