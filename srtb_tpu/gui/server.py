"""Live waterfall HTTP server.

The reference shows live Qt/QML waterfall windows, one per data stream
(ref: gui/gui.hpp:34-67, spectrum_image_provider.hpp, src/main.qml:14-28),
with the event loop repainting as SpectrumImageProvider appends lines.
The headless TPU equivalent: the WaterfallService writes PNG frames and
this stdlib HTTP server serves an *interactive* live view — per-stream
panes that poll ``/frames.json`` and swap the image in place (no page
reload), pause/resume, a history scrubber over the retained frames,
zoom, brightness/contrast (client-side CSS filters over the same
pre-colormapped pixels the reference pushes to its QImage), and a live
metrics bar fed from ``/metrics.json``.  Same interactivity surface as
the QML window — pause, look back, lean in — with no GUI toolkit on
the host.
"""

from __future__ import annotations

import html
import http.server
import json
import os
import re
import threading

from srtb_tpu.utils import termination
from srtb_tpu.utils.logging import log

_INDEX_TEMPLATE = """<!DOCTYPE html>
<html><head><title>srtb_tpu waterfall</title>
<style>
body{{background:#111;color:#eee;font-family:monospace;margin:12px}}
img{{image-rendering:pixelated;border:1px solid #444;display:block}}
.pane{{margin-bottom:14px}}
.bar{{margin:4px 0}}
button{{background:#222;color:#eee;border:1px solid #555;margin-right:4px}}
input[type=range]{{vertical-align:middle}}
#metrics{{color:#8c8;margin-bottom:10px}}
</style></head>
<body><h2>srtb_tpu spectrum waterfall</h2>
<div id="metrics">metrics: …</div>
<div id="panes">{body}</div>
<script>
"use strict";
const panes = {{}};   // stream -> {{paused, pos, frames, img, slider, label}}
// server-rendered pane markup with __S__ placeholders, so a stream that
// starts publishing only after page load still gets a pane (round-3
// advisor catch: tick() used to skip unknown streams forever)
const PANE_HTML = {pane_js};
function addPane(s) {{
  const host = document.createElement("div");
  host.innerHTML = PANE_HTML.replaceAll("__S__", s);
  // no frame name yet: drop the placeholder src (setFrame fills it on
  // the same tick) rather than fetching "/" into the <img>
  host.querySelector("img").removeAttribute("src");
  document.getElementById("panes").appendChild(host.firstElementChild);
  wire(s);
}}
function setFrame(s) {{
  const p = panes[s];
  if (!p.frames.length) return;
  const i = Math.min(p.pos, p.frames.length - 1);
  p.img.src = "/" + p.frames[i];
  p.label.textContent = p.frames[i] +
    (p.paused ? "  [paused]" : "  [live]");
  p.slider.max = p.frames.length - 1;
  p.slider.value = i;
}}
function wire(s) {{
  const el = document.getElementById("pane" + s);
  const p = panes[s] = {{
    paused: false, pos: 0, frames: [],
    img: el.querySelector("img"),
    slider: el.querySelector("input[type=range]"),
    label: el.querySelector(".fname"),
  }};
  el.querySelector(".pause").onclick = (e) => {{
    p.paused = !p.paused;
    e.target.textContent = p.paused ? "resume" : "pause";
    if (!p.paused) p.pos = Math.max(0, p.frames.length - 1);
    setFrame(s);
  }};
  p.slider.oninput = () => {{
    p.paused = true;
    el.querySelector(".pause").textContent = "resume";
    p.pos = +p.slider.value;
    setFrame(s);
  }};
  let zoom = 1;
  el.querySelector(".zin").onclick = () => {{
    zoom = Math.min(8, zoom * 2); p.img.style.width =
      (p.img.naturalWidth * zoom) + "px";
  }};
  el.querySelector(".zout").onclick = () => {{
    zoom = Math.max(0.25, zoom / 2); p.img.style.width =
      (p.img.naturalWidth * zoom) + "px";
  }};
  const bright = el.querySelector(".bright"),
        contrast = el.querySelector(".contrast");
  const filt = () => {{
    p.img.style.filter =
      `brightness(${{bright.value}}%) contrast(${{contrast.value}}%)`;
  }};
  bright.oninput = filt; contrast.oninput = filt;
}}
async function tick() {{
  try {{
    const r = await fetch("/frames.json");
    const data = await r.json();
    for (const s in data.streams) {{
      if (!(s in panes)) addPane(s);
      const p = panes[s];
      p.frames = data.streams[s];
      if (!p.paused) p.pos = Math.max(0, p.frames.length - 1);
      setFrame(s);
    }}
  }} catch (e) {{}}
  try {{
    const m = await (await fetch("/metrics.json")).json();
    const keys = ["segments", "samples", "segments_dropped",
                  "udp_lost_packets", "elapsed_s"];
    document.getElementById("metrics").textContent = "metrics: " +
      keys.filter(k => k in m).map(k => `${{k}}=${{m[k]}}`).join("  ");
  }} catch (e) {{}}
}}
document.querySelectorAll(".pane").forEach(
  el => wire(+el.dataset.stream));
tick(); setInterval(tick, 1000);
</script>
</body></html>
"""

_PANE_TEMPLATE = """<div class="pane" id="pane{s}" data-stream="{s}">
<div>stream {s}: <span class="fname">{name}</span></div>
<div class="bar">
<button class="pause">pause</button>
<button class="zin">zoom+</button>
<button class="zout">zoom-</button>
history <input type="range" min="0" max="0" value="0">
bright <input class="bright" type="range" min="20" max="300"
 value="100">
contrast <input class="contrast" type="range" min="20" max="300"
 value="100">
</div>
<img src="/{name}"></div>
"""


class _Handler(http.server.BaseHTTPRequestHandler):
    directory = "."
    health_stale_after_s = 30.0
    fleet_store_dir = ""  # rollup store surfaced via /fleet

    def log_message(self, *args):  # quiet
        pass

    def _all_frames(self):
        """stream -> frame names sorted by index (the retained history
        the scrubber moves over)."""
        pat = re.compile(r"waterfall_s(\d+)_(\d+)\.png$")
        frames: dict[int, list[tuple[int, str]]] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            names = []
        for name in names:
            m = pat.match(name)
            if m:
                frames.setdefault(int(m.group(1)), []).append(
                    (int(m.group(2)), name))
        return {s: [name for _, name in sorted(v)]
                for s, v in frames.items()}

    def _latest_frames(self):
        return {s: v[-1] for s, v in self._all_frames().items() if v}

    def do_GET(self):
        try:
            self._do_get()
        except ConnectionError:
            # browsers abort in-flight <img> loads on every index refresh
            pass

    def _do_get(self):
        if self.path in ("/metrics", "/metrics.json"):
            # live observability beyond the reference's log-only story
            # (SURVEY.md §5.5): JSON snapshot or Prometheus text
            # exposition (counters/gauges, sliding-window rates, and
            # the per-stage wall-clock histograms)
            from srtb_tpu.utils import slo
            from srtb_tpu.utils.metrics import metrics

            # refresh the SLO burn-rate gauges right before the
            # scrape (no-op when no objective is armed), so
            # slo_burn_rate / slo_state are current however long ago
            # the last segment (or /healthz hit) was
            slo.evaluate()
            if self.path == "/metrics.json":
                data = (json.dumps(metrics.snapshot(), sort_keys=True)
                        + "\n").encode()
                ctype = "application/json"
            else:
                data = metrics.prometheus().encode()
                ctype = "text/plain; version=0.0.4"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        if self.path == "/healthz":
            # last-segment-age staleness: 503 while the pipeline is
            # wedged (no cooperation needed from the stuck thread),
            # 200 when segments flow or before the first one (startup).
            # Multi-tenant fleet: the payload carries a per-stream
            # breakdown ("streams": {name: {last_segment_age_s, ok}})
            # for every ADMITTED stream, and the endpoint goes 503
            # when ANY of them is stale — one wedged tenant must flip
            # health even while its neighbors keep the global last-
            # segment stamp fresh (utils/telemetry.health).
            from srtb_tpu.utils.telemetry import health

            h = health(stale_after_s=self.health_stale_after_s)
            data = (json.dumps(h, sort_keys=True) + "\n").encode()
            self.send_response(200 if h["ok"] else 503)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        if self.path == "/fleet":
            # the control tower's status snapshot (obs/status.py):
            # pool member states, per-stream SLO burn, roofline,
            # batch occupancy, drift — plus the rollup-store tail
            # when the server was started with fleet_store_dir
            from srtb_tpu.obs.status import fleet_status

            status = fleet_status(store_dir=self.fleet_store_dir)
            data = (json.dumps(status, sort_keys=True)
                    + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        if self.path == "/frames.json":
            data = (json.dumps(
                {"streams": self._all_frames()}) + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        if self.path in ("/", "/index.html"):
            frames = self._latest_frames()
            if frames:
                body = "".join(
                    _PANE_TEMPLATE.format(s=s, name=html.escape(name))
                    for s, name in sorted(frames.items()))
            else:
                body = ('<p>no frames yet (panes appear on first '
                        'refresh with data)</p>'
                        '<meta http-equiv="refresh" content="2">')
            pane_js = json.dumps(
                _PANE_TEMPLATE.format(s="__S__", name=""))
            data = _INDEX_TEMPLATE.format(body=body,
                                          pane_js=pane_js).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        name = os.path.basename(self.path)
        path = os.path.join(self.directory, name)
        if name.endswith(".png") and os.path.exists(path):
            with open(path, "rb") as f:
                data = f.read()
            self.send_response(200)
            self.send_header("Content-Type", "image/png")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        self.send_response(404)
        self.end_headers()


class WaterfallHTTPServer:
    """Serve the waterfall PNG directory on a background thread.

    The serve thread is supervised (resilience/supervisor.py): if
    ``serve_forever`` dies — a momentary OS-level failure of the
    accept loop — it is restarted with a bounded budget instead of
    silently leaving the observation without its live view.  The GUI
    is best-effort, so the supervisor restarts regardless of the
    error's classification; an exhausted budget logs and gives up
    (never takes the pipeline down)."""

    def __init__(self, directory: str, port: int = 0,
                 address: str = "127.0.0.1",
                 health_stale_after_s: float = 30.0,
                 supervisor=None, fleet_store_dir: str = ""):
        handler = type("Handler", (_Handler,), {
            "directory": directory,
            "health_stale_after_s": health_stale_after_s,
            "fleet_store_dir": fleet_store_dir})
        self._httpd = http.server.ThreadingHTTPServer((address, port),
                                                      handler)
        self.port = self._httpd.server_address[1]
        if supervisor is None:
            from srtb_tpu.resilience.supervisor import Supervisor
            supervisor = Supervisor("gui_server", max_restarts=3,
                                    restart_fatal=True)
        self._supervisor = supervisor
        self._stopping = False
        self._thread = threading.Thread(target=self._serve,
                                        name="srtb-gui-server",
                                        daemon=True)
        termination.tag_thread(self._thread)

    def _serve(self):
        while True:
            try:
                self._httpd.serve_forever()
                return  # shutdown() was called: clean exit
            except Exception as e:  # noqa: BLE001 - supervised restart
                if self._stopping or \
                        not self._supervisor.should_restart(e):
                    log.error(f"[gui] server thread giving up: {e!r}")
                    return

    def start(self) -> "WaterfallHTTPServer":
        self._thread.start()
        log.info(f"[gui] waterfall at http://127.0.0.1:{self.port}/")
        return self

    def stop(self):
        self._stopping = True
        self._httpd.shutdown()
        self._httpd.server_close()
        # join the serve_forever thread: shutdown() only signals it,
        # and an unjoined (if daemon) thread is exactly the leak the
        # sanitizer's thread check exists to catch
        if self._thread.is_alive():
            self._thread.join(timeout=5)
