"""Running-mean 1-bit quantizer (ref: algorithm/running_mean.hpp:30-80).

Per channel: compare each sample against a sliding-window mean that trails
it by ``windowsize`` samples, emit 1 bit (sample > mean), and carry the
running mean across calls.  The reference loops serially per channel on
the GPU; here the recurrence is a ``lax.scan`` over the (vectorized)
channel axis — time is sequential, channels ride the VPU lanes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def running_mean_init_average(data: jnp.ndarray, windowsize: int):
    """Initial per-channel average over the first window
    (ref: running_mean.hpp:61-78).  ``data`` is [nsamp, nchan]."""
    return jnp.mean(data[:windowsize].astype(jnp.float32), axis=0)


def running_mean(data: jnp.ndarray, windowsize: int, ave: jnp.ndarray):
    """[nsamp, nchan] samples -> ([nsamp, nchan] 1-bit output, final ave).

    Mirrors the reference's two-phase update: for output row i the
    comparison uses the average state after consuming rows < i+windowsize,
    then updates with (tail - head)/windowsize; the final ``windowsize``
    rows reuse mirrored tail samples (ref: running_mean.hpp:41-57).
    """
    nsamp, nchan = data.shape
    x = data.astype(jnp.float32)

    def phase1(ave_j, i):
        head = x[i - windowsize]
        tail = x[i]
        out = (head > ave_j).astype(jnp.uint8)
        ave_next = ave_j + (tail - head) / windowsize
        return ave_next, out

    ave1, out1 = jax.lax.scan(phase1, ave,
                              jnp.arange(windowsize, nsamp))

    def phase2(ave_j, i):
        head = x[nsamp + i - windowsize]
        tail = x[nsamp - i - 1]
        out = (head > ave_j).astype(jnp.uint8)
        ave_next = ave_j + (tail - head) / windowsize
        return ave_next, out

    ave2, out2 = jax.lax.scan(phase2, ave1, jnp.arange(windowsize))

    out = jnp.concatenate([out1, out2], axis=0)
    del nchan
    return out, ave2


def running_mean_oracle(data: np.ndarray, windowsize: int,
                        ave: np.ndarray):
    """Direct transliteration for tests."""
    nsamp, nchan = data.shape
    out = np.zeros_like(data, dtype=np.uint8)
    ave = ave.astype(np.float64).copy()
    x = data.astype(np.float64)
    for j in range(nchan):
        a = ave[j]
        for i in range(windowsize, nsamp):
            head = x[i - windowsize, j]
            tail = x[i, j]
            out[i - windowsize, j] = head > a
            a += (tail - head) / windowsize
        for i in range(windowsize):
            head = x[nsamp + i - windowsize, j]
            tail = x[nsamp - i - 1, j]
            out[i + nsamp - windowsize, j] = head > a
            a += (tail - head) / windowsize
        ave[j] = a
    return out, ave
