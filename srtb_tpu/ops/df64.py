"""Double-single ("df64") arithmetic: emulate ~48-bit precision with two f32.

TPU v5e has no fast fp64 ALU, the same constraint as the consumer GPUs the
reference targets; the reference proves two-float arithmetic suffices for
the dedispersion phase (ref: 3rdparty/dsmath/dsmath_sycl.h, used via
coherent_dedispersion.hpp:31-53 when ``use_emulated_fp64``).  This module is
an independent implementation of the classic Dekker/Knuth error-free
transforms as vectorized JAX ops — everything fuses into one XLA kernel.

A df64 value is a pair ``(hi, lo)`` of float32 arrays with ``|lo| <=
ulp(hi)/2`` and value ``hi + lo``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_SPLITTER = np.float32(4097.0)  # 2^12 + 1 for f32 Dekker splitting

# The error-free transforms below only work if the compiler evaluates
# them literally: XLA's simplifier rewrites patterns like (a + b) - a to
# b, which zeroes every lo component and silently degrades df64 to f32
# under jit (verified on CPU: the chirp phase lost ~1 rad at k ~ 8e5).
# optimization_barrier makes the intermediate opaque to the simplifier.
_ob = jax.lax.optimization_barrier

# Older jax has no batching rule for optimization_barrier, which breaks
# any vmap over df64 code (micro-batched segments, DM-grid trials under
# shard_map).  The barrier is shape-identity per operand, so the rule is
# trivial: bind and pass the batch dims through unchanged.
try:
    from jax._src.lax import lax as _lax_internal
    from jax.interpreters import batching as _batching

    _ob_p = getattr(_lax_internal, "optimization_barrier_p", None)
    if _ob_p is not None and _ob_p not in _batching.primitive_batchers:
        def _ob_batcher(args, dims):
            outs = _ob_p.bind(*args)
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            return outs, dims
        _batching.primitive_batchers[_ob_p] = _ob_batcher
except ImportError:  # pragma: no cover - newer jax: rule ships built in
    pass


def two_sum(a, b):
    """Error-free sum: a + b = s + e exactly."""
    s = _ob(a + b)
    v = _ob(s - a)
    e = (a - (s - v)) + (b - v)
    return s, e


def quick_two_sum(a, b):
    """Error-free sum assuming |a| >= |b|."""
    s = _ob(a + b)
    e = b - (s - a)
    return s, e


def _split(a):
    """Dekker split of f32 into high/low halves with <=12-bit mantissas."""
    t = _ob(_SPLITTER * a)
    hi = _ob(t - (t - a))
    lo = a - hi
    return hi, lo


def two_prod(a, b):
    """Error-free product: a * b = p + e exactly (no FMA assumed)."""
    p = _ob(a * b)
    a_hi, a_lo = _split(a)
    b_hi, b_lo = _split(b)
    e = ((a_hi * b_hi - p) + a_hi * b_lo + a_lo * b_hi) + a_lo * b_lo
    return p, e


def df64(hi, lo=None):
    hi = jnp.asarray(hi, dtype=jnp.float32)
    if lo is None:
        lo = jnp.zeros_like(hi)
    return hi, lo


def from_float64(x) -> tuple[np.ndarray, np.ndarray]:
    """Host-side exact f64 -> (hi, lo) f32 pair (numpy)."""
    x = np.asarray(x, dtype=np.float64)
    hi = x.astype(np.float32)
    lo = (x - hi.astype(np.float64)).astype(np.float32)
    return hi, lo


def to_float64(a) -> np.ndarray:
    hi, lo = a
    return np.asarray(hi, dtype=np.float64) + np.asarray(lo, dtype=np.float64)


def add(a, b):
    a_hi, a_lo = a
    b_hi, b_lo = b
    s, e = two_sum(a_hi, b_hi)
    e = e + a_lo + b_lo
    return quick_two_sum(s, e)


def sub(a, b):
    b_hi, b_lo = b
    return add(a, (-b_hi, -b_lo))


def mul(a, b):
    a_hi, a_lo = a
    b_hi, b_lo = b
    p, e = two_prod(a_hi, b_hi)
    e = e + a_hi * b_lo + a_lo * b_hi
    return quick_two_sum(p, e)


def div(a, b):
    """df64 / df64 via one Newton refinement of the f32 quotient."""
    a_hi, a_lo = a
    b_hi, b_lo = b
    q1 = a_hi / b_hi
    # r = a - q1 * b, computed in df64
    r = sub(a, mul(df64(q1), b))
    q2 = r[0] / b_hi
    return quick_two_sum(q1, q2)


def frac(a):
    """Fractional part (value - round-toward-zero integer part), like
    ``modf`` in the reference phase computation
    (ref: coherent_dedispersion.hpp:142-143, math.hpp:97-154).

    Returns a plain f32 (the fraction fits comfortably in one float once the
    up-to-1e9 integer part is removed).
    """
    hi, lo = a
    int_hi = jnp.trunc(hi)
    # hi - int_hi is exact (both representable), then fold in lo
    f = (hi - int_hi) + lo
    # lo may push the fraction across an integer boundary
    f = f - jnp.trunc(f)
    # match modf semantics: fraction carries the sign of the full value
    # (hi dominates the sign); e.g. 1e9 + 0.6 stored as (1e9+64, -63.4)
    # must yield +0.6, not -0.4
    positive = hi >= 0
    f = jnp.where(positive & (f < 0), f + 1.0, f)
    f = jnp.where((~positive) & (f > 0), f - 1.0, f)
    return f
