"""Device kernels as jittable JAX functions (+ Pallas where it pays off).

Each op mirrors one device kernel of the reference (see table in SURVEY.md
§2.4) and is tested against a numpy golden model in ``tests/``.
"""

from srtb_tpu.ops import unpack, window, dedisperse, rfi, detect, fft, spectrum  # noqa: F401
