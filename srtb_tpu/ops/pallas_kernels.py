"""Pallas TPU kernels for the hot elementwise ops.

Two kernels where explicit VMEM control beats relying on XLA fusion:

1. ``dedisperse_df64``: chirp multiply with the phase computed **on the
   fly** inside the kernel using df64 two-float arithmetic.  The baseline
   path streams a precomputed chirp bank from HBM (8 bytes/channel/trial);
   computing the phase in-register turns the op from memory-bound (3
   arrays in, 2 out) into 2-in/2-out — and for DM search it removes the
   [n_dm, 2, n] chirp bank from HBM entirely.  (Same math as
   ops.dedisperse.chirp_factor_df64 / ref: coherent_dedispersion.hpp
   phase_factor_v3 with dsmath df64.)

2. ``unpack_2bit_window``: sub-byte unpack fused with the FFT-window
   multiply (ref: unpack.hpp:102-121 handwritten 2-bit kernel + fused
   transform) — one byte load produces four windowed f32 samples without
   an intermediate HBM round trip.

Both fall back transparently to the jnp implementations when Pallas is
unavailable (pure-CPU CI), and are validated against them in tests via
``interpret=True``.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools

import jax
import jax.numpy as jnp
import numpy as np

from srtb_tpu.ops import dedisperse as dd

# lane-friendly tile: rows x 128 lanes; f32 min tile is (8, 128)
_LANES = 128
_ROWS = 256  # 256*128 = 32768 elements per grid step, 128 KiB f32 in VMEM


def _pallas_available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401
        return True
    except ImportError:  # pragma: no cover
        return False


# ----------------------------------------------------------------
# df64 helpers usable inside kernels (f32-only, no tuples of refs).
#
# The error-free transforms only survive a compiler that won't rewrite
# (a + b) - a to b.  Which guard that takes depends on who compiles the
# kernel body:
#   * interpret=True runs the kernel as ordinary XLA ops, and XLA's
#     algebraic simplifier DOES that rewrite — optimization_barrier is
#     required (same as ops/df64.py; dropping it measurably zeroes every
#     lo component, test_dedisperse_df64_kernel_high_channel_offset).
#   * interpret=False lowers via Mosaic, which does not implement
#     optimization_barrier (NotImplementedError on a real chip) and does
#     not need it: its MLIR arith lowering keeps IEEE semantics.
#     Verified empirically on a v5e — the non-interpret kernel matches
#     the float64 chirp oracle at |k| ~ 1e9 turns, which would be off by
#     whole turns if any lo component were simplified away
#     (tests/test_pallas_kernels.py "mosaic" cases).
# The switch is a kernel-build argument: each pallas_call wrapper scopes
# it with ``_ob_mode(interpret)`` around kernel tracing (tracing happens
# inside pl.pallas_call, so the scope is exact).  It is a ContextVar,
# not a module global, so two threads building kernels concurrently
# (e.g. two SegmentProcessors) cannot see each other's setting.
# ----------------------------------------------------------------

_USE_OB: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "srtb_pallas_use_ob", default=True)


@contextlib.contextmanager
def _ob_mode(interpret: bool):
    """Scope the EFT-barrier decision for one kernel build: barriers on
    under interpret (XLA simplifier would rewrite the EFTs away), off
    under Mosaic (unimplemented there, and unneeded — see block comment
    above)."""
    token = _USE_OB.set(bool(interpret))
    try:
        yield
    finally:
        _USE_OB.reset(token)


def _ob(x):
    return jax.lax.optimization_barrier(x) if _USE_OB.get() else x


def _two_sum(a, b):
    s = _ob(a + b)
    v = _ob(s - a)
    return s, (a - (s - v)) + (b - v)


def _split(a):
    t = _ob(jnp.float32(4097.0) * a)
    hi = _ob(t - (t - a))
    return hi, a - hi


def _two_prod(a, b):
    p = _ob(a * b)
    a_hi, a_lo = _split(a)
    b_hi, b_lo = _split(b)
    return p, ((a_hi * b_hi - p) + a_hi * b_lo + a_lo * b_hi) + a_lo * b_lo


def _df_add(x_hi, x_lo, y_hi, y_lo):
    s, e = _two_sum(x_hi, y_hi)
    e = e + x_lo + y_lo
    s2 = _ob(s + e)
    return s2, e - (s2 - s)


def _df_mul(x_hi, x_lo, y_hi, y_lo):
    p, e = _two_prod(x_hi, y_hi)
    e = e + x_hi * y_lo + x_lo * y_hi
    s = _ob(p + e)
    return s, e - (s - p)


def _df_div(x_hi, x_lo, y_hi, y_lo):
    q1 = x_hi / y_hi
    p_hi, p_lo = _df_mul(q1, jnp.zeros_like(q1), y_hi, y_lo)
    r_hi, r_lo = _df_add(x_hi, x_lo, -p_hi, -p_lo)
    q2 = r_hi / y_hi
    s = _ob(q1 + q2)
    return s, q2 - (s - q1)


def _chirp_phase_block(i_hi, i_lo, f_min, df, f_c, dm):
    """delta_phi for channel indices i = i_hi + i_lo (both exact f32;
    split from integers by the caller — a float32 index is exact only
    below 2^24 and phase errors scale by whole turns beyond it) — df64
    arithmetic on split constants, mirroring
    ops.dedisperse._chirp_phase_df64."""
    def c(v):
        hi = np.float32(v)
        return jnp.float32(hi), jnp.float32(np.float64(v) - np.float64(hi))

    f_min_hi, f_min_lo = c(f_min)
    df_hi, df_lo = c(df)
    f_c_hi, f_c_lo = c(f_c)
    d_hi, d_lo = c(dd.D * 1e6)
    dm_hi, dm_lo = c(dm)

    i = i_hi + i_lo  # only used for shape/fill helpers below
    a_hi, a_lo = _df_mul(df_hi, df_lo, i_hi, jnp.zeros_like(i_hi))
    b_hi, b_lo = _df_mul(df_hi, df_lo, i_lo, jnp.zeros_like(i_lo))
    fi_hi, fi_lo = _df_add(a_hi, a_lo, b_hi, b_lo)
    f_hi, f_lo = _df_add(f_min_hi, jnp.full_like(i, f_min_lo), fi_hi, fi_lo)

    ddm_hi, ddm_lo = _df_mul(d_hi, d_lo, dm_hi, dm_lo)
    q_hi, q_lo = _df_div(jnp.full_like(i, ddm_hi), jnp.full_like(i, ddm_lo),
                         f_hi, f_lo)
    delf_hi, delf_lo = _df_add(f_hi, f_lo, -f_c_hi,
                               jnp.full_like(i, -f_c_lo))
    r_hi, r_lo = _df_div(delf_hi, delf_lo, jnp.full_like(i, f_c_hi),
                         jnp.full_like(i, f_c_lo))
    r2_hi, r2_lo = _df_mul(r_hi, r_lo, r_hi, r_lo)
    k_hi, k_lo = _df_mul(q_hi, q_lo, r2_hi, r2_lo)

    # frac with modf semantics (sign of the value)
    int_hi = jnp.trunc(k_hi)
    frac = (k_hi - int_hi) + k_lo
    frac = frac - jnp.trunc(frac)
    positive = k_hi >= 0
    frac = jnp.where(positive & (frac < 0), frac + 1.0, frac)
    frac = jnp.where((~positive) & (frac > 0), frac - 1.0, frac)
    return jnp.float32(-2.0 * np.pi) * frac


def _channel_index_split(rows: int, i0: int):
    """Global channel index of every element of this grid step's
    [rows, _LANES] block, as an exact hi/lo float32 split (hi a multiple
    of 2^12, f32-exact to 2^36; lo < 2^12) — the one preamble every
    per-channel kernel shares."""
    from jax.experimental import pallas as pl

    step = pl.program_id(0)
    base = i0 + step * (rows * _LANES)
    row_idx = jax.lax.broadcasted_iota(jnp.int32, (rows, _LANES), 0)
    lane_idx = jax.lax.broadcasted_iota(jnp.int32, (rows, _LANES), 1)
    i_int = jnp.int32(base) + row_idx * _LANES + lane_idx
    return ((i_int & ~0xFFF).astype(jnp.float32),
            (i_int & 0xFFF).astype(jnp.float32))


def _df_frac32(hi, lo):
    """Single-f32 fraction of a df64 value (mod-1 representative; the
    final cos/sin only sees the phase mod one turn)."""
    t = jnp.trunc(hi)
    f = (hi - t) + lo
    return f - jnp.trunc(f)


def _chirp_phase_block_anchored(rows, i0, consts):
    """Anchored-Taylor chirp phase for this grid step's [rows, _LANES]
    block: one df64 anchor evaluation PER ROW (a [rows, 1] vector —
    1/128th of the per-element work) plus a cheap per-lane Taylor
    update — replacing the exact path's ~3 df64 divisions *per element*
    (measured 6.6x the bank-multiply cost at 2^27).  Derivation, error
    budget and the validity bound live with ops.dedisperse
    .anchored_chirp_consts; the builders only pass ``consts`` when the
    cubic remainder over one row's 128 channels is < 1e-6 turns (true
    for every physical config — 128-channel spans are tiny)."""
    from jax.experimental import pallas as pl

    blk = rows * _LANES
    step = pl.program_id(0)
    row_idx = jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0)
    base = jnp.int32(i0) + step * jnp.int32(blk) + row_idx * _LANES
    b_hi = (base & ~0xFFF).astype(jnp.float32)        # [rows, 1]
    b_lo = (base & 0xFFF).astype(jnp.float32)

    def c(pair):
        return jnp.float32(pair[0]), jnp.float32(pair[1])

    df_hi, df_lo = c(consts["df"])
    fm_hi, fm_lo = c(consts["f_min"])
    A_hi, A_lo = c(consts["A"])
    C1_hi, C1_lo = c(consts["C1"])
    fc_hi, fc_lo = c(consts["f_c"])
    zero = jnp.float32(0)

    # f at each row anchor, then k via the product form u * r^2 (the
    # expanded C1*f - C2 + u form cancels ~1e9-turn terms and loses 3
    # digits of the fraction — measured 1.4e-5 turns)
    a1 = _df_mul(df_hi, df_lo, b_hi, zero)
    a2 = _df_mul(df_hi, df_lo, b_lo, zero)
    fi = _df_add(*a1, *a2)
    fa = _df_add(fm_hi, fm_lo, *fi)
    u = _df_div(A_hi, A_lo, *fa)              # A / f_a
    dfc = _df_add(*fa, -fc_hi, -fc_lo)
    r = _df_div(*dfc, fc_hi, fc_lo)
    k = _df_mul(*u, *_df_mul(*r, *r))
    k0f = _df_frac32(*k)                      # [rows, 1]

    # dk/d(channel) = df * (C1 - A/f^2), reduced mod 1 (delta is an
    # integer, so frac(k1*delta) == frac(frac(k1)*delta)), kept df64
    w = _df_div(*u, *fa)                      # A / f_a^2
    s = _df_add(C1_hi, C1_lo, -w[0], -w[1])
    k1 = _df_mul(df_hi, df_lo, *s)
    k1f = _two_sum(k1[0] - jnp.trunc(k1[0]), k1[1])

    # quadratic/cubic Taylor terms are < ~1e-4 turns over one row:
    # plain f32 suffices
    fa32 = fa[0]
    fa2 = fa32 * fa32
    k2 = jnp.float32(consts["df2A"]) / (fa2 * fa32)
    k3 = -jnp.float32(consts["df3A"]) / (fa2 * fa2)

    delta = jax.lax.broadcasted_iota(
        jnp.int32, (1, _LANES), 1).astype(jnp.float32)  # lane offset
    p_hi, p_lo = _df_mul(k1f[0], k1f[1],
                         jnp.broadcast_to(delta, (rows, _LANES)),
                         jnp.zeros((rows, _LANES), jnp.float32))
    v_hi, v_lo = _df_add(k0f, zero, p_hi, p_lo)
    poly = (delta * delta) * (k2 + k3 * delta)
    frac = (v_hi - jnp.trunc(v_hi)) + v_lo + poly
    frac = frac - jnp.trunc(frac)
    return jnp.float32(-2.0 * np.pi) * frac


def _chirp_consts(n, f_min, df, f_c, dm, i0, exact: bool = False):
    """Builder-side consts for the anchored in-kernel chirp; ``exact``
    (the Config.chirp_exact escape hatch) or the
    SRTB_PALLAS_CHIRP_EXACT=1 env knob forces the exact per-element
    path (hardware A/B of the round-3 anchored rewrite)."""
    import os
    if exact or os.environ.get("SRTB_PALLAS_CHIRP_EXACT", "") == "1":
        return None
    return dd.anchored_chirp_consts(n, f_min, df, f_c, dm, i0=int(i0),
                                    block=_LANES, allow_shrink=False)


def _chirp_phase(rows, i0, f_min, df, f_c, dm, consts):
    """Dispatch: anchored-Taylor when the builder proved it valid,
    exact per-element df64 otherwise."""
    if consts is not None:
        return _chirp_phase_block_anchored(rows, i0, consts)
    i_hi, i_lo = _channel_index_split(rows, i0)
    return _chirp_phase_block(i_hi, i_lo, f_min, df, f_c, dm)


def _spectrum_tiling(n: int):
    """(rows_total, rows, grid) for a [2, n] spectrum kernel launch —
    shared by every elementwise spectrum kernel here."""
    if n % _LANES:
        raise ValueError(f"n must be a multiple of {_LANES}")
    rows_total = n // _LANES
    rows = min(_ROWS, rows_total)
    if rows_total % rows:
        raise ValueError(f"{rows_total} rows not divisible by block {rows}")
    return rows_total, rows, (rows_total // rows,)


def _dedisperse_kernel(re_ref, im_ref, out_re_ref, out_im_ref, *,
                       f_min, df, f_c, dm, rows, i0, consts=None):
    phase = _chirp_phase(rows, i0, f_min, df, f_c, dm, consts)
    c = jnp.cos(phase)
    s = jnp.sin(phase)
    re = re_ref[:]
    im = im_ref[:]
    out_re_ref[:] = re * c - im * s
    out_im_ref[:] = re * s + im * c


def _rfi_dedisperse_kernel(re_ref, im_ref, thr_ref, mask_ref, out_re_ref,
                           out_im_ref, *, f_min, df, f_c, dm, rows, i0,
                           norm, has_mask, consts=None):
    """Fused RFI stage-1 (avg-threshold zap + normalize + manual mask,
    ref: rfi_mitigation_pipe.hpp:50-94) feeding the df64 chirp multiply:
    the spectrum crosses HBM once instead of once per stage."""
    re = re_ref[:]
    im = im_ref[:]
    # RFI s1: zap where power exceeds threshold*mean (thr_ref holds the
    # precomputed product), scale survivors by the normalization
    # coefficient (ref: rfi_mitigation_pipe.hpp:61-78)
    power = re * re + im * im
    keep = power <= thr_ref[0]
    scale = jnp.where(keep, jnp.float32(norm), 0.0)
    if has_mask:
        scale = scale * mask_ref[:]
    re = re * scale
    im = im * scale

    phase = _chirp_phase(rows, i0, f_min, df, f_c, dm, consts)
    c = jnp.cos(phase)
    s = jnp.sin(phase)
    out_re_ref[:] = re * c - im * s
    out_im_ref[:] = re * s + im * c


def rfi_s1_dedisperse_df64(spec_ri: jnp.ndarray, threshold: float,
                           norm: float, f_min: float, df: float,
                           f_c: float, dm: float,
                           mask: jnp.ndarray | None = None,
                           interpret: bool = False,
                           i0: int = 0, exact: bool = False) -> jnp.ndarray:
    """spec_ri [2, n] -> RFI-s1-zapped, normalized, manually-masked and
    dedispersed [2, n] in ONE kernel pass (the mean-power reduce runs as
    a jnp pass first; everything elementwise is fused here).

    Matches rfi.mitigate_rfi_average_and_normalize +
    rfi.mitigate_rfi_manual + the chirp multiply applied in sequence.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = spec_ri.shape[-1]
    rows_total, rows, grid = _spectrum_tiling(n)

    re = spec_ri[0].reshape(rows_total, _LANES)
    im = spec_ri[1].reshape(rows_total, _LANES)
    power_mean = jnp.mean(spec_ri[0] ** 2 + spec_ri[1] ** 2)
    thr = (jnp.float32(threshold) * power_mean).reshape(1)

    has_mask = mask is not None
    block = pl.BlockSpec((rows, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    if has_mask:
        # ``mask`` is a ZAP mask (True/1 = zero this bin, matching
        # rfi.mitigate_rfi_manual); the kernel multiplies by keep = 1-zap
        keep = 1.0 - mask.astype(jnp.float32)
        mask2d = keep.reshape(rows_total, _LANES)
        mask_block = block
    else:  # placeholder tile, never read by the kernel
        mask2d = jnp.zeros((1, _LANES), jnp.float32)
        mask_block = pl.BlockSpec((1, _LANES), lambda i: (0, 0),
                                  memory_space=pltpu.VMEM)
    kernel = functools.partial(_rfi_dedisperse_kernel, f_min=f_min, df=df,
                               f_c=f_c, dm=dm, rows=rows, i0=int(i0),
                               norm=float(norm), has_mask=has_mask,
                               consts=_chirp_consts(
                                   n, f_min, df, f_c, dm, i0, exact))
    with _ob_mode(interpret):
        out_re, out_im = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[block, block,
                      pl.BlockSpec(memory_space=pltpu.SMEM),
                      mask_block],
            out_specs=[block, block],
            out_shape=[jax.ShapeDtypeStruct((rows_total, _LANES),
                                            jnp.float32)] * 2,
            interpret=interpret,
        )(re, im, thr, mask2d)
    return jnp.stack([out_re.reshape(n), out_im.reshape(n)])


def dedisperse_df64(spec_ri: jnp.ndarray, f_min: float, df: float,
                    f_c: float, dm: float,
                    interpret: bool = False, i0: int = 0,
                    exact: bool = False) -> jnp.ndarray:
    """spec_ri [2, n] -> dedispersed [2, n], chirp generated in-kernel;
    ``i0`` is the global index of the first channel (sequence shards).

    n must be a multiple of 128; grid steps cover _ROWS*128 channels each.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = spec_ri.shape[-1]
    rows_total, rows, grid = _spectrum_tiling(n)

    re = spec_ri[0].reshape(rows_total, _LANES)
    im = spec_ri[1].reshape(rows_total, _LANES)
    kernel = functools.partial(_dedisperse_kernel, f_min=f_min, df=df,
                               f_c=f_c, dm=dm, rows=rows, i0=int(i0),
                               consts=_chirp_consts(
                                   n, f_min, df, f_c, dm, i0, exact))
    block = pl.BlockSpec((rows, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    with _ob_mode(interpret):
        out_re, out_im = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[block, block],
            out_specs=[block, block],
            out_shape=[jax.ShapeDtypeStruct((rows_total, _LANES),
                                            jnp.float32),
                       jax.ShapeDtypeStruct((rows_total, _LANES),
                                            jnp.float32)],
            interpret=interpret,
        )(re, im)
    return jnp.stack([out_re.reshape(n), out_im.reshape(n)])


# ----------------------------------------------------------------
# fused 2-bit unpack + window
# ----------------------------------------------------------------

# ----------------------------------------------------------------
# fused waterfall post-pass: spectral-kurtosis stats, zap, time series
# ----------------------------------------------------------------

def _sk_stats_kernel(re_ref, im_ref, s2_ref, s4_ref, fs_ref):
    from jax.experimental import pallas as pl

    t = pl.program_id(1)  # inner grid dim: time tiles

    @pl.when(t == 0)
    def _init():
        s2_ref[:] = jnp.zeros_like(s2_ref)
        s4_ref[:] = jnp.zeros_like(s4_ref)

    re = re_ref[:]
    im = im_ref[:]
    p = re * re + im * im                      # [R, TB]
    rows, tb = p.shape
    # keep 128 lanes through the reduction; final lane-sum happens outside
    p3 = p.reshape(rows, tb // _LANES, _LANES)
    s2_ref[:] += jnp.sum(p3, axis=1)           # [R, 128]
    s4_ref[:] += jnp.sum(p3 * p3, axis=1)

    @pl.when(t == 0)
    def _first_samples():
        fs_ref[:] = p[:, :_LANES]              # power of the first lanes


def _sk_apply_kernel(re_ref, im_ref, keep_ref, out_re_ref, out_im_ref,
                     ts_ref):
    from jax.experimental import pallas as pl

    f = pl.program_id(1)  # inner grid dim: frequency tiles
    keep = keep_ref[:, 0:1] != 0.0             # [R, 1] row mask
    # select, not multiply: a zapped row carrying Inf/NaN must become
    # exactly zero, matching the jnp path's jnp.where
    re = jnp.where(keep, re_ref[:], 0.0)
    im = jnp.where(keep, im_ref[:], 0.0)
    out_re_ref[:] = re
    out_im_ref[:] = im
    p = re * re + im * im                      # [R, TB]

    @pl.when(f == 0)
    def _init():
        ts_ref[:] = jnp.zeros_like(ts_ref)

    rows, tb = p.shape
    ts_ref[:] += jnp.sum(p, axis=0).reshape(tb // _LANES, _LANES)


def _sk_tiles(nfreq: int, ntime: int):
    """(rows, time_block) tiling for the fused SK kernels, or None when
    the waterfall shape cannot tile (single source of truth for both the
    capability check and the kernels).  tb is capped at 256 lanes-rows:
    512 puts the [rows, tb] f32 blocks at 16.25 MB of scoped VMEM, just
    over the 16 MB Mosaic stack limit on v5e."""
    rows = min(8, nfreq)
    tb = min(256 * _LANES, ntime)
    if nfreq % rows or ntime % _LANES or ntime % tb or tb % _LANES:
        return None
    return rows, tb


def sk_tiling_ok(nfreq: int, ntime: int) -> bool:
    """Whether the fused SK kernels can tile this waterfall (callers fall
    back to the jnp ops otherwise, e.g. tiny test/bench shapes)."""
    return _sk_tiles(nfreq, ntime) is not None


def sk_zap_timeseries(wf_ri: jnp.ndarray, sk_threshold: float,
                      interpret: bool = False):
    """Fused spectral-kurtosis zap + detection front half in two HBM
    passes over the waterfall ``wf_ri [2, F, T]`` (re, im):

    pass 1 reads the waterfall once, producing per-row ``s2``/``s4``
    partial sums and first-sample powers; the tiny SK decision
    (ref: spectrum/rfi_mitigation.hpp:290-341 thresholds) happens in jnp;
    pass 2 reads the waterfall again, writes the zapped waterfall and
    accumulates the frequency-summed power time series
    (ref: signal_detect_pipe.hpp:305-316) in the same read.

    The jnp path costs ~3 reads + 1 write of the waterfall (SK stats,
    zap rewrite, time-series sum); this costs 2 reads + 1 write, and the
    time series comes out "for free" with the zap.

    Returns ``(wf_zapped_ri [2, F, T], zero_count [], ts [T])`` with
    ``zero_count``/``ts`` matching ops.detect semantics (zapped rows and
    first-sample-zero rows both count; ts is not yet mean-subtracted).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _, nfreq, ntime = wf_ri.shape
    m = ntime
    tiles = _sk_tiles(nfreq, ntime)
    if tiles is None:
        raise ValueError(f"bad waterfall tiling [{nfreq}, {ntime}]")
    rows, tb = tiles

    re, im = wf_ri[0], wf_ri[1]

    # ---- pass 1: stats (grid: freq outer, time inner for accumulation)
    grid1 = (nfreq // rows, ntime // tb)
    in_block = pl.BlockSpec((rows, tb), lambda f, t: (f, t),
                            memory_space=pltpu.VMEM)
    row_block = pl.BlockSpec((rows, _LANES), lambda f, t: (f, 0),
                             memory_space=pltpu.VMEM)
    s2, s4, fs = pl.pallas_call(
        _sk_stats_kernel,
        grid=grid1,
        in_specs=[in_block, in_block],
        out_specs=[row_block, row_block, row_block],
        out_shape=[jax.ShapeDtypeStruct((nfreq, _LANES), jnp.float32)] * 3,
        interpret=interpret,
    )(re, im)

    # ---- tiny per-row decision in jnp, thresholds shared with
    # rfi.mitigate_rfi_spectral_kurtosis ----
    zap = sk_zap_decision(jnp.sum(s2, axis=-1), jnp.sum(s4, axis=-1), m,
                          sk_threshold)
    zero_count = jnp.sum(
        (zap | (fs[:, 0] == 0)).astype(jnp.int32))

    out_ri, ts = sk_apply_timeseries(wf_ri, zap, interpret)
    return out_ri, zero_count, ts


def sk_zap_decision(s2_sum, s4_sum, m: int, sk_threshold: float):
    """Per-row zap verdict from the power moments (thresholds shared with
    rfi.mitigate_rfi_spectral_kurtosis)."""
    from srtb_tpu.ops.rfi import sk_decision_thresholds
    thr_low_, thr_high_ = sk_decision_thresholds(m, sk_threshold)
    sk = m * s4_sum / (s2_sum * s2_sum)
    return (sk > thr_high_) | (sk < thr_low_)


def sk_apply_timeseries(wf_ri: jnp.ndarray, zap: jnp.ndarray,
                        interpret: bool = False):
    """Pass 2 of the fused SK chain, standalone: zap the verdict rows and
    accumulate the frequency-summed power time series in the same read.
    ``zap`` is the [F] boolean verdict (e.g. from
    :func:`sk_zap_decision` over stats collected by the waterfall FFT's
    fused epilogue, ops/pallas_fft.fft_rows_stats_ri — in that pairing
    the waterfall is never re-read for statistics at all).

    Returns ``(wf_zapped_ri [2, F, T], ts [T])``.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _, nfreq, ntime = wf_ri.shape
    tiles = _sk_tiles(nfreq, ntime)
    if tiles is None:
        raise ValueError(f"bad waterfall tiling [{nfreq}, {ntime}]")
    rows, tb = tiles
    re, im = wf_ri[0], wf_ri[1]
    keep = jnp.broadcast_to((~zap).astype(jnp.float32)[:, None],
                            (nfreq, _LANES))
    grid2 = (ntime // tb, nfreq // rows)
    in_block2 = pl.BlockSpec((rows, tb), lambda t, f: (f, t),
                             memory_space=pltpu.VMEM)
    keep_block = pl.BlockSpec((rows, _LANES), lambda t, f: (f, 0),
                              memory_space=pltpu.VMEM)
    ts_block = pl.BlockSpec((tb // _LANES, _LANES), lambda t, f: (t, 0),
                            memory_space=pltpu.VMEM)
    out_re, out_im, ts2d = pl.pallas_call(
        _sk_apply_kernel,
        grid=grid2,
        in_specs=[in_block2, in_block2, keep_block],
        out_specs=[in_block2, in_block2, ts_block],
        out_shape=[jax.ShapeDtypeStruct((nfreq, ntime), jnp.float32),
                   jax.ShapeDtypeStruct((nfreq, ntime), jnp.float32),
                   jax.ShapeDtypeStruct((ntime // _LANES, _LANES),
                                        jnp.float32)],
        interpret=interpret,
    )(re, im, keep)

    return jnp.stack([out_re, out_im]), ts2d.reshape(ntime)


# Sub-byte unpack needs a lane interleave (out[4c+j] = field_j(byte[c])),
# which Mosaic cannot lower today: every legal spelling (stack+reshape,
# repeat, per-field slice-assign then flatten) either raises
# "infer-vector-layout: unsupported shape cast" on a real chip or lands
# the fields in blocked, not sample, order.  The kernel stays for
# interpret-mode CI parity and as the reference spelling; real-TPU
# segments take the XLA unpack (ops/unpack.py), whose shift/mask chain
# XLA fuses into the FFT input anyway — unpack is a few percent of an
# FFT-dominated pipeline, so nothing measurable is lost.
UNPACK_MOSAIC_OK = False


def _unpack_subbyte_kernel(byte_ref, win_ref, out_ref, *, nbits,
                           apply_window):
    b = byte_ref[:].astype(jnp.int32)
    per_byte = 8 // nbits
    mask = (1 << nbits) - 1
    # MSB-first fields (ref: unpack.hpp:43-140 generic + handwritten
    # 1/2/4-bit kernels share this bit order)
    fields = [((b >> (8 - nbits * (j + 1))) & mask).astype(jnp.float32)
              for j in range(per_byte)]
    # interleave along lanes: [R, C] x per_byte -> [R, per_byte*C]
    out = jnp.stack(fields, axis=-1).reshape(
        b.shape[0], per_byte * b.shape[1])
    if apply_window:
        out = out * win_ref[:]
    out_ref[:] = out


def unpack_subbyte_window(data: jnp.ndarray, nbits: int,
                          window: jnp.ndarray | None = None,
                          interpret: bool = False) -> jnp.ndarray:
    """uint8 [m] -> f32 [(8/nbits)*m] for nbits in {1, 2, 4}: MSB-first
    sub-byte unpack fused with an optional window multiply, one HBM pass
    (ref: unpack.hpp handwritten 1/2/4-bit kernels + fused transform)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if nbits not in (1, 2, 4):
        raise ValueError(f"sub-byte unpack needs nbits in 1/2/4, got {nbits}")
    per_byte = 8 // nbits
    m = data.shape[-1]
    if m % _LANES:
        raise ValueError(f"byte count must be a multiple of {_LANES}")
    rows_total = m // _LANES
    rows = min(_ROWS, rows_total)
    if rows_total % rows:
        raise ValueError(f"{rows_total} rows not divisible by block {rows}")
    grid = (rows_total // rows,)

    bytes2d = data.reshape(rows_total, _LANES)
    apply_window = window is not None
    if window is None:
        window = jnp.ones((rows_total, per_byte * _LANES),
                          dtype=jnp.float32)
    else:
        window = window.reshape(rows_total, per_byte * _LANES)

    kernel = functools.partial(_unpack_subbyte_kernel, nbits=nbits,
                               apply_window=apply_window)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((rows, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((rows, per_byte * _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((rows, per_byte * _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows_total, per_byte * _LANES),
                                       jnp.float32),
        interpret=interpret,
    )(bytes2d, window)
    return out.reshape(per_byte * m)


def unpack_2bit_window(data: jnp.ndarray,
                       window: jnp.ndarray | None = None,
                       interpret: bool = False) -> jnp.ndarray:
    """uint8 [m] -> f32 [4m]; see :func:`unpack_subbyte_window`."""
    return unpack_subbyte_window(data, 2, window, interpret)


# ----------------------------------------------------------------
# blocked-plane sub-byte unpack (the Mosaic-lowerable spelling)
# ----------------------------------------------------------------

def _unpack_planes_kernel(byte_ref, win_ref, out_ref, *, nbits,
                          apply_window):
    b = byte_ref[:].astype(jnp.int32)            # [rows, LANES]
    count = 8 // nbits
    mask = (1 << nbits) - 1
    for j in range(count):
        # MSB-first field j of every byte (ref: unpack.hpp:43-140)
        f = ((b >> (8 - nbits * (j + 1))) & mask).astype(jnp.float32)
        if apply_window:
            f = f * win_ref[j]
        out_ref[j] = f


def unpack_subbyte_planes_window(data: jnp.ndarray, nbits: int,
                                 window_planes: jnp.ndarray | None = None,
                                 interpret: bool = False) -> jnp.ndarray:
    """uint8 [m] -> blocked field planes [count, m] f32 (count = 8/nbits,
    plane k = field k of every byte), fused with the blocked window
    multiply — ONE HBM pass for unpack + window.

    This is the Mosaic-LOWERABLE sub-byte unpack: the sample-order kernel
    (:func:`unpack_subbyte_window`) needs a lane interleave
    (out[4c+j] = field_j(byte[c])) that Mosaic cannot lower (see
    UNPACK_MOSAIC_OK), but blocked planes put each field on a new MAJOR
    axis — per-plane [rows, 128] writes, no lane shuffle anywhere.  The
    blocked layout is exactly what ops.fft.rfft_subbyte consumes (its
    FFT decimation absorbs the blocked->natural permutation), so nothing
    downstream ever wants sample order.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if nbits not in (1, 2, 4):
        raise ValueError(f"sub-byte unpack needs nbits in 1/2/4, got {nbits}")
    count = 8 // nbits
    m = data.shape[-1]
    if m % _LANES:
        raise ValueError(f"byte count {m} not a multiple of {_LANES}")
    rows_total = m // _LANES
    rows = min(_ROWS, rows_total)
    if rows_total % rows:
        raise ValueError(f"{rows_total} rows not divisible by block {rows}")
    grid = (rows_total // rows,)

    bytes2d = data.reshape(rows_total, _LANES)
    apply_window = window_planes is not None
    if window_planes is None:
        win3d = jnp.ones((count, 1, _LANES), dtype=jnp.float32)
        win_block = pl.BlockSpec((count, 1, _LANES), lambda i: (0, 0, 0),
                                 memory_space=pltpu.VMEM)
    else:
        win3d = window_planes.reshape(count, rows_total, _LANES)
        win_block = pl.BlockSpec((count, rows, _LANES), lambda i: (0, i, 0),
                                 memory_space=pltpu.VMEM)

    kernel = functools.partial(_unpack_planes_kernel, nbits=nbits,
                               apply_window=apply_window)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((rows, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
                  win_block],
        out_specs=pl.BlockSpec((count, rows, _LANES), lambda i: (0, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((count, rows_total, _LANES),
                                       jnp.float32),
        interpret=interpret,
    )(bytes2d, win3d)
    return out.reshape(count, m)


# Pending on-chip Mosaic validation (run tools_tpu_r3_queue.sh section
# "planes unpack probe", then flip to True): the spelling avoids every
# construct the sample-order kernel died on, but Mosaic acceptance is
# only provable by compiling on a real chip.  SRTB_PALLAS_PLANES_UNPACK=1
# opts in before that.
PLANES_UNPACK_MOSAIC_OK = False


def planes_unpack_enabled(interpret: bool) -> bool:
    import os
    return interpret or PLANES_UNPACK_MOSAIC_OK or \
        os.environ.get("SRTB_PALLAS_PLANES_UNPACK", "") == "1"


def planes_tiling_ok(m: int) -> bool:
    """Whether a byte count fits the planes-unpack launch geometry
    (same pre-flight role as sk_tiling_ok: callers fall back to the XLA
    unpack instead of crashing at trace)."""
    if m % _LANES:
        return False
    rows_total = m // _LANES
    return rows_total % min(_ROWS, rows_total) == 0
