"""RFI mitigation kernels.

Three methods, mirroring the reference:
- stage 1: average-intensity threshold zap with normalization fused in
  (ref: pipeline/rfi_mitigation_pipe.hpp:50-80);
- manual frequency-range zap from a "a-b, c-d" config string
  (ref: spectrum/rfi_mitigation.hpp:63-158);
- stage 2: spectral-kurtosis zap over the dynamic spectrum
  (ref: spectrum/rfi_mitigation.hpp:290-341,
  mitigate_rfi_spectral_kurtosis_method_2).

All are pure jittable functions over the whole spectrum — the reference's
map_average / multi_mapreduce reductions become jnp.mean/sum that XLA maps
onto the VPU reduction trees.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from srtb_tpu.utils.logging import log


def _norm(c: jnp.ndarray) -> jnp.ndarray:
    """|c|^2 like srtb::norm (ref: math.hpp:58-70)."""
    return jnp.real(c) ** 2 + jnp.imag(c) ** 2


def mitigate_rfi_average_and_normalize(
        spectrum: jnp.ndarray, threshold: float,
        normalization_coefficient) -> jnp.ndarray:
    """Zap channels whose power exceeds ``threshold * mean power``; scale the
    survivors by the normalization coefficient
    (ref: rfi_mitigation_pipe.hpp:50-80).

    The coefficient is ``(N^2 / spectrum_channel_count)^(-1/2)`` computed by
    the caller — it undoes the two unnormalized FFTs' N-growth
    (ref: rfi_mitigation_pipe.hpp:61-65).
    """
    power = _norm(spectrum)
    mean_power = jnp.mean(power, axis=-1, keepdims=True)
    return mitigate_rfi_s1_given_mean(spectrum, mean_power, threshold,
                                      normalization_coefficient)


def mitigate_rfi_s1_given_mean(spectrum: jnp.ndarray, mean_power,
                               threshold: float,
                               normalization_coefficient) -> jnp.ndarray:
    """The elementwise half of RFI stage 1, with the mean power supplied
    by the caller — the form the fused spectrum tail folds into the
    forward FFT's final pass (the mean then comes from
    :func:`mean_power_packed` over the packed C2C output instead of a
    separate sweep over the materialized spectrum)."""
    power = _norm(spectrum)
    zap = power > threshold * mean_power
    return jnp.where(zap, jnp.zeros((), dtype=spectrum.dtype),
                     spectrum * normalization_coefficient)


def mean_power_packed(zf: jnp.ndarray) -> jnp.ndarray:
    """Mean ``|X_k|^2`` over the m dropped-Nyquist rfft bins, computed
    from the packed half-size C2C output ``zf [..., m]`` WITHOUT forming
    the spectrum (keepdims ``[..., 1]``).

    Parseval: with z[t'] = x[2t'] + i·x[2t'+1] and F = FFT_m(z)
    (unnormalized), sum_t x^2 = (1/m)·sum_k |F_k|^2, and the real-input
    Hermitian symmetry of the full 2m-point transform gives

        sum_{k=0}^{m-1} |X_k|^2 = sum_k |F_k|^2 + 2·Re(F_0)·Im(F_0)

    (X_0 = Re F_0 + Im F_0, X_m = Re F_0 - Im F_0, so X_0^2 - X_m^2 =
    4·Re F_0·Im F_0).  This lets the RFI stage-1 threshold be evaluated
    inside the same pass that writes the spectrum: the mean is a
    reduction over the FFT's already-materialized input, not a re-read
    of its output.  Agrees with the direct ``jnp.mean(|spec|^2)`` to
    float32 rounding (pinned in tests/test_fusion.py); decision flips
    are only possible for bins within ~1 ulp of threshold·mean.
    """
    m = zf.shape[-1]
    p = _norm(zf)
    total = jnp.sum(p, axis=-1, keepdims=True)
    f0 = zf[..., :1]
    return (total + 2.0 * jnp.real(f0) * jnp.imag(f0)) / m


def normalization_coefficient(n_channels: int,
                              spectrum_channel_count: int) -> float:
    """(N^2/spectrum_channel_count)^-0.5 in f32, matching the reference's
    float evaluation (ref: rfi_mitigation_pipe.hpp:61-65)."""
    n = np.float32(n_channels)
    return float(np.power(n * n / np.float32(spectrum_channel_count),
                          np.float32(-0.5)))


# ----------------------------------------------------------------
# manual frequency-range zap
# ----------------------------------------------------------------

def eval_rfi_ranges(mitigate_rfi_freq_list: str) -> list[tuple[float, float]]:
    """Parse "11-12, 15-90" into (low, high) MHz pairs
    (ref: spectrum/rfi_mitigation.hpp:63-88)."""
    ranges = []
    text = mitigate_rfi_freq_list.strip()
    if not text:
        return ranges
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        pieces = [p for p in part.split("-") if p.strip()]
        if len(pieces) != 2:
            log.warning(f"[eval_rfi_ranges] cannot parse {part!r}")
            continue
        ranges.append((float(pieces[0]), float(pieces[1])))
    return ranges


def rfi_ranges_to_mask(ranges, n_channels: int, baseband_freq_low: float,
                       baseband_bandwidth: float) -> np.ndarray | None:
    """Host-side: turn frequency ranges into a boolean zap mask over bins.

    Bin mapping matches the reference: bin = round((f - f_low) / bw * (N-1)),
    inclusive on both ends, with range order flipped when the band is
    inverted (ref: spectrum/rfi_mitigation.hpp:102-143).  Returns None when
    there is nothing to zap (lets jit skip the multiply).
    """
    if not ranges:
        return None
    mask = np.zeros(n_channels, dtype=bool)
    bw_sign = np.signbit(baseband_bandwidth)
    freq_high = baseband_freq_low + baseband_bandwidth
    any_zap = False
    for rfi_low, rfi_high in ranges:
        if np.signbit(rfi_high - rfi_low) != bw_sign:
            rfi_low, rfi_high = rfi_high, rfi_low
        lo = int(round((rfi_low - baseband_freq_low) / baseband_bandwidth
                       * (n_channels - 1)))
        hi = int(round((rfi_high - baseband_freq_low) / baseband_bandwidth
                       * (n_channels - 1)))
        if 0 <= lo <= hi < n_channels:
            mask[lo:hi + 1] = True
            any_zap = True
        else:
            log.warning(
                f"[mitigate_rfi_manual] RFI range {rfi_low} - {rfi_high} MHz "
                f"out of baseband range {baseband_freq_low} - {freq_high} MHz")
    return mask if any_zap else None


def mitigate_rfi_manual(spectrum: jnp.ndarray,
                        zap_mask: jnp.ndarray | None) -> jnp.ndarray:
    """Apply a precomputed zap mask (ref: rfi_mitigation.hpp:97-158)."""
    if zap_mask is None:
        return spectrum
    return jnp.where(zap_mask, jnp.zeros((), dtype=spectrum.dtype), spectrum)


# ----------------------------------------------------------------
# spectral kurtosis (stage 2)
# ----------------------------------------------------------------

def sk_decision_thresholds(m: int, sk_threshold: float):
    """(low, high) acceptance bounds for the SK estimator over M samples:
    the configured threshold symmetrized around 2, rescaled by
    (M-1)/(M+1) (ref: spectrum/rfi_mitigation.hpp:290-341).  Shared by
    the jnp op and the fused Pallas kernel so their zap decisions cannot
    drift apart."""
    thr_high = max(sk_threshold, 2.0 - sk_threshold)
    thr_low = min(sk_threshold, 2.0 - sk_threshold)
    scale = (m - 1.0) / (m + 1.0)
    return (np.float32(thr_low * scale + 1.0),
            np.float32(thr_high * scale + 1.0))


def mitigate_rfi_spectral_kurtosis(waterfall: jnp.ndarray,
                                   sk_threshold: float) -> jnp.ndarray:
    """Zap frequency rows of the dynamic spectrum whose spectral kurtosis
    falls outside [2 - thr, thr] rescaled by (M-1)/(M+1)
    (ref: spectrum/rfi_mitigation.hpp:290-341).

    ``waterfall`` is frequency-major ``[..., freq, time]``; SK is computed
    per frequency row over the M time samples.
    """
    m = waterfall.shape[-1]
    thr_low_, thr_high_ = sk_decision_thresholds(m, sk_threshold)

    x2 = _norm(waterfall)
    s2 = jnp.sum(x2, axis=-1)
    s4 = jnp.sum(x2 * x2, axis=-1)
    sk = m * s4 / (s2 * s2)
    zap = (sk > thr_high_) | (sk < thr_low_)
    return jnp.where(zap[..., None], jnp.zeros((), dtype=waterfall.dtype),
                     waterfall)
