"""Coherent dedispersion: frequency-domain chirp multiply.

Physics follows the reference exactly (ref: coherent_dedispersion.hpp):
``D = 4.148808e3`` MHz^2 pc^-1 cm^3 s (line 67), per-channel phase turns

    k = D * 1e6 * dm / f * ((f - f_c) / f_c)^2        (phase_factor_v3, line 141)
    factor = exp(-2*pi*i * frac(k))                   (lines 142-148)

with ``frac`` extracted before the trig because k reaches ~1e9 at high DM
(line 49), far beyond f32 mantissa range.

TPU-native design: the chirp depends only on (n, f_min, df, f_c, dm) — it is
**constant across segments** — so the primary path precomputes it once on
host in f64 and keeps it resident in HBM (one complex64 array the size of
the spectrum).  For DM-search grids where a per-trial host precompute would
bottleneck, ``chirp_factor_df64`` computes the same thing on device with
two-float arithmetic (the reference's dsmath df64 trick, proven there on
fp64-less GPUs); it is pure elementwise VPU work that XLA fuses.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from srtb_tpu.ops import df64 as ds

# dispersion constant, MHz^2 pc^-1 cm^3 s (ref: coherent_dedispersion.hpp:67)
D = 4.148808e3


def dispersion_delay_time(f, f_c, dm):
    """Delay relative to f_c, seconds; positive for f > f_c
    (ref: coherent_dedispersion.hpp:75-78)."""
    return -D * dm * (1.0 / (f * f) - 1.0 / (f_c * f_c))


def max_delay_time(freq_low: float, bandwidth: float, dm: float) -> float:
    """Max dispersion delay across the band
    (ref: coherent_dedispersion.hpp:81-85)."""
    return dispersion_delay_time(freq_low + bandwidth, freq_low, dm)


def nsamps_reserved(cfg) -> int:
    """Real samples reserved (overlapped) between consecutive segments to
    mask dedispersion edge corruption (ref: coherent_dedispersion.hpp:103-128).

    The non-reserved portion is rounded down to a multiple of
    2 * spectrum_channel_count so the waterfall FFT tiles exactly.
    """
    if not cfg.baseband_reserve_sample:
        return 0
    minimal = 2 * round(
        max_delay_time(cfg.baseband_freq_low, cfg.baseband_bandwidth, cfg.dm)
        * cfg.baseband_sample_rate)
    per_bin = cfg.spectrum_channel_count * 2
    n = cfg.baseband_input_count
    refft_total = (n - minimal) // per_bin * per_bin
    if refft_total > 0:
        return n - refft_total
    return 0


# ----------------------------------------------------------------
# chirp generation
# ----------------------------------------------------------------

def chirp_factor_host(n: int, f_min: float, df: float, f_c: float,
                      dm: float) -> np.ndarray:
    """Chirp factors for n channels at f = f_min + df*i, computed on host in
    float64 (numpy), returned as complex64.

    Bit-comparable to phase_factor_v3 with phase_real = double
    (ref: coherent_dedispersion.hpp:134-150).
    """
    i = np.arange(n, dtype=np.float64)
    f = f_min + df * i
    delta_f = f - f_c
    k = (D * 1e6) * dm / f * ((delta_f / f_c) * (delta_f / f_c))
    k_frac = np.modf(k)[0]
    delta_phi = -2.0 * np.pi * k_frac
    return (np.cos(delta_phi) + 1j * np.sin(delta_phi)).astype(np.complex64)


def chirp_factor_df64(n: int, f_min: float, df: float, f_c: float, dm,
                      dtype=jnp.complex64, i0: int = 0,
                      dm_lo=None, exact: bool = False) -> jnp.ndarray:
    """Same chirp computed on device with two-float (df64) arithmetic —
    jittable, dm may be a traced scalar (DM-search grids).  ``i0``
    generates the block of channels starting at that global index.
    ``exact=True`` forces the per-element df64 division chains instead
    of the anchored-Taylor fast path (Config.chirp_exact escape hatch).

    Mirrors phase_factor_v3 with phase_real = dsmath::df64
    (ref: coherent_dedispersion.hpp:31-53,134-150).
    """
    delta_phi = _chirp_phase_df64(n, f_min, df, f_c, dm, i0=i0,
                                  dm_lo=dm_lo, exact=exact)
    return (jnp.cos(delta_phi) + 1j * jnp.sin(delta_phi)).astype(dtype)


def chirp_factor_host_ri(n: int, f_min: float, df: float, f_c: float,
                         dm: float) -> np.ndarray:
    """Chirp as stacked (real, imag) float32 [2, n].

    TPU-native boundary representation: some TPU runtimes don't transfer
    complex buffers across the host<->device boundary, and splitting
    re/im is the natural layout for the VPU anyway; complex exists only
    inside jit.
    """
    c = chirp_factor_host(n, f_min, df, f_c, dm)
    return np.stack([c.real, c.imag]).astype(np.float32)


def chirp_factor_df64_ri(n: int, f_min: float, df: float, f_c: float,
                         dm, i0: int = 0, dm_lo=None,
                         anchor_consts=None,
                         exact: bool = False) -> jnp.ndarray:
    """df64 on-device chirp as stacked (cos, sin) float32 [2, n] — jit-safe
    output dtype on complex-less runtimes.  ``exact=True`` forces the
    per-element division chains (Config.chirp_exact escape hatch)."""
    phase = _chirp_phase_df64(n, f_min, df, f_c, dm, i0=i0, dm_lo=dm_lo,
                              anchor_consts=anchor_consts, exact=exact)
    return jnp.stack([jnp.cos(phase), jnp.sin(phase)])


# ---- anchored-Taylor fast path for the on-device df64 phase ----
#
# The exact per-element df64 evaluation of k spends ~3 df64 divisions per
# channel (measured 6.6x the cost of the precomputed-bank multiply at
# 2^27 on a v5e).  But k is an extremely smooth function of the channel
# index: expanding
#
#     k(f) = A (f - f_c)^2 / (f_c^2 f) = C1*f - C2 + A/f,
#     A = D*1e6*dm,  C1 = A/f_c^2,  C2 = 2A/f_c
#
# and Taylor-expanding in the channel offset d around an anchor channel,
# the cubic remainder over a block of B channels is bounded by
# |A| (|df| B)^4 / min|f|^5 turns — ~1e-10 for the flagship config at
# B = 32768.  So one df64 anchor evaluation per block (amortized to
# nothing) plus a cheap per-element update replaces the division chains:
#
#     k(i_a + d) ~ k0 + k1*d + k2*d^2 + k3*d^3
#     k1 = df*(C1 - A/f_a^2)   [df64, reduced mod 1 — d is an integer,
#                               so frac(k1*d) == frac(frac(k1)*d)]
#     k2 = df^2 A/f_a^3, k3 = -df^3 A/f_a^4   [f32: the terms are < ~0.1
#                               turns, so f32's 1e-7 relative is plenty]
#
# d <= B stays exact in f32, and the df64 k1f*d product keeps absolute
# error ~B*2^-48.  The mod-1 value matches the exact path to ~1e-9
# turns — far inside the ~k*2^-48 ~ 5e-6-turn precision both paths
# inherit from df64 itself.  Precision validated against the f64 host
# chirp in tests/test_dedisperse.py and tests/test_df64.py.

_ANCHOR_BLOCK = 4096
_ANCHOR_REMAINDER_TOL = 1e-6


def anchored_chirp_consts(n: int, f_min, df, f_c, dm, i0: int = 0,
                          block: int = _ANCHOR_BLOCK,
                          allow_shrink: bool = True,
                          unit_dm: bool = False):
    """Host-side f64 constants for the anchored-Taylor chirp phase, or
    None when the expansion isn't applicable: traced dm/i0 (DM-search
    trials), a band touching f = 0, or a cubic-Taylor remainder over
    ``block`` channels above tolerance.

    ``unit_dm=True``: validate the bound at the given |dm| (the max of a
    DM-search grid) but store dm-independent coefficients (dm = 1) — k
    is linear in dm, so per-trial traced dm values scale the anchor
    coefficients on device (_chirp_phase_df64_anchored_dm)."""
    try:
        f_min64 = float(f_min)
        df64_ = float(df)
        f_c64 = float(f_c)
        dm64 = float(dm)
        i0 = int(i0)
    except (TypeError, ValueError):
        return None  # traced scalar: caller keeps the exact path
    # the last block's Taylor extension may be evaluated (then sliced
    # off) up to the padded end, so bound over the padded range
    n_pad = -(-n // block) * block
    f_at_start = f_min64 + df64_ * i0
    f_at_end = f_min64 + df64_ * (i0 + n_pad)
    if not (np.isfinite(f_at_start) and np.isfinite(f_at_end)) \
            or f_at_start * f_at_end <= 0 or f_c64 == 0:
        return None
    min_f = min(abs(f_at_start), abs(f_at_end))
    A = np.float64(D) * 1e6 * dm64
    # shrink the block until the cubic-Taylor remainder fits tolerance
    # (smaller blocks = more anchors, still amortized); below 32
    # channels per anchor the scheme stops paying for itself.  Callers
    # whose anchor span is fixed by kernel geometry (the Pallas per-row
    # anchors) pass allow_shrink=False: valid at `block` or not at all.
    denom = abs(A) * abs(df64_) ** 4
    if denom > 0:
        block_max = (_ANCHOR_REMAINDER_TOL * min_f ** 5 / denom) ** 0.25
        while allow_shrink and block > 32 and block > block_max:
            block //= 2
        if block > block_max:
            return None
    if unit_dm:
        A = np.float64(D) * 1e6
    return {
        "A": ds.from_float64(A),
        "C1": ds.from_float64(A / (f_c64 * f_c64)),
        "f_c": ds.from_float64(f_c64),
        "f_min": ds.from_float64(f_min64),
        "df": ds.from_float64(df64_),
        "df2A": np.float32(df64_ * df64_ * A),
        "df3A": np.float32(df64_ ** 3 * A),
        "block": block,
    }


def _anchor_values_raw(consts, ia_hi, ia_lo):
    """Unreduced per-anchor Taylor coefficients from exact hi/lo-split
    anchor channel indices: (k0 [df64], k1 [df64], k2 [f32], k3 [f32]).
    With unit_dm consts these are the per-unit-dm coefficients g0..g3."""
    df_d = ds.df64(*consts["df"])
    f_a = ds.add(ds.df64(*consts["f_min"]),
                 ds.add(ds.mul(df_d, ds.df64(ia_hi)),
                        ds.mul(df_d, ds.df64(ia_lo))))
    u = ds.div(ds.df64(*consts["A"]), f_a)            # A / f_a
    # anchor value via the original product form u * r^2: the expanded
    # form C1*f - C2 + A/f cancels ~1e9-turn terms down to ~1e6 and
    # loses 3 digits of the fraction (measured 1.4e-5 turns); u*r^2
    # keeps every factor's error *relative*, ~k * 2^-48
    f_c_d = ds.df64(*consts["f_c"])
    r = ds.div(ds.sub(f_a, f_c_d), f_c_d)
    k = ds.mul(u, ds.mul(r, r))
    w = ds.div(u, f_a)                                # A / f_a^2
    k1 = ds.mul(df_d, ds.sub(ds.df64(*consts["C1"]), w))
    fa32 = f_a[0]
    fa2 = fa32 * fa32
    k2 = consts["df2A"] / (fa2 * fa32)
    k3 = -consts["df3A"] / (fa2 * fa2)
    return k, k1, k2, k3


def _reduce_mod1(k):
    """Reduce a df64 value mod 1 keeping the pair's precision:
    hi - trunc(hi) is exact, then renormalize (two_sum — hi may be
    integral, leaving the whole fraction in lo, so quick_two_sum's
    |a| >= |b| precondition doesn't hold)."""
    return ds.two_sum(k[0] - jnp.trunc(k[0]), k[1])


def _anchor_values(consts, ia_hi, ia_lo):
    """Mod-1-reduced anchor coefficients:
    (k0f [f32], k1f [df64 pair], k2 [f32], k3 [f32])."""
    k, k1, k2, k3 = _anchor_values_raw(consts, ia_hi, ia_lo)
    return ds.frac(k), _reduce_mod1(k1), k2, k3


def _taylor_phase(k0f, k1f, k2, k3, delta):
    """-2*pi*frac(k0f + k1f*delta + k2*delta^2 + k3*delta^3), the
    anchored per-element update (all inputs broadcast against delta,
    which must be exact in f32)."""
    p = ds.mul(k1f, ds.df64(delta))
    v_hi, v_lo = ds.add((k0f, jnp.zeros_like(k0f)), p)
    poly = (delta * delta) * (k2 + k3 * delta)
    r = (v_hi - jnp.trunc(v_hi)) + v_lo + poly
    r = r - jnp.trunc(r)
    return jnp.float32(-2.0 * np.pi) * r


def _chirp_phase_df64_anchored(n: int, consts, i0=0, dm_d=None):
    """Anchored-Taylor delta_phi [n]: one df64 anchor per `block` channels
    (vectorized over anchors), cheap Taylor update within blocks.  i0 may
    be traced (shard-local offsets) — validity was bounded for the global
    range by anchored_chirp_consts.

    ``dm_d`` (a df64 hi/lo pair, may be traced — DM-search trials): k is
    linear in dm, so the dm-independent per-anchor coefficients g0..g3
    (consts built with unit_dm=True; validity bounded at the grid's max
    |dm|) are scaled by this trial's dm on device, then reduced mod 1
    exactly as the concrete path — ~3 df64 divisions per channel *per
    trial* become one df64 multiply per anchor."""
    block = min(consts["block"], n)
    nb = -(-n // block)
    ia = jnp.arange(nb, dtype=jnp.int32) * block + jnp.int32(i0)
    ia_hi = (ia & ~0xFFF).astype(jnp.float32)
    ia_lo = (ia & 0xFFF).astype(jnp.float32)
    if dm_d is None:
        k0f, k1f, k2, k3 = _anchor_values(consts, ia_hi, ia_lo)
    else:
        g0, g1, g2, g3 = _anchor_values_raw(consts, ia_hi, ia_lo)
        k0f = ds.frac(ds.mul(dm_d, g0))
        k1f = _reduce_mod1(ds.mul(dm_d, g1))
        k2 = dm_d[0] * g2
        k3 = dm_d[0] * g3
    delta = jnp.arange(block, dtype=jnp.float32)[None, :]
    phase = _taylor_phase(k0f[:, None], (k1f[0][:, None], k1f[1][:, None]),
                          k2[:, None], k3[:, None], delta)
    return phase.reshape(-1)[:n]


def _chirp_phase_df64(n: int, f_min: float, df: float, f_c: float, dm,
                      i0: int = 0, dm_lo=None, anchor_consts=None,
                      exact: bool = False):
    """delta_phi [n] in f32 via df64 arithmetic (shared by the complex and
    split-ri chirp generators).

    ``i0`` offsets the channel index (shard-local generation on a
    sequence-sharded spectrum).  Indices are split hi/lo from *integers*:
    a float32 arange is exact only below 2^24, and a channel-index error
    of even a few samples at 2^27 channels shifts the phase by whole
    turns (k ~ 1e9 turns scales as ~k/f per MHz).

    Concrete (non-traced) dm takes the anchored-Taylor fast path (see
    above).  Traced dm — DM-search trials — takes it too when the caller
    passes ``anchor_consts`` (built once with unit_dm=True at the grid's
    max |dm|); otherwise the exact per-element evaluation runs.
    ``exact=True`` skips the anchored path entirely — the
    Config.chirp_exact escape hatch and the hardware A/B knob.
    """
    if exact:
        anchor_consts = None
    if anchor_consts is not None:
        if dm_lo is None and isinstance(dm, (int, float, np.floating)):
            # same guard as the exact path below: a concrete dm must be
            # split hi/lo — one f32's 3e-8 relative error shifts
            # k ~ 1e9 turns by ~25 turns
            dm_arr = jnp.float32(np.float32(dm))
            dm_lo_arr = jnp.float32(np.float64(dm) - np.float32(dm))
        else:
            dm_arr = jnp.asarray(dm, dtype=jnp.float32)
            dm_lo_arr = jnp.zeros_like(dm_arr) if dm_lo is None \
                else jnp.asarray(dm_lo, dtype=jnp.float32)
        return _chirp_phase_df64_anchored(
            n, anchor_consts, i0=i0, dm_d=(dm_arr, dm_lo_arr))
    if dm_lo is None and not exact:
        consts = anchored_chirp_consts(n, f_min, df, f_c, dm, i0=i0)
        if consts is not None:
            return _chirp_phase_df64_anchored(n, consts, i0=i0)
    # int32 channel indices: silently wrong at/beyond 2^31 channels.
    # i0 may be traced (shard-local offset); guard what is static here.
    if isinstance(i0, (int, np.integer)):
        if i0 + n > 2**31 - 1:
            raise ValueError(
                f"channel index i0+n = {i0 + n} overflows int32")
    elif n > 2**31 - 1:
        raise ValueError(f"n = {n} overflows int32 channel indices")
    i_int = jnp.arange(n, dtype=jnp.int32) + jnp.int32(i0)
    # hi is a multiple of 2^12 (exact in f32 up to 2^36), lo < 2^12
    i_hi = (i_int & ~0xFFF).astype(jnp.float32)
    i_lo = (i_int & 0xFFF).astype(jnp.float32)
    f_min_d = ds.df64(jnp.float32(np.float32(f_min)),
                      jnp.float32(np.float64(f_min) - np.float32(f_min)))
    df_d = ds.df64(jnp.float32(np.float32(df)),
                   jnp.float32(np.float64(df) - np.float32(df)))
    f_c_d = ds.df64(jnp.float32(np.float32(f_c)),
                    jnp.float32(np.float64(f_c) - np.float32(f_c)))
    df_i = ds.add(ds.mul(df_d, ds.df64(i_hi)), ds.mul(df_d, ds.df64(i_lo)))
    f = ds.add(f_min_d, df_i)

    # dm must be split hi/lo too: truncating e.g. -478.80 to one f32
    # (2.5e-8 relative) shifts k ~ 1e9 turns by ~25 turns
    if isinstance(dm, (int, float, np.floating)):
        dm_d = ds.df64(jnp.float32(np.float32(dm)),
                       jnp.float32(np.float64(dm) - np.float32(dm)))
    else:
        dm_arr = jnp.asarray(dm, dtype=jnp.float32)
        dm_lo_arr = jnp.zeros_like(dm_arr) if dm_lo is None \
            else jnp.asarray(dm_lo, dtype=jnp.float32)
        dm_d = ds.df64(dm_arr, dm_lo_arr)
    D_ = np.float64(D * 1e6)
    D_d = ds.df64(jnp.float32(np.float32(D_)),
                  jnp.float32(D_ - np.float32(D_)))

    delta_f = ds.sub(f, f_c_d)
    ratio = ds.div(delta_f, f_c_d)
    k = ds.mul(ds.div(ds.mul(D_d, dm_d), f), ds.mul(ratio, ratio))
    k_frac = ds.frac(k)
    return jnp.float32(-2.0 * np.pi) * k_frac


def spectrum_frequencies(cfg, n: int):
    """(f_min, f_c, df) for the n-channel spectrum of one segment, matching
    dedisperse_pipe (ref: pipeline/dedisperse_pipe.hpp:31-47)."""
    f_min = cfg.baseband_freq_low
    f_c = f_min + cfg.baseband_bandwidth
    df = cfg.baseband_bandwidth / n
    return f_min, f_c, df


def dedisperse(spectrum: jnp.ndarray, chirp: jnp.ndarray) -> jnp.ndarray:
    """Apply the chirp: one complex multiply per channel
    (ref: coherent_dedispersion.hpp:223-248)."""
    return spectrum * chirp
