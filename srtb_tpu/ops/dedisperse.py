"""Coherent dedispersion: frequency-domain chirp multiply.

Physics follows the reference exactly (ref: coherent_dedispersion.hpp):
``D = 4.148808e3`` MHz^2 pc^-1 cm^3 s (line 67), per-channel phase turns

    k = D * 1e6 * dm / f * ((f - f_c) / f_c)^2        (phase_factor_v3, line 141)
    factor = exp(-2*pi*i * frac(k))                   (lines 142-148)

with ``frac`` extracted before the trig because k reaches ~1e9 at high DM
(line 49), far beyond f32 mantissa range.

TPU-native design: the chirp depends only on (n, f_min, df, f_c, dm) — it is
**constant across segments** — so the primary path precomputes it once on
host in f64 and keeps it resident in HBM (one complex64 array the size of
the spectrum).  For DM-search grids where a per-trial host precompute would
bottleneck, ``chirp_factor_df64`` computes the same thing on device with
two-float arithmetic (the reference's dsmath df64 trick, proven there on
fp64-less GPUs); it is pure elementwise VPU work that XLA fuses.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from srtb_tpu.ops import df64 as ds

# dispersion constant, MHz^2 pc^-1 cm^3 s (ref: coherent_dedispersion.hpp:67)
D = 4.148808e3


def dispersion_delay_time(f, f_c, dm):
    """Delay relative to f_c, seconds; positive for f > f_c
    (ref: coherent_dedispersion.hpp:75-78)."""
    return -D * dm * (1.0 / (f * f) - 1.0 / (f_c * f_c))


def max_delay_time(freq_low: float, bandwidth: float, dm: float) -> float:
    """Max dispersion delay across the band
    (ref: coherent_dedispersion.hpp:81-85)."""
    return dispersion_delay_time(freq_low + bandwidth, freq_low, dm)


def nsamps_reserved(cfg) -> int:
    """Real samples reserved (overlapped) between consecutive segments to
    mask dedispersion edge corruption (ref: coherent_dedispersion.hpp:103-128).

    The non-reserved portion is rounded down to a multiple of
    2 * spectrum_channel_count so the waterfall FFT tiles exactly.
    """
    if not cfg.baseband_reserve_sample:
        return 0
    minimal = 2 * round(
        max_delay_time(cfg.baseband_freq_low, cfg.baseband_bandwidth, cfg.dm)
        * cfg.baseband_sample_rate)
    per_bin = cfg.spectrum_channel_count * 2
    n = cfg.baseband_input_count
    refft_total = (n - minimal) // per_bin * per_bin
    if refft_total > 0:
        return n - refft_total
    return 0


# ----------------------------------------------------------------
# chirp generation
# ----------------------------------------------------------------

def chirp_factor_host(n: int, f_min: float, df: float, f_c: float,
                      dm: float) -> np.ndarray:
    """Chirp factors for n channels at f = f_min + df*i, computed on host in
    float64 (numpy), returned as complex64.

    Bit-comparable to phase_factor_v3 with phase_real = double
    (ref: coherent_dedispersion.hpp:134-150).
    """
    i = np.arange(n, dtype=np.float64)
    f = f_min + df * i
    delta_f = f - f_c
    k = (D * 1e6) * dm / f * ((delta_f / f_c) * (delta_f / f_c))
    k_frac = np.modf(k)[0]
    delta_phi = -2.0 * np.pi * k_frac
    return (np.cos(delta_phi) + 1j * np.sin(delta_phi)).astype(np.complex64)


def chirp_factor_df64(n: int, f_min: float, df: float, f_c: float, dm,
                      dtype=jnp.complex64, i0: int = 0,
                      dm_lo=None) -> jnp.ndarray:
    """Same chirp computed on device with two-float (df64) arithmetic —
    jittable, dm may be a traced scalar (DM-search grids).  ``i0``
    generates the block of channels starting at that global index.

    Mirrors phase_factor_v3 with phase_real = dsmath::df64
    (ref: coherent_dedispersion.hpp:31-53,134-150).
    """
    delta_phi = _chirp_phase_df64(n, f_min, df, f_c, dm, i0=i0,
                                  dm_lo=dm_lo)
    return (jnp.cos(delta_phi) + 1j * jnp.sin(delta_phi)).astype(dtype)


def chirp_factor_host_ri(n: int, f_min: float, df: float, f_c: float,
                         dm: float) -> np.ndarray:
    """Chirp as stacked (real, imag) float32 [2, n].

    TPU-native boundary representation: some TPU runtimes don't transfer
    complex buffers across the host<->device boundary, and splitting
    re/im is the natural layout for the VPU anyway; complex exists only
    inside jit.
    """
    c = chirp_factor_host(n, f_min, df, f_c, dm)
    return np.stack([c.real, c.imag]).astype(np.float32)


def chirp_factor_df64_ri(n: int, f_min: float, df: float, f_c: float,
                         dm, i0: int = 0, dm_lo=None) -> jnp.ndarray:
    """df64 on-device chirp as stacked (cos, sin) float32 [2, n] — jit-safe
    output dtype on complex-less runtimes."""
    phase = _chirp_phase_df64(n, f_min, df, f_c, dm, i0=i0, dm_lo=dm_lo)
    return jnp.stack([jnp.cos(phase), jnp.sin(phase)])


def _chirp_phase_df64(n: int, f_min: float, df: float, f_c: float, dm,
                      i0: int = 0, dm_lo=None):
    """delta_phi [n] in f32 via df64 arithmetic (shared by the complex and
    split-ri chirp generators).

    ``i0`` offsets the channel index (shard-local generation on a
    sequence-sharded spectrum).  Indices are split hi/lo from *integers*:
    a float32 arange is exact only below 2^24, and a channel-index error
    of even a few samples at 2^27 channels shifts the phase by whole
    turns (k ~ 1e9 turns scales as ~k/f per MHz).
    """
    # int32 channel indices: silently wrong at/beyond 2^31 channels.
    # i0 may be traced (shard-local offset); guard what is static here.
    if isinstance(i0, (int, np.integer)):
        if i0 + n > 2**31 - 1:
            raise ValueError(
                f"channel index i0+n = {i0 + n} overflows int32")
    elif n > 2**31 - 1:
        raise ValueError(f"n = {n} overflows int32 channel indices")
    i_int = jnp.arange(n, dtype=jnp.int32) + jnp.int32(i0)
    # hi is a multiple of 2^12 (exact in f32 up to 2^36), lo < 2^12
    i_hi = (i_int & ~0xFFF).astype(jnp.float32)
    i_lo = (i_int & 0xFFF).astype(jnp.float32)
    f_min_d = ds.df64(jnp.float32(np.float32(f_min)),
                      jnp.float32(np.float64(f_min) - np.float32(f_min)))
    df_d = ds.df64(jnp.float32(np.float32(df)),
                   jnp.float32(np.float64(df) - np.float32(df)))
    f_c_d = ds.df64(jnp.float32(np.float32(f_c)),
                    jnp.float32(np.float64(f_c) - np.float32(f_c)))
    df_i = ds.add(ds.mul(df_d, ds.df64(i_hi)), ds.mul(df_d, ds.df64(i_lo)))
    f = ds.add(f_min_d, df_i)

    # dm must be split hi/lo too: truncating e.g. -478.80 to one f32
    # (2.5e-8 relative) shifts k ~ 1e9 turns by ~25 turns
    if isinstance(dm, (int, float, np.floating)):
        dm_d = ds.df64(jnp.float32(np.float32(dm)),
                       jnp.float32(np.float64(dm) - np.float32(dm)))
    else:
        dm_arr = jnp.asarray(dm, dtype=jnp.float32)
        dm_lo_arr = jnp.zeros_like(dm_arr) if dm_lo is None \
            else jnp.asarray(dm_lo, dtype=jnp.float32)
        dm_d = ds.df64(dm_arr, dm_lo_arr)
    D_ = np.float64(D * 1e6)
    D_d = ds.df64(jnp.float32(np.float32(D_)),
                  jnp.float32(D_ - np.float32(D_)))

    delta_f = ds.sub(f, f_c_d)
    ratio = ds.div(delta_f, f_c_d)
    k = ds.mul(ds.div(ds.mul(D_d, dm_d), f), ds.mul(ratio, ratio))
    k_frac = ds.frac(k)
    return jnp.float32(-2.0 * np.pi) * k_frac


def spectrum_frequencies(cfg, n: int):
    """(f_min, f_c, df) for the n-channel spectrum of one segment, matching
    dedisperse_pipe (ref: pipeline/dedisperse_pipe.hpp:31-47)."""
    f_min = cfg.baseband_freq_low
    f_c = f_min + cfg.baseband_bandwidth
    df = cfg.baseband_bandwidth / n
    return f_min, f_c, df


def dedisperse(spectrum: jnp.ndarray, chirp: jnp.ndarray) -> jnp.ndarray:
    """Apply the chirp: one complex multiply per channel
    (ref: coherent_dedispersion.hpp:223-248)."""
    return spectrum * chirp
