"""Unpack raw baseband bytes to float32 samples.

TPU-native re-design of the reference unpack kernels (ref: unpack.hpp):
instead of one work-item per input byte doing scalar bit tricks, the whole
segment is unpacked with vectorized shift/mask lanes — a ``[bytes, k]``
broadcast that XLA lowers to pure VPU code and fuses with the optional
window multiply (the reference fuses its FFT window the same way,
unpack.hpp:32-33).

Bit-width semantics (ref: config.hpp:92-97 + unpack_pipe.hpp:46-136):
positive = unsigned, negative = signed; 1/2/4-bit fields are MSB-first
within each byte (ref: unpack.hpp:43-140); 32/64 are floating point.

Packet-format de-interleave variants:
- ``unpack_interleaved_2pol``   "1212"  (ref: unpack.hpp:214-244)
- ``unpack_naocpsr_snap1``      "1122"  (ref: unpack.hpp:253-283)
- ``unpack_gznupsr_a1``         4-way word-interleave, XOR 0x80
  unsigned->signed trick (ref: unpack.hpp:291-328)
- ``unpack_gznupsr_a1_v2_1``    2-way word-interleave (ref: unpack.hpp:336-369)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

SUPPORTED_BITS = (1, 2, 4, 8, -8, 16, -16, 32, 64)


def _unpack_subbyte(data: jnp.ndarray, nbits: int) -> jnp.ndarray:
    """Unpack 1/2/4-bit unsigned fields, MSB-first, to float32.

    in[x] -> out[(8/nbits)*x ...] exactly as unpack.hpp:43-75.
    """
    count = 8 // nbits
    mask = (1 << nbits) - 1
    # shifts are MSB-first: (count-1-i)*nbits
    shifts = jnp.arange(count - 1, -1, -1, dtype=jnp.uint8) * nbits
    fields = (data[:, None] >> shifts[None, :]) & mask
    return fields.reshape(-1).astype(jnp.float32)


def unpack_subbyte_planes(data: jnp.ndarray, nbits: int) -> jnp.ndarray:
    """Unpack 1/2/4-bit fields to **blocked field planes** ``[count, M]``
    (count = 8/nbits fields per byte, M = byte count): plane k holds field
    k (MSB-first) of every byte, i.e. sample ``count*b + k`` lands at
    ``planes[k, b]``.

    This is the TPU-native form of the unpack: every array keeps the byte
    axis minor and lane-dense.  The sample-order form (`_unpack_subbyte`)
    interleaves count fields per byte, which forces a ``[bytes, count]``
    minor-dim intermediate — on TPU that pads count -> 128 lanes, a 32x
    HBM expansion whenever XLA must materialize it (observed: a 16 GB
    copy at n = 2^27).  Blocked planes never interleave; the consumer
    (ops/fft.rfft_subbyte) folds the blocked->natural permutation into
    the FFT's decimation instead.
    """
    count = 8 // nbits
    mask = (1 << nbits) - 1
    shifts = jnp.arange(count - 1, -1, -1, dtype=jnp.uint8) * nbits
    fields = (data[..., None, :] >> shifts[:, None]) & mask
    return fields.astype(jnp.float32)


def unpack(data: jnp.ndarray, nbits: int,
           window: jnp.ndarray | None = None) -> jnp.ndarray:
    """Unpack a uint8 byte stream into float32 samples.

    ``window``, if given, is multiplied in (kernel fusion of the FFT window
    into the unpack stage, ref: unpack_pipe.hpp:72-127).
    """
    if nbits not in SUPPORTED_BITS:
        raise ValueError(f"unsupported baseband_input_bits {nbits}")
    data = data.astype(jnp.uint8) if data.dtype != jnp.uint8 else data
    if nbits in (1, 2, 4):
        out = _unpack_subbyte(data, nbits)
    elif nbits == 8:
        out = data.astype(jnp.float32)
    elif nbits == -8:
        out = data.view(jnp.int8).astype(jnp.float32)
    elif nbits == 16:
        out = data.view(jnp.uint16).astype(jnp.float32)
    elif nbits == -16:
        out = data.view(jnp.int16).astype(jnp.float32)
    elif nbits == 32:
        out = data.view(jnp.float32)
    elif nbits == 64:
        # float64 input decoded to f32 from the raw bit pattern — without
        # x64, jnp's .view(float64) silently truncates to a float32 view
        # (doubling the sample count and corrupting every value), so the
        # double is reassembled from its little-endian uint32 halves:
        # sign/exponent/mantissa-high in the high word, mantissa-low in
        # the low word, combined to f32 precision.
        u = data.view(jnp.uint32)
        lo = u[..., 0::2].astype(jnp.float32)
        hi = u[..., 1::2]
        sign = jnp.where((hi >> 31) != 0, jnp.float32(-1.0),
                         jnp.float32(1.0))
        exp = ((hi >> 20) & 0x7FF).astype(jnp.int32)
        frac = ((hi & 0xFFFFF).astype(jnp.float32) * jnp.float32(2.0 ** -20)
                + lo * jnp.float32(2.0 ** -52))
        # exact power of two via the f32 exponent field (jnp.exp2 lowers
        # to exp(x*ln2) and is ~1e-7-relative WRONG for large exponents);
        # clamping the biased exponent to [0, 255] makes out-of-f32-range
        # doubles flush to 0 / +-inf, and f64 subnormals (exp == 0,
        # magnitude < 2^-1021) flush to 0 — all correct truncations
        pw = jax.lax.bitcast_convert_type(
            (jnp.clip(exp - 1023 + 127, 0, 255) << 23).astype(jnp.int32),
            jnp.float32)
        mag = jnp.where(exp == 0, jnp.float32(0.0), (1.0 + frac) * pw)
        out = sign * mag
        out = jnp.where((exp == 0x7FF) & (frac > 0), jnp.float32(jnp.nan),
                        out)
    if window is not None:
        out = out * window
    return out


def samples_per_byte(nbits: int) -> float:
    return 8.0 / abs(nbits)


# ----------------------------------------------------------------
# de-interleave variants (multi-stream packet formats)
# ----------------------------------------------------------------

def unpack_interleaved_2pol(data: jnp.ndarray, nbits: int,
                            window: jnp.ndarray | None = None):
    """"1212" byte-interleaved 2 polarizations -> 2 streams
    (ref: unpack.hpp:214-244; dispatch unpack_pipe.hpp:146-260).

    Input element type is given by nbits (8/-8 supported, as snap-style
    boards emit 8-bit); returns (out1, out2) float32.
    """
    x = data.reshape(-1, 2)
    out1 = unpack(x[:, 0].reshape(-1), nbits, window)
    out2 = unpack(x[:, 1].reshape(-1), nbits, window)
    return out1, out2


def unpack_naocpsr_snap1(data: jnp.ndarray, nbits: int = -8,
                         window: jnp.ndarray | None = None):
    """"1122" pair-interleaved 2 polarizations -> 2 streams
    (ref: unpack.hpp:253-283).  Samples are int8."""
    x = data.reshape(-1, 4)
    out1 = unpack(x[:, 0:2].reshape(-1), nbits, window)
    out2 = unpack(x[:, 2:4].reshape(-1), nbits, window)
    return out1, out2


def unpack_gznupsr_a1(data: jnp.ndarray,
                      window: jnp.ndarray | None = None):
    """4-way word-interleaved (4 samples per stream per 16-byte word group),
    uint8 with XOR 0x80 -> int8 conversion (ref: unpack.hpp:291-328)."""
    x = data.reshape(-1, 4, 4)  # [word, stream, sample-in-word]
    x = jnp.bitwise_xor(x, jnp.uint8(0x80)).view(jnp.int8)
    outs = []
    for i in range(4):
        out = x[:, i, :].reshape(-1).astype(jnp.float32)
        if window is not None:
            out = out * window
        outs.append(out)
    return tuple(outs)


def unpack_gznupsr_a1_v2_1(data: jnp.ndarray,
                           window: jnp.ndarray | None = None):
    """2-way word-interleaved variant, int8 without the XOR trick
    (ref: unpack.hpp:336-369)."""
    x = data.reshape(-1, 2, 4).view(jnp.int8)
    outs = []
    for i in range(2):
        out = x[:, i, :].reshape(-1).astype(jnp.float32)
        if window is not None:
            out = out * window
        outs.append(out)
    return tuple(outs)


# ----------------------------------------------------------------
# numpy golden models (used by tests; kept next to the op on purpose)
# ----------------------------------------------------------------

def unpack_oracle(data: np.ndarray, nbits: int) -> np.ndarray:
    """Reference semantics in plain numpy (bit-for-bit vs unpack.hpp)."""
    data = np.asarray(data, dtype=np.uint8)
    if nbits in (1, 2, 4):
        count = 8 // nbits
        mask = (1 << nbits) - 1
        out = np.empty(data.size * count, dtype=np.float32)
        for i in range(count):
            shift = (count - 1 - i) * nbits
            out[i::count] = ((data >> shift) & mask).astype(np.float32)
        return out
    if nbits == 8:
        return data.astype(np.float32)
    if nbits == -8:
        return data.view(np.int8).astype(np.float32)
    if nbits == 16:
        return data.view(np.uint16).astype(np.float32)
    if nbits == -16:
        return data.view(np.int16).astype(np.float32)
    if nbits == 32:
        return data.view(np.float32)
    if nbits == 64:
        return data.view(np.float64).astype(np.float32)
    raise ValueError(nbits)
