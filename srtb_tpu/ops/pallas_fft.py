"""Pallas row FFT: batched C2C transforms computed entirely in VMEM.

XLA's TPU FFT moves each point through HBM several times per transform
(measured: 14.6 ms for the [2048, 2^16] waterfall backward C2C — ~6x the
one-read-one-write floor, PERF.md).  For rows that fit VMEM, the whole
transform instead runs inside one Pallas grid step: DMA a block of rows
in, run a two-level Cooley-Tukey split L = La*Lb where *both* levels are
DFT-matrix matmuls on the MXU, DMA the result out.  One HBM read + one
write per point.

Why two explicit matmul levels instead of the radix-128 recursion of
ops/mxu_fft: inside VMEM every array's minor dimension pads to the
128-lane tile, so the recursion's deep [..., 128, 4]-shaped base cases
would blow the block up 32x and OOM the ~16 MB VMEM.  The two-level
split keeps every intermediate's minor dimension at La, Lb or rows*Lb
(>= 64 lanes throughout):

    x[rows, La(j1), Lb(j2)]
      -> transpose [La, rows*Lb]            (VMEM relayout)
      -> Wa^T @ x          : A[k1, j2]      (MXU, contraction La)
      -> * tw[k1, j2]                       (VPU; table passed in, no
                                             in-kernel transcendentals)
      -> @ Wb              : B[k1, k2]      (MXU, contraction Lb)
      -> transpose/reshape [rows, Lb*La]    (natural order: k = k2*La+k1)

It spends La+Lb MACs per point where a true FFT spends 5*log2(L) flops —
deliberately: MXU FLOPs are the cheap resource, HBM passes the scarce
one (scaling-book roofline).  DFT matrices and twiddles are computed in
float64 on host / via the exact-phase generator and passed as kernel
inputs (Pallas forbids captured constants).

This is the TPU answer to the reference's per-vendor FFT wrappers for
the *batched* transforms (ref: fft/fft.hpp:54-160, fft_pipe.hpp:295-311
watfft batch): srtb's waterfall FFT and the four-step legs of the big
segment FFT are all batched rows of length <= 2^16.

Complex values cross the kernel boundary as separate re/im f32 planes
(Mosaic has no complex dtype).  Correctness is held to the same oracles
as every other FFT backend (tests/test_pallas_fft.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from srtb_tpu.ops import fft as F
from srtb_tpu.utils.logging import log

# v5e VMEM is ~16 MB/core.  Live per grid step: in + out + two stage
# intermediates (all [rows, L] f32 pairs) + matrices + twiddle.
_VMEM_BLOCK_ELEMS = 1 << 18  # 256K f32 = 1 MB per plane

# Matmul precision for the DFT contractions: 3-pass bf16 ("highest"
# would be 6) — for contraction lengths <= 512 the bf16x3 error is
# ~1e-6 relative, measured against the float64 oracle in tests.
_PRECISION = jax.lax.Precision.HIGH


def _split_la_lb(length: int):
    """Factor L = La*Lb with La pinned to 128: the final natural-order
    assembly transposes to a [rows, Lb, La] view, so La is the one minor
    dimension that must stay a full 128-lane tile.  Lb = L/128 lands in
    [32, 512] over the supported range ([Lb, Lb] tail matrix <= 1 MB per
    plane; Lb < 128 pads its stage intermediates up to 4x in VMEM, paid
    only on the small end)."""
    if length & (length - 1) or not (1 << 12) <= length <= (1 << 16):
        return None
    return 128, length // 128


def supported(length: int, batch: int) -> bool:
    """Whether the Pallas row FFT handles [batch, length]."""
    return _split_la_lb(length) is not None and batch >= 1


def vmem_fft_rows(xr, xi, war, wai, wbr, wbi, twr, twi, *, la, lb, rows):
    """The in-VMEM two-level row FFT on value arrays: [rows, L] f32
    (re, im) -> length-L C2C along each row in natural order, L = la*lb.
    Pure function of VMEM-resident values — shared by the kernels here
    and by the fused two-pass four-step in ops/pallas_fft2."""
    def mm(a, b):
        return jax.lax.dot(a, b, precision=_PRECISION,
                           preferred_element_type=jnp.float32)

    # [rows, L] -> [La, rows*Lb]  (j1 major for the level-1 contraction)
    def to_stage1(x):
        x = x.reshape(rows, la, lb)
        return jnp.transpose(x, (1, 0, 2)).reshape(la, rows * lb)

    xr, xi = to_stage1(xr), to_stage1(xi)
    # A[k1, (r, j2)] = sum_j1 Wa[j1, k1] x[j1, (r, j2)]
    ar = mm(war.T, xr) - mm(wai.T, xi)
    ai = mm(war.T, xi) + mm(wai.T, xr)
    # twiddle w[k1, j2], broadcast over rows
    a3r = ar.reshape(la, rows, lb)
    a3i = ai.reshape(la, rows, lb)
    twr = twr.reshape(la, 1, lb)
    twi = twi.reshape(la, 1, lb)
    br = a3r * twr - a3i * twi
    bi = a3r * twi + a3i * twr
    # B[(k1, r), k2] = sum_j2 A[(k1, r), j2] Wb[j2, k2]
    b2r = br.reshape(la * rows, lb)
    b2i = bi.reshape(la * rows, lb)
    cr = mm(b2r, wbr) - mm(b2i, wbi)
    ci = mm(b2r, wbi) + mm(b2i, wbr)
    # natural order: X[k2*La + k1] -> [rows, Lb(k2), La(k1)] -> [rows, L]
    c3r = cr.reshape(la, rows, lb)
    c3i = ci.reshape(la, rows, lb)
    yr = jnp.transpose(c3r, (1, 2, 0)).reshape(rows, la * lb)
    yi = jnp.transpose(c3i, (1, 2, 0)).reshape(rows, la * lb)
    return yr, yi


def dot_mid(a, b, dim):
    """dot_general contracting ``a``'s axis ``dim`` with ``b``'s axis 0
    under the module's DFT precision discipline — the single home of
    that convention for the dense spellings here and in pallas_fft2."""
    return jax.lax.dot_general(
        a, b, (((dim,), (0,)), ((), ())),
        precision=_PRECISION, preferred_element_type=jnp.float32)


def vmem_fft_rows_dense(xr, xi, war, wai, wbr, wbi, twr, twi, *,
                        la, lb, rows):
    """dot_general spelling of :func:`vmem_fft_rows` — same contract,
    different layout discipline: both DFT contractions run against the
    *middle* axis of dense ``[rows, la, lb]`` views, so no intermediate
    ever carries a sub-128 minor dim (the classic spelling's
    ``[la, rows, lb]`` stages lane-pad lb -> 128, up to 4x VMEM), and
    the only relayout is one final dense 3D transpose.  Kept alongside
    the classic form so hardware can A/B the two lowerings
    (SRTB_PALLAS2_ROWS in ops/pallas_fft2)."""
    dg = dot_mid
    x3r = xr.reshape(rows, la, lb)
    x3i = xi.reshape(rows, la, lb)
    # stage 1, contract j1: A[r, j2, k1] = sum_j1 x[r, j1, j2] Wa[j1, k1]
    ar = dg(x3r, war, 1) - dg(x3i, wai, 1)      # [rows, lb, la]
    ai = dg(x3r, wai, 1) + dg(x3i, war, 1)
    # twiddle w[k1, j2] at [1, j2, k1] orientation, broadcast over rows
    twr2 = twr.T.reshape(1, lb, la)
    twi2 = twi.T.reshape(1, lb, la)
    br = ar * twr2 - ai * twi2
    bi = ar * twi2 + ai * twr2
    # stage 2, contract j2: C[r, k1, k2] = sum_j2 B[r, j2, k1] Wb[j2, k2]
    cr = dg(br, wbr, 1) - dg(bi, wbi, 1)        # [rows, la, lb]
    ci = dg(br, wbi, 1) + dg(bi, wbr, 1)
    # natural order k = k2*la + k1 -> [rows, k2, k1] -> [rows, L]
    yr = jnp.transpose(cr, (0, 2, 1)).reshape(rows, la * lb)
    yi = jnp.transpose(ci, (0, 2, 1)).reshape(rows, la * lb)
    return yr, yi


def active_rows_helper():
    """Helper selection for the row-FFT kernels in this module:
    the proven classic spelling by default; SRTB_PALLAS_ROWS=dense
    switches to the dense dot_general spelling (hardware A/B — same
    contract, pinned to the same oracles)."""
    import os

    if os.environ.get("SRTB_PALLAS_ROWS", "classic") == "dense":
        return vmem_fft_rows_dense
    return vmem_fft_rows


def _fft_rows_kernel(re_ref, im_ref, war_ref, wai_ref, wbr_ref, wbi_ref,
                     twr_ref, twi_ref, out_re_ref, out_im_ref, *,
                     la, lb, rows, rows_helper=None):
    helper = rows_helper or vmem_fft_rows
    out_re_ref[:], out_im_ref[:] = helper(
        re_ref[:], im_ref[:], war_ref[:], wai_ref[:], wbr_ref[:],
        wbi_ref[:], twr_ref[:], twi_ref[:], la=la, lb=lb, rows=rows)


def _fft_rows_stats_kernel(re_ref, im_ref, war_ref, wai_ref, wbr_ref,
                           wbi_ref, twr_ref, twi_ref, dwr_ref,
                           out_re_ref, out_im_ref, s2_ref, s4_ref, *,
                           la, lb, rows, apply_dewindow,
                           rows_helper=None):
    """fft_rows kernel + fused epilogue: optional de-window multiply and
    per-row power moments (sum |x|^2, sum |x|^4 as 128-lane partials) —
    the spectral-kurtosis statistics collected while the waterfall rows
    are still in VMEM, so the SK stage never re-reads the waterfall from
    HBM (ref: spectrum/rfi_mitigation.hpp:290-341 computes them in a
    separate pass)."""
    _fft_rows_kernel(re_ref, im_ref, war_ref, wai_ref, wbr_ref, wbi_ref,
                     twr_ref, twi_ref, out_re_ref, out_im_ref,
                     la=la, lb=lb, rows=rows, rows_helper=rows_helper)
    yr = out_re_ref[:]
    yi = out_im_ref[:]
    if apply_dewindow:
        dw = dwr_ref[:]        # [1, L] reciprocal de-window coefficients
        yr = yr * dw
        yi = yi * dw
        out_re_ref[:] = yr
        out_im_ref[:] = yi
    p = yr * yr + yi * yi
    p3 = p.reshape(rows, (la * lb) // 128, 128)
    s2_ref[:] = jnp.sum(p3, axis=1)
    s4_ref[:] = jnp.sum(p3 * p3, axis=1)


def _vmem_mb() -> int | None:
    """Single parse + validation of SRTB_PALLAS_VMEM_MB (None = the
    proven default plan).  Both readers — the block sizing and the
    Mosaic vmem limit — branch on this one value, so a degenerate
    setting cannot make the two halves of the plan disagree."""
    import os

    env = os.environ.get("SRTB_PALLAS_VMEM_MB")
    if not env:
        return None
    try:
        mb = int(env)
    except ValueError:
        mb = 0
    if mb <= 0:
        raise ValueError(
            f"SRTB_PALLAS_VMEM_MB={env!r} must be a positive integer "
            "(MiB of VMEM the row-FFT plan may assume)")
    return mb


def _rows_budget_padded(length: int, budget_bytes: int,
                        dense: bool) -> int:
    """Largest rows whose PADDED footprint fits the budget, using the
    ops/pallas_fft2 accounting discipline: 2x-pipelined in/out block
    refs at rows*length f32 each, plus the helper's live stages — the
    classic spelling's [la, rows, lb] stages lane-pad lb -> 128 (up to
    4x on the small-length end), which a flat per-plane divisor would
    undercount exactly where it hurts."""
    la, lb = _split_la_lb(length)
    per_row_refs = 2 * 4 * length * 4
    if dense:
        per_row_live = 6 * length * 4 + 2 * la * max(lb, 128) * 4
    else:
        per_row_live = 6 * la * max(lb, 128) * 4
    consts = 4 * (2 * la * la + 2 * lb * max(lb, 128)
                  + 2 * la * max(lb, 128))
    per_row = per_row_refs + per_row_live
    return max(1, (budget_bytes - consts) // per_row)


def _row_block(length: int, batch: int) -> int:
    mb = _vmem_mb()
    if mb is None:
        elems = _VMEM_BLOCK_ELEMS
    else:
        dense = active_rows_helper() is vmem_fft_rows_dense
        rows = _rows_budget_padded(length, mb << 20, dense)
        elems = rows * length
    return _row_block_for(length, batch, elems)


def _call_kwargs(interpret: bool) -> dict:
    """Extra pallas_call kwargs: when SRTB_PALLAS_VMEM_MB enlarges the
    plan, Mosaic's default scoped-vmem limit must be raised to match;
    the proven default plan passes no params at all (bit-identical to
    the measured round-2 path)."""
    mb = None if interpret else _vmem_mb()
    if mb is None:
        return {}
    from jax.experimental.pallas import tpu as pltpu

    return {"compiler_params": pltpu.CompilerParams(
        vmem_limit_bytes=mb << 20)}


@functools.lru_cache(maxsize=None)
def _row_block_for(length: int, batch: int, elems: int) -> int:
    target = max(1, elems // length)
    rows = target
    while batch % rows:
        rows -= 1
    if rows == 1 and target > 1 and batch > 1:
        # a batch with no small factors (prime/odd channel counts) forces
        # one grid step per row — correct but loses the kernel's batching;
        # warn once per shape (lru_cache memoizes the search *and* the
        # warning) so pathological configs don't silently crawl
        log.warning(
            f"[pallas_fft] batch {batch} has no divisor <= {target}: "
            "row-FFT runs one row per grid step; prefer power-of-two "
            "channel counts (or fft_strategy=monolithic) for this shape")
    return rows


@functools.lru_cache(maxsize=None)
def _dft_matrix_np(r: int, inverse: bool):
    j = np.arange(r, dtype=np.float64)[:, None]
    k = np.arange(r, dtype=np.float64)[None, :]
    w = np.exp((2.0 if inverse else -2.0) * 1j * np.pi * j * k / r)
    return (np.ascontiguousarray(w.real.astype(np.float32)),
            np.ascontiguousarray(w.imag.astype(np.float32)))


def leg_consts(length: int, inverse: bool):
    """(la, lb, const arrays) for a two-level in-VMEM row FFT of this
    length — the DFT matrices and inner twiddle every kernel using
    :func:`vmem_fft_rows` must pass in.  Single home (with
    :func:`leg_const_specs`) so _Launch and ops/pallas_fft2 can never
    drift apart on split bounds, precision, or twiddle discipline."""
    split = _split_la_lb(length)
    if split is None:
        raise ValueError(f"row-FFT length {length} unsupported")
    la, lb = split
    war, wai = _dft_matrix_np(la, inverse)
    wbr, wbi = _dft_matrix_np(lb, inverse)
    # tw[k1, j2] = exp(+-2*pi*i*k1*j2/L): exact integer residues
    # through the hi/lo phase split (ops.fft._twiddle discipline)
    tw = F._twiddle(la, lb, inverse)
    return la, lb, (jnp.asarray(war), jnp.asarray(wai),
                    jnp.asarray(wbr), jnp.asarray(wbi),
                    jnp.real(tw), jnp.imag(tw))


def leg_const_specs(la: int, lb: int):
    """BlockSpecs matching :func:`leg_consts`'s arrays, in order."""
    return [_Launch.const_spec(s) for s in
            [(la, la), (la, la), (lb, lb), (lb, lb), (la, lb), (la, lb)]]


class _Launch:
    """Shared launch recipe for the row-FFT kernels: shape checks, the
    La/Lb split, VMEM block sizing, and the DFT/twiddle constants — one
    home, so the plain and stats variants can never drift apart."""

    def __init__(self, re, im, inverse):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        self.shape = re.shape
        self.length = self.shape[-1]
        self.batch = (int(np.prod(self.shape[:-1]))
                      if len(self.shape) > 1 else 1)
        if not supported(self.length, self.batch):
            raise ValueError(f"unsupported row FFT shape {self.shape}")
        self.la, self.lb = _split_la_lb(self.length)
        self.re2 = re.reshape(self.batch, self.length)
        self.im2 = im.reshape(self.batch, self.length)
        self.rows = _row_block(self.length, self.batch)
        self.grid = (self.batch // self.rows,)
        self.block = pl.BlockSpec((self.rows, self.length),
                                  lambda i: (i, 0),
                                  memory_space=pltpu.VMEM)
        _, _, self.consts = leg_consts(self.length, inverse)
        self.const_specs = leg_const_specs(self.la, self.lb)

    @staticmethod
    def const_spec(shp):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        return pl.BlockSpec(shp, lambda i: (0, 0),
                            memory_space=pltpu.VMEM)

    def out_shape(self):
        return jax.ShapeDtypeStruct((self.batch, self.length),
                                    jnp.float32)


def fft_rows_ri(re: jnp.ndarray, im: jnp.ndarray, inverse: bool = False,
                interpret: bool = False):
    """C2C FFT along the last axis of split re/im f32 [..., L] arrays
    (leading dims batch), one grid step per VMEM-sized row block.
    Unnormalized both directions (same conventions as ops.fft
    c2c_forward / c2c_backward)."""
    from jax.experimental import pallas as pl

    lc = _Launch(re, im, inverse)
    kernel = functools.partial(_fft_rows_kernel, la=lc.la, lb=lc.lb,
                               rows=lc.rows,
                               rows_helper=active_rows_helper())
    out_re, out_im = pl.pallas_call(
        kernel,
        grid=lc.grid,
        in_specs=[lc.block, lc.block] + lc.const_specs,
        out_specs=[lc.block, lc.block],
        out_shape=[lc.out_shape()] * 2,
        interpret=interpret,
        **_call_kwargs(interpret),
    )(lc.re2, lc.im2, *lc.consts)
    return out_re.reshape(lc.shape), out_im.reshape(lc.shape)


def fft_rows(x: jnp.ndarray, inverse: bool = False,
             interpret: bool = False) -> jnp.ndarray:
    """Complex convenience wrapper over :func:`fft_rows_ri`."""
    yr, yi = fft_rows_ri(jnp.real(x), jnp.imag(x), inverse, interpret)
    return jax.lax.complex(yr, yi)


def fft_rows_stats_ri(re: jnp.ndarray, im: jnp.ndarray,
                      inverse: bool = True,
                      dewindow: jnp.ndarray | None = None,
                      interpret: bool = False):
    """Waterfall form of :func:`fft_rows_ri`: C2C rows plus a fused
    epilogue computing the optional de-window multiply (``dewindow`` is
    the [L] coefficient vector to divide out, ref: fft_pipe.hpp:346-359)
    and the per-row power moments for spectral kurtosis.

    Returns ``(re, im, s2, s4)`` where s2/s4 are [B, 128] lane-partial
    sums of |x|^2 / |x|^4 per row (finish with ``.sum(-1)``)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    lc = _Launch(re, im, inverse)
    shape, length, batch = lc.shape, lc.length, lc.batch
    rows = lc.rows
    apply_dewindow = dewindow is not None
    if apply_dewindow:
        dwr = (1.0 / dewindow.astype(jnp.float32)).reshape(1, length)
    else:  # placeholder tile, never read by the kernel
        dwr = jnp.ones((1, length), jnp.float32)

    stat_block = pl.BlockSpec((rows, 128), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    kernel = functools.partial(_fft_rows_stats_kernel, la=lc.la, lb=lc.lb,
                               rows=rows, apply_dewindow=apply_dewindow,
                               rows_helper=active_rows_helper())
    out_re, out_im, s2, s4 = pl.pallas_call(
        kernel,
        grid=lc.grid,
        in_specs=[lc.block, lc.block] + lc.const_specs
                 + [lc.const_spec((1, length))],
        out_specs=[lc.block, lc.block, stat_block, stat_block],
        out_shape=[lc.out_shape(), lc.out_shape(),
                   jax.ShapeDtypeStruct((batch, 128), jnp.float32),
                   jax.ShapeDtypeStruct((batch, 128), jnp.float32)],
        interpret=interpret,
        **_call_kwargs(interpret),
    )(lc.re2, lc.im2, *lc.consts, dwr)
    return (out_re.reshape(shape), out_im.reshape(shape),
            s2.reshape(*shape[:-1], 128), s4.reshape(*shape[:-1], 128))
