"""Pallas row FFT: batched C2C transforms computed entirely in VMEM.

XLA's TPU FFT moves each point through HBM several times per transform
(measured: 14.6 ms for the [2048, 2^16] waterfall backward C2C — ~6x the
one-read-one-write floor, PERF.md).  For rows that fit VMEM, the whole
transform instead runs inside one Pallas grid step: DMA a block of rows
in, run a two-level Cooley-Tukey split L = La*Lb where *both* levels are
DFT-matrix matmuls on the MXU, DMA the result out.  One HBM read + one
write per point.

Why two explicit matmul levels instead of the radix-128 recursion of
ops/mxu_fft: inside VMEM every array's minor dimension pads to the
128-lane tile, so the recursion's deep [..., 128, 4]-shaped base cases
would blow the block up 32x and OOM the ~16 MB VMEM.  The two-level
split keeps every intermediate's minor dimension at La, Lb or rows*Lb
(>= 64 lanes throughout):

    x[rows, La(j1), Lb(j2)]
      -> transpose [La, rows*Lb]            (VMEM relayout)
      -> Wa^T @ x          : A[k1, j2]      (MXU, contraction La)
      -> * tw[k1, j2]                       (VPU; table passed in, no
                                             in-kernel transcendentals)
      -> @ Wb              : B[k1, k2]      (MXU, contraction Lb)
      -> transpose/reshape [rows, Lb*La]    (natural order: k = k2*La+k1)

It spends La+Lb MACs per point where a true FFT spends 5*log2(L) flops —
deliberately: MXU FLOPs are the cheap resource, HBM passes the scarce
one (scaling-book roofline).  DFT matrices and twiddles are computed in
float64 on host / via the exact-phase generator and passed as kernel
inputs (Pallas forbids captured constants).

This is the TPU answer to the reference's per-vendor FFT wrappers for
the *batched* transforms (ref: fft/fft.hpp:54-160, fft_pipe.hpp:295-311
watfft batch): srtb's waterfall FFT and the four-step legs of the big
segment FFT are all batched rows of length <= 2^16.

Complex values cross the kernel boundary as separate re/im f32 planes
(Mosaic has no complex dtype).  Correctness is held to the same oracles
as every other FFT backend (tests/test_pallas_fft.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from srtb_tpu.ops import fft as F

# Default row-block plan: 1 MB planes (v5e VMEM is 128 MiB/core, but
# small blocks keep the pipeline's working set comfortably inside the
# 100 MiB scoped limit _call_kwargs sets; SRTB_PALLAS_VMEM_MB scales
# both).  Live per grid step: in + out + stage intermediates (all
# [rows, *] f32 pairs) + matrices + twiddle.
_VMEM_BLOCK_ELEMS = 1 << 18  # 256K f32 = 1 MB per plane

# Matmul precision for the DFT contractions.  HIGHEST (6-pass bf16,
# f32-accurate) is the only accurate option real Mosaic accepts: the
# round-5 acceptance run rejected 3-pass bf16 outright
# ("NotImplementedError: Unsupported dot precision: HIGH",
# PERF_TPU.jsonl 2026-08-02) — an error CPU interpret mode cannot
# surface.  The extra passes run on VMEM-resident blocks in an
# HBM-bound pipeline (roofline_frac ~0.06), so HIGHEST costs nothing
# measurable end-to-end.
_PRECISION = jax.lax.Precision.HIGHEST


def _split_la_lb(length: int):
    """Factor L = La*Lb with La pinned to 128: the final natural-order
    assembly transposes to a [rows, Lb, La] view, so La is the one minor
    dimension that must stay a full 128-lane tile.  Lb = L/128 lands in
    [32, 512] over the supported range ([Lb, Lb] tail matrix <= 1 MB per
    plane; Lb < 128 pads its stage intermediates up to 4x in VMEM, paid
    only on the small end)."""
    if length & (length - 1) or not (1 << 12) <= length <= (1 << 16):
        return None
    return 128, length // 128


def supported(length: int, batch: int) -> bool:
    """Whether the Pallas row FFT handles [batch, length]."""
    return _split_la_lb(length) is not None and batch >= 1


def vmem_fft_rows(xr, xi, war, wai, wbr, wbi, twr, twi, *, la, lb, rows):
    """The in-VMEM two-level row FFT on value arrays: [rows, L] f32
    (re, im) -> length-L C2C along each row, L = la*lb, la = 128.
    Returns the natural-order result as a 3D ``[rows, la, lb]`` view
    whose row-major flatten IS the natural-order row (element
    ``[r, ka, kb]`` is bin ``k = ka*lb + kb``) — kernels store it to a
    matching 3D ref and callers flatten OUTSIDE the pallas_call, where
    the contiguous reshape is free metadata.  Pure function of
    VMEM-resident values — shared by the kernels here and by the fused
    two-pass four-step in ops/pallas_fft2.

    This is the one spelling real Mosaic accepts (round-5 acceptance
    probes, PERF_TPU.jsonl 2026-08-02): in-kernel lane-dim reshapes
    compile only when the minor dim is a multiple of 128 on both sides,
    which rules out the historical ``[rows, la, lb]`` input split and
    any in-kernel flatten of the assembled result.  Decimation here is
    ``j = jb*la + ja`` (ja the 128-lane minor digit), so the only input
    reshape is the supported minor-128 split, both DFT contractions are
    3D dot_generals against the middle axis, and the assembly is one
    supported 3D transpose."""
    dg = dot_mid
    # j = jb*la + ja: the minor-128 split Mosaic accepts
    x3r = xr.reshape(rows, lb, la)
    x3i = xi.reshape(rows, lb, la)
    # stage 1, contract jb: A[r, ja, kb] = sum_jb Wb[jb, kb] x[r, jb, ja]
    ar = dg(x3r, wbr, 1) - dg(x3i, wbi, 1)      # [rows, la, lb]
    ai = dg(x3r, wbi, 1) + dg(x3i, wbr, 1)
    # twiddle tw[ja, kb] = exp(-+2*pi*i*ja*kb/L), broadcast over rows
    twr3 = twr.reshape(1, la, lb)
    twi3 = twi.reshape(1, la, lb)
    br = ar * twr3 - ai * twi3
    bi = ar * twi3 + ai * twr3
    # stage 2, contract ja: C[r, kb, ka] = sum_ja Wa[ja, ka] B[r, ja, kb]
    cr = dg(br, war, 1) - dg(bi, wai, 1)        # [rows, lb, la]
    ci = dg(br, wai, 1) + dg(bi, war, 1)
    # natural order k = ka*lb + kb: one 3D transpose to [r, ka, kb]
    yr = jnp.transpose(cr, (0, 2, 1))           # [rows, la, lb]
    yi = jnp.transpose(ci, (0, 2, 1))
    return yr, yi


def dot_mid(a, b, dim):
    """dot_general contracting ``a``'s axis ``dim`` with ``b``'s axis 0
    under the module's DFT precision discipline — the single home of
    that convention for the spellings here and in pallas_fft2."""
    return jax.lax.dot_general(
        a, b, (((dim,), (0,)), ((), ())),
        precision=_PRECISION, preferred_element_type=jnp.float32)


def _fft_rows_kernel(re_ref, im_ref, war_ref, wai_ref, wbr_ref, wbi_ref,
                     twr_ref, twi_ref, out_re_ref, out_im_ref, *,
                     la, lb, rows):
    out_re_ref[:], out_im_ref[:] = vmem_fft_rows(
        re_ref[:], im_ref[:], war_ref[:], wai_ref[:], wbr_ref[:],
        wbi_ref[:], twr_ref[:], twi_ref[:], la=la, lb=lb, rows=rows)


def _fft_rows_stats_kernel(re_ref, im_ref, war_ref, wai_ref, wbr_ref,
                           wbi_ref, twr_ref, twi_ref, dwr_ref,
                           out_re_ref, out_im_ref, s2_ref, s4_ref, *,
                           la, lb, rows, apply_dewindow):
    """fft_rows kernel + fused epilogue: optional de-window multiply and
    per-row power moments (sum |x|^2, sum |x|^4 as 128-lane partials) —
    the spectral-kurtosis statistics collected while the waterfall rows
    are still in VMEM, so the SK stage never re-reads the waterfall from
    HBM (ref: spectrum/rfi_mitigation.hpp:290-341 computes them in a
    separate pass).  All values here carry the helper's 3D
    ``[rows, la, lb]`` natural-flat view: the de-window vector arrives
    pre-shaped ``[la, lb]`` from the host (an in-kernel [1, L] ->
    [la, lb] split would be the unsupported minor-lb reshape) and the
    moment partials reduce over kb, leaving [rows, la=128] lane
    partials — a different partial grouping than the flat kernel's
    historical L/128 chunks, same finished sums."""
    _fft_rows_kernel(re_ref, im_ref, war_ref, wai_ref, wbr_ref, wbi_ref,
                     twr_ref, twi_ref, out_re_ref, out_im_ref,
                     la=la, lb=lb, rows=rows)
    yr = out_re_ref[:]
    yi = out_im_ref[:]
    if apply_dewindow:
        dw = dwr_ref[:].reshape(1, la, lb)  # reciprocal de-window coeffs
        yr = yr * dw
        yi = yi * dw
        out_re_ref[:] = yr
        out_im_ref[:] = yi
    p = yr * yr + yi * yi
    s2_ref[:] = jnp.sum(p, axis=2)
    s4_ref[:] = jnp.sum(p * p, axis=2)


def _fft_rows_skzap_kernel(re_ref, im_ref, war_ref, wai_ref, wbr_ref,
                           wbi_ref, twr_ref, twi_ref, dwr_ref,
                           out_re_ref, out_im_ref, zap_ref, fs_ref,
                           ts_ref, *, la, lb, rows, apply_dewindow,
                           m, thr_low, thr_high):
    """The whole waterfall tail in ONE kernel: backward C2C + de-window
    + spectral-kurtosis decision + zap + detection time-series
    accumulation, all while the rows are VMEM-resident.

    The key structural fact making this legal: each waterfall row is
    transformed *entirely within one grid step* (the row fits VMEM), so
    its SK moments — which the two-kernel chain
    (fft_rows_stats_ri + pallas_kernels.sk_apply_timeseries) must round
    -trip through HBM to globalize — are complete before the row is
    ever written.  The zap verdict (thresholds precomputed by
    rfi.sk_decision_thresholds, ref: spectrum/rfi_mitigation.hpp:
    290-341) applies in-register, the zapped row is written once, and
    the row's contribution to the frequency-summed power time series
    (ref: signal_detect_pipe.hpp:305-316) accumulates into a single
    [la, lb] block revisited across grid steps — the detect stage never
    reads the waterfall back from HBM at all.

    Outputs beyond the zapped rows: ``zap_ref``/``fs_ref`` are
    [rows, 128] lane-broadcast per-row flags (zap verdict; first-time-
    sample power) for the zero-channel count, ``ts_ref`` the [la, lb]
    natural-flat time series (flatten outside the call)."""
    from jax.experimental import pallas as pl

    yr, yi = vmem_fft_rows(
        re_ref[:], im_ref[:], war_ref[:], wai_ref[:], wbr_ref[:],
        wbi_ref[:], twr_ref[:], twi_ref[:], la=la, lb=lb, rows=rows)
    if apply_dewindow:
        dw = dwr_ref[:].reshape(1, la, lb)  # reciprocal de-window coeffs
        yr = yr * dw
        yi = yi * dw
    p = yr * yr + yi * yi                       # [rows, la, lb]
    # complete per-row SK moments (the row is fully resident): reduce
    # lanes last so every intermediate keeps a 128-wide minor dim
    s2 = jnp.sum(jnp.sum(p, axis=2), axis=1, keepdims=True)   # [rows, 1]
    s4 = jnp.sum(jnp.sum(p * p, axis=2), axis=1, keepdims=True)
    sk = jnp.float32(m) * s4 / (s2 * s2)
    zap = (sk > thr_high) | (sk < thr_low)      # [rows, 1]
    # select, not multiply: a zapped row carrying Inf/NaN must become
    # exactly zero (same contract as rfi.mitigate_rfi_spectral_kurtosis)
    zap3 = zap[:, :, None]
    out_re_ref[:] = jnp.where(zap3, 0.0, yr)
    out_im_ref[:] = jnp.where(zap3, 0.0, yi)
    zap_ref[:] = jnp.broadcast_to(
        jnp.where(zap, 1.0, 0.0), zap_ref.shape)
    # natural-flat bin t=0 is [r, ka=0, kb=0]: first-sample power,
    # pre-zap (zapped rows count through the zap flag, matching the
    # jnp chain's `zap | (first == 0)` zero-channel accounting)
    fs_ref[:] = jnp.broadcast_to(p[:, 0:1, 0], fs_ref.shape)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        ts_ref[:] = jnp.zeros_like(ts_ref)

    ts_ref[:] += jnp.sum(jnp.where(zap3, 0.0, p), axis=0)


def fft_rows_skzap_ri(re: jnp.ndarray, im: jnp.ndarray,
                      sk_threshold: float,
                      inverse: bool = True,
                      dewindow: jnp.ndarray | None = None,
                      interpret: bool = False):
    """Fully-fused waterfall tail over split re/im rows ``[..., F, L]``
    (leading dims flattened to batch; callers run one data stream per
    call so the time series stays per-stream): one HBM read of the
    dedispersed spectrum rows, one write of the zapped waterfall, and
    the SK verdict + zero-channel flags + detection time series come
    out with the write — ``hbm_passes`` 2 where the jnp chain models 3
    and really does ~5.

    Returns ``(re, im, zapf, fs0, ts)``: zapped waterfall planes
    [..., F, L]; ``zapf``/``fs0`` [..., F, 128] lane-broadcast per-row
    zap flag and first-sample power (finish the zero-channel count with
    ``(zapf[..., 0] != 0) | (fs0[..., 0] == 0)``); ``ts`` [L] the
    not-yet-mean-subtracted power time series over kept rows.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from srtb_tpu.ops.rfi import sk_decision_thresholds

    lc = _Launch(re, im, inverse)
    thr_low, thr_high = sk_decision_thresholds(lc.length, sk_threshold)
    apply_dewindow = dewindow is not None
    if apply_dewindow:
        dwr = (1.0 / dewindow.astype(jnp.float32)).reshape(lc.la, lc.lb)
    else:  # placeholder tile, never read by the kernel
        dwr = jnp.ones((lc.la, lc.lb), jnp.float32)

    stat_block = pl.BlockSpec((lc.rows, 128), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    ts_block = pl.BlockSpec((lc.la, lc.lb), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    kernel = functools.partial(
        _fft_rows_skzap_kernel, la=lc.la, lb=lc.lb, rows=lc.rows,
        apply_dewindow=apply_dewindow, m=lc.length,
        thr_low=float(thr_low), thr_high=float(thr_high))
    out_re, out_im, zapf, fs0, ts = pl.pallas_call(
        kernel,
        grid=lc.grid,
        in_specs=[lc.block, lc.block] + lc.const_specs
                 + [lc.const_spec((lc.la, lc.lb))],
        out_specs=[lc.out_block, lc.out_block, stat_block, stat_block,
                   ts_block],
        out_shape=[lc.out_shape(), lc.out_shape(),
                   jax.ShapeDtypeStruct((lc.pbatch, 128), jnp.float32),
                   jax.ShapeDtypeStruct((lc.pbatch, 128), jnp.float32),
                   jax.ShapeDtypeStruct((lc.la, lc.lb), jnp.float32)],
        interpret=interpret,
        **_call_kwargs(interpret),
    )(lc.re2, lc.im2, *lc.consts, dwr)
    return (lc.unpad(out_re).reshape(lc.shape),
            lc.unpad(out_im).reshape(lc.shape),
            lc.unpad(zapf).reshape(*lc.shape[:-1], 128),
            lc.unpad(fs0).reshape(*lc.shape[:-1], 128),
            ts.reshape(lc.length))


def _vmem_mb() -> int | None:
    """Single parse + validation of SRTB_PALLAS_VMEM_MB (None = the
    proven default plan).  Both readers — the block sizing and the
    Mosaic vmem limit — branch on this one value, so a degenerate
    setting cannot make the two halves of the plan disagree."""
    import os

    env = os.environ.get("SRTB_PALLAS_VMEM_MB")
    if not env:
        return None
    try:
        mb = int(env)
    except ValueError:
        mb = 0
    if mb <= 0:
        raise ValueError(
            f"SRTB_PALLAS_VMEM_MB={env!r} must be a positive integer "
            "(MiB of VMEM the row-FFT plan may assume)")
    return mb


def _rows_budget_padded(length: int, budget_bytes: int) -> int:
    """Largest rows whose PADDED footprint fits the budget, using the
    ops/pallas_fft2 accounting discipline: 2x-pipelined in/out block
    refs at rows*length f32 each (the 3D output block's minor dim lb
    lane-pads to 128, up to 4x on the small-length end — which a flat
    per-plane divisor would undercount exactly where it hurts), plus
    the helper's live stages ([rows, la, lb] intermediates, lb
    lane-padded)."""
    la, lb = _split_la_lb(length)
    plb = max(lb, 128)
    # 2x pipeline x (2 input refs at length + 2 output refs at la*plb)
    per_row_refs = 2 * 2 * (length + la * plb) * 4
    per_row_live = 6 * la * plb * 4
    consts = 4 * (2 * la * la + 2 * lb * plb + 2 * la * plb)
    per_row = per_row_refs + per_row_live
    return max(1, (budget_bytes - consts) // per_row)


def _row_block(length: int, batch: int) -> int:
    mb = _vmem_mb()
    if mb is None:
        elems = _VMEM_BLOCK_ELEMS
    else:
        rows = _rows_budget_padded(length, mb << 20)
        elems = rows * length
    return _row_block_for(length, batch, elems)


def _call_kwargs(interpret: bool) -> dict:
    """Extra pallas_call kwargs: an explicit scoped-vmem limit, always.
    Mosaic's *default* limit is far below the v5e's physical 128 MiB,
    and the L=2^16 leg overflows it — in which case the axon remote
    compile helper crashes outright (HTTP 500) instead of reporting a
    budget error (measured round 5, PERF_TPU.jsonl 2026-08-02).  100
    MiB leaves headroom for Mosaic internal scratch; SRTB_PALLAS_VMEM_MB
    overrides (and then also drives the block sizing above)."""
    if interpret:
        return {}
    mb = _vmem_mb() or 100
    return {"compiler_params": tpu_compiler_params(
        vmem_limit_bytes=mb << 20)}


def tpu_compiler_params(**kwargs):
    """Mosaic compiler-params across the jax rename: ``CompilerParams``
    (new spelling) falling back to ``TPUCompilerParams`` (the only one
    this image's jax 0.4.37 ships) — shared by the pallas2 kernels."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return cls(**kwargs)


@functools.lru_cache(maxsize=None)
def _row_block_for(length: int, padded_batch: int, elems: int) -> int:
    """Row block for a batch already padded to a multiple of 8: real
    Mosaic requires the block's sublane dim divisible by 8 (round-5
    acceptance run), so rows is the largest multiple-of-8 divisor of
    the padded batch within the VMEM element target, floor 8."""
    target = max(8, elems // length)
    rows = (target // 8) * 8
    while rows > 8 and padded_batch % rows:
        rows -= 8
    return max(8, rows)


def _pad_batch(batch: int) -> int:
    """Smallest multiple of 8 >= batch (the Mosaic sublane-tile floor);
    padded rows are transformed and discarded — pure overhead only for
    batches < 8 or odd batches, which no production shape uses."""
    return -(-batch // 8) * 8


@functools.lru_cache(maxsize=None)
def _dft_matrix_np(r: int, inverse: bool):
    j = np.arange(r, dtype=np.float64)[:, None]
    k = np.arange(r, dtype=np.float64)[None, :]
    w = np.exp((2.0 if inverse else -2.0) * 1j * np.pi * j * k / r)
    return (np.ascontiguousarray(w.real.astype(np.float32)),
            np.ascontiguousarray(w.imag.astype(np.float32)))


def leg_consts(length: int, inverse: bool):
    """(la, lb, const arrays) for a two-level in-VMEM row FFT of this
    length — the DFT matrices and inner twiddle every kernel using
    :func:`vmem_fft_rows` must pass in.  Single home (with
    :func:`leg_const_specs`) so _Launch and ops/pallas_fft2 can never
    drift apart on split bounds, precision, or twiddle discipline."""
    split = _split_la_lb(length)
    if split is None:
        raise ValueError(f"row-FFT length {length} unsupported")
    la, lb = split
    war, wai = _dft_matrix_np(la, inverse)
    wbr, wbi = _dft_matrix_np(lb, inverse)
    # tw[k1, j2] = exp(+-2*pi*i*k1*j2/L): exact integer residues
    # through the hi/lo phase split (ops.fft._twiddle discipline)
    tw = F._twiddle(la, lb, inverse)
    return la, lb, (jnp.asarray(war), jnp.asarray(wai),
                    jnp.asarray(wbr), jnp.asarray(wbi),
                    jnp.real(tw), jnp.imag(tw))


def leg_const_specs(la: int, lb: int):
    """BlockSpecs matching :func:`leg_consts`'s arrays, in order."""
    return [_Launch.const_spec(s) for s in
            [(la, la), (la, la), (lb, lb), (lb, lb), (la, lb), (la, lb)]]


class _Launch:
    """Shared launch recipe for the row-FFT kernels: shape checks, the
    La/Lb split, VMEM block sizing, and the DFT/twiddle constants — one
    home, so the plain and stats variants can never drift apart."""

    def __init__(self, re, im, inverse):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        self.shape = re.shape
        self.length = self.shape[-1]
        self.batch = (int(np.prod(self.shape[:-1]))
                      if len(self.shape) > 1 else 1)
        if not supported(self.length, self.batch):
            raise ValueError(f"unsupported row FFT shape {self.shape}")
        self.la, self.lb = _split_la_lb(self.length)
        # pad the batch to the Mosaic sublane-tile floor (multiple of 8)
        self.pbatch = _pad_batch(self.batch)
        re2 = re.reshape(self.batch, self.length)
        im2 = im.reshape(self.batch, self.length)
        if self.pbatch != self.batch:
            pad = ((0, self.pbatch - self.batch), (0, 0))
            re2 = jnp.pad(re2, pad)
            im2 = jnp.pad(im2, pad)
        self.re2, self.im2 = re2, im2
        self.rows = _row_block(self.length, self.pbatch)
        self.grid = (self.pbatch // self.rows,)
        self.block = pl.BlockSpec((self.rows, self.length),
                                  lambda i: (i, 0),
                                  memory_space=pltpu.VMEM)
        # the kernels write the helper's 3D [rows, la, lb] natural-flat
        # view; callers flatten the [batch, la, lb] result outside the
        # pallas_call (contiguous row-major -> free metadata reshape)
        self.out_block = pl.BlockSpec((self.rows, self.la, self.lb),
                                      lambda i: (i, 0, 0),
                                      memory_space=pltpu.VMEM)
        _, _, self.consts = leg_consts(self.length, inverse)
        self.const_specs = leg_const_specs(self.la, self.lb)

    @staticmethod
    def const_spec(shp):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        return pl.BlockSpec(shp, lambda i: tuple(0 for _ in shp),
                            memory_space=pltpu.VMEM)

    def out_shape(self):
        return jax.ShapeDtypeStruct((self.pbatch, self.la, self.lb),
                                    jnp.float32)

    def unpad(self, out):
        """Drop the batch padding rows (no-op slice when unpadded)."""
        return out[:self.batch] if self.pbatch != self.batch else out


def fft_rows_ri(re: jnp.ndarray, im: jnp.ndarray, inverse: bool = False,
                interpret: bool = False):
    """C2C FFT along the last axis of split re/im f32 [..., L] arrays
    (leading dims batch), one grid step per VMEM-sized row block.
    Unnormalized both directions (same conventions as ops.fft
    c2c_forward / c2c_backward)."""
    from jax.experimental import pallas as pl

    lc = _Launch(re, im, inverse)
    kernel = functools.partial(_fft_rows_kernel, la=lc.la, lb=lc.lb,
                               rows=lc.rows)
    out_re, out_im = pl.pallas_call(
        kernel,
        grid=lc.grid,
        in_specs=[lc.block, lc.block] + lc.const_specs,
        out_specs=[lc.out_block, lc.out_block],
        out_shape=[lc.out_shape()] * 2,
        interpret=interpret,
        **_call_kwargs(interpret),
    )(lc.re2, lc.im2, *lc.consts)
    return (lc.unpad(out_re).reshape(lc.shape),
            lc.unpad(out_im).reshape(lc.shape))


def fft_rows(x: jnp.ndarray, inverse: bool = False,
             interpret: bool = False) -> jnp.ndarray:
    """Complex convenience wrapper over :func:`fft_rows_ri`."""
    yr, yi = fft_rows_ri(jnp.real(x), jnp.imag(x), inverse, interpret)
    return jax.lax.complex(yr, yi)


def fft_rows_stats_ri(re: jnp.ndarray, im: jnp.ndarray,
                      inverse: bool = True,
                      dewindow: jnp.ndarray | None = None,
                      interpret: bool = False):
    """Waterfall form of :func:`fft_rows_ri`: C2C rows plus a fused
    epilogue computing the optional de-window multiply (``dewindow`` is
    the [L] coefficient vector to divide out, ref: fft_pipe.hpp:346-359)
    and the per-row power moments for spectral kurtosis.

    Returns ``(re, im, s2, s4)`` where s2/s4 are [B, 128] lane-partial
    sums of |x|^2 / |x|^4 per row (finish with ``.sum(-1)``)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    lc = _Launch(re, im, inverse)
    shape, length, batch = lc.shape, lc.length, lc.batch
    rows = lc.rows
    apply_dewindow = dewindow is not None
    if apply_dewindow:
        # pre-shaped [la, lb] on the host: the natural-flat [r, ka, kb]
        # element is bin ka*lb + kb, and an in-kernel [1, L] -> [la, lb]
        # split would be the unsupported minor-lb reshape
        dwr = (1.0 / dewindow.astype(jnp.float32)).reshape(lc.la, lc.lb)
    else:  # placeholder tile, never read by the kernel
        dwr = jnp.ones((lc.la, lc.lb), jnp.float32)

    stat_block = pl.BlockSpec((rows, 128), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    kernel = functools.partial(_fft_rows_stats_kernel, la=lc.la, lb=lc.lb,
                               rows=rows, apply_dewindow=apply_dewindow)
    out_re, out_im, s2, s4 = pl.pallas_call(
        kernel,
        grid=lc.grid,
        in_specs=[lc.block, lc.block] + lc.const_specs
                 + [lc.const_spec((lc.la, lc.lb))],
        out_specs=[lc.out_block, lc.out_block, stat_block, stat_block],
        out_shape=[lc.out_shape(), lc.out_shape(),
                   jax.ShapeDtypeStruct((lc.pbatch, 128), jnp.float32),
                   jax.ShapeDtypeStruct((lc.pbatch, 128), jnp.float32)],
        interpret=interpret,
        **_call_kwargs(interpret),
    )(lc.re2, lc.im2, *lc.consts, dwr)
    return (lc.unpad(out_re).reshape(shape),
            lc.unpad(out_im).reshape(shape),
            lc.unpad(s2).reshape(*shape[:-1], 128),
            lc.unpad(s4).reshape(*shape[:-1], 128))
