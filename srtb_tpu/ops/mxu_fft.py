"""FFT on the MXU: radix-128 DFT stages as systolic-array matmuls.

XLA's TPU FFT runs the pipeline's dominant op — the segment C2C — at
~8x off the HBM roof (measured: 47 ms for 2^27-sample R2C on a v5e,
PERF.md).  The FLOPs of an FFT are tiny (5 n log2 n), so on a machine
whose matmul throughput is nearly free relative to HBM bandwidth, the
TPU-native formulation is the classic one from the supercomputing
literature: factor the DFT into radix-r stages and execute each stage as
a batched [r, r] DFT-matrix multiply on the MXU,

    DFT_n = (DFT_r tensor I_{n/r}) . twiddle . (I_r tensor DFT_{n/r}),

recursing on n/r.  With r = 128 each stage contracts a 128-point axis
against a constant [128, 128] DFT matrix — exactly the shape the MXU
tiles natively — and an n = 2^26 transform is 3 matmul stages plus one
small base case instead of one opaque XLA FFT op.

Complex arithmetic is split re/im (4 real matmuls per stage;
``jax.lax.Precision.HIGHEST`` keeps f32 accuracy through the bf16 MXU
passes).  Twiddle phases are generated from *integer* index products
reduced mod n and split hi/lo before the float conversion (same
precision discipline as ops/fft.py `_phase_exp` — a plain f32 phase at
n = 2^26 is wrong by whole turns).

This file implements the C2C transform (`mxu_fft`) with the same
unnormalized forward/backward conventions as ops/fft.py; `segment_rfft`
exposes it as ``fft_strategy="mxu"`` through the same half-size packed
C2C + Hermitian post-process used by the four-step path.

Reference roles covered: the vendor-FFT dispatcher's "another backend"
slot (ref: fft/fft.hpp:54-160) — this is a backend XLA does not
provide, not a wrapper over one.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Radix: the MXU's native tile edge.  The recursion bottoms out at
# lengths <= _RADIX with a single DFT-matrix contraction.
_RADIX = 128

# 6-pass bf16 by default; SRTB_MXU_PRECISION=high selects 3-pass bf16
# (pallas_fft runs 3-pass at even longer contractions with ~1e-6
# relative error on chip) — the accuracy x throughput A/B at this
# radix is probed on hardware by tools_tpu_r3_queue.sh before any
# default flip.  Read at trace time.
def _precision():
    import os
    return (jax.lax.Precision.HIGH
            if os.environ.get("SRTB_MXU_PRECISION", "") == "high"
            else jax.lax.Precision.HIGHEST)


@functools.lru_cache(maxsize=None)
def _dft_matrix(r: int, inverse: bool):
    """Constant [r, r] DFT matrix as (re, im) float32 numpy arrays,
    computed in float64.  W[j, k] = exp(-+2*pi*i*j*k/r)."""
    j = np.arange(r, dtype=np.float64)[:, None]
    k = np.arange(r, dtype=np.float64)[None, :]
    sign = 2.0 if inverse else -2.0
    w = np.exp(sign * 1j * np.pi * j * k / r)
    return (w.real.astype(np.float32), w.imag.astype(np.float32))


def _phase_ri(r: jnp.ndarray, n: int, inverse: bool):
    """(cos, sin) of sign*2*pi*r/n for int32 residues r in [0, n) with
    the hi/lo split keeping the phase exact beyond f32's 24-bit range
    (mirrors ops/fft.py `_phase_exp`, but on split planes)."""
    half = 1 << max(n.bit_length() // 2, 1)
    sign = 1.0 if inverse else -1.0
    scale = jnp.float32(sign * 2.0 * np.pi / n)
    a = ((r // half) * half).astype(jnp.float32) * scale
    b = (r % half).astype(jnp.float32) * scale
    ca, sa = jnp.cos(a), jnp.sin(a)
    cb, sb = jnp.cos(b), jnp.sin(b)
    return ca * cb - sa * sb, sa * cb + ca * sb


def _dft_contract(ar: jnp.ndarray, ai: jnp.ndarray, r: int, inverse: bool):
    """DFT over the length-r axis -2 of [..., r, t]: four real matmuls
    against the constant [r, r] matrix, MXU-shaped (the t axis provides
    the systolic array's streaming dimension)."""
    wr_np, wi_np = _dft_matrix(r, inverse)
    wr, wi = jnp.asarray(wr_np), jnp.asarray(wi_np)
    # y[..., k, t] = sum_j W[j, k] * a[..., j, t]
    def mm(w, x):
        return jnp.einsum("jk,...jt->...kt", w, x, precision=_precision())
    yr = mm(wr, ar) - mm(wi, ai)
    yi = mm(wr, ai) + mm(wi, ar)
    return yr, yi


def _fft_ri(ar: jnp.ndarray, ai: jnp.ndarray, inverse: bool,
            radix: int = _RADIX):
    """Recursive radix C2C over the last axis of (re, im) planes."""
    n = ar.shape[-1]
    if n <= radix:
        # single contraction: y[..., k] = sum_j a[..., j] W[j, k]
        wr_np, wi_np = _dft_matrix(n, inverse)
        wr, wi = jnp.asarray(wr_np), jnp.asarray(wi_np)
        def mm(x, w):
            return jnp.einsum("...j,jk->...k", x, w, precision=_precision())
        return (mm(ar, wr) - mm(ai, wi), mm(ai, wr) + mm(ar, wi))
    n1 = radix
    n2 = n // n1
    # x[j1*n2 + j2] viewed as [j1, j2]
    ar = ar.reshape(*ar.shape[:-1], n1, n2)
    ai = ai.reshape(*ai.shape[:-1], n1, n2)
    # stage: A[k1, j2] = sum_j1 W_n1[j1, k1] a[j1, j2]  (MXU contraction)
    ar, ai = _dft_contract(ar, ai, n1, inverse)
    # twiddle W_n^{k1*j2}: integer residue mod n stays exact in int32
    k1 = jax.lax.iota(jnp.int32, n1)[:, None]
    j2 = jax.lax.iota(jnp.int32, n2)[None, :]
    tw_r, tw_i = _phase_ri((k1 * j2) % n, n, inverse)
    ar, ai = ar * tw_r - ai * tw_i, ai * tw_r + ar * tw_i
    # recurse over j2 (last axis), batched over k1
    br, bi = _fft_ri(ar, ai, inverse, radix)
    # X[k2*n1 + k1] = B[k1, k2] -> [k2, k1] then flatten
    br = jnp.swapaxes(br, -1, -2).reshape(*br.shape[:-2], n)
    bi = jnp.swapaxes(bi, -1, -2).reshape(*bi.shape[:-2], n)
    return br, bi


def mxu_fft(x: jnp.ndarray, inverse: bool = False,
            radix: int = _RADIX) -> jnp.ndarray:
    """1-D C2C FFT of power-of-two length via MXU DFT-matmul stages.
    Unnormalized both directions (same conventions as four_step_fft);
    leading dims batch."""
    n = x.shape[-1]
    if n & (n - 1):
        raise ValueError("mxu_fft requires power-of-two length")
    if radix < 2 or radix & (radix - 1) or radix > 2048:
        raise ValueError("radix must be a power of two in [2, 2048]")
    yr, yi = _fft_ri(jnp.real(x), jnp.imag(x), inverse, radix)
    return jax.lax.complex(yr, yi)
