"""FFT layer.

Replaces the reference's vendor FFT dispatcher (ref: fft/fft.hpp:54-160 with
cuFFT/hipFFT/muFFT/FFTW/naive wrappers) with XLA's TPU FFT behind the same
conventions, plus a four-step (Bailey) decomposition for sizes where a
single monolithic 1-D FFT is slow or unsupported.

Conventions reproduced from the reference:
- forward transforms are unnormalized (cuFFT style);
- "backward" C2C means unnormalized inverse, i.e. numpy's
  ``ifft(..., norm="forward")``;
- the R2C output drops the Nyquist bin so the usable spectrum has exactly
  n/2 channels (ref: fft_pipe.hpp:75-77);
- the waterfall FFT reshapes the n/2-channel dedispersed spectrum to
  ``[spectrum_channel_count, watfft_len]`` (each row = one coarse frequency
  sub-band, contiguous) and runs an unnormalized backward C2C per row
  (ref: fft_pipe.hpp:295-311), giving a frequency-major dynamic spectrum.

The plan cache of the reference (fft_wrapper.hpp set_size / shared work
area) maps to the XLA compilation cache: a given (shape, kind) compiles
once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def rfft_drop_nyquist(x: jnp.ndarray) -> jnp.ndarray:
    """R2C FFT of the whole segment, highest bin dropped: n real samples ->
    n/2 complex channels (ref: fft_pipe.hpp:44-78)."""
    return jnp.fft.rfft(x)[..., :-1]


def c2c_forward(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    return jnp.fft.fft(x, axis=axis)


def c2c_backward(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Unnormalized inverse C2C (cuFFT BACKWARD semantics)."""
    return jnp.fft.ifft(x, axis=axis, norm="forward")


def waterfall_c2c(spectrum: jnp.ndarray, channel_count: int,
                  dewindow: jnp.ndarray | None = None,
                  len_cap: int | None = None) -> jnp.ndarray:
    """Dedispersed spectrum (n/2 complex) -> dynamic spectrum
    ``[channel_count, watfft_len]`` via per-row unnormalized backward C2C
    (ref: fft_pipe.hpp:285-372).  Rows are coarse frequency channels; columns
    are time samples within the segment.

    ``dewindow``: watfft_len divisors to de-apply after the backward
    transform, as the reference does for non-rectangle windows
    (ref: fft_pipe.hpp:346-359).  Callers must pass *pre-sanitized*
    coefficients from ``window.dewindow_coefficients`` (zero hann edges
    already replaced by 1 — the single home of that guard).
    """
    n = spectrum.shape[-1]
    watfft_len = n // channel_count
    x = spectrum[..., :channel_count * watfft_len]
    x = x.reshape(*spectrum.shape[:-1], channel_count, watfft_len)
    # row lengths beyond the XLA cap (coarse channelizations of long
    # segments, e.g. [2048, 2^17]) go through the four-step path
    wf = _fft_minor(x, inverse=True, len_cap=len_cap)
    if dewindow is not None:
        wf = wf / dewindow
    return wf


def ifft_refft_waterfall(spectrum: jnp.ndarray, channel_count: int,
                         nsamps_reserved_complex: int = 0,
                         window: jnp.ndarray | None = None,
                         len_cap: int | None = None) -> jnp.ndarray:
    """The reference's alternate channelization path (currently disabled in
    its main(), ref: main.cpp:182-186): full unnormalized inverse C2C back
    to the (dedispersed) complex time domain, trim the reserved tail, then
    forward C2C in chunks of ``channel_count``
    (ref: fft_pipe.hpp:88-170 ifft_1d_c2c_pipe, 183-278 refft_1d_c2c_pipe).

    Output is time-major: [n_chunks(time), channel_count(freq)] — the
    orientation consumed by signal_detect_pipe variant 1.
    """
    td = _fft_minor(spectrum, inverse=True, len_cap=len_cap)
    n = td.shape[-1]
    if 0 < nsamps_reserved_complex < n:
        td = td[..., : n - nsamps_reserved_complex]
    refft_length = min(channel_count, td.shape[-1])
    batch = td.shape[-1] // refft_length
    td = td[..., : batch * refft_length]
    td = td.reshape(*td.shape[:-1], batch, refft_length)
    if window is not None:
        td = td * window
    return c2c_forward(td, axis=-1)


# ----------------------------------------------------------------
# four-step (Bailey) decomposition for very large 1-D FFTs
# ----------------------------------------------------------------
#
# FFT_n = transpose . FFT_rows(n2) . twiddle . FFT_cols(n1) with n = n1*n2.
# On TPU this turns one huge 1-D FFT (which XLA may refuse or handle with a
# poor plan) into two large *batched* FFTs plus elementwise twiddles —
# exactly the shape XLA tiles well.  This is hard part #1 of SURVEY.md §7.

def _phase_exp(r: jnp.ndarray, n: int, sign: float) -> jnp.ndarray:
    """exp(i*sign*2*pi*r/n) for an int32 residue array r (0 <= r < ~n).

    The residue is split into high/low halves so each converts to float32
    exactly; the two sin/cos arguments are combined by angle addition.
    This keeps the phase accurate for n far beyond f32's 24-bit mantissa
    without materializing any host-side table.
    """
    half = 1 << max(n.bit_length() // 2, 1)
    scale = jnp.float32(sign * 2.0 * np.pi / n)
    a = ((r // half) * half).astype(jnp.float32) * scale  # exact multiples
    b = (r % half).astype(jnp.float32) * scale            # < half: exact
    # exp(i(a+b)) = exp(ia) * exp(ib)
    return (jax.lax.complex(jnp.cos(a), jnp.sin(a))
            * jax.lax.complex(jnp.cos(b), jnp.sin(b)))


def _iota_phase(m: int, n: int, sign: float,
                block: int = 256) -> jnp.ndarray:
    """exp(i*sign*2*pi*k/n) for k = 0..m-1, as the outer product of two
    small tables: k = block*q + r, so w[k] = W[q] * V[r] with
    W[q] = exp(i*s*block*q/n), V[r] = exp(i*s*r/n).

    Computing the phase per element costs ~4 transcendentals for each of
    m points (the dominant cost of the Hermitian post-process at
    m = 2^26, measured); the factored form needs m/block + block of them
    plus one complex multiply per point, and its [m/block, block] shape
    is lane-dense.  Accuracy: q*block and r are f32-exact (both well
    under 2^24), so each factor's phase argument is exact — same
    discipline as `_phase_exp`, via the structure of k instead of a
    hi/lo split."""
    if m % block or m < block:
        return _phase_exp(jax.lax.iota(jnp.int32, m), n, sign)
    scale = jnp.float32(sign * 2.0 * np.pi / n)
    q = jax.lax.iota(jnp.int32, m // block)[:, None].astype(jnp.float32) \
        * (block * scale)
    r = jax.lax.iota(jnp.int32, block)[None, :].astype(jnp.float32) * scale
    w = (jax.lax.complex(jnp.cos(q), jnp.sin(q))
         * jax.lax.complex(jnp.cos(r), jnp.sin(r)))
    return w.reshape(m)


def _twiddle(n1: int, n2: int, inverse: bool) -> jnp.ndarray:
    """w[j1, j2] = exp(+-2*pi*i*j1*j2/n), generated inside the trace.

    Materializing this as a host-side constant would bake an n-element
    complex64 literal into the compiled program (512 MB at n = 2^26), so the
    table is built from iota on device.  The phase j1*j2/n is reduced mod 1
    with *integer* arithmetic first — j1*j2 < n fits int32 exactly.
    """
    n = n1 * n2
    sign = 1.0 if inverse else -1.0
    j1 = jax.lax.iota(jnp.int32, n1)[:, None]
    block = 256
    if n2 % block or n2 < block:
        j2 = jax.lax.iota(jnp.int32, n2)[None, :]
        r = (j1 * j2) % n                  # exact, < n
        return _phase_exp(r, n, sign)
    # Factored form: j2 = block*q + s, so w[j1, j2] = A[j1, q] * C[j1, s]
    # with A = exp(i*sign*2*pi*j1*q*block/n), C = exp(.. j1*s/n).  Same
    # exact-integer-residue precision (both arguments go through
    # _phase_exp's hi/lo split), but n1*n2/block + n1*block
    # transcendentals instead of n — the per-element cost collapses to
    # one complex multiply (same trick as _iota_phase, extended to the
    # outer-product index j1*j2).
    q = jax.lax.iota(jnp.int32, n2 // block)[None, :]
    s = jax.lax.iota(jnp.int32, block)[None, :]
    a = _phase_exp((j1 * (q * block)) % n, n, sign)   # [n1, n2/block]
    c = _phase_exp((j1 * s) % n, n, sign)             # [n1, block]
    return (a[:, :, None] * c[:, None, :]).reshape(n1, n2)


def _split_factor(n: int) -> int:
    """Pick n1 ~ sqrt(n), a power of two (n must be a power of two)."""
    log2n = n.bit_length() - 1
    return 1 << (log2n // 2)


# Longest 1-D (possibly batched) FFT handed to XLA's TPU FFT directly.
# Measured on a v5e: batched rows of 2^17+ decompose internally to a
# [..., 128, 128, 8] form whose minor dim pads 8 -> 128 lanes, a 16x HBM
# blowup that OOMs the chip at pipeline sizes (e.g. waterfall
# [2048, 2^17] wants 2x16 GB of scratch); 2^16 and below tile cleanly.
# Default for the ``len_cap`` parameter below — a constant, never
# mutated: callers that need a different cap (tiny-shape multichip
# dryruns forcing the in-shard recursion; future hardware A/Bs) pass it
# explicitly / via Config.fft_len_cap.
_XLA_FFT_LEN_CAP = 1 << 16


def _fft_minor(x: jnp.ndarray, inverse: bool,
               rows_impl: str = "xla",
               len_cap: int | None = None) -> jnp.ndarray:
    """FFT along the minor (last) axis, recursing into the four-step
    decomposition for lengths XLA's TPU FFT handles badly.

    ``rows_impl``: "xla" | "pallas" | "pallas_interpret" — who executes
    the batched row transforms.  "pallas" runs rows that fit VMEM through
    ops/pallas_fft (one HBM read+write per point, MXU DFT-matmul stages);
    out-of-range rows fall back to XLA.

    ``len_cap``: longest row length handed to XLA's FFT directly
    (default _XLA_FFT_LEN_CAP); longer rows recurse into four_step_fft.
    """
    length = x.shape[-1]
    if length > (len_cap or _XLA_FFT_LEN_CAP):
        return four_step_fft(x, inverse, rows_impl, len_cap)
    batch = 1
    for s in x.shape[:-1]:
        batch *= s
    if rows_impl != "xla":
        from srtb_tpu.ops import pallas_fft as _pf
        if _pf.supported(length, batch):
            return _pf.fft_rows(x, inverse,
                                interpret=rows_impl == "pallas_interpret")
    # flatten batch dims: a major-dims-only reshape is free, and the TPU
    # FFT planner is only ever handed the one proven [batch, L] form
    # (a [2, 16384, 16384] batched FFT SIGSEGVed the XLA TPU compiler
    # where [32768, 16384] compiles fine)
    x2 = x.reshape(batch, length) if x.ndim > 2 else x
    if inverse:
        y = jnp.fft.ifft(x2, axis=-1, norm="forward")
    else:
        y = jnp.fft.fft(x2, axis=-1)
    return y.reshape(x.shape) if x.ndim > 2 else y


def four_step_stage1(x: jnp.ndarray, inverse: bool = False,
                     rows_impl: str = "xla",
                     len_cap: int | None = None) -> jnp.ndarray:
    """First half of the four-step FFT: [..., n] -> A[..., n2, k1].

    Splitting the decomposition in two lets very large segments run the
    two halves as *separate XLA programs* (pipeline/segment.py staged
    mode), freeing each program's transpose/FFT scratch before the next
    starts — the difference between fitting and OOMing a 2^30-sample
    segment in 16 GB of HBM.
    """
    n = x.shape[-1]
    if n & (n - 1):
        raise ValueError("four_step_fft requires power-of-two length")
    n1 = _split_factor(n)
    n2 = n // n1
    # view as [n1, n2] row-major: x[j1*n2 + j2]
    a = x.reshape(*x.shape[:-1], n1, n2)
    # step 1: FFT_n1 over j1 for each j2 — transpose so n1 is minor
    a = jnp.swapaxes(a, -1, -2)            # [j2, j1]
    return _fft_minor(a, inverse, rows_impl, len_cap)   # A[j2, k1]


def four_step_stage2(a: jnp.ndarray, inverse: bool = False,
                     rows_impl: str = "xla",
                     len_cap: int | None = None) -> jnp.ndarray:
    """Second half of the four-step FFT: A[..., n2, k1] -> X[..., n]."""
    n2, n1 = a.shape[-2], a.shape[-1]
    n = n1 * n2
    # step 2: twiddle w[j2, k1] = exp(-+2*pi*i*j2*k1/n); generated from
    # iota inside the trace (fuses into the multiply, nothing materialized)
    a = a * _twiddle(n2, n1, inverse)
    # step 3: FFT_n2 over j2 for each k1 — transpose so n2 is minor
    a = jnp.swapaxes(a, -1, -2)            # [k1, j2]
    a = _fft_minor(a, inverse, rows_impl, len_cap)      # C[k1, k2]
    # result index k = k2*n1 + k1 -> [k2, k1] then flatten
    a = jnp.swapaxes(a, -1, -2)
    return a.reshape(*a.shape[:-2], n)


def four_step_fft(x: jnp.ndarray, inverse: bool = False,
                  rows_impl: str = "xla",
                  len_cap: int | None = None) -> jnp.ndarray:
    """1-D C2C FFT of power-of-two length via the four-step algorithm.
    Unnormalized in both directions (matching c2c_forward / c2c_backward).
    Leading dims batch.

    Every sub-FFT runs along the *minor* axis with explicit transposes
    between steps — XLA's TPU FFT on a non-minor axis (and any row
    length > 2^16, see _XLA_FFT_LEN_CAP) triggers internal padded
    reshapes that are both slow and HBM-hungry, so the decomposition
    keeps the layout work visible: transpose -> batched FFT -> twiddle ->
    transpose -> batched FFT -> transpose, all row lengths <= 2^16.
    """
    return four_step_stage2(four_step_stage1(x, inverse, rows_impl,
                                             len_cap),
                            inverse, rows_impl, len_cap)


def rfft_via_c2c(x: jnp.ndarray, use_four_step: bool = False,
                 drop_nyquist: bool = False,
                 len_cap: int | None = None,
                 epilogue=None, premul=None) -> jnp.ndarray:
    """R2C FFT of 2m reals via one m-point C2C plus Hermitian post-process,
    returning m+1 bins (like rfft), or exactly m bins with
    ``drop_nyquist`` (the pipeline convention, ref: fft_pipe.hpp:75-77).
    This is the half-size C2C trick the reference implements in
    fft/fft_1d_r2c_post_process.hpp:33-82 and naive_fft.hpp:219-261;
    combined with four_step_fft it covers segment sizes beyond what a
    monolithic XLA R2C handles.

    ``drop_nyquist`` is not just a convenience: at segment sizes the
    m+1-bin form concatenates edge bins onto three 2m-byte arrays, and
    those odd-length copies put the peak HBM of a 2^30-sample compile
    over a v5e's capacity.  The m-bin form keeps every array exactly
    length m: F[(m-k) mod m] is a flip + roll that XLA fuses into the
    elementwise Hermitian combine."""
    z = pack_even_odd(x)
    zf = four_step_fft(z, len_cap=len_cap) if use_four_step \
        else jnp.fft.fft(z)
    return hermitian_rfft_post(zf, drop_nyquist, epilogue=epilogue,
                               premul=premul)


def pack_even_odd(x: jnp.ndarray) -> jnp.ndarray:
    """Pack 2m reals into m complex (even -> re, odd -> im) for the
    half-size C2C trick.  NOT x.reshape(m, 2): a materialized [m, 2] f32
    pads its minor dim 2 -> 128 lanes on TPU (T(8,128) layout), a 64x HBM
    blowup that OOMs compiles at segment sizes (observed: 128 GB scratch
    for n = 2^29).  Slicing even/odd lanes out of 256-lane rows keeps
    every intermediate lane-dense."""
    n = x.shape[-1]
    if n % 2:
        raise ValueError("even length required")
    m = n // 2
    if n % 256 == 0:
        x2 = x.reshape(*x.shape[:-1], n // 256, 256)
        re = x2[..., 0::2].reshape(*x.shape[:-1], m)
        im = x2[..., 1::2].reshape(*x.shape[:-1], m)
    else:  # tiny inputs (tests); layout padding is harmless here
        x2 = x.reshape(*x.shape[:-1], m, 2)
        re, im = x2[..., 0], x2[..., 1]
    return jax.lax.complex(re, im)


def hermitian_rfft_post(zf: jnp.ndarray,
                        drop_nyquist: bool = False,
                        epilogue=None,
                        premul=None) -> jnp.ndarray:
    """Hermitian post-process of the packed half-size C2C: F[m] -> X of
    the 2m-real rfft (ref: fft/fft_1d_r2c_post_process.hpp:33-82).
    X[k] = F[k] + conj(F[m-k]) pieces; the m-k indexing is a reverse +
    shift, written as flip/roll/concat (not a gather, which TPUs handle
    poorly at this size).

    ``epilogue``: optional ``f(zf, spec) -> spec`` applied to the
    assembled spectrum *inside the same elementwise producer*, so XLA
    writes the post-processed spectrum exactly once — the hook the
    fused spectrum tail (RFI s1 + chirp, pipeline/segment.py) hangs
    off.  ``zf`` is passed along so the epilogue can evaluate global
    reductions (the RFI mean power, via ``rfi.mean_power_packed``)
    against the FFT's already-materialized input instead of re-reading
    the spectrum.

    ``premul``: optional ``(c, cw)`` complex arrays [.., m] implementing
    the chirp·twiddle precombination: the output becomes
    ``c·even + cw·odd`` where ``cw = c·w`` was combined with the
    Hermitian twiddle ahead of time — the chirp multiply costs no extra
    pass and no in-trace trig when a chirp bank exists.  Requires
    ``drop_nyquist`` (the pipeline convention; the m+1-bin form has no
    precombined bank).
    """
    m = zf.shape[-1]
    n = 2 * m
    if drop_nyquist:
        f_k = zf                                           # k in [0, m)
        # [(m-0)%m, m-1, ..., 1] = roll(flip(zf), 1)
        f_mk = jnp.conj(jnp.roll(jnp.flip(zf, axis=-1), 1, axis=-1))
        w = None if premul is not None else _iota_phase(m, n, -1.0)
    else:
        if premul is not None:
            raise ValueError("premul requires drop_nyquist=True")
        f_k = jnp.concatenate([zf, zf[..., :1]], axis=-1)  # F[m] = F[0]
        rev = jnp.flip(zf, axis=-1)                        # [m-1, ..., 0]
        f_mk = jnp.conj(jnp.concatenate([zf[..., :1], rev], axis=-1))
        # w[k] = exp(-2*pi*i*k/n), k in [0, m] — exact hi/lo phase split
        # (avoids both a baked constant and f32 rounding of k)
        w = _phase_exp(jax.lax.iota(jnp.int32, m + 1), n, -1.0)
    even = 0.5 * (f_k + f_mk)
    odd = -0.5j * (f_k - f_mk)
    if premul is not None:
        c, cw = premul
        out = c * even + cw * odd
    else:
        out = even + w * odd
    if epilogue is not None:
        out = epilogue(zf, out)
    return out


def subbyte_window_planes(window: np.ndarray, nbits: int) -> np.ndarray:
    """Reorder a sample-order window [n] into blocked field planes
    [count, M] matching `unpack_subbyte_planes` (host-side numpy: the
    strided reshape would be a pathological layout on device)."""
    count = 8 // nbits
    return np.ascontiguousarray(
        np.asarray(window).reshape(-1, count).T)


def rfft_subbyte(data: jnp.ndarray, nbits: int, strategy: str = "four_step",
                 window_planes: jnp.ndarray | None = None,
                 drop_nyquist: bool = True,
                 planes: jnp.ndarray | None = None,
                 len_cap: int | None = None,
                 epilogue=None, premul=None) -> jnp.ndarray:
    """Fused unpack + even/odd pack + R2C for 1/2/4-bit baseband bytes,
    with every intermediate lane-dense.

    The sample-order composition (unpack -> pack_even_odd -> C2C) forces
    a [bytes, count]-shaped interleave whose TPU layout pads count -> 128
    lanes — materialized, that is a 16 GB copy at n = 2^27 (measured).
    This path never builds sample order at all:

    - `unpack_subbyte_planes` emits blocked field planes [count, M]
      (plane k = field k of every byte, sample count*b + k);
    - with count even, even-indexed samples are exactly the even field
      planes, so the packed half-size sequence z[t] = x[2t] + i*x[2t+1]
      is plane pairs: z[p*b + k'] = planes[2k'][b] + i*planes[2k'+1][b]
      — a [p, M] complex array, p = count/2, no interleave;
    - z is blocked over p planes, i.e. already in the [j2, j1] layout the
      four-step uses *after* its first transpose: FFT_M each plane, then
      the twiddle exp(-2pi*i*j2*k1/m) and a p-point cross-plane butterfly
      finish the m = p*M transform, and the [p(k2), M(k1)] result *is*
      natural order flattened — the blocked->natural permutation has been
      absorbed into the decimation for free;
    - Hermitian post-process as usual (ref fft_1d_r2c_post_process.hpp).

    ``window_planes``: optional [count, M] from `subbyte_window_planes`.
    ``strategy``: "four_step" (XLA batched FFTs) or "mxu" (DFT-matmul
    stages) for the M-point plane FFTs.
    ``planes``: optional precomputed (and already-windowed) blocked field
    planes [..., count, M] — e.g. from the fused Pallas
    unpack_subbyte_planes_window; when given, ``data``/``nbits`` unpack
    and ``window_planes`` are skipped entirely.
    """
    from srtb_tpu.ops import unpack as _U
    count = 8 // nbits
    if count < 2:
        raise ValueError("rfft_subbyte requires 1/2/4-bit input")
    if planes is None:
        planes = _U.unpack_subbyte_planes(data, nbits)    # [..., count, M]
        if window_planes is not None:
            planes = planes * window_planes
    z = subbyte_planes_to_packed(planes)
    if strategy == "mxu":
        from srtb_tpu.ops.mxu_fft import mxu_fft
        a = mxu_fft(z)                                    # [..., p, M]
    elif strategy == "monolithic":
        a = jnp.fft.fft(z, axis=-1)  # one batched XLA FFT over the planes
    elif strategy in ("pallas", "pallas_interpret"):
        a = _fft_minor(z, inverse=False, rows_impl=strategy,
                       len_cap=len_cap)
    elif strategy in ("pallas2", "pallas2_interpret"):
        a = _pallas2_or_fallback(z, strategy, len_cap)
    else:
        a = _fft_minor(z, inverse=False, len_cap=len_cap)
    return finish_rfft_subbyte(a, drop_nyquist, epilogue=epilogue,
                               premul=premul)


def _pallas2_or_fallback(z: jnp.ndarray, strategy: str,
                         len_cap: int | None = None) -> jnp.ndarray:
    """The fused two-pass Pallas C2C (ops/pallas_fft2) on [..., L] complex
    z, falling back to the four-step-with-Pallas-legs form for lengths
    outside its [2^24, 2^29] window (tiny test configs)."""
    from srtb_tpu.ops import pallas_fft2 as pf2
    interp = strategy.endswith("interpret")
    if pf2.supported(z.shape[-1]):
        return pf2.fft2_c2c(z, inverse=False, interpret=interp)
    # loud when an explicit SRTB_PALLAS2_N1 pin is why we're falling
    # back — the A/B knob must not silently measure the wrong path
    pf2.require_pin_fit(z.shape[-1])
    return _fft_minor(z, inverse=False,
                      rows_impl="pallas_interpret" if interp else "pallas",
                      len_cap=len_cap)


def subbyte_planes_to_packed(planes: jnp.ndarray) -> jnp.ndarray:
    """Blocked field planes [..., count, M] -> packed complex plane pairs
    z[..., p, M] (p = count/2): z[p*b + k'] = x[2t] + i*x[2t+1] of the
    sample-order sequence, held blocked."""
    return jax.lax.complex(planes[..., 0::2, :], planes[..., 1::2, :])


def finish_rfft_subbyte(a: jnp.ndarray,
                        drop_nyquist: bool = True,
                        epilogue=None, premul=None) -> jnp.ndarray:
    """Finish `rfft_subbyte` from the per-plane FFTs a[..., p, M]:
    twiddle + p-point cross-plane butterfly + Hermitian post-process.
    Split out so the staged execution plan (pipeline/segment.py) can run
    the plane FFTs and the finish in separate XLA programs."""
    p, m_bytes = a.shape[-2], a.shape[-1]
    m = p * m_bytes
    if p > 1:
        # w[j2, k1] = exp(-2*pi*i*j2*k1/m) is _twiddle(p, M) exactly —
        # reuse its factored form (m/256 + 256 transcendentals per row
        # instead of 4 per point on this hot path)
        a = a * _twiddle(p, m_bytes, inverse=False)
        # p-point DFT across the plane axis (p <= 4: a handful of
        # complex-scalar multiply-adds, fused elementwise by XLA)
        wp = np.exp(-2j * np.pi * np.outer(np.arange(p), np.arange(p))
                    / p).astype(np.complex64)
        rows = [sum(complex(wp[k2, j]) * a[..., j, :] for j in range(p))
                for k2 in range(p)]
        a = jnp.stack(rows, axis=-2)
    zf = a.reshape(*a.shape[:-2], m)
    return hermitian_rfft_post(zf, drop_nyquist, epilogue=epilogue,
                               premul=premul)


# Threshold (packed C2C length, = n/2) above which the segment R2C
# switches to the four-step path.  Tuned on a v5e: the monolithic XLA R2C
# works and wins through n = 2^29; at n = 2^30 XLA's compile OOMs
# (PERF_TPU.jsonl n2_29/n2_30 A/Bs), so only 2^30+ takes the four-step.
LARGE_FFT_THRESHOLD = 1 << 28


def resolve_strategy(n: int, strategy: str) -> str:
    """Resolve "auto" to a concrete segment-R2C strategy for n samples
    (monolithic XLA R2C wins through n = 2^29 on a v5e; above, four-step
    is the only one that fits — see LARGE_FFT_THRESHOLD)."""
    if strategy == "auto":
        return "four_step" if n // 2 > LARGE_FFT_THRESHOLD else "monolithic"
    return strategy


def segment_rfft(x: jnp.ndarray, strategy: str = "auto",
                 len_cap: int | None = None,
                 epilogue=None, premul=None) -> jnp.ndarray:
    """The segment-sized R2C with the drop-Nyquist convention.

    ``epilogue``/``premul`` fold elementwise spectrum work into the
    final (Hermitian post-process) pass — see
    :func:`hermitian_rfft_post`.  The monolithic strategy cannot host
    them (the spectrum is produced inside XLA's R2C custom call) and
    raises rather than silently running unfused.

    strategy:
    - "auto": monolithic below the four-step threshold, four_step above
      it ("mxu" is opt-in until validated end-to-end on hardware);
    - "monolithic": one XLA R2C op;
    - "four_step": half-size packed C2C via the Bailey decomposition +
      Hermitian post-process — two large *batched* XLA FFTs instead of
      one huge 1-D FFT;
    - "mxu": the packed C2C executed as radix-128 DFT-matrix matmuls on
      the systolic array (ops/mxu_fft.py) — measured ~25% faster than
      the monolithic XLA R2C at the 2^27 bench size on a v5e;
    - "pallas" ("pallas_interpret" off-TPU): the four-step decomposition
      with its batched row FFTs executed by the VMEM Pallas kernel
      (ops/pallas_fft) — one HBM read+write per point per leg;
    - "pallas2" ("pallas2_interpret" off-TPU): the fused two-pass
      four-step (ops/pallas_fft2) — transposes and twiddles absorbed
      into the two leg kernels, two HBM round trips for the whole C2C
      and no XLA FFT op anywhere.
    """
    strategy = resolve_strategy(x.shape[-1], strategy)
    if strategy == "monolithic" and (epilogue is not None
                                     or premul is not None):
        raise ValueError(
            "the monolithic XLA R2C cannot host a spectrum epilogue")
    if strategy in ("pallas2", "pallas2_interpret"):
        zf = _pallas2_or_fallback(pack_even_odd(x), strategy, len_cap)
        return hermitian_rfft_post(zf, drop_nyquist=True,
                                   epilogue=epilogue, premul=premul)
    if strategy in ("pallas", "pallas_interpret"):
        z = pack_even_odd(x)
        zf = four_step_fft(z, rows_impl=strategy, len_cap=len_cap)
        return hermitian_rfft_post(zf, drop_nyquist=True,
                                   epilogue=epilogue, premul=premul)
    if strategy == "four_step":
        return rfft_via_c2c(x, use_four_step=True, drop_nyquist=True,
                            len_cap=len_cap, epilogue=epilogue,
                            premul=premul)
    if strategy == "mxu":
        from srtb_tpu.ops.mxu_fft import mxu_fft
        z = pack_even_odd(x)
        return hermitian_rfft_post(mxu_fft(z), drop_nyquist=True,
                                   epilogue=epilogue, premul=premul)
    if strategy == "monolithic":
        return rfft_drop_nyquist(x)
    raise ValueError(f"unknown fft strategy {strategy!r}")
