"""FFT window functions (ref: fft/fft_window.hpp:27-123).

Cosine-sum windows evaluated at x = i / (n - 1) for i in [0, n); the
reference's default window is the rectangle (fft_window.hpp:83), in which
case application is skipped entirely.
"""

from __future__ import annotations

import numpy as np

# cosine-sum coefficients a_k with alternating sign (-1)^k, as in
# cosine_sum_window::operator() (fft_window.hpp:42-49)
_COSINE_SUM_COEFFS = {
    "hann": (0.5, 0.5),
    "hamming": (25.0 / 46.0, 21.0 / 46.0),
}


def window_coefficients(name: str, n: int, dtype=np.float32) -> np.ndarray | None:
    """Window coefficient array of length n, or None for the rectangle window
    (meaning: skip application, as the reference does for its default)."""
    name = name.lower()
    if name in ("rectangle", "boxcar", "none", ""):
        return None
    if name not in _COSINE_SUM_COEFFS:
        raise ValueError(f"unknown window {name!r}")
    if n == 1:
        # degenerate single-sample window: x = 0/0; the natural limit of
        # every cosine-sum window is 1.0 (scipy agrees), not NaN
        return np.ones(1, dtype=dtype)
    coeffs = _COSINE_SUM_COEFFS[name]
    x = np.arange(n, dtype=np.float64) / (n - 1)
    ret = np.zeros(n, dtype=np.float64)
    for k, a_k in enumerate(coeffs):
        sign = 1.0 if (k % 2 == 0) else -1.0
        ret += sign * a_k * np.cos(2.0 * np.pi * k * x)
    return ret.astype(dtype)


def dewindow_coefficients(name: str, n: int,
                          dtype=np.float32) -> np.ndarray | None:
    """Safe divisors for de-applying a window after the waterfall backward
    C2C (ref: fft_pipe.hpp:346-359): same as :func:`window_coefficients`
    but with exact zeros (hann edges) replaced by 1 so the division never
    produces inf — the shared sanitization for both the single-chip and
    distributed paths."""
    w = window_coefficients(name, n, dtype=dtype)
    if w is None:
        return None
    return np.where(w == 0.0, dtype(1.0), w)


DEFAULT_WINDOW = "rectangle"  # ref: fft_window.hpp:83
