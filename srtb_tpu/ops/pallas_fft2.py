"""Fused two-pass Pallas four-step C2C: the whole large-m transform in
two kernel passes plus one fusable transpose.

The existing "pallas" strategy runs the four-step legs (ops/pallas_fft)
inside XLA's decomposition: transpose, leg FFT, twiddle multiply,
transpose, leg FFT, transpose — each arrow a full HBM pass, ~6 round
trips for the C2C (measured 1481 vs monolithic's 1746 Msamples/s at
2^27, PERF_TPU.jsonl).  This module fuses each leg's surrounding
layout work *into the leg's kernel* so the C2C is two passes total:

  pass 1 (grid over j2 column blocks of z viewed [n1, n2] row-major):
    DMA a strided [n1, bb] column block into VMEM and run the two-level
    DFT decimation over j1 *column-natively*: both contractions are
    dot_generals against the j1 axes of the [la, lb, bb] view in place
    (no 2D transpose, every intermediate lane-dense), then the
    four-step twiddle w[k1, j2] = exp(s*2*pi*i*k1*j2/m) computed
    *in-kernel* from iota with the exact hi/lo phase split (no m-sized
    table exists anywhere), and DMA out: intermediate B[k1, j2] laid
    out [n1, n2].  (A transpose-to-rows spelling existed for hardware
    A/B until round 5's real-Mosaic acceptance run: its in-kernel
    flatten of the assembled row is a minor-lb reshape Mosaic rejects,
    so the column-native form is now the one spelling.)

  pass 2 (grid over k1 row blocks):
    DMA a contiguous [rb, n2] row block, run the row FFT over j2, store
    C[k1, k2] row-major.  The k1-major blocked order is deliberate: a
    natural-order [n2, rb] output block would lane-pad rb -> 128 in
    VMEM (8-32 MB/plane at production n2), so the blocked->natural
    permutation is instead an XLA transpose (``unblock``) that fuses
    into the consumer's next pass — the Hermitian post-process here.

Two kernel passes plus one fusable transpose, versus ~6 separate HBM
round trips for the XLA-orchestrated form.

No XLA FFT op appears anywhere in this path — which also makes it a
workaround candidate for the XLA TPU compiler SIGSEGV on the 2^30
staged blocked shape (PERF.md).  Like every FFT backend here it is
unnormalized in both directions and held to the same float64 oracle
tests (tests/test_pallas_fft2.py); the TPU answer to the reference's
single-call vendor FFTs for full segments (ref: fft/fft.hpp:54-160,
fft_pipe.hpp:44-78).

Front fusion (the ``staged_ffuse`` plan family, pipeline/segment.py):

  * :func:`pass1_front` takes the **raw uint8 segment** as its operand:
    each grid step DMAs its column block of packed bytes, unpacks
    (1/2/4/8-bit, simple or 2-pol byte-interleaved), applies the window
    and the even/odd pack in VMEM, runs the pass-1 column FFT +
    four-step twiddle, and writes the blocked intermediate exactly once
    — HBM pass 1 is one raw-byte read plus one blocked write, with the
    Parseval pieces of the RFI-s1 mean power accumulated on the side.
  * :func:`pass2_spectrum` appends the whole spectrum tail to pass 2's
    epilogue (the slot the skzap tail occupies on the waterfall side):
    row FFT, the Hermitian R2C post-process assembled in-kernel from
    mirrored row blocks, RFI-s1 zap/normalize/manual-mask, and the
    dedispersion chirp — the df64 in-register phase in production
    (staged plans are always bankless; the precombined
    ``(c, cw = c·w)`` blocked premul operands stay available for
    tests and non-staged callers) — so pass 2 emits the dedispersed
    spectrum directly.

  This is the traffic-minimizing move of the PIM-FFT literature
  (PAPERS.md: *Collaborative Acceleration for FFT on PIM*, *Near Memory
  Acceleration on Radio Astronomy Imaging*): do the format conversion
  where the data already is, never re-read what a kernel just wrote.
  Below the production leg window the passes fall back to single-stage
  DFT-matrix legs (``_leg``) so the family stays auditable/testable at
  CPU/CI shapes; Mosaic acceptance of the unpack lane interleave is
  gated like ops/pallas_kernels.UNPACK_MOSAIC_OK (see FFUSE_MOSAIC_OK).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from srtb_tpu.ops import fft as F
from srtb_tpu.ops import pallas_fft as PF


def _factor(m: int, strict: bool = True):
    """m = n1 * n2 with n1 the resident-column length (the whole n1 axis
    of a [n1, bb] block must fit VMEM, so n1 stays small) and n2 a row
    length the two-level kernel handles.  Both need la=128 splits with
    lb >= 32 to bound sublane padding, hence n1 in {4096, 8192} and
    n2 in [4096, 65536]: m in [2^24, 2^29] — exactly the segment sizes
    where monolithic XLA falters (PERF.md).  SRTB_PALLAS2_N1 pins n1
    for hardware A/B (a smaller n1 halves the padded pass-1 block refs
    — the fallback axis if the default plan misses VMEM on chip)."""
    if m & (m - 1):
        return None
    env = os.environ.get("SRTB_PALLAS2_N1")
    if env:
        try:
            n1 = int(env)
        except ValueError:
            n1 = 0
        if n1 <= 0 or n1 & (n1 - 1):
            raise ValueError(
                f"SRTB_PALLAS2_N1={env!r} must be a positive power of two")
        if PF._split_la_lb(n1) is None:
            # as loud as the parse error: a pow2 outside the leg range
            # must not masquerade as an "unsupported size" downstream
            raise ValueError(
                f"SRTB_PALLAS2_N1={n1} outside the leg-FFT range "
                "[4096, 65536]")
        cands = (n1,)
    else:
        cands = (4096, 8192)
    for n1 in cands:
        n2 = m // n1
        if m % n1 == 0 and PF._split_la_lb(n1) and 4096 <= n2 <= 65536:
            return n1, n2
    if env and strict:
        # the pin passed the pow2/leg-range checks above but fails for
        # THIS m — at kernel-build time an explicit knob must not
        # silently degrade to "unsupported size" (and thence the xla
        # fallback).  Boolean probes (``supported``) pass strict=False:
        # dispatchers ask about many sizes and a pin that doesn't fit a
        # probed size just means "not this path for this size".
        n1 = cands[0]
        if m % n1:
            raise ValueError(
                f"SRTB_PALLAS2_N1={n1} does not divide m={m}")
        raise ValueError(
            f"SRTB_PALLAS2_N1={n1} leaves n2={m // n1} outside the "
            "row-FFT range [4096, 65536] "
            f"for m={m}")
    return None


def supported(m: int) -> bool:
    return _factor(m, strict=False) is not None


def require_pin_fit(m: int) -> None:
    """Dispatchers call this in their not-supported fallback branch:
    when SRTB_PALLAS2_N1 is set and is the *reason* ``m`` is
    unsupported, raise the strict pin error instead of letting the
    operator's explicit A/B knob silently measure the fallback path.
    No-op when the pin is unset (the documented tiny-config fallback)
    or when m is unsupported for pin-independent reasons (non-pow2)."""
    if os.environ.get("SRTB_PALLAS2_N1"):
        _factor(m, strict=True)


def _vmem_budget() -> int:
    """Total VMEM bytes each kernel's plan may assume.  The round-2
    measurements ran on v5e, whose physical VMEM is 128 MiB/core;
    Mosaic's *default* scoped-vmem limit is far lower, so both
    pallas_calls pass an explicit ``vmem_limit_bytes`` alongside blocks
    sized by the padded-footprint model below.  Default 80 MiB leaves
    headroom for Mosaic internal scratch; SRTB_PALLAS2_VMEM_MB is the
    hardware A/B knob (a 16 MiB-era budget cannot fit ANY pass-1 block:
    the padded minimum 2*4*n1*128*4 B is 16 MiB at n1=4096 alone).
    Parsed + validated once, like pallas_fft._vmem_mb: a degenerate
    setting must fail loudly here, not as floor-zero blocks plus a
    nonpositive vmem_limit_bytes handed to Mosaic."""
    env = os.environ.get("SRTB_PALLAS2_VMEM_MB", "80")
    try:
        mb = int(env)
    except ValueError:
        mb = 0
    if mb <= 0:
        raise ValueError(
            f"SRTB_PALLAS2_VMEM_MB={env!r} must be a positive integer "
            "(MiB of VMEM the two-pass plan may assume)")
    return mb << 20


def _leg_const_bytes(la: int, lb: int) -> int:
    """Padded VMEM bytes of the six leg-FFT constant refs
    (war/wai [la,la], wbr/wbi [lb,lb], twr/twi [la,lb]) — lb < 128
    lane-pads its minor dim."""
    plb = max(lb, 128)
    return 4 * (2 * la * la + 2 * lb * plb + 2 * la * plb)


def _pass1_bytes(n1: int, bb: int) -> int:
    """Padded-VMEM footprint model for one pass-1 grid step: the four
    [n1, bb] block refs are double-buffered by the Pallas pipeline and
    lane-pad bb -> 128 (the round-3 review catch: logical-words sizing
    undercounted small-bb blocks 4x at n1=8192), plus the peak live
    column-native kernel intermediates, plus the leg consts."""
    la, lb = PF._split_la_lb(n1)
    refs = 2 * 4 * n1 * max(bb, 128) * 4
    # dense [lb, bb, la]/[bb, la, lb] stages; stage-2 outputs carry
    # minor dim lb (pads to 128), the final relayout minor dim bb
    live = (4 * la * lb * bb * 4
            + 2 * bb * la * max(lb, 128) * 4
            + 2 * n1 * max(bb, 128) * 4)
    return refs + live + _leg_const_bytes(la, lb)


def _pass2_bytes(n2: int, rb: int) -> int:
    """Same model for one pass-2 grid step: the [rb, n2] input blocks
    are lane-dense (rb is the sublane dim, min tile 8); the 3D output
    blocks and helper stages carry minor dim lb = n2/128, which pads to
    128 on the small-n2 end."""
    la, lb = PF._split_la_lb(n2)
    plb = max(lb, 128)
    refs = 2 * 2 * max(rb, 8) * (n2 + la * plb) * 4
    live = 6 * la * rb * plb * 4
    return refs + live + _leg_const_bytes(la, lb)


def _pick_block(candidates, fits, floor: int) -> int:
    """Largest candidate whose modeled footprint fits the budget; the
    floor (the minimum meaningful block) when none does — shrinking
    below it cannot reduce the padded refs, so a non-fitting floor is a
    hardware question for vmem_limit_bytes, not a sizing one."""
    for c in candidates:
        if fits(c):
            return c
    return floor


def _choose_block(env_var: str, cands, fallback: int, small: bool,
                  bytes_fn, floor: int) -> int:
    """Shared block-chooser rule of the four pass pickers below: the
    env pin overrides absolutely (hardware tuning); small-leg
    (sub-production) shapes take the largest candidate — the whole
    block is tiny and the padded-footprint model doesn't apply;
    otherwise the largest candidate whose modeled footprint fits the
    VMEM budget, or the floor."""
    env = os.environ.get(env_var)
    if env:
        return int(env)
    if small or not cands:
        return cands[0] if cands else fallback
    budget = _vmem_budget()
    return _pick_block(cands, lambda c: bytes_fn(c) <= budget, floor)


def _block_cols(n1: int, n2: int) -> int:
    """Pass-1 column-block width (= rows of the in-kernel leg FFT):
    largest power-of-two divisor of n2 in [128, 1024] that fits the
    padded-footprint budget.  bb >= 128 always — below that the block's
    lane padding keeps VMEM cost flat while throwing away strided-DMA
    width.  SRTB_PALLAS2_BB overrides absolutely (hardware tuning)."""
    return _choose_block(
        "SRTB_PALLAS2_BB",
        [c for c in (1024, 512, 256, 128) if n2 % c == 0],
        min(n2, 128), PF._split_la_lb(n1) is None,
        lambda c: _pass1_bytes(n1, c), 128)


def _block_rows(n2: int, n1: int) -> int:
    """Pass-2 row-block height: largest power-of-two divisor of n1 in
    [8, 256] that fits the budget (rb is the sublane dim — lane-dense
    at any size, so small rb is cheap and correct here)."""
    return _choose_block(
        "SRTB_PALLAS2_RB",
        [c for c in (256, 128, 64, 32, 16, 8) if n1 % c == 0],
        min(n1, 8), PF._split_la_lb(n2) is None,
        lambda c: _pass2_bytes(n2, c), 8)


def _pass1_front_bytes(n1: int, bb: int, streams: int, nbits: int,
                       windowed: bool) -> int:
    """:func:`_pass1_bytes` extended for the front-fused kernel
    (:func:`pass1_front`): the double-buffered raw-byte tile, the
    optional (w_even, w_odd) window blocks and the 2S output blocks +
    3S accumulators replace the classic 2-in/2-out ref model; the
    in-kernel unpack adds its int32 byte view plus the widened f32
    sample planes as live scratch; the per-stream column FFT keeps the
    classic live-intermediate term (streams are processed serially, so
    one stream's FFT intermediates are live at a time)."""
    la, lb = PF._split_la_lb(n1)
    blk_bytes = bb * 2 * streams * abs(nbits) // 8
    refs = 2 * n1 * max(blk_bytes, 128)               # u8 byte tile
    if windowed:
        refs += 2 * 2 * n1 * max(bb, 128) * 4         # (w_even, w_odd)
    refs += 2 * 2 * streams * n1 * max(bb, 128) * 4   # output blocks
    refs += 2 * 3 * streams * 8 * 128 * 4             # accumulators
    # unpack scratch: the int32 byte view plus ~2 widened f32 sample
    # planes covering all streams (field stack + lane de-interleave)
    scratch = (n1 * max(blk_bytes, 128) * 4
               + 2 * n1 * 2 * streams * max(bb, 128) * 4)
    live = (4 * la * lb * bb * 4 + 2 * bb * la * max(lb, 128) * 4
            + 2 * n1 * max(bb, 128) * 4)
    return refs + scratch + live + _leg_const_bytes(la, lb)


def _block_cols_front(n1: int, n2: int, streams: int, nbits: int,
                      windowed: bool) -> int:
    """Pass-1 column-block width for the front-fused kernel — the
    :func:`_block_cols` rule with the fused footprint model (the
    raw-byte tile + unpack scratch + per-stream outputs all count).
    SRTB_PALLAS2_BB still overrides absolutely."""
    return _choose_block(
        "SRTB_PALLAS2_BB",
        [c for c in (1024, 512, 256, 128) if n2 % c == 0],
        min(n2, 128), PF._split_la_lb(n1) is None,
        lambda c: _pass1_front_bytes(n1, c, streams, nbits, windowed),
        128)


def _pass2_spec_bytes(n2: int, rb: int, has_mask: bool,
                      has_premul: bool) -> int:
    """:func:`_pass2_bytes` extended for the fused-epilogue kernel
    (:func:`pass2_spectrum`): SIX streamed [rb, n2] input blocks (row
    + mirror + next pairs) plus the mask/premul operand blocks, two
    row FFTs live per step (the block's own rows and its mirror rows),
    and the Hermitian/zap/chirp elementwise planes."""
    la, lb = PF._split_la_lb(n2)
    plb = max(lb, 128)
    prb = max(rb, 8)
    nin = 6 + (1 if has_mask else 0) + (4 if has_premul else 0)
    refs = 2 * (nin + 2) * prb * n2 * 4        # lane-dense [rb, n2] refs
    live = (2 * 6 * la * rb * plb * 4          # two row-FFT bodies
            + 10 * prb * n2 * 4)               # hermitian/zap/chirp planes
    return refs + live + _leg_const_bytes(la, lb)


def _block_rows_spec(n2: int, n1: int, has_mask: bool,
                     has_premul: bool) -> int:
    """Pass-2 row-block height for the fused-epilogue kernel — the
    :func:`_block_rows` rule with the fused footprint model.
    SRTB_PALLAS2_RB still overrides absolutely."""
    return _choose_block(
        "SRTB_PALLAS2_RB",
        [c for c in (256, 128, 64, 32, 16, 8) if n1 % c == 0],
        min(n1, 8), PF._split_la_lb(n2) is None,
        lambda c: _pass2_spec_bytes(n2, c, has_mask, has_premul), 8)


# ------------------------------------------------------------------
# in-kernel DFT "legs".  The production window runs the two-level
# 128-lane VMEM leg (ops/pallas_fft); below it — the front-fuse
# family's CI/audit shapes — a leg is a single DFT-matrix contraction,
# so the same kernels stay lowerable at any power-of-two >= 8.

_SMALL_LEG_MAX = 512  # [L, L] f32 DFT-matrix pair tops out at 2 MB


def _leg(length: int, inverse: bool):
    """(kind, la, lb, const arrays) for the in-kernel DFT along one
    axis: kind "two" = the two-level 128-lane leg (PF.leg_consts),
    kind "one" = one [L, L] DFT-matrix dot_general (small lengths)."""
    if PF._split_la_lb(length) is not None:
        la, lb, consts = PF.leg_consts(length, inverse)
        return "two", la, lb, consts
    if length & (length - 1) or not 8 <= length <= _SMALL_LEG_MAX:
        raise ValueError(f"leg length {length} unsupported")
    wr, wi = PF._dft_matrix_np(length, inverse)
    return "one", length, 1, (jnp.asarray(wr), jnp.asarray(wi))


def _leg_specs(kind: str, la: int, lb: int):
    if kind == "two":
        return PF.leg_const_specs(la, lb)
    return [PF._Launch.const_spec((la, la)),
            PF._Launch.const_spec((la, la))]


def leg_supported(length: int) -> bool:
    return PF._split_la_lb(length) is not None or (
        not length & (length - 1) and 8 <= length <= _SMALL_LEG_MAX)


def ffuse_factor(m):
    """[n1, n2] factorization for the front-fused kernels: the standard
    production window (:func:`_factor`) first; below it a small-leg
    split so the ``staged_ffuse`` plan family stays auditable and
    testable at CPU/CI shapes.  None when ``m`` has no usable split."""
    fac = _factor(m, strict=False)
    if fac is not None:
        return fac
    if m & (m - 1) or m < (1 << 10):
        return None

    def ok(n1):
        if not 8 <= n1 <= _SMALL_LEG_MAX or m % n1:
            return False
        return leg_supported(m // n1) and m // n1 >= 128

    n1 = min(1 << ((m.bit_length() - 1) // 2), _SMALL_LEG_MAX)
    for cand in (n1, m // 4096, m // 128):
        if ok(cand):
            return cand, m // cand
    return None


def _phase_cos_sin(r, m: int, sign: float):
    """(cos, sin) of sign*2*pi*r/m for an int32 residue array r < m
    <= 2^29, via the hi/lo split so each cos/sin argument is f32-exact
    (the ops.fft._phase_exp discipline, in-register).  Single home of
    the split for both twiddle orientations — the window-edge
    precision test pins this one body."""
    half = 1 << 15
    scale = jnp.float32(sign * 2.0 * np.pi / m)
    a = (r // half).astype(jnp.float32) * (half * scale)
    b = (r % half).astype(jnp.float32) * scale
    ca, sa = jnp.cos(a), jnp.sin(a)
    cb, sb = jnp.cos(b), jnp.sin(b)
    return ca * cb - sa * sb, sa * cb + ca * sb


def _col_fft_block(x2r, x2i, cref, *, kind, n1, bb, la, lb):
    """Column-axis leg DFT of one [n1(j1), bb(j2)] value-block pair
    (contract j1) — the column-native body shared by the packed
    (:func:`pass1_2d`) and raw-front (:func:`pass1_front`) pass-1
    kernels.  Returns the y[k1, d] pair [n1, bb]."""
    dg = PF.dot_mid
    if kind == "one":
        # small-leg: one DFT-matrix contraction over j1
        war, wai = cref[0][:], cref[1][:]
        yr = dg(war, x2r, 0) - dg(wai, x2i, 0)  # [n1(k1), bb]
        yi = dg(war, x2i, 0) + dg(wai, x2r, 0)
        return yr, yi
    # column-native two-level leg: both DFT contractions run against
    # the j1 axes of the block in place — no input transpose, no padded
    # intermediate, one dense 3D relayout at the end
    war_ref, wai_ref, wbr_ref, wbi_ref, twr_ref, twi_ref = cref
    x3r = x2r.reshape(la, lb, bb)
    x3i = x2i.reshape(la, lb, bb)
    war, wai = war_ref[:], wai_ref[:]
    # stage 1, contract j1a: A[j2, d, k1]
    ar = dg(x3r, war, 0) - dg(x3i, wai, 0)      # [lb, bb, la]
    ai = dg(x3r, wai, 0) + dg(x3i, war, 0)
    # inner twiddle tw[k1, j2] at [j2, 1, k1] orientation
    twr2 = twr_ref[:].T.reshape(lb, 1, la)
    twi2 = twi_ref[:].T.reshape(lb, 1, la)
    br = ar * twr2 - ai * twi2
    bi = ar * twi2 + ai * twr2
    # stage 2, contract j1b(lb): C[d, k1, k2]
    wbr, wbi = wbr_ref[:], wbi_ref[:]
    cr = dg(br, wbr, 0) - dg(bi, wbi, 0)        # [bb, la, lb]
    ci = dg(br, wbi, 0) + dg(bi, wbr, 0)
    # leg-natural index k = k2*la + k1 -> [k2, k1, d] -> [n1, bb]
    yr = jnp.transpose(cr, (2, 1, 0)).reshape(n1, bb)
    yi = jnp.transpose(ci, (2, 1, 0)).reshape(n1, bb)
    return yr, yi


def _pass1_kernel(re_ref, im_ref, *rest, n1, bb, la, lb, m, sign, kind):
    from jax.experimental import pallas as pl

    cref = rest[:-2]
    out_re_ref, out_im_ref = rest[-2:]
    j2_0 = pl.program_id(0) * bb
    yr, yi = _col_fft_block(re_ref[:], im_ref[:], cref, kind=kind,
                            n1=n1, bb=bb, la=la, lb=lb)
    # four-step twiddle at [k, d] orientation
    wr, wi = _fourstep_twiddle_t(n1, bb, m, sign, j2_0)
    out_re_ref[:] = yr * wr - yi * wi
    out_im_ref[:] = yr * wi + yi * wr


def _fourstep_twiddle_t(n1: int, cols_j2: int, m: int, sign: float, j2_0):
    """Four-step twiddle w[k1, d] = exp(sign*2*pi*i*k1*(j2_0 + d)/m) for
    k1 < n1, d < cols_j2 — the [n1, bb] layout the column-native pass-1
    writes — computed in-kernel from iota (k1*j2 < m <= 2^29 is exact in
    int32)."""
    k1 = jax.lax.broadcasted_iota(jnp.int32, (n1, cols_j2), 0)
    d = jax.lax.broadcasted_iota(jnp.int32, (n1, cols_j2), 1) + j2_0
    return _phase_cos_sin(d * k1, m, sign)


def _row_fft_block(xr, xi, cref, *, kind, n2, rb, la, lb):
    """Row-axis leg DFT of one [rb, n2] value-block pair (length-n2
    C2C along each row), natural order, as a flat [rb, n2] pair.  The
    two-level kind flattens the helper's [rb, la, lb] view in-kernel —
    a minor-lb reshape real Mosaic rejects, sanctioned here because
    every caller is either interpret-mode (CPU CI) or behind the
    FFUSE_MOSAIC_OK hardware-probe gate; the classic
    :func:`_pass2_kernel` path keeps the 3D-out-ref spelling."""
    dg = PF.dot_mid
    if kind == "one":
        wr, wi = cref[0][:], cref[1][:]
        yr = dg(xr, wr, 1) - dg(xi, wi, 1)      # [rb, n2]
        yi = dg(xr, wi, 1) + dg(xi, wr, 1)
        return yr, yi
    yr3, yi3 = PF.vmem_fft_rows(xr, xi, *[r[:] for r in cref],
                                la=la, lb=lb, rows=rb)
    return yr3.reshape(rb, n2), yi3.reshape(rb, n2)


def _pass2_kernel(re_ref, im_ref, *rest, n2, rb, la, lb, kind):
    cref = rest[:-2]
    out_re_ref, out_im_ref = rest[-2:]
    if kind == "one":
        yr, yi = _row_fft_block(re_ref[:], im_ref[:], cref, kind=kind,
                                n2=n2, rb=rb, la=la, lb=lb)
        out_re_ref[:] = yr
        out_im_ref[:] = yi
        return
    # output stays k1-major blocked (a natural-order [n2, rb] column
    # block would lane-pad rb -> 128 in VMEM, 8-32 MB per plane at
    # production n2) — callers restore order with unblock(), an XLA
    # transpose the next elementwise pass absorbs.  The helper returns
    # its [rb, la, lb] natural-flat view; the 3D out refs match and the
    # caller's flatten to [rb, n2] happens outside the pallas_call.
    yr, yi = PF.vmem_fft_rows(re_ref[:], im_ref[:], *[r[:] for r in cref],
                              la=la, lb=lb, rows=rb)
    out_re_ref[:] = yr
    out_im_ref[:] = yi




def pass1_2d(re2, im2, inverse: bool = False, interpret: bool = False):
    """Fused pass 1 on one [n1, n2]-viewed transform: column FFTs over
    j1 + four-step twiddle, intermediate B[k1, j2] as an [n1, n2] f32
    pair.  Split out so the staged 2^30 plan can run each pass as its
    own XLA program (pipeline/segment.py)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n1, n2 = re2.shape
    m = n1 * n2
    sign = 1.0 if inverse else -1.0
    bb = _block_cols(n1, n2)
    if n2 % bb:
        raise ValueError(f"pass-1 block {bb} must divide n2={n2}")
    kind1, la1, lb1, consts1 = _leg(n1, inverse)
    col_block = pl.BlockSpec((n1, bb), lambda i: (0, i),
                             memory_space=pltpu.VMEM)
    k1 = functools.partial(_pass1_kernel, n1=n1, bb=bb, la=la1, lb=lb1,
                           m=m, sign=sign, kind=kind1)
    mid_shape = jax.ShapeDtypeStruct((n1, n2), jnp.float32)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = PF.tpu_compiler_params(
            vmem_limit_bytes=_vmem_budget())
    return pl.pallas_call(
        k1,
        grid=(n2 // bb,),
        in_specs=[col_block, col_block] + _leg_specs(kind1, la1, lb1),
        out_specs=[col_block, col_block],
        out_shape=[mid_shape, mid_shape],
        interpret=interpret,
        **kwargs,
    )(re2, im2, *consts1)


def pass2_2d(br, bi, inverse: bool = False, interpret: bool = False):
    """Fused pass 2 on the [n1, n2] intermediate: row FFTs over j2.
    Output is [n1, n2] k1-major blocked (C[k1, k2]; the true transform
    index is k2*n1 + k1) — callers restore natural order with
    :func:`unblock`, whose XLA transpose fuses into their next
    elementwise pass."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n1, n2 = br.shape
    rb = _block_rows(n2, n1)
    if n1 % rb:
        raise ValueError(f"pass-2 block {rb} must divide n1={n1}")
    kind2, la2, lb2, consts2 = _leg(n2, inverse)
    row_block = pl.BlockSpec((rb, n2), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    if kind2 == "two":
        out_block = pl.BlockSpec((rb, la2, lb2), lambda i: (i, 0, 0),
                                 memory_space=pltpu.VMEM)
        out_shape = jax.ShapeDtypeStruct((n1, la2, lb2), jnp.float32)
    else:  # small-leg: the row block is already the natural-flat form
        out_block = row_block
        out_shape = jax.ShapeDtypeStruct((n1, n2), jnp.float32)
    k2 = functools.partial(_pass2_kernel, n2=n2, rb=rb, la=la2, lb=lb2,
                           kind=kind2)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = PF.tpu_compiler_params(
            vmem_limit_bytes=_vmem_budget())
    yr3, yi3 = pl.pallas_call(
        k2,
        grid=(n1 // rb,),
        in_specs=[row_block, row_block] + _leg_specs(kind2, la2, lb2),
        out_specs=[out_block, out_block],
        out_shape=[out_shape, out_shape],
        interpret=interpret,
        **kwargs,
    )(br, bi, *consts2)
    # contiguous [n1, la2, lb2] -> [n1, n2]: free metadata reshape
    return yr3.reshape(n1, n2), yi3.reshape(n1, n2)


def _fft2_2d(re2, im2, n1, n2, inverse, natural, interpret):
    """The two fused passes on one [n1, n2]-viewed transform; with
    ``natural`` the blocked result is unblocked by an XLA transpose
    (fused into the caller's consumer pass)."""
    br, bi = pass1_2d(re2, im2, inverse, interpret)
    yr, yi = pass2_2d(br, bi, inverse, interpret)
    if natural:
        return yr.T, yi.T
    return yr, yi


def pass1_ri(re: jnp.ndarray, im: jnp.ndarray, inverse: bool = False,
             interpret: bool = False):
    """Batched pass 1: [..., m] f32 pair -> [..., n1, n2] intermediate
    pair (the staged plan's (a)/(b) boundary representation)."""
    m = re.shape[-1]
    n1, n2 = _factor(m)
    lead = re.shape[:-1]
    re2 = re.reshape(-1, m)
    im2 = im.reshape(-1, m)
    outs = [pass1_2d(re2[b].reshape(n1, n2), im2[b].reshape(n1, n2),
                     inverse, interpret) for b in range(re2.shape[0])]
    br = jnp.stack([o[0] for o in outs]).reshape(*lead, n1, n2)
    bi = jnp.stack([o[1] for o in outs]).reshape(*lead, n1, n2)
    return br, bi


def pass2_ri(br: jnp.ndarray, bi: jnp.ndarray, inverse: bool = False,
             interpret: bool = False):
    """Batched pass 2: [..., n1, n2] intermediate pair -> [..., m]
    natural-order f32 pair."""
    n1, n2 = br.shape[-2], br.shape[-1]
    m = n1 * n2
    lead = br.shape[:-2]
    br2 = br.reshape(-1, n1, n2)
    bi2 = bi.reshape(-1, n1, n2)
    outs = [pass2_2d(br2[b], bi2[b], inverse, interpret)
            for b in range(br2.shape[0])]
    # unblock: C[k1, k2] -> natural k2*n1 + k1 (XLA transpose, fused
    # into the Hermitian post-process that consumes this)
    yr = jnp.stack([o[0].T.reshape(m) for o in outs]).reshape(*lead, m)
    yi = jnp.stack([o[1].T.reshape(m) for o in outs]).reshape(*lead, m)
    return yr, yi


def fft2_c2c_ri(re: jnp.ndarray, im: jnp.ndarray, inverse: bool = False,
                natural: bool = True, interpret: bool = False):
    """C2C FFT along the last axis of split re/im f32 [..., m] arrays in
    two fused Pallas passes.  Unnormalized both directions (ops.fft
    conventions).  ``natural=False`` returns the result in [n1, n2]
    k1-major blocked order (flatten index k1*n2 + k2; true index is
    k2*n1 + k1) for consumers that absorb the permutation — use
    :func:`unblock` to restore natural order.
    """
    m = re.shape[-1]
    fac = _factor(m)
    if fac is None:
        raise ValueError(f"pallas2 unsupported length {m}")
    n1, n2 = fac
    lead = re.shape[:-1]
    re2 = re.reshape(-1, m)
    im2 = im.reshape(-1, m)
    outs = [_fft2_2d(re2[b].reshape(n1, n2), im2[b].reshape(n1, n2),
                     n1, n2, inverse, natural, interpret)
            for b in range(re2.shape[0])]
    yr = jnp.stack([o[0].reshape(m) for o in outs])
    yi = jnp.stack([o[1].reshape(m) for o in outs])
    return yr.reshape(*lead, m), yi.reshape(*lead, m)


def fft2_c2c(x: jnp.ndarray, inverse: bool = False, natural: bool = True,
             interpret: bool = False) -> jnp.ndarray:
    """Complex convenience wrapper over :func:`fft2_c2c_ri`."""
    yr, yi = fft2_c2c_ri(jnp.real(x), jnp.imag(x), inverse, natural,
                         interpret)
    return jax.lax.complex(yr, yi)


def unblock(y: jnp.ndarray, m: int) -> jnp.ndarray:
    """[..., m] in k1-major blocked order (from ``natural=False``) ->
    natural order, as an XLA transpose the consumer's next elementwise
    pass can fuse with."""
    n1, n2 = _factor(m)
    y2 = y.reshape(*y.shape[:-1], n1, n2)
    return jnp.swapaxes(y2, -1, -2).reshape(*y.shape[:-1], m)


# ==================================================================
# front fusion: unpack -> window -> even/odd pack -> pass 1 in ONE
# kernel (raw bytes in, blocked intermediate out), and the whole
# spectrum tail (Hermitian + RFI s1 + chirp) as pass 2's epilogue.
# ==================================================================

# Pending on-chip Mosaic validation (tools_tpu_r9_queue.sh "ffuse
# probe" legs, then flip to True): the front kernels use the sub-byte
# lane interleave ops/pallas_kernels.UNPACK_MOSAIC_OK documents as
# unlowerable today, plus strided lane de-interleaves, an in-kernel
# minor-lb flatten (_row_fft_block) and a lane flip/roll — every one
# fine under interpret (CPU CI), each a real-Mosaic question.
# SRTB_PALLAS_FFUSE=1 opts in before the probe; front_fuse="on"
# (Config) forces regardless — the hardware A/B spelling.
FFUSE_MOSAIC_OK = False

# unpack variants the front kernel can spell in-register, and the
# sample widths each supports (ops/unpack.py semantics: positive =
# unsigned, negative = signed int8)
FFUSE_VARIANT_BITS = {
    "simple": (1, 2, 4, 8, -8),
    "interleaved_samples_2": (8, -8),
}


def ffuse_enabled() -> bool:
    """Whether front_fuse="auto" may resolve ON: the Mosaic probe flag
    or the env opt-in.  Deliberately NOT true merely under interpret —
    "auto" flipping every existing pallas2-staged config (and its
    pinned plan card) onto the new megakernel the moment the code
    landed would be a silent plan change; the staged_ffuse family,
    tests and ci force front_fuse="on" instead."""
    return FFUSE_MOSAIC_OK or \
        os.environ.get("SRTB_PALLAS_FFUSE", "") == "1"


def _front_unpack(b32, variant: str, nbits: int):
    """int32 byte block [n1, BB] -> per-stream (re, im) f32 sample
    blocks [n1, bb] in even/odd-packed order — the in-kernel mirror of
    ops.unpack + ops.fft.pack_even_odd.  Every value is a small exact
    integer, so any op order is value-identical to the XLA path; the
    lane interleave/de-interleave spellings are what FFUSE_MOSAIC_OK
    gates on real chips."""
    if nbits in (8, -8):
        vals = b32
        if nbits == -8:
            vals = vals - 2 * (vals & 0x80)  # u8 bits -> s8 value
        vals = vals.astype(jnp.float32)
    else:
        count = 8 // nbits
        mask = (1 << nbits) - 1
        # MSB-first fields (ref: unpack.hpp:43-140), interleaved back
        # to sample order along the lane axis
        fields = [((b32 >> (8 - nbits * (j + 1))) & mask)
                  .astype(jnp.float32) for j in range(count)]
        vals = jnp.stack(fields, axis=-1).reshape(
            b32.shape[0], b32.shape[1] * count)
    if variant == "interleaved_samples_2":
        # "1212" byte interleave: z_s[j] = x[4j+s] + i*x[4j+2+s]
        return [(vals[:, s::4], vals[:, 2 + s::4]) for s in range(2)]
    return [(vals[:, 0::2], vals[:, 1::2])]


def _pass1_front_kernel(byte_ref, *rest, n1, bb, la, lb, m, sign, kind,
                        variant, nbits, streams, windowed):
    from jax.experimental import pallas as pl

    idx = 0
    win = None
    if windowed:
        win = (rest[0], rest[1])
        idx = 2
    ncon = 6 if kind == "two" else 2
    cref = rest[idx:idx + ncon]
    outs = rest[idx + ncon:]
    step = pl.program_id(0)
    j2_0 = step * bb
    b32 = byte_ref[:].astype(jnp.int32)
    pairs = _front_unpack(b32, variant, nbits)
    wr4, wi4 = _fourstep_twiddle_t(n1, bb, m, sign, j2_0)
    for s, (re, im) in enumerate(pairs):
        if windowed:
            re = re * win[0][:]
            im = im * win[1][:]
        yr, yi = _col_fft_block(re, im, cref, kind=kind, n1=n1, bb=bb,
                                la=la, lb=lb)
        br = yr * wr4 - yi * wi4
        bi = yr * wi4 + yi * wr4
        outs[2 * s][:] = br
        outs[2 * s + 1][:] = bi
        # RFI-s1 mean-power pieces, accumulated while the block is in
        # VMEM (TPU grids are sequential): sum |B|^2 over the whole
        # intermediate plus the DC-bin partials F0 = sum_j2 B[0, j2],
        # as 128-lane partial vectors (finished in front_mean_power)
        s2_ref, f0r_ref, f0i_ref = outs[2 * streams + 3 * s:
                                        2 * streams + 3 * s + 3]

        @pl.when(step == 0)
        def _init(s2_ref=s2_ref, f0r_ref=f0r_ref, f0i_ref=f0i_ref):
            s2_ref[:] = jnp.zeros_like(s2_ref)
            f0r_ref[:] = jnp.zeros_like(f0r_ref)
            f0i_ref[:] = jnp.zeros_like(f0i_ref)

        p = br * br + bi * bi
        s2_ref[:] += p.sum(axis=0).reshape(bb // 128, 128).sum(axis=0,
                                                               keepdims=True)
        f0r_ref[:] += br[0:1, :].reshape(bb // 128, 128).sum(
            axis=0, keepdims=True)
        f0i_ref[:] += bi[0:1, :].reshape(bb // 128, 128).sum(
            axis=0, keepdims=True)


def pass1_front(raw: jnp.ndarray, *, m: int, streams: int, variant: str,
                nbits: int, window_eo=None, inverse: bool = False,
                interpret: bool = False):
    """Front-fused pass 1: the RAW uint8 segment is the kernel operand.

    Each grid step DMAs its column block of packed bytes, unpacks
    (``FFUSE_VARIANT_BITS``), multiplies the window, performs the
    even/odd half-size pack and the pass-1 column FFT + four-step
    twiddle in VMEM, and writes the blocked intermediate exactly once:
    HBM pass 1 = one raw-byte read + one blocked write.  The Parseval
    pieces of the RFI stage-1 mean power ride along as per-stream
    128-lane accumulators so stage (b) never re-reads anything
    spectrum-sized to evaluate the zap threshold.

    ``raw``: uint8 [streams * 2m * |nbits| / 8] (all streams
    interleaved, as read from file/UDP).  ``window_eo``: optional
    (w_even, w_odd) f32 [n1, n2] pair — the per-stream sample window
    split even/odd and viewed blocked (SegmentProcessor precomputes
    it).  Returns ``(br, bi, aux)``: [S, n1, n2] intermediate pair +
    [S, 3, 128] accumulators.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from srtb_tpu.ops import pallas_kernels as pk

    if nbits not in FFUSE_VARIANT_BITS.get(variant, ()):
        raise ValueError(
            f"front fuse unsupported for variant {variant!r} at "
            f"{nbits}-bit")
    fac = ffuse_factor(m)
    if fac is None:
        raise ValueError(f"front fuse unsupported length {m}")
    n1, n2 = fac
    sign = 1.0 if inverse else -1.0
    bb = _block_cols_front(n1, n2, streams, nbits,
                           window_eo is not None)
    if n2 % bb:
        raise ValueError(f"pass-1 block {bb} must divide n2={n2}")
    if bb % 128:
        # the accumulator reduction reshapes each block to
        # [bb // 128, 128] lanes
        raise ValueError(f"pass-1 front block {bb} must be a multiple "
                         "of 128")
    bits_per_col = 2 * streams * abs(nbits)  # one packed column = 2S samples
    if (n2 * bits_per_col) % 8 or (bb * bits_per_col) % 8:
        raise ValueError(f"byte-misaligned ffuse block {bb}x{bits_per_col}b")
    row_bytes = n2 * bits_per_col // 8
    blk_bytes = bb * bits_per_col // 8
    if raw.shape != (n1 * row_bytes,):
        raise ValueError(
            f"raw must be {n1 * row_bytes} bytes, got {raw.shape}")
    raw2 = raw.reshape(n1, row_bytes)
    kind, la, lb, consts = _leg(n1, inverse)

    byte_block = pl.BlockSpec((n1, blk_bytes), lambda i: (0, i),
                              memory_space=pltpu.VMEM)
    col_block = pl.BlockSpec((n1, bb), lambda i: (0, i),
                             memory_space=pltpu.VMEM)
    acc_block = pl.BlockSpec((1, 128), lambda i: (0, 0),
                             memory_space=pltpu.VMEM)
    in_specs = [byte_block]
    operands = [raw2]
    windowed = window_eo is not None
    if windowed:
        in_specs += [col_block, col_block]
        operands += [window_eo[0], window_eo[1]]
    in_specs += _leg_specs(kind, la, lb)
    operands += list(consts)
    mid = jax.ShapeDtypeStruct((n1, n2), jnp.float32)
    acc = jax.ShapeDtypeStruct((1, 128), jnp.float32)
    out_specs = [col_block] * (2 * streams) + [acc_block] * (3 * streams)
    out_shape = [mid] * (2 * streams) + [acc] * (3 * streams)
    kernel = functools.partial(
        _pass1_front_kernel, n1=n1, bb=bb, la=la, lb=lb, m=m, sign=sign,
        kind=kind, variant=variant, nbits=nbits, streams=streams,
        windowed=windowed)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = PF.tpu_compiler_params(
            vmem_limit_bytes=_vmem_budget())
    with pk._ob_mode(interpret):
        outs = pl.pallas_call(
            kernel,
            grid=(n2 // bb,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
            **kwargs,
        )(*operands)
    br = jnp.stack([outs[2 * s] for s in range(streams)])
    bi = jnp.stack([outs[2 * s + 1] for s in range(streams)])
    aux = jnp.stack([
        jnp.concatenate(outs[2 * streams + 3 * s:
                             2 * streams + 3 * s + 3], axis=0)
        for s in range(streams)])
    return br, bi, aux


def front_mean_power(aux: jnp.ndarray, n2: int, m: int) -> jnp.ndarray:
    """Per-stream RFI-s1 mean |X_k|^2 from the pass-1 accumulators
    ``aux [S, 3, 128]`` — rfi.mean_power_packed with the reduction
    moved one FFT level earlier: Parseval along the row transform
    gives sum|F|^2 = n2 * sum|B|^2, and F0 = sum_j2 B[0, j2].  Agrees
    with the packed form to f32 rounding (same ~1-ulp decision-flip
    caveat rfi.mean_power_packed documents)."""
    s2 = aux[:, 0, :].sum(axis=-1)
    f0r = aux[:, 1, :].sum(axis=-1)
    f0i = aux[:, 2, :].sum(axis=-1)
    return (n2 * s2 + 2.0 * f0r * f0i) / m


def _pass2_spec_kernel(*refs, n1, n2, rb, la, lb, m, kind, norm,
                       has_mask, has_premul, chirp):
    from jax.experimental import pallas as pl
    from srtb_tpu.ops import pallas_kernels as pk

    i = pl.program_id(0)
    a_re, a_im, b_re, b_im, c_re, c_im = refs[:6]
    pos = 6
    ncon = 6 if kind == "two" else 2
    cref = refs[pos:pos + ncon]
    pos += ncon
    thr_ref = refs[pos]
    mask_ref = refs[pos + 1]
    pos += 2
    pm = refs[pos:pos + 4] if has_premul else None
    out_re_ref, out_im_ref = refs[-2:]

    # row FFT of this step's k1 block
    zar, zai = _row_fft_block(a_re[:], a_im[:], cref, kind=kind,
                              n2=n2, rb=rb, la=la, lb=lb)
    # ... and of the MIRROR rows {n1-k1}: rows B[1:] of the reflected
    # block plus the first row of the next one ((G-i) mod G, which for
    # i == 0 wraps to this block's own row 0 — exactly the k1 = 0
    # self-mirror), reversed so Zm[t] is row n1-a-t
    mr = jnp.flip(jnp.concatenate([b_re[1:, :], c_re[0:1, :]], axis=0),
                  axis=0)
    mi = jnp.flip(jnp.concatenate([b_im[1:, :], c_im[0:1, :]], axis=0),
                  axis=0)
    zmr, zmi = _row_fft_block(mr, mi, cref, kind=kind, n2=n2, rb=rb,
                              la=la, lb=lb)
    # Hermitian mirror F[(m-k) mod m], k = k2*n1 + k1 blocked: a lane
    # flip (k2 -> n2-1-k2) for every k1 >= 1 row; the one global
    # k1 == 0 row additionally rolls by one (its mirror column is
    # (n2-k2) mod n2) — the blocked spelling of hermitian_rfft_post's
    # roll(flip(zf), 1)
    fmr = jnp.flip(zmr, axis=-1)
    fmi = jnp.flip(zmi, axis=-1)
    row0 = (jax.lax.broadcasted_iota(jnp.int32, (rb, 1), 0) == 0) \
        & (i == 0)
    fmr = jnp.where(row0, jnp.roll(fmr, 1, axis=-1), fmr)
    fmi = jnp.where(row0, jnp.roll(fmi, 1, axis=-1), fmi)
    fmi = -fmi  # conj
    even_re = 0.5 * (zar + fmr)
    even_im = 0.5 * (zai + fmi)
    odd_re = 0.5 * (zai - fmi)
    odd_im = -0.5 * (zar - fmr)
    if pm is not None:
        cr_, ci_, cwr, cwi = [r[:] for r in pm]
        xr = (cr_ * even_re - ci_ * even_im) \
            + (cwr * odd_re - cwi * odd_im)
        xi = (cr_ * even_im + ci_ * even_re) \
            + (cwr * odd_im + cwi * odd_re)
        k_int = None
    else:
        # true bin index of each blocked element (int32-exact, m <= 2^29)
        k_int = (i * rb
                 + jax.lax.broadcasted_iota(jnp.int32, (rb, n2), 0)) \
            + jax.lax.broadcasted_iota(jnp.int32, (rb, n2), 1) * n1
        wtr, wti = _phase_cos_sin(k_int, 2 * m, -1.0)
        xr = even_re + (wtr * odd_re - wti * odd_im)
        xi = even_im + (wtr * odd_im + wti * odd_re)
    # RFI stage 1 (rfi.mitigate_rfi_s1_given_mean): zap bins whose
    # power exceeds threshold*mean (thr holds the product), scale
    # survivors by the normalization coefficient, manual mask
    power = xr * xr + xi * xi
    scale = jnp.where(power <= thr_ref[0], jnp.float32(norm), 0.0)
    if has_mask:
        scale = scale * mask_ref[:]
    xr = xr * scale
    xi = xi * scale
    if chirp is not None and pm is None:
        # bankless: exact per-element df64 chirp phase in-register —
        # the blocked lanes stride k by n1, so the anchored-Taylor
        # fast path's contiguous-span premise doesn't hold here
        i_hi = (k_int & ~0xFFF).astype(jnp.float32)
        i_lo = (k_int & 0xFFF).astype(jnp.float32)
        ph = pk._chirp_phase_block(i_hi, i_lo, chirp["f_min"],
                                   chirp["df"], chirp["f_c"],
                                   chirp["dm"])
        c = jnp.cos(ph)
        s = jnp.sin(ph)
        xr, xi = xr * c - xi * s, xr * s + xi * c
    out_re_ref[:] = xr
    out_im_ref[:] = xi


def pass2_spectrum(br: jnp.ndarray, bi: jnp.ndarray, *, thr, norm: float,
                   mask_blocked=None, premul_blocked=None, chirp=None,
                   interpret: bool = False):
    """Pass 2 with the whole spectrum tail as its epilogue: row FFT
    over the [n1, n2] intermediate, the Hermitian R2C post-process
    assembled in-kernel (each grid step also transforms its mirror
    rows — ~2x the pass-2 FLOPs, which the dispatch-bound pipeline has
    headroom for, in exchange for never materializing the packed C2C
    spectrum), RFI-s1 zap/normalize/manual-mask against ``thr`` =
    threshold·mean (from :func:`front_mean_power`), and the
    dedispersion chirp — ``premul_blocked`` = (c_re, c_im, cw_re,
    cw_im) blocked [n1, n2] banks (the SegmentProcessor._premul_bank
    precombination), or ``chirp`` = dict(f_min, df, f_c, dm) for the
    bankless in-register df64 phase.  Emits the dedispersed spectrum
    directly, in k1-major blocked order (the consumer unblocks with a
    metadata-view transpose fused into its first read).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from srtb_tpu.ops import pallas_kernels as pk

    n1, n2 = br.shape
    m = n1 * n2
    has_mask = mask_blocked is not None
    has_premul = premul_blocked is not None
    rb = _block_rows_spec(n2, n1, has_mask, has_premul)
    if n1 % rb:
        raise ValueError(f"pass-2 block {rb} must divide n1={n1}")
    grid_n = n1 // rb
    kind, la, lb, consts = _leg(n2, inverse=False)
    row_block = pl.BlockSpec((rb, n2), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    mirror_block = pl.BlockSpec((rb, n2), lambda i: (grid_n - 1 - i, 0),
                                memory_space=pltpu.VMEM)
    next_block = pl.BlockSpec((rb, n2),
                              lambda i: ((grid_n - i) % grid_n, 0),
                              memory_space=pltpu.VMEM)
    in_specs = [row_block, row_block, mirror_block, mirror_block,
                next_block, next_block]
    operands = [br, bi, br, bi, br, bi]
    in_specs += _leg_specs(kind, la, lb)
    operands += list(consts)
    in_specs += [pl.BlockSpec(memory_space=pltpu.SMEM)]
    operands += [jnp.asarray(thr, jnp.float32).reshape(1)]
    if has_mask:
        in_specs += [row_block]
        operands += [mask_blocked]
    else:  # placeholder tile, never read by the kernel
        in_specs += [pl.BlockSpec((1, n2), lambda i: (0, 0),
                                  memory_space=pltpu.VMEM)]
        operands += [jnp.zeros((1, n2), jnp.float32)]
    if has_premul:
        in_specs += [row_block] * 4
        operands += list(premul_blocked)
    kernel = functools.partial(
        _pass2_spec_kernel, n1=n1, n2=n2, rb=rb, la=la, lb=lb, m=m,
        kind=kind, norm=np.float32(norm), has_mask=has_mask,
        has_premul=has_premul,
        chirp=None if chirp is None else dict(chirp))
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = PF.tpu_compiler_params(
            vmem_limit_bytes=_vmem_budget())
    out = jax.ShapeDtypeStruct((n1, n2), jnp.float32)
    with pk._ob_mode(interpret):
        sr, si = pl.pallas_call(
            kernel,
            grid=(grid_n,),
            in_specs=in_specs,
            out_specs=[row_block, row_block],
            out_shape=[out, out],
            interpret=interpret,
            **kwargs,
        )(*operands)
    return sr, si
