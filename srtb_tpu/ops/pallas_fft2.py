"""Fused two-pass Pallas four-step C2C: the whole large-m transform in
two kernel passes plus one fusable transpose.

The existing "pallas" strategy runs the four-step legs (ops/pallas_fft)
inside XLA's decomposition: transpose, leg FFT, twiddle multiply,
transpose, leg FFT, transpose — each arrow a full HBM pass, ~6 round
trips for the C2C (measured 1481 vs monolithic's 1746 Msamples/s at
2^27, PERF_TPU.jsonl).  This module fuses each leg's surrounding
layout work *into the leg's kernel* so the C2C is two passes total:

  pass 1 (grid over j2 column blocks of z viewed [n1, n2] row-major):
    DMA a strided [n1, bb] column block into VMEM and run the two-level
    DFT decimation over j1 *column-natively*: both contractions are
    dot_generals against the j1 axes of the [la, lb, bb] view in place
    (no 2D transpose, every intermediate lane-dense), then the
    four-step twiddle w[k1, j2] = exp(s*2*pi*i*k1*j2/m) computed
    *in-kernel* from iota with the exact hi/lo phase split (no m-sized
    table exists anywhere), and DMA out: intermediate B[k1, j2] laid
    out [n1, n2].  (A transpose-to-rows spelling existed for hardware
    A/B until round 5's real-Mosaic acceptance run: its in-kernel
    flatten of the assembled row is a minor-lb reshape Mosaic rejects,
    so the column-native form is now the one spelling.)

  pass 2 (grid over k1 row blocks):
    DMA a contiguous [rb, n2] row block, run the row FFT over j2, store
    C[k1, k2] row-major.  The k1-major blocked order is deliberate: a
    natural-order [n2, rb] output block would lane-pad rb -> 128 in
    VMEM (8-32 MB/plane at production n2), so the blocked->natural
    permutation is instead an XLA transpose (``unblock``) that fuses
    into the consumer's next pass — the Hermitian post-process here.

Two kernel passes plus one fusable transpose, versus ~6 separate HBM
round trips for the XLA-orchestrated form.

No XLA FFT op appears anywhere in this path — which also makes it a
workaround candidate for the XLA TPU compiler SIGSEGV on the 2^30
staged blocked shape (PERF.md).  Like every FFT backend here it is
unnormalized in both directions and held to the same float64 oracle
tests (tests/test_pallas_fft2.py); the TPU answer to the reference's
single-call vendor FFTs for full segments (ref: fft/fft.hpp:54-160,
fft_pipe.hpp:44-78).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from srtb_tpu.ops import fft as F
from srtb_tpu.ops import pallas_fft as PF


def _factor(m: int, strict: bool = True):
    """m = n1 * n2 with n1 the resident-column length (the whole n1 axis
    of a [n1, bb] block must fit VMEM, so n1 stays small) and n2 a row
    length the two-level kernel handles.  Both need la=128 splits with
    lb >= 32 to bound sublane padding, hence n1 in {4096, 8192} and
    n2 in [4096, 65536]: m in [2^24, 2^29] — exactly the segment sizes
    where monolithic XLA falters (PERF.md).  SRTB_PALLAS2_N1 pins n1
    for hardware A/B (a smaller n1 halves the padded pass-1 block refs
    — the fallback axis if the default plan misses VMEM on chip)."""
    if m & (m - 1):
        return None
    env = os.environ.get("SRTB_PALLAS2_N1")
    if env:
        try:
            n1 = int(env)
        except ValueError:
            n1 = 0
        if n1 <= 0 or n1 & (n1 - 1):
            raise ValueError(
                f"SRTB_PALLAS2_N1={env!r} must be a positive power of two")
        if PF._split_la_lb(n1) is None:
            # as loud as the parse error: a pow2 outside the leg range
            # must not masquerade as an "unsupported size" downstream
            raise ValueError(
                f"SRTB_PALLAS2_N1={n1} outside the leg-FFT range "
                "[4096, 65536]")
        cands = (n1,)
    else:
        cands = (4096, 8192)
    for n1 in cands:
        n2 = m // n1
        if m % n1 == 0 and PF._split_la_lb(n1) and 4096 <= n2 <= 65536:
            return n1, n2
    if env and strict:
        # the pin passed the pow2/leg-range checks above but fails for
        # THIS m — at kernel-build time an explicit knob must not
        # silently degrade to "unsupported size" (and thence the xla
        # fallback).  Boolean probes (``supported``) pass strict=False:
        # dispatchers ask about many sizes and a pin that doesn't fit a
        # probed size just means "not this path for this size".
        n1 = cands[0]
        if m % n1:
            raise ValueError(
                f"SRTB_PALLAS2_N1={n1} does not divide m={m}")
        raise ValueError(
            f"SRTB_PALLAS2_N1={n1} leaves n2={m // n1} outside the "
            "row-FFT range [4096, 65536] "
            f"for m={m}")
    return None


def supported(m: int) -> bool:
    return _factor(m, strict=False) is not None


def require_pin_fit(m: int) -> None:
    """Dispatchers call this in their not-supported fallback branch:
    when SRTB_PALLAS2_N1 is set and is the *reason* ``m`` is
    unsupported, raise the strict pin error instead of letting the
    operator's explicit A/B knob silently measure the fallback path.
    No-op when the pin is unset (the documented tiny-config fallback)
    or when m is unsupported for pin-independent reasons (non-pow2)."""
    if os.environ.get("SRTB_PALLAS2_N1"):
        _factor(m, strict=True)


def _vmem_budget() -> int:
    """Total VMEM bytes each kernel's plan may assume.  The round-2
    measurements ran on v5e, whose physical VMEM is 128 MiB/core;
    Mosaic's *default* scoped-vmem limit is far lower, so both
    pallas_calls pass an explicit ``vmem_limit_bytes`` alongside blocks
    sized by the padded-footprint model below.  Default 80 MiB leaves
    headroom for Mosaic internal scratch; SRTB_PALLAS2_VMEM_MB is the
    hardware A/B knob (a 16 MiB-era budget cannot fit ANY pass-1 block:
    the padded minimum 2*4*n1*128*4 B is 16 MiB at n1=4096 alone).
    Parsed + validated once, like pallas_fft._vmem_mb: a degenerate
    setting must fail loudly here, not as floor-zero blocks plus a
    nonpositive vmem_limit_bytes handed to Mosaic."""
    env = os.environ.get("SRTB_PALLAS2_VMEM_MB", "80")
    try:
        mb = int(env)
    except ValueError:
        mb = 0
    if mb <= 0:
        raise ValueError(
            f"SRTB_PALLAS2_VMEM_MB={env!r} must be a positive integer "
            "(MiB of VMEM the two-pass plan may assume)")
    return mb << 20


def _leg_const_bytes(la: int, lb: int) -> int:
    """Padded VMEM bytes of the six leg-FFT constant refs
    (war/wai [la,la], wbr/wbi [lb,lb], twr/twi [la,lb]) — lb < 128
    lane-pads its minor dim."""
    plb = max(lb, 128)
    return 4 * (2 * la * la + 2 * lb * plb + 2 * la * plb)


def _pass1_bytes(n1: int, bb: int) -> int:
    """Padded-VMEM footprint model for one pass-1 grid step: the four
    [n1, bb] block refs are double-buffered by the Pallas pipeline and
    lane-pad bb -> 128 (the round-3 review catch: logical-words sizing
    undercounted small-bb blocks 4x at n1=8192), plus the peak live
    column-native kernel intermediates, plus the leg consts."""
    la, lb = PF._split_la_lb(n1)
    refs = 2 * 4 * n1 * max(bb, 128) * 4
    # dense [lb, bb, la]/[bb, la, lb] stages; stage-2 outputs carry
    # minor dim lb (pads to 128), the final relayout minor dim bb
    live = (4 * la * lb * bb * 4
            + 2 * bb * la * max(lb, 128) * 4
            + 2 * n1 * max(bb, 128) * 4)
    return refs + live + _leg_const_bytes(la, lb)


def _pass2_bytes(n2: int, rb: int) -> int:
    """Same model for one pass-2 grid step: the [rb, n2] input blocks
    are lane-dense (rb is the sublane dim, min tile 8); the 3D output
    blocks and helper stages carry minor dim lb = n2/128, which pads to
    128 on the small-n2 end."""
    la, lb = PF._split_la_lb(n2)
    plb = max(lb, 128)
    refs = 2 * 2 * max(rb, 8) * (n2 + la * plb) * 4
    live = 6 * la * rb * plb * 4
    return refs + live + _leg_const_bytes(la, lb)


def _pick_block(candidates, fits, floor: int) -> int:
    """Largest candidate whose modeled footprint fits the budget; the
    floor (the minimum meaningful block) when none does — shrinking
    below it cannot reduce the padded refs, so a non-fitting floor is a
    hardware question for vmem_limit_bytes, not a sizing one."""
    for c in candidates:
        if fits(c):
            return c
    return floor


def _block_cols(n1: int, n2: int) -> int:
    """Pass-1 column-block width (= rows of the in-kernel leg FFT):
    largest power-of-two divisor of n2 in [128, 1024] that fits the
    padded-footprint budget.  bb >= 128 always — below that the block's
    lane padding keeps VMEM cost flat while throwing away strided-DMA
    width.  SRTB_PALLAS2_BB overrides absolutely (hardware tuning)."""
    env = os.environ.get("SRTB_PALLAS2_BB")
    if env:
        return int(env)
    budget = _vmem_budget()
    cands = [c for c in (1024, 512, 256, 128) if n2 % c == 0]
    return _pick_block(
        cands, lambda c: _pass1_bytes(n1, c) <= budget, 128)


def _block_rows(n2: int, n1: int) -> int:
    """Pass-2 row-block height: largest power-of-two divisor of n1 in
    [8, 256] that fits the budget (rb is the sublane dim — lane-dense
    at any size, so small rb is cheap and correct here)."""
    env = os.environ.get("SRTB_PALLAS2_RB")
    if env:
        return int(env)
    budget = _vmem_budget()
    cands = [c for c in (256, 128, 64, 32, 16, 8) if n1 % c == 0]
    return _pick_block(
        cands, lambda c: _pass2_bytes(n2, c) <= budget, 8)


def _phase_cos_sin(r, m: int, sign: float):
    """(cos, sin) of sign*2*pi*r/m for an int32 residue array r < m
    <= 2^29, via the hi/lo split so each cos/sin argument is f32-exact
    (the ops.fft._phase_exp discipline, in-register).  Single home of
    the split for both twiddle orientations — the window-edge
    precision test pins this one body."""
    half = 1 << 15
    scale = jnp.float32(sign * 2.0 * np.pi / m)
    a = (r // half).astype(jnp.float32) * (half * scale)
    b = (r % half).astype(jnp.float32) * scale
    ca, sa = jnp.cos(a), jnp.sin(a)
    cb, sb = jnp.cos(b), jnp.sin(b)
    return ca * cb - sa * sb, sa * cb + ca * sb


def _pass1_kernel(re_ref, im_ref, war_ref, wai_ref, wbr_ref, wbi_ref,
                  twr_ref, twi_ref, out_re_ref, out_im_ref, *,
                  n1, bb, la, lb, m, sign):
    from jax.experimental import pallas as pl

    j2_0 = pl.program_id(0) * bb
    # column-native: both DFT contractions run against the j1 axes
    # of the [n1(j1), bb(j2)] block in place — no input transpose,
    # no padded intermediate, one dense 3D relayout at the end
    dg = PF.dot_mid
    x3r = re_ref[:].reshape(la, lb, bb)
    x3i = im_ref[:].reshape(la, lb, bb)
    war, wai = war_ref[:], wai_ref[:]
    # stage 1, contract j1a: A[j2, d, k1]
    ar = dg(x3r, war, 0) - dg(x3i, wai, 0)      # [lb, bb, la]
    ai = dg(x3r, wai, 0) + dg(x3i, war, 0)
    # inner twiddle tw[k1, j2] at [j2, 1, k1] orientation
    twr2 = twr_ref[:].T.reshape(lb, 1, la)
    twi2 = twi_ref[:].T.reshape(lb, 1, la)
    br = ar * twr2 - ai * twi2
    bi = ar * twi2 + ai * twr2
    # stage 2, contract j1b(lb): C[d, k1, k2]
    wbr, wbi = wbr_ref[:], wbi_ref[:]
    cr = dg(br, wbr, 0) - dg(bi, wbi, 0)        # [bb, la, lb]
    ci = dg(br, wbi, 0) + dg(bi, wbr, 0)
    # leg-natural index k = k2*la + k1 -> [k2, k1, d] -> [n1, bb]
    yr = jnp.transpose(cr, (2, 1, 0)).reshape(n1, bb)
    yi = jnp.transpose(ci, (2, 1, 0)).reshape(n1, bb)
    # four-step twiddle at [k, d] orientation
    wr, wi = _fourstep_twiddle_t(n1, bb, m, sign, j2_0)
    out_re_ref[:] = yr * wr - yi * wi
    out_im_ref[:] = yr * wi + yi * wr


def _fourstep_twiddle_t(n1: int, cols_j2: int, m: int, sign: float, j2_0):
    """Four-step twiddle w[k1, d] = exp(sign*2*pi*i*k1*(j2_0 + d)/m) for
    k1 < n1, d < cols_j2 — the [n1, bb] layout the column-native pass-1
    writes — computed in-kernel from iota (k1*j2 < m <= 2^29 is exact in
    int32)."""
    k1 = jax.lax.broadcasted_iota(jnp.int32, (n1, cols_j2), 0)
    d = jax.lax.broadcasted_iota(jnp.int32, (n1, cols_j2), 1) + j2_0
    return _phase_cos_sin(d * k1, m, sign)


def _pass2_kernel(re_ref, im_ref, war_ref, wai_ref, wbr_ref, wbi_ref,
                  twr_ref, twi_ref, out_re_ref, out_im_ref, *,
                  n2, rb, la, lb):
    # output stays k1-major blocked (a natural-order [n2, rb] column
    # block would lane-pad rb -> 128 in VMEM, 8-32 MB per plane at
    # production n2) — callers restore order with unblock(), an XLA
    # transpose the next elementwise pass absorbs.  The helper returns
    # its [rb, la, lb] natural-flat view; the 3D out refs match and the
    # caller's flatten to [rb, n2] happens outside the pallas_call.
    yr, yi = PF.vmem_fft_rows(re_ref[:], im_ref[:], war_ref[:],
                              wai_ref[:], wbr_ref[:], wbi_ref[:],
                              twr_ref[:], twi_ref[:],
                              la=la, lb=lb, rows=rb)
    out_re_ref[:] = yr
    out_im_ref[:] = yi




def pass1_2d(re2, im2, inverse: bool = False, interpret: bool = False):
    """Fused pass 1 on one [n1, n2]-viewed transform: column FFTs over
    j1 + four-step twiddle, intermediate B[k1, j2] as an [n1, n2] f32
    pair.  Split out so the staged 2^30 plan can run each pass as its
    own XLA program (pipeline/segment.py)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n1, n2 = re2.shape
    m = n1 * n2
    sign = 1.0 if inverse else -1.0
    bb = _block_cols(n1, n2)
    if n2 % bb:
        raise ValueError(f"pass-1 block {bb} must divide n2={n2}")
    la1, lb1, consts1 = PF.leg_consts(n1, inverse)
    col_block = pl.BlockSpec((n1, bb), lambda i: (0, i),
                             memory_space=pltpu.VMEM)
    k1 = functools.partial(_pass1_kernel, n1=n1, bb=bb, la=la1, lb=lb1,
                           m=m, sign=sign)
    mid_shape = jax.ShapeDtypeStruct((n1, n2), jnp.float32)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = PF.tpu_compiler_params(
            vmem_limit_bytes=_vmem_budget())
    return pl.pallas_call(
        k1,
        grid=(n2 // bb,),
        in_specs=[col_block, col_block] + PF.leg_const_specs(la1, lb1),
        out_specs=[col_block, col_block],
        out_shape=[mid_shape, mid_shape],
        interpret=interpret,
        **kwargs,
    )(re2, im2, *consts1)


def pass2_2d(br, bi, inverse: bool = False, interpret: bool = False):
    """Fused pass 2 on the [n1, n2] intermediate: row FFTs over j2.
    Output is [n1, n2] k1-major blocked (C[k1, k2]; the true transform
    index is k2*n1 + k1) — callers restore natural order with
    :func:`unblock`, whose XLA transpose fuses into their next
    elementwise pass."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n1, n2 = br.shape
    rb = _block_rows(n2, n1)
    if n1 % rb:
        raise ValueError(f"pass-2 block {rb} must divide n1={n1}")
    la2, lb2, consts2 = PF.leg_consts(n2, inverse)
    row_block = pl.BlockSpec((rb, n2), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    out_block = pl.BlockSpec((rb, la2, lb2), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM)
    k2 = functools.partial(_pass2_kernel, n2=n2, rb=rb, la=la2, lb=lb2)
    out_shape = jax.ShapeDtypeStruct((n1, la2, lb2), jnp.float32)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = PF.tpu_compiler_params(
            vmem_limit_bytes=_vmem_budget())
    yr3, yi3 = pl.pallas_call(
        k2,
        grid=(n1 // rb,),
        in_specs=[row_block, row_block] + PF.leg_const_specs(la2, lb2),
        out_specs=[out_block, out_block],
        out_shape=[out_shape, out_shape],
        interpret=interpret,
        **kwargs,
    )(br, bi, *consts2)
    # contiguous [n1, la2, lb2] -> [n1, n2]: free metadata reshape
    return yr3.reshape(n1, n2), yi3.reshape(n1, n2)


def _fft2_2d(re2, im2, n1, n2, inverse, natural, interpret):
    """The two fused passes on one [n1, n2]-viewed transform; with
    ``natural`` the blocked result is unblocked by an XLA transpose
    (fused into the caller's consumer pass)."""
    br, bi = pass1_2d(re2, im2, inverse, interpret)
    yr, yi = pass2_2d(br, bi, inverse, interpret)
    if natural:
        return yr.T, yi.T
    return yr, yi


def pass1_ri(re: jnp.ndarray, im: jnp.ndarray, inverse: bool = False,
             interpret: bool = False):
    """Batched pass 1: [..., m] f32 pair -> [..., n1, n2] intermediate
    pair (the staged plan's (a)/(b) boundary representation)."""
    m = re.shape[-1]
    n1, n2 = _factor(m)
    lead = re.shape[:-1]
    re2 = re.reshape(-1, m)
    im2 = im.reshape(-1, m)
    outs = [pass1_2d(re2[b].reshape(n1, n2), im2[b].reshape(n1, n2),
                     inverse, interpret) for b in range(re2.shape[0])]
    br = jnp.stack([o[0] for o in outs]).reshape(*lead, n1, n2)
    bi = jnp.stack([o[1] for o in outs]).reshape(*lead, n1, n2)
    return br, bi


def pass2_ri(br: jnp.ndarray, bi: jnp.ndarray, inverse: bool = False,
             interpret: bool = False):
    """Batched pass 2: [..., n1, n2] intermediate pair -> [..., m]
    natural-order f32 pair."""
    n1, n2 = br.shape[-2], br.shape[-1]
    m = n1 * n2
    lead = br.shape[:-2]
    br2 = br.reshape(-1, n1, n2)
    bi2 = bi.reshape(-1, n1, n2)
    outs = [pass2_2d(br2[b], bi2[b], inverse, interpret)
            for b in range(br2.shape[0])]
    # unblock: C[k1, k2] -> natural k2*n1 + k1 (XLA transpose, fused
    # into the Hermitian post-process that consumes this)
    yr = jnp.stack([o[0].T.reshape(m) for o in outs]).reshape(*lead, m)
    yi = jnp.stack([o[1].T.reshape(m) for o in outs]).reshape(*lead, m)
    return yr, yi


def fft2_c2c_ri(re: jnp.ndarray, im: jnp.ndarray, inverse: bool = False,
                natural: bool = True, interpret: bool = False):
    """C2C FFT along the last axis of split re/im f32 [..., m] arrays in
    two fused Pallas passes.  Unnormalized both directions (ops.fft
    conventions).  ``natural=False`` returns the result in [n1, n2]
    k1-major blocked order (flatten index k1*n2 + k2; true index is
    k2*n1 + k1) for consumers that absorb the permutation — use
    :func:`unblock` to restore natural order.
    """
    m = re.shape[-1]
    fac = _factor(m)
    if fac is None:
        raise ValueError(f"pallas2 unsupported length {m}")
    n1, n2 = fac
    lead = re.shape[:-1]
    re2 = re.reshape(-1, m)
    im2 = im.reshape(-1, m)
    outs = [_fft2_2d(re2[b].reshape(n1, n2), im2[b].reshape(n1, n2),
                     n1, n2, inverse, natural, interpret)
            for b in range(re2.shape[0])]
    yr = jnp.stack([o[0].reshape(m) for o in outs])
    yi = jnp.stack([o[1].reshape(m) for o in outs])
    return yr.reshape(*lead, m), yi.reshape(*lead, m)


def fft2_c2c(x: jnp.ndarray, inverse: bool = False, natural: bool = True,
             interpret: bool = False) -> jnp.ndarray:
    """Complex convenience wrapper over :func:`fft2_c2c_ri`."""
    yr, yi = fft2_c2c_ri(jnp.real(x), jnp.imag(x), inverse, natural,
                         interpret)
    return jax.lax.complex(yr, yi)


def unblock(y: jnp.ndarray, m: int) -> jnp.ndarray:
    """[..., m] in k1-major blocked order (from ``natural=False``) ->
    natural order, as an XLA transpose the consumer's next elementwise
    pass can fuse with."""
    n1, n2 = _factor(m)
    y2 = y.reshape(*y.shape[:-1], n1, n2)
    return jnp.swapaxes(y2, -1, -2).reshape(*y.shape[:-1], m)
