"""Spectrum waterfall simplification: resample + normalize + colormap.

Re-design of the reference's resample_spectrum kernels
(ref: spectrum/simplify_spectrum.hpp:137-230 v1 math; 423-620 v3 is the
same math with GPU work-group tuning) for the MXU: the 2-D downsample is
area-weighted along frequency and linearly interpolated along time, which
is exactly two banded weight matrices — so the whole resample becomes

    out[H, W] = W_freq[H, in_h] @ power[in_h, in_w] @ W_time[in_w, W]

two matmuls that XLA tiles onto the systolic array, instead of the
reference's one-work-group-per-output-pixel tree reduction.

Normalization (ref: simplify_spectrum.hpp:627-644) and the ARGB colormap
(ref: simplify_spectrum.hpp:652-731, colors config.hpp:60-68) follow.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# GUI colors (ref: config.hpp:60-68)
OPAQUE = 0xFF000000
COLOR_0 = 0x1F1E33 | OPAQUE
COLOR_1 = 0x33E1F1 | OPAQUE
COLOR_OVERFLOW = 0xE0E1CC | OPAQUE


def time_interp_weights(in_w: int, out_w: int,
                        dtype=np.float32) -> np.ndarray:
    """[in_w, out_w] linear-interpolation weights along the time axis
    (ref: simplify_spectrum.hpp:152-181: x1 = x2/out_w*in_w, split between
    floor(x1) and floor(x1)+1)."""
    w = np.zeros((in_w, out_w), dtype=np.float64)
    for x2 in range(out_w):
        x1 = x2 / out_w * in_w
        left = int(np.floor(x1))
        right = left + 1
        left_portion = (left + 1) - x1
        right_portion = x1 - left
        w[min(left, in_w - 1), x2] += left_portion
        w[min(right, in_w - 1), x2] += right_portion
    return w.astype(dtype)


def freq_area_weights(in_h: int, out_h: int,
                      dtype=np.float32) -> np.ndarray:
    """[out_h, in_h] area-sum weights along the frequency axis
    (ref: simplify_spectrum.hpp:183-225: output row y2 sums input rows in
    [y2/out_h*in_h, (y2+1)/out_h*in_h) with fractional edge weights)."""
    w = np.zeros((out_h, in_h), dtype=np.float64)
    for y2 in range(out_h):
        up_acc = y2 / out_h * in_h
        down_acc = (y2 + 1) / out_h * in_h
        up = int(np.ceil(up_acc))
        down = int(np.floor(down_acc))
        if up > up_acc:
            w[y2, up - 1] += up - up_acc
        w[y2, up:down] += 1.0
        if down_acc > down and down < in_h:
            w[y2, down] += down_acc - down
    return w.astype(dtype)


def resample_spectrum(power: jnp.ndarray, w_freq: jnp.ndarray,
                      w_time: jnp.ndarray) -> jnp.ndarray:
    """power [in_h(freq), in_w(time)] -> [out_h, out_w] via two matmuls."""
    return (w_freq @ power) @ w_time


def normalize_by_average(img: jnp.ndarray) -> jnp.ndarray:
    """Scale so the average maps to 0.5 (ref: simplify_spectrum.hpp:627-644);
    skipped when the average is ~0."""
    avg = jnp.mean(img)
    coeff = jnp.where(avg > jnp.finfo(img.dtype).eps, 1.0 / (2.0 * avg), 1.0)
    return img * coeff


def _argb_components(argb: int):
    return ((argb >> 24) & 0xFF, (argb >> 16) & 0xFF,
            (argb >> 8) & 0xFF, argb & 0xFF)


def generate_pixmap(intensity: jnp.ndarray, color_0: int = COLOR_0,
                    color_1: int = COLOR_1,
                    color_overflow: int = COLOR_OVERFLOW) -> jnp.ndarray:
    """Map intensities in [0,1] to ARGB32 by per-channel lerp; out-of-range
    values get the overflow color (ref: simplify_spectrum.hpp:652-731)."""
    comps_0 = _argb_components(color_0)
    comps_1 = _argb_components(color_1)
    in_range = (intensity >= 0) & (intensity <= 1)
    x = jnp.clip(intensity, 0.0, 1.0)
    out = jnp.zeros(intensity.shape, dtype=jnp.uint32)
    for shift, c0, c1 in zip((24, 16, 8, 0), comps_0, comps_1):
        chan = ((1.0 - x) * c0 + x * c1).astype(jnp.uint32)
        out = out | (chan << shift)
    overflow = jnp.uint32(color_overflow)
    return jnp.where(in_range, out, overflow)


# ----------------------------------------------------------------
# numpy golden model of the reference kernel (for tests)
# ----------------------------------------------------------------

def resample_oracle(power: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Direct per-pixel transliteration of the v1 kernel semantics."""
    in_h, in_w = power.shape
    out = np.zeros((out_h, out_w), dtype=np.float64)
    for y2 in range(out_h):
        for x2 in range(out_w):
            x1 = x2 / out_w * in_w
            left = int(np.floor(x1))
            right = left + 1
            lp = (left + 1) - x1
            rp = x1 - left

            def sample(y):
                r = power[y, min(right, in_w - 1)]
                return lp * power[y, left] + rp * r

            up_acc = y2 / out_h * in_h
            down_acc = (y2 + 1) / out_h * in_h
            up = int(np.ceil(up_acc))
            down = int(np.floor(down_acc))
            s = 0.0
            if up > up_acc:
                s += (up - up_acc) * sample(up - 1)
            for y in range(up, down):
                s += sample(y)
            if down_acc > down and down < in_h:
                s += (down_acc - down) * sample(down)
            out[y2, x2] = s
    return out
