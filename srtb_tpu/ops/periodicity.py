"""Periodicity search: harmonic-summed power spectrum + phase folding.

The module set of the FPGA pulsar-search composition paper (PAPERS.md,
*Combining Multiple Optimised FPGA-based Pulsar Search Modules Using
OpenCL*): after dedispersion, a pulsar's pulse train concentrates its
power at the spin frequency and its harmonics of the time-series power
spectrum.  The classic search (also PRESTO's accelsearch shape) is:

1. **power spectrum** of the (mean-subtracted) dedispersed time
   series — one rFFT of ``T`` samples (``T = n_spectrum /
   channel_count``; tiny next to the segment FFTs);
2. **incoherent harmonic summing**: for each fundamental bin ``k``,
   sum the power at ``j*k`` for ``j = 1..h`` over a ladder of harmonic
   counts ``h = 1, 2, 4, ...`` — a narrow pulse spreads power over
   many harmonics, and the matched ``h`` maximizes detection SNR;
3. **candidate selection**: normalize each harmonic level to unit
   variance (sum of ``h`` approximately-exponential powers has mean
   ``h * mean(P)`` and sigma ``sqrt(h) * sigma(P)``), take the best
   level per bin, top-K bins overall;
4. **phase folding** at each candidate's period: average the time
   series into ``n_bins`` phase bins — the folded pulse profile a
   human (or a downstream classifier) vets.

Everything is static-shape and jit-clean (the "count then
conditionally copy" discipline of ops/detect.py): candidates are a
fixed top-K per stream, folding is a scatter-add over a fixed bin
count, and the host decides what to write.  All arrays here are
time-series-sized — ``T`` is ``2^11``-``2^15`` at production shapes —
so the mode's HBM cost is noise next to the segment FFTs and the
plan's spectrum-sized ``hbm_passes`` floor is unchanged (the plan
audit pins that).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class PeriodicityCandidates(NamedTuple):
    """Static-shape periodicity result for one data stream."""
    bins: jnp.ndarray        # [K] int32: fundamental bin per candidate
    snr: jnp.ndarray         # [K] f32: harmonic-summed, normalized SNR
    harmonics: jnp.ndarray   # [K] int32: harmonic count that maximized
    profiles: jnp.ndarray    # [K, n_bins] f32: folded pulse profiles


def harmonic_levels(max_harmonics: int) -> tuple:
    """Static harmonic-count ladder 1, 2, 4, ... <= max (>= (1,))."""
    levels = [1]
    h = 2
    while h <= int(max_harmonics):
        levels.append(h)
        h *= 2
    return tuple(levels)


def power_spectrum(ts: jnp.ndarray) -> jnp.ndarray:
    """Time series [T] (already mean-subtracted) -> power [M], the
    one-sided rFFT power with the DC bin zeroed (mean subtraction
    leaves it ~0 anyway; zeroing makes the exclusion exact)."""
    spec = jnp.fft.rfft(ts.astype(jnp.float32))
    power = (jnp.real(spec) ** 2 + jnp.imag(spec) ** 2) \
        .astype(jnp.float32)
    return power.at[..., 0].set(0.0)


def harmonic_sum(power: jnp.ndarray, levels: tuple) -> jnp.ndarray:
    """Incoherent harmonic sums ``[n_levels, M]``: row ``i`` holds
    ``sum_{j=1..levels[i]} power[min(j*k, M-1)]`` per fundamental bin
    ``k``.  Gathers only — static shapes, no host sync.  Clamping to
    the last bin slightly over-counts fundamentals whose harmonics
    fall off the spectrum; those bins are the top fraction ``1/h`` of
    the band, where a real detection would have been found at a lower
    level anyway."""
    m = power.shape[-1]
    k = jnp.arange(m)
    rows = []
    acc = power
    j = 1
    for h in levels:
        while j < h:
            j += 1
            idx = jnp.minimum(k * j, m - 1)
            acc = acc + power[..., idx]
        rows.append(acc)
    return jnp.stack(rows)


def candidate_search(ts: jnp.ndarray, levels: tuple, top_k: int,
                     min_bin: int = 2):
    """Harmonic-summed candidate selection over one stream's time
    series.  Returns ``(bins [K] i32, snr [K] f32, harm [K] i32)``
    ranked by normalized SNR; bins below ``min_bin`` (DC + red-noise
    leakage) are excluded."""
    power = power_spectrum(ts)
    m = power.shape[-1]
    sums = harmonic_sum(power, levels)                 # [L, M]
    # normalization per level: the valid-bin population's mean/sigma
    # (exclude the masked low bins so a strong red-noise ramp cannot
    # deflate every real candidate's SNR)
    valid = (jnp.arange(m) >= min_bin).astype(jnp.float32)
    n_valid = jnp.maximum(valid.sum(), 1.0)
    mean = (sums * valid).sum(axis=-1, keepdims=True) / n_valid
    var = (((sums - mean) * valid) ** 2).sum(axis=-1,
                                             keepdims=True) / n_valid
    snr_l = (sums - mean) / jnp.sqrt(jnp.maximum(var, 1e-30))
    snr_l = jnp.where(valid > 0, snr_l, -jnp.inf)
    best = jnp.max(snr_l, axis=0)                      # [M]
    best_level = jnp.argmax(snr_l, axis=0)             # [M]
    k = min(int(top_k), m)
    import jax
    snr, bins = jax.lax.top_k(best, k)
    harm = jnp.asarray(levels, dtype=jnp.int32)[best_level[bins]]
    return bins.astype(jnp.int32), snr.astype(jnp.float32), harm


def fold(ts: jnp.ndarray, bin_k: jnp.ndarray, n_bins: int) -> jnp.ndarray:
    """Phase-fold one stream's time series at the period of power-
    spectrum bin ``bin_k`` (``bin_k`` cycles per ``T`` samples):
    phase_i = (i * k mod T) / T, averaged into ``n_bins`` phase bins.
    Returns the folded profile ``[n_bins] f32`` (bins no sample lands
    in read 0)."""
    t = ts.shape[-1]
    # uint32 phase product: i * k <= T * M ~ T^2 / 2.  A power-of-two
    # T is ALWAYS exact (t divides 2^32, so mod-2^32 wraparound
    # commutes with % t); a non-power-of-two T is exact only while the
    # product stays under 2^32 — beyond that the wrapped phases would
    # silently corrupt the folded profiles, so refuse loudly at trace
    # time (x64 is globally disabled, so int64 is not an option).
    # Production T = n_spectrum / channel_count is 2^11-2^15.
    if (t & (t - 1)) and t * (t // 2) >= (1 << 32):
        raise ValueError(
            f"fold: time series length {t} is non-power-of-two and "
            "long enough that uint32 phase products wrap — reduce "
            "the series (spectrum_channel_count) below 2^16 samples "
            "or make it a power of two")
    i = jnp.arange(t, dtype=jnp.uint32)
    phase_idx = (((i * bin_k.astype(jnp.uint32)) % t)
                 * n_bins) // t                         # [T] in [0, nb)
    sums = jnp.zeros((n_bins,), jnp.float32).at[phase_idx].add(ts)
    counts = jnp.zeros((n_bins,), jnp.float32).at[phase_idx].add(1.0)
    return sums / jnp.maximum(counts, 1.0)


def periodicity_search(ts: jnp.ndarray, max_harmonics: int, top_k: int,
                       n_bins: int,
                       min_bin: int = 2) -> PeriodicityCandidates:
    """Full periodicity module for one stream: harmonic-summed
    candidate selection + a folded profile per candidate."""
    import jax
    levels = harmonic_levels(max_harmonics)
    bins, snr, harm = candidate_search(ts, levels, top_k,
                                       min_bin=min_bin)
    profiles = jax.vmap(lambda b: fold(ts, b, n_bins))(bins)
    return PeriodicityCandidates(bins=bins, snr=snr, harmonics=harm,
                                 profiles=profiles)


# ----------------------------------------------------------------
# numpy golden model (for tests)
# ----------------------------------------------------------------

def periodicity_oracle(ts: np.ndarray, max_harmonics: int, top_k: int,
                       n_bins: int, min_bin: int = 2):
    """Reference-faithful numpy recomputation of the search above."""
    spec = np.fft.rfft(ts.astype(np.float32))
    power = (spec.real ** 2 + spec.imag ** 2).astype(np.float32)
    power[0] = 0.0
    m = power.shape[-1]
    levels = harmonic_levels(max_harmonics)
    k = np.arange(m)
    rows, acc, j = [], power.copy(), 1
    for h in levels:
        while j < h:
            j += 1
            acc = acc + power[np.minimum(k * j, m - 1)]
        rows.append(acc.copy())
    sums = np.stack(rows)
    valid = k >= min_bin
    mean = sums[:, valid].mean(axis=-1, keepdims=True)
    sig = np.maximum(sums[:, valid].std(axis=-1, keepdims=True), 1e-15)
    snr_l = (sums - mean) / sig
    snr_l[:, ~valid] = -np.inf
    best = snr_l.max(axis=0)
    order = np.argsort(-best, kind="stable")[:top_k]
    t = ts.shape[-1]
    profiles = []
    for b in order:
        idx = (((np.arange(t) * int(b)) % t) * n_bins) // t
        sums_b = np.zeros(n_bins, np.float32)
        counts = np.zeros(n_bins, np.float32)
        np.add.at(sums_b, idx, ts)
        np.add.at(counts, idx, 1.0)
        profiles.append(sums_b / np.maximum(counts, 1.0))
    harm = np.asarray(levels)[snr_l.argmax(axis=0)[order]]
    return (order.astype(np.int32), best[order].astype(np.float32),
            harm.astype(np.int32), np.stack(profiles))
