"""Single-pulse signal detection.

Mirrors signal_detect_pipe_2 (ref: pipeline/signal_detect_pipe.hpp:244-443)
and count_signal (ref: signal_detect.hpp:32-72), re-shaped for jit: instead
of data-dependent host branching and dynamic result lists, everything is
computed with static shapes — a ``[n_boxcars]`` vector of detection counts
plus the (fixed-size) candidate time series — and the host decides what to
write out.  This is the "count then conditionally copy" pattern of the
reference made jit-clean (SURVEY.md §7 hard part #5).

Pipeline per segment, waterfall ``[freq, time]``:
1. zapped-channel count: channels whose time-0 sample is exactly zero
   (ref: signal_detect_pipe.hpp:262-284);
2. trim the reserved tail: T = time - nsamps_reserved/freq_bins
   (ref: signal_detect_pipe.hpp:287-299);
3. time series = sum over frequency of |x|^2 (ref: 305-316);
4. subtract mean (ref: 321-334);
5. sigma-threshold count at boxcar length 1 (ref: 347-366);
6. boxcar matched filtering: prefix sum, sliding-window difference for
   lengths 2, 4, ..., max_boxcar_length, re-detect each (ref: 368-424).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


def _norm(c):
    return jnp.real(c) ** 2 + jnp.imag(c) ** 2


class DetectResult(NamedTuple):
    """Static-shape detection result for one segment / one data stream."""
    zero_count: jnp.ndarray          # [] int32: zapped frequency channels
    time_series: jnp.ndarray         # [T] f32, mean-subtracted, boxcar 1
    boxcar_lengths: tuple            # static: (1, 2, 4, ..., max)
    signal_counts: jnp.ndarray       # [n_boxcars] int32: samples over threshold
    boxcar_series: jnp.ndarray       # [n_boxcars, T] f32 (rows zero-padded at tail)
    snr_peaks: jnp.ndarray           # [n_boxcars] f32: max SNR per boxcar


def boxcar_lengths(max_boxcar_length: int, time_series_count: int) -> tuple:
    """Static list of boxcar lengths: 1 then 2,4,... while <= max and < T
    (ref: signal_detect_pipe.hpp:387-389)."""
    lengths = [1]
    b = 2
    while b <= max_boxcar_length and b < time_series_count:
        lengths.append(b)
        b *= 2
    return tuple(lengths)


def count_signal(x: jnp.ndarray, snr_threshold: float):
    """Count samples with x > threshold*sqrt(mean(x^2)), assuming mean(x)=0
    (ref: signal_detect.hpp:32-72).  Returns (count, peak_snr)."""
    sigma = jnp.sqrt(jnp.mean(x * x, axis=-1, keepdims=True))
    thr = snr_threshold * sigma
    count = jnp.sum((x > thr).astype(jnp.int32), axis=-1)
    peak_snr = (jnp.max(x, axis=-1, keepdims=True)
                / jnp.maximum(sigma, jnp.float32(1e-30)))[..., 0]
    return count, peak_snr


def trimmed_length(time_samples: int, time_reserved_count: int) -> int:
    """Usable time samples after dropping the reserved (dedispersion-
    corrupted) tail; keeps everything when the segment is too short
    (ref: signal_detect_pipe.hpp:291-296 warns and keeps all)."""
    if time_samples <= time_reserved_count:
        return time_samples
    return time_samples - time_reserved_count


def detect(waterfall: jnp.ndarray, time_reserved_count: int,
           snr_threshold: float, max_boxcar_length: int) -> DetectResult:
    """Full detection chain on a frequency-major dynamic spectrum."""
    t = trimmed_length(waterfall.shape[-1], time_reserved_count)

    # zapped channels: first time sample exactly zero (ref: 262-284)
    zero_count = jnp.sum(
        (_norm(waterfall[..., 0]) == 0).astype(jnp.int32), axis=-1)

    # time series: sum power over frequency for the first t samples (ref: 305-316)
    ts = jnp.sum(_norm(waterfall[..., :t]), axis=-2)
    return detect_from_time_series(ts, zero_count, snr_threshold,
                                   max_boxcar_length)


def detect_from_time_series(ts: jnp.ndarray, zero_count: jnp.ndarray,
                            snr_threshold: float,
                            max_boxcar_length: int) -> DetectResult:
    """Boxcar detection ladder from a (not yet mean-subtracted) power time
    series ``ts [..., t]`` — the tail of :func:`detect`, split out so fused
    kernels that already produced the time series (Pallas SK+sum pass) can
    reuse it."""
    t = ts.shape[-1]
    ts = ts - jnp.mean(ts, axis=-1, keepdims=True)  # ref: 321-334

    lengths = boxcar_lengths(max_boxcar_length, t)
    n_box = len(lengths)

    # prefix sum once, sliding-window differences per length (ref: 368-399)
    acc = jnp.cumsum(ts, axis=-1)

    counts = []
    peaks = []
    series_rows = []
    for b in lengths:
        if b == 1:
            series = ts
        else:
            # d_accumulated[i + b] - d_accumulated[i] for i in [0, t-b)
            series = acc[..., b:] - acc[..., :-b]
        c, p = count_signal(series, snr_threshold)
        counts.append(c)
        peaks.append(p)
        pad = t - series.shape[-1]
        if pad:
            series = jnp.pad(series,
                             [(0, 0)] * (series.ndim - 1) + [(0, pad)])
        series_rows.append(series)
    del n_box
    return DetectResult(
        zero_count=zero_count,
        time_series=ts,
        boxcar_lengths=lengths,
        signal_counts=jnp.stack(counts, axis=-1),
        boxcar_series=jnp.stack(series_rows, axis=-2),
        snr_peaks=jnp.stack(peaks, axis=-1),
    )


# ----------------------------------------------------------------
# numpy golden model
# ----------------------------------------------------------------

def detect_oracle(waterfall: np.ndarray, time_reserved_count: int,
                  snr_threshold: float, max_boxcar_length: int):
    """Reference-faithful numpy recomputation (for tests)."""
    time_samples = waterfall.shape[-1]
    t = time_samples - time_reserved_count \
        if time_samples > time_reserved_count else time_samples
    power = np.abs(waterfall) ** 2
    zero_count = int(np.sum(power[..., 0] == 0))
    ts = power[:, :t].sum(axis=0)
    ts = ts - ts.mean()
    lengths = boxcar_lengths(max_boxcar_length, t)
    acc = np.cumsum(ts)
    counts = []
    for b in lengths:
        if b == 1:
            series = ts
        else:
            series = acc[b:] - acc[:-b]
            series = series[: t - b]
        thr = snr_threshold * np.sqrt(np.mean(series * series))
        counts.append(int(np.sum(series > thr)))
    return zero_count, ts, lengths, counts
