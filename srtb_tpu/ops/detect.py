"""Single-pulse signal detection.

Mirrors signal_detect_pipe_2 (ref: pipeline/signal_detect_pipe.hpp:244-443)
and count_signal (ref: signal_detect.hpp:32-72), re-shaped for jit: instead
of data-dependent host branching and dynamic result lists, everything is
computed with static shapes — a ``[n_boxcars]`` vector of detection counts
plus the (fixed-size) candidate time series — and the host decides what to
write out.  This is the "count then conditionally copy" pattern of the
reference made jit-clean (SURVEY.md §7 hard part #5).

Pipeline per segment, waterfall ``[freq, time]``:
1. zapped-channel count: channels whose time-0 sample is exactly zero
   (ref: signal_detect_pipe.hpp:262-284);
2. trim the reserved tail: T = time - nsamps_reserved/freq_bins
   (ref: signal_detect_pipe.hpp:287-299);
3. time series = sum over frequency of |x|^2 (ref: 305-316);
4. subtract mean (ref: 321-334);
5. sigma-threshold count at boxcar length 1 (ref: 347-366);
6. boxcar matched filtering: prefix sum, sliding-window difference for
   lengths 2, 4, ..., max_boxcar_length, re-detect each (ref: 368-424).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


def _norm(c):
    return jnp.real(c) ** 2 + jnp.imag(c) ** 2


def tree_sum_freq(power: jnp.ndarray) -> jnp.ndarray:
    """Sum ``power [..., K, T]`` over the frequency axis (-2) with an
    explicit pairwise (binary-tree) reduction: K -> K/2 -> ... -> 1.

    Why not ``jnp.sum``: XLA's reduction order is implementation-defined
    — on XLA:CPU the 2^15-channel production sum accumulates mostly
    sequentially, and the measured time-series error at the flagship
    geometry was 3.1e-4 (artifacts/production_oracle.json, round 4),
    ~600x the waterfall error feeding it.  The reference does the same
    sum naively in f32 (ref: signal_detect_pipe.hpp:305-316) and
    inherits the same growth; this beats it instead of matching it.

    The pairwise tree makes the rounding bound deterministic and
    backend-independent: ceil(log2 K) + 1 levels, each contributing at
    most one ulp of the running partial per element, so for nonnegative
    summands

        |err[t]| <= (ceil(log2 K) + 1) * eps * sum_k power[k, t]

    (eps = 2^-24); at K = 2^15 that is ~1e-6 relative to the raw series
    — vs the O(K * eps) = 2e-3 worst case of a sequential sum.  Cost:
    the level arrays form a geometric series, ~2x the HBM traffic of a
    single fused reduce — noise next to the segment FFTs.  Asserted
    against a float64 oracle in tests/test_reference_crosscheck.py.
    """
    k = power.shape[-2]
    t = power.shape[-1]
    lead = power.shape[:-2]
    carry = None
    while k > 1:
        if k % 2:
            last = power[..., -1:, :]
            carry = last if carry is None else carry + last
            power = power[..., :-1, :]
            k -= 1
        power = power.reshape(*lead, k // 2, 2, t)
        power = power[..., 0, :] + power[..., 1, :]
        k //= 2
    out = power[..., 0, :]
    if carry is not None:
        out = out + carry[..., 0, :]
    return out


class DetectResult(NamedTuple):
    """Static-shape detection result for one segment / one data stream."""
    zero_count: jnp.ndarray          # [] int32: zapped frequency channels
    time_series: jnp.ndarray         # [T] f32, mean-subtracted, boxcar 1
    boxcar_lengths: tuple            # static: (1, 2, 4, ..., max)
    signal_counts: jnp.ndarray       # [n_boxcars] int32: samples over threshold
    boxcar_series: jnp.ndarray       # [n_boxcars, T] f32 (rows zero-padded at tail)
    snr_peaks: jnp.ndarray           # [n_boxcars] f32: max SNR per boxcar
    # data-quality epilogue side-output (srtb_tpu/quality/stats.py
    # packed [S, N_SCALARS + 2*B] vector; None unless
    # Config.quality_stats armed the epilogue — None is an empty
    # pytree subtree, so every existing consumer is unaffected)
    quality: jnp.ndarray | None = None


def time_series_error_gates(k_ch: int, t_len: int, ts_raw_max: float,
                            wf_err_abs: float) -> tuple:
    """Derived absolute error bounds for the detection time series vs a
    float64 oracle, decomposed by cause (single home of the formulas:
    tools/production_oracle.py gates the flagship geometry with these
    and tests/test_reference_crosscheck.py pins them in CI).

    Returns ``(ts_sum_gate, ts_prop_gate)``:

    - ``ts_sum_gate`` bounds the f32 summation error of
      :func:`tree_sum_freq` + the tree mean-subtract vs exact f64 on
      the *same* f32 waterfall: (ceil(lg K) + ceil(lg T) + 5) pairwise
      levels, each <= eps of the running nonnegative partial, times the
      raw (un-mean-subtracted) series max; factor 2 for the mean's few
      extra ulps.  Deterministic and backend-independent — measured
      4.2e-5 relative at K = 2^15 vs 1.8e-3 for a sequential f32 sum
      (round-5 A/B).
    - ``ts_prop_gate`` bounds the waterfall's own f32 error
      ``wf_err_abs`` propagated through |.|^2 and the channel sum:
      per time sample |sum_k(|x+d|^2 - |x|^2)| <= 2*wf_err*sum_k|x| +
      K*wf_err^2 <= 2*wf_err*sqrt(K*ts_raw_max) + K*wf_err^2 —
      worst-case coherent alignment, no statistical assumption.  The
      comparison happens on *mean-subtracted* series, and subtracting
      the (equally perturbed) mean can double the per-sample
      difference, hence the outer factor 2.
    """
    eps = 2.0 ** -24
    levels = (int(np.ceil(np.log2(max(k_ch, 2))))
              + int(np.ceil(np.log2(max(t_len, 2)))) + 5)
    ts_sum_gate = 2.0 * levels * eps * ts_raw_max
    ts_prop_gate = 2.0 * (
        2.0 * wf_err_abs * float(np.sqrt(k_ch * ts_raw_max))
        + k_ch * wf_err_abs ** 2)
    return ts_sum_gate, ts_prop_gate


def tree_mean(ts: jnp.ndarray) -> jnp.ndarray:
    """Mean over the last axis via the pairwise tree (shape [..., 1]) —
    the single home of the mean-subtract spelling whose rounding the
    ``time_series_error_gates`` bound accounts for; used by the
    single-chip detect tail and the distributed step body."""
    return tree_sum_freq(ts[..., :, None]) / ts.shape[-1]


def boxcar_lengths(max_boxcar_length: int, time_series_count: int) -> tuple:
    """Static list of boxcar lengths: 1 then 2,4,... while <= max and < T
    (ref: signal_detect_pipe.hpp:387-389)."""
    lengths = [1]
    b = 2
    while b <= max_boxcar_length and b < time_series_count:
        lengths.append(b)
        b *= 2
    return tuple(lengths)


def count_signal(x: jnp.ndarray, snr_threshold: float):
    """Count samples with x > threshold*sqrt(mean(x^2)), assuming mean(x)=0
    (ref: signal_detect.hpp:32-72).  Returns (count, peak_snr)."""
    sigma = jnp.sqrt(jnp.mean(x * x, axis=-1, keepdims=True))
    thr = snr_threshold * sigma
    count = jnp.sum((x > thr).astype(jnp.int32), axis=-1)
    peak_snr = (jnp.max(x, axis=-1, keepdims=True)
                / jnp.maximum(sigma, jnp.float32(1e-30)))[..., 0]
    return count, peak_snr


def trimmed_length(time_samples: int, time_reserved_count: int) -> int:
    """Usable time samples after dropping the reserved (dedispersion-
    corrupted) tail; keeps everything when the segment is too short
    (ref: signal_detect_pipe.hpp:291-296 warns and keeps all)."""
    if time_samples <= time_reserved_count:
        return time_samples
    return time_samples - time_reserved_count


def detect(waterfall: jnp.ndarray, time_reserved_count: int,
           snr_threshold: float, max_boxcar_length: int) -> DetectResult:
    """Full detection chain on a frequency-major dynamic spectrum."""
    t = trimmed_length(waterfall.shape[-1], time_reserved_count)

    # zapped channels: first time sample exactly zero (ref: 262-284)
    zero_count = jnp.sum(
        (_norm(waterfall[..., 0]) == 0).astype(jnp.int32), axis=-1)

    # time series: sum power over frequency for the first t samples
    # (ref: 305-316) — pairwise tree, not jnp.sum: see tree_sum_freq
    ts = tree_sum_freq(_norm(waterfall[..., :t]))
    return detect_from_time_series(ts, zero_count, snr_threshold,
                                   max_boxcar_length)


def detect_from_time_series(ts: jnp.ndarray, zero_count: jnp.ndarray,
                            snr_threshold: float,
                            max_boxcar_length: int) -> DetectResult:
    """Boxcar detection ladder from a (not yet mean-subtracted) power time
    series ``ts [..., t]`` — the tail of :func:`detect`, split out so fused
    kernels that already produced the time series (Pallas SK+sum pass) can
    reuse it."""
    t = ts.shape[-1]
    # mean subtraction (ref: 321-334) with the same pairwise-tree
    # discipline as the frequency sum: the series sits at K*mean_power
    # scale, so an order-unspecified sum over T = 2^14 samples could
    # contribute more error than the whole frequency reduction
    ts = ts - tree_mean(ts)

    lengths = boxcar_lengths(max_boxcar_length, t)
    n_box = len(lengths)

    # prefix sum once, sliding-window differences per length (ref: 368-399)
    acc = jnp.cumsum(ts, axis=-1)

    counts = []
    peaks = []
    series_rows = []
    for b in lengths:
        if b == 1:
            series = ts
        else:
            # d_accumulated[i + b] - d_accumulated[i] for i in [0, t-b)
            series = acc[..., b:] - acc[..., :-b]
        c, p = count_signal(series, snr_threshold)
        counts.append(c)
        peaks.append(p)
        pad = t - series.shape[-1]
        if pad:
            series = jnp.pad(series,
                             [(0, 0)] * (series.ndim - 1) + [(0, pad)])
        series_rows.append(series)
    del n_box
    return DetectResult(
        zero_count=zero_count,
        time_series=ts,
        boxcar_lengths=lengths,
        signal_counts=jnp.stack(counts, axis=-1),
        boxcar_series=jnp.stack(series_rows, axis=-2),
        snr_peaks=jnp.stack(peaks, axis=-1),
    )


# ----------------------------------------------------------------
# numpy golden model
# ----------------------------------------------------------------

def detect_oracle(waterfall: np.ndarray, time_reserved_count: int,
                  snr_threshold: float, max_boxcar_length: int):
    """Reference-faithful numpy recomputation (for tests)."""
    time_samples = waterfall.shape[-1]
    t = time_samples - time_reserved_count \
        if time_samples > time_reserved_count else time_samples
    power = np.abs(waterfall) ** 2
    zero_count = int(np.sum(power[..., 0] == 0))
    ts = power[:, :t].sum(axis=0)
    ts = ts - ts.mean()
    lengths = boxcar_lengths(max_boxcar_length, t)
    acc = np.cumsum(ts)
    counts = []
    for b in lengths:
        if b == 1:
            series = ts
        else:
            series = acc[b:] - acc[:-b]
            series = series[: t - b]
        thr = snr_threshold * np.sqrt(np.mean(series * series))
        counts.append(int(np.sum(series > thr)))
    return zero_count, ts, lengths, counts
