"""Append-only perf ledger: every measurement becomes queryable history.

The repo's perf trajectory lived in two places that don't compose:
hand-written PERF.md rounds and driver-captured ``BENCH_r0*.json``
artifacts — neither queryable, neither keyed well enough to compare
apples to apples across hosts and commits.  The ledger is one JSONL
file of structured records keyed by the four things that make a perf
number comparable:

- ``plan``/``plan_signature_sha`` — WHAT ran (the SegmentProcessor
  plan id and a short hash of its full trace signature; two records
  with equal hashes executed the same compiled-program family);
- ``shape`` — the measured working set (log2n, channels, nbits);
- ``host_fp`` — WHERE it ran (a stable fingerprint of the host;
  cross-host comparisons must be calibrated, see tools/perf_gate.py);
- ``git_sha`` — WHICH code.

Writers: ``bench.py`` (``SRTB_PERF_LEDGER=path``), steady-state
pipeline runs (``Config.perf_ledger_path`` — one record per run at
drain end), ``tools/perf_gate.py`` captures, and
``tools/perf_ledger.py --import`` (the legacy BENCH_r0*.json
backfill).  Reader: ``tools/perf_report.py`` renders the trajectory.

Records carry ``samples_s`` (per-rep seconds) when the producer has
them — that is what makes the regression gate statistical instead of
a two-number diff.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

from srtb_tpu.utils.logging import log

RECORD_TYPE = "perf_record"
RECORD_VERSION = 1


def host_fingerprint() -> str:
    """Short stable id of this host + software stack: records from
    different hosts (or after a jax/python upgrade) must not be
    compared raw.  Deliberately excludes anything run-local (cwd,
    pid, time)."""
    import platform
    parts = {
        "node": platform.node(),
        "machine": platform.machine(),
        "cpus": os.cpu_count(),
        "python": platform.python_version(),
    }
    try:
        import jax
        parts["jax"] = jax.__version__
    except Exception:  # pure-host tools must not require jax
        parts["jax"] = ""
    blob = json.dumps(parts, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def git_sha(root: str | None = None) -> str:
    """Current commit sha (short), "" outside a git checkout."""
    import subprocess
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            cwd=root or os.getcwd(), capture_output=True, text=True,
            timeout=10)
        return out.stdout.strip() if out.returncode == 0 else ""
    except (OSError, subprocess.SubprocessError):
        # SubprocessError covers TimeoutExpired (a wedged object
        # store) — provenance lookup must never abort the caller
        return ""


def signature_sha(signature: str | None) -> str:
    """Short hash of a full plan signature (the signature itself is a
    multi-KB JSON blob; the ledger needs equality, not contents)."""
    if not signature:
        return ""
    return hashlib.sha256(signature.encode()).hexdigest()[:16]


def make_record(source: str, value: float, unit: str,
                plan: str = "", plan_signature: str | None = None,
                shape: dict | None = None, platform: str = "",
                samples_s: list | None = None,
                extra: dict | None = None,
                ts: float | None = None,
                host_fp: str | None = None,
                git_sha_value: str | None = None) -> dict:
    """One ledger record.  ``source`` names the producer protocol
    ("bench", "steady", "gate", "import").  ``host_fp`` /
    ``git_sha_value`` default to the CURRENT host/commit; producers
    describing measurements they did not run (the legacy importer)
    pass explicit values — usually "" — instead of paying for, then
    discarding, the fingerprint hash and the git subprocess."""
    rec = {
        "type": RECORD_TYPE,
        "v": RECORD_VERSION,
        "ts": time.time() if ts is None else float(ts),
        "source": str(source),
        "value": float(value),
        "unit": str(unit),
        "plan": str(plan),
        "plan_signature_sha": signature_sha(plan_signature),
        "shape": dict(shape or {}),
        "platform": str(platform),
        "host_fp": host_fingerprint() if host_fp is None
        else str(host_fp),
        "git_sha": git_sha() if git_sha_value is None
        else str(git_sha_value),
    }
    if samples_s:
        rec["samples_s"] = [float(s) for s in samples_s]
    if extra:
        rec["extra"] = dict(extra)
    return rec


class PerfLedger:
    """Append-only JSONL; best-effort (a perf record must never abort
    the run it describes)."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def append(self, record: dict) -> bool:
        try:
            with open(self.path, "a") as f:
                f.write(json.dumps(record, sort_keys=True) + "\n")
            return True
        except OSError as e:
            log.warning(f"[perf_ledger] append to {self.path} failed: "
                        f"{e}")
            return False

    def load(self) -> list[dict]:
        return load(self.path)


def load(path: str) -> list[dict]:
    """Parse perf records, oldest-first by file order, tolerating torn
    tails and foreign lines (the ledger may share a directory with
    journals)."""
    records = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("type") == RECORD_TYPE:
                    records.append(rec)
    except OSError:
        pass
    return records


def history(records: list[dict], plan: str, host_fp: str | None = None,
            shape: dict | None = None, max_records: int = 3) -> list:
    """Concatenated per-rep samples from the NEWEST records matching
    ``(plan, host_fp, shape)`` — the baseline side of a mid-run
    regression check (srtb_tpu/obs/regression.py).  Records without
    ``samples_s`` carry no statistical weight and are skipped; pass
    ``host_fp=None``/``shape=None`` to not filter on that key."""
    matches = []
    for rec in records:
        if rec.get("plan") != plan or not rec.get("samples_s"):
            continue
        if host_fp is not None and rec.get("host_fp") != host_fp:
            continue
        if shape is not None and rec.get("shape") != dict(shape):
            continue
        matches.append(rec)
    out: list[float] = []
    for rec in matches[-max(1, int(max_records)):]:
        out.extend(float(s) for s in rec["samples_s"])
    return out


def import_keys(records: list[dict]) -> set:
    """The idempotency keys already in the ledger: a re-run of
    ``--import`` must not duplicate history."""
    return {r["extra"]["import_key"] for r in records
            if r.get("extra", {}).get("import_key")}


def record_steady_state(cfg, stats, processor) -> None:
    """One "steady" record for a finished pipeline run (called by the
    runtime when ``Config.perf_ledger_path`` is set and the run
    processed at least one segment).  Value = lifetime Msamples/s over
    the run; per-segment samples live in the telemetry journal, not
    here (the ledger stays one line per run)."""
    path = getattr(cfg, "perf_ledger_path", "")
    if not path or not getattr(stats, "segments", 0):
        return
    try:
        _record_steady_state(cfg, stats, processor, path)
    except Exception as e:  # noqa: BLE001 — the module contract:
        # a perf record must never abort the run it describes (an
        # unwritable ledger dir, a wedged git lookup, a retired
        # processor — all reduce to a warning)
        log.warning(f"[perf_ledger] steady-state record failed: {e}")


def _record_steady_state(cfg, stats, processor, path: str) -> None:
    import math
    sig = None
    plan = getattr(processor, "plan_name", "")
    sig_fn = getattr(processor, "plan_signature", None)
    if sig_fn is not None:
        try:
            sig = sig_fn()
        except Exception:  # a retired/stub processor owes no signature
            sig = None
    try:
        import jax
        platform = jax.default_backend()
    except Exception:
        platform = ""
    n = int(getattr(cfg, "baseband_input_count", 0) or 0)
    shape = {
        "log2n": int(math.log2(n)) if n > 0 else 0,
        "channels": int(getattr(cfg, "spectrum_channel_count", 0) or 0),
        "nbits": int(getattr(cfg, "baseband_input_bits", 0) or 0),
    }
    extra = {
        "segments": int(stats.segments),
        "elapsed_s": round(float(stats.elapsed_s), 4),
        "stream": str(getattr(cfg, "stream_name", "") or ""),
    }
    PerfLedger(path).append(make_record(
        "steady", stats.msamples_per_sec, "Msamples/s", plan=plan,
        plan_signature=sig, shape=shape, platform=platform,
        extra=extra))
