"""CPU affinity for ingest threads (ref: util/thread_affinity.hpp:34-122,
used by udp_receiver_pipe.hpp:88-98 to pin receivers near the NIC's NUMA
node).  Uses os.sched_setaffinity (Linux), falling back to the native
helper in libsrtb_udp.so."""

from __future__ import annotations

import os

from srtb_tpu.utils.logging import log


def set_thread_affinity(cpu: int) -> bool:
    """Pin the calling thread to one CPU.  Returns True on success."""
    try:
        os.sched_setaffinity(0, {cpu})
        return True
    except (AttributeError, OSError) as e:
        log.warning(f"[thread_affinity] sched_setaffinity failed: {e}")
    try:
        from srtb_tpu.io.udp import _NATIVE
        if _NATIVE is not None:
            return _NATIVE.srtb_set_thread_affinity(cpu) == 0
    except Exception:
        pass
    return False
