"""Honor JAX_PLATFORMS in environments that force a platform plugin.

Some deployments force an accelerator platform via ``jax.config`` at
interpreter startup (sitecustomize); programmatic config wins over the
``JAX_PLATFORMS`` environment variable, so ``JAX_PLATFORMS=cpu <tool>``
silently still targets the (possibly unreachable) accelerator.  Every
entry point calls :func:`apply_platform_env` before first device use to
restore the documented env-var semantics (same dance as
tests/conftest.py and bench.py).
"""

from __future__ import annotations

import os


def apply_platform_env() -> None:
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    import jax

    try:
        jax.config.update("jax_platforms", platforms)
    except Exception:  # backend already initialized: keep whatever is up
        pass


def to_host(x):
    """Explicit device->host fetch for possibly-device arrays — the
    single home of the sanctioned D2H spelling.  ``np.asarray`` on a
    ``jax.Array`` is an *implicit* transfer (srtb-lint sync-hot-path;
    the runtime sanitizer's tripwire raises on it), so every sink/GUI
    fetch funnels through here."""
    import jax
    import numpy as np

    if isinstance(x, jax.Array):
        return jax.device_get(x)
    return np.asarray(x)


def on_accelerator() -> bool:
    """Whether the default JAX backend is real TPU hardware (directly or
    via the axon relay) — the single home of the backend set that gates
    Pallas interpret-mode downgrades."""
    import jax

    return jax.default_backend() in ("tpu", "axon")
