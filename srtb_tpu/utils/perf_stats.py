"""Noise-aware perf statistics: the math behind tools/perf_gate.py.

PERF.md's methodology was "paired A/B, call anything within ±4% the
CPU noise floor" — an eyeballed constant.  This module computes the
floor from the samples instead and makes the regression verdict a
statistical test over *per-rep samples*, not a comparison of two
single numbers:

- :func:`mann_whitney_u` — exact-tie-corrected normal-approximation
  Mann-Whitney U (two-sided): "do these two sample sets come from the
  same distribution at all?"  Rank-based, so one GC pause outlier
  cannot manufacture (or hide) a verdict the way it moves a mean.
- :func:`bootstrap_effect_ci` — percentile-bootstrap confidence
  interval of the relative median effect (median_b / median_a - 1,
  positive = B slower), deterministic (seeded) so CI reruns agree.
- :func:`noise_floor` — the computed replacement for the hand-written
  ±4%: the 95% standard error of the median-ratio under the observed
  robust scatter (MAD-based sigma, immune to a single outlier rep).
  With ~4%-sigma samples and n=9 reps this lands near the historical
  4% — the constant was an okay eyeball; now it is derived.
- :func:`compare` — the gate verdict: REGRESSION only when the
  distributions differ (Mann-Whitney p < alpha), the bootstrap CI
  excludes zero, AND the effect exceeds max(noise_floor, min_effect).

Samples are *seconds per rep* (smaller = faster) everywhere: a
positive effect means B is slower than A.

No scipy/numpy dependency beyond numpy (already required): the gate
must run in the same minimal environment as ci.sh.
"""

from __future__ import annotations

import math

import numpy as np

# two-sided 95% z quantile, used by the normal-approx U test and the
# noise-floor standard error
_Z975 = 1.959963984540054


def _median(x: np.ndarray) -> float:
    return float(np.median(x))


def robust_rel_sigma(samples) -> float:
    """Robust relative scatter of one sample set: 1.4826 * MAD /
    median (the MAD-consistent sigma estimate for a normal core,
    insensitive to a single pathological rep).  0.0 for degenerate
    inputs (n < 2 or zero median)."""
    x = np.asarray(samples, dtype=float)
    if x.size < 2:
        return 0.0
    med = _median(x)
    if med == 0:
        return 0.0
    mad = _median(np.abs(x - med))
    return float(1.4826 * mad / abs(med))


def noise_floor(a, b, z: float = _Z975) -> float:
    """The computed noise floor for the relative median effect of B
    vs A: ``z * sqrt(rsem_a^2 + rsem_b^2)`` where ``rsem`` is each
    set's robust relative standard error of the median
    (``1.2533 * rel_sigma / sqrt(n)`` — the asymptotic median
    efficiency factor sqrt(pi/2)).  An effect smaller than this is
    indistinguishable from sampling noise at the ~95% level, whatever
    PERF.md's historical ±4% said."""
    def rsem(x):
        x = np.asarray(x, dtype=float)
        if x.size < 2:
            return 0.0
        return 1.2533141373155003 * robust_rel_sigma(x) / math.sqrt(x.size)

    return float(z * math.hypot(rsem(a), rsem(b)))


def mann_whitney_u(a, b) -> tuple[float, float]:
    """Two-sided Mann-Whitney U via the tie-corrected normal
    approximation.  Returns ``(u, p)`` with ``u`` the statistic for
    sample A.  For the gate's rep counts (>= ~8 per side) the normal
    approximation is accurate to well under the alpha it is compared
    against; tiny inputs degrade gracefully (p = 1.0 when a verdict
    is impossible)."""
    a = np.asarray(a, dtype=float)
    b = np.asarray(b, dtype=float)
    n1, n2 = a.size, b.size
    if n1 == 0 or n2 == 0:
        return 0.0, 1.0
    both = np.concatenate([a, b])
    order = np.argsort(both, kind="mergesort")
    ranks = np.empty(both.size, dtype=float)
    ranks[order] = np.arange(1, both.size + 1, dtype=float)
    # midranks for ties (and the tie correction below)
    vals, inv, counts = np.unique(both, return_inverse=True,
                                  return_counts=True)
    if vals.size != both.size:
        cum = np.cumsum(counts)
        start = cum - counts
        mid = (start + 1 + cum) / 2.0
        ranks = mid[inv]
    r1 = float(ranks[:n1].sum())
    u1 = r1 - n1 * (n1 + 1) / 2.0
    mu = n1 * n2 / 2.0
    n = n1 + n2
    tie_term = float(((counts ** 3 - counts).sum())) / (n * (n - 1)) \
        if n > 1 else 0.0
    var = n1 * n2 / 12.0 * ((n + 1) - tie_term)
    if var <= 0:
        return u1, 1.0
    # continuity-corrected two-sided p
    z = (abs(u1 - mu) - 0.5) / math.sqrt(var)
    z = max(z, 0.0)
    p = math.erfc(z / math.sqrt(2.0))
    return u1, min(1.0, max(0.0, p))


def bootstrap_effect_ci(a, b, n_boot: int = 4000, seed: int = 0,
                        alpha: float = 0.05) -> tuple[float, float]:
    """Percentile-bootstrap CI of the relative median effect
    ``median(b)/median(a) - 1`` (positive = B slower).  Deterministic
    for a given seed so the gate's verdict reproduces."""
    a = np.asarray(a, dtype=float)
    b = np.asarray(b, dtype=float)
    if a.size == 0 or b.size == 0:
        return 0.0, 0.0
    rng = np.random.default_rng(seed)
    ia = rng.integers(0, a.size, size=(n_boot, a.size))
    ib = rng.integers(0, b.size, size=(n_boot, b.size))
    med_a = np.median(a[ia], axis=1)
    med_b = np.median(b[ib], axis=1)
    ok = med_a != 0
    eff = np.zeros(n_boot)
    eff[ok] = med_b[ok] / med_a[ok] - 1.0
    lo, hi = np.quantile(eff, [alpha / 2.0, 1.0 - alpha / 2.0])
    return float(lo), float(hi)


def compare(a, b, alpha: float = 0.05, min_effect: float = 0.0,
            n_boot: int = 4000, seed: int = 0) -> dict:
    """The gate verdict for per-rep timing samples A (reference) vs B
    (candidate), seconds per rep.  ``regression`` is True only when
    ALL of:

    - Mann-Whitney rejects "same distribution" at ``alpha``;
    - the bootstrap CI of the median effect excludes zero from below
      (``ci_low > 0``: B slower with ~95% confidence);
    - the point effect exceeds ``max(noise_floor, min_effect)`` — the
      computed floor formalizes PERF.md's hand ±4%; ``min_effect``
      lets CI demand a materially larger slowdown (e.g. cross-host
      calibrated comparisons, where scheduling noise dwarfs the
      within-host floor).

    ``improvement`` is the symmetric verdict (B faster).  Everything
    that fed the decision is in the dict — the gate's JSON line is
    auditable, not just a boolean."""
    a = np.asarray(a, dtype=float)
    b = np.asarray(b, dtype=float)
    med_a = _median(a) if a.size else 0.0
    med_b = _median(b) if b.size else 0.0
    effect = (med_b / med_a - 1.0) if med_a else 0.0
    u, p = mann_whitney_u(a, b)
    ci_low, ci_high = bootstrap_effect_ci(a, b, n_boot=n_boot,
                                          seed=seed, alpha=alpha)
    floor = noise_floor(a, b)
    threshold = max(floor, float(min_effect))
    differs = p < alpha
    return {
        "n_a": int(a.size), "n_b": int(b.size),
        "median_a_s": med_a, "median_b_s": med_b,
        "effect": effect,          # + = B slower
        "ci_low": ci_low, "ci_high": ci_high,
        "u": u, "p": p, "alpha": alpha,
        "noise_floor": floor, "min_effect": float(min_effect),
        "threshold": threshold,
        "regression": bool(differs and ci_low > 0.0
                           and effect > threshold),
        "improvement": bool(differs and ci_high < 0.0
                            and -effect > threshold),
    }
