"""Persistent XLA compilation cache — the FFTW-wisdom analog.

The reference persists FFTW plans to ``fft_fftw_wisdom_path`` so later
runs skip planning (ref: fft/fftw_wrapper.hpp:196-238, config.hpp:176).
The TPU equivalent of "planning" is XLA compilation (20-40 s for the big
fused segment program); JAX's on-disk compilation cache plays the role of
the wisdom file, so a restarted observation resumes at full speed.
"""

from __future__ import annotations

import os

from srtb_tpu.utils.logging import log


def enable_compile_cache(path: str = "") -> str | None:
    """Point JAX's persistent compilation cache at ``path`` (created if
    missing).  Returns the directory used, or None if unavailable.

    CPU backends are excluded: the cache exists for the TPU pipeline's
    minutes-long compiles, while XLA:CPU caches AOT *machine code* keyed
    without the host's CPU features — after a host swap a stale entry
    loads with a SIGILL warning ("Machine type used for XLA:CPU
    compilation doesn't match") and can crash mid-run (observed as a
    transient bench value-0 failure, round 4).  CPU compiles are cheap;
    correctness across host swaps is not."""
    import jax

    if jax.default_backend() == "cpu":
        log.debug("[compile_cache] skipped on CPU (host-fragile AOT)")
        return None
    if not path:
        path = os.path.join(os.path.expanduser("~"), ".cache",
                            "srtb_tpu_xla_cache")
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything, however small — streaming restart latency is
        # what matters, not disk
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        log.debug(f"[compile_cache] enabled at {path}")
        return path
    except Exception as e:  # unsupported backend/config name drift
        log.warning(f"[compile_cache] could not enable: {e}")
        return None
