from srtb_tpu.utils.expression import parse_expression  # noqa: F401
from srtb_tpu.utils.logging import get_logger, log  # noqa: F401
