"""Segment-span telemetry: rotating JSONL journal + pipeline health.

The reference's per-pipe timestamp logs (SURVEY.md §5.1, §5.5) answer
"where did this segment spend its time" only via grep.  Here every
processed segment emits one structured JSONL record — segment id,
per-stage wall-clock (from the pipeline's integrated StageTimer),
queue depth, cumulative loss/drop counters, detection count and the
dump decision — to a size-rotated journal file.  Host stages are also
wrapped in ``jax.profiler.TraceAnnotation`` (pipeline/runtime.py), so
an xprof trace and the journal correlate by stage name.

``tools/telemetry_report.py`` turns a journal into per-stage percentile
tables and throughput timelines; ``health()`` feeds the ``/healthz``
endpoint (gui/server.py) with last-segment-age staleness detection.
"""

from __future__ import annotations

import json
import os
import threading
import time

from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

# v2 (async overlap engine): adds ``overlap_hidden_ms`` (host/transfer
# time hidden under device compute for this segment) and
# ``inflight_depth`` (dispatched-not-yet-drained segments at drain
# time).
# v3 (resilience): adds the degradation state at drain
# (``degrade_level``) and the cumulative recovery counters
# ``retries`` / ``requeues`` / ``restarts`` / ``shed_waterfalls`` /
# ``shed_baseband`` (same cumulative convention as
# ``segments_dropped``: deltas between consecutive records localize a
# recovery burst to a segment).
# v4 (self-healing compute): adds the cumulative ``plan_demotions`` /
# ``plan_promotions`` / ``device_reinits`` counters, the demotion-
# ladder position at drain (``plan_ladder_level``, 0 = the configured
# plan) and — when the writer knows it — ``active_plan`` (the
# SegmentProcessor.plan_name active at drain time; consecutive-record
# changes give the plan timeline).
# v5 (durable outputs): adds the cumulative crash-recovery counters
# ``recovered_segments`` (committed segments the manifest rescued
# beyond the checkpoint at startup), ``replayed_skips`` (sink pushes
# skipped on replay because the manifest already holds their commit)
# and ``rolled_back_intents`` (uncommitted artifacts rolled back by
# manifest recovery) — all zero on a run that never crashed.
# v6 (multi-tenant fleet): adds ``stream`` (the Config.stream_name
# label of the stream this span belongs to — omitted on unnamed
# single-stream runs, never a fake placeholder) so a fleet journal
# (or N per-stream journals merged) attributes every span, loss
# burst, demotion and shed to its tenant.
# v7 (causal tracing): adds ``trace_id`` (the SegmentWork's causal id,
# utils/events.py — omitted when the engine never stamped one, e.g.
# events disabled) so a journal span and the flight recorder's events
# for the same segment correlate exactly; an incident bundle's
# spans_tail.jsonl joins its trace.jsonl on this field.
# v8 (performance observatory): adds per-segment DEVICE-time
# accounting and live roofline fields — ``device_ms`` (dispatch-return
# -> drain-head-ready wall clock: an upper bound on device busy time,
# exact in serial mode; omitted when the engine did not measure it),
# ``achieved_msamps`` / ``roofline_frac`` (this segment's throughput
# against its plan's audited hbm_passes traffic floor and the
# configured HBM peak — both LOWER bounds, since device_ms is an
# upper bound) — plus the cumulative compile/cache accounting
# ``compile_ms`` (first-dispatch trace+compile wall, plus AOT-miss
# compiles), ``plan_compiles``, ``aot_cache_hits`` /
# ``aot_cache_misses``.
# v9 (science observatory): adds two optional ``extra`` sections —
# ``quality`` (the per-segment data-quality dict QualityMonitor
# journals: zap_frac, bandpass mean/var, SK mean/max, dead/hot
# fractions, drift score/alert, and the coarse occupancy + bandpass
# maps) and ``canary`` (pulse-injection verdict: injected, segment,
# recovered/expected S/N, sensitivity ratio, ok — or just the
# injection flag on a replayed drain).  Both ride the existing
# ``extra`` envelope, so pre-v9 readers skip them.
# v10 (cross-tenant continuous batching): adds ``batch_size`` (how
# many segments — possibly from DIFFERENT streams — shared this
# segment's device dispatch; pipeline/fleet._BatchFormer) and
# ``batch_wait_ms`` (wall clock this segment waited in the former
# between becoming ready and the shared dispatch — the linger cost
# the fleet_batch_linger_ms deadline bounds).  Both OMITTED on solo
# dispatches (never a fake 1/0): a journal with no batching armed
# reads exactly as v9.
# v11 (elastic device pool): adds ``device`` — which pool member
# (pipeline/pool.py label, e.g. "dev0") this segment was dispatched
# through at drain time; after a live migration a lane's spans switch
# labels at the migration boundary, which is how the migration soak
# proves victims resumed on the survivor.  OMITTED outside a fleet
# (no pool, no label): a solo run's journal reads exactly as v10.
# Readers must tolerate mixed v1-v11 journals: rotation can leave an
# older-schema tail in the previous generation after an upgrade.
SPAN_SCHEMA_VERSION = 11

# gauge names shared between the pipeline (writer) and health() (reader)
LAST_SEGMENT_MONOTONIC = "last_segment_monotonic"
LAST_SEGMENT_UNIX = "last_segment_unix"


class SpanJournal:
    """Append-only JSONL with single-generation size rotation: when
    the active file would exceed ``max_bytes`` the previous generation
    is replaced and a fresh file starts — an always-on journal on a
    long observation can never fill the disk, and the last
    ~2 x max_bytes of spans are always on hand.  With ``compress``
    (the default) the rotated generation is gzipped to ``<path>.1.gz``
    (level 1 — ~10x smaller JSONL for one cheap pass, off the
    dispatch path since rotation happens at most once per max_bytes of
    spans); ``compress=False`` keeps the legacy plaintext ``<path>.1``.
    Readers (tools/telemetry_report.load) handle both transparently."""

    def __init__(self, path: str, max_bytes: int = 64 << 20,
                 compress: bool = True):
        if max_bytes <= 0:
            raise ValueError("max_bytes must be positive")
        self.path = path
        self.max_bytes = int(max_bytes)
        self.compress = bool(compress)
        self._lock = threading.Lock()
        # serializes gzip passes: a journal whose max_bytes fills
        # faster than one generation compresses must queue the second
        # pass, not interleave two writers into one temp file
        self._compress_lock = threading.Lock()
        self._rot_seq = 0
        self._published_seq = 0  # newest generation already in .1.gz
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # finish a rotation a previous life died in the middle of:
        # an orphaned .rotN plaintext generation becomes the legacy
        # .1 (newest wins, older orphans dropped — single-generation
        # semantics)
        base = os.path.basename(path)
        try:
            orphans = sorted(
                (os.path.join(d or ".", n)
                 for n in os.listdir(d or ".")
                 if n.startswith(base + ".rot")),
                key=lambda p: os.path.getmtime(p))
        except OSError:
            orphans = []
        for p in orphans[:-1]:
            try:
                os.unlink(p)
            except OSError:
                pass
        if orphans:
            try:
                os.replace(orphans[-1], path + ".1")
            except OSError:
                pass
        self._file = open(path, "a")
        self._size = self._file.tell()

    def write(self, record: dict) -> None:
        """Best-effort append: an I/O failure (disk full, rotation
        rename error) logs once and disables the journal — telemetry
        must never abort the observation it is describing."""
        line = json.dumps(record, sort_keys=True) + "\n"
        rotated = None
        with self._lock:
            if self._file is None:
                return
            try:
                if self._size and self._size + len(line) > self.max_bytes:
                    rotated = self._rotate()
                self._file.write(line)
                self._file.flush()
                self._size += len(line)
            except OSError as e:
                log.warning(f"[telemetry] journal {self.path} failed "
                            f"({e!r}); disabling span journal")
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
        if rotated:
            # gzip OUTSIDE the lock: concurrent writers keep
            # appending to the fresh file while the one writer that
            # tripped rotation pays the (single, per-max_bytes)
            # compress pass
            self._compress(*rotated)

    def _rotate(self) -> str | None:
        """Swap in a fresh active file (cheap: close + rename + open,
        under the lock).  Returns the renamed-out generation's path
        for :meth:`_compress` when compression is on.  The rename
        target is UNIQUE per rotation (``<path>.rotN``): a second
        rotation completing while the previous generation is still
        gzipping must not clobber the file being read, and the
        in-flight compress must not unlink a newer generation that
        reused its name."""
        self._file.close()
        if self.compress:
            self._rot_seq += 1
            plain = f"{self.path}.rot{self._rot_seq}"
        else:
            plain = self.path + ".1"
        os.replace(self.path, plain)
        self._file = open(self.path, "a")
        self._size = 0
        return (plain, self._rot_seq) if self.compress else None

    def _compress(self, plain: str, seq: int) -> None:
        """Gzip one rotated generation to ``<path>.1.gz`` (atomic via
        a per-generation temp + rename; on failure the generation is
        renamed to the legacy plaintext ``.1`` — never lost, just
        uncompressed).  Serialized by ``_compress_lock`` AND ordered
        by ``seq``: a lock alone doesn't order contenders, so a
        slower/preempted pass for an OLDER generation that loses the
        race is dropped instead of overwriting the newer ``.1.gz`` —
        single-generation semantics keep the newest."""
        import gzip
        import shutil
        with self._compress_lock:
            if seq < self._published_seq:
                # a newer generation already published while this one
                # waited: keeping ours would resurrect older data
                try:
                    os.unlink(plain)
                except OSError:
                    pass
                return
            gz = self.path + ".1.gz"
            tmp = plain + ".gz.srtb_tmp"  # unique per generation
            try:
                with open(plain, "rb") as src, \
                        gzip.open(tmp, "wb", compresslevel=1) as dst:
                    shutil.copyfileobj(src, dst)
                os.replace(tmp, gz)  # a crash mid-compress leaves
                # only the temp + the .rotN plain (swept at next
                # open), never a torn .gz
                self._published_seq = seq
                os.unlink(plain)
                # a plaintext generation from a pre-compression run
                # (or a past failed compress) must not linger as a
                # phantom second history
                try:
                    os.unlink(self.path + ".1")
                except FileNotFoundError:
                    pass
            except OSError as e:
                log.warning(f"[telemetry] journal rotation gzip "
                            f"failed ({e!r}); keeping the plaintext "
                            "generation")
                for cleanup in (tmp,):
                    try:
                        os.unlink(cleanup)
                    except OSError:
                        pass
                try:
                    os.replace(plain, self.path + ".1")
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def segment_span(segment: int, stages_s: dict, queue_depth: int,
                 detections: int, dump: bool, samples: int,
                 timestamp_ns: int = 0, extra: dict | None = None,
                 overlap_hidden_s: float | None = None,
                 inflight_depth: int | None = None,
                 active_plan: str | None = None,
                 stream: str | None = None,
                 trace_id: int | None = None,
                 device_s: float | None = None,
                 achieved_msamps: float | None = None,
                 roofline_frac: float | None = None,
                 batch_size: int | None = None,
                 batch_wait_ms: float | None = None,
                 device: str | None = None) -> dict:
    """One journal record.  ``stages_s`` maps stage name -> seconds for
    THIS segment; loss/drop counters are the cumulative registry values
    at drain time (deltas between consecutive records localize a loss
    burst to a segment).

    v2 fields: ``overlap_hidden_ms`` is the wall clock between this
    segment's dispatch returning and its fetch starting — host work
    (ingest/dispatch of later segments, sink of earlier ones) that ran
    while the device computed this segment, i.e. latency the async
    engine hid.  It is an UPPER bound on hidden device time: the host
    gap also covers time after the device already finished, so on a
    source- or sink-bound pipeline (device mostly idle) it reads high
    — interpret it together with the ingest/sink stage shares.  It is
    NOT part of ``stages_ms`` (concurrent with, not additional to, the
    staged wall clock).  Both v2 fields are OMITTED when the caller did
    not measure them (``None``) — a pipeline that overlaps but does not
    measure (ThreadedPipeline) must not journal a fake 0, which would
    read as "measured, nothing hidden".  ``inflight_depth`` counts
    dispatched-but-not-fully-drained segments (through sink completion,
    matching the ``srtb_inflight_depth`` gauge) at this segment's
    drain."""
    rec = {
        "type": "segment_span",
        "v": SPAN_SCHEMA_VERSION,
        "ts": time.time(),
        "segment": int(segment),
        "timestamp_ns": int(timestamp_ns),
        "stages_ms": {k: round(v * 1e3, 3) for k, v in stages_s.items()},
        "queue_depth": int(queue_depth),
        "detections": int(detections),
        "dump": bool(dump),
        "samples": int(samples),
        "packets_total": metrics.get("packets_total"),
        "packets_lost": metrics.get("packets_lost"),
        "segments_dropped": metrics.get("segments_dropped"),
        # v3 resilience fields (cumulative registry values at drain)
        "degrade_level": int(metrics.get("degrade_level")),
        "retries": int(metrics.get("retries_total")),
        "requeues": int(metrics.get("watchdog_requeues")),
        "restarts": int(metrics.get("worker_restarts")),
        "shed_waterfalls": int(metrics.get("shed_waterfalls")),
        "shed_baseband": int(metrics.get("shed_baseband")),
        # ingest-ring H2D accounting (cumulative at drain; deltas
        # between consecutive records give per-segment upload bytes —
        # stride_bytes warm, segment_bytes cold)
        "h2d_bytes": int(metrics.get("h2d_bytes")),
        "ring_cold_dispatches": int(metrics.get("ring_cold_dispatches")),
        # v4 self-healing compute fields (cumulative counters + the
        # ladder position gauge at drain)
        "plan_demotions": int(metrics.get("plan_demotions")),
        "plan_promotions": int(metrics.get("plan_promotions")),
        "device_reinits": int(metrics.get("device_reinits")),
        "plan_ladder_level": int(metrics.get("plan_ladder_level")),
        # v5 durable-output fields (cumulative at drain)
        "recovered_segments": int(metrics.get("recovered_segments")),
        "replayed_skips": int(metrics.get("replayed_skips")),
        "rolled_back_intents": int(metrics.get("rolled_back_intents")),
        # v8 compile/plan-cache accounting (cumulative at drain):
        # compile_ms is first-dispatch trace+compile wall (an upper
        # bound: it includes the first dispatch itself) plus exact
        # AOT-miss compile time; the cache counters localize a
        # mid-run recompile burst to a segment via deltas, like every
        # other cumulative field
        "compile_ms": round(metrics.get("compile_seconds") * 1e3, 1),
        "plan_compiles": int(metrics.get("plan_compiles")),
        "aot_cache_hits": int(metrics.get("aot_cache_hits")),
        "aot_cache_misses": int(metrics.get("aot_cache_misses")),
    }
    if overlap_hidden_s is not None:
        rec["overlap_hidden_ms"] = round(
            max(overlap_hidden_s, 0.0) * 1e3, 3)
    if inflight_depth is not None:
        rec["inflight_depth"] = int(inflight_depth)
    if device_s is not None:
        # v8: dispatch->drain-head-ready wall for THIS segment.  NOT
        # part of stages_ms (concurrent with, not additional to, the
        # host stages); omitted when unmeasured (ThreadedPipeline) —
        # never a fake 0, same rule as overlap_hidden_ms.
        rec["device_ms"] = round(max(device_s, 0.0) * 1e3, 3)
    if achieved_msamps is not None:
        rec["achieved_msamps"] = round(achieved_msamps, 2)
    if batch_size is not None:
        # v10: segments sharing this segment's device dispatch (the
        # cross-stream batch former); omitted on solo dispatches —
        # never a fake 1
        rec["batch_size"] = int(batch_size)
    if batch_wait_ms is not None:
        rec["batch_wait_ms"] = round(max(batch_wait_ms, 0.0), 3)
    if roofline_frac is not None:
        rec["roofline_frac"] = round(roofline_frac, 4)
    if active_plan is not None:
        # the plan ACTIVE AT DRAIN TIME (like every cumulative field
        # above; in overlapped mode a demotion between this segment's
        # dispatch and its drain stamps the newer plan).  Omitted when
        # the writer has no plan-aware processor (duck-typed stubs) —
        # never a fake placeholder.
        rec["active_plan"] = str(active_plan)
    if stream:
        # v6: which tenant this span belongs to (Config.stream_name;
        # the fleet stamps every lane's).  Omitted when unnamed — a
        # solo run's journal reads exactly as before.  In a NAMED
        # span the per-stream-attributable cumulative fields are the
        # stream's OWN labeled series, not the process-wide totals: a
        # healthy lane's journal must not inherit its noisy
        # neighbor's demotions/loss (retries/requeues/restarts stay
        # process-wide — their sites are not stream-labeled).
        rec["stream"] = str(stream)
        lbl = {"stream": str(stream)}
        for key in ("segments_dropped", "degrade_level",
                    "shed_waterfalls", "shed_baseband",
                    "plan_demotions", "plan_promotions",
                    "device_reinits", "plan_ladder_level",
                    # v8: compile/cache accounting is per-processor
                    # and the processor knows its stream, so a named
                    # span's books are the tenant's own
                    "plan_compiles", "aot_cache_hits",
                    "aot_cache_misses"):
            rec[key] = type(rec[key])(metrics.get(key, labels=lbl))
        rec["compile_ms"] = round(
            metrics.get("compile_seconds", labels=lbl) * 1e3, 1)
    if device:
        # v11: the pool member this segment dispatched through (the
        # fleet stamps its lanes; a migration switches the label at
        # the boundary).  Omitted outside a fleet — never a fake
        # placeholder.
        rec["device"] = str(device)
    if trace_id:
        # v7: joins this span to its flight-recorder events (omitted
        # when tracing is off — never a fake 0)
        rec["trace_id"] = int(trace_id)
    if extra:
        rec.update(extra)
    return rec


def rotated_generation(path: str) -> str | None:
    """The journal's previous on-disk generation — ``<path>.1.gz``,
    or the legacy plaintext ``<path>.1`` — or None when the journal
    has never rotated.  When BOTH exist (a failed compress left a
    newer plaintext generation next to an older .gz) the NEWER one is
    the previous generation (single-generation semantics).  Shared by
    every journal reader (tools/telemetry_report.load, the obs
    aggregator) so generation-pick policy lives in one place.  The
    mtime read races with a live journal's rotation (compress unlinks
    the .1 it just gzipped): a vanished candidate sorts oldest and
    drops out."""
    cands = [p for p in (path + ".1.gz", path + ".1")
             if os.path.exists(p)]
    if not cands:
        return None
    if len(cands) == 1:
        return cands[0]

    def _mtime(p: str) -> float:
        try:
            return os.path.getmtime(p)
        except OSError:
            return -1.0

    return max(cands, key=_mtime)


# admitted fleet streams whose liveness /healthz must track: name ->
# registration time.  Registered by StreamFleet when a lane starts,
# released when it finishes/fails — a finished stream is legitimately
# quiet and must not read as stale.
_ADMITTED_STREAMS: dict[str, float] = {}
_STREAMS_LOCK = threading.Lock()


def register_stream(name: str) -> None:
    """Admit ``name`` to per-stream staleness tracking: health() goes
    unhealthy if ANY registered stream's last segment goes stale."""
    with _STREAMS_LOCK:
        _ADMITTED_STREAMS[name] = time.monotonic()


def release_stream(name: str) -> None:
    with _STREAMS_LOCK:
        _ADMITTED_STREAMS.pop(name, None)


def admitted_streams() -> list[str]:
    with _STREAMS_LOCK:
        return sorted(_ADMITTED_STREAMS)


def mark_segment(stream: str | None = None) -> None:
    """Stamp the registry with "a segment just finished" — the signal
    health() ages against.  With ``stream`` set, also stamps that
    stream's labeled gauge so /healthz can age each admitted tenant
    independently."""
    now = time.monotonic()
    metrics.set(LAST_SEGMENT_MONOTONIC, now)
    metrics.set(LAST_SEGMENT_UNIX, time.time())
    if stream:
        metrics.set(LAST_SEGMENT_MONOTONIC, now,
                    labels={"stream": str(stream)})


def health(stale_after_s: float = 30.0) -> dict:
    """Pipeline liveness from the shared registry: ``ok`` before any
    segment (startup / idle server is healthy), ``ok`` while the last
    segment is younger than ``stale_after_s``, ``stale`` otherwise — a
    wedged accelerator or dead source flips /healthz to 503 without any
    in-process cooperation from the stuck thread.

    Multi-tenant fleet: every ADMITTED stream (register_stream) is aged
    independently against its own labeled last-segment stamp; the
    report carries a per-stream breakdown and ``ok`` is False when ANY
    admitted stream is stale — one wedged tenant must flip /healthz
    even while its neighbors keep the global stamp fresh."""
    last = metrics.get(LAST_SEGMENT_MONOTONIC)
    now = time.monotonic()
    out = {
        "segments": metrics.get("segments"),
        "signals": metrics.get("signals"),
        "stale_after_s": float(stale_after_s),
    }
    streams = admitted_streams()
    if streams:
        per = {}
        stale_streams = []
        for s in streams:
            st_last = metrics.get(LAST_SEGMENT_MONOTONIC,
                                  labels={"stream": s})
            if not st_last:
                # no segment yet: startup is healthy, exactly like
                # the solo contract — a lane still inside its first
                # cold plan compile must not flip a liveness probe
                # to 503 (and so restart the pod) at every start
                per[s] = {"last_segment_age_s": None, "ok": True}
                continue
            age = now - st_last
            per[s] = {"last_segment_age_s": round(age, 3),
                      "ok": age <= stale_after_s}
            if age > stale_after_s:
                stale_streams.append(s)
        out["streams"] = per
        if stale_streams:
            out["stale_streams"] = stale_streams
    else:
        stale_streams = []
    if not last and not streams:
        out.update(status="idle", ok=True, last_segment_age_s=None)
        return out
    age = now - last if last else None
    if age is not None:
        out["last_segment_age_s"] = round(age, 3)
    globally_stale = age is not None and age > stale_after_s
    if globally_stale or stale_streams:
        out.update(status="stale", ok=False)
    else:
        out.update(status="ok", ok=True)
    # SLO burn-rate evaluation (utils/slo.py): "degraded but within
    # budget" and "burning error budget" as distinct, scrapeable
    # states, per stream.  Deliberately NOT folded into ``ok`` — this
    # endpoint's 503 is a LIVENESS contract (restart the pod); a
    # burning SLO is an alerting concern, answered by the payload and
    # the slo_burn_rate / slo_state gauges, not by killing the
    # process that is still making (too slow / too lossy) progress.
    from srtb_tpu.utils import slo as _slo
    slo_report = _slo.evaluate()
    if slo_report is not None:
        out["slo"] = slo_report
        out["slo_ok"] = all(v.get("ok", True)
                            for v in slo_report.values())
    # elastic device pool (pipeline/pool.py): per-member state and
    # lane count, present only when a fleet published the pool gauges
    # this process.  Deliberately NOT folded into liveness ``ok``
    # either: a halted member whose lanes already live-migrated onto
    # survivors is a CAPACITY alert (the fleet_device_state gauge and
    # device_drains counter), not a reason to restart a process that
    # is still draining every stream.
    dev_states = metrics.by_label("fleet_device_state", label="device")
    if dev_states:
        _names = {0: "ok", 1: "draining", 2: "halted"}
        dev_lanes = metrics.by_label("fleet_device_lanes",
                                     label="device")
        out["devices"] = {
            d: {"state": _names.get(int(v), str(int(v))),
                "lanes": int(dev_lanes.get(d, 0))}
            for d, v in sorted(dev_states.items())}
        out["migrations"] = int(metrics.get("migrations"))
        out["device_drains"] = int(metrics.get("device_drains"))
    # detection health (quality/canary.py): present only once a
    # pulse-injection canary has been CHECKED this process — a
    # canary-off run (or one whose first canary hasn't drained)
    # reports no detection section rather than a fake "ok".  Same
    # rule as the SLO embed: NOT folded into liveness ``ok`` — a
    # sensitivity regression is an alerting/escalation concern (the
    # incident bundle + detection_health_state gauge), and restarting
    # a pipeline that still drains segments would not fix the RFI
    # environment or the broken subband that caused it.
    if metrics.get("canary_checked"):
        state = int(metrics.get("detection_health_state"))
        out["detection"] = {
            "state": "ok" if state == 0 else "degraded",
            "canary_checked": int(metrics.get("canary_checked")),
            "canary_failed": int(metrics.get("canary_failed")),
            "last_snr": round(metrics.get("canary_last_snr"), 3),
            "expected_snr": round(metrics.get("canary_expected_snr"),
                                  3),
            "sensitivity_ratio": round(
                metrics.get("canary_sensitivity_ratio"), 4),
        }
    return out
