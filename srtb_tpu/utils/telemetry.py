"""Segment-span telemetry: rotating JSONL journal + pipeline health.

The reference's per-pipe timestamp logs (SURVEY.md §5.1, §5.5) answer
"where did this segment spend its time" only via grep.  Here every
processed segment emits one structured JSONL record — segment id,
per-stage wall-clock (from the pipeline's integrated StageTimer),
queue depth, cumulative loss/drop counters, detection count and the
dump decision — to a size-rotated journal file.  Host stages are also
wrapped in ``jax.profiler.TraceAnnotation`` (pipeline/runtime.py), so
an xprof trace and the journal correlate by stage name.

``tools/telemetry_report.py`` turns a journal into per-stage percentile
tables and throughput timelines; ``health()`` feeds the ``/healthz``
endpoint (gui/server.py) with last-segment-age staleness detection.
"""

from __future__ import annotations

import json
import os
import threading
import time

from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

# v2 (async overlap engine): adds ``overlap_hidden_ms`` (host/transfer
# time hidden under device compute for this segment) and
# ``inflight_depth`` (dispatched-not-yet-drained segments at drain
# time).
# v3 (resilience): adds the degradation state at drain
# (``degrade_level``) and the cumulative recovery counters
# ``retries`` / ``requeues`` / ``restarts`` / ``shed_waterfalls`` /
# ``shed_baseband`` (same cumulative convention as
# ``segments_dropped``: deltas between consecutive records localize a
# recovery burst to a segment).
# v4 (self-healing compute): adds the cumulative ``plan_demotions`` /
# ``plan_promotions`` / ``device_reinits`` counters, the demotion-
# ladder position at drain (``plan_ladder_level``, 0 = the configured
# plan) and — when the writer knows it — ``active_plan`` (the
# SegmentProcessor.plan_name active at drain time; consecutive-record
# changes give the plan timeline).
# v5 (durable outputs): adds the cumulative crash-recovery counters
# ``recovered_segments`` (committed segments the manifest rescued
# beyond the checkpoint at startup), ``replayed_skips`` (sink pushes
# skipped on replay because the manifest already holds their commit)
# and ``rolled_back_intents`` (uncommitted artifacts rolled back by
# manifest recovery) — all zero on a run that never crashed.
# v6 (multi-tenant fleet): adds ``stream`` (the Config.stream_name
# label of the stream this span belongs to — omitted on unnamed
# single-stream runs, never a fake placeholder) so a fleet journal
# (or N per-stream journals merged) attributes every span, loss
# burst, demotion and shed to its tenant.  Readers must tolerate
# mixed v1-v6 journals: rotation can leave an older-schema tail in
# ``<path>.1`` after an upgrade.
SPAN_SCHEMA_VERSION = 6

# gauge names shared between the pipeline (writer) and health() (reader)
LAST_SEGMENT_MONOTONIC = "last_segment_monotonic"
LAST_SEGMENT_UNIX = "last_segment_unix"


class SpanJournal:
    """Append-only JSONL with single-generation size rotation: when the
    active file would exceed ``max_bytes`` it is renamed to ``<path>.1``
    (replacing the previous generation) and a fresh file starts — an
    always-on journal on a long observation can never fill the disk,
    and the last ~2 x max_bytes of spans are always on hand."""

    def __init__(self, path: str, max_bytes: int = 64 << 20):
        if max_bytes <= 0:
            raise ValueError("max_bytes must be positive")
        self.path = path
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._file = open(path, "a")
        self._size = self._file.tell()

    def write(self, record: dict) -> None:
        """Best-effort append: an I/O failure (disk full, rotation
        rename error) logs once and disables the journal — telemetry
        must never abort the observation it is describing."""
        line = json.dumps(record, sort_keys=True) + "\n"
        with self._lock:
            if self._file is None:
                return
            try:
                if self._size and self._size + len(line) > self.max_bytes:
                    self._rotate()
                self._file.write(line)
                self._file.flush()
                self._size += len(line)
            except OSError as e:
                log.warning(f"[telemetry] journal {self.path} failed "
                            f"({e!r}); disabling span journal")
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    def _rotate(self) -> None:
        self._file.close()
        os.replace(self.path, self.path + ".1")
        self._file = open(self.path, "a")
        self._size = 0

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def segment_span(segment: int, stages_s: dict, queue_depth: int,
                 detections: int, dump: bool, samples: int,
                 timestamp_ns: int = 0, extra: dict | None = None,
                 overlap_hidden_s: float | None = None,
                 inflight_depth: int | None = None,
                 active_plan: str | None = None,
                 stream: str | None = None) -> dict:
    """One journal record.  ``stages_s`` maps stage name -> seconds for
    THIS segment; loss/drop counters are the cumulative registry values
    at drain time (deltas between consecutive records localize a loss
    burst to a segment).

    v2 fields: ``overlap_hidden_ms`` is the wall clock between this
    segment's dispatch returning and its fetch starting — host work
    (ingest/dispatch of later segments, sink of earlier ones) that ran
    while the device computed this segment, i.e. latency the async
    engine hid.  It is an UPPER bound on hidden device time: the host
    gap also covers time after the device already finished, so on a
    source- or sink-bound pipeline (device mostly idle) it reads high
    — interpret it together with the ingest/sink stage shares.  It is
    NOT part of ``stages_ms`` (concurrent with, not additional to, the
    staged wall clock).  Both v2 fields are OMITTED when the caller did
    not measure them (``None``) — a pipeline that overlaps but does not
    measure (ThreadedPipeline) must not journal a fake 0, which would
    read as "measured, nothing hidden".  ``inflight_depth`` counts
    dispatched-but-not-fully-drained segments (through sink completion,
    matching the ``srtb_inflight_depth`` gauge) at this segment's
    drain."""
    rec = {
        "type": "segment_span",
        "v": SPAN_SCHEMA_VERSION,
        "ts": time.time(),
        "segment": int(segment),
        "timestamp_ns": int(timestamp_ns),
        "stages_ms": {k: round(v * 1e3, 3) for k, v in stages_s.items()},
        "queue_depth": int(queue_depth),
        "detections": int(detections),
        "dump": bool(dump),
        "samples": int(samples),
        "packets_total": metrics.get("packets_total"),
        "packets_lost": metrics.get("packets_lost"),
        "segments_dropped": metrics.get("segments_dropped"),
        # v3 resilience fields (cumulative registry values at drain)
        "degrade_level": int(metrics.get("degrade_level")),
        "retries": int(metrics.get("retries_total")),
        "requeues": int(metrics.get("watchdog_requeues")),
        "restarts": int(metrics.get("worker_restarts")),
        "shed_waterfalls": int(metrics.get("shed_waterfalls")),
        "shed_baseband": int(metrics.get("shed_baseband")),
        # ingest-ring H2D accounting (cumulative at drain; deltas
        # between consecutive records give per-segment upload bytes —
        # stride_bytes warm, segment_bytes cold)
        "h2d_bytes": int(metrics.get("h2d_bytes")),
        "ring_cold_dispatches": int(metrics.get("ring_cold_dispatches")),
        # v4 self-healing compute fields (cumulative counters + the
        # ladder position gauge at drain)
        "plan_demotions": int(metrics.get("plan_demotions")),
        "plan_promotions": int(metrics.get("plan_promotions")),
        "device_reinits": int(metrics.get("device_reinits")),
        "plan_ladder_level": int(metrics.get("plan_ladder_level")),
        # v5 durable-output fields (cumulative at drain)
        "recovered_segments": int(metrics.get("recovered_segments")),
        "replayed_skips": int(metrics.get("replayed_skips")),
        "rolled_back_intents": int(metrics.get("rolled_back_intents")),
    }
    if overlap_hidden_s is not None:
        rec["overlap_hidden_ms"] = round(
            max(overlap_hidden_s, 0.0) * 1e3, 3)
    if inflight_depth is not None:
        rec["inflight_depth"] = int(inflight_depth)
    if active_plan is not None:
        # the plan ACTIVE AT DRAIN TIME (like every cumulative field
        # above; in overlapped mode a demotion between this segment's
        # dispatch and its drain stamps the newer plan).  Omitted when
        # the writer has no plan-aware processor (duck-typed stubs) —
        # never a fake placeholder.
        rec["active_plan"] = str(active_plan)
    if stream:
        # v6: which tenant this span belongs to (Config.stream_name;
        # the fleet stamps every lane's).  Omitted when unnamed — a
        # solo run's journal reads exactly as before.  In a NAMED
        # span the per-stream-attributable cumulative fields are the
        # stream's OWN labeled series, not the process-wide totals: a
        # healthy lane's journal must not inherit its noisy
        # neighbor's demotions/loss (retries/requeues/restarts stay
        # process-wide — their sites are not stream-labeled).
        rec["stream"] = str(stream)
        lbl = {"stream": str(stream)}
        for key in ("segments_dropped", "degrade_level",
                    "shed_waterfalls", "shed_baseband",
                    "plan_demotions", "plan_promotions",
                    "device_reinits", "plan_ladder_level"):
            rec[key] = type(rec[key])(metrics.get(key, labels=lbl))
    if extra:
        rec.update(extra)
    return rec


# admitted fleet streams whose liveness /healthz must track: name ->
# registration time.  Registered by StreamFleet when a lane starts,
# released when it finishes/fails — a finished stream is legitimately
# quiet and must not read as stale.
_ADMITTED_STREAMS: dict[str, float] = {}
_STREAMS_LOCK = threading.Lock()


def register_stream(name: str) -> None:
    """Admit ``name`` to per-stream staleness tracking: health() goes
    unhealthy if ANY registered stream's last segment goes stale."""
    with _STREAMS_LOCK:
        _ADMITTED_STREAMS[name] = time.monotonic()


def release_stream(name: str) -> None:
    with _STREAMS_LOCK:
        _ADMITTED_STREAMS.pop(name, None)


def admitted_streams() -> list[str]:
    with _STREAMS_LOCK:
        return sorted(_ADMITTED_STREAMS)


def mark_segment(stream: str | None = None) -> None:
    """Stamp the registry with "a segment just finished" — the signal
    health() ages against.  With ``stream`` set, also stamps that
    stream's labeled gauge so /healthz can age each admitted tenant
    independently."""
    now = time.monotonic()
    metrics.set(LAST_SEGMENT_MONOTONIC, now)
    metrics.set(LAST_SEGMENT_UNIX, time.time())
    if stream:
        metrics.set(LAST_SEGMENT_MONOTONIC, now,
                    labels={"stream": str(stream)})


def health(stale_after_s: float = 30.0) -> dict:
    """Pipeline liveness from the shared registry: ``ok`` before any
    segment (startup / idle server is healthy), ``ok`` while the last
    segment is younger than ``stale_after_s``, ``stale`` otherwise — a
    wedged accelerator or dead source flips /healthz to 503 without any
    in-process cooperation from the stuck thread.

    Multi-tenant fleet: every ADMITTED stream (register_stream) is aged
    independently against its own labeled last-segment stamp; the
    report carries a per-stream breakdown and ``ok`` is False when ANY
    admitted stream is stale — one wedged tenant must flip /healthz
    even while its neighbors keep the global stamp fresh."""
    last = metrics.get(LAST_SEGMENT_MONOTONIC)
    now = time.monotonic()
    out = {
        "segments": metrics.get("segments"),
        "signals": metrics.get("signals"),
        "stale_after_s": float(stale_after_s),
    }
    streams = admitted_streams()
    if streams:
        per = {}
        stale_streams = []
        for s in streams:
            st_last = metrics.get(LAST_SEGMENT_MONOTONIC,
                                  labels={"stream": s})
            if not st_last:
                # no segment yet: startup is healthy, exactly like
                # the solo contract — a lane still inside its first
                # cold plan compile must not flip a liveness probe
                # to 503 (and so restart the pod) at every start
                per[s] = {"last_segment_age_s": None, "ok": True}
                continue
            age = now - st_last
            per[s] = {"last_segment_age_s": round(age, 3),
                      "ok": age <= stale_after_s}
            if age > stale_after_s:
                stale_streams.append(s)
        out["streams"] = per
        if stale_streams:
            out["stale_streams"] = stale_streams
    else:
        stale_streams = []
    if not last and not streams:
        out.update(status="idle", ok=True, last_segment_age_s=None)
        return out
    age = now - last if last else None
    if age is not None:
        out["last_segment_age_s"] = round(age, 3)
    globally_stale = age is not None and age > stale_after_s
    if globally_stale or stale_streams:
        out.update(status="stale", ok=False)
    else:
        out.update(status="ok", ok=True)
    return out
