"""Fault handling: signal handlers with stack traces.

Mirrors the reference's termination handler (ref: util/termination_handler.
hpp:38-113: std::terminate + SIGTERM/SEGV/INT/ILL/ABRT/FPE handlers
printing a boost::stacktrace then chaining to the original handlers).
Python's ``faulthandler`` covers the hard faults; sys.excepthook and
signal handlers cover the rest.
"""

from __future__ import annotations

import faulthandler
import signal
import sys
import traceback

from srtb_tpu.utils.logging import log

_installed = False


def install_termination_handler() -> None:
    global _installed
    if _installed:
        return
    _installed = True
    # SIGSEGV/SIGFPE/SIGABRT/SIGILL -> stack dump (like boost::stacktrace)
    faulthandler.enable(all_threads=True)

    def _excepthook(exc_type, exc, tb):
        log.error("[termination_handler] uncaught exception:")
        for line in traceback.format_exception(exc_type, exc, tb):
            log.error(line.rstrip())
        sys.__excepthook__(exc_type, exc, tb)

    sys.excepthook = _excepthook

    def _signal_handler(signum, frame):
        log.error(f"[termination_handler] received signal {signum}")
        traceback.print_stack(frame)
        # chain to default behavior (ref chains to original handlers)
        signal.signal(signum, signal.SIG_DFL)
        signal.raise_signal(signum)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _signal_handler)
        except (ValueError, OSError):
            pass  # not main thread or unsupported
