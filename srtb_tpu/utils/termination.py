"""Fault handling: signal handlers with stack traces.

Mirrors the reference's termination handler (ref: util/termination_handler.
hpp:38-113: std::terminate + SIGTERM/SEGV/INT/ILL/ABRT/FPE handlers
printing a boost::stacktrace then chaining to the original handlers).
Python's ``faulthandler`` covers the hard faults; sys.excepthook and
signal handlers cover the rest.
"""

from __future__ import annotations

import faulthandler
import signal
import sys
import threading
import time
import traceback

from srtb_tpu.utils.logging import log

_installed = False

# Thread-join audit (PR 3 satellite): every thread the runtime spawns
# and where it is joined on shutdown —
# - pipeline sink pipe ("sink_drain"): joined in Pipeline.run finally;
# - ThreadedPipeline pipes ("source"/"device"/"drain"): joined by
#   framework.on_exit;
# - AsyncWriterPool workers: joined by pool.close() / GC finalizer
#   (Pipeline.close closes an owned pool);
# - DropOldestSegmentBuffer pump: joined (5 s) in close();
# - UDP receiver threads: joined in the receivers' close();
# - WaterfallHTTPServer: joined in stop() (leak fixed in PR 3);
# - sync_with_deadline watchdog Timers: daemon, cancelled in finally.
# The helpers below let the sanitizer assert this list stays true.

# pools that legitimately outlive one pipeline run (owned by objects
# with their own close()): lazily-spawned worker threads of these
# prefixes are not "leaks" of the run that first used them.
# "srtb-writer": the Python-fallback AsyncWriterPool spawns workers on
# first submit (mid-run) and joins them at Pipeline.close(), after run()
LEAK_ALLOW_PREFIXES = ("ThreadPoolExecutor", "srtb-writer", "pydevd",
                       "asyncio_")


def tag_thread(thread: threading.Thread) -> None:
    """Stamp ``thread`` with the file:line that constructed it, so
    leak/wedge reports name the spawn site instead of just the thread
    name.  The site recorded is the first frame OUTSIDE the calling
    module (the wrapper — Pipe.__init__, a receiver constructor —
    is not the interesting site; whoever asked for the thread is);
    when the whole stack is in one file, the immediate caller is
    kept.  Cheap: frame walking only, no stack formatting."""
    f = sys._getframe(1)
    wrapper_file = f.f_code.co_filename
    g = f
    while g is not None and g.f_code.co_filename == wrapper_file:
        g = g.f_back
    f = g or f
    thread._srtb_created_at = (f"{f.f_code.co_filename}:"
                               f"{f.f_lineno}")


def created_at(thread: threading.Thread) -> str | None:
    """The creation site stamped by :func:`tag_thread`, or None for
    threads spawned outside the instrumented paths."""
    return getattr(thread, "_srtb_created_at", None)


def describe_threads(threads) -> str:
    """One-line-per-thread description with the creation site when
    known — the leaked-thread report's attribution."""
    parts = []
    for t in threads:
        site = created_at(t)
        parts.append(f"'{t.name}'"
                     + (f" (created at {site})" if site else ""))
    return ", ".join(parts)


def thread_snapshot() -> set[int]:
    """Idents of currently-live threads (leak-check baseline)."""
    return {t.ident for t in threading.enumerate()}


def leaked_threads(snapshot: set[int], grace_s: float = 1.0,
                   allow_prefixes=LEAK_ALLOW_PREFIXES) -> list:
    """Threads alive now that were not in ``snapshot``, after giving
    stragglers ``grace_s`` to finish joining.  Used by the runtime
    sanitizer to assert a pipeline run cleans up every thread it
    spawned (a leaked sink/pump thread keeps buffers and file handles
    pinned for the rest of the process)."""
    deadline = time.monotonic() + max(0.0, grace_s)
    while True:
        leaked = [
            t for t in threading.enumerate()
            if t.ident not in snapshot and t.is_alive()
            and t is not threading.current_thread()
            and not any(t.name.startswith(p) for p in allow_prefixes)]
        if not leaked or time.monotonic() >= deadline:
            return leaked
        time.sleep(0.02)


def format_thread_stacks(threads) -> str:
    """Current stack of each given thread (via ``sys._current_frames``)
    — what a wedged pipe was doing when the bounded join gave up."""
    frames = sys._current_frames()
    parts = []
    for t in threads:
        site = created_at(t)
        header = (f"--- thread {t.name!r} (ident {t.ident}, "
                  f"daemon={t.daemon}"
                  + (f", created at {site}" if site else "")
                  + ") ---")
        frame = frames.get(t.ident)
        if frame is None:
            parts.append(header + "\n  <no frame: already exiting>")
        else:
            parts.append(header + "\n"
                         + "".join(traceback.format_stack(frame)))
    return "\n".join(parts)


def report_wedged(threads, context: str) -> None:
    """Leaked/wedged-thread report for bounded shutdown paths
    (framework.on_exit, the pipeline's bounded sink join): one loud
    log block with each thread's name and current stack, plus the
    ``wedged_threads`` counter so a quietly-wedging deployment shows
    on /metrics."""
    threads = [t for t in threads if t.is_alive()]
    if not threads:
        return
    from srtb_tpu.utils.metrics import metrics
    metrics.add("wedged_threads", len(threads))
    log.error(f"[termination] {len(threads)} thread(s) still alive "
              f"after {context}:")
    for line in format_thread_stacks(threads).splitlines():
        log.error(line)


def install_termination_handler() -> None:
    global _installed
    if _installed:
        return
    _installed = True
    # SIGSEGV/SIGFPE/SIGABRT/SIGILL -> stack dump (like boost::stacktrace)
    faulthandler.enable(all_threads=True)

    def _excepthook(exc_type, exc, tb):
        log.error("[termination_handler] uncaught exception:")
        for line in traceback.format_exception(exc_type, exc, tb):
            log.error(line.rstrip())
        sys.__excepthook__(exc_type, exc, tb)

    sys.excepthook = _excepthook

    def _signal_handler(signum, frame):
        log.error(f"[termination_handler] received signal {signum}")
        traceback.print_stack(frame)
        # chain to default behavior (ref chains to original handlers)
        signal.signal(signum, signal.SIG_DFL)
        signal.raise_signal(signum)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _signal_handler)
        except (ValueError, OSError):
            pass  # not main thread or unsupported
