"""Leveled colored logging with seconds-since-start prefix.

Mirrors the behavior of the reference logger (ref: log/log.hpp:23-128):
levels NONE/ERROR/WARNING/INFO/DEBUG, runtime level from the
``SRTB_LOG_LEVEL`` environment variable or the ``log_level`` config option,
and a ``[+seconds]`` relative-timestamp prefix on every line.
"""

from __future__ import annotations

import os
import sys
import threading
import time

_START_TIME = time.monotonic()

LEVEL_NONE = 0
LEVEL_ERROR = 1
LEVEL_WARNING = 2
LEVEL_INFO = 3
LEVEL_DEBUG = 4

_LEVEL_NAMES = {
    LEVEL_ERROR: ("E", "\033[31m"),  # red
    LEVEL_WARNING: ("W", "\033[33m"),  # yellow
    LEVEL_INFO: ("I", "\033[32m"),  # green
    LEVEL_DEBUG: ("D", "\033[36m"),  # cyan
}
_RESET = "\033[0m"

_lock = threading.Lock()


def _default_level() -> int:
    env = os.environ.get("SRTB_LOG_LEVEL", "")
    try:
        return int(env)
    except ValueError:
        return LEVEL_INFO


class Logger:
    """Process-wide leveled logger; thread-safe line output."""

    def __init__(self, name: str = "srtb", level: int | None = None,
                 stream=None):
        self.name = name
        self.level = _default_level() if level is None else level
        self.stream = stream if stream is not None else sys.stderr

    def _log(self, level: int, *args) -> None:
        if level > self.level:
            return
        tag, color = _LEVEL_NAMES[level]
        elapsed = time.monotonic() - _START_TIME
        use_color = hasattr(self.stream, "isatty") and self.stream.isatty()
        prefix = f"[{tag} +{elapsed:.6f}s]"
        if use_color:
            prefix = f"{color}{prefix}{_RESET}"
        msg = " ".join(str(a) for a in args)
        with _lock:
            print(f"{prefix} {msg}", file=self.stream, flush=True)

    def error(self, *args) -> None:
        self._log(LEVEL_ERROR, *args)

    def warning(self, *args) -> None:
        self._log(LEVEL_WARNING, *args)

    def info(self, *args) -> None:
        self._log(LEVEL_INFO, *args)

    def debug(self, *args) -> None:
        self._log(LEVEL_DEBUG, *args)


log = Logger()


def get_logger() -> Logger:
    return log
