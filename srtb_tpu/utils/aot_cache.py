"""Ahead-of-time executable persistence — the warm-restart fallback.

The persistent XLA compilation cache (utils/compile_cache.py) is the
first line against the staged 2^30 plan's ~11-minute cold compile; but
if the deployment's remote-compile service bypasses the local cache, a
mid-observation restart is an 11-minute outage.  This module persists
the *compiled executables themselves* via
``jax.experimental.serialize_executable`` so a restarted process loads
and runs them without recompiling — the strong form of the reference's
FFTW-wisdom persistence (ref: fft/fftw_wrapper.hpp:196-238: plans are
re-created per run from wisdom; here the "plan" IS the executable).

Safety model:
- Blobs are keyed by SHA-256 of (jax version, backend platform, device
  kind, program name, plan signature) — a changed config, JAX upgrade,
  or different accelerator generation misses cleanly and recompiles.
  The plan signature (SegmentProcessor.plan_signature) allowlists every
  trace-shaping config field, including the overlap engine's knobs
  (``inflight_segments``, ``micro_batch_segments``) and the input
  donation flag: a restarted process with different overlap settings
  can never load a stale executable whose donation/aliasing or batch
  shape no longer matches.
- CPU backends are OFF by default, same policy and same reason as
  compile_cache.enable_compile_cache: XLA:CPU AOT machine code is keyed
  without host CPU features, and a stale entry after a host swap can
  SIGILL (observed round 4).  Tests opt in with ``allow_cpu=True``
  (save + load on one host is safe); deployments can force it with
  SRTB_AOT_ALLOW_CPU=1.
- Deserialization failures of any kind fall back to a fresh compile —
  the cache can cost a recompile, never correctness.
"""

from __future__ import annotations

import hashlib
import os
import pickle

from srtb_tpu.utils.logging import log


def _device_key() -> str:
    import jax

    dev = jax.devices()[0]
    return f"{jax.__version__}/{dev.platform}/{dev.device_kind}"


def cpu_allowed() -> bool:
    return bool(int(os.environ.get("SRTB_AOT_ALLOW_CPU", "0")))


class AotPlanCache:
    """Directory of serialized compiled executables, one file per
    (program name, plan signature, device key)."""

    def __init__(self, root: str, allow_cpu: bool = False,
                 labels: dict | None = None):
        self.root = root
        self.allow_cpu = allow_cpu or cpu_allowed()
        # per-stream labeled twins for the hit/miss/compile counters
        # (multi-tenant fleet: cache economics must be attributable
        # to the tenant that paid the compile)
        self.labels = dict(labels) if labels else None
        os.makedirs(root, exist_ok=True)

    def _count(self, name: str, value: float = 1.0) -> None:
        from srtb_tpu.utils.metrics import metrics
        metrics.add(name, value)
        if self.labels:
            metrics.add(name, value, labels=self.labels)

    def enabled(self) -> bool:
        import jax

        if jax.default_backend() == "cpu" and not self.allow_cpu:
            log.debug("[aot_cache] skipped on CPU (host-fragile AOT); "
                      "set SRTB_AOT_ALLOW_CPU=1 to force")
            return False
        return True

    def _path(self, name: str, signature: str) -> str:
        h = hashlib.sha256(
            f"{_device_key()}|{name}|{signature}".encode()).hexdigest()
        return os.path.join(self.root, f"{name}.{h[:16]}.aot")

    def load(self, name: str, signature: str):
        """Deserialized compiled executable, or None on miss/any error."""
        if not self.enabled():
            return None
        path = self._path(name, signature)
        if not os.path.exists(path):
            return None
        try:
            import jax
            from jax.experimental.serialize_executable import (
                deserialize_and_load)

            with open(path, "rb") as f:
                blob, in_tree, out_tree = pickle.load(f)
            # pin execution to device 0: the segment plans are
            # single-device programs, and the default (all local
            # devices) makes the loaded executable demand one shard
            # per device on multi-device hosts (e.g. the forced
            # 8-device CPU test platform).  Older jax releases do not
            # take the kwarg — fall back to the default placement
            # (single-device hosts are unaffected).
            try:
                compiled = deserialize_and_load(
                    blob, in_tree, out_tree,
                    execution_devices=[jax.devices()[0]])
            except TypeError:
                compiled = deserialize_and_load(blob, in_tree, out_tree)
            log.info(f"[aot_cache] loaded {name} from {path}")
            self._count("aot_cache_hits")
            return compiled
        except Exception as e:  # corrupt blob / jax drift: recompile
            log.warning(f"[aot_cache] load failed for {name}: {e}; "
                        "recompiling")
            return None

    def save(self, name: str, signature: str, compiled) -> str | None:
        if not self.enabled():
            return None
        path = self._path(name, signature)
        try:
            from jax.experimental.serialize_executable import serialize

            payload = serialize(compiled)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(payload, f)
            os.replace(tmp, path)  # atomic: a crashed save never
            # leaves a truncated blob for the next start to trip on
            log.info(f"[aot_cache] saved {name} -> {path}")
            return path
        except Exception as e:  # pragma: no cover - backend quirk
            log.warning(f"[aot_cache] save failed for {name}: {e}")
            return None

    def get_or_compile(self, name: str, signature: str, jitted, *example):
        """Cached executable for ``jitted`` (a jax.jit wrapper), compiling
        + persisting on miss.  ``example`` entries only need shape/dtype
        (jax.ShapeDtypeStruct works)."""
        compiled = self.load(name, signature)
        if compiled is None:
            # AOT-protocol compile accounting: unlike the lazy-jit
            # first-dispatch timer (pipeline/segment.py), this measures
            # the compile EXACTLY — lower+compile with no execution in
            # the window
            import time
            t0 = time.perf_counter()
            compiled = jitted.lower(*example).compile()
            dt = time.perf_counter() - t0
            self._count("aot_cache_misses")
            self._count("plan_compiles")
            self._count("compile_seconds", dt)
            from srtb_tpu.utils.metrics import metrics
            metrics.set("last_compile_ms", dt * 1e3)
            self.save(name, signature, compiled)
        return compiled
