"""Per-stream SLO burn-rate evaluation.

The /healthz staleness probe answers "is it alive"; the degrade and
demotion ladders answer "is it coping".  Neither answers the operator
question that decides whether to page: **are we spending error budget
faster than we can afford** — "degraded but within budget" and
"burning error budget" are different states, and conflating them
either pages on every transient or sleeps through a slow burn.

Three configurable objectives, each evaluated per stream (the flat
process-wide series doubles as the solo pipeline's stream):

- **latency**  (``slo_latency_ms`` > 0 arms): a segment is *bad* when
  its host wall clock (the span's summed stages) exceeds the target;
  the budget is ``slo_latency_budget`` (allowed bad fraction).
- **loss**     (``slo_loss_budget`` > 0 arms): bad fraction =
  dropped / (drained + dropped) — accounted whole-segment loss only,
  the same quantity ``segments_dropped`` counts.
- **staleness** (``slo_staleness_s`` > 0 arms): bad time = seconds the
  stream has gone beyond the allowed gap since its last segment; the
  budget is ``slo_staleness_budget`` (allowed stale fraction of the
  window).
- **sensitivity** (``slo_sensitivity_budget`` > 0 arms): a checked
  pulse-injection canary (srtb_tpu/quality/canary.py) is *bad* when
  its recovered S/N falls below ``canary_min_ratio`` of the expected
  reference; the budget is the allowed bad fraction of checks.
  Canaries are sparse (one per ``canary_every_segments``), so size
  the windows to hold several checks or the fast burn quantizes.

Each objective is evaluated over TWO windows — ``slo_fast_window_s``
(default 5 min) and ``slo_slow_window_s`` (default 1 h) — the standard
multi-window burn-rate recipe: **burn = bad_fraction / budget** (1.0 =
spending exactly the budget), and a stream is *burning* only when BOTH
windows exceed ``slo_burn_threshold`` — the fast window makes the
alert prompt, the slow window keeps a brief spike from paging.  States:

- ``ok``        no violations in the slow window;
- ``degraded``  violations present, burn below threshold (within
  budget — visible, not pageable);
- ``burning``   both windows above threshold.

Every evaluation lands in the metrics registry as labeled gauges —
``slo_burn_rate{objective=,window=[,stream=]}`` and
``slo_state{objective=[,stream=]}`` (0 ok / 1 degraded / 2 burning) —
so Prometheus alerting and /healthz (which embeds :func:`evaluate`'s
report) see the same numbers.  State transitions also emit ``slo``
events onto the flight recorder.

Like the metrics registry and the event hub, the tracker is
process-global: ``configure(cfg)`` arms it (Pipeline.__init__ calls
this; fleet lanes share one tracker and are told apart by stream).
"""

from __future__ import annotations

import threading
import time

from srtb_tpu.utils import events
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

OBJECTIVES = ("latency", "loss", "staleness", "sensitivity")
STATE_OK = "ok"
STATE_DEGRADED = "degraded"
STATE_BURNING = "burning"
_STATE_CODE = {STATE_OK: 0, STATE_DEGRADED: 1, STATE_BURNING: 2}


class _Ratio:
    """bad/total over one trailing window, in FIXED time buckets.

    A deque-of-events window stores one tuple per observation for the
    whole window — at tens of segments/s over a 1-hour slow window
    that is ~10^5 retained tuples per series per stream, for a metric
    that only ever needs a ratio.  ``n_buckets`` counters (epoch-
    stamped, recycled in place) compute the same burn fractions in
    O(buckets) memory and O(1) per add, at a granularity of
    window/n_buckets (irrelevant against the burn thresholds).
    Not self-locking: the owning tracker serializes access."""

    __slots__ = ("bucket_s", "n", "tot", "bad", "stamp", "_clock")

    def __init__(self, window_s: float, clock, n_buckets: int = 60):
        self.n = int(n_buckets)
        self.bucket_s = float(window_s) / self.n
        self.tot = [0.0] * self.n
        self.bad = [0.0] * self.n
        self.stamp = [-1] * self.n   # epoch index currently held
        self._clock = clock

    def _slot(self) -> int:
        k = int(self._clock() // self.bucket_s)
        i = k % self.n
        if self.stamp[i] != k:  # recycle an expired bucket in place
            self.stamp[i] = k
            self.tot[i] = 0.0
            self.bad[i] = 0.0
        return i

    def add(self, n: float, bad: float) -> None:
        i = self._slot()
        self.tot[i] += n
        self.bad[i] += bad

    def total(self) -> float:
        kmin = int(self._clock() // self.bucket_s) - self.n + 1
        return sum(t for t, s in zip(self.tot, self.stamp)
                   if s >= kmin)

    def fraction(self) -> tuple[float, float]:
        kmin = int(self._clock() // self.bucket_s) - self.n + 1
        t = b = 0.0
        for i in range(self.n):
            if self.stamp[i] >= kmin:
                t += self.tot[i]
                b += self.bad[i]
        return (b / t if t > 0 else 0.0), b


class _StreamState:
    def __init__(self, fast_s: float, slow_s: float, clock):
        self.lat = (_Ratio(fast_s, clock), _Ratio(slow_s, clock))
        self.loss = (_Ratio(fast_s, clock), _Ratio(slow_s, clock))
        self.sens = (_Ratio(fast_s, clock), _Ratio(slow_s, clock))
        self.last_segment: float | None = None
        self.states: dict[str, str] = {}


class SloTracker:
    """Burn-rate state for every observed stream ("" = the solo /
    process-wide pipeline).  Thread-safe: segments feed from engine or
    sink threads, the scraper evaluates from the HTTP thread."""

    def __init__(self, latency_ms: float = 0.0,
                 latency_budget: float = 0.01,
                 loss_budget: float = 0.0,
                 staleness_s: float = 0.0,
                 staleness_budget: float = 0.05,
                 sensitivity_budget: float = 0.0,
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0,
                 burn_threshold: float = 1.0,
                 clock=time.monotonic):
        self.latency_ms = float(latency_ms)
        self.latency_budget = max(1e-9, float(latency_budget))
        self.loss_budget = float(loss_budget)
        self.staleness_s = float(staleness_s)
        self.staleness_budget = max(1e-9, float(staleness_budget))
        self.sensitivity_budget = float(sensitivity_budget)
        self.fast_s = float(fast_window_s)
        self.slow_s = float(slow_window_s)
        self.threshold = float(burn_threshold)
        self._clock = clock
        self._lock = threading.Lock()
        self._streams: dict[str, _StreamState] = {}

    @property
    def objectives(self) -> tuple[str, ...]:
        out = []
        if self.latency_ms > 0:
            out.append("latency")
        if self.loss_budget > 0:
            out.append("loss")
        if self.staleness_s > 0:
            out.append("staleness")
        if self.sensitivity_budget > 0:
            out.append("sensitivity")
        return tuple(out)

    @classmethod
    def from_config(cls, cfg) -> "SloTracker | None":
        """None (zero-cost off) when no objective is armed."""
        t = cls(
            latency_ms=float(getattr(cfg, "slo_latency_ms", 0.0) or 0),
            latency_budget=float(getattr(cfg, "slo_latency_budget",
                                         0.01)),
            loss_budget=float(getattr(cfg, "slo_loss_budget", 0.0)
                              or 0),
            staleness_s=float(getattr(cfg, "slo_staleness_s", 0.0)
                              or 0),
            staleness_budget=float(getattr(cfg, "slo_staleness_budget",
                                           0.05)),
            sensitivity_budget=float(getattr(
                cfg, "slo_sensitivity_budget", 0.0) or 0),
            fast_window_s=float(getattr(cfg, "slo_fast_window_s",
                                        300.0)),
            slow_window_s=float(getattr(cfg, "slo_slow_window_s",
                                        3600.0)),
            burn_threshold=float(getattr(cfg, "slo_burn_threshold",
                                         1.0)))
        return t if t.objectives else None

    # ------------------------------------------------------- feeding

    def _state(self, stream: str) -> _StreamState:
        st = self._streams.get(stream)
        if st is None:
            with self._lock:
                st = self._streams.setdefault(
                    stream, _StreamState(self.fast_s, self.slow_s,
                                         self._clock))
        return st

    def note_segment(self, stream: str, latency_s: float) -> None:
        """One drained segment: feeds the latency ratio and the loss
        denominator, and refreshes the staleness stamp.  The bucket
        counters are not self-locking — the tracker lock serializes
        feeders (engine/sink threads) against the scraper."""
        st = self._state(stream or "")
        bad = 1.0 if (self.latency_ms > 0
                      and latency_s * 1e3 > self.latency_ms) else 0.0
        with self._lock:
            for r in st.lat:
                r.add(1.0, bad)
            for r in st.loss:
                r.add(1.0, 0.0)
            st.last_segment = self._clock()

    def note_dropped(self, stream: str, n: int = 1) -> None:
        """``n`` accounted whole-segment drops."""
        st = self._state(stream or "")
        with self._lock:
            for r in st.loss:
                r.add(float(n), float(n))

    def note_canary(self, stream: str, ok: bool) -> None:
        """One checked pulse-injection canary: bad when the recovered
        S/N failed the sensitivity gate."""
        st = self._state(stream or "")
        bad = 0.0 if ok else 1.0
        with self._lock:
            for r in st.sens:
                r.add(1.0, bad)

    # ---------------------------------------------------- evaluation

    def _burns(self, st: _StreamState, objective: str,
               now: float) -> tuple[float, float, float]:
        """(burn_fast, burn_slow, bad_slow) for one objective."""
        if objective == "latency":
            (ff, _), (fs, bs) = (st.lat[0].fraction(),
                                 st.lat[1].fraction())
            return (ff / self.latency_budget,
                    fs / self.latency_budget, bs)
        if objective == "loss":
            (ff, _), (fs, bs) = (st.loss[0].fraction(),
                                 st.loss[1].fraction())
            return ff / self.loss_budget, fs / self.loss_budget, bs
        if objective == "sensitivity":
            (ff, _), (fs, bs) = (st.sens[0].fraction(),
                                 st.sens[1].fraction())
            return (ff / self.sensitivity_budget,
                    fs / self.sensitivity_budget, bs)
        # staleness: time beyond the allowed gap, as a window fraction
        if st.last_segment is None:
            return 0.0, 0.0, 0.0  # startup: no budget spent yet
        over = max(0.0, (now - st.last_segment) - self.staleness_s)
        bf = (min(over, self.fast_s) / self.fast_s) \
            / self.staleness_budget
        bs = (min(over, self.slow_s) / self.slow_s) \
            / self.staleness_budget
        return bf, bs, over

    def evaluate(self) -> dict:
        """stream -> objective -> {burn_fast, burn_slow, state}; also
        refreshes the ``slo_burn_rate`` / ``slo_state`` gauges and
        emits an ``slo`` event on every state transition."""
        now = self._clock()
        with self._lock:
            streams = dict(self._streams)
        out = {}
        for stream, st in sorted(streams.items()):
            per = {}
            for obj in self.objectives:
                with self._lock:
                    bf, bs, bad = self._burns(st, obj, now)
                    if bf >= self.threshold and bs >= self.threshold:
                        state = STATE_BURNING
                    elif bad > 0:
                        state = STATE_DEGRADED
                    else:
                        state = STATE_OK
                    # claim the transition ATOMICALLY: /metrics and
                    # /healthz both evaluate from the threaded HTTP
                    # server, and two scrapes crossing a threshold at
                    # once must emit/log the transition exactly once.
                    # A never-evaluated objective baselines at "ok":
                    # a stream that is already burning at its FIRST
                    # scrape must emit the onset, not swallow it.
                    prev = st.states.get(obj, STATE_OK)
                    st.states[obj] = state
                changed = prev != state
                per[obj] = {"burn_fast": round(bf, 4),
                            "burn_slow": round(bs, 4),
                            "state": state}
                base = {"objective": obj}
                if stream:
                    base["stream"] = stream
                metrics.set("slo_burn_rate", bf,
                            labels=dict(base, window="fast"))
                metrics.set("slo_burn_rate", bs,
                            labels=dict(base, window="slow"))
                metrics.set("slo_state", _STATE_CODE[state],
                            labels=base)
                if changed:
                    events.emit("slo", trace=0, stream=stream,
                                info=f"{obj}:{prev}->{state}")
                    lvl = (log.warning if state == STATE_BURNING
                           else log.info)
                    lvl(f"[slo] {stream or 'pipeline'}/{obj}: "
                        f"{prev} -> {state} (burn fast {bf:.2f} / "
                        f"slow {bs:.2f})")
            per["ok"] = all(v["state"] != STATE_BURNING
                            for k, v in per.items() if k != "ok")
            out[stream or "_pipeline"] = per
        return out


# ---------------------------------------------------------------------
# process-global tracker (the /healthz + /metrics view)
# ---------------------------------------------------------------------

tracker: SloTracker | None = None


def configure(cfg) -> "SloTracker | None":
    """Arm the process-global tracker from ``cfg`` (None when no
    objective is configured — zero-cost off).  An armed tracker with
    identical parameters is KEPT (fleet lanes must not wipe each
    other's windows)."""
    global tracker
    new = SloTracker.from_config(cfg)
    if new is None:
        # deliberately NOT disarming a live tracker: in a fleet, a
        # lane without objectives must not blind its neighbors'
        cur = tracker
        return cur
    cur = tracker
    if cur is not None and (
            cur.latency_ms, cur.latency_budget, cur.loss_budget,
            cur.staleness_s, cur.staleness_budget,
            cur.sensitivity_budget, cur.fast_s,
            cur.slow_s, cur.threshold) == (
            new.latency_ms, new.latency_budget, new.loss_budget,
            new.staleness_s, new.staleness_budget,
            new.sensitivity_budget, new.fast_s,
            new.slow_s, new.threshold):
        return cur
    tracker = new
    return new


def reset() -> None:
    """Disarm (tests)."""
    global tracker
    tracker = None


def note_segment(stream: str, latency_s: float) -> None:
    t = tracker
    if t is not None:
        t.note_segment(stream, latency_s)


def note_dropped(stream: str, n: int = 1) -> None:
    t = tracker
    if t is not None:
        t.note_dropped(stream, n)


def note_canary(stream: str, ok: bool) -> None:
    t = tracker
    if t is not None:
        t.note_canary(stream, ok)


def evaluate() -> dict | None:
    """The /healthz + /metrics refresh hook: None when disarmed."""
    t = tracker
    return t.evaluate() if t is not None else None
