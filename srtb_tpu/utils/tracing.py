"""Profiling/tracing hooks.

The reference has only ad-hoc timing (per-pipe debug logs, benchmark
harness in test-fft_wrappers, hand-recorded kernel timings — SURVEY.md
§5.1).  On TPU the native story is better: ``jax.profiler`` traces
(viewable in xprof/tensorboard) plus lightweight wall-clock stage timers.
"""

from __future__ import annotations

import contextlib
import time

from srtb_tpu.utils.logging import log


@contextlib.contextmanager
def device_trace(trace_dir: str):
    """Capture a jax profiler trace to ``trace_dir`` (xprof format)."""
    import jax

    try:
        jax.profiler.start_trace(trace_dir)
        started = True
        log.info(f"[tracing] jax profiler trace -> {trace_dir}")
    except Exception as e:  # backend without profiler support
        log.warning(f"[tracing] profiler unavailable: {e}")
        started = False
    try:
        yield
    finally:
        if started:
            jax.profiler.stop_trace()


class StageTimer:
    """Accumulates wall-clock per named stage; the per-pipe-timestamp logs
    of the reference, queryable instead of grep-able."""

    def __init__(self):
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def summary(self) -> dict:
        return {name: {"total_s": round(t, 6),
                       "count": self.counts[name],
                       "mean_ms": round(1e3 * t / self.counts[name], 3)}
                for name, t in sorted(self.totals.items())}
