"""Profiling/tracing hooks.

The reference has only ad-hoc timing (per-pipe debug logs, benchmark
harness in test-fft_wrappers, hand-recorded kernel timings — SURVEY.md
§5.1).  On TPU the native story is better: ``jax.profiler`` traces
(viewable in xprof/tensorboard) plus lightweight wall-clock stage timers.
"""

from __future__ import annotations

import contextlib
import threading
import time

from srtb_tpu.utils.logging import log


@contextlib.contextmanager
def trace_annotation(name: str):
    """``jax.profiler.TraceAnnotation`` when available (shows host-side
    stage extents on the xprof timeline, correlating the span journal
    with device traces by stage name); a no-op on backends without it.
    Importing jax lazily keeps pure-host tools (telemetry_report) free
    of the jax import cost."""
    try:
        import jax

        cm = jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler-less backend
        cm = contextlib.nullcontext()
    with cm:
        yield


@contextlib.contextmanager
def device_trace(trace_dir: str):
    """Capture a jax profiler trace to ``trace_dir`` (xprof format)."""
    import jax

    try:
        jax.profiler.start_trace(trace_dir)
        started = True
        log.info(f"[tracing] jax profiler trace -> {trace_dir}")
    except Exception as e:  # backend without profiler support
        log.warning(f"[tracing] profiler unavailable: {e}")
        started = False
    try:
        yield
    finally:
        if started:
            jax.profiler.stop_trace()


class ProfileCapture:
    """On-demand ``jax.profiler`` capture of the first N drained
    segments of a run (``Config.profile_capture_segments``): a REAL
    XLA/device trace recorded into ``Config.profile_capture_dir``,
    next to the Perfetto event export (tools/trace_export.py), so the
    device-level timeline and the causal-event timeline line up — the
    sidecar ``capture.json`` records the first/last trace_id and
    segment index covered, and the journal spans carry the same
    trace_ids.

    Lifecycle: :meth:`start` at run begin (tolerates a profiler-less
    backend or an already-running trace — capture is best-effort
    observability, never a run-killer), :meth:`note_segment` per
    drained segment until N, then auto-stop; :meth:`stop` is
    idempotent and also runs from the engine's ``finally`` so a short
    or crashed run still flushes a valid trace."""

    def __init__(self, out_dir: str, n_segments: int):
        self.out_dir = out_dir
        self.n_segments = int(n_segments)
        self.active = False
        self.first_trace_id = 0
        self.last_trace_id = 0
        self.first_segment = -1
        self.last_segment = -1
        self._seen = 0
        self._t0 = 0.0

    @classmethod
    def from_config(cls, cfg) -> "ProfileCapture | None":
        n = int(getattr(cfg, "profile_capture_segments", 0) or 0)
        if n <= 0:
            return None
        return cls(getattr(cfg, "profile_capture_dir",
                           "artifacts/profile") or "artifacts/profile",
                   n)

    def start(self) -> bool:
        import os
        try:
            import jax
            os.makedirs(self.out_dir, exist_ok=True)
            jax.profiler.start_trace(self.out_dir)
        except Exception as e:  # profiler-less backend / double start
            log.warning(f"[tracing] profile capture unavailable: {e}")
            return False
        self.active = True
        self._t0 = time.time()
        log.info(f"[tracing] profiling first {self.n_segments} "
                 f"segment(s) -> {self.out_dir}")
        return True

    def note_segment(self, segment: int, trace_id: int = 0) -> None:
        """One drained segment; stops the capture once N are in."""
        if not self.active:
            return
        if self._seen == 0:
            self.first_segment = int(segment)
            self.first_trace_id = int(trace_id)
        self.last_segment = int(segment)
        self.last_trace_id = int(trace_id)
        self._seen += 1
        if self._seen >= self.n_segments:
            self.stop()

    def stop(self) -> None:
        if not self.active:
            return
        self.active = False
        import json
        import os
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:  # pragma: no cover - backend quirk
            log.warning(f"[tracing] profiler stop failed: {e}")
            return
        # the trace_id join key: device timeline <-> causal events /
        # journal spans.  Written last so a capture.json implies a
        # complete capture.
        sidecar = {
            "type": "profile_capture",
            "dir": self.out_dir,
            "segments": self._seen,
            "first_segment": self.first_segment,
            "last_segment": self.last_segment,
            "first_trace_id": self.first_trace_id,
            "last_trace_id": self.last_trace_id,
            "wall_start": self._t0,
            "wall_end": time.time(),
        }
        try:
            with open(os.path.join(self.out_dir, "capture.json"),
                      "w") as f:
                json.dump(sidecar, f, indent=1, sort_keys=True)
                f.write("\n")
        except OSError as e:
            log.warning(f"[tracing] capture sidecar failed: {e}")
        from srtb_tpu.utils.metrics import metrics
        metrics.add("profile_captures")
        log.info(f"[tracing] profile capture complete: {self._seen} "
                 f"segment(s), trace_ids {self.first_trace_id}.."
                 f"{self.last_trace_id} -> {self.out_dir}")


class StageTimer:
    """Accumulates wall-clock per named stage; the per-pipe-timestamp logs
    of the reference, queryable instead of grep-able.

    Integrated into pipeline/runtime.py (each host stage of every
    segment runs under ``stage()``): ``last`` holds the most recent
    duration per stage so the caller can assemble a per-segment span,
    and ``on_stage(name, seconds)`` (when set) feeds every completed
    timing to the metrics histograms.  Thread-safe — the threaded
    pipeline runs each stage on its own thread.
    """

    def __init__(self, on_stage=None):
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self.last: dict[str, float] = {}
        self.on_stage = on_stage
        self._lock = threading.Lock()

    def record(self, name: str, dt: float) -> None:
        """Record one externally timed stage duration (used where the
        caller must decide *after* timing whether the sample counts —
        e.g. the terminal failed source read must not pollute the
        ingest histogram)."""
        with self._lock:
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1
            self.last[name] = dt
        if self.on_stage is not None:
            self.on_stage(name, dt)

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0)

    def summary(self) -> dict:
        with self._lock:
            return {name: {"total_s": round(t, 6),
                           "count": self.counts[name],
                           "mean_ms": round(1e3 * t / self.counts[name],
                                            3)}
                    for name, t in sorted(self.totals.items())}
