"""Profiling/tracing hooks.

The reference has only ad-hoc timing (per-pipe debug logs, benchmark
harness in test-fft_wrappers, hand-recorded kernel timings — SURVEY.md
§5.1).  On TPU the native story is better: ``jax.profiler`` traces
(viewable in xprof/tensorboard) plus lightweight wall-clock stage timers.
"""

from __future__ import annotations

import contextlib
import threading
import time

from srtb_tpu.utils.logging import log


@contextlib.contextmanager
def trace_annotation(name: str):
    """``jax.profiler.TraceAnnotation`` when available (shows host-side
    stage extents on the xprof timeline, correlating the span journal
    with device traces by stage name); a no-op on backends without it.
    Importing jax lazily keeps pure-host tools (telemetry_report) free
    of the jax import cost."""
    try:
        import jax

        cm = jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler-less backend
        cm = contextlib.nullcontext()
    with cm:
        yield


@contextlib.contextmanager
def device_trace(trace_dir: str):
    """Capture a jax profiler trace to ``trace_dir`` (xprof format)."""
    import jax

    try:
        jax.profiler.start_trace(trace_dir)
        started = True
        log.info(f"[tracing] jax profiler trace -> {trace_dir}")
    except Exception as e:  # backend without profiler support
        log.warning(f"[tracing] profiler unavailable: {e}")
        started = False
    try:
        yield
    finally:
        if started:
            jax.profiler.stop_trace()


class StageTimer:
    """Accumulates wall-clock per named stage; the per-pipe-timestamp logs
    of the reference, queryable instead of grep-able.

    Integrated into pipeline/runtime.py (each host stage of every
    segment runs under ``stage()``): ``last`` holds the most recent
    duration per stage so the caller can assemble a per-segment span,
    and ``on_stage(name, seconds)`` (when set) feeds every completed
    timing to the metrics histograms.  Thread-safe — the threaded
    pipeline runs each stage on its own thread.
    """

    def __init__(self, on_stage=None):
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self.last: dict[str, float] = {}
        self.on_stage = on_stage
        self._lock = threading.Lock()

    def record(self, name: str, dt: float) -> None:
        """Record one externally timed stage duration (used where the
        caller must decide *after* timing whether the sample counts —
        e.g. the terminal failed source read must not pollute the
        ingest histogram)."""
        with self._lock:
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1
            self.last[name] = dt
        if self.on_stage is not None:
            self.on_stage(name, dt)

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0)

    def summary(self) -> dict:
        with self._lock:
            return {name: {"total_s": round(t, 6),
                           "count": self.counts[name],
                           "mean_ms": round(1e3 * t / self.counts[name],
                                            3)}
                    for name, t in sorted(self.totals.items())}
