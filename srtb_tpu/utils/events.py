"""Causal segment tracing + the always-on flight recorder.

The repo's *mechanisms* (heal/demote ladders, watchdog requeues,
supervisor restarts, manifest rollback, fleet bulkheads) each log and
count — but when something escalates there is no causal STORY: which
segment hit which fault, what the healer did about it, and what it
cost.  This module is that story's spine:

- every :class:`~srtb_tpu.pipeline.work.SegmentWork` carries a
  ``trace_id`` (stamped at ingest by the pipeline from
  :func:`next_trace_id`);
- every subsystem that touches a segment emits a typed,
  monotonic-clocked event onto the hub — stage edges
  (ingest/dispatch/fetch/sink), retry attempts, device-fault
  classifications, heal/demote/promote/reinit decisions,
  degrade-ladder and admission/shed decisions, watchdog requeues,
  supervisor restarts, ring cold re-arms, manifest
  intent/commit/done/ckpt;
- the hub IS the **flight recorder**: a bounded in-memory ring of the
  last N events per thread (lock-light — the emit path touches only
  thread-local state; shards are merged on :meth:`EventHub.dump`), so
  the recent past is always reconstructable — an incident bundle
  (utils/incidents.py) snapshots it, and ``tools/trace_export.py``
  renders a dump as a Chrome-trace/Perfetto timeline with flow arrows
  following ``trace_id`` across threads.

Cost contract (PERF.md round 17): the DISABLED path is the
established zero-cost-off None-hook pattern — call sites hold
``self.events`` (the hub or None) and pay one attribute read + None
check; module-level :func:`emit` is one global read + None check.
The ARMED path does no per-event growth: each shard preallocates its
``ring_size`` slots once and emits overwrite slots in place (one
small tuple per event, no dict, no deque, no resizing), so the
recorder is O(ring size) memory however long the run.

The hub is PROCESS-GLOBAL (like the metrics registry): fleet lanes
share it, and ``Config.events_enable`` arms/disarms it for the whole
process (last pipeline constructed wins — document mixed-config
fleets accordingly).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time

# ---------------------------------------------------------------------
# event taxonomy (the README table is generated from this intent):
#
#   stage.ingest / stage.dispatch / stage.fetch / stage.sink
#       one per segment per stage edge; ``dur`` is the stage seconds
#   ring.cold / ring.invalidate
#       ingest-ring warm/cold transitions (warm is the steady state and
#       is implied between a cold re-arm and the next invalidation)
#   retry
#       one per retry attempt; info = "site:category:attempt"
#   fault.injected
#       a Config.fault_plan entry fired; info = the spec string
#   fault.device
#       a dispatch/fetch failure classified as a device fault;
#       info = "kind:ExcType"
#   heal.demote / heal.promote / heal.reinit
#       self-healing ladder decisions; info = "step@level" / "level"
#   degrade
#       sink-side degradation ladder level change; info = "old->new"
#   admission
#       fleet admission decision; info = "decision" (stream labels it)
#   shed.segment / shed.ingest / fleet.force_shed
#       whole-segment loss decisions (watchdog wedge, parked window,
#       fleet fairness)
#   watchdog.requeue / watchdog.escalate
#       in-flight segment cancel/re-dispatch and its escalation
#   supervisor.restart
#       a bounded-restart supervisor approved a worker restart;
#       info = "name:count"
#   manifest.intent / manifest.commit / manifest.done / manifest.ckpt
#       durable-output WAL records; info = "seg:sink[:path]"
#   manifest.loss
#       recovery flagged unrecoverable loss (fsck-grade)
#   fleet.reinit / fleet.lane_failed
#       shared device reinit; a lane's contained failure
#   fleet.device_halt / fleet.device_drain / fleet.migrate
#       elastic pool (pipeline/pool.py): a pool member halted
#       (info = its label) and its lanes drain onto survivors; a
#       rolling-restart drain of one member; one lane's live
#       migration (info = "src->dst", stream labels the lane) —
#       admission re-attribution rides the ``admission`` kind with
#       info = "migrate:src->dst"
#   incident
#       an incident bundle was written; info = the bundle dir name
#   obs.regression
#       the mid-run regression watch (obs/regression.py) confirmed a
#       throughput regression against ledger history;
#       info = "plan=...effect=...p=..."
#   slo
#       an SLO objective changed state; info = "objective:state"
# ---------------------------------------------------------------------

DEFAULT_RING_SIZE = 4096

_trace_counter = itertools.count(1)


def next_trace_id() -> int:
    """Process-unique causal id for one segment's journey.  Stamped
    onto ``SegmentWork.trace_id`` at ingest; every event a subsystem
    emits while working on that segment carries it, across threads."""
    return next(_trace_counter)


# total shard bound: memory stays O(MAX_SHARDS x ring_size) however
# many worker threads a long-lived process churns through (archive
# replay over hundreds of files spawns a sink thread per run).  When
# a new thread would exceed it, DEAD threads' shards are evicted
# oldest-registration-first — live threads are never evicted, and
# recently-dead shards (the post-mortem evidence an incident bundle
# wants) survive until the bound actually forces them out.
MAX_SHARDS = 64


class _Shard:
    """One thread's ring: ``ring_size`` preallocated slots overwritten
    in place.  Only its owning thread writes; dump() reads without a
    lock (a torn read of a slot being overwritten yields either the
    old or the new tuple — tuple assignment is atomic under the GIL)."""

    __slots__ = ("slots", "i", "n", "thread", "thread_obj")

    def __init__(self, n: int, thread):
        self.slots = [None] * n
        self.i = 0
        self.n = n
        self.thread = thread.name
        self.thread_obj = thread


class EventHub:
    """The flight recorder: per-thread ring shards + a merge-on-dump
    view.  ``emit`` is the single write path; all fields are scalars
    (no per-event dict), packed as one tuple:

        (t_monotonic, etype, trace_id, stream, seg, dur_s, info)
    """

    def __init__(self, ring_size: int = DEFAULT_RING_SIZE):
        if ring_size <= 0:
            raise ValueError("ring_size must be positive")
        self.ring_size = int(ring_size)
        self._tls = threading.local()
        self._shards: list[_Shard] = []
        self._lock = threading.Lock()
        # monotonic->wall mapping captured once, so dumps/exports can
        # place events on the epoch timeline without per-event clock
        # syscalls beyond the one monotonic read
        self.mono0 = time.monotonic()
        self.wall0 = time.time()

    # ------------------------------------------------------ hot path

    def _shard(self) -> _Shard:
        sh = getattr(self._tls, "shard", None)
        if sh is None:
            sh = _Shard(self.ring_size, threading.current_thread())
            self._tls.shard = sh
            with self._lock:
                if len(self._shards) >= MAX_SHARDS:
                    # evict dead threads' shards, oldest first
                    dead = [s for s in self._shards
                            if not s.thread_obj.is_alive()]
                    for victim in dead[:len(self._shards)
                                       - MAX_SHARDS + 1]:
                        self._shards.remove(victim)
                self._shards.append(sh)
        return sh

    def emit(self, etype: str, trace: int = 0, stream: str = "",
             seg: int = -1, dur: float = 0.0, info: str = "") -> None:
        sh = getattr(self._tls, "shard", None)
        if sh is None:
            sh = self._shard()
        sh.slots[sh.i % sh.n] = (time.monotonic(), etype, trace,
                                 stream, seg, dur, info)
        sh.i += 1

    # ----------------------------------------------------- dump side

    def dump(self, trace: int | None = None) -> list[dict]:
        """Merged view of every shard, oldest first.  ``trace`` filters
        to one segment's causal story.  Reads are lock-light: the
        shard list is copied under the lock, slots are read live (a
        slot overwritten mid-dump yields a valid tuple either way)."""
        with self._lock:
            shards = list(self._shards)
        out = []
        for sh in shards:
            n, i = sh.n, sh.i
            start = max(0, i - n)
            for k in range(start, i):
                ev = sh.slots[k % n]
                if ev is None:
                    continue
                if trace is not None and ev[2] != trace:
                    continue
                out.append({
                    "t": ev[0],
                    "ts": self.wall0 + (ev[0] - self.mono0),
                    "type": ev[1],
                    "trace": ev[2],
                    "stream": ev[3],
                    "seg": ev[4],
                    "dur_ms": round(ev[5] * 1e3, 4),
                    "info": ev[6],
                    "thread": sh.thread,
                })
        out.sort(key=lambda e: e["t"])
        return out

    def dump_jsonl(self, path: str,
                   trace: int | None = None) -> int:
        """Write a dump to ``path`` (one JSON object per line, the
        format ``tools/trace_export.py`` and the incident bundles
        consume).  Returns the record count."""
        evs = self.dump(trace=trace)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            for ev in evs:
                f.write(json.dumps(ev, sort_keys=True) + "\n")
        return len(evs)


# ---------------------------------------------------------------------
# process-global hub + ambient trace context
# ---------------------------------------------------------------------

# the always-on default: the flight recorder exists from import so the
# recent past is reconstructable even before any Pipeline configures it
hub: EventHub | None = EventHub()

_ambient = threading.local()


def configure(enabled: bool = True,
              ring_size: int = DEFAULT_RING_SIZE) -> None:
    """Arm/disarm the process-global hub.  Arming with the hub already
    live at the same ring size KEEPS it (and its recent events) — a
    fleet constructing N lanes must not wipe the recorder N times."""
    global hub
    if not enabled:
        hub = None
        return
    if hub is None or hub.ring_size != int(ring_size):
        hub = EventHub(ring_size=ring_size)


def set_current(trace: int, stream: str = "") -> None:
    """Bind the ambient (thread-local) causal context: events emitted
    by subsystems that don't thread a trace id through their API
    (retry backoffs, manifest records, heal decisions) attach to the
    segment whose work this thread is currently doing."""
    _ambient.trace = trace
    _ambient.stream = stream


def current() -> tuple[int, str]:
    return (getattr(_ambient, "trace", 0),
            getattr(_ambient, "stream", ""))


def emit(etype: str, trace: int | None = None, stream: str | None = None,
         seg: int = -1, dur: float = 0.0, info: str = "") -> None:
    """Module-level emit with ambient-context fallback: ``trace=None``
    /``stream=None`` resolve from :func:`set_current`.  One global
    read + None check when the recorder is off."""
    h = hub
    if h is None:
        return
    if trace is None or stream is None:
        at, astream = current()
        if trace is None:
            trace = at
        if stream is None:
            stream = astream
    h.emit(etype, trace=trace, stream=stream, seg=seg, dur=dur,
           info=info)
