"""Arithmetic expression evaluation for config values.

The reference accepts arithmetic expressions in config values, e.g.
``baseband_input_count = 2 ** 30`` or ``baseband_freq_low = 1405 + (64/2)``
(ref: program_options.hpp:197-214 via 3rdparty/exprgrammar).  Here the same
capability is provided with a restricted AST walker over Python syntax, which
is a superset of the reference grammar (+ - * / % ** and parentheses).
"""

from __future__ import annotations

import ast
import operator

_BIN_OPS = {
    ast.Add: operator.add,
    ast.Sub: operator.sub,
    ast.Mult: operator.mul,
    ast.Div: operator.truediv,
    ast.FloorDiv: operator.floordiv,
    ast.Mod: operator.mod,
    ast.Pow: operator.pow,
    ast.BitXor: operator.pow,  # some radio configs write 2^30 meaning 2**30
    ast.LShift: operator.lshift,
    ast.RShift: operator.rshift,
}

_UNARY_OPS = {
    ast.UAdd: operator.pos,
    ast.USub: operator.neg,
}


def _eval_node(node: ast.AST):
    if isinstance(node, ast.Expression):
        return _eval_node(node.body)
    if isinstance(node, ast.Constant):
        if isinstance(node.value, (int, float)):
            return node.value
        raise ValueError(f"non-numeric constant {node.value!r}")
    if isinstance(node, ast.BinOp):
        op_type = type(node.op)
        if op_type not in _BIN_OPS:
            raise ValueError(f"unsupported operator {op_type.__name__}")
        return _BIN_OPS[op_type](_eval_node(node.left), _eval_node(node.right))
    if isinstance(node, ast.UnaryOp):
        op_type = type(node.op)
        if op_type not in _UNARY_OPS:
            raise ValueError(f"unsupported unary operator {op_type.__name__}")
        return _UNARY_OPS[op_type](_eval_node(node.operand))
    raise ValueError(f"unsupported syntax {type(node).__name__}")


def parse_expression(text: str) -> float:
    """Evaluate an arithmetic config expression such as ``"2 ** 30"``.

    Returns a float or int; raises ValueError on anything that is not pure
    arithmetic.
    """
    tree = ast.parse(text.strip(), mode="eval")
    return _eval_node(tree)


def parse_number(text: str) -> float:
    """Parse a config value that may be a plain number or an expression."""
    try:
        return float(text)
    except ValueError:
        return float(parse_expression(text))
