"""Host buffer pool — the cached-allocator analog.

The reference caches device/host allocations in size-bucketed free lists
because raw (pinned) allocation costs 0.5-5 s/GB (ref: memory/
cached_allocator.hpp:38-235, main.cpp:57).  On the TPU side HBM is managed
by XLA (buffer reuse inside jit; donation at boundaries), so what remains
worth pooling is the *host* side: the big per-segment numpy byte buffers
the readers fill.  Same policy as the reference: exact-or-larger reuse
with a 0.5 threshold (a cached block at least the requested size but no
more than 2x is reused, cached_allocator.hpp:75-121), explicit
``free_all``, and double-release diagnostics.
"""

from __future__ import annotations

import threading

import numpy as np

from srtb_tpu.utils.logging import log


class BufferPool:
    def __init__(self, name: str = "host"):
        self.name = name
        self._free: dict[int, list[np.ndarray]] = {}
        self._out: set[int] = set()
        self._lock = threading.Lock()

    def acquire(self, nbytes: int, zero: bool = True) -> np.ndarray:
        """Get a uint8 buffer of exactly nbytes (a view of a possibly
        larger cached block)."""
        with self._lock:
            best_size = None
            for size in self._free:
                if nbytes <= size <= 2 * nbytes:  # the 0.5 reuse threshold
                    if best_size is None or size < best_size:
                        best_size = size
            if best_size is not None:
                block = self._free[best_size].pop()
                if not self._free[best_size]:
                    del self._free[best_size]
            else:
                log.debug(f"[buffer_pool {self.name}] new block "
                          f"{nbytes} bytes")
                block = np.empty(nbytes, dtype=np.uint8)
            self._out.add(id(block))
        if zero:
            block[:nbytes] = 0
        return block[:nbytes] if block.nbytes != nbytes else block

    def release(self, buf: np.ndarray) -> None:
        base = buf.base if buf.base is not None else buf
        with self._lock:
            if id(base) not in self._out:
                log.warning(f"[buffer_pool {self.name}] releasing unknown "
                            "or already-freed buffer")
                return
            self._out.discard(id(base))
            self._free.setdefault(base.nbytes, []).append(base)

    def stats(self) -> dict:
        """Occupancy snapshot for the buffer gauges (telemetry): cached
        block count/bytes and buffers currently out."""
        with self._lock:
            cached = sum(len(v) for v in self._free.values())
            cached_bytes = sum(size * len(v)
                               for size, v in self._free.items())
            return {"cached_blocks": cached,
                    "cached_bytes": cached_bytes,
                    "in_use": len(self._out)}

    def free_all(self) -> int:
        """Drop all cached blocks (ref: deallocate_all_free_ptrs); returns
        count of buffers still in use (leak diagnostic,
        ref: cached_allocator.hpp:230-233)."""
        with self._lock:
            self._free.clear()
            in_use = len(self._out)
        if in_use:
            log.warning(f"[buffer_pool {self.name}] {in_use} buffers still "
                        "in use")
        return in_use
